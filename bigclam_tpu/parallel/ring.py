"""Ring-pass sharded training: rotate F node-shards around the ICI ring
instead of all-gathering F.

The C21 "ring-attention analog" (SURVEY.md §2/§5): at pod scale the
all-gather schedule of parallel/sharded.py materializes a full (N_pad, K_loc)
copy of F per device — impossible for com-Friendster-class graphs
(N=65M x K=25K). Here each device only ever holds a handful of
(N_pad/dp, K_loc) shards: its own F_loc and a rotating buffer F_rot that
`lax.ppermute`s around the "nodes" ring, one hop per phase, exactly like
ring attention rotates KV blocks (the default double-buffered schedule
adds one more in-flight shard buffer; cfg.ring_overlap=False drops back
to exactly two). Edges are bucketed by destination shard at ingest; in
phase r device i processes the bucket whose destinations live in shard
(i + r) % dp, accumulating neighbor LLH/gradient contributions, then passes
F_rot to its ring predecessor. Every rotation goes through the shared
`rotate_scan` primitive, which by default DOUBLE-BUFFERS the rotation: the
ppermute carrying phase r+1's shard is issued concurrently with phase r's
sweep, so the inter-chip hop hides behind compute (cfg.ring_overlap=False
forces the strictly serialized sweep->hop schedule; identical numerics
either way). Communication totals match the all-gather
(every shard visits every device) but peak HBM drops from O(N*K_loc) to
O(2 * N/dp * K_loc); the gradient pass and the 16-candidate Armijo pass each
take one full rotation (the candidate pass re-rotates because it needs the
finished gradient).

Semantics are IDENTICAL to the single-chip and all-gather trainers —
verified by the shard-invariance suite (tests/test_ring.py). The hot sweeps
run either as XLA chunk scans (the fallback and the tp > 1 path) or on the
blocked-CSR MXU kernels via per-(shard, phase) tile buckets
(make_ring_csr_train_step; auto-engaged on TPU at tp == 1).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.models.bigclam import (
    TrainState,
    _round_up,
    attach_donating,
    edge_chunk_bound,
)
from bigclam_tpu.ops.objective import EdgeChunks, edge_terms
from bigclam_tpu.parallel.mesh import K_AXIS, NODES_AXIS
from bigclam_tpu.parallel.multihost import put_sharded
from bigclam_tpu.parallel.sharded import (
    ShardedBigClamModel,
    _mark_varying,
    _rowdot,
    _shard_grad_stats,
    _shard_health,
    _StoreBackedMixin,
    armijo_tail_select_sharded,
)
from bigclam_tpu.utils.compat import shard_map


# a bucket holding more than this multiple of the mean marks the id space
# as locality-ordered: the padded sweep then does up to dp x the real edge
# work (measured 15.7x at dp=8, RINGMEM_r05.json). One constant shared by
# the warning, the auto-balance engagement rule, AND the imbalance
# anomaly (obs.comms.IMBALANCE_FACTOR is the canonical home since ISSUE
# 10 — the event fires exactly where the warning used to), so the
# default schedule engages exactly where the warning used to fire.
from bigclam_tpu.obs.comms import IMBALANCE_FACTOR as RING_IMBALANCE_FACTOR


def ring_bucket_imbalance(
    g: Graph, dp: int, n_pad: int
) -> tuple[int, float]:
    """(max, mean) directed-edge count over the dp*dp (src shard, phase)
    buckets — the imbalance statistic behind _warn_bucket_imbalance and
    the auto-balance rule (RingBigClamModel)."""
    shard_rows = max(n_pad // dp, 1)
    src_shard = g.src // shard_rows
    phase = ((g.dst // shard_rows) - src_shard) % dp
    counts = np.zeros((dp, dp), dtype=np.int64)
    np.add.at(counts, (src_shard, phase), 1)
    return int(counts.max()) if counts.size else 1, max(
        float(g.src.size) / (dp * dp), 1.0
    )


def _warn_imbalance_counts(
    total_directed: int, dp: int, max_count: int,
    hint: str = "relabel (balance=True or the default balance=None auto "
                "rule) or shuffle ids before the ring schedule",
) -> None:
    """The count-based half of _warn_bucket_imbalance, shared with the
    store-backed ring build (which knows the total from the manifest and
    the max from a cross-host exchange, never a global CSR). Since ISSUE
    10 the firing condition ALSO emits an `anomaly` event
    (check="imbalance") — the stderr line reached only whoever watched
    the console; the event reaches `cli report`, `cli watch`, and the
    perf ledger's anomaly count."""
    mean_count = max(float(total_directed) / (dp * dp), 1.0)
    if max_count > RING_IMBALANCE_FACTOR * mean_count:
        import warnings

        from bigclam_tpu.obs import comms as _comms

        _comms.emit_imbalance_anomaly(
            "ring_buckets", max_count, mean_count, hint=hint
        )
        warnings.warn(
            f"ring phase buckets are imbalanced: max {max_count} vs mean "
            f"{mean_count:.0f} edges/bucket — the padded sweep does "
            f"~{max_count / mean_count:.1f}x the real edge work. Node ids "
            f"look locality-ordered; {hint}.",
            stacklevel=4,
        )


def _warn_bucket_imbalance(g: Graph, dp: int, max_count: int) -> None:
    """Every (shard, phase) bucket pads to the max: a locality-ordered id
    space (contiguous communities, BFS orders) concentrates edges in the
    diagonal buckets and the padded sweep does up to dp x the real edge
    work (measured 15.7x at dp=8, RINGMEM_r05.json; balance=True cut ring
    step time 5.1x on the same graph). Shared by the XLA edge buckets and
    the CSR tile buckets — the distribution is the same. Only reachable
    with balance=False (the explicit escape hatch): the default ring
    build auto-engages the balance relabeling on the same heuristic."""
    _warn_imbalance_counts(int(g.src.size), dp, max_count)


def ring_bucket_local_max(shard, dp: int, n_pad: int) -> int:
    """Max directed-edge count over THIS host's (shard, phase) buckets —
    the local half of ring_bucket_imbalance, computed from HostShard rows
    only. The global max is a one-int cross-host exchange
    (multihost.global_max_int)."""
    from bigclam_tpu.ops.csr_tiles import _local_shard_edge_slices

    shard_rows = max(n_pad // dp, 1)
    mx = 0
    for i, _, dst in _local_shard_edge_slices(shard, dp, n_pad):
        if dst.size:
            phase = ((dst.astype(np.int64) // shard_rows) - i) % dp
            mx = max(mx, int(np.bincount(phase, minlength=dp).max()))
    return max(mx, 1)


def rotate_scan(F0, acc0, xs, sweep, perm, overlap: bool):
    """The shared rotation primitive: scan the ring phases, sweeping each
    phase's edge bucket against the resident rotating shard and moving the
    shard one hop per phase.

    `sweep(acc, x, F_rot) -> acc` consumes one phase's bucket slice `x`
    (any pytree sliced along the leading phase axis of `xs`) against the
    resident rotating shard. Every rotation site in this module goes
    through here, so the communication schedule is decided in exactly one
    place.

    overlap=True (the default, cfg.ring_overlap): DOUBLE-BUFFERED. The
    ppermute carrying phase r+1's shard is issued before phase r's sweep
    and has no data dependence on it — two (N/dp, K_loc) buffers are live
    (the one being read by the sweep, the one in flight) and the async
    collective-permute proceeds concurrently with the sweep, hiding the
    inter-chip hop whenever the sweep outlasts the shard transfer
    (the rotate-and-reduce overlap of Sparse Allreduce, arXiv:1312.3020).

    overlap=False: the FORCED-serial schedule — an optimization_barrier
    makes the hop wait for the sweep, so every hop is dead time on the
    compute timeline by construction. Note this is stricter than the
    pre-primitive code (which had the same hop/sweep dataflow but left the
    ordering to the scheduler): the A/B against it measures the hop time
    that overlapping CAN hide — an upper bound on the win over a build
    whose scheduler already overlapped some of it. Kept for that
    measurement (utils.profiling.overlap_report), for the parity suite,
    and as the fallback that guarantees only two live shard buffers. Both
    schedules compute bit-identical results (the barrier moves no math).

    Returns (F_back, acc): the shard after the full rotation (== F0 — every
    shard visits every device exactly once) and the final accumulator.
    """

    def phase(carry, x):
        F_rot, acc = carry
        if overlap:
            F_next = lax.ppermute(F_rot, NODES_AXIS, perm)
            acc = sweep(acc, x, F_rot)
        else:
            acc = sweep(acc, x, F_rot)
            F_rot, acc = lax.optimization_barrier((F_rot, acc))
            F_next = lax.ppermute(F_rot, NODES_AXIS, perm)
        return (F_next, acc), None

    (F_back, acc), _ = lax.scan(phase, (F0, acc0), xs)
    return F_back, acc


def ring_shard_edges(
    g: Graph,
    cfg: BigClamConfig,
    dp: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
) -> EdgeChunks:
    """Bucket each src shard's edges by destination shard.

    Returns (dp, dp, C, chunk) arrays: axis 0 = owning (src) shard, axis 1 =
    ring phase r (destinations in shard (i + r) % dp). BOTH src and dst are
    stored shard-local; padding keeps src sorted (last local row) with
    mask 0. All buckets are padded to the global max bucket size (static
    SPMD shapes; power-law skew shows up as padding, mitigated by the
    degree-bucketing planned in PARITY.md).
    """
    from bigclam_tpu.obs import trace as _trace

    # span (obs.trace): the host-side bucket build is a real model-build
    # cost at pod shard counts — attribute it next to the ring's other
    # phases instead of folding it into an opaque model_build stage;
    # `source` lets the perf ledger tell the host-global builder from the
    # store-native one (ISSUE 9)
    with _trace.span("ring/bucket_build", dp=dp, source="host_global") as _sp:
        shard_rows = n_pad // dp
        src_shard = g.src // shard_rows
        dst_shard = g.dst // shard_rows
        phase = (dst_shard - src_shard) % dp
        max_count = max(ring_bucket_imbalance(g, dp, n_pad)[0], 1)
        _warn_bucket_imbalance(g, dp, max_count)
        chunk = min(chunk_bound or cfg.edge_chunk, max_count)
        c = -(-max_count // chunk)
        padded = c * chunk
        _sp.set(max_bucket=int(max_count), padded_slots=int(padded * dp * dp))
        src = np.full((dp, dp, padded), shard_rows - 1, dtype=np.int32)
        dst = np.zeros((dp, dp, padded), dtype=np.int32)
        mask = np.zeros((dp, dp, padded), dtype=np.float32)
        # stable bucket fill preserving CSR (src-sorted) order per bucket
        order = np.lexsort((np.arange(g.src.size), phase, src_shard))
        s_sorted = g.src[order]
        d_sorted = g.dst[order]
        ss = src_shard[order]
        ph = phase[order]
        # walk contiguous (shard, phase) runs
        run_starts = np.flatnonzero(
            np.r_[True, (ss[1:] != ss[:-1]) | (ph[1:] != ph[:-1])]
        )
        run_ends = np.r_[run_starts[1:], ss.size]
        for lo, hi in zip(run_starts, run_ends):
            i, r = int(ss[lo]), int(ph[lo])
            m = hi - lo
            src[i, r, :m] = s_sorted[lo:hi] - i * shard_rows
            dst[i, r, :m] = d_sorted[lo:hi] - ((i + r) % dp) * shard_rows
            mask[i, r, :m] = 1.0
        return EdgeChunks(
            src=src.reshape(dp, dp, c, chunk),
            dst=dst.reshape(dp, dp, c, chunk),
            mask=mask.reshape(dp, dp, c, chunk).astype(dtype),
        )


def ring_shard_edges_local(
    shard,
    cfg: BigClamConfig,
    dp: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
    max_count: int = 0,
) -> EdgeChunks:
    """This host's rows of the ring (shard, phase) edge buckets, built from
    a per-host graph-store slice (graph/store.HostShard) — the out-of-core
    twin of ring_shard_edges: no global CSR exists anywhere.

    `max_count` is the GLOBAL max bucket edge count (ring_bucket_local_max
    + multihost.global_max_int — every host pads identically without
    seeing another host's edges); 0 uses the local max (exact on
    single-host loads). dst translation to the rotating shard's local rows
    needs only the manifest node ranges.
    """
    from bigclam_tpu.obs import trace as _trace
    from bigclam_tpu.ops.csr_tiles import _local_shard_edge_slices

    with _trace.span("ring/bucket_build", dp=dp, source="store") as _sp:
        shard_rows = n_pad // dp
        if not max_count:
            max_count = ring_bucket_local_max(shard, dp, n_pad)
        chunk = min(chunk_bound or cfg.edge_chunk, max(max_count, 1))
        c = -(-max_count // chunk)
        padded = c * chunk
        n_local = len(shard.shard_ids)
        _sp.set(max_bucket=int(max_count),
                padded_slots=int(padded * dp * dp))
        src = np.full((n_local, dp, padded), shard_rows - 1, dtype=np.int32)
        dst = np.zeros((n_local, dp, padded), dtype=np.int32)
        mask = np.zeros((n_local, dp, padded), dtype=np.float32)
        for row, (i, s_loc, d_glob) in enumerate(
            _local_shard_edge_slices(shard, dp, n_pad)
        ):
            if d_glob.size == 0:
                continue
            phase = ((d_glob.astype(np.int64) // shard_rows) - i) % dp
            # CSR order within each bucket (matches ring_shard_edges'
            # global lexsort, stable within one (shard, phase) run)
            order = np.lexsort((np.arange(d_glob.size), phase))
            ss = s_loc[order]
            dd = d_glob[order].astype(np.int64)
            ph = phase[order]
            bounds = np.searchsorted(ph, np.arange(dp + 1))
            for r in range(dp):
                lo, hi = bounds[r], bounds[r + 1]
                m = hi - lo
                if m == 0:
                    continue
                src[row, r, :m] = ss[lo:hi]
                dst[row, r, :m] = dd[lo:hi] - ((i + r) % dp) * shard_rows
                mask[row, r, :m] = 1.0
        return EdgeChunks(
            src=src.reshape(n_local, dp, c, chunk),
            dst=dst.reshape(n_local, dp, c, chunk),
            mask=mask.reshape(n_local, dp, c, chunk).astype(dtype),
        )


def make_ring_train_step(
    mesh: Mesh, edges: EdgeChunks, cfg: BigClamConfig
) -> Callable[[TrainState], TrainState]:
    """One jitted iteration with ring-rotated F shards (two rotations:
    gradient pass + candidate pass)."""
    dp = mesh.shape[NODES_AXIS]
    perm = [(j, (j - 1) % dp) for j in range(dp)]   # send to ring predecessor

    def step_shard(F_loc, src, dst, mask, it):
        src, dst, mask = src[0], dst[0], mask[0]    # (dp, C, chunk), phase-major
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        etas = jnp.asarray(cfg.step_candidates, F_loc.dtype)
        n_loc = F_loc.shape[0]
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)

        def sweep_chunks(carry_fn, init, s_ph, d_ph, m_ph, F_rot):
            """Scan a phase's chunks, accumulating via carry_fn."""
            def body(acc, sdm):
                return carry_fn(acc, sdm, F_rot), None
            out, _ = lax.scan(body, init, (s_ph, d_ph, m_ph))
            return out

        # --- rotation 1: fused gradient + LLH ---
        def grad_chunk(acc, sdm, F_rot):
            nbr_llh, nbr_grad = acc
            s, d, m = sdm
            fs, fd = F_loc[s], F_rot[d]
            x = lax.psum(jnp.einsum("ek,ek->e", fs, fd), K_AXIS)
            omp, ell = edge_terms(x, cfg)
            coeff = m / omp
            return (
                nbr_llh + jax.ops.segment_sum(
                    (ell * m).astype(adt), s, num_segments=n_loc,
                    indices_are_sorted=True,
                ),
                nbr_grad + jax.ops.segment_sum(
                    fd * coeff[:, None], s, num_segments=n_loc,
                    indices_are_sorted=True,
                ),
            )

        def grad_sweep(acc, sdm_ph, F_rot):
            s_ph, d_ph, m_ph = sdm_ph
            return sweep_chunks(grad_chunk, acc, s_ph, d_ph, m_ph, F_rot)

        init_acc = (
            _mark_varying(jnp.zeros(n_loc, adt), (NODES_AXIS,)),
            _mark_varying(jnp.zeros_like(F_loc), (NODES_AXIS, K_AXIS)),
        )
        F_back, (nbr_llh, nbr_grad) = rotate_scan(
            F_loc, init_acc, (src, dst, mask), grad_sweep, perm,
            cfg.ring_overlap,
        )
        grad = nbr_grad - sumF[None, :] + F_loc
        node_llh = nbr_llh + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)

        # --- rotation 2: the 16 Armijo candidates ---
        def cand_chunk(cand, sdm, F_rot):
            s, d, m = sdm
            fs, gs, fd = F_loc[s], grad[s], F_rot[d]

            def one_eta(eta):
                nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
                xc = lax.psum(jnp.einsum("ek,ek->e", nf, fd), K_AXIS)
                _, ellc = edge_terms(xc, cfg)
                return jax.ops.segment_sum(
                    (ellc * m).astype(adt), s, num_segments=n_loc,
                    indices_are_sorted=True,
                )

            return cand + lax.map(one_eta, etas)

        def cand_sweep(cand, sdm_ph, F_rot):
            s_ph, d_ph, m_ph = sdm_ph
            return sweep_chunks(cand_chunk, cand, s_ph, d_ph, m_ph, F_rot)

        init_cand = _mark_varying(
            jnp.zeros((len(cfg.step_candidates), n_loc), adt), (NODES_AXIS,)
        )
        _, cand_nbr = rotate_scan(
            F_back, init_cand, (src, dst, mask), cand_sweep, perm,
            cfg.ring_overlap,
        )

        # --- Armijo acceptance + Jacobi update (shared helper) ---
        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_loc, grad, node_llh, cand_nbr, sumF, cfg, with_stats=True
        )
        sumF_new = lax.psum(sum_loc, NODES_AXIS)
        hist = lax.psum(hist, NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step(state: TrainState, src, dst, mask) -> TrainState:
        F_new, sumF, llh, it, hist, gstats = shard_map(
            step_shard,
            mesh=mesh,
            in_specs=(
                P(NODES_AXIS, K_AXIS),
                P(NODES_AXIS, None, None, None),
                P(NODES_AXIS, None, None, None),
                P(NODES_AXIS, None, None, None),
                P(),
            ),
            out_specs=(
                P(NODES_AXIS, K_AXIS), P(K_AXIS), P(), P(), P(), P(),
            ),
        )(state.F, src, dst, mask, state.it)
        return TrainState(
            F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
            health=_shard_health(cfg, state, F_new, sumF, hist, gstats),
        )

    # edge arrays as jit ARGUMENTS (multi-controller: no closing over
    # non-addressable-device arrays; see parallel/sharded.py)
    jitted = jax.jit(step)

    def step_fn(state):
        return jitted(state, edges.src, edges.dst, edges.mask)

    # AOT handles for scripts/ring_memory.py's compiler memory analysis
    step_fn.jitted = jitted
    step_fn.jit_args = (edges.src, edges.dst, edges.mask)
    return attach_donating(step_fn, step, fixed_args=step_fn.jit_args)


def make_ring_csr_train_step(
    mesh: Mesh, tiles: dict, cfg: BigClamConfig
) -> Callable[[TrainState], TrainState]:
    """Ring-pass iteration on the blocked-CSR MXU kernels.

    Same two rotations as make_ring_train_step, but each phase runs the
    grad / candidate Pallas kernels (ops.pallas_csr) over that phase's
    pre-built block-tile bucket (ops.csr_tiles.ring_block_tiles) against
    the resident rotating F shard: the per-phase (n_tiles, T, K_loc) fd
    gather reads only F_rot — peak HBM stays O(2 * N/dp * K_loc) like the
    XLA ring. Per-block kernel outputs accumulate across phases in the scan
    carry; Armijo tails are added once at the end (shared helper — the
    candidate kernels run with with_tails=False since each phase sees only
    a partial edge set).

    With the K axis ALSO sharded (tp > 1) each phase uses the TP kernel
    split (ops.pallas_csr TP suite): partial-dot kernel over this device's
    K_loc columns -> lax.psum of the per-edge partials over "k" (1 float
    per edge per phase — tiny next to the rotating F shard) -> consume
    kernels. This closes the schedule x kernel matrix at the Friendster
    corner (SURVEY.md C21): ring memory profile + K sharding + MXU kernels
    simultaneously."""
    from bigclam_tpu.ops.pallas_csr import (
        TilesDev,
        _cand_blocks,
        _grad_blocks,
        cand_dots_csr,
        cand_nbr_from_x_csr,
        edge_dots_csr,
        grad_nbr_from_x_csr,
    )
    from bigclam_tpu.ops.pallas_fused import (
        _cand_blocks_fused,
        _grad_blocks_fused,
        cand_dots_fused,
        edge_dots_fused,
        grad_nbr_from_x_fused,
    )

    dp = mesh.shape[NODES_AXIS]
    tp = mesh.shape[K_AXIS]
    perm = [(j, (j - 1) % dp) for j in range(dp)]
    interp = cfg.pallas_interpret
    block_b = tiles["block_b"]
    tile_t = tiles["tile_t"]
    n_blocks = tiles["n_blocks"]
    kc = tiles.get("kc", 0)
    fused = bool(tiles.get("fused"))
    num_s = len(cfg.step_candidates)

    def step_shard_kb(F_loc, srcl, dstl, mask, bid, it):
        # K-BLOCKED ring phases (K_loc > the kernels' VMEM bound): inside
        # each phase, a lax.scan over this device's kc-column K blocks
        # accumulates the partial edge dots against the ROTATING F shard,
        # one psum over "k" completes them (identity at tp == 1), and a
        # per-K-block consume stage builds that phase's gradient columns.
        # Same composition as ops.pallas_csr
        # .train_pass_csr_grouped_kblocked_tp, with ring buckets in place
        # of block groups.
        srcl, dstl, mask, bid = srcl[0], dstl[0], mask[0], bid[0]
        n_loc, k_loc = F_loc.shape
        n_kb = k_loc // kc
        nt = srcl.shape[1]                   # tiles per phase bucket
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)       # (K_loc,)

        def td_of(xs):
            s, d, m, b_ = xs
            td = TilesDev(
                src_local=s, dst=d, mask=m, block_id=b_,
                block_b=block_b, tile_t=tile_t, n_blocks=n_blocks,
            )
            return td, d

        def fd_of(F_rot, d, kb):
            cols = lax.dynamic_slice_in_dim(F_rot, kb * kc, kc, axis=1)
            return jnp.take(cols, d, axis=0)             # (nt, T, kc)

        # --- rotation 1: K-block dots -> psum -> per-K-block consume ---
        def grad_sweep(acc, xs, F_rot):
            gn_acc, ln_acc = acc
            td, d = td_of(xs)

            def dots_kb(x_acc, kb):
                if fused:
                    # in-kernel gather from the rotating shard: the
                    # kc-column window exists only in DMA descriptors
                    x_kb = edge_dots_fused(
                        F_loc, td, F_rot, kb, kc, interpret=interp
                    )
                else:
                    F_kb = lax.dynamic_slice_in_dim(
                        F_loc, kb * kc, kc, axis=1
                    )
                    x_kb = edge_dots_csr(
                        F_kb, td, fd_of(F_rot, d, kb), interpret=interp
                    )
                return x_acc + x_kb, None

            x_loc, _ = lax.scan(
                dots_kb, jnp.zeros((nt, 1, tile_t), F_loc.dtype),
                jnp.arange(n_kb),
            )
            x = lax.psum(x_loc, K_AXIS)

            def consume_kb(_, kb):
                if fused:
                    # neighbor-only (no -sumF + F fold: the ring
                    # accumulates gn across phases first)
                    gn_kb, ln_kb = grad_nbr_from_x_fused(
                        x, td, F_rot, kb, kc, cfg, interpret=interp
                    )
                else:
                    gn_kb, ln_kb = grad_nbr_from_x_csr(
                        x, td, fd_of(F_rot, d, kb), cfg, interpret=interp
                    )
                return None, (gn_kb, ln_kb)

            _, (gns, lns) = lax.scan(consume_kb, None, jnp.arange(n_kb))
            gn = gns.transpose(1, 0, 2).reshape(n_loc, k_loc)
            # ln depends only on the (already global) x — identical across
            # K blocks
            return gn_acc + gn, ln_acc + lns[0]

        init = (
            _mark_varying(
                jnp.zeros((n_loc, k_loc), F_loc.dtype), (NODES_AXIS, K_AXIS)
            ),
            _mark_varying(jnp.zeros(n_loc, F_loc.dtype), (NODES_AXIS,)),
        )
        F_back, (gn, ln) = rotate_scan(
            F_loc, init, (srcl, dstl, mask, bid), grad_sweep, perm,
            cfg.ring_overlap,
        )
        grad = gn - sumF[None, :] + F_loc
        node_llh = ln.astype(adt) + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)

        # --- rotation 2: candidate K-block dots -> psum -> consume ---
        def cand_sweep(cn_acc, xs, F_rot):
            td, d = td_of(xs)

            def cdots_kb(xc_acc, kb):
                g_kb = lax.dynamic_slice_in_dim(grad, kb * kc, kc, axis=1)
                if fused:
                    xc_kb = cand_dots_fused(
                        F_loc, g_kb, td, F_rot, kb, kc, cfg,
                        interpret=interp,
                    )
                else:
                    F_kb = lax.dynamic_slice_in_dim(
                        F_loc, kb * kc, kc, axis=1
                    )
                    xc_kb = cand_dots_csr(
                        F_kb, g_kb, td, fd_of(F_rot, d, kb), cfg,
                        interpret=interp,
                    )
                return xc_acc + xc_kb, None

            xc_loc, _ = lax.scan(
                cdots_kb, jnp.zeros((nt, num_s, tile_t), F_loc.dtype),
                jnp.arange(n_kb),
            )
            xc = lax.psum(xc_loc, K_AXIS)
            cb = cand_nbr_from_x_csr(xc, td, cfg, interpret=interp)
            return cn_acc + cb

        initc = _mark_varying(
            jnp.zeros((num_s, n_loc), F_loc.dtype), (NODES_AXIS,)
        )
        _, cb = rotate_scan(
            F_back, initc, (srcl, dstl, mask, bid), cand_sweep, perm,
            cfg.ring_overlap,
        )
        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_loc, grad, node_llh, cb.astype(adt), sumF, cfg, with_stats=True
        )
        sumF_new = lax.psum(sum_loc, NODES_AXIS)
        hist = lax.psum(hist, NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step_shard_tp(F_loc, srcl, dstl, mask, bid, it):
        srcl, dstl, mask, bid = srcl[0], dstl[0], mask[0], bid[0]
        n_loc, k_loc = F_loc.shape
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)       # (K_loc,)

        def td_of(xs):
            s, d, m, b_ = xs
            td = TilesDev(
                src_local=s, dst=d, mask=m, block_id=b_,
                block_b=block_b, tile_t=tile_t, n_blocks=n_blocks,
            )
            return td, d

        # --- rotation 1: partial dots -> psum over "k" -> grad consume ---
        def grad_sweep(acc, xs, F_rot):
            gn_acc, ln_acc = acc
            td, d = td_of(xs)
            k_loc = F_loc.shape[1]
            if fused:
                # fused TP phases: whole-K_loc rows DMA'd in-kernel from
                # the rotating shard (kb=0, kc=K_loc)
                x = lax.psum(
                    edge_dots_fused(
                        F_loc, td, F_rot, 0, k_loc, interpret=interp
                    ),
                    K_AXIS,
                )
                gn, ln = grad_nbr_from_x_fused(
                    x, td, F_rot, 0, k_loc, cfg, interpret=interp
                )
            else:
                fd = jnp.take(F_rot, d, axis=0)  # K_loc columns of F_rot
                x = lax.psum(
                    edge_dots_csr(F_loc, td, fd, interpret=interp), K_AXIS
                )
                gn, ln = grad_nbr_from_x_csr(
                    x, td, fd, cfg, interpret=interp
                )
            return gn_acc + gn, ln_acc + ln

        init = (
            _mark_varying(
                jnp.zeros((n_loc, k_loc), F_loc.dtype), (NODES_AXIS, K_AXIS)
            ),
            _mark_varying(jnp.zeros(n_loc, F_loc.dtype), (NODES_AXIS,)),
        )
        F_back, (gn, ln) = rotate_scan(
            F_loc, init, (srcl, dstl, mask, bid), grad_sweep, perm,
            cfg.ring_overlap,
        )
        grad = gn - sumF[None, :] + F_loc
        node_llh = ln.astype(adt) + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)

        # --- rotation 2: candidate partial dots -> psum -> consume ---
        def cand_sweep(cn_acc, xs, F_rot):
            td, d = td_of(xs)
            if fused:
                xc = lax.psum(
                    cand_dots_fused(
                        F_loc, grad, td, F_rot, 0, F_loc.shape[1], cfg,
                        interpret=interp,
                    ),
                    K_AXIS,
                )
            else:
                fd = jnp.take(F_rot, d, axis=0)
                xc = lax.psum(
                    cand_dots_csr(
                        F_loc, grad, td, fd, cfg, interpret=interp
                    ),
                    K_AXIS,
                )
            cb = cand_nbr_from_x_csr(xc, td, cfg, interpret=interp)
            return cn_acc + cb

        initc = _mark_varying(
            jnp.zeros((num_s, n_loc), F_loc.dtype), (NODES_AXIS,)
        )
        _, cb = rotate_scan(
            F_back, initc, (srcl, dstl, mask, bid), cand_sweep, perm,
            cfg.ring_overlap,
        )
        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_loc, grad, node_llh, cb.astype(adt), sumF, cfg, with_stats=True
        )
        sumF_new = lax.psum(sum_loc, NODES_AXIS)
        hist = lax.psum(hist, NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step_shard(F_loc, srcl, dstl, mask, bid, it):
        srcl, dstl, mask, bid = srcl[0], dstl[0], mask[0], bid[0]
        n_loc, k = F_loc.shape
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)

        def td_of(xs):
            s, d, m, b_ = xs
            td = TilesDev(
                src_local=s, dst=d, mask=m, block_id=b_,
                block_b=block_b, tile_t=tile_t, n_blocks=n_blocks,
            )
            return td, d

        # --- rotation 1: per-phase grad/LLH kernels, block accumulators ---
        def grad_sweep(acc, xs, F_rot):
            gn_acc, ln_acc = acc
            td, d = td_of(xs)
            if fused:
                # per-phase fused kernel: dst rows of the ROTATING shard
                # DMA'd in-kernel, double-buffered — no per-phase fd
                gn, ln = _grad_blocks_fused(F_loc, td, cfg, F_rot, interp)
            else:
                fd = jnp.take(F_rot, d, axis=0)  # local rows of F_rot
                gn, ln = _grad_blocks(F_loc, td, cfg, fd, interp)
            return gn_acc + gn, ln_acc + ln

        init = (
            _mark_varying(
                jnp.zeros((n_blocks, block_b, k), F_loc.dtype),
                (NODES_AXIS,),
            ),
            _mark_varying(
                jnp.zeros((n_blocks, 1, block_b), F_loc.dtype),
                (NODES_AXIS,),
            ),
        )
        F_back, (gn, ln) = rotate_scan(
            F_loc, init, (srcl, dstl, mask, bid), grad_sweep, perm,
            cfg.ring_overlap,
        )
        grad = gn.reshape(n_loc, k) - sumF[None, :] + F_loc
        node_llh = ln.reshape(n_loc).astype(adt) + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)

        # --- rotation 2: per-phase candidate kernels (neighbor terms) ---
        def cand_sweep(cn_acc, xs, F_rot):
            td, d = td_of(xs)
            if fused:
                cb = _cand_blocks_fused(F_loc, grad, td, cfg, F_rot, interp)
            else:
                fd = jnp.take(F_rot, d, axis=0)
                cb = _cand_blocks(
                    F_loc, grad, sumF, td, cfg, fd, interp,
                    with_tails=False,
                )
            return cn_acc + cb

        initc = _mark_varying(
            jnp.zeros((n_blocks, num_s, block_b), F_loc.dtype),
            (NODES_AXIS,),
        )
        # F_back: the full rotation restored F
        _, cb = rotate_scan(
            F_back, initc, (srcl, dstl, mask, bid), cand_sweep, perm,
            cfg.ring_overlap,
        )
        cand_nbr = cb.transpose(1, 0, 2).reshape(num_s, n_loc).astype(adt)
        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_loc, grad, node_llh, cand_nbr, sumF, cfg, with_stats=True
        )
        sumF_new = lax.psum(sum_loc, NODES_AXIS)
        hist = lax.psum(hist, NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step(state: TrainState, srcl, dstl, mask, bid) -> TrainState:
        F_new, sumF, llh, it, hist, gstats = shard_map(
            step_shard_kb
            if kc
            else (step_shard_tp if tp > 1 else step_shard),
            mesh=mesh,
            in_specs=(
                P(NODES_AXIS, K_AXIS),
                P(NODES_AXIS, None, None, None, None),
                P(NODES_AXIS, None, None, None),
                P(NODES_AXIS, None, None, None, None),
                P(NODES_AXIS, None, None),
                P(),
            ),
            out_specs=(
                P(NODES_AXIS, K_AXIS), P(K_AXIS), P(), P(), P(), P(),
            ),
            check_vma=False,       # pallas interpret + prefetch (see sharded)
        )(state.F, srcl, dstl, mask, bid, state.it)
        return TrainState(
            F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
            health=_shard_health(cfg, state, F_new, sumF, hist, gstats),
        )

    # tile arrays as jit ARGUMENTS (multi-controller: no closing over
    # non-addressable-device arrays; see parallel/sharded.py)
    jitted = jax.jit(step)

    def step_fn(state):
        return jitted(
            state, tiles["src_local"], tiles["dst_local"], tiles["mask"],
            tiles["block_id"],
        )

    step_fn.jitted = jitted
    step_fn.jit_args = (
        tiles["src_local"], tiles["dst_local"], tiles["mask"],
        tiles["block_id"],
    )
    return attach_donating(step_fn, step, fixed_args=step_fn.jit_args)


class RingBigClamModel(ShardedBigClamModel):
    """Sharded trainer using the ring-pass schedule (same API/trajectories
    as ShardedBigClamModel; different memory/communication profile).

    With the blocked-CSR kernels engaged (auto on TPU) each ring phase runs
    the MXU kernels over its (shard, phase) tile bucket; with the K axis
    also sharded (tp > 1) each phase uses the TP kernel split (partial dots
    + psum over "k"). The XLA chunk-scan schedule remains the fallback.

    EDGE-ORDER SENSITIVITY (measured, RINGMEM_r05.json): the per-(shard,
    phase) edge buckets are padded to the LARGEST bucket so phases can run
    under one compiled scan. On a graph whose node ids are locality-
    ordered (contiguous communities, BFS/DFS orderings), ~every edge is
    shard-local, the diagonal bucket holds ~all of the shard's edges, and
    the padded sweep does up to dp x the real edge work — the entire
    "7.8x ring slowdown" in WEAKSCALING_r04 (15.7x padded slots at dp=8).
    With edges spread uniformly over shard pairs the buckets balance and
    the ring steps at PARITY with the all-gather schedule while holding
    peak per-device F memory at O(2 * N/dp * K_loc) vs O(N * K_loc)
    (all-gather peak grows ~one per-shard F per added shard; compiler-
    verified). Since round 6 the fix is AUTOMATIC: balance=None (the
    default) measures the bucket imbalance up front and applies the
    degree-balanced relabeling (parallel/balance.py) whenever the warning
    heuristic fires (max bucket > RING_IMBALANCE_FACTOR x mean — VERDICT
    r5 weak #6: a schedule that needs a manual flag to not waste dp x the
    edge work is not a schedule). balance=False is the escape hatch
    (keeps the unbalanced layout AND the warning — the measurement
    configuration); balance=True forces the relabeling unconditionally.
    Results are mapped back to original ids either way (extract_F /
    FitResult), so the auto decision is invisible to callers that do not
    read raw internal state."""

    def __init__(
        self,
        g: Graph,
        cfg: BigClamConfig,
        mesh: Mesh,
        dtype=None,
        balance=None,
    ):
        if balance is None:
            from bigclam_tpu.obs import trace as _trace

            dp = mesh.shape[NODES_AXIS]
            # the pre-CSR n_pad: the CSR layout may round shard_rows up
            # further, but the imbalance statistic is a 4x-threshold
            # heuristic — the small padding shift cannot flip a
            # locality-ordered graph across it
            n_pad = _round_up(max(g.num_nodes, dp), dp)
            with _trace.span("ring/auto_balance_probe", dp=dp) as _sp:
                mx, mean = ring_bucket_imbalance(g, dp, n_pad)
                balance = dp > 1 and mx > RING_IMBALANCE_FACTOR * mean
                _sp.set(max_bucket=int(mx), mean_bucket=float(mean),
                        engaged=bool(balance))
            if balance:
                import os
                import sys

                if os.environ.get("BIGCLAM_QUIET") != "1":
                    print(
                        f"[bigclam] RingBigClamModel: auto-engaging "
                        f"balance relabeling (max bucket {mx} > "
                        f"{RING_IMBALANCE_FACTOR:g}x mean {mean:.0f}; "
                        "pass balance=False to keep the raw layout)",
                        file=sys.stderr,
                    )
        super().__init__(g, cfg, mesh, dtype=dtype, balance=balance)

    @property
    def engaged_path(self) -> str:
        """Ring CSR reports DISTINCT labels: its comm/memory profile
        (ppermute rotations, O(N/dp) peak HBM) is nothing like the
        all-gather sharded "csr" schedule, and metrics/bench records must
        tell them apart (ADVICE round-2). csr_ring_kb = K-blocked phases
        (K_loc beyond the kernels' VMEM bound)."""
        if not self._csr_wanted:
            return "xla"
        if getattr(self, "_csr_fused", False):
            return (
                "csr_ring_fused_kb"
                if getattr(self, "_csr_kc", 0)
                else "csr_ring_fused"
            )
        return "csr_ring_kb" if getattr(self, "_csr_kc", 0) else "csr_ring"

    def _bucket_slots_per_phase(self) -> int:
        """Padded edge-slot count of ONE (shard, phase) bucket of the
        built layout (the tp > 1 per-phase partial-dot psums price it)."""
        if self._csr_wanted:
            src = self._tiles_dev["src_local"]      # (dp, dp, nt, 1, t)
        else:
            src = self.edges.src                    # (dp, dp, C, chunk)
        return int(np.prod(src.shape[2:]))

    def _build_comms_model(self):
        from bigclam_tpu.obs import comms as _comms

        return _comms.ring_step_model(
            n_pad=self.n_pad,
            k_pad=self.k_pad,
            dp=self.mesh.shape[NODES_AXIS],
            tp=self.mesh.shape[K_AXIS],
            itemsize=jnp.dtype(self.dtype).itemsize,
            num_candidates=len(self.cfg.step_candidates),
            bucket_slots=self._bucket_slots_per_phase(),
            health_every=self.cfg.health_every,
            model=type(self).__name__,
            health_participants=self.mesh.size,
        )

    def _build_memory_model(self):
        """Ring memory model (obs.memory, ISSUE 12): the rotating-shard
        pair replaces the all-gather's full F copy — the O(2 * N/dp *
        K_loc) peak-HBM claim of this schedule, now a model instead of a
        docstring (its comms model carries the matching HIGHER wire
        claim; together they are the tradeoff in numbers)."""
        from bigclam_tpu.obs import memory as _mem

        cfg = self.cfg
        return _mem.ring_memory_model(
            self.n_pad,
            self.k_pad,
            self.mesh.shape[NODES_AXIS],
            self.mesh.shape[K_AXIS],
            jnp.dtype(self.dtype).itemsize,
            len(cfg.step_candidates),
            self._graph_buffer_bytes(),
            health_on=int(getattr(cfg, "health_every", 0) or 0) > 0,
            donate=bool(cfg.donate_state),
            rollback=int(getattr(cfg, "rollback_budget", 0) or 0) > 0,
            fd_bytes=self._memory_fd_bytes(),
            fused=self._csr_wanted and getattr(self, "_csr_fused", False),
            overlap=bool(cfg.ring_overlap),
            comms=self.comms,
            model=type(self).__name__,
        )

    def _csr_economy_ok(self, dp: int) -> bool:
        """Probe the ring tile layout: dp*dp buckets padded to the max tile
        count (empty buckets cost one tile each), per-phase fd gather
        bounded by GROUP_FD_BUDGET (it is materialized per scan step)."""
        from bigclam_tpu.models.bigclam import GROUP_FD_BUDGET
        from bigclam_tpu.ops.csr_tiles import (
            layout_economical,
            ring_block_tiles,
        )

        block_b, tile_t = self._csr_shape
        n_pad = _round_up(max(self.g.num_nodes, dp), dp * block_b)
        rbt = ring_block_tiles(self.g, dp, n_pad, block_b, tile_t)
        e = max(self.g.num_directed_edges, 1)
        n_tiles = rbt.src_local.shape[2]
        # fd columns materialized per phase: kc when the K axis is
        # processed in blocks (step_shard_kb gathers one K block at a
        # time), else K_loc
        k_loc = getattr(self, "_csr_kc", 0) or (
            self._csr_k_pad // self.mesh.shape[K_AXIS]
        )
        phase_fd = n_tiles * tile_t * k_loc * 4
        pad_ok = layout_economical(
            rbt.slots, e, dp * dp * rbt.n_blocks, tile_t
        )
        # fused phases gather in-kernel — no per-phase fd to budget
        if pad_ok and (
            getattr(self, "_csr_fused", False)
            or phase_fd <= GROUP_FD_BUDGET
        ):
            self._probe_tiles = rbt
            self._csr_nb = None
            return True
        if self.cfg.use_pallas_csr is True:
            raise ValueError(
                f"use_pallas_csr=True but ring layout uneconomical: "
                f"{rbt.slots - e} padded edge slots on {e}, per-phase fd "
                f"gather {phase_fd >> 20} MiB (try balance=True or the "
                "all-gather trainer)"
            )
        self._csr_reason = (
            f"ring layout uneconomical: {rbt.slots - e} padded edge slots "
            f"on {e} edges, per-phase fd gather {phase_fd >> 20} MiB"
        )
        return False

    def _build_csr_step(self, dp: int) -> None:
        from bigclam_tpu.obs import trace as _trace
        from bigclam_tpu.ops.csr_tiles import ring_block_tiles

        rbt = getattr(self, "_probe_tiles", None)
        self._probe_tiles = None
        if rbt is None or self._perm is not None:
            with _trace.span(
                "ring/tile_build", dp=dp, source="host_global"
            ) as _sp:
                rbt = ring_block_tiles(
                    self.g, dp, self.n_pad, *self._csr_shape
                )
                _sp.set(slots=int(rbt.slots))
        dp_, dpp, nt, t = rbt.src_local.shape
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = tile_pad_stats(rbt.mask)
        # same distribution as the XLA edge buckets: warn on the TRUE max
        # bucket edge count (tile-slot counts over-fire on balanced graphs
        # where per-dst-block rounding, not locality, pads the tiles);
        # single counting implementation — ring_bucket_imbalance
        _warn_bucket_imbalance(
            self.g, dp, ring_bucket_imbalance(self.g, dp, self.n_pad)[0]
        )

        def nspec(ndim: int) -> NamedSharding:
            return NamedSharding(
                self.mesh, P(NODES_AXIS, *([None] * (ndim - 1)))
            )

        tiles = {
            "src_local": put_sharded(
                rbt.src_local.reshape(dp_, dpp, nt, 1, t).astype(np.int32),
                nspec(5),
            ),
            "dst_local": put_sharded(
                rbt.dst_local.astype(np.int32), nspec(4)
            ),
            "mask": put_sharded(
                rbt.mask.reshape(dp_, dpp, nt, 1, t).astype(self.dtype),
                nspec(5),
            ),
            "block_id": put_sharded(rbt.block_id.astype(np.int32), nspec(3)),
            "block_b": rbt.block_b,
            "tile_t": rbt.tile_t,
            "n_blocks": rbt.n_blocks,
            "kc": getattr(self, "_csr_kc", 0),
            "fused": getattr(self, "_csr_fused", False),
        }
        self.edges = None
        self._tiles_dev = tiles                  # kept for rebuild_step
        self._step = make_ring_csr_train_step(self.mesh, tiles, self.cfg)

    def rebuild_step(self) -> None:
        """Swap in the train step for the CURRENT self.cfg, reusing the
        device buffers (same contract and step cache as
        ShardedBigClamModel.rebuild_step)."""
        from bigclam_tpu.models.bigclam import step_cfg_key

        key = step_cfg_key(self.cfg)
        if key not in self._step_cache:
            if self._csr_wanted:
                self._step_cache[key] = make_ring_csr_train_step(
                    self.mesh, self._tiles_dev, self.cfg
                )
            else:
                self._step_cache[key] = make_ring_train_step(
                    self.mesh, self.edges, self.cfg
                )
            from bigclam_tpu.obs import note_step_build

            note_step_build(self.cfg, type(self).__name__)
        self._step = self._step_cache[key]

    def _build_edges_and_step(self) -> None:
        dp = self.mesh.shape[NODES_AXIS]
        tp = self.mesh.shape[K_AXIS]
        if self._csr_wanted:
            self._build_csr_step(dp)
            return
        bound = edge_chunk_bound(
            self.cfg, max(self.k_pad // tp, 1), self.dtype
        )
        edges_host = ring_shard_edges(
            self.g, self.cfg, dp, self.n_pad, np.float32, chunk_bound=bound
        )
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = tile_pad_stats(edges_host.mask)
        espec = NamedSharding(self.mesh, P(NODES_AXIS, None, None, None))
        self.edges = EdgeChunks(
            src=put_sharded(edges_host.src, espec),
            dst=put_sharded(edges_host.dst, espec),
            mask=put_sharded(edges_host.mask.astype(self.dtype), espec),
        )
        self._step = make_ring_train_step(self.mesh, self.edges, self.cfg)


class StoreRingBigClamModel(_StoreBackedMixin, RingBigClamModel):
    """Ring-pass trainer fed per-host from a compiled graph cache (the
    store-native twin of RingBigClamModel, ISSUE 9).

    Each process loads ONLY its own shard blobs, builds only its rows of
    the per-(shard, phase) edge buckets (ring_shard_edges_local) or ring
    CSR tile buckets (ops.csr_tiles.local_ring_tile_parts), and places
    them with put_host_local — the ring's O(2 * N/dp * K_loc) peak-HBM
    profile now comes with O(shard) host RSS too, the combination the
    Friendster drill needs. Bucket padding is agreed via the manifest's
    global counts plus a one-int cross-host max exchange.

    Balance is baked at INGEST (`cli ingest --balance`) — the auto-balance
    relabeling of the in-memory ring cannot run without a global CSR, so
    an imbalanced unbalanced cache warns with a re-ingest hint instead.
    Trajectories are byte-identical to RingBigClamModel(balance=False) on
    the same graph."""

    def __init__(self, store, cfg: BigClamConfig, mesh: Mesh, dtype=None,
                 verify: bool = True):
        from bigclam_tpu.parallel.sharded import _StoreGraphView

        self._store_init(store, mesh, verify)
        # balance=False skips the in-memory auto-probe (it needs g.src);
        # the store build warns from local stats + the manifest instead
        super().__init__(
            _StoreGraphView(store), cfg, mesh, dtype=dtype, balance=False,
        )

    def _global_max_bucket(self, dp: int) -> int:
        from bigclam_tpu.parallel.multihost import global_max_int

        return global_max_int(
            ring_bucket_local_max(self._load_host_shard(), dp, self.n_pad)
        )

    def _csr_static_ok(self, tp: int) -> bool:
        # the ring K-blocked phases (kc) run on the SAME flat ring tiles,
        # so unlike the sharded store trainer kc needs no grouped layout —
        # only the row/block alignment constraint applies
        if not ShardedBigClamModel._csr_static_ok(self, tp):
            return False
        return self._store_rows_ok()

    def _csr_economy_ok(self, dp: int) -> bool:
        """Store-native twin of the ring economy probe — identical
        numbers (manifest edge counts + cross-host maxima), identical
        engage/fallback decision."""
        from bigclam_tpu.models.bigclam import GROUP_FD_BUDGET
        from bigclam_tpu.obs import trace as _trace
        from bigclam_tpu.ops.csr_tiles import (
            layout_economical,
            local_ring_tile_parts,
        )

        block_b, tile_t = self._csr_shape
        shard = self._load_host_shard()
        n_pad = dp * self.store.rows_per_shard
        with _trace.span("ring/tile_build", dp=dp, source="store") as _sp:
            parts = local_ring_tile_parts(
                shard, dp, n_pad, block_b, tile_t
            )
            local_max = max(
                p.n_tiles for phase_parts in parts for p in phase_parts
            )
            pad_tiles = self._store_pad_tiles_for(local_max)
            _sp.set(local_tiles=int(local_max), pad_tiles=int(pad_tiles))
        e = max(self.store.num_directed_edges, 1)
        slots = dp * dp * pad_tiles * tile_t
        k_loc = getattr(self, "_csr_kc", 0) or (
            self._csr_k_pad // self.mesh.shape[K_AXIS]
        )
        n_blocks = (n_pad // dp) // block_b
        phase_fd = pad_tiles * tile_t * k_loc * 4
        pad_ok = layout_economical(slots, e, dp * dp * n_blocks, tile_t)
        if pad_ok and (
            getattr(self, "_csr_fused", False)
            or phase_fd <= GROUP_FD_BUDGET
        ):
            self._probe_parts = parts
            self._store_ring_pad_tiles = pad_tiles
            self._csr_nb = None
            return True
        if self.cfg.use_pallas_csr is True:
            raise ValueError(
                f"use_pallas_csr=True but ring layout uneconomical: "
                f"{slots - e} padded edge slots on {e}, per-phase fd "
                f"gather {phase_fd >> 20} MiB (re-ingest with --balance "
                "or use the all-gather trainer)"
            )
        self._csr_reason = (
            f"store-backed ring layout uneconomical: {slots - e} padded "
            f"edge slots on {e} edges, per-phase fd gather "
            f"{phase_fd >> 20} MiB"
        )
        return False

    def _build_csr_step(self, dp: int) -> None:
        from bigclam_tpu.obs import trace as _trace
        from bigclam_tpu.ops.csr_tiles import stack_ring_tile_parts
        from bigclam_tpu.parallel.multihost import put_host_local

        parts = self._probe_parts
        self._probe_parts = None
        with _trace.span(
            "ring/tile_build", dp=dp, source="store", stage="stack"
        ) as _sp:
            rbt = stack_ring_tile_parts(parts, self._store_ring_pad_tiles)
            _sp.set(slots=int(dp * dp * rbt.src_local.shape[2] * rbt.tile_t))
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = {
            **tile_pad_stats(rbt.mask),
            "scope": "host_local",
            "pad_tiles": int(self._store_ring_pad_tiles),
        }
        _warn_imbalance_counts(
            self.store.num_directed_edges, dp, self._global_max_bucket(dp),
            hint="re-ingest the cache with --balance",
        )
        n_local, dpp, nt, t = rbt.src_local.shape

        def nspec(ndim: int) -> NamedSharding:
            return NamedSharding(
                self.mesh, P(NODES_AXIS, *([None] * (ndim - 1)))
            )

        tiles = {
            "src_local": put_host_local(
                rbt.src_local.reshape(n_local, dpp, nt, 1, t).astype(
                    np.int32
                ),
                nspec(5), (dp, dpp, nt, 1, t),
            ),
            "dst_local": put_host_local(
                rbt.dst_local.astype(np.int32), nspec(4), (dp, dpp, nt, t)
            ),
            "mask": put_host_local(
                rbt.mask.reshape(n_local, dpp, nt, 1, t).astype(self.dtype),
                nspec(5), (dp, dpp, nt, 1, t),
            ),
            "block_id": put_host_local(
                rbt.block_id.astype(np.int32), nspec(3), (dp, dpp, nt)
            ),
            "block_b": rbt.block_b,
            "tile_t": rbt.tile_t,
            "n_blocks": rbt.n_blocks,
            "kc": getattr(self, "_csr_kc", 0),
            "fused": getattr(self, "_csr_fused", False),
        }
        self.edges = None
        self._tiles_dev = tiles                  # kept for rebuild_step
        self._step = make_ring_csr_train_step(self.mesh, tiles, self.cfg)

    def _build_edges_and_step(self) -> None:
        dp = self.mesh.shape[NODES_AXIS]
        tp = self.mesh.shape[K_AXIS]
        if self._csr_wanted:
            self._build_csr_step(dp)
            return
        from bigclam_tpu.parallel.multihost import put_host_local

        shard = self._load_host_shard()
        max_count = self._global_max_bucket(dp)
        _warn_imbalance_counts(
            self.store.num_directed_edges, dp, max_count,
            hint="re-ingest the cache with --balance",
        )
        bound = edge_chunk_bound(
            self.cfg, max(self.k_pad // tp, 1), self.dtype
        )
        local = ring_shard_edges_local(
            shard, self.cfg, dp, self.n_pad, np.float32,
            chunk_bound=bound, max_count=max_count,
        )
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = {
            **tile_pad_stats(local.mask), "scope": "host_local",
        }
        espec = NamedSharding(self.mesh, P(NODES_AXIS, None, None, None))
        gshape = (dp,) + local.src.shape[1:]
        self.edges = EdgeChunks(
            src=put_host_local(local.src, espec, gshape),
            dst=put_host_local(local.dst, espec, gshape),
            mask=put_host_local(
                local.mask.astype(self.dtype), espec, gshape
            ),
        )
        self._step = make_ring_train_step(self.mesh, self.edges, self.cfg)
