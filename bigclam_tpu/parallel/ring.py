"""Ring-pass sharded training: rotate F node-shards around the ICI ring
instead of all-gathering F.

The C21 "ring-attention analog" (SURVEY.md §2/§5): at pod scale the
all-gather schedule of parallel/sharded.py materializes a full (N_pad, K_loc)
copy of F per device — impossible for com-Friendster-class graphs
(N=65M x K=25K). Here each device only ever holds TWO (N_pad/dp, K_loc)
shards: its own F_loc and a rotating buffer F_rot that `lax.ppermute`s
around the "nodes" ring, one hop per phase, exactly like ring attention
rotates KV blocks. Edges are bucketed by destination shard at ingest; in
phase r device i processes the bucket whose destinations live in shard
(i + r) % dp, accumulating neighbor LLH/gradient contributions, then passes
F_rot to its ring predecessor. Communication totals match the all-gather
(every shard visits every device) but peak HBM drops from O(N*K_loc) to
O(2 * N/dp * K_loc); the gradient pass and the 16-candidate Armijo pass each
take one full rotation (the candidate pass re-rotates because it needs the
finished gradient).

Semantics are IDENTICAL to the single-chip and all-gather trainers —
verified by the shard-invariance suite (tests/test_ring.py).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.models.bigclam import TrainState, edge_chunk_bound
from bigclam_tpu.ops.objective import EdgeChunks, edge_terms
from bigclam_tpu.parallel.mesh import K_AXIS, NODES_AXIS
from bigclam_tpu.parallel.multihost import put_sharded
from bigclam_tpu.parallel.sharded import ShardedBigClamModel, _mark_varying, _rowdot


def ring_shard_edges(
    g: Graph,
    cfg: BigClamConfig,
    dp: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
) -> EdgeChunks:
    """Bucket each src shard's edges by destination shard.

    Returns (dp, dp, C, chunk) arrays: axis 0 = owning (src) shard, axis 1 =
    ring phase r (destinations in shard (i + r) % dp). BOTH src and dst are
    stored shard-local; padding keeps src sorted (last local row) with
    mask 0. All buckets are padded to the global max bucket size (static
    SPMD shapes; power-law skew shows up as padding, mitigated by the
    degree-bucketing planned in PARITY.md).
    """
    shard_rows = n_pad // dp
    src_shard = g.src // shard_rows
    dst_shard = g.dst // shard_rows
    phase = (dst_shard - src_shard) % dp
    counts = np.zeros((dp, dp), dtype=np.int64)
    np.add.at(counts, (src_shard, phase), 1)
    max_count = max(int(counts.max()), 1)
    chunk = min(chunk_bound or cfg.edge_chunk, max_count)
    c = -(-max_count // chunk)
    padded = c * chunk
    src = np.full((dp, dp, padded), shard_rows - 1, dtype=np.int32)
    dst = np.zeros((dp, dp, padded), dtype=np.int32)
    mask = np.zeros((dp, dp, padded), dtype=np.float32)
    # stable bucket fill preserving CSR (src-sorted) order per bucket
    order = np.lexsort((np.arange(g.src.size), phase, src_shard))
    s_sorted = g.src[order]
    d_sorted = g.dst[order]
    ss = src_shard[order]
    ph = phase[order]
    # walk contiguous (shard, phase) runs
    run_starts = np.flatnonzero(
        np.r_[True, (ss[1:] != ss[:-1]) | (ph[1:] != ph[:-1])]
    )
    run_ends = np.r_[run_starts[1:], ss.size]
    for lo, hi in zip(run_starts, run_ends):
        i, r = int(ss[lo]), int(ph[lo])
        m = hi - lo
        src[i, r, :m] = s_sorted[lo:hi] - i * shard_rows
        dst[i, r, :m] = d_sorted[lo:hi] - ((i + r) % dp) * shard_rows
        mask[i, r, :m] = 1.0
    return EdgeChunks(
        src=src.reshape(dp, dp, c, chunk),
        dst=dst.reshape(dp, dp, c, chunk),
        mask=mask.reshape(dp, dp, c, chunk).astype(dtype),
    )


def make_ring_train_step(
    mesh: Mesh, edges: EdgeChunks, cfg: BigClamConfig
) -> Callable[[TrainState], TrainState]:
    """One jitted iteration with ring-rotated F shards (two rotations:
    gradient pass + candidate pass)."""
    dp = mesh.shape[NODES_AXIS]
    perm = [(j, (j - 1) % dp) for j in range(dp)]   # send to ring predecessor

    def step_shard(F_loc, src, dst, mask, it):
        src, dst, mask = src[0], dst[0], mask[0]    # (dp, C, chunk), phase-major
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        etas = jnp.asarray(cfg.step_candidates, F_loc.dtype)
        n_loc = F_loc.shape[0]
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)

        def sweep_chunks(carry_fn, init, s_ph, d_ph, m_ph, F_rot):
            """Scan a phase's chunks, accumulating via carry_fn."""
            def body(acc, sdm):
                return carry_fn(acc, sdm, F_rot), None
            out, _ = lax.scan(body, init, (s_ph, d_ph, m_ph))
            return out

        # --- rotation 1: fused gradient + LLH ---
        def grad_chunk(acc, sdm, F_rot):
            nbr_llh, nbr_grad = acc
            s, d, m = sdm
            fs, fd = F_loc[s], F_rot[d]
            x = lax.psum(jnp.einsum("ek,ek->e", fs, fd), K_AXIS)
            p, ell = edge_terms(x, cfg)
            coeff = m / (1.0 - p)
            return (
                nbr_llh + jax.ops.segment_sum(
                    (ell * m).astype(adt), s, num_segments=n_loc,
                    indices_are_sorted=True,
                ),
                nbr_grad + jax.ops.segment_sum(
                    fd * coeff[:, None], s, num_segments=n_loc,
                    indices_are_sorted=True,
                ),
            )

        def grad_phase(carry, sdm_ph):
            (F_rot, acc) = carry
            s_ph, d_ph, m_ph = sdm_ph
            acc = sweep_chunks(grad_chunk, acc, s_ph, d_ph, m_ph, F_rot)
            F_rot = lax.ppermute(F_rot, NODES_AXIS, perm)
            return (F_rot, acc), None

        init_acc = (
            _mark_varying(jnp.zeros(n_loc, adt), (NODES_AXIS,)),
            _mark_varying(jnp.zeros_like(F_loc), (NODES_AXIS, K_AXIS)),
        )
        (F_back, (nbr_llh, nbr_grad)), _ = lax.scan(
            grad_phase, (F_loc, init_acc), (src, dst, mask)
        )
        grad = nbr_grad - sumF[None, :] + F_loc
        node_llh = nbr_llh + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)

        # --- rotation 2: the 16 Armijo candidates ---
        def cand_chunk(cand, sdm, F_rot):
            s, d, m = sdm
            fs, gs, fd = F_loc[s], grad[s], F_rot[d]

            def one_eta(eta):
                nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
                xc = lax.psum(jnp.einsum("ek,ek->e", nf, fd), K_AXIS)
                _, ellc = edge_terms(xc, cfg)
                return jax.ops.segment_sum(
                    (ellc * m).astype(adt), s, num_segments=n_loc,
                    indices_are_sorted=True,
                )

            return cand + lax.map(one_eta, etas)

        def cand_phase(carry, sdm_ph):
            (F_rot, cand) = carry
            s_ph, d_ph, m_ph = sdm_ph
            cand = sweep_chunks(cand_chunk, cand, s_ph, d_ph, m_ph, F_rot)
            F_rot = lax.ppermute(F_rot, NODES_AXIS, perm)
            return (F_rot, cand), None

        init_cand = _mark_varying(
            jnp.zeros((len(cfg.step_candidates), n_loc), adt), (NODES_AXIS,)
        )
        (_, cand_nbr), _ = lax.scan(
            cand_phase, (F_back, init_cand), (src, dst, mask)
        )

        # --- Armijo acceptance + Jacobi update (node-local, as sharded.py) ---
        gg = _rowdot(grad, grad).astype(adt)

        def tail_for(eta):
            nf = jnp.clip(F_loc + eta * grad, cfg.min_f, cfg.max_f)
            sf_adj = sumF[None, :] - F_loc + nf
            return (-_rowdot(nf, sf_adj) + _rowdot(nf, nf)).astype(adt)

        tails = lax.map(tail_for, etas)
        cand_llh = cand_nbr + tails
        ok = cand_llh >= node_llh[None, :] + cfg.alpha * etas[:, None] * gg[None, :]
        best_eta = jnp.max(jnp.where(ok, etas[:, None], 0.0), axis=0)
        accepted = jnp.any(ok, axis=0)
        F_new = jnp.where(
            accepted[:, None],
            jnp.clip(F_loc + best_eta[:, None] * grad, cfg.min_f, cfg.max_f),
            F_loc,
        )
        sumF_new = lax.psum(F_new.sum(axis=0), NODES_AXIS)
        return F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1

    def step(state: TrainState) -> TrainState:
        F_new, sumF, llh, it = jax.shard_map(
            step_shard,
            mesh=mesh,
            in_specs=(
                P(NODES_AXIS, K_AXIS),
                P(NODES_AXIS, None, None, None),
                P(NODES_AXIS, None, None, None),
                P(NODES_AXIS, None, None, None),
                P(),
            ),
            out_specs=(P(NODES_AXIS, K_AXIS), P(K_AXIS), P(), P()),
        )(state.F, edges.src, edges.dst, edges.mask, state.it)
        return TrainState(F=F_new, sumF=sumF, llh=llh, it=it)

    return jax.jit(step)


class RingBigClamModel(ShardedBigClamModel):
    """Sharded trainer using the ring-pass schedule (same API/trajectories
    as ShardedBigClamModel; different memory/communication profile)."""

    def _csr_static_ok(self, tp: int) -> bool:
        # the ring schedule rotates F shards; the blocked-CSR kernels assume
        # an all-gathered F — not applicable here (future work, PARITY.md)
        if self.cfg.use_pallas_csr is True:
            raise ValueError(
                "use_pallas_csr=True is not supported on the ring schedule "
                "(the kernels need an all-gathered F); use "
                "ShardedBigClamModel or leave use_pallas_csr unset"
            )
        from bigclam_tpu.models.bigclam import csr_want_reason

        want, reason = csr_want_reason(self.cfg)
        self._csr_reason = (
            "ring schedule: CSR kernels not yet supported" if want else reason
        )
        return False

    def _build_edges_and_step(self) -> None:
        dp = self.mesh.shape[NODES_AXIS]
        tp = self.mesh.shape[K_AXIS]
        bound = edge_chunk_bound(
            self.cfg, max(self.k_pad // tp, 1), self.dtype
        )
        edges_host = ring_shard_edges(
            self.g, self.cfg, dp, self.n_pad, np.float32, chunk_bound=bound
        )
        espec = NamedSharding(self.mesh, P(NODES_AXIS, None, None, None))
        self.edges = EdgeChunks(
            src=put_sharded(edges_host.src, espec),
            dst=put_sharded(edges_host.dst, espec),
            mask=put_sharded(edges_host.mask.astype(self.dtype), espec),
        )
        self._step = make_ring_train_step(self.mesh, self.edges, self.cfg)
