"""Device-mesh construction for BigCLAM's two parallel axes.

Replaces C20/C21 (SURVEY.md §2): the reference's only distribution strategy
was Spark data-parallelism over node partitions with the model fully
replicated (F and the adjacency broadcast to every executor each iteration,
Bigclamv2.scala:34,118). Here the mesh has two named axes:

  * "nodes" — data parallelism over contiguous node ranges: F rows, edge
    lists and all per-node state are sharded; the analog of the reference's
    RDD partitioning, minus the replication.
  * "k"     — tensor parallelism over the community axis: F columns and sumF
    are sharded when N*K exceeds a chip's HBM (the TP analog in SURVEY.md
    §5); per-node F_u.F_v dots become partial dots + psum over "k".

Collectives ride ICI within a slice and DCN across slices, scheduled by XLA
from the shardings (jax.lax.psum / all_gather inside shard_map) — there is no
driver in the data path.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

NODES_AXIS = "nodes"
K_AXIS = "k"


def make_mesh(
    shape: Tuple[int, int] = (1, 1),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (nodes, k) mesh over the given devices (default: all).

    shape = (node_shards, k_shards); their product must equal the device
    count used. For multi-host meshes pass jax.devices() after
    jax.distributed.initialize() — device order determines which axis rides
    ICI; keep the faster-varying axis ("k") within a host/slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    dp, tp = shape
    if dp * tp != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {dp * tp} devices, got {len(devices)}"
        )
    arr = np.asarray(devices).reshape(dp, tp)
    return Mesh(arr, (NODES_AXIS, K_AXIS))
