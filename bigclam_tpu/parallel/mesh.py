"""Device-mesh construction for BigCLAM's two parallel axes.

Replaces C20/C21 (SURVEY.md §2): the reference's only distribution strategy
was Spark data-parallelism over node partitions with the model fully
replicated (F and the adjacency broadcast to every executor each iteration,
Bigclamv2.scala:34,118). Here the mesh has two named axes:

  * "nodes" — data parallelism over contiguous node ranges: F rows, edge
    lists and all per-node state are sharded; the analog of the reference's
    RDD partitioning, minus the replication.
  * "k"     — tensor parallelism over the community axis: F columns and sumF
    are sharded when N*K exceeds a chip's HBM (the TP analog in SURVEY.md
    §5); per-node F_u.F_v dots become partial dots + psum over "k".

Collectives ride ICI within a slice and DCN across slices, scheduled by XLA
from the shardings (jax.lax.psum / all_gather inside shard_map) — there is no
driver in the data path.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

NODES_AXIS = "nodes"
K_AXIS = "k"

# 2D edge-block partitioning (ISSUE 16 / ROADMAP item 4): the node axis is
# factored into processor rows x replica cols per arXiv:2002.10083. F stays
# fully sharded over BOTH axes (block b = i*C + j on chip (i, j) — no
# replication anywhere); "cols" is the replica-group axis for the src-row
# gather / grad psum / candidate psum_scatter, "rows" is the group axis for
# the capped closure all_to_all. A trivial size-1 "k" axis keeps the shared
# 1D helpers (_rowdot, armijo_tail_select_sharded) usable unchanged.
ROWS_AXIS = "rows"
COLS_AXIS = "cols"


def make_mesh(
    shape: Tuple[int, int] = (1, 1),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (nodes, k) mesh over the given devices (default: all).

    shape = (node_shards, k_shards); their product must equal the device
    count used. For multi-host meshes pass jax.devices() after
    jax.distributed.initialize() — device order determines which axis rides
    ICI; keep the faster-varying axis ("k") within a host/slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    dp, tp = shape
    if dp * tp != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {dp * tp} devices, got {len(devices)}"
        )
    arr = np.asarray(devices).reshape(dp, tp)
    return Mesh(arr, (NODES_AXIS, K_AXIS))


def make_mesh_2d(
    shape: Tuple[int, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (rows, cols, k=1) mesh for the 2D edge-block partition.

    shape = (dp_rows, replica_cols); their product must equal the device
    count used. Device order is row-major, so chip (i, j) = device i*C + j
    owns node block b = i*C + j under P(("rows", "cols")) — the same
    contiguous block order the 1D node axis uses, which is what makes the
    C=1 degeneration bit-identical to the 1D schedule. The size-1 "k" axis
    exists only so axis-named helpers shared with the 1D trainers resolve;
    2D does not shard the community axis (refused at model build).
    """
    devices = list(devices if devices is not None else jax.devices())
    rows, cols = shape
    if rows * cols != len(devices):
        raise ValueError(
            f"2d mesh shape {shape} needs {rows * cols} devices, got {len(devices)}"
        )
    arr = np.asarray(devices).reshape(rows, cols, 1)
    return Mesh(arr, (ROWS_AXIS, COLS_AXIS, K_AXIS))
