"""Communication-avoiding 2D edge-block partitioning (ISSUE 16).

Kills the dense F all-gather for the large-K regime. The 1D sharded step
(parallel/sharded.py) all-gathers the FULL (N_pad, K_pad) F every
iteration — (p-1)/p * N*K*itemsize per chip per step, flat in p: at
Friendster scale with K = 25,000 that one transient is the capacity wall
long before FLOPs are. Here the node axis is factored into R processor
rows x C replica cols (the classic 2D SpMM factorization, arXiv:2002.10083
lineage) and each chip exchanges only

  * its processor row's F rows  — all_gather over "cols",  N*K/(R*C) * (C-1)
    wire bytes: 1/R of the 1D gather, and
  * the CLOSURE of its edge block's dst columns — a capped all_to_all over
    "rows" of just the rows some edge actually touches (gather lists baked
    at ingest, graph/store.bake_closure_lists).

Layout (mesh from parallel.mesh.make_mesh_2d — axes "rows" x "cols" x a
trivial size-1 "k" so helpers shared with the 1D families resolve):

  F          (N_pad, K_pad)  sharded P(("rows","cols"), "k") — block
                             b = i*C + j on chip (i, j); NO replication
                             anywhere (the accumulator/scratch state is
                             likewise replica-sharded: tentpole (c))
  edges      (p, c, chunk)   P(("rows","cols")): chip (i, j) owns the edge
                             BLOCK (src in processor row i's node blocks,
                             dst in column stripe {b : b % C == j}); src is
                             stored group-LOCAL, dst as a CLOSURE position
  send_idx   (p, R, cap)     P(("rows","cols")): block-local rows chip
                             (i', j) must send each requester row group

Step (chip (i, j)): all_gather F over "cols" -> the C*n_blk src rows of
row group i; gather own rows listed in send_idx and all_to_all over
"rows" -> closure_flat, the (R*cap, K) table of every dst row this block
touches; the same fused grad/LLH + 16-candidate scans as the 1D XLA step
(dst indices pre-baked as closure positions); partial-group psum of grad
over "cols"; psum_scatter of the candidate/LLH accumulators over "cols"
(each chip Armijo-selects ONLY its own n_blk rows); scalar psums over
both axes. At C == 1 every "cols" collective is skipped at trace time and
the schedule degenerates to the 1D sharded step bit-for-bit (pinned by
scripts/comms2d_gate.py).

The whole schedule is expressible in shard_map over named axes —
lax.all_gather / lax.all_to_all / lax.psum_scatter partial-group
collectives all accept a single mesh axis — so no jax custom_partitioning
escape hatch is needed (DESIGN.md records the analysis).

ISSUE 17 wires the round-17 FUSED Pallas superstep to this mesh: the
closure table is already the flat row table the kernels' dst-DMA
consumes, so the per-block CSR tiles store dst as closure POSITIONS and
the in-kernel cur/next DMA descriptors stream compacted closure rows
exactly the way the 1D dst-row gather does (kernel_path csr_fused_2d,
csr_fused_2d_kb for the K-blocked large-K layout; C = 1 stays
bit-identical to the 1D fused trainer). Only the fused superstep is
wired — the split/grouped kernel suites have no closure-buffer DMA path
and fall back with an explicit reason. The second ISSUE 17 leg replaces
the dense neighbor-grad psum over "cols" with a touched-rows-only
exchange over the baked closure lists (grad_exchange="closure",
parallel.sparse_collectives.closure_grad_allreduce): two capped
all_to_alls move only the rows some edge actually touched, with a
per-step dense-psum fallback on cap overflow and the same
comm_ids/comm_dense counters the sparse representation's allreduce
rides.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.models.bigclam import (
    TrainState,
    _round_up,
    attach_donating,
    edge_chunk_bound,
)
from bigclam_tpu.ops import diagnostics as dx
from bigclam_tpu.ops.objective import EdgeChunks, edge_terms
from bigclam_tpu.parallel.mesh import COLS_AXIS, K_AXIS, ROWS_AXIS
from bigclam_tpu.parallel.multihost import put_host_local, put_sharded
from bigclam_tpu.parallel.sharded import (
    ShardedBigClamModel,
    _StoreBackedMixin,
    _StoreGraphView,
    _mark_varying,
    _rowdot,
    _shard_health,
    armijo_tail_select_sharded,
)
from bigclam_tpu.utils.compat import shard_map


def twod_mesh_shape(cfg: BigClamConfig, num_devices: int) -> Tuple[int, int]:
    """(R, C) for `num_devices` chips under cfg.replica_cols."""
    C = max(int(cfg.replica_cols or 1), 1)
    if num_devices % C:
        raise ValueError(
            f"replica_cols={C} does not divide the device count "
            f"{num_devices}; pick a divisor"
        )
    return (num_devices // C, C)


@dataclasses.dataclass(frozen=True)
class TwoDLayout:
    """Host-side 2D edge-block layout: the (blocks, c, chunk) edge arrays
    (global rows from twod_shard_edges, this host's rows from
    twod_shard_edges_local), the (blocks, R, cap) contributor send lists,
    and the telemetry counts the comms/balance models price from."""

    edges: EdgeChunks
    send_idx: np.ndarray
    cap: int
    block_edge_counts: np.ndarray      # per edge block, row-major (i, j)
    closure_rows: int                  # real (unpadded) closure rows/step
    # touched-rows grad-exchange tables (ISSUE 17 second leg; None at
    # C == 1 where there is no cols reduction to compress). out/in are
    # (local_blocks, C, grad_cap) int32 — out ids group-local with
    # sentinel C*n_blk, in ids block-local with sentinel n_blk — and
    # grad_counts is each block's TRUE worst pair size (the runtime
    # overflow check against an explicit cfg.closure_grad_cap).
    grad_out: Optional[np.ndarray] = None
    grad_in: Optional[np.ndarray] = None
    grad_counts: Optional[np.ndarray] = None
    grad_cap: int = 0                  # baked table width (0 = no rows)
    grad_pair_max: int = 0             # exact global worst pair size


def _grad_table_cap(cfg: BigClamConfig, pair_max: int, n_blk: int) -> int:
    """Exchange-table width: an explicit cfg.closure_grad_cap is clamped
    to the block size (wider than n_blk can never pay — the dense psum
    already moves n_blk rows); 0 means auto = the exact baked worst pair
    size, so the auto cap never overflows at runtime."""
    explicit = int(getattr(cfg, "closure_grad_cap", 0) or 0)
    if explicit > 0:
        return min(explicit, n_blk)
    return int(pair_max)


def _pack_grad_tables(
    out_sets, in_sets, C: int, n_blk: int, group_rows: int, gcap: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack per-block touched-row sets into the fixed-width int32 tables
    closure_grad_allreduce consumes. `out_sets[b][c]` are the group-local
    rows of block c this edge block touches (sentinel-filled to
    group_rows); `in_sets[b][c]` are the block-local rows of block b
    that peer column c touches (sentinel n_blk). Entries past `gcap` are
    truncated — the per-block true worst size in the returned counts is
    what flips the runtime to the dense psum when that happens."""
    nloc = len(out_sets)
    out_tab = np.full((nloc, C, max(gcap, 1)), group_rows, dtype=np.int32)
    in_tab = np.full((nloc, C, max(gcap, 1)), n_blk, dtype=np.int32)
    counts = np.zeros(nloc, dtype=np.int32)
    for r in range(nloc):
        worst = 0
        for c in range(C):
            o = np.asarray(out_sets[r][c], dtype=np.int64)
            i_ = np.asarray(in_sets[r][c], dtype=np.int64)
            worst = max(worst, int(o.size), int(i_.size))
            if gcap > 0:
                out_tab[r, c, : min(o.size, gcap)] = o[:gcap]
                in_tab[r, c, : min(i_.size, gcap)] = i_[:gcap]
        counts[r] = worst
    return out_tab, in_tab, counts


def _remap_dst(dsel: np.ndarray, unions, n_blk: int, C: int,
               cap: int) -> np.ndarray:
    """Global dst ids -> closure positions i_con*cap + rank-in-union."""
    pos = np.empty(dsel.shape[0], dtype=np.int64)
    icon = (dsel // n_blk) // C
    for i_con in np.unique(icon):
        sel = icon == i_con
        pos[sel] = i_con * cap + np.searchsorted(
            unions[int(i_con)], dsel[sel]
        )
    return pos


def twod_shard_edges(
    g: Graph,
    cfg: BigClamConfig,
    R: int,
    C: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
) -> TwoDLayout:
    """Partition directed edges into R*C edge BLOCKS: block (i, j) holds
    the edges with src in processor row i's node blocks and dst in column
    stripe j ({b : b % C == j}).

    CSR order means each row group's edges are one contiguous slice and
    the stable stripe selection preserves it, so at C == 1 the layout is
    exactly shard_edges' (same slices, same chunk geometry, same src
    rebase/padding) — the bit-identity anchor. src is group-LOCAL
    ([0, C*n_blk); pad = last local row, mask 0); dst is stored as a
    CLOSURE position i_con*cap + rank (pad 0 — a real gathered row whose
    contribution is masked to an exact +0.0)."""
    p = R * C
    n_blk = n_pad // p
    group_rows = C * n_blk
    gsrc = np.asarray(g.src)
    gdst = np.asarray(g.dst)
    gb = np.searchsorted(
        gsrc, np.arange(0, n_pad + group_rows, group_rows)
    )
    sel: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray]] = {}
    lists: Dict[Tuple[int, int, int], np.ndarray] = {}
    counts = np.zeros((R, C), dtype=np.int64)
    for i in range(R):
        s_i = gsrc[gb[i]:gb[i + 1]].astype(np.int64)
        d_i = gdst[gb[i]:gb[i + 1]].astype(np.int64)
        dblk = d_i // n_blk
        for j in range(C):
            m = (dblk % C) == j
            dsel = d_i[m]
            sel[(i, j)] = (s_i[m] - i * group_rows, dsel)
            counts[i, j] = dsel.size
            icon = dblk[m] // C
            for i_con in range(R):
                # union over the group's shards of out(s -> block): the
                # rows of block (i_con, j) this edge block must gather
                lists[(i, j, i_con)] = np.unique(dsel[icon == i_con])
    cap = max(1, max((u.size for u in lists.values()), default=1))
    max_count = int(counts.max()) if counts.size else 1
    chunk = min(chunk_bound or cfg.edge_chunk, max(max_count, 1))
    c = max(1, -(-max_count // chunk))
    padded = c * chunk
    src = np.full((p, padded), group_rows - 1, dtype=np.int32)
    dst = np.zeros((p, padded), dtype=np.int32)
    mask = np.zeros((p, padded), dtype=np.float32)
    send_idx = np.zeros((p, R, cap), dtype=np.int32)
    for i in range(R):
        for j in range(C):
            b = i * C + j
            s_l, d_l = sel[(i, j)]
            m = s_l.size
            src[b, :m] = s_l
            dst[b, :m] = _remap_dst(
                d_l, {ic: lists[(i, j, ic)] for ic in range(R)},
                n_blk, C, cap,
            )
            mask[b, :m] = 1.0
            # contributor side of the SAME lists: block b sends each
            # requester row group the rows that group's edges touch
            lo_b = b * n_blk
            for i_req in range(R):
                u = lists[(i_req, j, i)]
                send_idx[b, i_req, :u.size] = (u - lo_b).astype(np.int32)
    grad_out = grad_in = grad_counts = None
    grad_cap = pair_max = 0
    if C > 1:
        touched = {
            (i, j): np.unique(sel[(i, j)][0])
            for i in range(R) for j in range(C)
        }

        def seg(i: int, j: int, c: int) -> np.ndarray:
            # touched(i, j) rows falling in block (i, c)'s group-local range
            t = touched[(i, j)]
            lo = np.searchsorted(t, c * n_blk)
            hi = np.searchsorted(t, (c + 1) * n_blk)
            return t[lo:hi]

        pair_max = max(
            (
                seg(i, j, c).size
                for i in range(R) for j in range(C) for c in range(C)
            ),
            default=0,
        )
        grad_cap = _grad_table_cap(cfg, pair_max, n_blk)
        out_sets = [
            [seg(i, j, c) for c in range(C)]
            for i in range(R) for j in range(C)
        ]
        in_sets = [
            [seg(i, c, j) - j * n_blk for c in range(C)]
            for i in range(R) for j in range(C)
        ]
        grad_out, grad_in, grad_counts = _pack_grad_tables(
            out_sets, in_sets, C, n_blk, group_rows, grad_cap
        )
    return TwoDLayout(
        edges=EdgeChunks(
            src=src.reshape(p, c, chunk),
            dst=dst.reshape(p, c, chunk),
            mask=mask.reshape(p, c, chunk).astype(dtype),
        ),
        send_idx=send_idx,
        cap=cap,
        block_edge_counts=counts,
        closure_rows=int(sum(u.size for u in lists.values())),
        grad_out=grad_out,
        grad_in=grad_in,
        grad_counts=grad_counts,
        grad_cap=grad_cap,
        grad_pair_max=int(pair_max),
    )


def twod_shard_edges_local(
    shard,
    pair_lists: Dict[int, tuple],
    cfg: BigClamConfig,
    R: int,
    C: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
) -> TwoDLayout:
    """This host's rows of the 2D edge blocks, from a graph-store slice
    (graph/store.HostShard) — the out-of-core twin of twod_shard_edges:
    no global CSR exists anywhere.

    `pair_lists` maps each OWNED shard s to its (out_ids, in_ids,
    edge_counts) closure triple — the ingest-baked v3 lists
    (GraphStore.load_closure_lists) or the v2 streaming fallback
    (store.closure_pair_lists on the host's own CSR). Both sides of every
    exchange come from the host's OWN shards: the gather unions from the
    requester group's out-lists, the send lists from the owned block's
    in-lists — identical sets by edge symmetry (in(b)[s] == out(s)[b]),
    which is what keeps files_read isolation intact. A None pair (the
    bake's cap overflow) degrades to the FULL dst block on both sides.
    Padded geometry (chunk count, closure cap) is agreed cross-host via
    one-int max exchanges (multihost.global_max_int), mirroring the CSR
    tile pad contract."""
    from bigclam_tpu.parallel.multihost import global_max_int

    p = R * C
    n_blk = n_pad // p
    group_rows = C * n_blk
    if shard.rows_per_shard != n_blk:
        raise ValueError(
            f"cache rows_per_shard={shard.rows_per_shard} != trainer "
            f"block rows {n_blk} (n_pad={n_pad}, rows*cols={p}); "
            "recompile the cache with num_shards == rows*cols"
        )
    own = list(shard.shard_ids)
    if own and (own[0] % C or len(own) % C):
        raise ValueError(
            "store-native 2d needs every process to own whole processor "
            f"rows: first owned shard {own[0]} and owned count {len(own)} "
            f"must be multiples of replica_cols={C} — use dp_rows "
            "divisible by the process count (or fewer cols)"
        )
    n = shard.num_nodes

    def full_block(b: int) -> np.ndarray:
        return np.arange(b * n_blk, min((b + 1) * n_blk, n), dtype=np.int64)

    def union_over_group(i_req: int, b_con: int, side: int) -> np.ndarray:
        """Union over requester group i_req's shards of the pair lists
        against block b_con; side 0 = out (gather), 1 = in (send). The
        overflow decision matches across sides because the paired lists
        have equal sizes."""
        parts = []
        for s in range(i_req * C, (i_req + 1) * C):
            lst = (
                pair_lists[s][0][b_con] if side == 0
                else pair_lists[b_con][1][s]
            )
            if lst is None:
                return full_block(b_con)
            parts.append(np.asarray(lst, dtype=np.int64))
        if not parts:
            return np.empty(0, dtype=np.int64)
        return np.unique(np.concatenate(parts))

    groups = range(own[0] // C, (own[-1] + 1) // C) if own else range(0)
    unions: Dict[Tuple[int, int, int], np.ndarray] = {}
    for i in groups:
        for j in range(C):
            for i_con in range(R):
                unions[(i, j, i_con)] = union_over_group(
                    i, i_con * C + j, side=0
                )
    sends: Dict[Tuple[int, int], np.ndarray] = {}
    for b in own:
        for i_req in range(R):
            sends[(b, i_req)] = union_over_group(i_req, b, side=1)
    local_cap = max(
        [u.size for u in unions.values()]
        + [u.size for u in sends.values()] + [1]
    )
    cap = global_max_int(int(local_cap))

    deg = np.diff(shard.indptr)
    blocks: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray]] = {}
    counts: Dict[Tuple[int, int], int] = {}
    for i in groups:
        glo = min(i * group_rows, n)
        ghi = min((i + 1) * group_rows, n)
        e0 = int(shard.indptr[glo - shard.lo])
        e1 = int(shard.indptr[ghi - shard.lo])
        srcs = np.repeat(
            np.arange(glo, ghi, dtype=np.int64),
            deg[glo - shard.lo: ghi - shard.lo],
        )
        dsts = np.asarray(shard.indices[e0:e1], dtype=np.int64)
        stripe = (dsts // n_blk) % C
        for j in range(C):
            m = stripe == j
            blocks[(i, j)] = (srcs[m] - i * group_rows, dsts[m])
            counts[(i, j)] = int(m.sum())
            want = sum(
                pair_lists[s][2][i_con * C + j]
                for s in range(i * C, (i + 1) * C)
                for i_con in range(R)
            )
            if counts[(i, j)] != want:
                raise ValueError(
                    f"edge block ({i}, {j}): closure lists say {want} "
                    f"directed edges but the loaded CSR holds "
                    f"{counts[(i, j)]} — cache inconsistent (partially "
                    "rebuilt, or loaded with verify=False?)"
                )
    max_count = global_max_int(
        max(list(counts.values()) + [1])
    )
    chunk = min(chunk_bound or cfg.edge_chunk, max(max_count, 1))
    c = max(1, -(-max_count // chunk))
    padded = c * chunk
    n_local = len(own)
    src = np.full((n_local, padded), group_rows - 1, dtype=np.int32)
    dst = np.zeros((n_local, padded), dtype=np.int32)
    mask = np.zeros((n_local, padded), dtype=np.float32)
    send_idx = np.zeros((n_local, R, cap), dtype=np.int32)
    local_counts = np.zeros(n_local, dtype=np.int64)
    for row, b in enumerate(own):
        i, j = b // C, b % C
        s_l, d_l = blocks[(i, j)]
        m = s_l.size
        local_counts[row] = m
        src[row, :m] = s_l
        dst[row, :m] = _remap_dst(
            d_l, {ic: unions[(i, j, ic)] for ic in range(R)},
            n_blk, C, cap,
        )
        mask[row, :m] = 1.0
        lo_b = b * n_blk
        for i_req in range(R):
            u = sends[(b, i_req)]
            send_idx[row, i_req, :u.size] = (u - lo_b).astype(np.int32)
    grad_out = grad_in = grad_counts = None
    grad_cap = pair_max = 0
    if C > 1:
        def stripe_in(s_shard: int, j: int) -> np.ndarray:
            # global ids of shard s_shard's rows with an edge into stripe
            # j — the union of its baked in-lists against the stripe's
            # blocks; by edge symmetry this equals the src-touched set.
            # A None pair (bake cap overflow) degrades to the full block:
            # a superset only adds rows whose partials are exactly 0.0,
            # so store and in-memory trajectories still agree.
            parts = []
            for i_con in range(R):
                lst = pair_lists[s_shard][1][i_con * C + j]
                if lst is None:
                    return full_block(s_shard)
                parts.append(np.asarray(lst, dtype=np.int64))
            if not parts:
                return np.empty(0, dtype=np.int64)
            return np.unique(np.concatenate(parts))

        S: Dict[Tuple[int, int, int], np.ndarray] = {}
        for i in groups:
            for j in range(C):
                for c_ in range(C):
                    S[(i, j, c_)] = stripe_in(i * C + c_, j)
        local_pair_max = max((v.size for v in S.values()), default=0)
        pair_max = global_max_int(int(local_pair_max))
        grad_cap = _grad_table_cap(cfg, pair_max, n_blk)
        out_sets, in_sets = [], []
        for b in own:
            i, j = b // C, b % C
            out_sets.append(
                [S[(i, j, c_)] - i * group_rows for c_ in range(C)]
            )
            in_sets.append(
                [S[(i, c_, j)] - b * n_blk for c_ in range(C)]
            )
        grad_out, grad_in, grad_counts = _pack_grad_tables(
            out_sets, in_sets, C, n_blk, group_rows, grad_cap
        )
    return TwoDLayout(
        edges=EdgeChunks(
            src=src.reshape(n_local, c, chunk),
            dst=dst.reshape(n_local, c, chunk),
            mask=mask.reshape(n_local, c, chunk).astype(dtype),
        ),
        send_idx=send_idx,
        cap=cap,
        block_edge_counts=local_counts,
        closure_rows=int(sum(u.size for u in unions.values())),
        grad_out=grad_out,
        grad_in=grad_in,
        grad_counts=grad_counts,
        grad_cap=grad_cap,
        grad_pair_max=int(pair_max),
    )


def _closure_grad_wanted(cfg: BigClamConfig, C: int, grad_tabs) -> bool:
    """Trace-time decision for the touched-rows grad exchange: cols to
    reduce over, cfg says closure (the step-baked default), and the
    layout baked tables. C == 1 is always 'dense' (there is no cols
    reduction at all — both modes compile the identical step, which is
    why the ledger stamps the EFFECTIVE mode)."""
    return (
        C > 1
        and getattr(cfg, "grad_exchange", "closure") == "closure"
        and grad_tabs is not None
    )


def _cols_grad_exchange(nbr_grad, gout, gin, gcnt, gcap, use_closure):
    """Reduce neighbor-grad partials over the cols axis. Dense mode is
    the PR 16 partial-group psum; closure mode routes only the baked
    touched rows (sparse_collectives.closure_grad_allreduce) and returns
    the (exchanged ids, dense-fallback) counter pair replicated over the
    whole mesh. gcap == 0 (nothing touched anywhere) skips the exchange
    at trace time — every partial is exactly 0.0, so the sum already is
    the psum."""
    zero = jnp.zeros((), jnp.int32)
    if not use_closure:
        return lax.psum(nbr_grad, COLS_AXIS), zero, zero
    if gcap <= 0:
        return nbr_grad, zero, zero
    from bigclam_tpu.parallel.sparse_collectives import (
        closure_grad_allreduce,
    )

    out, cnt, fb = closure_grad_allreduce(
        nbr_grad, gout, gin, gcnt, gcap, COLS_AXIS
    )
    return out, lax.pmax(cnt, ROWS_AXIS), lax.pmax(fb, ROWS_AXIS)


def _twod_health(cfg, state, F_new, sumF, hist, gstats, cnt, fb, gcap):
    """Health record for a closure-grad step: the shared pack plus the
    exchange counters latched max-since-last-sample into the
    exchanged_ids / dense_fallback / cap_occupancy event slots (the same
    surface the sparse representation's allreduce reports through)."""
    if not dx.health_on(cfg):
        return None
    extras = {
        "exchanged_ids": cnt,
        "dense_fallback": fb,
        "cap_occupancy": cnt.astype(jnp.float32) / jnp.float32(max(gcap, 1)),
    }
    extras, carry = dx.latch_extras(state.health, extras)
    return dx.health_pack(
        cfg, state.it, state.F, F_new, sumF, hist, gstats,
        extras=extras, skip_carry=carry,
    )


def make_twod_train_step(
    mesh: Mesh, edges: EdgeChunks, send_idx, cfg: BigClamConfig,
    grad_tabs: Optional[dict] = None,
) -> Callable[[TrainState], TrainState]:
    """One jitted 2D-partitioned iteration. Same math as the 1D XLA
    sharded step — the Jacobi candidate pass, the Armijo acceptance, the
    segment-sum sweeps are shared or verbatim — with the dense F
    all-gather replaced by the row-group gather + capped closure
    all_to_all, and the Armijo accumulators replica-sharded via
    psum_scatter (tentpole (c): no chip ever holds another block's
    candidate table past the scatter). With grad_exchange="closure" and
    baked tables (`grad_tabs`: out/in/count device arrays + the int
    cap), the cols grad psum becomes the touched-rows exchange and the
    returned state carries the comm_ids/comm_dense counters.

    At C == 1 (and R == 1) every "cols" ("rows") collective is skipped at
    TRACE time, which with the layout degeneration makes trajectories
    bit-identical to the 1D sharded step (gate-pinned)."""
    R = mesh.shape[ROWS_AXIS]
    C = mesh.shape[COLS_AXIS]
    cap = int(send_idx.shape[-1])
    both = (ROWS_AXIS, COLS_AXIS)
    use_closure = _closure_grad_wanted(cfg, C, grad_tabs)
    gcap = int(grad_tabs["cap"]) if use_closure else 0

    def step_shard(F_blk, src, dst, mask, sidx, *rest):
        # squeeze the leading per-block axis shard_map leaves on the blocks
        if use_closure:
            gout, gin, gcnt, it = rest
            gout, gin, gcnt = gout[0], gin[0], gcnt[0]
        else:
            (it,) = rest
        src, dst, mask, sidx = src[0], dst[0], mask[0], sidx[0]
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_blk.dtype
        etas = jnp.asarray(cfg.step_candidates, F_blk.dtype)
        n_blk = F_blk.shape[0]
        n_row = C * n_blk

        # row group's src rows: 1/R of the 1D dense gather (skipped whole
        # at C == 1 — each block is its own row group slice)
        if C > 1:
            F_row = lax.all_gather(F_blk, COLS_AXIS, axis=0, tiled=True)
        else:
            F_row = F_blk
        sumF = lax.psum(F_blk.sum(axis=0), both)

        # capped closure exchange: each block sends every requester row
        # group exactly the rows that group's edges touch (ingest-baked
        # lists); received table is indexed by the pre-baked dst positions
        send = F_blk[sidx.reshape(-1)].reshape(R, cap, F_blk.shape[1])
        if R > 1:
            closure = lax.all_to_all(
                send, ROWS_AXIS, split_axis=0, concat_axis=0
            )
        else:
            closure = send
        closure_flat = closure.reshape(R * cap, F_blk.shape[1])

        def grad_body(carry, sdm):
            nbr_llh, nbr_grad = carry
            s, d, m = sdm
            fs, fd = F_row[s], closure_flat[d]
            x = lax.psum(jnp.einsum("ek,ek->e", fs, fd), K_AXIS)
            omp, ell = edge_terms(x, cfg)
            coeff = m / omp
            nbr_llh = nbr_llh + jax.ops.segment_sum(
                (ell * m).astype(adt), s, num_segments=n_row,
                indices_are_sorted=True,
            )
            nbr_grad = nbr_grad + jax.ops.segment_sum(
                fd * coeff[:, None], s, num_segments=n_row,
                indices_are_sorted=True,
            )
            return (nbr_llh, nbr_grad), None

        (nbr_llh, nbr_grad), _ = lax.scan(
            grad_body,
            (
                _mark_varying(jnp.zeros(n_row, adt), both),
                _mark_varying(
                    jnp.zeros((n_row, F_blk.shape[1]), F_blk.dtype), both
                ),
            ),
            (src, dst, mask),
        )
        # partial-group reductions: grad rows stay within the row group
        # ("cols" psum), never crossing processor rows; the per-node LLH
        # accumulator lands replica-sharded (each chip keeps its block)
        cnt = fb = jnp.zeros((), jnp.int32)
        if C > 1:
            if use_closure:
                nbr_grad, cnt, fb = _cols_grad_exchange(
                    nbr_grad, gout, gin, gcnt, gcap, True
                )
            else:
                nbr_grad = lax.psum(nbr_grad, COLS_AXIS)
            nbr_llh_own = lax.psum_scatter(
                nbr_llh, COLS_AXIS, scatter_dimension=0, tiled=True
            )
        else:
            nbr_llh_own = nbr_llh
        grad_row = nbr_grad - sumF[None, :] + F_row
        if C > 1:
            j = lax.axis_index(COLS_AXIS)
            grad_own = lax.dynamic_slice_in_dim(
                grad_row, j * n_blk, n_blk, axis=0
            )
        else:
            grad_own = grad_row
        node_llh_own = nbr_llh_own + (
            -lax.psum(F_blk @ sumF, K_AXIS) + _rowdot(F_blk, F_blk)
        ).astype(adt)
        llh_cur = lax.psum(node_llh_own.sum(), both)

        def cand_body(cand, sdm):
            s, d, m = sdm
            fs, gs, fd = F_row[s], grad_row[s], closure_flat[d]

            def one_eta(eta):
                nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
                xc = lax.psum(jnp.einsum("ek,ek->e", nf, fd), K_AXIS)
                _, ellc = edge_terms(xc, cfg)
                return jax.ops.segment_sum(
                    (ellc * m).astype(adt), s, num_segments=n_row,
                    indices_are_sorted=True,
                )

            return cand + lax.map(one_eta, etas), None

        cand_nbr, _ = lax.scan(
            cand_body,
            _mark_varying(
                jnp.zeros((len(cfg.step_candidates), n_row), adt), both
            ),
            (src, dst, mask),
        )
        # tentpole (c): the (nc, C*n_blk) candidate table is reduced AND
        # scattered in one collective — each chip keeps only its own
        # block's columns, so Armijo state is sharded over the replica
        # axis instead of replicated across it
        if C > 1:
            cand_own = lax.psum_scatter(
                cand_nbr, COLS_AXIS, scatter_dimension=1, tiled=True
            )
        else:
            cand_own = cand_nbr

        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_blk, grad_own, node_llh_own, cand_own, sumF, cfg,
            with_stats=True,
        )
        sumF_new = lax.psum(sum_loc, both)
        hist = lax.psum(hist, both)
        if dx.health_on(cfg):
            gstats = dx.gated_grad_stats(
                cfg, it, grad_own, node_axis=both, k_axis=K_AXIS
            )
        else:
            gstats = dx.zero_grad_stats()
        out = (
            F_new, sumF_new, llh_cur.astype(F_blk.dtype), it + 1, hist,
            gstats,
        )
        return out + (cnt, fb) if use_closure else out

    nspec = P((ROWS_AXIS, COLS_AXIS), None, None)
    cspec = P((ROWS_AXIS, COLS_AXIS))
    extra_in = (nspec, nspec, cspec) if use_closure else ()
    extra_out = (P(), P()) if use_closure else ()

    def step(state: TrainState, src, dst, mask, sidx, *gt) -> TrainState:
        outs = shard_map(
            step_shard,
            mesh=mesh,
            in_specs=(
                P((ROWS_AXIS, COLS_AXIS), K_AXIS),
                nspec, nspec, nspec, nspec,
            ) + extra_in + (P(),),
            out_specs=(
                P((ROWS_AXIS, COLS_AXIS), K_AXIS),
                P(K_AXIS), P(), P(), P(), P(),
            ) + extra_out,
        )(state.F, src, dst, mask, sidx, *gt, state.it)
        if use_closure:
            F_new, sumF, llh, it, hist, gstats, cnt, fb = outs
            return TrainState(
                F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
                health=_twod_health(
                    cfg, state, F_new, sumF, hist, gstats, cnt, fb, gcap
                ),
                comm_ids=cnt, comm_dense=fb,
            )
        F_new, sumF, llh, it, hist, gstats = outs
        return TrainState(
            F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
            health=_shard_health(cfg, state, F_new, sumF, hist, gstats),
        )

    # edge/send arrays as jit ARGUMENTS (multi-controller: no closing over
    # non-addressable-device arrays; see make_sharded_csr_train_step)
    jitted = jax.jit(step)
    gt_args = (
        (grad_tabs["out"], grad_tabs["in"], grad_tabs["count"])
        if use_closure else ()
    )

    def step_fn(state):
        return jitted(
            state, edges.src, edges.dst, edges.mask, send_idx, *gt_args
        )

    step_fn.jitted = jitted
    step_fn.jit_args = (
        edges.src, edges.dst, edges.mask, send_idx
    ) + gt_args
    return attach_donating(step_fn, step, fixed_args=step_fn.jit_args)


def twod_block_tiles(
    layout: TwoDLayout, C: int, n_blk: int, block_b: int, tile_t: int,
    pad_tiles: Optional[int] = None,
):
    """Per edge-block flat CSR tiles from a committed 2D layout: src is
    already group-local ([0, C*n_blk)) and CSR-sorted within each block,
    dst already a closure POSITION — both stream through
    ops.csr_tiles.build_block_tiles_arrays untouched, so the fused
    kernels' cur/next DMA descriptors read the compacted closure buffer
    exactly the way the 1D path reads the all-gathered F. Returns the
    stacked ShardedBlockTiles (leading axis = this host's edge blocks),
    padded to `pad_tiles` tiles (None = the local max; store callers pass
    the cross-host agreed pad)."""
    from bigclam_tpu.ops.csr_tiles import stack_block_tile_parts

    parts = _twod_tile_parts(layout, C, n_blk, block_b, tile_t)
    return stack_block_tile_parts(
        parts, pad_tiles or max(p.n_tiles for p in parts)
    )


def _twod_tile_parts(
    layout: TwoDLayout, C: int, n_blk: int, block_b: int, tile_t: int
) -> list:
    """Per edge-block BlockTiles (first stage of twod_block_tiles) — the
    store probe needs the un-stacked parts to run the cross-host
    pad-tiles exchange before stacking."""
    from bigclam_tpu.ops.csr_tiles import build_block_tiles_arrays

    group_rows = C * n_blk
    nloc = layout.edges.src.shape[0]
    src2 = np.asarray(layout.edges.src).reshape(nloc, -1)
    dst2 = np.asarray(layout.edges.dst).reshape(nloc, -1)
    counts = np.asarray(layout.block_edge_counts).reshape(-1)
    parts = []
    for r in range(nloc):
        m = int(counts[r])
        parts.append(
            build_block_tiles_arrays(
                src2[r, :m], dst2[r, :m], group_rows, block_b, tile_t
            )
        )
    return parts


def make_twod_csr_train_step(
    mesh: Mesh, tiles: dict, send_idx, cfg: BigClamConfig,
    grad_tabs: Optional[dict] = None,
) -> Callable[[TrainState], TrainState]:
    """The fused-Pallas 2D iteration (ISSUE 17 tentpole): the XLA
    schedule's prologue — row-group gather, sumF psum, capped closure
    all_to_all — verbatim, then the per-edge-block sweeps run in the
    round-17 fused kernels with the closure buffer as the dst-DMA
    source. Dispatch:

      C == 1, flat : fused_superstep_csr — the whole superstep in one
                     kernel; every psum spans both axes, which at C == 1
                     is the 1D NODES axis, so trajectories are
                     BIT-identical to the 1D fused trainer (gate-pinned).
      C == 1, kc   : train_pass_csr_kblocked_fused + the 1D finish.
      C >  1, flat : _grad_blocks_fused / _cand_blocks_fused around the
                     cols grad exchange (closure or dense) and the
                     psum_scatter accumulators of the XLA schedule.
      C >  1, kc   : the K-block scans of train_pass_csr_kblocked_fused
                     inlined so the grad exchange and the -sumF + F fold
                     happen OUTSIDE the kernels, between the scans.
    """
    from bigclam_tpu.ops.linesearch import accept_stats
    from bigclam_tpu.ops.pallas_csr import TilesDev, cand_nbr_from_x_csr
    from bigclam_tpu.ops.pallas_fused import (
        _cand_blocks_fused,
        _grad_blocks_fused,
        cand_dots_fused,
        edge_dots_fused,
        fused_superstep_csr,
        grad_nbr_from_x_fused,
        train_pass_csr_kblocked_fused,
    )

    interp = cfg.pallas_interpret
    R = mesh.shape[ROWS_AXIS]
    C = mesh.shape[COLS_AXIS]
    cap = int(send_idx.shape[-1])
    both = (ROWS_AXIS, COLS_AXIS)
    block_b = tiles["block_b"]
    tile_t = tiles["tile_t"]
    n_blocks = tiles["n_blocks"]
    kc = tiles.get("kc", 0)
    num_s = None  # bound below from cfg
    use_closure = _closure_grad_wanted(cfg, C, grad_tabs)
    gcap = int(grad_tabs["cap"]) if use_closure else 0

    def gather_closure(F_blk, sidx):
        """The shared prologue: row-group F gather, global sumF, capped
        closure exchange — identical collectives to the XLA step."""
        if C > 1:
            F_row = lax.all_gather(F_blk, COLS_AXIS, axis=0, tiled=True)
        else:
            F_row = F_blk
        sumF = lax.psum(F_blk.sum(axis=0), both)
        send = F_blk[sidx.reshape(-1)].reshape(R, cap, F_blk.shape[1])
        if R > 1:
            closure = lax.all_to_all(
                send, ROWS_AXIS, split_axis=0, concat_axis=0
            )
        else:
            closure = send
        return F_row, sumF, closure.reshape(R * cap, F_blk.shape[1])

    def tiles_dev(srcl, dstt, maskt, bid, seq=None, with_kc=False):
        return TilesDev(
            src_local=srcl, dst=dstt, mask=maskt, block_id=bid,
            block_b=block_b, tile_t=tile_t, n_blocks=n_blocks,
            seq=seq, **({"kc": kc} if with_kc else {}),
        )

    def step_shard_c1(F_blk, srcl, dstt, maskt, bid, seq, sidx, it):
        # one-pass fused superstep, C == 1: n_row == n_blk, psums over
        # both axes ARE the 1D NODES psums — bit-identity anchor
        srcl, dstt, maskt, bid, seq, sidx = (
            srcl[0], dstt[0], maskt[0], bid[0], seq[0], sidx[0]
        )
        td = tiles_dev(srcl, dstt, maskt, bid, seq=seq)
        F_row, sumF, closure_flat = gather_closure(F_blk, sidx)
        F_new, grad, node_llh, ok = fused_superstep_csr(
            F_blk, sumF, td, cfg, interpret=interp, F_gather=closure_flat
        )
        llh_cur = lax.psum(node_llh.sum(), both)
        sumF_new = lax.psum(F_new.sum(axis=0), both)
        hist = lax.psum(accept_stats(ok > 0), both)
        if dx.health_on(cfg):
            gstats = dx.gated_grad_stats(
                cfg, it, grad, node_axis=both, k_axis=K_AXIS
            )
        else:
            gstats = dx.zero_grad_stats()
        return (
            F_new, sumF_new, llh_cur.astype(F_blk.dtype), it + 1, hist,
            gstats,
        )

    def step_shard_kb_c1(F_blk, srcl, dstt, maskt, bid, sidx, it):
        # K-blocked fused, C == 1: the 1D fused_kb step with the closure
        # buffer as the gather source (k_axis psums are identity — the
        # 2D mesh's k axis is 1, same as the 1D dp mesh)
        srcl, dstt, maskt, bid, sidx = (
            srcl[0], dstt[0], maskt[0], bid[0], sidx[0]
        )
        td = tiles_dev(srcl, dstt, maskt, bid, with_kc=True)
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_blk.dtype
        F_row, sumF, closure_flat = gather_closure(F_blk, sidx)
        grad, llh_nbr, cand_nbr = train_pass_csr_kblocked_fused(
            F_blk, sumF, td, cfg, k_axis=K_AXIS, interpret=interp,
            F_gather=closure_flat,
        )
        node_llh = llh_nbr.astype(adt) + (
            -lax.psum(F_blk @ sumF, K_AXIS) + _rowdot(F_blk, F_blk)
        ).astype(adt)
        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_blk, grad, node_llh, cand_nbr.astype(adt), sumF, cfg,
            with_stats=True,
        )
        sumF_new = lax.psum(sum_loc, both)
        llh_cur = lax.psum(node_llh.sum(), both)
        hist = lax.psum(hist, both)
        if dx.health_on(cfg):
            gstats = dx.gated_grad_stats(
                cfg, it, grad, node_axis=both, k_axis=K_AXIS
            )
        else:
            gstats = dx.zero_grad_stats()
        return (
            F_new, sumF_new, llh_cur.astype(F_blk.dtype), it + 1, hist,
            gstats,
        )

    def tail_cn(F_blk, nbr_grad, nbr_llh, cnt, fb, sumF, F_row,
                closure_flat, td, it, cand_fn):
        """C > 1 epilogue shared by the flat and kb variants: grad row
        assembly, psum_scatter accumulators, Armijo on own block."""
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_blk.dtype
        n_blk = F_blk.shape[0]
        nbr_llh_own = lax.psum_scatter(
            nbr_llh, COLS_AXIS, scatter_dimension=0, tiled=True
        )
        grad_row = nbr_grad - sumF[None, :] + F_row
        j = lax.axis_index(COLS_AXIS)
        grad_own = lax.dynamic_slice_in_dim(
            grad_row, j * n_blk, n_blk, axis=0
        )
        node_llh_own = nbr_llh_own + (
            -lax.psum(F_blk @ sumF, K_AXIS) + _rowdot(F_blk, F_blk)
        ).astype(adt)
        llh_cur = lax.psum(node_llh_own.sum(), both)
        cand_nbr = cand_fn(grad_row).astype(adt)
        cand_own = lax.psum_scatter(
            cand_nbr, COLS_AXIS, scatter_dimension=1, tiled=True
        )
        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_blk, grad_own, node_llh_own, cand_own, sumF, cfg,
            with_stats=True,
        )
        sumF_new = lax.psum(sum_loc, both)
        hist = lax.psum(hist, both)
        if dx.health_on(cfg):
            gstats = dx.gated_grad_stats(
                cfg, it, grad_own, node_axis=both, k_axis=K_AXIS
            )
        else:
            gstats = dx.zero_grad_stats()
        out = (
            F_new, sumF_new, llh_cur.astype(F_blk.dtype), it + 1, hist,
            gstats,
        )
        return out + (cnt, fb) if use_closure else out

    def step_shard_flat_cn(F_blk, srcl, dstt, maskt, bid, sidx, *rest):
        if use_closure:
            gout, gin, gcnt, it = rest
            gout, gin, gcnt = gout[0], gin[0], gcnt[0]
        else:
            gout = gin = gcnt = None
            (it,) = rest
        srcl, dstt, maskt, bid, sidx = (
            srcl[0], dstt[0], maskt[0], bid[0], sidx[0]
        )
        td = tiles_dev(srcl, dstt, maskt, bid)
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_blk.dtype
        n_row = C * F_blk.shape[0]
        k = F_blk.shape[1]
        F_row, sumF, closure_flat = gather_closure(F_blk, sidx)
        gparts, lparts = _grad_blocks_fused(
            F_row, td, cfg, closure_flat, interpret=interp
        )
        nbr_grad = gparts.reshape(n_row, k)
        nbr_llh = lparts.reshape(n_row).astype(adt)
        nbr_grad, cnt, fb = _cols_grad_exchange(
            nbr_grad, gout, gin, gcnt, gcap, use_closure
        )

        def cand_fn(grad_row):
            cparts = _cand_blocks_fused(
                F_row, grad_row, td, cfg, closure_flat, interpret=interp
            )
            return cparts.transpose(1, 0, 2).reshape(num_s, n_row)

        return tail_cn(
            F_blk, nbr_grad, nbr_llh, cnt, fb, sumF, F_row, closure_flat,
            td, it, cand_fn,
        )

    def step_shard_kb_cn(F_blk, srcl, dstt, maskt, bid, sidx, *rest):
        if use_closure:
            gout, gin, gcnt, it = rest
            gout, gin, gcnt = gout[0], gin[0], gcnt[0]
        else:
            gout = gin = gcnt = None
            (it,) = rest
        srcl, dstt, maskt, bid, sidx = (
            srcl[0], dstt[0], maskt[0], bid[0], sidx[0]
        )
        td = tiles_dev(srcl, dstt, maskt, bid, with_kc=True)
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_blk.dtype
        n_row = C * F_blk.shape[0]
        k = F_blk.shape[1]
        n_kb = k // kc
        F_row, sumF, closure_flat = gather_closure(F_blk, sidx)
        n_tiles = td.src_local.shape[0]

        # the train_pass_csr_kblocked_fused scans, inlined: the fold and
        # the cols exchange must happen between the grad scan and the
        # candidate scan, outside the kernels
        def dots_kb(x_acc, kb):
            return x_acc + edge_dots_fused(
                F_row, td, closure_flat, kb, kc, interpret=interp
            ), None

        x, _ = lax.scan(
            dots_kb,
            _mark_varying(
                jnp.zeros((n_tiles, 1, tile_t), F_blk.dtype), both
            ),
            jnp.arange(n_kb),
        )
        x = lax.psum(x, K_AXIS)

        def consume_kb(carry, kb):
            gkb, ln = grad_nbr_from_x_fused(
                x, td, closure_flat, kb, kc, cfg, interpret=interp
            )
            return carry, (gkb, ln)

        _, (gs, lns) = lax.scan(consume_kb, 0, jnp.arange(n_kb))
        nbr_grad = gs.transpose(1, 0, 2).reshape(n_row, k)
        nbr_llh = lns[0].astype(adt)
        nbr_grad, cnt, fb = _cols_grad_exchange(
            nbr_grad, gout, gin, gcnt, gcap, use_closure
        )

        def cand_fn(grad_row):
            def cand_kb(xc_acc, kb):
                gkb = lax.dynamic_slice_in_dim(
                    grad_row, kb * kc, kc, axis=1
                )
                return xc_acc + cand_dots_fused(
                    F_row, gkb, td, closure_flat, kb, kc, cfg,
                    interpret=interp,
                ), None

            xc, _ = lax.scan(
                cand_kb,
                _mark_varying(
                    jnp.zeros((n_tiles, num_s, tile_t), F_blk.dtype), both
                ),
                jnp.arange(n_kb),
            )
            xc = lax.psum(xc, K_AXIS)
            return cand_nbr_from_x_csr(xc, td, cfg, interpret=interp)

        return tail_cn(
            F_blk, nbr_grad, nbr_llh, cnt, fb, sumF, F_row, closure_flat,
            td, it, cand_fn,
        )

    num_s = len(cfg.step_candidates)
    if C == 1:
        step_shard = step_shard_kb_c1 if kc else step_shard_c1
    else:
        step_shard = step_shard_kb_cn if kc else step_shard_flat_cn

    nspec = P((ROWS_AXIS, COLS_AXIS), None, None)
    cspec = P((ROWS_AXIS, COLS_AXIS))

    tile_args = [
        tiles["src_local"], tiles["dst"], tiles["mask"], tiles["block_id"],
    ]
    if step_shard is step_shard_c1:
        tile_args.append(tiles["seq"])
    tile_args.append(send_idx)
    counters_out = use_closure and C > 1
    gt_args = (
        (grad_tabs["out"], grad_tabs["in"], grad_tabs["count"])
        if counters_out else ()
    )
    extra_in = (nspec, nspec, cspec) if counters_out else ()
    extra_out = (P(), P()) if counters_out else ()

    def spec_for(arr) -> P:
        return P((ROWS_AXIS, COLS_AXIS), *([None] * (arr.ndim - 1)))

    def step(state: TrainState, *targs) -> TrainState:
        # check_vma=False as on the 1D CSR steps: pallas_call's
        # interpret-mode lowering mixes varying and replicated operands
        # in ways the VMA type check cannot express yet
        outs = shard_map(
            step_shard,
            mesh=mesh,
            in_specs=(
                (P((ROWS_AXIS, COLS_AXIS), K_AXIS),)
                + tuple(spec_for(a) for a in targs[: len(tile_args)])
                + extra_in + (P(),)
            ),
            out_specs=(
                P((ROWS_AXIS, COLS_AXIS), K_AXIS),
                P(K_AXIS), P(), P(), P(), P(),
            ) + extra_out,
            check_vma=False,
        )(state.F, *targs, state.it)
        if counters_out:
            F_new, sumF, llh, it, hist, gstats, cnt, fb = outs
            return TrainState(
                F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
                health=_twod_health(
                    cfg, state, F_new, sumF, hist, gstats, cnt, fb, gcap
                ),
                comm_ids=cnt, comm_dense=fb,
            )
        F_new, sumF, llh, it, hist, gstats = outs
        return TrainState(
            F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
            health=_shard_health(cfg, state, F_new, sumF, hist, gstats),
        )

    jitted = jax.jit(step)
    all_args = tuple(tile_args) + gt_args

    def step_fn(state):
        return jitted(state, *all_args)

    step_fn.jitted = jitted
    step_fn.jit_args = all_args
    return attach_donating(step_fn, step, fixed_args=step_fn.jit_args)


class TwoDShardedBigClamModel(ShardedBigClamModel):
    """2D edge-block trainer over a (rows, cols, k=1) mesh.

    Same API and math as ShardedBigClamModel — fit/checkpoint/serve
    machinery is inherited through the mesh/layout hooks — but the step
    exchanges closure rows instead of all-gathering F. cfg.partition is
    step-baked: this class refuses to build unless cfg says "2d" (the
    perf ledger keys on it). The round-17 FUSED superstep engages here
    exactly as on the 1D trainer (auto on TPU, use_pallas_csr
    override, the same economy/shape gates) with per-edge-block tiles
    whose dst-DMA streams the closure buffer — kernel_path
    csr_fused_2d[_kb]; the split/grouped/ring kernel suites stay on the
    1d families (explicit reason, no silent fallback). The cols grad
    reduction is grad_exchange-baked: "closure" (default) routes only
    the baked touched rows, "dense" keeps the PR 16 partial-group
    psum."""

    def __init__(
        self,
        g: Graph,
        cfg: BigClamConfig,
        mesh: Mesh,
        dtype=None,
        balance: bool = False,
    ):
        self.g = g
        self.cfg = cfg
        self.mesh = mesh
        for ax in (ROWS_AXIS, COLS_AXIS, K_AXIS):
            if ax not in mesh.shape:
                raise ValueError(
                    "partition='2d' needs a (rows, cols, k) mesh from "
                    f"make_mesh_2d; got axes {tuple(mesh.shape)}"
                )
        R, C = mesh.shape[ROWS_AXIS], mesh.shape[COLS_AXIS]
        if mesh.shape[K_AXIS] != 1:
            raise ValueError(
                "partition='2d' does not shard the community axis: the "
                "mesh 'k' axis must be 1 (TP rides the 1d families)"
            )
        if cfg.partition != "2d":
            raise ValueError(
                f"cfg.partition={cfg.partition!r} on the 2d trainer: the "
                "step and the perf-ledger match key are partition-baked — "
                "set partition='2d'"
            )
        if cfg.replica_cols != C:
            raise ValueError(
                f"cfg.replica_cols={cfg.replica_cols} != mesh cols {C}; "
                "build the mesh from the config (twod_mesh_shape)"
            )
        if getattr(cfg, "grad_exchange", "closure") not in (
            "closure", "dense"
        ):
            raise ValueError(
                f"grad_exchange={cfg.grad_exchange!r}: the 2d cols grad "
                "reduction is step-baked as 'closure' (touched-rows "
                "exchange over the baked lists) or 'dense' (partial-"
                "group psum)"
            )
        self.R, self.C = R, C
        self.p = R * C
        self.dtype = dtype or (
            jnp.float64 if cfg.dtype == "float64" else jnp.float32
        )
        if cfg.min_f != 0.0:
            raise ValueError("sharded padding requires min_f == 0.0")
        self.n_pad = _round_up(max(g.num_nodes, self.p), self.p)
        self.k_pad = cfg.num_communities
        self._csr_reason = (
            "partition=2d XLA closure-gather schedule (fused superstep "
            "not engaged)"
        )
        self._probe_layout = None
        self._probe_tiles = None
        self._grad_tabs_dev = None
        # fused-superstep engagement, mirroring the 1D trainer's gates
        # (tp is pinned to 1 — the 2D mesh's k axis is trivial); when
        # engaged the paddings are re-derived for the tile geometry
        self._csr_wanted = (
            self._csr_static_ok(1) and self._csr_economy_ok(self.p)
        )
        if self._csr_wanted:
            self.n_pad = _round_up(
                max(g.num_nodes, self.p), self.p * self._csr_shape[0]
            )
            self.k_pad = self._csr_k_pad
            self._csr_reason = ""
        self._perm = None
        self.g_original = g
        if balance and self.p > 1:
            from bigclam_tpu.parallel.balance import balance_graph

            self.g, self._perm = balance_graph(g, self.p, self.n_pad)
            # the economy probe ran on the pre-balance graph; relabeling
            # invalidates its cached layout (engagement stands — balance
            # only evens the layout further)
            self._probe_layout = None
            self._probe_tiles = None
        self._pad_stats = None
        self._build_edges_and_step()
        from bigclam_tpu.models.bigclam import (
            log_engaged_path,
            step_cfg_key,
        )
        from bigclam_tpu.obs import note_step_build

        self._step_cache = {step_cfg_key(self.cfg): self._step}
        self.path_reason = self._csr_reason
        note_step_build(self.cfg, type(self).__name__)
        log_engaged_path(
            type(self).__name__, self.engaged_path, self.path_reason
        )
        self.comms = self._build_comms_model()
        self._emit_comms_and_balance()
        self._bake_memory_model()

    # ------------------------------------------------- mesh/layout hooks
    def _node_shards(self) -> int:
        return self.p

    def _fspec(self) -> NamedSharding:
        return NamedSharding(self.mesh, P((ROWS_AXIS, COLS_AXIS), K_AXIS))

    def _espec(self) -> NamedSharding:
        return NamedSharding(self.mesh, P((ROWS_AXIS, COLS_AXIS), None, None))

    def _memory_dp(self) -> int:
        return self.p

    @property
    def engaged_path(self) -> str:
        if not self._csr_wanted:
            return "xla_2d"
        return (
            "csr_fused_2d_kb" if getattr(self, "_csr_kc", 0)
            else "csr_fused_2d"
        )

    @property
    def _closure_grad_on(self) -> bool:
        """Whether the CURRENT cfg's step carries the touched-rows grad
        exchange (and therefore the comm_ids/comm_dense counters)."""
        return _closure_grad_wanted(
            self.cfg, self.C, self._grad_tabs_dev
        )

    @property
    def grad_exchange(self) -> str:
        """The EFFECTIVE step-baked grad-exchange mode — what the perf
        ledger stamps. C == 1 reports "dense": there is no cols
        reduction at all, so both cfg values compile the identical
        step and their baselines must keep matching."""
        return "closure" if self._closure_grad_on else "dense"

    # --------------------------------------------- fused-kernel engagement
    def _csr_static_ok(self, tp: int) -> bool:
        if not super()._csr_static_ok(tp):
            return False
        if not self._csr_fused:
            msg = (
                "partition='2d' wires only the FUSED superstep — the "
                "split/grouped kernel suites have no closure-buffer DMA "
                "path; drop csr_fused=False, or run --partition 1d for "
                "the split suite"
            )
            if self.cfg.use_pallas_csr is True:
                raise ValueError(f"use_pallas_csr=True but {msg}")
            self._csr_reason = msg
            return False
        return True

    def _csr_economy_ok(self, p: int) -> bool:
        """Probe the per-edge-block tile layout's padding economy on the
        prospective fused paddings (pre-balance graph, like the 1D
        probe); caches the layout AND tiles for the commit."""
        from bigclam_tpu.ops.csr_tiles import layout_economical

        cfg = self.cfg
        block_b, tile_t = self._csr_shape
        n_pad = _round_up(max(self.g.num_nodes, p), p * block_b)
        bound = edge_chunk_bound(cfg, max(self._csr_k_pad, 1), self.dtype)
        layout = twod_shard_edges(
            self.g, cfg, self.R, self.C, n_pad, np.float32,
            chunk_bound=bound,
        )
        sbt = twod_block_tiles(
            layout, self.C, n_pad // p, block_b, tile_t
        )
        slots = sbt.src_local.size
        e = max(self.g.num_directed_edges, 1)
        if layout_economical(slots, e, p * sbt.n_blocks, tile_t):
            self._probe_layout = layout
            self._probe_tiles = sbt
            self._csr_nb = None
            return True
        if cfg.use_pallas_csr is True:
            raise ValueError(
                f"use_pallas_csr=True but the 2d fused layout is "
                f"uneconomical: {slots - e} padded edge slots on {e} "
                "edges (power-law skew? try balance=True or "
                "--partition 1d)"
            )
        self._csr_reason = (
            f"2d fused layout uneconomical: {slots - e} padded edge "
            f"slots on {e} edges"
        )
        return False

    # ------------------------------------------------------ layout/step
    def _build_edges_and_step(self) -> None:
        bound = edge_chunk_bound(self.cfg, max(self.k_pad, 1), self.dtype)
        if self._csr_wanted:
            layout, sbt = self._probe_layout, self._probe_tiles
            self._probe_layout = self._probe_tiles = None
            if layout is None:        # balance relabeled after the probe
                layout = twod_shard_edges(
                    self.g, self.cfg, self.R, self.C, self.n_pad,
                    np.float32, chunk_bound=bound,
                )
                sbt = twod_block_tiles(
                    layout, self.C, self.n_pad // self.p,
                    *self._csr_shape,
                )
            self._commit_csr_layout(layout, sbt)
            return
        layout = twod_shard_edges(
            self.g, self.cfg, self.R, self.C, self.n_pad, np.float32,
            chunk_bound=bound,
        )
        self._commit_layout(
            layout,
            src=put_sharded(layout.edges.src, self._espec()),
            dst=put_sharded(layout.edges.dst, self._espec()),
            mask=put_sharded(
                layout.edges.mask.astype(self.dtype), self._espec()
            ),
            send=put_sharded(layout.send_idx, self._espec()),
        )

    def _nspec(self, ndim: int) -> NamedSharding:
        return NamedSharding(
            self.mesh, P((ROWS_AXIS, COLS_AXIS), *([None] * (ndim - 1)))
        )

    def _place_block_array(self, a: np.ndarray):
        """Device placement for a (blocks, ...) host array — the
        in-memory builder holds all blocks; the store twin overrides
        with the host-local placement."""
        return put_sharded(a, self._nspec(a.ndim))

    def _commit_grad_tables(self, layout: TwoDLayout) -> None:
        """Device-place the touched-rows exchange tables (baked whenever
        C > 1 — cheap, and rebuild_step can then toggle
        grad_exchange without a relayout)."""
        self._grad_cap = int(layout.grad_cap)
        self._grad_pair_max = int(layout.grad_pair_max)
        self._grad_tabs_dev = None
        if layout.grad_out is not None:
            self._grad_tabs_dev = {
                "out": self._place_block_array(layout.grad_out),
                "in": self._place_block_array(layout.grad_in),
                "count": self._place_block_array(
                    layout.grad_counts.astype(np.int32)
                ),
                "cap": int(layout.grad_cap),
            }

    def _commit_pad_stats(self, layout: TwoDLayout, mask_host) -> None:
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = dict(tile_pad_stats(mask_host))
        self._pad_stats["closure_cap"] = int(layout.cap)
        self._pad_stats["closure_slots_padded"] = (
            self.p * self.R * int(layout.cap)
        )
        self._pad_stats["closure_rows"] = int(layout.closure_rows)
        if layout.grad_out is not None:
            self._pad_stats["grad_cap"] = int(layout.grad_cap)
            self._pad_stats["grad_pair_max"] = int(layout.grad_pair_max)

    def _commit_layout(self, layout: TwoDLayout, src, dst, mask,
                       send) -> None:
        self._commit_pad_stats(layout, layout.edges.mask)
        self._twod_cap = int(layout.cap)
        self._block_counts = layout.block_edge_counts
        self._commit_grad_tables(layout)
        self.edges = EdgeChunks(src=src, dst=dst, mask=mask)
        self._send_idx = send
        self._tiles_dev = None
        self._step = make_twod_train_step(
            self.mesh, self.edges, self._send_idx, self.cfg,
            grad_tabs=self._grad_tabs_dev,
        )

    def _commit_csr_layout(self, layout: TwoDLayout, sbt) -> None:
        """Commit the fused path: per-edge-block tiles on device (same
        dict layout as the 1D flat fused tiles), the closure send lists,
        and the grad tables; the chunked edge arrays stay host-side —
        the kernels stream the tile arrays instead."""
        from bigclam_tpu.parallel.sharded import _fused_tile_extras

        nloc, nt, t = sbt.src_local.shape
        place = self._place_block_array
        tiles = {
            "src_local": place(
                sbt.src_local.reshape(nloc, nt, 1, t).astype(np.int32)
            ),
            "dst": place(sbt.dst.astype(np.int32)),
            "mask": place(
                sbt.mask.reshape(nloc, nt, 1, t).astype(self.dtype)
            ),
            "block_id": place(sbt.block_id.astype(np.int32)),
            "block_b": sbt.block_b,
            "tile_t": sbt.tile_t,
            "n_blocks": sbt.n_blocks,
        }
        _fused_tile_extras(
            tiles, sbt.block_id, self._csr_kc, 1,
            lambda a: place(np.asarray(a)),
        )
        self._commit_pad_stats(layout, sbt.mask)
        self._pad_stats["pad_tiles"] = int(nt)
        self._twod_cap = int(layout.cap)
        self._block_counts = layout.block_edge_counts
        self._commit_grad_tables(layout)
        self.edges = None                  # not used by the fused step
        self._tiles_dev = tiles
        self._send_idx = self._place_block_array(layout.send_idx)
        self._step = make_twod_csr_train_step(
            self.mesh, tiles, self._send_idx, self.cfg,
            grad_tabs=self._grad_tabs_dev,
        )

    def _make_step(self):
        if self._csr_wanted:
            return make_twod_csr_train_step(
                self.mesh, self._tiles_dev, self._send_idx, self.cfg,
                grad_tabs=self._grad_tabs_dev,
            )
        return make_twod_train_step(
            self.mesh, self.edges, self._send_idx, self.cfg,
            grad_tabs=self._grad_tabs_dev,
        )

    def rebuild_step(self) -> None:
        from bigclam_tpu.models.bigclam import step_cfg_key

        key = step_cfg_key(self.cfg)
        cache = self._step_cache
        if key not in cache:
            cache[key] = self._make_step()
            from bigclam_tpu.obs import note_step_build

            note_step_build(self.cfg, type(self).__name__)
        self._step = cache[key]

    # ----------------------------------------------------- state plumbing
    def _with_counters(self, state: TrainState) -> TrainState:
        """Zero exchange counters when the closure grad exchange is
        engaged: attach_donating's scratch must be a pytree twin of the
        step output from iteration one."""
        if self._closure_grad_on:
            return state._replace(
                comm_ids=jnp.zeros((), jnp.int32),
                comm_dense=jnp.zeros((), jnp.int32),
            )
        return state

    def reset_state(self, F: jax.Array) -> TrainState:
        return self._with_counters(super().reset_state(F))

    def _state_from_arrays(self, arrays: dict) -> TrainState:
        return self._with_counters(super()._state_from_arrays(arrays))

    def _memory_state_arrays(self, state) -> list:
        return super()._memory_state_arrays(state) + [
            getattr(state, "comm_ids", None),
            getattr(state, "comm_dense", None),
        ]

    def last_comm(self, state) -> Tuple[int, bool]:
        """(worst exchanged id count, dense-fallback?) of the last step;
        (0, False) when the closure grad exchange is not engaged."""
        if getattr(state, "comm_ids", None) is None:
            return 0, False
        return int(state.comm_ids), bool(int(state.comm_dense))

    def comms_measured(self, state):
        from bigclam_tpu.obs import comms as _comms

        return _comms.twod_measured(self.comms, state)

    # ------------------------------------------------------ observability
    def _build_comms_model(self):
        from bigclam_tpu.obs import comms as _comms

        return _comms.twod_step_model(
            n_pad=self.n_pad,
            k_pad=self.k_pad,
            rows=self.R,
            cols=self.C,
            itemsize=jnp.dtype(self.dtype).itemsize,
            num_candidates=len(self.cfg.step_candidates),
            edge_slots=self._edge_slots_per_shard(),
            closure_cap=self._twod_cap,
            health_every=self.cfg.health_every,
            model=type(self).__name__,
            grad_exchange=self.grad_exchange,
            grad_cap=self._grad_cap if self._closure_grad_on else 0,
            fused=self._csr_wanted,
        )

    def _shard_edge_counts(self) -> np.ndarray:
        return np.asarray(self._block_counts, dtype=np.int64).reshape(-1)

    def _graph_device_arrays(self) -> dict:
        if self._csr_wanted:
            t = self._tiles_dev
            out = {
                "graph/tiles_src": t["src_local"],
                "graph/tiles_dst": t["dst"],
                "graph/tiles_mask": t["mask"],
                "graph/tiles_block_id": t["block_id"],
                "graph/closure_send_idx": self._send_idx,
            }
            if t.get("seq") is not None:
                out["graph/tiles_seq"] = t["seq"]
        else:
            out = {
                "graph/edges_src": self.edges.src,
                "graph/edges_dst": self.edges.dst,
                "graph/edges_mask": self.edges.mask,
                "graph/closure_send_idx": self._send_idx,
            }
        if self._grad_tabs_dev is not None:
            out["graph/grad_out_tab"] = self._grad_tabs_dev["out"]
            out["graph/grad_in_tab"] = self._grad_tabs_dev["in"]
            out["graph/grad_count"] = self._grad_tabs_dev["count"]
        return out

    def _build_memory_model(self):
        from bigclam_tpu.obs import memory as _mem

        cfg = self.cfg
        return _mem.twod_memory_model(
            self.n_pad,
            self.k_pad,
            self.R,
            self.C,
            jnp.dtype(self.dtype).itemsize,
            len(cfg.step_candidates),
            self._graph_buffer_bytes(),
            closure_cap=self._twod_cap,
            health_on=int(getattr(cfg, "health_every", 0) or 0) > 0,
            donate=bool(cfg.donate_state),
            rollback=int(getattr(cfg, "rollback_budget", 0) or 0) > 0,
            fd_bytes=self._memory_fd_bytes(),
            comms=self.comms,
            model=type(self).__name__,
            fused=self._csr_wanted,
            grad_exchange=self.grad_exchange,
            grad_cap=self._grad_cap if self._closure_grad_on else 0,
        )


class StoreTwoDShardedBigClamModel(_StoreBackedMixin,
                                   TwoDShardedBigClamModel):
    """2D trainer fed per-host from a compiled graph cache.

    Each process loads ONLY its own shard blobs and closure blobs;
    requester gather unions and contributor send lists are both derived
    from the host's OWN lists (edge symmetry — see twod_shard_edges_local),
    so the global CSR and the global closure never exist on any host. On
    pre-v3 caches the lists are streamed from the host's own CSR slice
    (explicit path_reason note; `cli ingest` re-bakes them). Requires
    num_shards == rows*cols and whole-processor-row process ownership
    ((num_shards / process_count) % replica_cols == 0) so the edge-block
    redistribution stays host-internal."""

    def __init__(self, store, cfg: BigClamConfig, mesh: Mesh, dtype=None,
                 verify: bool = True):
        self._store_init(store, mesh, verify)
        super().__init__(
            _StoreGraphView(store), cfg, mesh, dtype=dtype, balance=False,
        )

    def _store_init(self, store, mesh: Mesh, verify: bool) -> None:
        p = mesh.shape[ROWS_AXIS] * mesh.shape[COLS_AXIS]
        if store.num_shards != p:
            raise ValueError(
                f"cache has {store.num_shards} shards but the 2d mesh "
                f"has rows*cols={p} node blocks; recompile with "
                f"--shards {p}"
            )
        self.store = store
        self._shard_verify = verify
        self.host_shard = None

    def _pair_lists(self, shard) -> Dict[int, tuple]:
        """Owned shards' closure triples: baked v3 lists when the cache
        has them, else the v2 streaming fallback on the host's own CSR
        (recorded in path_reason — same derivation, more host time)."""
        from bigclam_tpu.graph.store import closure_pair_lists

        own = list(shard.shard_ids)
        entries = self.store.manifest["shards"]
        if own and all("closure" in entries[s] for s in own):
            cl = self.store.load_closure_lists(
                own[0], own[-1] + 1, verify=self._shard_verify
            )
            return {
                s: (sc.out_ids, sc.in_ids, sc.edge_counts)
                for s, sc in cl.shards.items()
            }
        self._csr_reason += (
            "; closure gather lists streamed from the cached CSR (cache "
            "format < v3 — re-ingest to bake closures)"
        )
        rps = shard.rows_per_shard
        n = shard.num_nodes
        out: Dict[int, tuple] = {}
        for s in own:
            glo, ghi = min(s * rps, n), min((s + 1) * rps, n)
            a = int(shard.indptr[glo - shard.lo])
            b = int(shard.indptr[ghi - shard.lo])
            ip = shard.indptr[glo - shard.lo: ghi - shard.lo + 1] - a
            out[s] = closure_pair_lists(
                glo, ip, shard.indices[a:b], rps, self.p, cap=0
            )
        return out

    def _csr_static_ok(self, tp: int) -> bool:
        if not super()._csr_static_ok(tp):
            return False
        return self._store_rows_ok()

    def _csr_economy_ok(self, p: int) -> bool:
        """Store-native twin of the 2D economy probe: the edge-block
        layout and per-block tiles are built from this host's shard and
        closure blobs only, tile counts padded to the cross-host max so
        shard_map stays SPMD. The accept decision prices the GLOBAL
        padded slot count (manifest edge totals + the agreed pad), so
        engage/fallback matches the in-memory trainer on the same
        graph."""
        from bigclam_tpu.obs import trace as _trace
        from bigclam_tpu.ops.csr_tiles import (
            layout_economical,
            stack_block_tile_parts,
        )

        cfg = self.cfg
        block_b, tile_t = self._csr_shape
        shard = self._load_host_shard()
        n_pad = p * self.store.rows_per_shard
        bound = edge_chunk_bound(cfg, max(self._csr_k_pad, 1), self.dtype)
        with _trace.span(
            "sharded/tile_build", dp=p, source="store"
        ) as _sp:
            layout = twod_shard_edges_local(
                shard, self._pair_lists(shard), cfg, self.R, self.C,
                n_pad, np.float32, chunk_bound=bound,
            )
            parts = _twod_tile_parts(
                layout, self.C, n_pad // p, block_b, tile_t
            )
            local_max = max(pt.n_tiles for pt in parts)
            pad_tiles = self._store_pad_tiles_for(local_max)
            sbt = stack_block_tile_parts(parts, pad_tiles)
            _sp.set(local_tiles=int(local_max), pad_tiles=int(pad_tiles))
        e = max(self.store.num_directed_edges, 1)
        slots = p * pad_tiles * tile_t          # global, all edge blocks
        if layout_economical(slots, e, p * sbt.n_blocks, tile_t):
            self._probe_layout = layout
            self._probe_tiles = sbt
            self._csr_nb = None
            return True
        if cfg.use_pallas_csr is True:
            raise ValueError(
                f"use_pallas_csr=True but the store-backed 2d fused "
                f"layout is uneconomical: {slots - e} padded edge slots "
                f"on {e} edges (power-law skew? re-ingest with --balance "
                "or --partition 1d)"
            )
        self._csr_reason = (
            f"store-backed 2d fused layout uneconomical: {slots - e} "
            f"padded edge slots on {e} edges"
        )
        return False

    def _place_block_array(self, a: np.ndarray):
        # this host's edge blocks only; the global leading axis is the
        # full rows*cols block count
        return put_host_local(
            a, self._nspec(a.ndim), (self.p,) + a.shape[1:]
        )

    def _commit_pad_stats(self, layout: TwoDLayout, mask_host) -> None:
        super()._commit_pad_stats(layout, mask_host)
        # THIS host's slots only — no global mask exists on any host
        self._pad_stats["scope"] = "host_local"

    def _build_edges_and_step(self) -> None:
        if self._csr_wanted:
            layout, sbt = self._probe_layout, self._probe_tiles
            self._probe_layout = self._probe_tiles = None
            self._commit_csr_layout(layout, sbt)
            return
        shard = self._load_host_shard()
        bound = edge_chunk_bound(self.cfg, max(self.k_pad, 1), self.dtype)
        local = twod_shard_edges_local(
            shard, self._pair_lists(shard), self.cfg, self.R, self.C,
            self.n_pad, np.float32, chunk_bound=bound,
        )
        gshape = (self.p,) + local.edges.src.shape[1:]
        sshape = (self.p, self.R, local.cap)
        self._commit_layout(
            local,
            src=put_host_local(local.edges.src, self._espec(), gshape),
            dst=put_host_local(local.edges.dst, self._espec(), gshape),
            mask=put_host_local(
                local.edges.mask.astype(self.dtype), self._espec(), gshape
            ),
            send=put_host_local(local.send_idx, self._espec(), sshape),
        )

    def _shard_edge_counts(self) -> np.ndarray:
        """Per edge-BLOCK counts from the v3 manifest's per-pair closure
        counts (block (i, j) = group i's edges into stripe j); pre-v3
        caches fall back to the per-shard totals — the stripe split is
        not manifest-visible there."""
        entries = self.store.manifest["shards"]
        if all("closure" in e for e in entries):
            per_pair = np.asarray(
                [e["closure"]["edge_counts"] for e in entries],
                dtype=np.int64,
            )                                   # (S, S): s -> b'
            R, C, p = self.R, self.C, self.p
            out = np.zeros(p, dtype=np.int64)
            for i in range(R):
                grp = per_pair[i * C:(i + 1) * C].sum(axis=0)   # (S,)
                for j in range(C):
                    out[i * C + j] = grp[j::C].sum()
            return out
        return np.asarray(
            [int(e["edges"]) for e in entries], dtype=np.int64
        )
