"""Communication-avoiding 2D edge-block partitioning (ISSUE 16).

Kills the dense F all-gather for the large-K regime. The 1D sharded step
(parallel/sharded.py) all-gathers the FULL (N_pad, K_pad) F every
iteration — (p-1)/p * N*K*itemsize per chip per step, flat in p: at
Friendster scale with K = 25,000 that one transient is the capacity wall
long before FLOPs are. Here the node axis is factored into R processor
rows x C replica cols (the classic 2D SpMM factorization, arXiv:2002.10083
lineage) and each chip exchanges only

  * its processor row's F rows  — all_gather over "cols",  N*K/(R*C) * (C-1)
    wire bytes: 1/R of the 1D gather, and
  * the CLOSURE of its edge block's dst columns — a capped all_to_all over
    "rows" of just the rows some edge actually touches (gather lists baked
    at ingest, graph/store.bake_closure_lists).

Layout (mesh from parallel.mesh.make_mesh_2d — axes "rows" x "cols" x a
trivial size-1 "k" so helpers shared with the 1D families resolve):

  F          (N_pad, K_pad)  sharded P(("rows","cols"), "k") — block
                             b = i*C + j on chip (i, j); NO replication
                             anywhere (the accumulator/scratch state is
                             likewise replica-sharded: tentpole (c))
  edges      (p, c, chunk)   P(("rows","cols")): chip (i, j) owns the edge
                             BLOCK (src in processor row i's node blocks,
                             dst in column stripe {b : b % C == j}); src is
                             stored group-LOCAL, dst as a CLOSURE position
  send_idx   (p, R, cap)     P(("rows","cols")): block-local rows chip
                             (i', j) must send each requester row group

Step (chip (i, j)): all_gather F over "cols" -> the C*n_blk src rows of
row group i; gather own rows listed in send_idx and all_to_all over
"rows" -> closure_flat, the (R*cap, K) table of every dst row this block
touches; the same fused grad/LLH + 16-candidate scans as the 1D XLA step
(dst indices pre-baked as closure positions); partial-group psum of grad
over "cols"; psum_scatter of the candidate/LLH accumulators over "cols"
(each chip Armijo-selects ONLY its own n_blk rows); scalar psums over
both axes. At C == 1 every "cols" collective is skipped at trace time and
the schedule degenerates to the 1D sharded step bit-for-bit (pinned by
scripts/comms2d_gate.py).

The whole schedule is expressible in shard_map over named axes —
lax.all_gather / lax.all_to_all / lax.psum_scatter partial-group
collectives all accept a single mesh axis — so no jax custom_partitioning
escape hatch is needed (DESIGN.md records the analysis). The fused Pallas
superstep is NOT wired to this mesh: the closure table is laid out as the
flat row table its dst-DMA consumes, but the kernels ride the 1d families
for now (explicit path_reason fallback; use_pallas_csr=True refuses).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.models.bigclam import (
    TrainState,
    _round_up,
    attach_donating,
    edge_chunk_bound,
)
from bigclam_tpu.ops import diagnostics as dx
from bigclam_tpu.ops.objective import EdgeChunks, edge_terms
from bigclam_tpu.parallel.mesh import COLS_AXIS, K_AXIS, ROWS_AXIS
from bigclam_tpu.parallel.multihost import put_host_local, put_sharded
from bigclam_tpu.parallel.sharded import (
    ShardedBigClamModel,
    _StoreBackedMixin,
    _StoreGraphView,
    _mark_varying,
    _rowdot,
    _shard_health,
    armijo_tail_select_sharded,
)
from bigclam_tpu.utils.compat import shard_map


def twod_mesh_shape(cfg: BigClamConfig, num_devices: int) -> Tuple[int, int]:
    """(R, C) for `num_devices` chips under cfg.replica_cols."""
    C = max(int(cfg.replica_cols or 1), 1)
    if num_devices % C:
        raise ValueError(
            f"replica_cols={C} does not divide the device count "
            f"{num_devices}; pick a divisor"
        )
    return (num_devices // C, C)


@dataclasses.dataclass(frozen=True)
class TwoDLayout:
    """Host-side 2D edge-block layout: the (blocks, c, chunk) edge arrays
    (global rows from twod_shard_edges, this host's rows from
    twod_shard_edges_local), the (blocks, R, cap) contributor send lists,
    and the telemetry counts the comms/balance models price from."""

    edges: EdgeChunks
    send_idx: np.ndarray
    cap: int
    block_edge_counts: np.ndarray      # per edge block, row-major (i, j)
    closure_rows: int                  # real (unpadded) closure rows/step


def _remap_dst(dsel: np.ndarray, unions, n_blk: int, C: int,
               cap: int) -> np.ndarray:
    """Global dst ids -> closure positions i_con*cap + rank-in-union."""
    pos = np.empty(dsel.shape[0], dtype=np.int64)
    icon = (dsel // n_blk) // C
    for i_con in np.unique(icon):
        sel = icon == i_con
        pos[sel] = i_con * cap + np.searchsorted(
            unions[int(i_con)], dsel[sel]
        )
    return pos


def twod_shard_edges(
    g: Graph,
    cfg: BigClamConfig,
    R: int,
    C: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
) -> TwoDLayout:
    """Partition directed edges into R*C edge BLOCKS: block (i, j) holds
    the edges with src in processor row i's node blocks and dst in column
    stripe j ({b : b % C == j}).

    CSR order means each row group's edges are one contiguous slice and
    the stable stripe selection preserves it, so at C == 1 the layout is
    exactly shard_edges' (same slices, same chunk geometry, same src
    rebase/padding) — the bit-identity anchor. src is group-LOCAL
    ([0, C*n_blk); pad = last local row, mask 0); dst is stored as a
    CLOSURE position i_con*cap + rank (pad 0 — a real gathered row whose
    contribution is masked to an exact +0.0)."""
    p = R * C
    n_blk = n_pad // p
    group_rows = C * n_blk
    gsrc = np.asarray(g.src)
    gdst = np.asarray(g.dst)
    gb = np.searchsorted(
        gsrc, np.arange(0, n_pad + group_rows, group_rows)
    )
    sel: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray]] = {}
    lists: Dict[Tuple[int, int, int], np.ndarray] = {}
    counts = np.zeros((R, C), dtype=np.int64)
    for i in range(R):
        s_i = gsrc[gb[i]:gb[i + 1]].astype(np.int64)
        d_i = gdst[gb[i]:gb[i + 1]].astype(np.int64)
        dblk = d_i // n_blk
        for j in range(C):
            m = (dblk % C) == j
            dsel = d_i[m]
            sel[(i, j)] = (s_i[m] - i * group_rows, dsel)
            counts[i, j] = dsel.size
            icon = dblk[m] // C
            for i_con in range(R):
                # union over the group's shards of out(s -> block): the
                # rows of block (i_con, j) this edge block must gather
                lists[(i, j, i_con)] = np.unique(dsel[icon == i_con])
    cap = max(1, max((u.size for u in lists.values()), default=1))
    max_count = int(counts.max()) if counts.size else 1
    chunk = min(chunk_bound or cfg.edge_chunk, max(max_count, 1))
    c = max(1, -(-max_count // chunk))
    padded = c * chunk
    src = np.full((p, padded), group_rows - 1, dtype=np.int32)
    dst = np.zeros((p, padded), dtype=np.int32)
    mask = np.zeros((p, padded), dtype=np.float32)
    send_idx = np.zeros((p, R, cap), dtype=np.int32)
    for i in range(R):
        for j in range(C):
            b = i * C + j
            s_l, d_l = sel[(i, j)]
            m = s_l.size
            src[b, :m] = s_l
            dst[b, :m] = _remap_dst(
                d_l, {ic: lists[(i, j, ic)] for ic in range(R)},
                n_blk, C, cap,
            )
            mask[b, :m] = 1.0
            # contributor side of the SAME lists: block b sends each
            # requester row group the rows that group's edges touch
            lo_b = b * n_blk
            for i_req in range(R):
                u = lists[(i_req, j, i)]
                send_idx[b, i_req, :u.size] = (u - lo_b).astype(np.int32)
    return TwoDLayout(
        edges=EdgeChunks(
            src=src.reshape(p, c, chunk),
            dst=dst.reshape(p, c, chunk),
            mask=mask.reshape(p, c, chunk).astype(dtype),
        ),
        send_idx=send_idx,
        cap=cap,
        block_edge_counts=counts,
        closure_rows=int(sum(u.size for u in lists.values())),
    )


def twod_shard_edges_local(
    shard,
    pair_lists: Dict[int, tuple],
    cfg: BigClamConfig,
    R: int,
    C: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
) -> TwoDLayout:
    """This host's rows of the 2D edge blocks, from a graph-store slice
    (graph/store.HostShard) — the out-of-core twin of twod_shard_edges:
    no global CSR exists anywhere.

    `pair_lists` maps each OWNED shard s to its (out_ids, in_ids,
    edge_counts) closure triple — the ingest-baked v3 lists
    (GraphStore.load_closure_lists) or the v2 streaming fallback
    (store.closure_pair_lists on the host's own CSR). Both sides of every
    exchange come from the host's OWN shards: the gather unions from the
    requester group's out-lists, the send lists from the owned block's
    in-lists — identical sets by edge symmetry (in(b)[s] == out(s)[b]),
    which is what keeps files_read isolation intact. A None pair (the
    bake's cap overflow) degrades to the FULL dst block on both sides.
    Padded geometry (chunk count, closure cap) is agreed cross-host via
    one-int max exchanges (multihost.global_max_int), mirroring the CSR
    tile pad contract."""
    from bigclam_tpu.parallel.multihost import global_max_int

    p = R * C
    n_blk = n_pad // p
    group_rows = C * n_blk
    if shard.rows_per_shard != n_blk:
        raise ValueError(
            f"cache rows_per_shard={shard.rows_per_shard} != trainer "
            f"block rows {n_blk} (n_pad={n_pad}, rows*cols={p}); "
            "recompile the cache with num_shards == rows*cols"
        )
    own = list(shard.shard_ids)
    if own and (own[0] % C or len(own) % C):
        raise ValueError(
            "store-native 2d needs every process to own whole processor "
            f"rows: first owned shard {own[0]} and owned count {len(own)} "
            f"must be multiples of replica_cols={C} — use dp_rows "
            "divisible by the process count (or fewer cols)"
        )
    n = shard.num_nodes

    def full_block(b: int) -> np.ndarray:
        return np.arange(b * n_blk, min((b + 1) * n_blk, n), dtype=np.int64)

    def union_over_group(i_req: int, b_con: int, side: int) -> np.ndarray:
        """Union over requester group i_req's shards of the pair lists
        against block b_con; side 0 = out (gather), 1 = in (send). The
        overflow decision matches across sides because the paired lists
        have equal sizes."""
        parts = []
        for s in range(i_req * C, (i_req + 1) * C):
            lst = (
                pair_lists[s][0][b_con] if side == 0
                else pair_lists[b_con][1][s]
            )
            if lst is None:
                return full_block(b_con)
            parts.append(np.asarray(lst, dtype=np.int64))
        if not parts:
            return np.empty(0, dtype=np.int64)
        return np.unique(np.concatenate(parts))

    groups = range(own[0] // C, (own[-1] + 1) // C) if own else range(0)
    unions: Dict[Tuple[int, int, int], np.ndarray] = {}
    for i in groups:
        for j in range(C):
            for i_con in range(R):
                unions[(i, j, i_con)] = union_over_group(
                    i, i_con * C + j, side=0
                )
    sends: Dict[Tuple[int, int], np.ndarray] = {}
    for b in own:
        for i_req in range(R):
            sends[(b, i_req)] = union_over_group(i_req, b, side=1)
    local_cap = max(
        [u.size for u in unions.values()]
        + [u.size for u in sends.values()] + [1]
    )
    cap = global_max_int(int(local_cap))

    deg = np.diff(shard.indptr)
    blocks: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray]] = {}
    counts: Dict[Tuple[int, int], int] = {}
    for i in groups:
        glo = min(i * group_rows, n)
        ghi = min((i + 1) * group_rows, n)
        e0 = int(shard.indptr[glo - shard.lo])
        e1 = int(shard.indptr[ghi - shard.lo])
        srcs = np.repeat(
            np.arange(glo, ghi, dtype=np.int64),
            deg[glo - shard.lo: ghi - shard.lo],
        )
        dsts = np.asarray(shard.indices[e0:e1], dtype=np.int64)
        stripe = (dsts // n_blk) % C
        for j in range(C):
            m = stripe == j
            blocks[(i, j)] = (srcs[m] - i * group_rows, dsts[m])
            counts[(i, j)] = int(m.sum())
            want = sum(
                pair_lists[s][2][i_con * C + j]
                for s in range(i * C, (i + 1) * C)
                for i_con in range(R)
            )
            if counts[(i, j)] != want:
                raise ValueError(
                    f"edge block ({i}, {j}): closure lists say {want} "
                    f"directed edges but the loaded CSR holds "
                    f"{counts[(i, j)]} — cache inconsistent (partially "
                    "rebuilt, or loaded with verify=False?)"
                )
    max_count = global_max_int(
        max(list(counts.values()) + [1])
    )
    chunk = min(chunk_bound or cfg.edge_chunk, max(max_count, 1))
    c = max(1, -(-max_count // chunk))
    padded = c * chunk
    n_local = len(own)
    src = np.full((n_local, padded), group_rows - 1, dtype=np.int32)
    dst = np.zeros((n_local, padded), dtype=np.int32)
    mask = np.zeros((n_local, padded), dtype=np.float32)
    send_idx = np.zeros((n_local, R, cap), dtype=np.int32)
    local_counts = np.zeros(n_local, dtype=np.int64)
    for row, b in enumerate(own):
        i, j = b // C, b % C
        s_l, d_l = blocks[(i, j)]
        m = s_l.size
        local_counts[row] = m
        src[row, :m] = s_l
        dst[row, :m] = _remap_dst(
            d_l, {ic: unions[(i, j, ic)] for ic in range(R)},
            n_blk, C, cap,
        )
        mask[row, :m] = 1.0
        lo_b = b * n_blk
        for i_req in range(R):
            u = sends[(b, i_req)]
            send_idx[row, i_req, :u.size] = (u - lo_b).astype(np.int32)
    return TwoDLayout(
        edges=EdgeChunks(
            src=src.reshape(n_local, c, chunk),
            dst=dst.reshape(n_local, c, chunk),
            mask=mask.reshape(n_local, c, chunk).astype(dtype),
        ),
        send_idx=send_idx,
        cap=cap,
        block_edge_counts=local_counts,
        closure_rows=int(sum(u.size for u in unions.values())),
    )


def make_twod_train_step(
    mesh: Mesh, edges: EdgeChunks, send_idx, cfg: BigClamConfig
) -> Callable[[TrainState], TrainState]:
    """One jitted 2D-partitioned iteration. Same math as the 1D XLA
    sharded step — the Jacobi candidate pass, the Armijo acceptance, the
    segment-sum sweeps are shared or verbatim — with the dense F
    all-gather replaced by the row-group gather + capped closure
    all_to_all, and the Armijo accumulators replica-sharded via
    psum_scatter (tentpole (c): no chip ever holds another block's
    candidate table past the scatter).

    At C == 1 (and R == 1) every "cols" ("rows") collective is skipped at
    TRACE time, which with the layout degeneration makes trajectories
    bit-identical to the 1D sharded step (gate-pinned)."""
    R = mesh.shape[ROWS_AXIS]
    C = mesh.shape[COLS_AXIS]
    cap = int(send_idx.shape[-1])
    both = (ROWS_AXIS, COLS_AXIS)

    def step_shard(F_blk, src, dst, mask, sidx, it):
        # squeeze the leading per-block axis shard_map leaves on the blocks
        src, dst, mask, sidx = src[0], dst[0], mask[0], sidx[0]
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_blk.dtype
        etas = jnp.asarray(cfg.step_candidates, F_blk.dtype)
        n_blk = F_blk.shape[0]
        n_row = C * n_blk

        # row group's src rows: 1/R of the 1D dense gather (skipped whole
        # at C == 1 — each block is its own row group slice)
        if C > 1:
            F_row = lax.all_gather(F_blk, COLS_AXIS, axis=0, tiled=True)
        else:
            F_row = F_blk
        sumF = lax.psum(F_blk.sum(axis=0), both)

        # capped closure exchange: each block sends every requester row
        # group exactly the rows that group's edges touch (ingest-baked
        # lists); received table is indexed by the pre-baked dst positions
        send = F_blk[sidx.reshape(-1)].reshape(R, cap, F_blk.shape[1])
        if R > 1:
            closure = lax.all_to_all(
                send, ROWS_AXIS, split_axis=0, concat_axis=0
            )
        else:
            closure = send
        closure_flat = closure.reshape(R * cap, F_blk.shape[1])

        def grad_body(carry, sdm):
            nbr_llh, nbr_grad = carry
            s, d, m = sdm
            fs, fd = F_row[s], closure_flat[d]
            x = lax.psum(jnp.einsum("ek,ek->e", fs, fd), K_AXIS)
            omp, ell = edge_terms(x, cfg)
            coeff = m / omp
            nbr_llh = nbr_llh + jax.ops.segment_sum(
                (ell * m).astype(adt), s, num_segments=n_row,
                indices_are_sorted=True,
            )
            nbr_grad = nbr_grad + jax.ops.segment_sum(
                fd * coeff[:, None], s, num_segments=n_row,
                indices_are_sorted=True,
            )
            return (nbr_llh, nbr_grad), None

        (nbr_llh, nbr_grad), _ = lax.scan(
            grad_body,
            (
                _mark_varying(jnp.zeros(n_row, adt), both),
                _mark_varying(
                    jnp.zeros((n_row, F_blk.shape[1]), F_blk.dtype), both
                ),
            ),
            (src, dst, mask),
        )
        # partial-group reductions: grad rows stay within the row group
        # ("cols" psum), never crossing processor rows; the per-node LLH
        # accumulator lands replica-sharded (each chip keeps its block)
        if C > 1:
            nbr_grad = lax.psum(nbr_grad, COLS_AXIS)
            nbr_llh_own = lax.psum_scatter(
                nbr_llh, COLS_AXIS, scatter_dimension=0, tiled=True
            )
        else:
            nbr_llh_own = nbr_llh
        grad_row = nbr_grad - sumF[None, :] + F_row
        if C > 1:
            j = lax.axis_index(COLS_AXIS)
            grad_own = lax.dynamic_slice_in_dim(
                grad_row, j * n_blk, n_blk, axis=0
            )
        else:
            grad_own = grad_row
        node_llh_own = nbr_llh_own + (
            -lax.psum(F_blk @ sumF, K_AXIS) + _rowdot(F_blk, F_blk)
        ).astype(adt)
        llh_cur = lax.psum(node_llh_own.sum(), both)

        def cand_body(cand, sdm):
            s, d, m = sdm
            fs, gs, fd = F_row[s], grad_row[s], closure_flat[d]

            def one_eta(eta):
                nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
                xc = lax.psum(jnp.einsum("ek,ek->e", nf, fd), K_AXIS)
                _, ellc = edge_terms(xc, cfg)
                return jax.ops.segment_sum(
                    (ellc * m).astype(adt), s, num_segments=n_row,
                    indices_are_sorted=True,
                )

            return cand + lax.map(one_eta, etas), None

        cand_nbr, _ = lax.scan(
            cand_body,
            _mark_varying(
                jnp.zeros((len(cfg.step_candidates), n_row), adt), both
            ),
            (src, dst, mask),
        )
        # tentpole (c): the (nc, C*n_blk) candidate table is reduced AND
        # scattered in one collective — each chip keeps only its own
        # block's columns, so Armijo state is sharded over the replica
        # axis instead of replicated across it
        if C > 1:
            cand_own = lax.psum_scatter(
                cand_nbr, COLS_AXIS, scatter_dimension=1, tiled=True
            )
        else:
            cand_own = cand_nbr

        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_blk, grad_own, node_llh_own, cand_own, sumF, cfg,
            with_stats=True,
        )
        sumF_new = lax.psum(sum_loc, both)
        hist = lax.psum(hist, both)
        if dx.health_on(cfg):
            gstats = dx.gated_grad_stats(
                cfg, it, grad_own, node_axis=both, k_axis=K_AXIS
            )
        else:
            gstats = dx.zero_grad_stats()
        return (
            F_new, sumF_new, llh_cur.astype(F_blk.dtype), it + 1, hist,
            gstats,
        )

    nspec = P((ROWS_AXIS, COLS_AXIS), None, None)

    def step(state: TrainState, src, dst, mask, sidx) -> TrainState:
        F_new, sumF, llh, it, hist, gstats = shard_map(
            step_shard,
            mesh=mesh,
            in_specs=(
                P((ROWS_AXIS, COLS_AXIS), K_AXIS),
                nspec, nspec, nspec, nspec, P(),
            ),
            out_specs=(
                P((ROWS_AXIS, COLS_AXIS), K_AXIS),
                P(K_AXIS), P(), P(), P(), P(),
            ),
        )(state.F, src, dst, mask, sidx, state.it)
        return TrainState(
            F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
            health=_shard_health(cfg, state, F_new, sumF, hist, gstats),
        )

    # edge/send arrays as jit ARGUMENTS (multi-controller: no closing over
    # non-addressable-device arrays; see make_sharded_csr_train_step)
    jitted = jax.jit(step)

    def step_fn(state):
        return jitted(state, edges.src, edges.dst, edges.mask, send_idx)

    step_fn.jitted = jitted
    step_fn.jit_args = (edges.src, edges.dst, edges.mask, send_idx)
    return attach_donating(step_fn, step, fixed_args=step_fn.jit_args)


class TwoDShardedBigClamModel(ShardedBigClamModel):
    """2D edge-block trainer over a (rows, cols, k=1) mesh.

    Same API and math as ShardedBigClamModel — fit/checkpoint/serve
    machinery is inherited through the mesh/layout hooks — but the step
    exchanges closure rows instead of all-gathering F. cfg.partition is
    step-baked: this class refuses to build unless cfg says "2d" (the
    perf ledger keys on it), and the CSR/fused kernel families refuse
    with an explicit reason (the closure schedule is XLA-only for now)."""

    def __init__(
        self,
        g: Graph,
        cfg: BigClamConfig,
        mesh: Mesh,
        dtype=None,
        balance: bool = False,
    ):
        self.g = g
        self.cfg = cfg
        self.mesh = mesh
        for ax in (ROWS_AXIS, COLS_AXIS, K_AXIS):
            if ax not in mesh.shape:
                raise ValueError(
                    "partition='2d' needs a (rows, cols, k) mesh from "
                    f"make_mesh_2d; got axes {tuple(mesh.shape)}"
                )
        R, C = mesh.shape[ROWS_AXIS], mesh.shape[COLS_AXIS]
        if mesh.shape[K_AXIS] != 1:
            raise ValueError(
                "partition='2d' does not shard the community axis: the "
                "mesh 'k' axis must be 1 (TP rides the 1d families)"
            )
        if cfg.partition != "2d":
            raise ValueError(
                f"cfg.partition={cfg.partition!r} on the 2d trainer: the "
                "step and the perf-ledger match key are partition-baked — "
                "set partition='2d'"
            )
        if cfg.replica_cols != C:
            raise ValueError(
                f"cfg.replica_cols={cfg.replica_cols} != mesh cols {C}; "
                "build the mesh from the config (twod_mesh_shape)"
            )
        if cfg.use_pallas_csr is True:
            raise ValueError(
                "use_pallas_csr=True is not supported under "
                "partition='2d': the closure-gather schedule is XLA-only "
                "— drop the override, or run --partition 1d for the "
                "fused kernels"
            )
        self.R, self.C = R, C
        self.p = R * C
        self.dtype = dtype or (
            jnp.float64 if cfg.dtype == "float64" else jnp.float32
        )
        if cfg.min_f != 0.0:
            raise ValueError("sharded padding requires min_f == 0.0")
        self.n_pad = _round_up(max(g.num_nodes, self.p), self.p)
        self.k_pad = cfg.num_communities
        self._csr_wanted = False
        self._csr_reason = (
            "partition=2d runs the XLA closure-gather schedule; the "
            "fused/CSR kernels ride the 1d families (the closure table "
            "is already the flat row layout their dst-DMA consumes — "
            "see DESIGN.md)"
        )
        self._perm = None
        self.g_original = g
        if balance and self.p > 1:
            from bigclam_tpu.parallel.balance import balance_graph

            self.g, self._perm = balance_graph(g, self.p, self.n_pad)
        self._pad_stats = None
        self._build_edges_and_step()
        from bigclam_tpu.models.bigclam import (
            log_engaged_path,
            step_cfg_key,
        )
        from bigclam_tpu.obs import note_step_build

        self._step_cache = {step_cfg_key(self.cfg): self._step}
        self.path_reason = self._csr_reason
        note_step_build(self.cfg, type(self).__name__)
        log_engaged_path(
            type(self).__name__, self.engaged_path, self.path_reason
        )
        self.comms = self._build_comms_model()
        self._emit_comms_and_balance()
        self._bake_memory_model()

    # ------------------------------------------------- mesh/layout hooks
    def _node_shards(self) -> int:
        return self.p

    def _fspec(self) -> NamedSharding:
        return NamedSharding(self.mesh, P((ROWS_AXIS, COLS_AXIS), K_AXIS))

    def _espec(self) -> NamedSharding:
        return NamedSharding(self.mesh, P((ROWS_AXIS, COLS_AXIS), None, None))

    def _memory_dp(self) -> int:
        return self.p

    @property
    def engaged_path(self) -> str:
        return "xla_2d"

    # ------------------------------------------------------ layout/step
    def _build_edges_and_step(self) -> None:
        bound = edge_chunk_bound(self.cfg, max(self.k_pad, 1), self.dtype)
        layout = twod_shard_edges(
            self.g, self.cfg, self.R, self.C, self.n_pad, np.float32,
            chunk_bound=bound,
        )
        self._commit_layout(
            layout,
            src=put_sharded(layout.edges.src, self._espec()),
            dst=put_sharded(layout.edges.dst, self._espec()),
            mask=put_sharded(
                layout.edges.mask.astype(self.dtype), self._espec()
            ),
            send=put_sharded(layout.send_idx, self._espec()),
        )

    def _commit_layout(self, layout: TwoDLayout, src, dst, mask,
                       send) -> None:
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = dict(tile_pad_stats(layout.edges.mask))
        self._pad_stats["closure_cap"] = int(layout.cap)
        self._pad_stats["closure_slots_padded"] = (
            self.p * self.R * int(layout.cap)
        )
        self._pad_stats["closure_rows"] = int(layout.closure_rows)
        self._twod_cap = int(layout.cap)
        self._block_counts = layout.block_edge_counts
        self.edges = EdgeChunks(src=src, dst=dst, mask=mask)
        self._send_idx = send
        self._step = make_twod_train_step(
            self.mesh, self.edges, self._send_idx, self.cfg
        )

    def rebuild_step(self) -> None:
        from bigclam_tpu.models.bigclam import step_cfg_key

        key = step_cfg_key(self.cfg)
        cache = self._step_cache
        if key not in cache:
            cache[key] = make_twod_train_step(
                self.mesh, self.edges, self._send_idx, self.cfg
            )
            from bigclam_tpu.obs import note_step_build

            note_step_build(self.cfg, type(self).__name__)
        self._step = cache[key]

    # ------------------------------------------------------ observability
    def _build_comms_model(self):
        from bigclam_tpu.obs import comms as _comms

        return _comms.twod_step_model(
            n_pad=self.n_pad,
            k_pad=self.k_pad,
            rows=self.R,
            cols=self.C,
            itemsize=jnp.dtype(self.dtype).itemsize,
            num_candidates=len(self.cfg.step_candidates),
            edge_slots=self._edge_slots_per_shard(),
            closure_cap=self._twod_cap,
            health_every=self.cfg.health_every,
            model=type(self).__name__,
        )

    def _shard_edge_counts(self) -> np.ndarray:
        return np.asarray(self._block_counts, dtype=np.int64).reshape(-1)

    def _graph_device_arrays(self) -> dict:
        return {
            "graph/edges_src": self.edges.src,
            "graph/edges_dst": self.edges.dst,
            "graph/edges_mask": self.edges.mask,
            "graph/closure_send_idx": self._send_idx,
        }

    def _build_memory_model(self):
        from bigclam_tpu.obs import memory as _mem

        cfg = self.cfg
        return _mem.twod_memory_model(
            self.n_pad,
            self.k_pad,
            self.R,
            self.C,
            jnp.dtype(self.dtype).itemsize,
            len(cfg.step_candidates),
            self._graph_buffer_bytes(),
            closure_cap=self._twod_cap,
            health_on=int(getattr(cfg, "health_every", 0) or 0) > 0,
            donate=bool(cfg.donate_state),
            rollback=int(getattr(cfg, "rollback_budget", 0) or 0) > 0,
            fd_bytes=self._memory_fd_bytes(),
            comms=self.comms,
            model=type(self).__name__,
        )


class StoreTwoDShardedBigClamModel(_StoreBackedMixin,
                                   TwoDShardedBigClamModel):
    """2D trainer fed per-host from a compiled graph cache.

    Each process loads ONLY its own shard blobs and closure blobs;
    requester gather unions and contributor send lists are both derived
    from the host's OWN lists (edge symmetry — see twod_shard_edges_local),
    so the global CSR and the global closure never exist on any host. On
    pre-v3 caches the lists are streamed from the host's own CSR slice
    (explicit path_reason note; `cli ingest` re-bakes them). Requires
    num_shards == rows*cols and whole-processor-row process ownership
    ((num_shards / process_count) % replica_cols == 0) so the edge-block
    redistribution stays host-internal."""

    def __init__(self, store, cfg: BigClamConfig, mesh: Mesh, dtype=None,
                 verify: bool = True):
        self._store_init(store, mesh, verify)
        super().__init__(
            _StoreGraphView(store), cfg, mesh, dtype=dtype, balance=False,
        )

    def _store_init(self, store, mesh: Mesh, verify: bool) -> None:
        p = mesh.shape[ROWS_AXIS] * mesh.shape[COLS_AXIS]
        if store.num_shards != p:
            raise ValueError(
                f"cache has {store.num_shards} shards but the 2d mesh "
                f"has rows*cols={p} node blocks; recompile with "
                f"--shards {p}"
            )
        self.store = store
        self._shard_verify = verify
        self.host_shard = None

    def _pair_lists(self, shard) -> Dict[int, tuple]:
        """Owned shards' closure triples: baked v3 lists when the cache
        has them, else the v2 streaming fallback on the host's own CSR
        (recorded in path_reason — same derivation, more host time)."""
        from bigclam_tpu.graph.store import closure_pair_lists

        own = list(shard.shard_ids)
        entries = self.store.manifest["shards"]
        if own and all("closure" in entries[s] for s in own):
            cl = self.store.load_closure_lists(
                own[0], own[-1] + 1, verify=self._shard_verify
            )
            return {
                s: (sc.out_ids, sc.in_ids, sc.edge_counts)
                for s, sc in cl.shards.items()
            }
        self._csr_reason += (
            "; closure gather lists streamed from the cached CSR (cache "
            "format < v3 — re-ingest to bake closures)"
        )
        rps = shard.rows_per_shard
        n = shard.num_nodes
        out: Dict[int, tuple] = {}
        for s in own:
            glo, ghi = min(s * rps, n), min((s + 1) * rps, n)
            a = int(shard.indptr[glo - shard.lo])
            b = int(shard.indptr[ghi - shard.lo])
            ip = shard.indptr[glo - shard.lo: ghi - shard.lo + 1] - a
            out[s] = closure_pair_lists(
                glo, ip, shard.indices[a:b], rps, self.p, cap=0
            )
        return out

    def _build_edges_and_step(self) -> None:
        shard = self._load_host_shard()
        bound = edge_chunk_bound(self.cfg, max(self.k_pad, 1), self.dtype)
        local = twod_shard_edges_local(
            shard, self._pair_lists(shard), self.cfg, self.R, self.C,
            self.n_pad, np.float32, chunk_bound=bound,
        )
        gshape = (self.p,) + local.edges.src.shape[1:]
        sshape = (self.p, self.R, local.cap)
        self._commit_layout(
            local,
            src=put_host_local(local.edges.src, self._espec(), gshape),
            dst=put_host_local(local.edges.dst, self._espec(), gshape),
            mask=put_host_local(
                local.edges.mask.astype(self.dtype), self._espec(), gshape
            ),
            send=put_host_local(local.send_idx, self._espec(), sshape),
        )

    def _shard_edge_counts(self) -> np.ndarray:
        """Per edge-BLOCK counts from the v3 manifest's per-pair closure
        counts (block (i, j) = group i's edges into stripe j); pre-v3
        caches fall back to the per-shard totals — the stripe split is
        not manifest-visible there."""
        entries = self.store.manifest["shards"]
        if all("closure" in e for e in entries):
            per_pair = np.asarray(
                [e["closure"]["edge_counts"] for e in entries],
                dtype=np.int64,
            )                                   # (S, S): s -> b'
            R, C, p = self.R, self.C, self.p
            out = np.zeros(p, dtype=np.int64)
            for i in range(R):
                grp = per_pair[i * C:(i + 1) * C].sum(axis=0)   # (S,)
                for j in range(C):
                    out[i * C + j] = grp[j::C].sum()
            return out
        return np.asarray(
            [int(e["edges"]) for e in entries], dtype=np.int64
        )
