"""2D-sharded BigCLAM training: DP over node ranges x TP over the K axis.

Replaces C20 (SURVEY.md §2/§3.2): the reference's hot loop re-broadcast ALL
of F from the driver every iteration (Bigclamv2.scala:118) and ran three
more driver round trips per step. Here one `jax.lax.all_gather` of the
node-sharded F over the "nodes" axis (compiler-scheduled over ICI) replaces
the broadcast, happens ONCE per iteration, and its result feeds both the
gradient pass and all 16 line-search candidate evaluations; sumF and the
global LLH are `psum`s. With the K axis sharded (TP analog), per-edge
F_u.F_v dots are partial dots + psum over "k".

Layout:
  F          (N_pad, K_pad)   sharded P("nodes", "k")
  edges      (dp, C, chunk)   sharded P("nodes") — each node shard owns the
                              directed edges whose src it owns (src is stored
                              LOCAL to the shard; dst stays global)
  sumF       (K_pad,)         sharded P("k"), replicated over "nodes"

The per-shard edge counts of power-law graphs are unequal; shards are padded
to the max count (mask = 0). Degree-bucketed rebalancing and the ring-pass
schedule (parallel/ring.py) address the imbalance at pod scale.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.models.bigclam import (
    FLAT_FD_BUDGET,
    GROUP_FD_BUDGET,
    FitResult,
    MemoryAccountedModel,
    TrainState,
    _lcm,
    _round_up,
    attach_donating,
    edge_chunk_bound,
    restore_checkpoint,
    rowkeyed_init_F,
    rowkeyed_init_rows,
    run_fit_loop,
)
from bigclam_tpu.ops import diagnostics as dx
from bigclam_tpu.ops.objective import EdgeChunks, edge_terms
from bigclam_tpu.parallel.mesh import K_AXIS, NODES_AXIS
from bigclam_tpu.parallel.multihost import (
    addressable_row_bounds,
    fetch_global,
    host_shard_ids,
    load_host_shard,
    put_host_local,
    put_sharded,
)
from bigclam_tpu.utils.compat import shard_map


def _shard_bounds(src: np.ndarray, n_pad: int, dp: int) -> np.ndarray:
    """Edge-array index bounds of the dp row split over src-sorted
    edges — ONE implementation, shared by shard_edges' layout and the
    balance telemetry (obs.comms, ISSUE 10): the counts the telemetry
    reports are by construction the counts the trainer built."""
    shard_rows = n_pad // dp
    return np.searchsorted(
        src, np.arange(0, n_pad + shard_rows, shard_rows)
    )


def shard_edge_counts(src: np.ndarray, n_pad: int, dp: int) -> np.ndarray:
    """Per-shard directed-edge counts of the dp row split (the balance
    events' work distribution; the sparse sharded trainer shares it)."""
    return np.diff(_shard_bounds(src, n_pad, dp))


def shard_edges(
    g: Graph,
    cfg: BigClamConfig,
    dp: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
) -> EdgeChunks:
    """Partition directed edges by src ownership into (dp, C, chunk) blocks.

    CSR order means each shard's edges are one contiguous slice. src indices
    are rebased to shard-local rows; padding uses the shard's last local row
    (keeps src sorted) with mask 0. chunk_bound caps the per-chunk gather
    bytes (callers derive it via models.bigclam.edge_chunk_bound from the
    per-device gathered column count and model dtype).
    """
    shard_rows = n_pad // dp
    bounds = _shard_bounds(g.src, n_pad, dp)
    counts = np.diff(bounds)
    max_count = int(counts.max()) if counts.size else 1
    chunk = min(chunk_bound or cfg.edge_chunk, max(max_count, 1))
    c = max(1, -(-max_count // chunk))
    padded = c * chunk
    src = np.full((dp, padded), shard_rows - 1, dtype=np.int32)
    dst = np.zeros((dp, padded), dtype=np.int32)
    mask = np.zeros((dp, padded), dtype=np.float32)
    for i in range(dp):
        lo, hi = bounds[i], bounds[i + 1]
        m = hi - lo
        src[i, :m] = g.src[lo:hi] - i * shard_rows
        dst[i, :m] = g.dst[lo:hi]
        mask[i, :m] = 1.0
    return EdgeChunks(
        src=src.reshape(dp, c, chunk),
        dst=dst.reshape(dp, c, chunk),
        mask=mask.reshape(dp, c, chunk).astype(dtype),
    )


def shard_edges_local(
    shard,
    cfg: BigClamConfig,
    dp: int,
    n_pad: int,
    dtype,
    chunk_bound: int = 0,
) -> EdgeChunks:
    """This host's rows of the (dp, C, chunk) edge blocks, built from a
    per-host graph-store slice (graph/store.HostShard) — the out-of-core
    twin of shard_edges: no global CSR exists anywhere.

    The chunk geometry (max per-shard count -> chunk -> C) is computed from
    the manifest's GLOBAL per-shard edge counts, so every host pads
    identically without seeing another host's edges. Requires the cache to
    have been compiled with num_shards == dp: the store's node ranges are
    then exactly the trainer's shard rows (store rows_per_shard ==
    n_pad // dp), and this host's store shards map 1:1 onto its trainer
    shards.
    """
    shard_rows = n_pad // dp
    if shard.rows_per_shard != shard_rows:
        raise ValueError(
            f"cache rows_per_shard={shard.rows_per_shard} != trainer shard "
            f"rows {shard_rows} (n_pad={n_pad}, dp={dp}); recompile the "
            "cache with num_shards == dp"
        )
    counts = np.asarray(shard.shard_edge_counts, dtype=np.int64)
    max_count = int(counts.max()) if counts.size else 1
    chunk = min(chunk_bound or cfg.edge_chunk, max(max_count, 1))
    c = max(1, -(-max_count // chunk))
    padded = c * chunk
    n_local = len(shard.shard_ids)
    src = np.full((n_local, padded), shard_rows - 1, dtype=np.int32)
    dst = np.zeros((n_local, padded), dtype=np.int32)
    mask = np.zeros((n_local, padded), dtype=np.float32)
    n = shard.num_nodes
    deg = np.diff(shard.indptr)
    for row, s in enumerate(shard.shard_ids):
        glo = min(s * shard_rows, n)
        ghi = min((s + 1) * shard_rows, n)
        e0 = int(shard.indptr[glo - shard.lo])
        e1 = int(shard.indptr[ghi - shard.lo])
        m = e1 - e0
        if m != counts[s]:
            raise ValueError(
                f"shard {s}: manifest says {int(counts[s])} directed edges "
                f"but the loaded indptr holds {m} — cache inconsistent "
                "(partially rebuilt, or loaded with verify=False?)"
            )
        src[row, :m] = (
            np.repeat(np.arange(glo, ghi, dtype=np.int64),
                      deg[glo - shard.lo : ghi - shard.lo])
            - s * shard_rows
        )
        dst[row, :m] = shard.indices[e0:e1]
        mask[row, :m] = 1.0
    return EdgeChunks(
        src=src.reshape(n_local, c, chunk),
        dst=dst.reshape(n_local, c, chunk),
        mask=mask.reshape(n_local, c, chunk).astype(dtype),
    )


def _rowdot(a: jax.Array, b: jax.Array) -> jax.Array:
    """Per-row dot with the K axis sharded: partial dot + psum over 'k'."""
    return lax.psum(jnp.einsum("nk,nk->n", a, b), K_AXIS)


def _shard_grad_stats(grad: jax.Array, cfg: BigClamConfig, it) -> jax.Array:
    """In-shard ISSUE 8 grad stats, replicated over both mesh axes (psum
    over a size-1 axis is identity, so one call covers every tp),
    cadence-gated on `it` so off-cadence iterations skip the O(N*K)
    reductions; a constant zeros pair with health off."""
    if not dx.health_on(cfg):
        return dx.zero_grad_stats()
    return dx.gated_grad_stats(
        cfg, it, grad, node_axis=NODES_AXIS, k_axis=K_AXIS
    )


def _shard_health(cfg, state, F_new, sumF_new, hist, gstats):
    """Outer-wrapper health pack for the sharded/ring steps: computed on
    the GLOBAL (sharded) arrays after shard_map — jit partitions the
    reductions; None at trace time with health off."""
    if not dx.health_on(cfg):
        return None
    return dx.health_pack(
        cfg, state.it, state.F, F_new, sumF_new, hist, gstats
    )


def _mark_varying(x: jax.Array, axes: tuple) -> jax.Array:
    """Mark x as varying over the given mesh axes for the VMA type system
    (idempotent: axes already varying are left alone; no-op on jax 0.4.x,
    where the type system — and the need for the annotation — is absent)."""
    from bigclam_tpu.utils.compat import pcast_varying, vma_of

    vma = vma_of(x)
    missing = tuple(a for a in axes if a not in vma)
    return pcast_varying(x, missing) if missing else x


def armijo_tail_select_sharded(
    F_loc: jax.Array,
    grad: jax.Array,
    node_llh: jax.Array,
    cand_nbr: jax.Array,
    sumF: jax.Array,
    cfg: BigClamConfig,
    with_stats: bool = False,
):
    """Armijo tails (rowdot-psums over "k") + acceptance + max-accepted-step
    Jacobi update, K-shard aware. ONE implementation shared by the XLA
    sharded step, the ring step, and the CSR TP step — any tuning of the
    acceptance rule lands in all schedules at once.

    gg is computed in accum dtype exactly as ops.linesearch.armijo_update,
    so sharded acceptance decisions match single-chip bit-for-bit. Returns
    (F_new, local column sums of F_new) — the caller psums the latter.
    with_stats=True adds this shard's accept_stats histogram (the caller
    psums it over "nodes"; it is replicated over "k" since every input to
    the acceptance test is already psum'd over "k").
    """
    from bigclam_tpu.ops.linesearch import accept_stats

    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
    etas = jnp.asarray(cfg.step_candidates, F_loc.dtype)
    gg = _rowdot(grad, grad).astype(adt)

    def tail_for(eta):
        nf = jnp.clip(F_loc + eta * grad, cfg.min_f, cfg.max_f)
        sf_adj = sumF[None, :] - F_loc + nf
        return (-_rowdot(nf, sf_adj) + _rowdot(nf, nf)).astype(adt)

    tails = lax.map(tail_for, etas)
    cand_llh = cand_nbr + tails
    ok = cand_llh >= node_llh[None, :] + cfg.alpha * etas[:, None] * gg[None, :]
    best_eta = jnp.max(jnp.where(ok, etas[:, None], 0.0), axis=0)
    accepted = jnp.any(ok, axis=0)
    F_new = jnp.where(
        accepted[:, None],
        jnp.clip(F_loc + best_eta[:, None] * grad, cfg.min_f, cfg.max_f),
        F_loc,
    )
    if with_stats:
        return F_new, F_new.sum(axis=0), accept_stats(ok)
    return F_new, F_new.sum(axis=0)


def _fused_tile_extras(tiles: dict, block_id, csr_kc: int, tp: int,
                       place) -> None:
    """Augment a flat tiles dict with the fused-superstep fields
    (ISSUE 13) — ONE implementation for the in-memory and store-native
    builders, whose bit-identity is the store path's headline guarantee:
    the `fused`/`kc` flags plus, on the schedules that actually run the
    one-pass superstep (tp == 1, no K blocking — the TP and K-blocked
    fused steps never read it), the per-shard grid entry sequence built
    from `block_id` rows and device-placed via `place((dp_local, 2*nt,
    2) int32 array)`."""
    tiles["fused"] = True
    tiles["kc"] = csr_kc
    if not csr_kc and tp == 1:
        from bigclam_tpu.ops.pallas_fused import fused_entry_seq

        tiles["seq"] = place(
            np.stack([fused_entry_seq(row) for row in block_id]).astype(
                np.int32
            )
        )


def make_sharded_csr_train_step(
    mesh: Mesh, tiles, cfg: BigClamConfig
) -> Callable[[TrainState], TrainState]:
    """Sharded iteration on the blocked-CSR MXU kernels (ops.pallas_csr).

    Five schedules, chosen by the tile layout + mesh (the grouped ones
    also come K-blocked — tiles["kc"] > 0 — when even the per-device
    column count exceeds the kernels' VMEM bound):

    * tp == 1, flat: each shard all-gathers F over "nodes", gathers its
      tiles' dst rows ONCE (shared by both kernels), runs the same two
      fused Pallas kernels as the single-chip path.
    * tp > 1 (K axis sharded): the in-VMEM edge dots cannot psum mid-kernel,
      so each sweep splits into a partial-dot kernel, a lax.psum of the
      per-edge partials over "k" (1 float/edge — tiny next to any F-row
      exchange), and a consume kernel (see the TP suite in ops.pallas_csr).
      Armijo tails are XLA rowdot-psums as in the XLA sharded step.
    * grouped (large K, tp == 1): scan over block-group windows with
      per-group dst gathers from the all-gathered F (bounds the fd gather
      to GROUP_FD_BUDGET where the flat gather would blow HBM).

    LLH and sumF are psums either way. `tiles` is a dict of device arrays +
    static fields built by ShardedBigClamModel._build_csr_step.
    """
    from bigclam_tpu.ops.linesearch import accept_stats, armijo_select
    from bigclam_tpu.ops.pallas_csr import (
        GroupedTilesDev,
        TilesDev,
        cand_dots_csr,
        cand_nbr_from_x_csr,
        candidates_csr,
        edge_dots_csr,
        grad_llh_csr,
        grad_nbr_from_x_csr,
        train_pass_csr_grouped,
        train_pass_csr_grouped_kblocked_tp,
        train_pass_csr_grouped_tp,
    )
    from bigclam_tpu.ops.pallas_fused import (
        cand_dots_fused,
        edge_dots_fused,
        fused_superstep_csr,
        grad_nbr_from_x_fused,
        train_pass_csr_kblocked_fused,
    )

    interp = cfg.pallas_interpret
    tp = mesh.shape[K_AXIS]
    block_b = tiles["block_b"]
    tile_t = tiles["tile_t"]
    grouped = tiles.get("nb") is not None
    kc = tiles.get("kc", 0)
    fused = bool(tiles.get("fused"))
    has_seq = fused and tiles.get("seq") is not None

    def finish(F_loc, grad, node_llh, cand_nbr, sumF, it):
        """Armijo tails + select + update (shared helper) + the psums."""
        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_loc, grad, node_llh, cand_nbr, sumF, cfg, with_stats=True
        )
        sumF_new = lax.psum(sum_loc, NODES_AXIS)
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)
        hist = lax.psum(hist, NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step_shard_flat(F_loc, srcl, dst, mask, bid, it):
        srcl, dst, mask, bid = srcl[0], dst[0], mask[0], bid[0]
        td = TilesDev(
            src_local=srcl, dst=dst, mask=mask, block_id=bid,
            block_b=block_b, tile_t=tile_t, n_blocks=tiles["n_blocks"],
        )
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)
        fd = jnp.take(F_full, td.dst, axis=0)
        grad, node_llh = grad_llh_csr(
            F_loc, sumF, td, cfg, fd=fd, interpret=interp
        )
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)
        cand_full = candidates_csr(
            F_loc, grad, sumF, td, cfg, fd=fd, interpret=interp
        )
        F_new, sum_loc, hist = armijo_select(
            F_loc, grad, node_llh, cand_full, cfg, with_stats=True
        )
        sumF_new = lax.psum(sum_loc, NODES_AXIS)
        hist = lax.psum(hist, NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step_shard_tp(F_loc, srcl, dst, mask, bid, it):
        srcl, dst, mask, bid = srcl[0], dst[0], mask[0], bid[0]
        td = TilesDev(
            src_local=srcl, dst=dst, mask=mask, block_id=bid,
            block_b=block_b, tile_t=tile_t, n_blocks=tiles["n_blocks"],
        )
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)       # (K_loc,)
        fd = jnp.take(F_full, td.dst, axis=0)                # K-local rows
        x = lax.psum(
            edge_dots_csr(F_loc, td, fd, interpret=interp), K_AXIS
        )
        grad_nbr, llh_nbr = grad_nbr_from_x_csr(
            x, td, fd, cfg, interpret=interp
        )
        grad = grad_nbr - sumF[None, :] + F_loc
        node_llh = llh_nbr.astype(adt) + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        xc = lax.psum(
            cand_dots_csr(F_loc, grad, td, fd, cfg, interpret=interp),
            K_AXIS,
        )
        cand_nbr = cand_nbr_from_x_csr(xc, td, cfg, interpret=interp)
        return finish(F_loc, grad, node_llh, cand_nbr.astype(adt), sumF, it)

    def step_shard_grouped(F_loc, srcl, dst, mask, bid, it):
        gt = GroupedTilesDev(
            src_local=srcl[0], dst=dst[0], mask=mask[0], block_id=bid[0],
            block_b=block_b, tile_t=tile_t, nb=tiles["nb"],
            n_groups=tiles["n_groups"],
        )
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)
        grad, node_llh, cand_full = train_pass_csr_grouped(
            F_loc, sumF, gt, cfg, interpret=interp, F_gather=F_full
        )
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)
        F_new, sum_loc, hist = armijo_select(
            F_loc, grad, node_llh, cand_full, cfg, with_stats=True
        )
        sumF_new = lax.psum(sum_loc, NODES_AXIS)
        hist = lax.psum(hist, NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step_shard_grouped_tp(F_loc, srcl, dst, mask, bid, it):
        gt = GroupedTilesDev(
            src_local=srcl[0], dst=dst[0], mask=mask[0], block_id=bid[0],
            block_b=block_b, tile_t=tile_t, nb=tiles["nb"],
            n_groups=tiles["n_groups"],
        )
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)       # (K_loc,)
        grad, llh_nbr, cand_nbr = train_pass_csr_grouped_tp(
            F_loc, sumF, gt, cfg, K_AXIS, interpret=interp, F_gather=F_full
        )
        node_llh = llh_nbr.astype(adt) + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        return finish(F_loc, grad, node_llh, cand_nbr.astype(adt), sumF, it)

    def step_shard_grouped_kb(F_loc, srcl, dst, mask, bid, it):
        # K-blocked grouped pass (K_loc > VMEM bound): identical shape to
        # the grouped-TP step, the K-block scan lives inside the pass; at
        # tp == 1 its psums over "k" are identity and this is the sharded
        # twin of the single-chip csr_grouped_kb step
        gt = GroupedTilesDev(
            src_local=srcl[0], dst=dst[0], mask=mask[0], block_id=bid[0],
            block_b=block_b, tile_t=tile_t, nb=tiles["nb"],
            n_groups=tiles["n_groups"], kc=kc,
        )
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)       # (K_loc,)
        grad, llh_nbr, cand_nbr = train_pass_csr_grouped_kblocked_tp(
            F_loc, sumF, gt, cfg, K_AXIS, interpret=interp, F_gather=F_full
        )
        node_llh = llh_nbr.astype(adt) + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        return finish(F_loc, grad, node_llh, cand_nbr.astype(adt), sumF, it)

    def step_shard_fused(F_loc, srcl, dst, mask, bid, seq, it):
        # the ONE-PASS fused superstep per shard (ISSUE 13, tp == 1):
        # in-kernel dst DMA from the all-gathered F, grad VMEM-resident,
        # Armijo select + projection in the same kernel — only the psums
        # of the already-reduced outputs remain in XLA
        srcl, dst, mask, bid, seq = (
            srcl[0], dst[0], mask[0], bid[0], seq[0]
        )
        td = TilesDev(
            src_local=srcl, dst=dst, mask=mask, block_id=bid,
            block_b=block_b, tile_t=tile_t, n_blocks=tiles["n_blocks"],
            seq=seq,
        )
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)
        F_new, grad, node_llh, ok = fused_superstep_csr(
            F_loc, sumF, td, cfg, interpret=interp, F_gather=F_full
        )
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)
        sumF_new = lax.psum(F_new.sum(axis=0), NODES_AXIS)
        hist = lax.psum(accept_stats(ok > 0), NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step_shard_fused_tp(F_loc, srcl, dst, mask, bid, it):
        # K-sharded fused (tp > 1): the TP kernel split with the fd
        # gather moved in-kernel (whole K_loc rows DMA'd from F_full —
        # kb=0, kc=K_loc); psums between kernels unchanged
        srcl, dst, mask, bid = srcl[0], dst[0], mask[0], bid[0]
        td = TilesDev(
            src_local=srcl, dst=dst, mask=mask, block_id=bid,
            block_b=block_b, tile_t=tile_t, n_blocks=tiles["n_blocks"],
        )
        k_loc = F_loc.shape[1]
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)       # (K_loc,)
        x = lax.psum(
            edge_dots_fused(
                F_loc, td, F_full, 0, k_loc, interpret=interp
            ),
            K_AXIS,
        )
        grad_nbr, llh_nbr = grad_nbr_from_x_fused(
            x, td, F_full, 0, k_loc, cfg, interpret=interp
        )
        grad = grad_nbr - sumF[None, :] + F_loc
        node_llh = llh_nbr.astype(adt) + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        xc = lax.psum(
            cand_dots_fused(
                F_loc, grad, td, F_full, 0, k_loc, cfg, interpret=interp
            ),
            K_AXIS,
        )
        cand_nbr = cand_nbr_from_x_csr(xc, td, cfg, interpret=interp)
        return finish(F_loc, grad, node_llh, cand_nbr.astype(adt), sumF, it)

    def step_shard_fused_kb(F_loc, srcl, dst, mask, bid, it):
        # K-blocked fused (large K, any tp) on FLAT tiles: no grouped
        # layout — with the gather in-kernel there is no fd to budget,
        # which is also what makes this layout store-native (the flat
        # local tile builders already exist)
        srcl, dst, mask, bid = srcl[0], dst[0], mask[0], bid[0]
        td = TilesDev(
            src_local=srcl, dst=dst, mask=mask, block_id=bid,
            block_b=block_b, tile_t=tile_t, n_blocks=tiles["n_blocks"],
            kc=kc,
        )
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)       # (K_loc,)
        grad, llh_nbr, cand_nbr = train_pass_csr_kblocked_fused(
            F_loc, sumF, td, cfg, k_axis=K_AXIS, interpret=interp,
            F_gather=F_full,
        )
        node_llh = llh_nbr.astype(adt) + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        return finish(F_loc, grad, node_llh, cand_nbr.astype(adt), sumF, it)

    if fused and kc:
        step_shard = step_shard_fused_kb
    elif fused and tp > 1:
        step_shard = step_shard_fused_tp
    elif has_seq:
        step_shard = step_shard_fused
    elif grouped and kc:
        step_shard = step_shard_grouped_kb
    elif grouped and tp > 1:
        step_shard = step_shard_grouped_tp
    elif grouped:
        step_shard = step_shard_grouped
    elif tp > 1:
        step_shard = step_shard_tp
    else:
        step_shard = step_shard_flat

    def spec_for(arr) -> P:
        return P(NODES_AXIS, *([None] * (arr.ndim - 1)))

    tile_args = [
        tiles["src_local"], tiles["dst"], tiles["mask"], tiles["block_id"],
    ]
    if step_shard is step_shard_fused:
        tile_args.append(tiles["seq"])

    def step(state: TrainState, *targs) -> TrainState:
        # check_vma=False: pallas_call's interpret-mode lowering mixes
        # varying (scalar-prefetched block ids) and replicated operands in
        # dynamic_slice, which the VMA type check cannot express yet; the
        # XLA sharded step keeps the checked path and the equivalence tests
        # (tests/test_pallas_csr.py::TestShardedCSR) pin the semantics
        F_new, sumF, llh, it, hist, gstats = shard_map(
            step_shard,
            mesh=mesh,
            in_specs=(
                (P(NODES_AXIS, K_AXIS),)
                + tuple(spec_for(a) for a in targs)
                + (P(),)
            ),
            out_specs=(
                P(NODES_AXIS, K_AXIS), P(K_AXIS), P(), P(), P(), P(),
            ),
            check_vma=False,
        )(state.F, *targs, state.it)
        return TrainState(
            F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
            health=_shard_health(cfg, state, F_new, sumF, hist, gstats),
        )

    # tile arrays ride as jit ARGUMENTS, not closure constants: under
    # multi-controller jax, closing over an array that spans non-addressable
    # devices is an error (caught by tests/test_multihost.py's true
    # two-process test)
    jitted = jax.jit(step)

    def step_fn(state):
        return jitted(state, *tile_args)

    # AOT handles for scripts/ring_memory.py's compiler memory analysis
    step_fn.jitted = jitted
    step_fn.jit_args = tuple(tile_args)
    return attach_donating(step_fn, step, fixed_args=step_fn.jit_args)


def make_sharded_train_step(
    mesh: Mesh, edges: EdgeChunks, cfg: BigClamConfig
) -> Callable[[TrainState], TrainState]:
    """One jitted sharded iteration: all-gather F once, fused grad/LLH sweep,
    16-candidate sweep against the same gathered F, Jacobi update, psum LLH.
    Semantics identical to the single-chip step (shard-count invariance is
    tested on the CPU device-count fake, SURVEY.md §4.4)."""

    def step_shard(F_loc, src, dst, mask, it):
        # squeeze the leading per-shard axis shard_map leaves on the blocks
        src, dst, mask = src[0], dst[0], mask[0]
        adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F_loc.dtype
        etas = jnp.asarray(cfg.step_candidates, F_loc.dtype)

        # ONE all-gather per iteration (vs the reference's full re-broadcast
        # + 16 cartesian re-reads): F rows for edge destinations
        F_full = lax.all_gather(F_loc, NODES_AXIS, axis=0, tiled=True)
        sumF = lax.psum(F_loc.sum(axis=0), NODES_AXIS)      # (K_loc,)

        # grad needs only pass-1 results; compute grad before candidates
        # by running the fused sweep in two stages: first grad/LLH, then
        # candidates (gathers shared within each stage's chunk)
        n_loc = F_loc.shape[0]

        def grad_body(carry, sdm):
            nbr_llh, nbr_grad = carry
            s, d, m = sdm
            fs, fd = F_loc[s], F_full[d]
            x = lax.psum(jnp.einsum("ek,ek->e", fs, fd), K_AXIS)
            omp, ell = edge_terms(x, cfg)
            coeff = m / omp
            nbr_llh = nbr_llh + jax.ops.segment_sum(
                (ell * m).astype(adt), s, num_segments=n_loc,
                indices_are_sorted=True,
            )
            nbr_grad = nbr_grad + jax.ops.segment_sum(
                fd * coeff[:, None], s, num_segments=n_loc,
                indices_are_sorted=True,
            )
            return (nbr_llh, nbr_grad), None

        # scan carries are varying across shards: mark them so the VMA
        # type check accepts the accumulation
        (nbr_llh, nbr_grad), _ = lax.scan(
            grad_body,
            (
                _mark_varying(jnp.zeros(n_loc, adt), (NODES_AXIS,)),
                _mark_varying(jnp.zeros_like(F_loc), (NODES_AXIS, K_AXIS)),
            ),
            (src, dst, mask),
        )
        grad = nbr_grad - sumF[None, :] + F_loc
        node_llh = nbr_llh + (
            -lax.psum(F_loc @ sumF, K_AXIS) + _rowdot(F_loc, F_loc)
        ).astype(adt)
        llh_cur = lax.psum(node_llh.sum(), NODES_AXIS)

        def cand_body(cand, sdm):
            s, d, m = sdm
            fs, gs, fd = F_loc[s], grad[s], F_full[d]

            def one_eta(eta):
                nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
                xc = lax.psum(jnp.einsum("ek,ek->e", nf, fd), K_AXIS)
                _, ellc = edge_terms(xc, cfg)
                return jax.ops.segment_sum(
                    (ellc * m).astype(adt), s, num_segments=n_loc,
                    indices_are_sorted=True,
                )

            return cand + lax.map(one_eta, etas), None

        cand_nbr, _ = lax.scan(
            cand_body,
            _mark_varying(
                jnp.zeros((len(cfg.step_candidates), n_loc), adt), (NODES_AXIS,)
            ),
            (src, dst, mask),
        )

        # Armijo acceptance + max-accepted-step update (shared helper)
        F_new, sum_loc, hist = armijo_tail_select_sharded(
            F_loc, grad, node_llh, cand_nbr, sumF, cfg, with_stats=True
        )
        sumF_new = lax.psum(sum_loc, NODES_AXIS)             # (K_loc,)
        hist = lax.psum(hist, NODES_AXIS)
        return (
            F_new, sumF_new, llh_cur.astype(F_loc.dtype), it + 1, hist,
            _shard_grad_stats(grad, cfg, it),
        )

    def step(state: TrainState, src, dst, mask) -> TrainState:
        F_new, sumF, llh, it, hist, gstats = shard_map(
            step_shard,
            mesh=mesh,
            in_specs=(
                P(NODES_AXIS, K_AXIS),
                P(NODES_AXIS, None, None),
                P(NODES_AXIS, None, None),
                P(NODES_AXIS, None, None),
                P(),
            ),
            out_specs=(
                P(NODES_AXIS, K_AXIS), P(K_AXIS), P(), P(), P(), P(),
            ),
        )(state.F, src, dst, mask, state.it)
        return TrainState(
            F=F_new, sumF=sumF, llh=llh, it=it, accept_hist=hist,
            health=_shard_health(cfg, state, F_new, sumF, hist, gstats),
        )

    # edge arrays as jit ARGUMENTS (multi-controller: no closing over
    # non-addressable-device arrays; see make_sharded_csr_train_step)
    jitted = jax.jit(step)

    def step_fn(state):
        return jitted(state, edges.src, edges.dst, edges.mask)

    step_fn.jitted = jitted
    step_fn.jit_args = (edges.src, edges.dst, edges.mask)
    return attach_donating(step_fn, step, fixed_args=step_fn.jit_args)


class ShardedBigClamModel(MemoryAccountedModel):
    """Multi-chip BigCLAM trainer over a (nodes, k) mesh.

    Mirrors models.BigClamModel's API; identical trajectories (the sharding
    changes the schedule, not the math).
    """

    def __init__(
        self,
        g: Graph,
        cfg: BigClamConfig,
        mesh: Mesh,
        dtype=None,
        balance: bool = False,
    ):
        self.g = g
        self.cfg = cfg
        self.mesh = mesh
        dp = mesh.shape[NODES_AXIS]
        tp = mesh.shape[K_AXIS]
        self.dtype = dtype or (
            jnp.float64 if cfg.dtype == "float64" else jnp.float32
        )
        if cfg.min_f != 0.0:
            raise ValueError("sharded padding requires min_f == 0.0")
        self.n_pad = _round_up(max(g.num_nodes, dp), dp)
        self.k_pad = _round_up(cfg.num_communities, tp)
        self._csr_wanted = self._csr_static_ok(tp) and self._csr_economy_ok(dp)
        if self._csr_wanted:
            # blocked-CSR kernel layout: shards hold whole node blocks (and
            # whole block GROUPS on the grouped path) and K_loc rides the
            # 128-lane MXU tiling (padding rows/cols are inert). Committed
            # only now — the economy probe above already accepted the
            # layout, so the XLA fallback never sees inflated padding.
            self.n_pad = _round_up(
                max(g.num_nodes, dp),
                dp * self._csr_shape[0] * (self._csr_nb or 1),
            )
            self.k_pad = self._csr_k_pad
        # degree-balanced relabeling (parallel/balance.py): the trainer runs
        # on the relabeled graph; F0 in / results out stay in original ids
        # (g_original keeps the caller's id space for host-side passes that
        # consume FitResult.F, e.g. quality repair)
        self._perm = None
        self.g_original = g
        if balance and dp > 1:
            from bigclam_tpu.parallel.balance import balance_graph

            self.g, self._perm = balance_graph(g, dp, self.n_pad)
        # tile/edge-padding slot accounting (obs.comms balance events):
        # filled by whichever layout builder runs below
        self._pad_stats = None
        self._build_edges_and_step()    # hook: subclasses swap the schedule
        from bigclam_tpu.models.bigclam import step_cfg_key

        self._step_cache = {step_cfg_key(self.cfg): self._step}
        self.path_reason = getattr(self, "_csr_reason", "")
        from bigclam_tpu.models.bigclam import log_engaged_path
        from bigclam_tpu.obs import note_step_build

        note_step_build(self.cfg, type(self).__name__)
        log_engaged_path(
            type(self).__name__, self.engaged_path, self.path_reason
        )
        # collective-traffic model + per-shard balance (obs.comms,
        # ISSUE 10): baked from the SAME committed layout the step
        # compiled against, emitted as `comms`/`balance` events and kept
        # on the model for the reconciliation gate (comms_measured)
        self.comms = self._build_comms_model()
        self._emit_comms_and_balance()
        # static memory model (obs.memory, ISSUE 12): the per-device
        # HBM + per-host RSS twin of the comms model, baked from the
        # SAME committed layout (collective scratch priced from the
        # comms Sites just built)
        self._bake_memory_model()

    # ------------------------------------------------- mesh/layout hooks
    # The 2D edge-block partition (parallel/twod.py, ISSUE 16) reuses this
    # class's fit/checkpoint/state machinery on a (rows, cols, k) mesh;
    # everything axis-named goes through these three hooks so the
    # subclass swaps the layout without forking the plumbing.
    def _node_shards(self) -> int:
        """How many ways the node axis is sharded (dp here; R*C in 2D)."""
        return self.mesh.shape[NODES_AXIS]

    def _fspec(self) -> NamedSharding:
        """Sharding of F (and any (n_pad, k_pad) state array)."""
        return NamedSharding(self.mesh, P(NODES_AXIS, K_AXIS))

    def _espec(self) -> NamedSharding:
        """Sharding of the (shards, C, chunk) edge-block arrays."""
        return NamedSharding(self.mesh, P(NODES_AXIS, None, None))

    @property
    def engaged_path(self) -> str:
        """Edge-sweep implementation this trainer compiled (see
        log_engaged_path); subclasses with more schedules override."""
        if not self._csr_wanted:
            return "xla"
        if getattr(self, "_csr_fused", False):
            return (
                "csr_fused_kb" if getattr(self, "_csr_kc", 0) else "csr_fused"
            )
        if getattr(self, "_csr_nb", None):
            return (
                "csr_grouped_kb"
                if getattr(self, "_csr_kc", 0)
                else "csr_grouped"
            )
        return "csr"

    # ------------------------------------------- comms accounting (ISSUE 10)
    def _edge_slots_per_shard(self) -> int:
        """Per-shard padded edge-slot count of the BUILT layout (the
        tp > 1 partial-dot psums move one float per slot per sweep)."""
        if self._csr_wanted:
            src = self._tiles_dev["src_local"]
        else:
            src = self.edges.src
        return int(np.prod(src.shape[1:]))

    def _build_comms_model(self):
        from bigclam_tpu.obs import comms as _comms

        return _comms.sharded_step_model(
            n_pad=self.n_pad,
            k_pad=self.k_pad,
            dp=self.mesh.shape[NODES_AXIS],
            tp=self.mesh.shape[K_AXIS],
            itemsize=jnp.dtype(self.dtype).itemsize,
            num_candidates=len(self.cfg.step_candidates),
            edge_slots=self._edge_slots_per_shard(),
            health_every=self.cfg.health_every,
            model=type(self).__name__,
            health_participants=self.mesh.size,
        )

    def _shard_edge_counts(self) -> np.ndarray:
        """Per-shard directed-edge counts of the trainer's row split —
        the balance event's work distribution (the store trainers read
        the manifest instead: no global CSR exists there)."""
        return shard_edge_counts(
            self.g.src, self.n_pad, self._node_shards()
        )

    def _emit_comms_and_balance(self) -> None:
        from bigclam_tpu.obs import comms as _comms
        from bigclam_tpu.obs import telemetry as _obs

        _comms.emit_model(self.comms)
        if _obs.current() is None:
            return
        dp = self._node_shards()
        fields = dict(self._pad_stats or {})
        fields["model"] = type(self).__name__
        fields["dp"] = dp
        _comms.emit_shard_balance(
            "shard_edges", self._shard_edge_counts(), dp,
            process_count=jax.process_count(),
            hint="relabel (balance=True) or re-ingest with --balance",
            **fields,
        )

    def comms_measured(self, state: TrainState):
        """The comms model re-priced from the LIVE TrainState's
        addressable device buffers (obs.comms.measured_payloads) — what
        scripts/comms_gate.py reconciles the static model against."""
        from bigclam_tpu.obs import comms as _comms

        return self.comms.remeasure(
            _comms.measured_payloads(self.comms.family, state)
        )

    # ------------------------------------------ memory model (ISSUE 12)
    def _graph_device_arrays(self) -> dict:
        if self._csr_wanted:
            t = self._tiles_dev
            return {
                "graph/tiles_src": t["src_local"],
                "graph/tiles_dst": t.get("dst", t.get("dst_local")),
                "graph/tiles_mask": t["mask"],
                "graph/tiles_block_id": t["block_id"],
            }
        return {
            "graph/edges_src": self.edges.src,
            "graph/edges_dst": self.edges.dst,
            "graph/edges_mask": self.edges.mask,
        }

    def _memory_fd_bytes(self) -> float:
        """Per-shard dst-row gather bytes: one group/phase window on the
        grouped/ring CSR layouts, the whole per-shard tile set on the
        flat layout, (chunk, K_loc) per scan step on XLA — or, on the
        fused paths, the (2, T, Kc) in-kernel DMA double buffer that
        replaces the gather (ISSUE 13)."""
        isz = jnp.dtype(self.dtype).itemsize
        k_loc = self.k_pad // self.mesh.shape[K_AXIS]
        cols = getattr(self, "_csr_kc", 0) or k_loc
        if self._csr_wanted and getattr(self, "_csr_fused", False):
            return 2.0 * self._tiles_dev["tile_t"] * cols * isz
        if self._csr_wanted:
            t = self._tiles_dev
            dst = t.get("dst", t.get("dst_local"))
            if dst.ndim >= 4:      # grouped (dp, ng, G, T) / ring
                per = float(np.prod(dst.shape[2:]))   # (dp, dp, nt, T)
            else:                  # flat (dp, nt, T)
                per = float(np.prod(dst.shape[1:]))
            return per * cols * isz
        return float(self.edges.src.shape[-1]) * cols * isz

    def _build_memory_model(self):
        from bigclam_tpu.obs import memory as _mem

        cfg = self.cfg
        return _mem.sharded_memory_model(
            self.n_pad,
            self.k_pad,
            self.mesh.shape[NODES_AXIS],
            self.mesh.shape[K_AXIS],
            jnp.dtype(self.dtype).itemsize,
            len(cfg.step_candidates),
            self._graph_buffer_bytes(),
            health_on=int(getattr(cfg, "health_every", 0) or 0) > 0,
            donate=bool(cfg.donate_state),
            rollback=int(getattr(cfg, "rollback_budget", 0) or 0) > 0,
            fd_bytes=self._memory_fd_bytes(),
            fused=self._csr_wanted and getattr(self, "_csr_fused", False),
            comms=self.comms,
            model=type(self).__name__,
        )

    def _to_internal_rows(self, F0: np.ndarray) -> np.ndarray:
        """Original-id F rows -> the trainer's (possibly relabeled) row order."""
        if self._perm is None:
            return F0
        out = np.empty_like(F0)
        out[self._perm] = F0
        return out

    def _from_internal_rows(self, F: np.ndarray) -> np.ndarray:
        """Trainer row order -> original ids (inverse of _to_internal_rows)."""
        return F if self._perm is None else F[self._perm]

    def _csr_static_ok(self, tp: int) -> bool:
        """Static engagement check for the blocked-CSR sharded step (the
        economy checks that need the built tiles live in _csr_economy_ok).

        tp > 1 is supported via the TP kernel suite (partial dots + psum
        over "k", ops.pallas_csr); it needs K_loc = k_pad/tp to satisfy the
        Mosaic lane alignment, so k_pad is rounded up to 128*tp."""
        from bigclam_tpu.ops.pallas_csr import (
            csr_tiles_supported,
            fit_tile_shape,
        )

        from bigclam_tpu.models.bigclam import csr_want_reason

        from bigclam_tpu.models.bigclam import csr_fused_want

        cfg = self.cfg
        want, reason = csr_want_reason(cfg)
        if not want:
            self._csr_reason = reason
            return False
        self._csr_fused = csr_fused_want(cfg)
        # per-device column count governs the kernels' VMEM working set
        self._csr_kc = 0
        if cfg.csr_k_block:
            # explicit K-blocked mode (also the interpret-mode test hook):
            # per-device columns processed kc at a time
            self._csr_kc = cfg.csr_k_block
            self._csr_k_pad = _round_up(
                self.k_pad,
                self._csr_kc * tp if cfg.pallas_interpret
                else _lcm(self._csr_kc, 128) * tp,
            )
        else:
            self._csr_k_pad = (
                self.k_pad
                if cfg.pallas_interpret
                else _round_up(self.k_pad, 128 * tp)
            )
        k_loc = self._csr_k_pad // tp
        # shrink tiles to the kernels' VMEM budget, like the single-chip path
        if cfg.pallas_interpret:
            self._csr_shape = (cfg.csr_block_b, cfg.csr_tile_t)
        else:
            self._csr_shape = fit_tile_shape(
                cfg.csr_block_b, cfg.csr_tile_t, self._csr_kc or k_loc,
                fused=self._csr_fused,
            )
            if self._csr_shape is None and not self._csr_kc:
                # K_loc itself exceeds VMEM (extreme K / small tp):
                # K-blocked sharded mode, same policy as the single-chip
                # trainer; the step then runs
                # train_pass_csr_grouped_kblocked_tp (split) or
                # train_pass_csr_kblocked_fused on flat tiles (fused)
                from bigclam_tpu.ops.pallas_csr import largest_fitting_kblock

                found = largest_fitting_kblock(
                    cfg.csr_block_b, cfg.csr_tile_t, k_loc,
                    fused=self._csr_fused,
                )
                if found is not None:
                    self._csr_kc, self._csr_shape = found
        ok = (
            self.dtype == jnp.float32
            and cfg.accum_dtype in (None, "float32")
            and self._csr_shape is not None
            and csr_tiles_supported(
                *self._csr_shape, self._csr_kc or k_loc, cfg.pallas_interpret
            )
        )
        if not ok and cfg.use_pallas_csr is True:
            raise ValueError(
                "use_pallas_csr=True on the sharded trainer requires "
                "float32 F/accum and 128-multiple block_b/tile_t/K_loc; "
                f"got tp={tp}, dtype={self.dtype}, "
                f"block_b={cfg.csr_block_b}, tile_t={cfg.csr_tile_t}"
            )
        if not ok:
            self._csr_reason = (
                f"static constraints unmet: tp={tp}, dtype={self.dtype}, "
                f"accum_dtype={cfg.accum_dtype}, tile shape={self._csr_shape}"
            )
        return ok

    def _csr_economy_ok(self, dp: int) -> bool:
        """Probe the tile layout's padding/memory economy BEFORE committing
        the CSR paddings (runs on the pre-balance graph — balancing only
        evens the layout further). Raises when use_pallas_csr=True.

        When the flat per-shard fd gather exceeds FLAT_FD_BUDGET (large
        N_loc*K), falls through to the grouped layout (tp == 1 only) —
        exactly the regime where round 1 silently degraded to XLA."""
        from bigclam_tpu.ops.csr_tiles import (
            layout_economical,
            shard_block_tiles,
        )

        cfg = self.cfg
        tp = self.mesh.shape[K_AXIS]
        block_b, tile_t = self._csr_shape
        n_pad = _round_up(
            max(self.g.num_nodes, dp), dp * block_b
        )
        k_loc = self._csr_k_pad // tp            # gathered fd column count
        sbt = shard_block_tiles(self.g, dp, n_pad, block_b, tile_t)
        slots = sbt.src_local.size               # dp * n_tiles * T
        e = max(self.g.num_directed_edges, 1)
        fd_bytes = sbt.n_tiles * tile_t * k_loc * 4              # per shard
        pad_ok = layout_economical(slots, e, dp * sbt.n_blocks, tile_t)
        if self._csr_fused:
            # fused superstep (ISSUE 13): the gather is in-kernel, so
            # there is no fd budget and no grouped layout — the flat
            # layout's padding economy is the only constraint
            if pad_ok:
                self._probe_tiles = sbt
                self._csr_nb = None
                return True
            if cfg.use_pallas_csr is True:
                raise ValueError(
                    f"use_pallas_csr=True but sharded layout "
                    f"uneconomical: {slots - e} padded edge slots on {e} "
                    "(power-law skew? try balance=True or the ring "
                    "trainer)"
                )
            self._csr_reason = (
                f"sharded layout uneconomical: {slots - e} padded edge "
                f"slots on {e} edges"
            )
            return False
        if pad_ok and not self._csr_kc and fd_bytes <= FLAT_FD_BUDGET:
            # reuse the probe's layout in _build_csr_step unless balancing
            # relabels the graph in between (the only thing that changes it)
            # (K-blocked mode never takes the flat layout: the kblocked
            # pass is defined on grouped tiles, whose per-group fd is what
            # keeps the kc-column gathers bounded)
            self._probe_tiles = sbt
            self._csr_nb = None
            return True
        if pad_ok and self._grouped_economy_ok(dp, sbt):
            return True
        if cfg.use_pallas_csr is True:
            grouped_why = getattr(self, "_csr_reason", "")
            raise ValueError(
                f"use_pallas_csr=True but sharded layout uneconomical: "
                f"{slots - e} padded edge slots on {e}, per-shard fd "
                f"gather {fd_bytes >> 20} MiB (power-law skew? try "
                "balance=True, the ring trainer, or a sharded K axis)"
                + (f"; {grouped_why}" if grouped_why else "")
            )
        if not pad_ok:
            # otherwise _grouped_economy_ok already recorded the grouped
            # attempt's specific reason — keep it
            self._csr_reason = (
                f"sharded layout uneconomical: {slots - e} padded edge "
                f"slots on {e} edges, per-shard fd gather "
                f"{fd_bytes >> 20} MiB"
            )
        return False

    def _grouped_economy_ok(self, dp: int, sbt) -> bool:
        """Try the grouped (large-K) sharded layout: block-group windows
        scanned with per-group fd gathers bounded by GROUP_FD_BUDGET.
        Mirrors the single-chip grouping policy (models.bigclam). Under a
        sharded K axis the gathered fd holds K_loc columns, so the budgets
        scale with K/tp (the grouped-TP step then runs the partial-dot +
        psum-over-"k" kernel split per group)."""
        from bigclam_tpu.ops.csr_tiles import (
            layout_economical,
            shard_grouped_tiles,
        )

        block_b, tile_t = self._csr_shape
        # fd columns materialized per scan step: kc when K-blocked (the
        # kblocked pass gathers one K block at a time), else K_loc
        k_pad = self._csr_kc or (self._csr_k_pad // self.mesh.shape[K_AXIS])
        e = max(self.g.num_directed_edges, 1)
        tiles_per_group = max(GROUP_FD_BUDGET // (tile_t * k_pad * 4), 1)
        avg_tiles = max(sbt.n_tiles / sbt.n_blocks, 1e-9)
        # cap at the per-shard block count: a window larger than the shard
        # only inflates n_pad with phantom groups
        nb = min(max(int(tiles_per_group / avg_tiles), 1), sbt.n_blocks)

        def build(nb_):
            n_pad_g = _round_up(
                max(self.g.num_nodes, dp), dp * nb_ * block_b
            )
            return shard_grouped_tiles(
                self.g, dp, n_pad_g, block_b, tile_t, nb_
            )

        sgt = build(nb)
        while (
            nb > 1
            and sgt.src_local.shape[2] * tile_t * k_pad * 4
            > 2 * GROUP_FD_BUDGET
        ):
            nb = max(nb // 2, 1)
            sgt = build(nb)
        group_fd = sgt.src_local.shape[2] * tile_t * k_pad * 4
        ok = (
            layout_economical(
                sgt.slots, e, dp * sgt.n_groups * sgt.nb, tile_t
            )
            # even at nb=1 a single hub block can exceed the budget: that
            # gather would OOM at runtime, so refuse here
            and group_fd <= 4 * GROUP_FD_BUDGET
        )
        if not ok:
            self._csr_reason = (
                f"grouped sharded layout uneconomical: {sgt.slots - e} "
                f"padded slots on {e} edges (nb={nb}, group fd "
                f"{group_fd >> 20} MiB)"
            )
            return False
        self._probe_tiles = sgt
        self._csr_nb = nb
        return True

    def _build_csr_step(self, dp: int) -> None:
        """Build shard tiles + the CSR train step (engagement already
        decided by _csr_static_ok + _csr_economy_ok)."""
        from bigclam_tpu.obs import trace as _trace

        def nspec(ndim: int) -> NamedSharding:
            return NamedSharding(
                self.mesh, P(NODES_AXIS, *([None] * (ndim - 1)))
            )

        # span (obs.trace): tile builds are a real model-build cost at pod
        # shard counts; `source` lets the perf ledger attribute build-time
        # deltas to the host-global vs store-native builder (ISSUE 9)
        with _trace.span(
            "sharded/tile_build", dp=dp, source="host_global"
        ) as _sp:
            self.__build_csr_tiles(dp, nspec, _sp)
        self._step = make_sharded_csr_train_step(
            self.mesh, self._tiles_dev, self.cfg
        )

    def __build_csr_tiles(self, dp: int, nspec, _sp) -> None:
        from bigclam_tpu.ops.csr_tiles import (
            shard_block_tiles,
            shard_grouped_tiles,
        )

        sbt = getattr(self, "_probe_tiles", None)
        self._probe_tiles = None
        if self._csr_nb is not None:
            if sbt is None or self._perm is not None:
                sbt = shard_grouped_tiles(
                    self.g, dp, self.n_pad, *self._csr_shape, self._csr_nb
                )
            dp_, ng, gmax, t = sbt.src_local.shape
            tiles = {
                "src_local": put_sharded(
                    sbt.src_local.reshape(dp_, ng, gmax, 1, t).astype(
                        np.int32
                    ),
                    nspec(5),
                ),
                "dst": put_sharded(sbt.dst.astype(np.int32), nspec(4)),
                "mask": put_sharded(
                    sbt.mask.reshape(dp_, ng, gmax, 1, t).astype(self.dtype),
                    nspec(5),
                ),
                "block_id": put_sharded(
                    sbt.block_id.astype(np.int32), nspec(3)
                ),
                "block_b": sbt.block_b,
                "tile_t": sbt.tile_t,
                "nb": sbt.nb,
                "n_groups": sbt.n_groups,
                "kc": self._csr_kc,
            }
        else:
            if sbt is None or self._perm is not None:
                sbt = shard_block_tiles(
                    self.g, dp, self.n_pad, *self._csr_shape
                )
            dp_, nt, t = sbt.src_local.shape
            tiles = {
                "src_local": put_sharded(
                    sbt.src_local.reshape(dp_, nt, 1, t).astype(np.int32),
                    nspec(4),
                ),
                "dst": put_sharded(sbt.dst.astype(np.int32), nspec(3)),
                "mask": put_sharded(
                    sbt.mask.reshape(dp_, nt, 1, t).astype(self.dtype),
                    nspec(4),
                ),
                "block_id": put_sharded(
                    sbt.block_id.astype(np.int32), nspec(2)
                ),
                "block_b": sbt.block_b,
                "tile_t": sbt.tile_t,
                "n_blocks": sbt.n_blocks,
            }
            if getattr(self, "_csr_fused", False):
                _fused_tile_extras(
                    tiles, sbt.block_id, self._csr_kc,
                    self.mesh.shape[K_AXIS],
                    lambda a: put_sharded(a, nspec(3)),
                )
        _sp.set(slots=int(sbt.src_local.size), grouped=self._csr_nb is not None)
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = tile_pad_stats(sbt.mask)
        self.edges = None                        # not used by the CSR step
        self._tiles_dev = tiles                  # kept for rebuild_step

    def _build_edges_and_step(self) -> None:
        dp = self.mesh.shape[NODES_AXIS]
        tp = self.mesh.shape[K_AXIS]
        if self._csr_wanted:
            self._build_csr_step(dp)
            return
        bound = edge_chunk_bound(
            self.cfg, max(self.k_pad // tp, 1), self.dtype
        )
        edges_host = shard_edges(
            self.g, self.cfg, dp, self.n_pad, np.float32, chunk_bound=bound
        )
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = tile_pad_stats(edges_host.mask)
        espec = NamedSharding(self.mesh, P(NODES_AXIS, None, None))
        self.edges = EdgeChunks(
            src=put_sharded(edges_host.src, espec),
            dst=put_sharded(edges_host.dst, espec),
            mask=put_sharded(edges_host.mask.astype(self.dtype), espec),
        )
        self._step = make_sharded_train_step(self.mesh, self.edges, self.cfg)

    def rebuild_step(self) -> None:
        """Swap in the train step for the CURRENT self.cfg, reusing the
        device tile/edge buffers (see models.bigclam.BigClamModel
        .rebuild_step — same contract and step cache, used by quality
        mode's max_p relaxation; the engaged schedule/kernels never
        change)."""
        from bigclam_tpu.models.bigclam import step_cfg_key

        key = step_cfg_key(self.cfg)
        cache = self._step_cache
        if key not in cache:
            if self._csr_wanted:
                cache[key] = make_sharded_csr_train_step(
                    self.mesh, self._tiles_dev, self.cfg
                )
            else:
                cache[key] = make_sharded_train_step(
                    self.mesh, self.edges, self.cfg
                )
            from bigclam_tpu.obs import note_step_build

            note_step_build(self.cfg, type(self).__name__)
        self._step = cache[key]

    def init_state(self, F0: Optional[np.ndarray] = None) -> TrainState:
        n, k = self.g.num_nodes, self.cfg.num_communities
        if F0 is None:
            # row-keyed counter init (ISSUE 15 satellite): the HOST-
            # GLOBAL materialization of the same bits the store-backed
            # trainers generate per host — the bit-identity baseline
            F0 = rowkeyed_init_F(self.g, self.cfg)
        assert F0.shape == (n, k), (F0.shape, (n, k))
        F_host = np.zeros((self.n_pad, self.k_pad), dtype=np.float64)
        F_host[:n, :k] = self._to_internal_rows(F0)
        F = put_sharded(F_host.astype(self.dtype), self._fspec())
        return self.reset_state(F)

    def reset_state(self, F: jax.Array) -> TrainState:
        """TrainState from an already-sharded PADDED F (init_state minus the
        host upload; same contract as BigClamModel.reset_state)."""
        return TrainState(
            F=F,
            sumF=F.sum(axis=0),
            llh=jnp.asarray(-jnp.inf, F.dtype),
            it=jnp.zeros((), jnp.int32),
            accept_hist=jnp.zeros(
                len(self.cfg.step_candidates) + 1, jnp.int32
            ),
            health=dx.init_health(self.cfg),
        )

    def extract_F(self, state: TrainState) -> np.ndarray:
        """All-gather + fetch the live (num_nodes, K) F block in ORIGINAL
        node ids (inverts the balance relabeling)."""
        n, k = self.g.num_nodes, self.cfg.num_communities
        return self._from_internal_rows(fetch_global(state.F)[:n])[:, :k]

    def health_sig(self, state: TrainState) -> jax.Array:
        """(N_pad,) int32 top-community signature on the sharded F (the
        argmax runs under jit on the global array — no gather; see
        models.bigclam.BigClamModel.health_sig)."""
        from bigclam_tpu.ops.diagnostics import dense_top_community

        return dense_top_community(state.F)

    def internal_row_to_node(self) -> Optional[np.ndarray]:
        """Device row index -> ORIGINAL node index, or None when rows were
        never relabeled. For ops.extraction.extract_communities_device
        callers holding the original graph (with the trainer's own
        `model.g`, raw ids already agree and this is unnecessary)."""
        if self._perm is None:
            return None
        inv = np.empty_like(self._perm)
        inv[self._perm] = np.arange(self._perm.size)
        return inv

    def _ckpt_meta(self) -> dict:
        return {
            "num_nodes": self.g.num_nodes,
            "num_directed_edges": self.g.num_directed_edges,
            "k": self.cfg.num_communities,
            "n_pad": self.n_pad,
            "k_pad": self.k_pad,
            # checkpointed F is stored in the trainer's internal row order,
            # which depends on the balance setting AND (when balanced) on the
            # node-shard count: a run with either different must not restore
            "balanced": self._perm is not None,
            "node_shards": (
                self._node_shards() if self._perm is not None else 0
            ),
            # rng lineage for --resume auto (see BigClamModel._ckpt_meta)
            "seed": self.cfg.seed,
        }

    def _state_to_arrays(self, state: TrainState) -> dict:
        return {
            "F": fetch_global(state.F),
            "sumF": fetch_global(state.sumF),
            "llh": np.asarray(state.llh),
            "it": np.asarray(state.it),
        }

    def _state_from_arrays(self, arrays: dict) -> TrainState:
        F = put_sharded(np.asarray(arrays["F"], self.dtype), self._fspec())
        return TrainState(
            F=F,
            sumF=F.sum(axis=0),
            llh=jnp.asarray(arrays["llh"], self.dtype),
            it=jnp.asarray(arrays["it"], jnp.int32),
            accept_hist=jnp.zeros(
                len(self.cfg.step_candidates) + 1, jnp.int32
            ),
            health=dx.init_health(self.cfg),
        )

    def fit(
        self,
        F0: np.ndarray,
        callback: Optional[Callable[[int, float], None]] = None,
        checkpoints=None,
        resume: bool = True,
    ) -> FitResult:
        """Train to convergence (shared loop: models.bigclam.run_fit_loop);
        resumes from `checkpoints` when it holds a saved state (resume=
        False forces a cold start that still saves)."""
        state, hist = self.init_state(F0), ()
        if checkpoints is not None and resume:
            restored, hist = restore_checkpoint(
                checkpoints, self._ckpt_meta(), self._state_from_arrays
            )
            if restored is not None:
                state = restored
        from bigclam_tpu.models.bigclam import _ScaleRebuilder

        rebuilder = _ScaleRebuilder(self)
        try:
            return run_fit_loop(
                self._step,
                state,
                self.cfg,
                callback,
                self.extract_F,
                checkpoints=checkpoints,
                state_to_arrays=self._state_to_arrays,
                initial_hist=hist,
                ckpt_meta=self._ckpt_meta(),
                rebuild_step=rebuilder,
                health_sig=self.health_sig,
                health_n=self.g.num_nodes,
            )
        finally:
            rebuilder.restore()

    def fit_state(
        self,
        state: TrainState,
        callback: Optional[Callable[[int, float], None]] = None,
    ):
        """State-resident convergence loop (same contract as
        models.bigclam.BigClamModel.fit_state): no all-gather of F to the
        host; only per-iteration LLH scalars cross the boundary."""
        from bigclam_tpu.models.bigclam import _ScaleRebuilder

        rebuilder = _ScaleRebuilder(self)
        try:
            return run_fit_loop(
                self._step, state, self.cfg, callback, None,
                rebuild_step=rebuilder,
                health_sig=self.health_sig,
                health_n=self.g.num_nodes,
            )
        finally:
            rebuilder.restore()


class _StoreGraphView:
    """Graph-shaped scalar metadata for the store-backed trainer: just the
    sizes the training loop needs. Global CSR arrays deliberately do not
    exist — touching .src/.dst/.indptr here is the bug this class exists
    to turn into a loud error."""

    def __init__(self, store):
        self.num_nodes = store.num_nodes
        self.num_directed_edges = store.num_directed_edges
        self.num_edges = store.num_directed_edges // 2

    def __getattr__(self, name):
        raise AttributeError(
            f"store-backed trainer has no global CSR (asked for {name!r}); "
            "load the full graph with GraphStore.load_graph() if you "
            "really need it on this host"
        )


class _StoreBackedMixin:
    """Shared plumbing of the store-backed trainers (StoreSharded / ring's
    StoreRing): per-host shard loading, the mesh-vs-process-ownership
    check, the rows-per-shard <-> block alignment constraint, and the
    cross-host tile-pad agreement. The global CSR never exists on any
    host; every builder consumes HostShard local rows."""

    def _store_init(self, store, mesh: Mesh, verify: bool) -> None:
        dp = mesh.shape[NODES_AXIS]
        if store.num_shards != dp:
            raise ValueError(
                f"cache has {store.num_shards} shards but the mesh has "
                f"dp={dp} node shards; recompile with --shards {dp}"
            )
        self.store = store
        self._shard_verify = verify
        self.host_shard = None

    def _load_host_shard(self):
        """Load this process's shard slice ONCE (the CSR economy probe and
        the step builder both need it), after checking the mesh places
        this process's rows where process-major shard ownership says."""
        if self.host_shard is None:
            dp = self._node_shards()
            lo_s, hi_s = addressable_row_bounds(self._espec(), (dp, 1, 1))
            ids = host_shard_ids(dp)
            if (ids.start, ids.stop) != (lo_s, hi_s):
                raise ValueError(
                    f"mesh places this process's node shards at [{lo_s}, "
                    f"{hi_s}) but process-major shard ownership is "
                    f"[{ids.start}, {ids.stop}); use a slice-major mesh "
                    "(make_multihost_mesh)"
                )
            self.host_shard = load_host_shard(
                self.store, verify=self._shard_verify
            )
        return self.host_shard

    def init_state(self, F0: Optional[np.ndarray] = None) -> TrainState:
        """PER-HOST init (ISSUE 15 satellite — the last global-memory
        site of ROADMAP item 1a): with F0=None each host seeds ONLY its
        own row range from the row-keyed counter RNG
        (models.bigclam.rowkeyed_init_rows — entry (r, c) is a pure
        function of (seed, r, c)) and places it process-locally, so no
        host ever materializes the O(N*K) F0 array. Bit-identical to
        the host-global `init_state(None)` of the in-memory trainers at
        matching seeds (pinned by tests/test_delta.py). An explicit F0
        keeps the host-global upload path (conductance seeding)."""
        if F0 is not None:
            return super().init_state(F0)
        n, k = self.g.num_nodes, self.cfg.num_communities
        fspec = self._fspec()
        lo, hi = addressable_row_bounds(
            fspec, (self.n_pad, self.k_pad)
        )
        local = np.zeros((hi - lo, self.k_pad), dtype=np.float64)
        live_hi = min(hi, n)
        if live_hi > lo:
            local[: live_hi - lo, :k] = rowkeyed_init_rows(
                lo, live_hi, k, self.cfg.seed
            )
        F = jax.make_array_from_process_local_data(
            fspec, np.ascontiguousarray(local.astype(self.dtype)),
            (self.n_pad, self.k_pad),
        )
        return self.reset_state(F)

    def _store_rows_ok(self) -> bool:
        """The store-native CSR layouts keep trainer shard rows == the
        cache's rows_per_shard (a larger block-rounded shard would pull
        rows another host's files own — the exact isolation breach the
        store exists to prevent), so block_b must divide rows_per_shard.
        Raises when use_pallas_csr=True; records the fallback reason and
        returns False otherwise."""
        block_b = self._csr_shape[0]
        rows = self.store.rows_per_shard
        if rows % block_b == 0:
            return True
        msg = (
            f"cache rows_per_shard={rows} is not a multiple of "
            f"csr_block_b={block_b}: store-native tiles cannot cross "
            "shard-file boundaries (re-ingest with block-aligned shards "
            "or set csr_block_b to a divisor)"
        )
        if self.cfg.use_pallas_csr is True:
            raise ValueError(f"use_pallas_csr=True but {msg}")
        self._csr_reason = msg
        return False

    def _shard_edge_counts(self) -> np.ndarray:
        """Per-shard directed-edge counts from the store MANIFEST — the
        balance telemetry never needs a global CSR (the whole point of
        the store path): every host already agreed on these numbers at
        cache open."""
        return np.asarray(
            [int(e["edges"]) for e in self.store.manifest["shards"]],
            dtype=np.int64,
        )

    def _store_pad_tiles_for(self, local_max: int) -> int:
        """The uniform cross-host tile-count pad: cfg.csr_store_pad_tiles
        when set (deterministic shapes across restarts), else a one-int
        max exchange over the process group (multihost.global_max_int)."""
        from bigclam_tpu.parallel.multihost import global_max_int

        explicit = self.cfg.csr_store_pad_tiles
        if explicit:
            if explicit < local_max:
                raise ValueError(
                    f"csr_store_pad_tiles={explicit} below this host's "
                    f"tile count {local_max}; raise it (or 0 for the "
                    "automatic cross-host max)"
                )
            return explicit
        return global_max_int(local_max)


class StoreShardedBigClamModel(_StoreBackedMixin, ShardedBigClamModel):
    """Sharded trainer fed per-host from a compiled graph cache.

    Each process loads ONLY its own shard blobs
    (multihost.load_host_shard), builds only its rows of the edge blocks
    (shard_edges_local) or blocked-CSR tiles
    (ops.csr_tiles.local_block_tile_parts), and places them with
    put_host_local — the global CSR is never materialized on any host,
    which is the whole point of the store at Friendster scale. The math is
    byte-identical to ShardedBigClamModel on the same graph (same edge
    blocks / tiles, same step).

    Since ISSUE 9 the blocked-CSR MXU kernels engage here exactly like the
    in-memory trainer (same csr_tiles_supported / auto-shrink policy, same
    economy probe on manifest-global counts + local tiles) on the FLAT
    layout; the grouped/K-blocked large-K layouts still fall back to XLA
    with a recorded reason. Balance is baked at INGEST time (`cli ingest
    --balance`), not at model build: the cache's node order IS the
    trainer's row order, so results come back in cache order (map to
    original ids via the cache's raw_ids).
    """

    def __init__(self, store, cfg: BigClamConfig, mesh: Mesh, dtype=None,
                 verify: bool = True):
        self._store_init(store, mesh, verify)
        super().__init__(
            _StoreGraphView(store), cfg, mesh, dtype=dtype, balance=False,
        )

    def _csr_static_ok(self, tp: int) -> bool:
        if not super()._csr_static_ok(tp):
            return False
        if self._csr_kc and not self._csr_fused:
            # the SPLIT sharded K-blocked pass runs on GROUPED tiles,
            # which the store-native builder does not produce; the FUSED
            # K-blocked pass (ops.pallas_fused) runs on the flat tiles
            # the store builders already make — large-K store-native
            # runs engage it instead of falling back (ISSUE 13)
            msg = (
                f"K_loc={self._csr_k_pad // tp} needs the K-blocked "
                "grouped layout, which is not store-native on the split "
                "kernel path (csr_fused=False); drop the override — the "
                "fused K-blocked pass runs on store tiles"
            )
            if self.cfg.use_pallas_csr is True:
                raise ValueError(f"use_pallas_csr=True but {msg}")
            self._csr_reason = msg
            return False
        return self._store_rows_ok()

    def _csr_economy_ok(self, dp: int) -> bool:
        """Store-native twin of the base economy probe: the slot/padding
        and fd-gather numbers are identical by construction (manifest
        edge counts + a cross-host max of the local tile counts), so the
        engage/fallback decision matches the in-memory trainer on the
        same graph — only who builds the tiles changes. The grouped
        large-K fallback is not store-native yet: layouts that need it
        fall back to XLA (or refuse under use_pallas_csr=True)."""
        from bigclam_tpu.obs import trace as _trace
        from bigclam_tpu.ops.csr_tiles import (
            layout_economical,
            local_block_tile_parts,
        )

        cfg = self.cfg
        tp = self.mesh.shape[K_AXIS]
        block_b, tile_t = self._csr_shape
        shard = self._load_host_shard()
        n_pad = dp * self.store.rows_per_shard
        with _trace.span(
            "sharded/tile_build", dp=dp, source="store"
        ) as _sp:
            parts = local_block_tile_parts(
                shard, dp, n_pad, block_b, tile_t
            )
            local_max = max(p.n_tiles for p in parts)
            pad_tiles = self._store_pad_tiles_for(local_max)
            _sp.set(local_tiles=int(local_max), pad_tiles=int(pad_tiles))
        e = max(self.store.num_directed_edges, 1)
        slots = dp * pad_tiles * tile_t
        k_loc = self._csr_k_pad // tp
        n_blocks = (n_pad // dp) // block_b
        fd_bytes = pad_tiles * tile_t * k_loc * 4        # per shard
        pad_ok = layout_economical(slots, e, dp * n_blocks, tile_t)
        # the fused paths gather in-kernel: no fd budget applies, and the
        # K-blocked fused pass runs on these same flat tiles — the
        # grouped large-K store gap is closed on this branch (ISSUE 13)
        if pad_ok and (self._csr_fused or fd_bytes <= FLAT_FD_BUDGET):
            self._probe_parts = parts
            self._store_pad_tiles = pad_tiles
            self._csr_nb = None
            return True
        if cfg.use_pallas_csr is True:
            raise ValueError(
                f"use_pallas_csr=True but sharded layout uneconomical: "
                f"{slots - e} padded edge slots on {e}, per-shard fd "
                f"gather {fd_bytes >> 20} MiB (power-law skew? re-ingest "
                "with --balance, the ring trainer, or a sharded K axis; "
                "the grouped large-K layout is not store-native yet)"
            )
        self._csr_reason = (
            f"store-backed sharded layout uneconomical: {slots - e} "
            f"padded edge slots on {e} edges, per-shard fd gather "
            f"{fd_bytes >> 20} MiB (grouped large-K fallback is not "
            "store-native yet)"
        )
        return False

    def _build_csr_step(self, dp: int) -> None:
        from bigclam_tpu.obs import trace as _trace
        from bigclam_tpu.ops.csr_tiles import stack_block_tile_parts

        def nspec(ndim: int) -> NamedSharding:
            return NamedSharding(
                self.mesh, P(NODES_AXIS, *([None] * (ndim - 1)))
            )

        parts = self._probe_parts
        self._probe_parts = None
        with _trace.span(
            "sharded/tile_build", dp=dp, source="store", stage="stack"
        ) as _sp:
            sbt = stack_block_tile_parts(parts, self._store_pad_tiles)
            _sp.set(slots=int(dp * sbt.n_tiles * sbt.tile_t))
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        # THIS host's rows only (no global mask exists); scope recorded
        # so the report reads it as a per-host figure
        self._pad_stats = {
            **tile_pad_stats(sbt.mask),
            "scope": "host_local",
            "pad_tiles": int(self._store_pad_tiles),
        }
        n_local, nt, t = sbt.src_local.shape
        tiles = {
            "src_local": put_host_local(
                sbt.src_local.reshape(n_local, nt, 1, t).astype(np.int32),
                nspec(4), (dp, nt, 1, t),
            ),
            "dst": put_host_local(
                sbt.dst.astype(np.int32), nspec(3), (dp, nt, t)
            ),
            "mask": put_host_local(
                sbt.mask.reshape(n_local, nt, 1, t).astype(self.dtype),
                nspec(4), (dp, nt, 1, t),
            ),
            "block_id": put_host_local(
                sbt.block_id.astype(np.int32), nspec(2), (dp, nt)
            ),
            "block_b": sbt.block_b,
            "tile_t": sbt.tile_t,
            "n_blocks": sbt.n_blocks,
        }
        if getattr(self, "_csr_fused", False):
            _fused_tile_extras(
                tiles, sbt.block_id, self._csr_kc,
                self.mesh.shape[K_AXIS],
                lambda a: put_host_local(a, nspec(3), (dp,) + a.shape[1:]),
            )
        self.edges = None
        self._tiles_dev = tiles                  # kept for rebuild_step
        self._step = make_sharded_csr_train_step(self.mesh, tiles, self.cfg)

    def _build_edges_and_step(self) -> None:
        dp = self.mesh.shape[NODES_AXIS]
        tp = self.mesh.shape[K_AXIS]
        if self._csr_wanted:
            self._build_csr_step(dp)
            return
        espec = NamedSharding(self.mesh, P(NODES_AXIS, None, None))
        shard = self._load_host_shard()
        bound = edge_chunk_bound(
            self.cfg, max(self.k_pad // tp, 1), self.dtype
        )
        local = shard_edges_local(
            shard, self.cfg, dp, self.n_pad, np.float32,
            chunk_bound=bound,
        )
        from bigclam_tpu.ops.csr_tiles import tile_pad_stats

        self._pad_stats = {
            **tile_pad_stats(local.mask), "scope": "host_local",
        }
        gshape = (dp,) + local.src.shape[1:]
        self.edges = EdgeChunks(
            src=put_host_local(local.src, espec, gshape),
            dst=put_host_local(local.dst, espec, gshape),
            mask=put_host_local(
                local.mask.astype(self.dtype), espec, gshape
            ),
        )
        self._step = make_sharded_train_step(self.mesh, self.edges, self.cfg)
