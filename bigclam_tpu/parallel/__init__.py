from bigclam_tpu.parallel.mesh import make_mesh, make_mesh_2d
from bigclam_tpu.parallel.multihost import (
    initialize_distributed,
    load_host_seed_scores,
    load_host_shard,
    make_multihost_mesh,
    put_sharded,
)
from bigclam_tpu.parallel.ring import (
    RingBigClamModel,
    StoreRingBigClamModel,
)
from bigclam_tpu.parallel.sharded import (
    ShardedBigClamModel,
    StoreShardedBigClamModel,
)
from bigclam_tpu.parallel.sparse_sharded import SparseShardedBigClamModel
from bigclam_tpu.parallel.twod import (
    StoreTwoDShardedBigClamModel,
    TwoDShardedBigClamModel,
    twod_mesh_shape,
)

__all__ = [
    "initialize_distributed",
    "load_host_seed_scores",
    "load_host_shard",
    "make_mesh",
    "make_mesh_2d",
    "make_multihost_mesh",
    "put_sharded",
    "twod_mesh_shape",
    "RingBigClamModel",
    "ShardedBigClamModel",
    "SparseShardedBigClamModel",
    "StoreRingBigClamModel",
    "StoreShardedBigClamModel",
    "StoreTwoDShardedBigClamModel",
    "TwoDShardedBigClamModel",
]
