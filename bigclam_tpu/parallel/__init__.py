from bigclam_tpu.parallel.mesh import make_mesh
from bigclam_tpu.parallel.ring import RingBigClamModel
from bigclam_tpu.parallel.sharded import ShardedBigClamModel

__all__ = ["make_mesh", "RingBigClamModel", "ShardedBigClamModel"]
