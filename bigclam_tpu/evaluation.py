"""Evaluation against ground-truth communities: average best-match F1 and
overlapping NMI.

C22 (SURVEY.md §2): the reference shipped SNAP's com-amazon ground-truth
community file but contained no scoring code — this module is built new, to
the metrics named in BASELINE.json ("F1 vs ground-truth cmty").

F1: the symmetric average best-match F1 of Yang & Leskovec (WSDM'13 §5):
    F1(P, T) = 1/2 * ( mean_i max_j f1(p_i, t_j) + mean_j max_i f1(p_i, t_j) )

NMI: overlapping-cover NMI of Lancichinetti, Fortunato & Kertesz (NJP 2009),
per-community binary variables with the admissibility constraint
h(P11) + h(P00) >= h(P01) + h(P10) on candidate matches.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np


def f1_score_pair(a: frozenset, b: frozenset) -> float:
    inter = len(a & b)
    if inter == 0:
        return 0.0
    p = inter / len(a)
    r = inter / len(b)
    return 2 * p * r / (p + r)


def avg_f1(pred: Sequence[Iterable[int]], truth: Sequence[Iterable[int]]) -> float:
    """Symmetric average best-match F1 in [0, 1]."""
    P = [frozenset(c) for c in pred if len(c)]
    T = [frozenset(c) for c in truth if len(c)]
    if not P or not T:
        return 0.0
    # inverted index: node -> truth communities containing it (best-match
    # candidates are only communities sharing >= 1 node; others give f1=0)
    node_to_t: dict[int, list[int]] = {}
    for j, t in enumerate(T):
        for u in t:
            node_to_t.setdefault(u, []).append(j)
    best_pt = np.zeros(len(P))
    best_tp = np.zeros(len(T))
    for i, p in enumerate(P):
        cands = {j for u in p for j in node_to_t.get(u, ())}
        for j in cands:
            s = f1_score_pair(p, T[j])
            if s > best_pt[i]:
                best_pt[i] = s
            if s > best_tp[j]:
                best_tp[j] = s
    return 0.5 * (best_pt.mean() + best_tp.mean())


def _h(p):
    """Entropy contribution -p*log2(p) (elementwise, 0 at p=0)."""
    p = np.asarray(p, dtype=np.float64)
    out = np.zeros_like(p)
    nz = p > 0
    out[nz] = -p[nz] * np.log2(p[nz])
    return out if out.ndim else float(out)


def _cover_matrix(cover: Sequence[Iterable[int]], nodes: dict[int, int]) -> np.ndarray:
    M = np.zeros((len(cover), len(nodes)), dtype=bool)
    for i, c in enumerate(cover):
        for u in c:
            M[i, nodes[u]] = True
    return M


def overlapping_nmi(
    pred: Sequence[Iterable[int]], truth: Sequence[Iterable[int]]
) -> float:
    """LFK overlapping NMI in [0, 1] over the union of covered nodes."""
    pred = [list(c) for c in pred if len(c)]
    truth = [list(c) for c in truth if len(c)]
    if not pred or not truth:
        return 0.0
    nodes = {u: i for i, u in enumerate(sorted({u for c in pred + truth for u in c}))}
    n = len(nodes)
    X = _cover_matrix(pred, nodes)
    Y = _cover_matrix(truth, nodes)

    def cond_norm(A: np.ndarray, B: np.ndarray) -> float:
        """mean_i min_j H(a_i | b_j) / H(a_i), with the LFK admissibility rule."""
        pb1 = B.mean(axis=1)                      # loop-invariant: H(b_j)
        hB = _h(pb1) + _h(1 - pb1)
        ratios = []
        # joint counts via boolean algebra, vectorized over j for each i
        for i in range(A.shape[0]):
            a = A[i]
            pa1 = a.mean()
            ha = float(_h(pa1) + _h(1 - pa1))
            if ha == 0.0:
                ratios.append(1.0)  # degenerate (empty/full) community carries
                continue            # no information about the other cover
            d = (B & a).sum(axis=1) / n          # P(a=1, b=1)
            c = (~B & a).sum(axis=1) / n         # P(a=1, b=0)
            b_ = (B & ~a).sum(axis=1) / n        # P(a=0, b=1)
            e = (~B & ~a).sum(axis=1) / n        # P(a=0, b=0)
            hd, hc, hb, he = _h(d), _h(c), _h(b_), _h(e)
            admissible = (hd + he) >= (hc + hb)
            h_cond = (hd + hc + hb + he) - hB     # H(a,b) - H(b)
            h_cond = np.where(admissible, h_cond, ha)
            ratios.append(float(np.min(h_cond)) / ha)
        return float(np.mean(ratios))

    return 1.0 - 0.5 * (cond_norm(X, Y) + cond_norm(Y, X))
