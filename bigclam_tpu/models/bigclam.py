"""The BigCLAM model on device: state, train step, fit loop.

Replaces L3/L4/L6 of the reference (SURVEY.md §1): model state F lives as a
single (N, K) device array (the reference kept it as an RDD of per-node rows,
re-broadcast in full to every executor each iteration — Bigclamv2.scala:96,118,
the O(N*K) scalability ceiling, Q9). One outer iteration here is:

    grad/LLH pass  ->  16-candidate Armijo pass  ->  masked Jacobi update

all inside a single jitted function; the host loop only reads back one scalar
LLH per iteration for the convergence test (|1 - LLH_new/LLH_old| < tol,
Bigclamv2.scala:214). The LLH each step reports is the LLH of its *input* F,
which equals the post-update LLH of the previous step — the reference's
pass-3 LLH (Bigclamv2.scala:158-181) substitutes updated rows for both edge
endpoints and so is exactly the post-update LLH; we get it for free from the
next step's fused pass instead of paying an 18th edge sweep.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.ops.linesearch import armijo_update, candidates_pass
from bigclam_tpu.ops.objective import EdgeChunks, grad_llh
from bigclam_tpu.utils.dist import is_primary


def csr_want_reason(cfg: BigClamConfig) -> tuple[bool, str]:
    """Shared 'should the CSR kernels engage?' predicate + the fallback
    reason when they should not (single source for every trainer)."""
    want = cfg.use_pallas_csr
    if want is None:
        want = jax.default_backend() == "tpu" or cfg.pallas_interpret
    if want:
        return True, ""
    reason = (
        "use_pallas_csr=False"
        if cfg.use_pallas_csr is False
        else f"auto: backend {jax.default_backend()!r} is not tpu"
    )
    return False, reason


def csr_fused_want(cfg: BigClamConfig) -> bool:
    """Fused edge superstep engagement (ISSUE 13): auto = ON whenever the
    blocked-CSR kernels engage (ops.pallas_fused is the default schedule
    since r17; csr_fused=False keeps the pre-r17 split kernels — the A/B
    and perf-baseline path). Shared by every trainer family so the
    resolved kernel path can never differ between them for one config."""
    return cfg.csr_fused is not False


# Fields that only the HOST-side loops read (never baked into the compiled
# step): normalized away by step_cfg_key so rebuild_step can cache compiled
# steps across host-only cfg swaps (quality mode toggles conv_tol + max_p
# around every annealing schedule — without the cache that is two fresh
# compiles per fit_quality call, per K in a sweep).
_HOST_ONLY_FIELDS = dict(
    conv_tol=0.0, max_iters=0, donate_state=False,
    min_com=1, max_com=1, div_com=1, ksweep_tol=0.0,
    seed=0, seed_include_self=True, isolated_phi_sentinel=0.0,
    seeding_degree_cap=None, seed_exclusion=None,
    quality_mode=False, init_noise=None, init_noise_mass=0.0,
    restart_cycles=0, restart_tol=0.0, restart_patience=0,
    quality_conv_tol=0.0, quality_max_p=None,
    checkpoint_dir=None, checkpoint_every=0, metrics_path=None,
    # resilience: rollback policy is host-loop-only; step_scale is NOT here
    # (it rescales the baked Armijo ladder — a rollback's step cut compiles
    # a new step, cached by this key)
    rollback_budget=0, rollback_shrink=0.0, rollback_snapshot_every=0,
    # store-native tile pad: changes data shapes (jit arguments), not
    # step-baked constants — retraces ride the shape key, not this one
    csr_store_pad_tiles=0,
)


def step_cfg_key(cfg: BigClamConfig) -> BigClamConfig:
    """Step-baked identity of a config (hashable — the frozen dataclass):
    two configs with equal keys compile byte-identical train steps."""
    return cfg.replace(**_HOST_ONLY_FIELDS)


def attach_donating(step_fn, step, fixed_args=()):
    """Attach `step_fn.donating(scratch, state)`: the same step compiled
    with a DONATED ping-pong scratch state prepended.

    `scratch` must be a shape/dtype/sharding twin of `state` (in practice:
    a previous TrainState the caller guarantees dead). Its buffers are
    donated to XLA and reused for the outputs — the new F lands in the old
    F's storage instead of a fresh allocation, so a step holds ONE live F
    copy plus the output instead of two plus the output. The scratch is
    data-dead (never read; keep_unused=True keeps it in the signature so
    the aliasing survives jit's unused-argument pruning), and the caller
    must not touch it afterwards: on backends that honor donation its
    buffers are DELETED.

    run_fit_loop drives this entry (cfg.donate_state) with the state it
    dropped one iteration ago — the ping-pong that keeps the convergence
    protocol's "return the PREVIOUS state" semantics exact (the current
    input is never donated). `fixed_args` ride along un-donated (edge/tile
    device arrays, matching step_fn.jit_args).

    Compiled lazily on first use: callers that never donate (bench loops,
    parity tests stepping two models in lockstep) pay nothing.
    """

    def _donating_step(scratch, state, *a):
        del scratch                     # storage-only: aliased to outputs
        return step(state, *a)

    jitted_d = jax.jit(
        _donating_step, donate_argnums=(0,), keep_unused=True
    )

    def donating(scratch, state):
        return jitted_d(scratch, state, *fixed_args)

    step_fn.donating = donating
    step_fn.jitted_donating = jitted_d
    return step_fn


def finalize_step(step):
    """jit `step` and wrap it in a plain closure carrying the AOT handle
    (`.jitted`) and the donating entry (attach_donating) — jit's compiled
    callable cannot hold attributes itself."""
    jitted = jax.jit(step)

    def step_fn(state):
        return jitted(state)

    step_fn.jitted = jitted
    step_fn.jit_args = ()
    return attach_donating(step_fn, step)


def donation_scratch(state):
    """A donate-able twin of `state`: same shapes/dtypes/shardings, values
    irrelevant (jnp.copy is elementwise identity, so sharding propagation
    preserves the layout on every backend). Used by run_fit_loop for the
    first calls of a fit, before a dropped previous state exists."""
    return jax.tree.map(jnp.copy, state)


def _snapshot_ping_copy(dead, state):
    """Device-side copy of `state` written into the DONATED buffers of the
    previous snapshot (`dead`) — the rollback snapshot's in-HBM ping-pong:
    one extra state-sized buffer stays resident, refreshed with a pure
    device copy, never a host round trip. Module-level jit so repeated
    fits at the same shapes hit the cache (the compile-flatness pin in
    tests/test_telemetry.py counts every backend compile)."""
    del dead                        # storage-only: aliased to the outputs
    return jax.tree.map(jnp.copy, state)


_SNAPSHOT_PING = jax.jit(
    _snapshot_ping_copy, donate_argnums=(0,), keep_unused=True
)


class _ScaleRebuilder:
    """run_fit_loop's step-cut hook (non-finite rollback): rebuilds the
    model's train step with the Armijo ladder scaled by cfg.step_scale.
    Works for every trainer exposing .cfg / .rebuild_step() / ._step
    (BigClamModel, the sharded/ring trainers — the same surface quality
    mode's max_p relaxation drives). `restore()` puts the model back on
    its original config after the fit, so a shrunken ladder never leaks
    into the caller's next fit; compiled steps stay cached either way."""

    def __init__(self, model):
        self.model = model
        self.orig_cfg = model.cfg
        self.engaged = False

    def __call__(self, scale: float):
        self.engaged = True
        m = self.model
        m.cfg = m.cfg.replace(step_scale=scale)
        m.rebuild_step()
        return m._step

    def restore(self) -> None:
        if not self.engaged:
            return
        m = self.model
        m.cfg = self.orig_cfg
        m.rebuild_step()


def log_engaged_path(model_name: str, path: str, reason: str = "") -> None:
    """One-line kernel-path engagement report at model build.

    Silent fallbacks hid perf regressions in round-1 production runs (the
    7.66M-vs-27.4M bench capture artifact); every trainer now states which
    edge-sweep implementation it compiled, and why the CSR kernels did not
    engage when they did not. Set BIGCLAM_QUIET=1 to suppress the stderr
    line; the telemetry event (and its post-placement device-memory
    watermark) is emitted regardless — the event log stays complete under
    --quiet."""
    import os
    import sys

    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is not None:
        tel.event("model_build", model=model_name, path=path, reason=reason)
        tel.watermark(f"model_build:{model_name}")
    if os.environ.get("BIGCLAM_QUIET") == "1":
        return
    why = (
        f" ({reason})"
        if reason
        and path
        not in (
            "csr", "csr_grouped", "csr_grouped_kb", "csr_ring",
            "csr_ring_kb",
        )
        else ""
    )
    print(
        f"[bigclam] {model_name}: edge-sweep path = {path}{why}",
        file=sys.stderr,
    )


class MemoryAccountedModel:
    """Shared memory-accounting surface (obs.memory, ISSUE 12): every
    trainer family bakes a static per-device HBM model + per-host RSS
    model at step build (`_bake_memory_model`, mirroring the comms-model
    pattern) and can reconcile it against the LIVE addressable shard
    bytes of a state (`memory_reconcile` — exact on the CPU fake, the
    MEM gate's headline check; drift past the band fires the
    `memory_drift` anomaly, the leak/retained-buffer detector).

    Subclasses provide `_graph_device_arrays()` (the committed edge/
    tile/support device arrays the compiled step keeps resident) and
    `_build_memory_model()`; the host model, measurement, and emission
    are shared here."""

    memory = None                # the baked obs.memory.MemoryModel

    def _bake_memory_model(self) -> None:
        from bigclam_tpu.obs import memory as _mem

        self.memory = self._build_memory_model()
        _mem.emit_model(self.memory, self._host_memory_model())

    def _graph_device_arrays(self) -> dict:
        raise NotImplementedError

    def _build_memory_model(self):
        raise NotImplementedError

    def _memory_dp(self) -> int:
        mesh = getattr(self, "mesh", None)
        if mesh is None:
            return 1
        from bigclam_tpu.parallel.mesh import NODES_AXIS

        return mesh.shape[NODES_AXIS]

    def _graph_buffer_bytes(self) -> dict:
        """Per-device bytes of the committed graph buffers: the arrays
        are P(nodes)-sharded (or single-device), so per-device = global
        / dp — the same division measured_device_bytes recovers from
        the live shards."""
        from bigclam_tpu.obs import memory as _mem

        dp = self._memory_dp()
        return {
            name: _mem.nbytes_of(a) / dp
            for name, a in self._graph_device_arrays().items()
        }

    def _host_memory_model(self):
        from bigclam_tpu.obs import memory as _mem

        g, cfg = self.g, self.cfg
        store = getattr(self, "store", None)
        processes = 1
        if getattr(self, "mesh", None) is not None:
            processes = jax.process_count()
        return _mem.host_rss_model(
            g.num_nodes,
            g.num_directed_edges,
            cfg.num_communities,
            jnp.dtype(self.dtype).itemsize,
            n_pad=self.n_pad,
            k_pad=self.k_pad,
            store_native=store is not None,
            processes=processes,
            num_shards=(
                store.num_shards if store is not None else self._memory_dp()
            ),
            representation=cfg.representation,
            sparse_m=getattr(self, "m", 0),
        )

    def _memory_state_arrays(self, state) -> list:
        return [
            state.F, state.sumF, state.llh, state.it, state.accept_hist,
            getattr(state, "health", None),
        ]

    def memory_measured(self, state, extra=()) -> float:
        """Exact per-device bytes of the LIVE addressable buffers this
        model's step keeps resident: state arrays + committed graph
        arrays (+ `extra` — the gate's planted-leak hook: pass retained
        buffers the model does not know about)."""
        from bigclam_tpu.obs import memory as _mem

        arrays = (
            self._memory_state_arrays(state)
            + list(self._graph_device_arrays().values())
            + list(extra)
        )
        return _mem.measured_device_bytes(arrays)

    def memory_reconcile(self, state, extra=(), emit=True) -> dict:
        """Static model vs live bytes (obs.memory.MemoryModel.reconcile);
        emits the `memory_drift` anomaly when the drift exceeds the band
        (a retained/leaked buffer — or stale model arithmetic)."""
        from bigclam_tpu.obs import memory as _mem

        recon = self.memory.reconcile(self.memory_measured(state, extra))
        if emit and not recon["ok"]:
            _mem.emit_drift_anomaly(recon)
        return recon


class TrainState(NamedTuple):
    F: jax.Array        # (N_pad, K_pad)
    sumF: jax.Array     # (K_pad,)
    llh: jax.Array      # scalar: LLH of the PREVIOUS F (see module docstring)
    it: jax.Array       # iteration counter
    # (S+1,) int32 accepted-step histogram of the update that PRODUCED this
    # state (ops.linesearch.accept_stats); zeros at init. SURVEY §5 names
    # line-search health an observability requirement — without it a fit
    # whose Armijo ladder collapses to 1e-15 steps is indistinguishable
    # from a healthy one in the metrics.
    accept_hist: Optional[jax.Array] = None
    # (ops.diagnostics.HEALTH_LEN,) float32 device health pack of the
    # update that PRODUCED this state, computed inside the jitted step at
    # the cfg.health_every cadence (ISSUE 8); None with health off — the
    # pre-health pytree, bit-identical trajectory.
    health: Optional[jax.Array] = None
    # Capped-exchange counters of the update that PRODUCED this state:
    # worst exchanged id count and the dense-fallback flag (int32
    # scalars). Carried by the sparse representation's sumF allreduce
    # and the 2D closure grad exchange (ISSUE 17); None on every other
    # step — the pre-counter pytree. Present from reset_state on when a
    # trainer engages them: donation needs the scratch state to be a
    # pytree twin of the step output from iteration one.
    comm_ids: Optional[jax.Array] = None
    comm_dense: Optional[jax.Array] = None


@dataclasses.dataclass(frozen=True)
class FitResult:
    F: np.ndarray       # (N, K) — un-padded
    sumF: np.ndarray    # (K,)
    llh: float
    num_iters: int
    llh_history: tuple


# whole-graph dst-gather budget for the flat CSR layout, and the per-group
# gather budget for the grouped (large-K) layout
FLAT_FD_BUDGET = 2 << 30
GROUP_FD_BUDGET = 512 << 20


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def random_init_F(g, cfg: BigClamConfig, seed: Optional[int] = None) -> np.ndarray:
    """Bernoulli(0.5) {0,1} init, the reference's random-row distribution
    (Bigclamv2.scala:62) — the one implementation every trainer
    (dense, sparse, sharded) delegates to so the distribution can never
    diverge between representations."""
    rng = np.random.default_rng(cfg.seed if seed is None else seed)
    return rng.integers(
        0, 2, size=(g.num_nodes, cfg.num_communities)
    ).astype(np.float64)


# row-keyed counter RNG (ISSUE 15 satellite / ROADMAP 1a): splitmix64
# finalizer constants, identical to the native sampler's PRNG
# (ops.seeding._splitmix64 / graph/native bc_splitmix64)
_SM_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SM_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_M2 = np.uint64(0x94D049BB133111EB)
_ROW_MIX = np.uint64(0xA24BAED4963EE407)


def _splitmix64_vec(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over a uint64 array (wrapping
    arithmetic; same avalanche as ops.seeding._splitmix64)."""
    z = x + _SM_GAMMA
    z = (z ^ (z >> np.uint64(30))) * _SM_M1
    z = (z ^ (z >> np.uint64(27))) * _SM_M2
    return z ^ (z >> np.uint64(31))


def rowkeyed_init_rows(
    lo: int, hi: int, k: int, seed: int
) -> np.ndarray:
    """Bernoulli(0.5) {0,1} float64 rows [lo, hi) of the ROW-KEYED
    counter init: entry (r, c) is a pure function of (seed, global row
    r, column c), so any row range generates bit-identically to the
    same slice of the host-global array — the per-host init_state
    refactor ROADMAP item 1a names (a store-native host materializes
    O(N_loc * K), never O(N * K)). Same {0,1} distribution as
    random_init_F; a DIFFERENT stream (np.default_rng vs splitmix64),
    so the two inits are distinct trajectories by construction."""
    if hi <= lo:
        return np.empty((0, k), dtype=np.float64)
    base = _splitmix64_vec(np.asarray(seed, dtype=np.uint64).reshape(1))
    rows = np.arange(lo, hi, dtype=np.uint64)[:, None]
    cols = np.arange(k, dtype=np.uint64)[None, :]
    z = _splitmix64_vec((rows * _ROW_MIX + cols) ^ base)
    return ((z >> np.uint64(63)) & np.uint64(1)).astype(np.float64)


def rowkeyed_init_F(
    g, cfg: BigClamConfig, seed: Optional[int] = None
) -> np.ndarray:
    """Host-global (N, K) twin of rowkeyed_init_rows — the comparison
    baseline for the per-host store-native init (bit-identical slices
    at matching seeds, pinned by tests/test_delta.py)."""
    return rowkeyed_init_rows(
        0, g.num_nodes, cfg.num_communities,
        cfg.seed if seed is None else seed,
    )


def _lcm(a: int, b: int) -> int:
    import math

    return a * b // math.gcd(a, b)


def _rel_change(new: float, old: float) -> float:
    """|1 - new/old| with the old == 0.0 corner handled (all-zero F0 has
    LLH exactly 0.0): converged iff new is also 0."""
    if old == 0.0:
        return 0.0 if new == 0.0 else float("inf")
    return abs(1.0 - new / old)


def edge_chunk_bound(
    cfg: BigClamConfig, k_cols: Optional[int] = None, dtype=None
) -> int:
    """cfg.edge_chunk capped so one gathered (chunk, k_cols) array stays under
    ~1 GB of HBM — the candidate pass holds several such arrays live at once.
    Shared by the single-chip (prepare_graph), sharded, and ring edge preps.
    k_cols is the per-device column count of the gathered arrays (k_pad on a
    single chip, k_pad // tp under K-axis sharding); dtype their element type.
    """
    cols = k_cols if k_cols else cfg.num_communities
    per_edge_bytes = max(cols, 1) * jnp.dtype(dtype or jnp.float32).itemsize
    return min(max(cfg.edge_chunk, 1), max((1 << 30) // per_edge_bytes, 1024))


def prepare_graph(
    g: Graph,
    cfg: BigClamConfig,
    node_multiple: int = 1,
    dtype=None,
    k_pad: Optional[int] = None,
) -> tuple[EdgeChunks, int]:
    """Chunk + pad directed-edge arrays for static-shape device sweeps.

    Padding: src = n_pad - 1 (keeps src sorted for segment_sum), dst = 0,
    mask = 0. Returns (EdgeChunks, padded node count). k_pad is the padded
    community count the gathered (chunk, k_pad) arrays will actually have;
    it defaults to the unpadded K for callers that do not pad.
    """
    dtype = jnp.dtype(dtype or jnp.float32)
    n_pad = _round_up(max(g.num_nodes, 1), node_multiple)
    src, dst = g.src, g.dst
    m = src.shape[0]
    # balance chunks: pick the chunk count from the configured bound, then
    # size chunks evenly — avoids up to chunk-1 edges of padding waste in
    # the last chunk. Chunks >= 1024 align to the Pallas edge-tile size
    # (XLA lays 1-D operands out in 1024-element tiles and Mosaic blocks
    # must match); smaller chunks (tiny graphs / chunking tests) align to 8
    # and dispatch to the XLA candidate path instead.
    chunk_bound = edge_chunk_bound(cfg, k_pad, dtype)
    c = max(1, -(-m // chunk_bound))
    chunk = max(-(-m // c), 1)
    chunk = _round_up(chunk, 1024 if chunk >= 1024 else 8)
    pad = c * chunk - m
    src_p = np.pad(src, (0, pad), constant_values=n_pad - 1).reshape(c, chunk)
    dst_p = np.pad(dst, (0, pad), constant_values=0).reshape(c, chunk)
    mask_p = np.pad(np.ones(m, np.float32), (0, pad)).reshape(c, chunk)
    return (
        EdgeChunks(
            src=jnp.asarray(src_p, jnp.int32),
            dst=jnp.asarray(dst_p, jnp.int32),
            mask=jnp.asarray(mask_p, dtype),
        ),
        n_pad,
    )


def run_fit_loop(
    step_fn: Callable[[TrainState], TrainState],
    state: TrainState,
    cfg: BigClamConfig,
    callback: Optional[Callable[[int, float], None]],
    extract_F: Optional[Callable[[TrainState], np.ndarray]],
    checkpoints=None,
    state_to_arrays: Optional[Callable[[TrainState], dict]] = None,
    initial_hist: tuple = (),
    ckpt_meta: Optional[dict] = None,
    rebuild_step: Optional[Callable[[float], Callable]] = None,
    health_sig: Optional[Callable] = None,
    health_n: Optional[int] = None,
):
    """Shared convergence loop (MBSGD semantics, Bigclamv2.scala:203-219),
    used by both the single-chip and the sharded trainer.

    The convergence check compares LLH(F_t) against LLH(F_{t-1}); when it
    fires, F_{t-1} is the final model (exactly the reference's stopping
    state). The step that computed LLH(F_t) also eagerly produced F_{t+1};
    that speculative update is discarded.

    When a utils.checkpoint.CheckpointManager is given, the state tuple is
    saved every cfg.checkpoint_every iterations (SURVEY.md §5 — the
    reference had no checkpointing); initial_hist carries the restored LLH
    history on resume so convergence tests continue seamlessly.

    Callbacks taking a third parameter additionally receive an extras dict
    with the accepted-step histogram of the update applied this iteration
    ({"accept_hist": [count per step_candidates entry..., rejected]});
    2-parameter callbacks keep the (it, llh) protocol.

    With extract_F=None the loop runs STATE-RESIDENT: it returns
    (final_state, final_llh, num_iters, llh_history) and never fetches F
    to the host — the trainers' fit_state and the device-resident quality
    annealing (models.quality.fit_quality_device) build on this.

    BUFFER DONATION (cfg.donate_state, default on): when step_fn exposes a
    `donating(scratch, state)` entry (attach_donating), the loop feeds each
    step the TrainState it dropped one iteration ago as a donated scratch,
    so XLA writes the new F into the old F's storage — ping-pong buffers
    instead of a fresh F-sized allocation per step. The CURRENT input is
    never donated (the convergence protocol returns it as the final
    state), and a caller-provided initial state is never donated either
    (the caller may still hold it); the first calls donate a freshly
    allocated twin until a loop-owned state is available to recycle.
    Trajectories are bit-identical to the non-donated path — donation
    moves storage, not math (pinned by tests/test_donation.py).

    OBSERVABILITY (bigclam_tpu.obs): each iteration beats the stall
    heartbeat of the installed RunTelemetry (progress = iter + LLH), and
    its phases run under emit=False spans (obs.trace: fit_loop/dispatch,
    /sync, /callback, plus per-save fit_loop/checkpoint and the final
    fit_loop/extract_F) — per-phase totals land in the run report, the
    per-span breakdown of `cli report`, and the perf ledger, and a stall
    mid-collective names the open phase in its stall event. A
    NON-FINITE LLH aborts through _abort_nonfinite — F/accept-hist
    diagnostics are dumped (to the telemetry dir when one is active)
    before the FloatingPointError, instead of the loop silently iterating
    on garbage until max_iters. Telemetry off costs one None check per
    iteration plus math.isfinite on a host float (pinned < 2% of step time
    by tests/test_telemetry.py).

    NON-FINITE ROLLBACK (cfg.rollback_budget > 0, resilience/ISSUE 5):
    instead of abort-only, the loop keeps an in-HBM snapshot of the last
    VERIFIED-finite state (refreshed every cfg.rollback_snapshot_every
    iterations by a ping-pong device copy — no host round trip on the
    happy path). On a non-finite LLH it emits a `rollback` event, restores
    the snapshot (truncating the LLH history to the snapshot point so the
    convergence test replays rather than spuriously firing), cuts the
    Armijo ladder by cfg.rollback_shrink via `rebuild_step(scale)` (when
    the caller provides the hook — _ScaleRebuilder), and continues. After
    cfg.rollback_budget rollbacks the existing abort/diagnostic path
    fires. The fault-injection harness (resilience.faults) is consulted
    once per iteration at site "fit.step" (kill / delay / nan_inject).
    """
    import inspect
    import math

    from bigclam_tpu.obs import telemetry as _obs
    from bigclam_tpu.obs import trace as _trace

    tel = _obs.current()
    # MODEL HEALTH (ISSUE 8): with telemetry active and cfg.health_every
    # > 0, the steps carry a device health pack (ops.diagnostics) and the
    # monitor turns the cadence samples into `health` events, membership
    # churn against a rolling signature (health_sig — the trainer's
    # state->top-community map), LLH-window derivatives, and `anomaly`
    # events from the obs.health detectors. Off (either switch): one None
    # check per iteration.
    monitor = None
    if tel is not None and int(getattr(cfg, "health_every", 0) or 0) > 0:
        from bigclam_tpu.obs.health import HealthMonitor

        monitor = HealthMonitor(cfg, tel, sig_fn=health_sig, n_live=health_n)
    # per-iteration phase spans (obs.trace, ISSUE 6): slash-named so they
    # group under "fit_loop/" beneath whatever span encloses the fit (the
    # CLI's "fit" stage). emit=False — exact per-phase totals in the run
    # report/ledger, no per-iteration event lines. With telemetry off
    # _span returns the shared no-op (zero-cost contract, test_trace.py).
    _span = _trace.span

    cb_arity = 0
    if callback is not None:
        try:
            params = inspect.signature(callback).parameters.values()
            # only parameters that can take a positional argument count:
            # `def cb(it, llh, **tags)` must stay on the 2-arg protocol,
            # while *args accepts the extras
            cb_arity = sum(
                p.kind
                in (
                    inspect.Parameter.POSITIONAL_ONLY,
                    inspect.Parameter.POSITIONAL_OR_KEYWORD,
                )
                for p in params
            )
            if any(
                p.kind == inspect.Parameter.VAR_POSITIONAL for p in params
            ):
                cb_arity = 3
        except (TypeError, ValueError):
            cb_arity = 2
    from bigclam_tpu.resilience import faults as _faults

    donating = getattr(step_fn, "donating", None)
    donate = bool(getattr(cfg, "donate_state", False)) and donating is not None
    scratch = None      # dead previous state whose buffers the next donating
    hist: list[float] = list(initial_hist)  # call recycles
    # --- rollback state (see docstring) ---
    budget = max(int(getattr(cfg, "rollback_budget", 0)), 0)
    snap_every = max(int(getattr(cfg, "rollback_snapshot_every", 1)), 1)
    snapshot = None          # last verified-finite state (device copy)
    snap_hist_len = len(hist)
    fallback = state if budget else None    # pre-first-snapshot target
    rollbacks = 0
    since_snap = 0
    scale = float(getattr(cfg, "step_scale", 1.0))
    owned = False       # state is loop-produced (donatable when dropped);
    while True:         # the caller's initial state never is
        fault = _faults.maybe_fire("fit.step", it=int(state.it))
        if fault is not None and fault.get("kind") == "nan_inject":
            i0, j0 = fault.get("index", (0, 0))
            state = state._replace(
                F=state.F.at[int(i0), int(j0)].set(float("nan"))
            )
        with _span("fit_loop/dispatch", emit=False):
            # enqueue the compiled step (async on real backends)
            if donate:
                dead, scratch = scratch, None
                if dead is None:
                    dead = donation_scratch(state)
                new_state = donating(dead, state)
            else:
                new_state = step_fn(state)
        with _span("fit_loop/sync", emit=False):
            # the host block on the scalar LLH — device compute, in-step
            # collective waits, and the D2H transfer are indistinguishable
            # from the host, so this span IS the iteration's "collective
            # wait + host sync" phase (DESIGN.md span taxonomy)
            llh_t = float(new_state.llh)       # LLH of state.F
        if not math.isfinite(llh_t):
            target = snapshot if snapshot is not None else fallback
            if rollbacks >= budget or target is None:
                _abort_nonfinite(state, new_state, llh_t, hist, rollbacks)
            rollbacks += 1
            shrink = float(getattr(cfg, "rollback_shrink", 1.0) or 1.0)
            scale *= shrink
            if tel is not None:
                tel.event(
                    "rollback",
                    iter=int(state.it),
                    llh=llh_t,
                    rollbacks=rollbacks,
                    resume_iter=int(target.it),
                    step_scale=scale,
                )
            # restore by COPY: the target must stay alive for further
            # rollbacks while the restored state re-enters the donation
            # ping-pong as a loop-owned buffer
            state = jax.tree.map(jnp.copy, target)
            owned = True
            scratch = None
            # truncate the history to the restore point: the replayed
            # iterations re-evaluate their LLHs, and the convergence test
            # must compare them against the SAME predecessors as the
            # original pass (not against themselves)
            del hist[(snap_hist_len if snapshot is not None
                      else len(initial_hist)):]
            since_snap = 0
            if rebuild_step is not None and scale != 1.0:
                step_fn = rebuild_step(scale)
                donating = getattr(step_fn, "donating", None)
                donate = (
                    bool(getattr(cfg, "donate_state", False))
                    and donating is not None
                )
            continue
        if budget and (snapshot is None or since_snap >= snap_every):
            # state.F is VERIFIED finite (llh_t is its LLH): refresh the
            # rollback snapshot on the ping-pong cadence
            snapshot = (
                _SNAPSHOT_PING(snapshot, state)
                if snapshot is not None
                else jax.tree.map(jnp.copy, state)
            )
            snap_hist_len = len(hist)
            since_snap = 0
            fallback = None      # the snapshot supersedes the initial state
        since_snap += 1
        if tel is not None:
            tel.step_beat(int(state.it), llh_t)
        if monitor is not None:
            monitor.maybe_observe(int(state.it), llh_t, new_state)
        if callback is not None:
            with _span("fit_loop/callback", emit=False):
                if cb_arity >= 3:
                    ah = getattr(new_state, "accept_hist", None)
                    extras = (
                        {"accept_hist": np.asarray(ah).tolist()}
                        if ah is not None
                        else None
                    )
                    callback(int(state.it), llh_t, extras)
                else:
                    callback(int(state.it), llh_t)
        if hist and _rel_change(llh_t, hist[-1]) < cfg.conv_tol:
            final, final_llh, iters = state, llh_t, int(state.it)
            hist.append(llh_t)
            break
        hist.append(llh_t)
        if int(state.it) >= cfg.max_iters:
            # hit max_iters without converging; `state` is the last state
            # whose LLH was actually evaluated (hist[-1])
            final, final_llh, iters = state, llh_t, int(state.it)
            break
        if owned:
            # loop-produced and dropped below -> next call's donation; the
            # caller's initial state (owned=False) may still be held
            scratch = state
        state = new_state
        owned = True
        if (
            checkpoints is not None
            and cfg.checkpoint_every > 0
            and int(state.it) % cfg.checkpoint_every == 0
            and int(state.it) <= cfg.max_iters   # never persist the final
            and state_to_arrays is not None      # speculative (unevaluated) F
        ):
            # state_to_arrays may be a COLLECTIVE (fetch_global allgathers
            # across processes), so every process must enter it; only the
            # file write itself is single-writer (utils.dist)
            with _span("fit_loop/checkpoint", it=int(state.it)):
                arrays = state_to_arrays(state)
                if is_primary():
                    checkpoints.save(
                        int(state.it),
                        arrays,
                        meta={"llh_history": hist, **(ckpt_meta or {})},
                    )
                if tel is not None:
                    tel.event("checkpoint", step=int(state.it))
    if extract_F is None:
        # state-resident mode (fit_state / device annealing): hand back the
        # converged TrainState with NO host F fetch — the only scalars
        # crossing the host boundary were the per-iteration LLHs
        return final, final_llh, iters, tuple(hist)
    with _span("fit_loop/extract_F"):
        F = extract_F(final)
    return FitResult(
        F=F, sumF=F.sum(axis=0), llh=final_llh,
        num_iters=iters, llh_history=tuple(hist),
    )


def _abort_nonfinite(
    state, new_state, llh_t: float, hist, rollbacks: int = 0
) -> None:
    """Non-finite-LLH sentinel (SURVEY §5 / ISSUE 4): diagnose, dump,
    abort.

    A NaN/inf LLH means the optimizer state is already poisoned — every
    further iteration is wasted accelerator time and the convergence test
    (|1 - new/old|) can never fire on NaN, so the loop would silently burn
    to max_iters. Diagnostics are computed DEVICE-SIDE (reductions on the
    possibly-globally-sharded F return replicated scalars, so this works
    under multi-controller where np.asarray(F) would throw), emitted as a
    `nonfinite` telemetry event, and dumped to <telemetry>/nonfinite_dump
    .npz before raising FloatingPointError. With rollback enabled
    (cfg.rollback_budget) this is the ESCALATION path — `rollbacks` says
    how many recovery attempts were already burned."""
    import jax.numpy as jnp

    from bigclam_tpu.obs import telemetry as _obs

    F = state.F
    diag = {
        "iter": int(state.it),
        "llh": llh_t,
        "rollbacks": rollbacks,
        "f_nonfinite": int(jnp.size(F) - jnp.isfinite(F).sum()),
        "f_min": float(jnp.min(F)),
        "f_max": float(jnp.max(F)),
        "sumF_min": float(jnp.min(state.sumF)),
        "sumF_max": float(jnp.max(state.sumF)),
        "llh_tail": [float(v) for v in hist[-5:]],
    }
    ah = getattr(new_state, "accept_hist", None)
    try:
        diag["accept_hist"] = np.asarray(ah).tolist() if ah is not None else None
    except Exception:            # not fully addressable on this process
        diag["accept_hist"] = None
    tel = _obs.current()
    dump = ""
    if tel is not None:
        tel.event("nonfinite", **diag)
        if is_primary():
            import os

            dump = os.path.join(tel.directory, "nonfinite_dump.npz")
            np.savez(
                dump,
                **{
                    k: np.asarray(v)
                    for k, v in diag.items()
                    if v is not None
                },
            )
        tel.finalize()           # the report must exist even on abort
    raise FloatingPointError(
        f"non-finite LLH {llh_t} at iteration {diag['iter']}: "
        f"{diag['f_nonfinite']} non-finite F entries, "
        f"F range [{diag['f_min']:.3g}, {diag['f_max']:.3g}], "
        f"accept_hist={diag['accept_hist']}"
        + (
            f"; rollback budget exhausted after {rollbacks} rollback(s)"
            if rollbacks
            else ""
        )
        + (f"; diagnostics dumped to {dump}" if dump else "")
    )


def restore_checkpoint(checkpoints, expected_meta: dict, state_from_arrays):
    """Restore the newest checkpoint, refusing shape/graph mismatches.

    JAX clips out-of-range gathers and drops out-of-range scatters silently,
    so resuming with an F whose padding or graph differs from the compiled
    step would corrupt results without an exception — validate instead.

    PADDED shapes (n_pad, k_pad) are soft: padding rows/columns are inert
    zeros (ops.objective padding conventions), so a checkpoint written under
    a different padding regime (e.g. CPU XLA path vs TPU CSR-kernel path) is
    cropped to the live (num_nodes, k) region and re-padded. The live graph
    and K must match exactly.

    Returns (state, llh_history) or (None, ()) when nothing is stored.
    """
    restored = checkpoints.restore()
    if restored is None:
        return None, ()
    ckpt_step, arrays, meta = restored
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is not None:
        tel.event("restore", step=int(ckpt_step))
    soft = {"n_pad", "k_pad"}
    for key, val in expected_meta.items():
        if key in soft:
            continue
        got = meta.get(key)
        if got is None and not val:
            continue    # key added after this checkpoint was written; a
            # falsy expectation matches its implicit default
        if got != val:
            raise ValueError(
                f"checkpoint incompatible with this run: {key}={got} in "
                f"checkpoint vs {val} expected (dir: {checkpoints.directory})"
            )
    n_pad, k_pad = expected_meta["n_pad"], expected_meta["k_pad"]
    n, k = expected_meta["num_nodes"], expected_meta["k"]
    F = np.asarray(arrays["F"])
    if tuple(F.shape) != (n_pad, k_pad):
        if F.shape[0] < n or F.shape[1] < k:
            raise ValueError(
                f"checkpoint F shape {F.shape} smaller than live region "
                f"({n}, {k}) (dir: {checkpoints.directory})"
            )
        repad = np.zeros((n_pad, k_pad), F.dtype)
        repad[:n, :k] = F[:n, :k]
        arrays = dict(arrays)
        arrays["F"] = repad
        arrays["sumF"] = repad.sum(axis=0)
    return state_from_arrays(arrays), tuple(meta.get("llh_history", ()))


def pick_candidates_impl(
    edges: EdgeChunks, k_pad: int, cfg: BigClamConfig
) -> tuple[Callable, str]:
    """Choose the candidate-pass implementation for the non-CSR step.

    Returns (impl_fn, path_name) with path_name in {"pallas_vmem", "xla"} —
    the single source of truth consumed by BOTH make_train_step and the
    engagement report (engaged_path), so the recorded path is by construction
    the one that compiles."""
    want = cfg.use_pallas
    if want is None:
        want = jax.default_backend() == "tpu"
    if not want:
        return candidates_pass, "xla"
    from bigclam_tpu.ops.pallas_kernels import (
        candidates_pass_pallas,
        pallas_block_size,
    )

    chunk = int(edges.src.shape[-1])
    ok = pallas_block_size(chunk, k_pad) is not None and k_pad % 128 == 0
    if not ok:
        if cfg.use_pallas:                     # explicit request: refuse loudly
            raise ValueError(
                f"use_pallas=True but tiling constraints unmet "
                f"(chunk={chunk}, K_pad={k_pad}); pad K to a multiple of "
                "128 (k_multiple=128) and keep edge chunks >= 1024"
            )
        return candidates_pass, "xla"          # auto mode: reported fallback
    return candidates_pass_pallas, "pallas_vmem"


def make_train_step(
    edges: EdgeChunks, cfg: BigClamConfig, tiles=None, k_pad: int = 0
) -> tuple[Callable[[TrainState], TrainState], str]:
    """Build the jitted one-iteration update: 17 fused edge sweeps total
    (1 grad/LLH + 16 candidates), no host round trips.

    With `tiles` (an ops.pallas_csr.TilesDev), the whole edge sweep runs in
    the blocked-CSR MXU kernels: ONE dst-row gather shared by the grad and
    candidate passes, src expansion / scatter as one-hot matmuls, Armijo
    tails folded into the candidate kernel. Otherwise the candidate pass
    dispatches to the older Pallas VMEM kernel (ops.pallas_kernels) on TPU
    backends when the edge-chunk/K tiling constraints hold; cfg.use_pallas
    overrides that auto choice."""
    from bigclam_tpu.ops import diagnostics as dx

    def maybe_health(state, F_new, sumF_new, grad, hist):
        """The ISSUE 8 health pack for the single-chip steps: computed in
        the step body (grad rides into the pack's cond, so its reductions
        run on cadence iterations only), None at trace time with health
        off — zero added ops on the default path."""
        if not dx.health_on(cfg):
            return None
        return dx.health_pack(
            cfg, state.it, state.F, F_new, sumF_new, hist, grad=grad,
        )

    if tiles is not None:
        from bigclam_tpu.ops.linesearch import accept_stats, armijo_select
        from bigclam_tpu.ops.objective import node_tail
        from bigclam_tpu.ops.pallas_csr import (
            GroupedTilesDev,
            TilesDev,
            candidates_csr,
            gather_dst_rows,
            grad_llh_csr,
            train_pass_csr_grouped,
            train_pass_csr_grouped_kblocked,
        )

        interp = cfg.pallas_interpret
        grouped = isinstance(tiles, GroupedTilesDev)
        kblocked = grouped and tiles.kc > 0
        # fused superstep layouts (ISSUE 13, ops.pallas_fused): a FLAT
        # TilesDev carrying the grid-entry sequence (one-pass superstep)
        # or a kc column block size (K-blocked fused — flat tiles, no
        # grouped layout: with the gather in-kernel there is no fd to
        # budget)
        fused_flat = (
            isinstance(tiles, TilesDev) and tiles.seq is not None
        )
        fused_kb = (
            isinstance(tiles, TilesDev) and tiles.kc > 0 and not fused_flat
        )

        def fused_superstep_step(state: TrainState) -> TrainState:
            from bigclam_tpu.ops.pallas_fused import fused_superstep_csr

            F, sumF = state.F, state.sumF
            adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
            F_new, grad, node_llh, ok = fused_superstep_csr(
                F, sumF, tiles, cfg, interpret=interp
            )
            llh_cur = node_llh.astype(adt).sum()
            hist = accept_stats(ok > 0)
            return TrainState(
                F=F_new, sumF=F_new.sum(axis=0), llh=llh_cur.astype(F.dtype),
                it=state.it + 1, accept_hist=hist,
                health=maybe_health(
                    state, F_new, F_new.sum(axis=0), grad, hist
                ),
            )

        if fused_flat:
            return finalize_step(fused_superstep_step), "csr_fused"

        def fused_kb_step(state: TrainState) -> TrainState:
            # single-chip large K, fused: flat tiles, kc columns per
            # kernel call, gather in-kernel; candidate terms are
            # neighbor-only so the Armijo tails ride armijo_update
            from bigclam_tpu.ops.pallas_fused import (
                train_pass_csr_kblocked_fused,
            )

            F, sumF = state.F, state.sumF
            adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
            grad, llh_nbr, cand_nbr = train_pass_csr_kblocked_fused(
                F, sumF, tiles, cfg, interpret=interp
            )
            node_llh = llh_nbr.astype(adt) + node_tail(F, sumF).astype(adt)
            llh_cur = node_llh.sum()
            F_new, sumF_new, hist = armijo_update(
                F, sumF, grad, node_llh, cand_nbr.astype(adt), cfg,
                with_stats=True,
            )
            return TrainState(
                F=F_new, sumF=sumF_new, llh=llh_cur, it=state.it + 1,
                accept_hist=hist,
                health=maybe_health(state, F_new, sumF_new, grad, hist),
            )

        if fused_kb:
            return finalize_step(fused_kb_step), "csr_fused_kb"

        def csr_step_kblocked(state: TrainState) -> TrainState:
            # single-chip large K: grouped layout + K-column-blocked
            # kernels; candidate terms are neighbor-only, so the Armijo
            # tails ride the XLA armijo_update path
            F, sumF = state.F, state.sumF
            adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
            grad, llh_nbr, cand_nbr = train_pass_csr_grouped_kblocked(
                F, sumF, tiles, cfg, interpret=interp
            )
            node_llh = llh_nbr.astype(adt) + node_tail(F, sumF).astype(adt)
            llh_cur = node_llh.sum()
            F_new, sumF_new, hist = armijo_update(
                F, sumF, grad, node_llh, cand_nbr.astype(adt), cfg,
                with_stats=True,
            )
            return TrainState(
                F=F_new, sumF=sumF_new, llh=llh_cur, it=state.it + 1,
                accept_hist=hist,
                health=maybe_health(state, F_new, sumF_new, grad, hist),
            )

        if kblocked:
            return finalize_step(csr_step_kblocked), "csr_grouped_kb"

        def csr_step(state: TrainState) -> TrainState:
            F, sumF = state.F, state.sumF
            if grouped:
                # large-K layout: ONE scan over block groups, each group's
                # dst gather shared by its grad and candidate kernels
                grad, node_llh, cand_full = train_pass_csr_grouped(
                    F, sumF, tiles, cfg, interpret=interp
                )
            else:
                fd = gather_dst_rows(F, tiles)
                grad, node_llh = grad_llh_csr(
                    F, sumF, tiles, cfg, fd=fd, interpret=interp
                )
                cand_full = candidates_csr(
                    F, grad, sumF, tiles, cfg, fd=fd, interpret=interp
                )
            llh_cur = node_llh.sum()
            F_new, sumF_new, hist = armijo_select(
                F, grad, node_llh, cand_full, cfg, with_stats=True
            )
            return TrainState(
                F=F_new, sumF=sumF_new, llh=llh_cur, it=state.it + 1,
                accept_hist=hist,
                health=maybe_health(state, F_new, sumF_new, grad, hist),
            )

        return finalize_step(csr_step), ("csr_grouped" if grouped else "csr")

    cand_impl, cand_path = pick_candidates_impl(
        edges, k_pad or cfg.num_communities, cfg
    )

    def step(state: TrainState) -> TrainState:
        F, sumF = state.F, state.sumF
        grad, node_llh = grad_llh(F, sumF, edges, cfg)
        llh_cur = node_llh.sum()               # LLH of current F
        cand_nbr = cand_impl(F, grad, edges, cfg)
        F_new, sumF_new, hist = armijo_update(
            F, sumF, grad, node_llh, cand_nbr, cfg, with_stats=True
        )
        return TrainState(
            F=F_new, sumF=sumF_new, llh=llh_cur, it=state.it + 1,
            accept_hist=hist,
            health=maybe_health(state, F_new, sumF_new, grad, hist),
        )

    return finalize_step(step), cand_path


class BigClamModel(MemoryAccountedModel):
    """Single-chip (or single-mesh-context) BigCLAM trainer.

    Usage:
        model = BigClamModel(graph, cfg)
        result = model.fit(F0)          # F0: (N, K) nonneg init
    """

    def __init__(
        self,
        g: Graph,
        cfg: BigClamConfig,
        node_multiple: int = 1,
        k_multiple: int = 1,
        dtype=None,
    ):
        self.g = g
        self.cfg = cfg
        self.dtype = dtype or (
            jnp.float64 if cfg.dtype == "float64" else jnp.float32
        )
        self.k_pad = _round_up(cfg.num_communities, k_multiple)
        self._tiles = self._maybe_build_tiles(node_multiple)
        if self._tiles is not None:
            # the CSR kernels never read the EdgeChunks arrays — defer their
            # (device-resident) construction so HBM holds only the tiles.
            # _node_multiple_csr (set by _maybe_build_tiles) makes the lazy
            # EdgeChunks padding agree with the tile layout's n_pad
            self._node_multiple = _lcm(
                node_multiple, self._node_multiple_csr
            )
            self._edges = None
            self.n_pad = self._tiles.n_pad
        else:
            self._node_multiple = node_multiple
            self._edges, self.n_pad = prepare_graph(
                g, cfg, node_multiple=node_multiple, dtype=self.dtype,
                k_pad=self.k_pad,
            )
        if (self.n_pad > g.num_nodes or self.k_pad > cfg.num_communities) and (
            cfg.min_f != 0.0
        ):
            # padding inertness relies on clip(0 + eta*grad) staying 0; a
            # positive box floor would lift phantom rows/columns off zero
            raise ValueError(
                "node/K padding requires min_f == 0.0; got "
                f"min_f={cfg.min_f} with padding "
                f"{g.num_nodes}->{self.n_pad}, {cfg.num_communities}->{self.k_pad}"
            )
        self._step, self.engaged_path = make_train_step(
            self._edges, cfg, tiles=self._tiles, k_pad=self.k_pad
        )
        self._step_cache = {step_cfg_key(cfg): (self._step, self.engaged_path)}
        self.path_reason = getattr(self, "_csr_reason", "")
        from bigclam_tpu.obs import note_step_build

        note_step_build(cfg, "BigClamModel")
        log_engaged_path("BigClamModel", self.engaged_path, self.path_reason)
        # static memory model (obs.memory, ISSUE 12): baked from the
        # SAME committed layout the step compiled against, emitted as
        # `memory_model` events + kept for memory_reconcile
        self._bake_memory_model()

    def rebuild_step(self) -> None:
        """Swap in the train step for the CURRENT self.cfg.

        Device tile/edge buffers are reused — only step-baked constants
        (clip bounds, Armijo candidates) change. Path selection is NOT
        re-run: quality mode's max_p relaxation (models.quality) must not
        flip the engaged kernels mid-schedule. Steps are cached by
        step_cfg_key, so toggling between a pair of configs (quality's
        relax/restore around every schedule) compiles each step once."""
        key = step_cfg_key(self.cfg)
        if key not in self._step_cache:
            self._step_cache[key] = make_train_step(
                self._edges, self.cfg, tiles=self._tiles, k_pad=self.k_pad
            )
            from bigclam_tpu.obs import note_step_build

            note_step_build(self.cfg, "BigClamModel")
        self._step, self.engaged_path = self._step_cache[key]

    # --------------------------------------- memory accounting (ISSUE 12)
    def _graph_device_arrays(self) -> dict:
        """The device arrays the compiled step keeps resident: the CSR
        tiles on the kernel path, the EdgeChunks on XLA (self._edges
        directly, NOT the lazy .edges property — on the CSR path the
        step never reads EdgeChunks, so baking them into the model
        would price a buffer that does not exist)."""
        out = {}
        if self._tiles is not None:
            t = self._tiles
            out.update({
                "graph/tiles_src": t.src_local,
                "graph/tiles_dst": t.dst,
                "graph/tiles_mask": t.mask,
                "graph/tiles_block_id": t.block_id,
            })
        if self._edges is not None:
            out.update({
                "graph/edges_src": self._edges.src,
                "graph/edges_dst": self._edges.dst,
                "graph/edges_mask": self._edges.mask,
            })
        return out

    def _memory_fused(self) -> bool:
        """Did this build commit a FUSED tile layout (ISSUE 13)? Flat
        TilesDev carrying the entry sequence (superstep) or a kc column
        block (K-blocked fused) — the layouts with NO HBM fd gather."""
        t = self._tiles
        return t is not None and (
            getattr(t, "seq", None) is not None
            or (getattr(t, "kc", 0) and not hasattr(t, "nb"))
        )

    def _memory_fd_bytes(self) -> float:
        """Bytes of the step's dst-row transient: the shared HBM fd
        gather on the split paths ((chunk, K_pad) per scan step on XLA,
        the whole layout's / one group window's dst rows on CSR), or —
        when the fused kernels engage — the (2, T, Kc) double-buffered
        in-kernel DMA scratch that replaces it (VMEM-resident; priced so
        the fd elimination is visible in the model, ISSUE 13)."""
        isz = jnp.dtype(self.dtype).itemsize
        if self._tiles is not None:
            if self._memory_fused():
                cols = getattr(self._tiles, "kc", 0) or self.k_pad
                return 2.0 * self._tiles.tile_t * cols * isz
            dst = self._tiles.dst
            kc = getattr(self._tiles, "kc", 0) or self.k_pad
            if dst.ndim >= 3:           # grouped: one (G, T) window live
                import numpy as _np

                return float(_np.prod(dst.shape[1:])) * kc * isz
            return float(dst.size) * kc * isz
        return float(self._edges.src.shape[-1]) * self.k_pad * isz

    def _build_memory_model(self):
        from bigclam_tpu.obs import memory as _mem

        cfg = self.cfg
        return _mem.dense_memory_model(
            self.n_pad,
            self.k_pad,
            jnp.dtype(self.dtype).itemsize,
            len(cfg.step_candidates),
            self._graph_buffer_bytes(),
            health_on=int(getattr(cfg, "health_every", 0) or 0) > 0,
            donate=bool(cfg.donate_state),
            rollback=int(getattr(cfg, "rollback_budget", 0) or 0) > 0,
            fd_bytes=self._memory_fd_bytes(),
            fused=self._memory_fused(),
            model=type(self).__name__,
        )

    @property
    def edges(self) -> EdgeChunks:
        """Chunked edge arrays (built lazily on the CSR-kernel path, where
        the train step itself never reads them)."""
        if self._edges is None:
            self._edges, n_pad = prepare_graph(
                self.g, self.cfg, node_multiple=self._node_multiple,
                dtype=self.dtype, k_pad=self.k_pad,
            )
            assert n_pad == self.n_pad, (n_pad, self.n_pad)
        return self._edges

    def _maybe_build_tiles(self, node_multiple: int):
        """Decide + build the blocked-CSR tile layout (ops.csr_tiles).

        Auto mode (use_pallas_csr=None): engage on TPU backends when f32,
        the Mosaic tiling constraints hold, the tile padding overhead is
        bounded, and the shared dst-row gather fits a ~2 GB HBM budget.
        Explicit True raises on unmet constraints rather than degrading.
        Each non-engagement records its reason in self._csr_reason (surfaced
        by engaged_path / log_engaged_path)."""
        cfg = self.cfg
        want, reason = csr_want_reason(cfg)
        if not want:
            self._csr_reason = reason
            return None
        from bigclam_tpu.ops.csr_tiles import build_block_tiles
        from bigclam_tpu.ops.pallas_csr import csr_tiles_supported, device_tiles

        explicit = cfg.use_pallas_csr is True
        if self.dtype != jnp.float32 or cfg.accum_dtype not in (None, "float32"):
            # the kernels accumulate per-block sums in F.dtype; a promised
            # wider accum_dtype must keep the XLA path
            if explicit:
                raise ValueError(
                    "use_pallas_csr requires float32 F and "
                    "accum_dtype in (None, 'float32')"
                )
            self._csr_reason = (
                f"requires float32 F/accum (dtype={self.dtype}, "
                f"accum_dtype={cfg.accum_dtype})"
            )
            return None
        # MXU/VMEM lane alignment: pad K up rather than fall back — zero
        # columns are inert (see ops.objective padding conventions). Only
        # committed to self.k_pad once the path actually engages.
        k_pad = _round_up(self.k_pad, 128)
        n = self.g.num_nodes
        from bigclam_tpu.ops.pallas_csr import fit_tile_shape

        fused = csr_fused_want(cfg)
        kc = 0
        if cfg.csr_k_block:
            # explicit K-blocked mode (also the interpret-mode test hook)
            kc = cfg.csr_k_block
            k_pad = _round_up(k_pad, kc)
            shape = (
                fit_tile_shape(cfg.csr_block_b, cfg.csr_tile_t, kc,
                               fused=fused)
                if not cfg.pallas_interpret
                else (cfg.csr_block_b, cfg.csr_tile_t)
            )
        else:
            shape = (
                fit_tile_shape(cfg.csr_block_b, cfg.csr_tile_t, k_pad,
                               fused=fused)
                if not cfg.pallas_interpret
                else (cfg.csr_block_b, cfg.csr_tile_t)
            )
            if shape is None:
                # whole-K rows exceed VMEM: single-chip large-K mode
                # (kernels then scan K blocks;
                # train_pass_csr_grouped_kblocked on the split path,
                # train_pass_csr_kblocked_fused on flat tiles when the
                # fused schedule engages); policy shared with the
                # sharded trainer
                from bigclam_tpu.ops.pallas_csr import largest_fitting_kblock

                found = largest_fitting_kblock(
                    cfg.csr_block_b, cfg.csr_tile_t, k_pad, fused=fused
                )
                if found is not None:
                    kc, shape = found
        if shape is None:
            # kernels cannot fit VMEM at this K — XLA path (or shard K)
            if explicit:
                raise ValueError(
                    f"use_pallas_csr=True but no tile shape fits VMEM at "
                    f"k_pad={k_pad}; shard the K axis instead"
                )
            self._csr_reason = f"no tile shape fits VMEM at k_pad={k_pad}"
            return None
        block_b, tile_t = shape
        if not csr_tiles_supported(
            block_b, tile_t, kc or k_pad, cfg.pallas_interpret
        ):
            if explicit:
                raise ValueError(
                    f"use_pallas_csr=True but tiling unsupported: "
                    f"block_b={cfg.csr_block_b}, tile_t={cfg.csr_tile_t}, "
                    f"k_pad={k_pad} (need multiples of 128)"
                )
            self._csr_reason = (
                f"tiling constraints unmet: block_b={block_b}, "
                f"tile_t={tile_t}, k_pad={k_pad} (need 128-multiples)"
            )
            return None
        if cfg.min_f != 0.0 and (
            _round_up(n, block_b) != n or k_pad != cfg.num_communities
        ):
            # padding inertness needs min_f == 0 (see __init__'s guard);
            # auto mode degrades to the XLA path instead of raising there
            if explicit:
                raise ValueError(
                    "use_pallas_csr=True requires min_f == 0.0 when node/K "
                    f"padding is introduced (min_f={cfg.min_f})"
                )
            self._csr_reason = f"min_f={cfg.min_f} != 0 with padding"
            return None
        if _round_up(n, _lcm(node_multiple, block_b)) != _round_up(
            n, block_b
        ):
            # caller's node_multiple would pad rows beyond the tile layout's
            # n_pad = n_blocks * block_b
            if explicit:
                raise ValueError(
                    f"use_pallas_csr=True incompatible with "
                    f"node_multiple={node_multiple} (block_b={block_b})"
                )
            self._csr_reason = (
                f"node_multiple={node_multiple} incompatible with "
                f"block_b={block_b}"
            )
            return None
        from bigclam_tpu.ops.csr_tiles import group_tiles, layout_economical

        bt = build_block_tiles(self.g, block_b, tile_t)
        fd_bytes = bt.src_local.size * k_pad * 4
        e = max(self.g.num_directed_edges, 1)
        pad_ok = layout_economical(
            bt.src_local.size, e, bt.n_blocks, tile_t
        )
        if not pad_ok:
            if explicit:
                raise ValueError(
                    f"use_pallas_csr=True but layout uneconomical: "
                    f"{bt.padded_edges} padded edges on {e}"
                )
            self._csr_reason = (
                f"flat layout uneconomical: {bt.padded_edges} padded edge "
                f"slots on {e} edges"
            )
            return None
        if fused:
            # fused superstep (ISSUE 13): the dst gather happens inside
            # the kernel, so there is NO fd buffer to budget — the flat
            # layout serves every N, and large K takes the K-blocked
            # fused pass on the SAME flat tiles (no grouped layout)
            self.k_pad = k_pad
            self._node_multiple_csr = bt.n_blocks * bt.block_b
            return device_tiles(bt, self.dtype, with_seq=not kc, kc=kc)
        if fd_bytes <= FLAT_FD_BUDGET and not kc:
            self.k_pad = k_pad
            self._node_multiple_csr = bt.n_blocks * bt.block_b
            return device_tiles(bt, self.dtype)
        # large K: one whole-graph dst gather would blow HBM — regroup into
        # block windows scanned with per-group gathers (GROUP_FD_BUDGET
        # each). K-blocked mode always grouped; its live gather per scan
        # step holds kc columns, so budgets scale with kc
        group_cols = kc or k_pad
        group_budget = GROUP_FD_BUDGET
        tiles_per_group = max(
            group_budget // (tile_t * group_cols * 4), 1
        )
        avg_tiles = max(bt.src_local.shape[0] / bt.n_blocks, 1e-9)
        nb = max(int(tiles_per_group / avg_tiles), 1)
        gbt = group_tiles(bt, nb)
        while (
            nb > 1
            and gbt.src_local.shape[1] * tile_t * group_cols * 4
            > 2 * group_budget
        ):
            nb = max(nb // 2, 1)
            gbt = group_tiles(bt, nb)
        group_fd = gbt.src_local.shape[1] * tile_t * group_cols * 4
        ok = (
            layout_economical(gbt.slots, e, gbt.n_groups * gbt.nb, tile_t)
            and gbt.n_pad % max(node_multiple, 1) == 0
            # even at nb=1 a single hub block can exceed the budget: that
            # gather would OOM at runtime, so refuse here
            and group_fd <= 4 * group_budget
        )
        if not ok:
            if explicit:
                raise ValueError(
                    f"use_pallas_csr=True but grouped layout uneconomical: "
                    f"{gbt.slots - e} padded slots on {e} (nb={nb})"
                )
            self._csr_reason = (
                f"grouped layout uneconomical: {gbt.slots - e} padded slots "
                f"on {e} edges (nb={nb}, group fd {group_fd >> 20} MiB)"
            )
            return None
        from bigclam_tpu.ops.pallas_csr import device_grouped_tiles

        self.k_pad = k_pad
        self._node_multiple_csr = gbt.n_pad
        return device_grouped_tiles(gbt, self.dtype, kc=kc)

    def init_state(self, F0: Optional[np.ndarray] = None) -> TrainState:
        n, k = self.g.num_nodes, self.cfg.num_communities
        if F0 is None:
            # row-keyed counter init (ISSUE 15 satellite): the same bits
            # any per-host range generation produces — single-chip just
            # materializes the whole range
            F0 = rowkeyed_init_F(self.g, self.cfg)
        assert F0.shape == (n, k), (F0.shape, (n, k))
        F = jnp.zeros((self.n_pad, self.k_pad), self.dtype)
        F = F.at[:n, :k].set(jnp.asarray(F0, self.dtype))
        return self.reset_state(F)

    def reset_state(self, F: jax.Array) -> TrainState:
        """TrainState from an already-device-resident PADDED F — init_state
        minus the host upload (the device annealing loop's per-cycle state;
        single source of the state-field construction)."""
        from bigclam_tpu.ops import diagnostics as dx

        return TrainState(
            F=F,
            sumF=F.sum(axis=0),
            llh=jnp.asarray(-jnp.inf, F.dtype),
            it=jnp.zeros((), jnp.int32),
            accept_hist=jnp.zeros(
                len(self.cfg.step_candidates) + 1, jnp.int32
            ),
            health=dx.init_health(self.cfg),
        )

    def extract_F(self, state: TrainState) -> np.ndarray:
        """Fetch the live (num_nodes, K) F block to the host."""
        n, k = self.g.num_nodes, self.cfg.num_communities
        return np.asarray(state.F[:n, :k])

    def health_sig(self, state: TrainState) -> jax.Array:
        """(N_pad,) int32 top-community signature — the rolling membership
        snapshot obs.health churns against (padding rows are -1 forever,
        so they never register as churn)."""
        from bigclam_tpu.ops.diagnostics import dense_top_community

        return dense_top_community(state.F)

    def _ckpt_meta(self) -> dict:
        return {
            "num_nodes": self.g.num_nodes,
            "num_directed_edges": self.g.num_directed_edges,
            "k": self.cfg.num_communities,
            "n_pad": self.n_pad,
            "k_pad": self.k_pad,
            # --resume auto reconstructs the rng lineage from here: a
            # checkpoint written under a different seed must refuse, not
            # silently splice two trajectories (restore_checkpoint's
            # falsy-default rule keeps old seedless checkpoints loadable)
            "seed": self.cfg.seed,
        }

    def _state_to_arrays(self, state: TrainState) -> dict:
        return {
            "F": np.asarray(state.F),
            "sumF": np.asarray(state.sumF),
            "llh": np.asarray(state.llh),
            "it": np.asarray(state.it),
        }

    def _state_from_arrays(self, arrays: dict) -> TrainState:
        from bigclam_tpu.ops import diagnostics as dx

        return TrainState(
            F=jnp.asarray(arrays["F"], self.dtype),
            sumF=jnp.asarray(arrays["sumF"], self.dtype),
            llh=jnp.asarray(arrays["llh"], self.dtype),
            it=jnp.asarray(arrays["it"], jnp.int32),
            accept_hist=jnp.zeros(
                len(self.cfg.step_candidates) + 1, jnp.int32
            ),
            health=dx.init_health(self.cfg),
        )

    def fit(
        self,
        F0: np.ndarray,
        callback: Optional[Callable[[int, float], None]] = None,
        checkpoints=None,
        resume: bool = True,
    ) -> FitResult:
        """Train to convergence (see run_fit_loop). If `checkpoints` (a
        utils.checkpoint.CheckpointManager) holds a saved state, training
        resumes from it (resume=False forces a cold start while still
        SAVING new checkpoints — `cli fit --resume never`); F0 is only the
        cold-start init."""
        state, hist = self.init_state(F0), ()
        if checkpoints is not None and resume:
            restored, hist = restore_checkpoint(
                checkpoints, self._ckpt_meta(), self._state_from_arrays
            )
            if restored is not None:
                state = restored
        rebuilder = _ScaleRebuilder(self)
        try:
            return run_fit_loop(
                self._step,
                state,
                self.cfg,
                callback,
                self.extract_F,
                checkpoints=checkpoints,
                state_to_arrays=self._state_to_arrays,
                initial_hist=hist,
                ckpt_meta=self._ckpt_meta(),
                rebuild_step=rebuilder,
                health_sig=self.health_sig,
                health_n=self.g.num_nodes,
            )
        finally:
            rebuilder.restore()

    def fit_state(
        self,
        state: TrainState,
        callback: Optional[Callable[[int, float], None]] = None,
    ):
        """Train to convergence from a DEVICE-RESIDENT TrainState, returning
        (final_state, final_llh, num_iters, llh_history) without fetching F
        to the host — the pod-scale entry point (fit() wraps init_state +
        host extraction around the same loop)."""
        rebuilder = _ScaleRebuilder(self)
        try:
            return run_fit_loop(
                self._step, state, self.cfg, callback, None,
                rebuild_step=rebuilder,
                health_sig=self.health_sig,
                health_n=self.g.num_nodes,
            )
        finally:
            rebuilder.restore()

    def random_init(self, seed: Optional[int] = None) -> np.ndarray:
        """Bernoulli(0.5) {0,1} init, the reference's random-row distribution
        (Bigclamv2.scala:62). Conductance-seeded init lives in ops.seeding."""
        return random_init_F(self.g, self.cfg, seed)

    def foldin_rows(
        self,
        state: TrainState,
        nodes,
        max_deg: Optional[int] = None,
        max_iters: Optional[int] = None,
        conv_tol: Optional[float] = None,
        init: str = "own",
    ):
        """Batched FOLD-IN (ISSUE 14): re-optimize the rows of `nodes`
        against this state's FROZEN F — the per-node half of the train
        step extracted as a standalone batch primitive (ops.foldin), and
        the operator `cli serve`'s suggest family and the live-graph
        warm-start refit (ROADMAP 3b) are built on. Each node's row runs
        the same Armijo candidate ascent as the full step, holding every
        other row fixed.

        init="own" (default) warm-starts each node from its CURRENT row:
        a trained node's row is a fixed point of its own fold-in
        objective, so fold-in recovers the trained row within the
        convergence band (pinned by tests/test_serve.py) and refines it
        when the frozen F has drifted (the live-graph warm-start).
        init="mean" cold-starts from the neighbor mean — the brand-new-
        node path; the per-node objective is non-concave, so a cold
        start may land on a DIFFERENT local optimum of the row (the
        serve gate bands its LLH against a full refit instead of
        asserting row equality).

        Returns (rows (B, K) np.ndarray, llh (B,), iters (B,))."""
        from bigclam_tpu.ops import foldin as fi
        from bigclam_tpu.serve.snapshot import pad_neighbor_batch

        nodes = np.asarray(nodes, np.int64)
        nbr_ids, nbr_mask, _ = pad_neighbor_batch(
            self.g.indptr, self.g.indices, nodes, max_deg=max_deg
        )
        F = state.F
        nbr_rows = fi.gather_neighbor_rows(F, jnp.asarray(nbr_ids))
        mask = jnp.asarray(nbr_mask, F.dtype)
        own = F[jnp.asarray(nodes)]
        sumF_others = state.sumF[None, :] - own
        rows0 = (
            own if init == "own"
            else fi.neighbor_mean_rows(nbr_rows, mask)
        )
        rows0 = jnp.array(rows0)        # donated: never alias frozen F
        fit = fi.make_foldin_fit(
            self.cfg, max_iters=max_iters, conv_tol=conv_tol
        )
        rows, llh, iters = fit(rows0, nbr_rows, mask, sumF_others)
        k = self.cfg.num_communities
        return (
            np.asarray(rows)[:, :k],
            np.asarray(llh),
            np.asarray(iters),
        )

    def refit_commit(
        self, state: TrainState, nodes, rows: np.ndarray
    ) -> TrainState:
        """Scatter freshly folded rows back into the state (the
        warm-start incremental refit's commit half, ISSUE 15): F rows
        replaced, sumF updated by the row delta — everything else
        (llh/it/health) is refit-round bookkeeping the restricted loop
        owns (models.refit.warm_start_refit)."""
        from bigclam_tpu.ops.foldin import apply_rows

        k = self.cfg.num_communities
        rows_p = np.zeros((len(nodes), self.k_pad), dtype=np.float64)
        rows_p[:, :k] = rows
        F, sumF = apply_rows(
            state.F, state.sumF, jnp.asarray(np.asarray(nodes, np.int64)),
            jnp.asarray(rows_p, self.dtype),
        )
        return state._replace(F=F, sumF=sumF)

    def warm_start_refit(self, F_prev: np.ndarray, touched, **kw):
        """Incremental warm-start refit from a previous F restricted to
        the touched rows + halo (ISSUE 15 tentpole; see
        models.refit.warm_start_refit for the round/escalation
        semantics)."""
        from bigclam_tpu.models.refit import warm_start_refit

        return warm_start_refit(self, F_prev, touched, **kw)
