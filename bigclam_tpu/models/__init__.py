from bigclam_tpu.models.bigclam import (
    BigClamModel,
    TrainState,
    FitResult,
    prepare_graph,
)

__all__ = ["BigClamModel", "TrainState", "FitResult", "prepare_graph"]
