from bigclam_tpu.models.bigclam import (
    BigClamModel,
    TrainState,
    FitResult,
    prepare_graph,
)
from bigclam_tpu.models.model_selection import SweepResult, build_kset, sweep_k
from bigclam_tpu.models.quality import (
    QualityResult,
    fit_quality,
    fit_quality_device,
)
from bigclam_tpu.models.refit import (
    RefitResult,
    follow_deltas,
    warm_start_refit,
)
from bigclam_tpu.models.sparse import SparseBigClamModel

__all__ = [
    "BigClamModel",
    "SparseBigClamModel",
    "RefitResult",
    "warm_start_refit",
    "follow_deltas",
    "TrainState",
    "FitResult",
    "prepare_graph",
    "SweepResult",
    "build_kset",
    "sweep_k",
    "QualityResult",
    "fit_quality",
    "fit_quality_device",
]
