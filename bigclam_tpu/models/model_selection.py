"""Automatic selection of the community count K (C17, SURVEY.md §2).

Replaces bigclam4-7.scala:115-133 (log-spaced K grid) and :244-266 (the
sweep): seeds are computed ONCE (v4:75) and reused for every K; for each K
in the grid the model is re-seeded and trained to convergence; the sweep
stops at the first K whose relative LLH improvement over the previous K
falls below ksweep_tol ((1 - LLH_Knew/LLH_Kold) < tol, v4:259 — NOT an
absolute value, faithfully replicated).

TPU-shaped difference: the F buffer is allocated once at K_max and masked
per-K (columns >= K stay identically zero, which the padding-inertness
property of the kernels guarantees — see ops/objective.py), so ONE
compilation of the train step serves the whole sweep instead of re-jitting
per K.

Quirk fixes (documented in PARITY.md):
  * Q3 (v4:251): `LLHKold == null` on a Double is always false, so the
    reference compared the first K's LLH against 0.0; here the first K
    simply primes LLH_Kold.
  * SGDFindC (v4:225-243) returns LLHold — the second-to-last LLH — and
    burns one untracked update before the loop (v4:228); we use the
    converged LLH from the shared fit loop.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.models.bigclam import BigClamModel, FitResult
from bigclam_tpu.ops import seeding
from bigclam_tpu.utils.dist import is_primary


def build_kset(min_com: int, max_com: int, div_com: int) -> List[int]:
    """The log-spaced K grid, exactly as bigclam4-7.scala:116-133.

    conGap = exp(log(maxCom/minCom)/divCom) with Scala *integer* division of
    maxCom/minCom; the walk multiplies-and-truncates, bumps by 1 when stuck,
    stops at maxCom and appends it. Golden: (50, 200, 15) reproduces the
    pasted run artifact Array(50, 54, 59, ..., 184, 200) at v4:268.
    """
    if min_com <= 0 or max_com < min_com:
        raise ValueError(f"need 0 < min_com <= max_com, got {min_com}, {max_com}")
    ratio = max_com // min_com               # Scala Int/Int division
    if ratio < 1:
        return [int(min_com), int(max_com)]
    con_gap = math.exp(math.log(ratio) / div_com)
    kset = [int(min_com)]
    x = int(min_com)
    while True:
        xtemp = int(x * con_gap)             # .toInt truncation
        if xtemp == x:
            xtemp += 1
        x = xtemp
        if x >= max_com:
            break
        kset.append(x)
    kset.append(int(max_com))
    return kset


@dataclasses.dataclass(frozen=True)
class SweepResult:
    chosen_k: int                 # KforC: first K with sub-tol improvement
    llh_by_k: Dict[int, float]    # converged LLH per trained K
    kset: List[int]               # the full grid (sweep may stop early)
    best_fit: Optional[FitResult]  # fit at the last trained K


def sweep_k(
    g: Graph,
    cfg: BigClamConfig,
    model_factory: Optional[Callable[[BigClamConfig], object]] = None,
    callback: Optional[Callable[[int, float], None]] = None,
    rng: Optional[np.random.Generator] = None,
    state_dir: Optional[str] = None,
    device_annealing: bool = False,
    resume: bool = True,
) -> SweepResult:
    """Train across the K grid and pick KforC (bigclam4-7.scala:244-266).

    model_factory(cfg_at_kmax) may supply a sharded trainer; default is the
    single-chip BigClamModel with K padded to the grid max so one compiled
    step serves every K.

    When state_dir is given, per-K converged LLHs are journaled to
    state_dir/sweep_state.json and already-trained Ks are skipped on restart
    (SURVEY.md §5: a K-sweep on a large graph is hours; the reference could
    only restart from scratch). With cfg.checkpoint_every > 0, each K's fit
    additionally checkpoints WITHIN the K (state_dir/k_<K>/), so a crash
    hours into one K resumes inside that K instead of restarting it; a K's
    checkpoints are deleted once its LLH is journaled. `resume=False`
    (cli --resume never) ignores the existing journal and within-K
    checkpoints — every K retrains cold — while still journaling fresh
    results.
    """
    import json
    import os
    import shutil

    kset = build_kset(cfg.min_com, cfg.max_com, cfg.div_com)
    k_max = kset[-1]
    cfg_max = cfg.replace(num_communities=k_max)
    model = (
        model_factory(cfg_max) if model_factory is not None
        else BigClamModel(g, cfg_max)
    )
    # Per-K PRNG streams, fixed UP FRONT for the whole grid: journaled Ks
    # skip init_F on resume, so a single shared generator would sit at a
    # different stream position than the uninterrupted run whenever any K
    # pads F0 with Bernoulli columns (|seeds| < K) — silently changing
    # llh_by_k / chosen_k across a restart. Seeding each K independently
    # ([cfg.seed, k], or child seeds drawn once from a caller-supplied rng)
    # makes F0(k) a pure function of the config regardless of resume point.
    if rng is None:
        k_rngs = {k: np.random.default_rng([cfg.seed, k]) for k in kset}
    else:
        child = rng.integers(2**63, size=len(kset))
        k_rngs = {
            k: np.random.default_rng(int(s)) for k, s in zip(kset, child)
        }
    # computed once (v4:75); at k_max so the covering walk (quality mode's
    # seed_exclusion) yields enough seeds for every K in the grid
    seeds = seeding.conductance_seeds(g, cfg_max)

    llh_by_k: Dict[int, float] = {}
    state_path = None
    if state_dir is not None:
        os.makedirs(state_dir, exist_ok=True)
        state_path = os.path.join(state_dir, "sweep_state.json")
        if resume and os.path.exists(state_path):
            with open(state_path) as f:
                llh_by_k = {int(k): v for k, v in json.load(f).items()}

    llh_old: Optional[float] = None
    chosen = kset[-1]
    best_fit: Optional[FitResult] = None
    for k in kset:
        if k in llh_by_k:                           # journaled on a prior run
            res_llh = llh_by_k[k]
        else:
            ckpt_k = None
            ckpt_dir = None
            if state_dir is not None and (
                cfg.checkpoint_every > 0
                # the device-annealing path checkpoints at REPAIR-ROUND
                # granularity (round 6) regardless of checkpoint_every
                # (which governs within-fit cadence only)
                or (cfg.quality_mode and device_annealing)
            ):
                from bigclam_tpu.utils.checkpoint import CheckpointManager

                ckpt_dir = os.path.join(state_dir, f"k_{k:06d}")
                ckpt_k = CheckpointManager(ckpt_dir)
            F0k = seeding.init_F(
                g, seeds, cfg.replace(num_communities=k), k_rngs[k]
            )
            F0 = np.zeros((g.num_nodes, k_max))
            F0[:, :k] = F0k                         # columns >= k stay zero
            if cfg.quality_mode and device_annealing:
                # per-K device-resident annealing: one upload per K (the
                # seeded F0 is host-built), no per-cycle round trips.
                # Round 6: the k_<K> dir carries REPAIR-ROUND checkpoints
                # (fit_quality_device wires the discrete stage through
                # <dir>/repair); within-cycle saves remain host-path-only
                from bigclam_tpu.models.quality import fit_quality_device

                qres = fit_quality_device(
                    model, F0, kick_cols=k, key_salt=k, checkpoints=ckpt_k,
                    resume=resume,
                )
                res = qres.fit
            elif cfg.quality_mode:
                # quality sweep: each K trains with the annealing schedule
                # (models.quality); the kick is restricted to the active K
                # columns so the >= k padding stays on its inert zeros. The
                # relax/restore step swap is cached (step_cfg_key), so the
                # whole sweep still compiles each step exactly once.
                from bigclam_tpu.models.quality import fit_quality

                qres = fit_quality(
                    model, F0, checkpoints=ckpt_k, kick_cols=k,
                    resume=resume,
                )
                res = qres.fit
            else:
                res = model.fit(F0, checkpoints=ckpt_k, resume=resume)
            res_llh = res.llh
            llh_by_k[k] = res_llh
            best_fit = res
            if state_path is not None and is_primary():
                with open(state_path + ".tmp", "w") as f:
                    json.dump({str(kk): v for kk, v in llh_by_k.items()}, f)
                os.replace(state_path + ".tmp", state_path)
            if ckpt_dir is not None and is_primary():
                # journaled: within-K checkpoints are spent (and must never
                # leak into a later K, whose model shape they would match)
                shutil.rmtree(ckpt_dir, ignore_errors=True)
        if callback is not None:
            callback(k, res_llh)
        if llh_old is not None and llh_old != 0.0:
            if (1.0 - res_llh / llh_old) < cfg.ksweep_tol:
                chosen = k                          # KforC = current K (v4:260)
                break
        llh_old = res_llh
    return SweepResult(
        chosen_k=chosen, llh_by_k=llh_by_k, kset=kset, best_fit=best_fit
    )
