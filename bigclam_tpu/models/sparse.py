"""Single-chip BigCLAM trainer on the sparse top-M membership
representation (ops.sparse_members; DESIGN.md "Sparse membership
representation").

Mirrors models.bigclam.BigClamModel's surface — init_state / fit /
fit_state / rebuild_step / checkpointing — over the two-array sparse
state (member ids + weights). The shared fit loop (run_fit_loop),
buffer donation, non-finite rollback snapshots, and the fault-injection
sites all work unchanged: SparseTrainState names its weight array `F`
and is a flat NamedTuple the donation/snapshot tree-maps recycle like
any other state.

One outer iteration:

    [support update every cfg.support_every iters: admit candidate
     communities from neighbor lists, keep top-M]
    -> sparse grad/LLH pass -> 16-candidate Armijo pass (member lookup
       shared) -> masked Jacobi update -> sparse sumF scatter

all inside one jitted step; the support update rides a lax.cond keyed
on the iteration counter so the host loop stays oblivious.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.models.bigclam import (
    FitResult,
    MemoryAccountedModel,
    _round_up,
    _ScaleRebuilder,
    finalize_step,
    log_engaged_path,
    prepare_graph,
    random_init_F,
    run_fit_loop,
    step_cfg_key,
)
from bigclam_tpu.ops import sparse_members as sm
from bigclam_tpu.ops.sparse_members import SparseTrainState


def effective_m(cfg: BigClamConfig) -> int:
    """The per-node slot count actually allocated: cfg.sparse_m clamped
    to K (more slots than communities cannot hold anything; M >= K is
    exactly the dense-parity regime)."""
    return max(1, min(int(cfg.sparse_m), int(cfg.num_communities)))


def make_sparse_train_step(
    edges, blocks, cfg: BigClamConfig, k_pad: int, m: int,
    n_live: Optional[int] = None,
):
    """One jitted sparse iteration (support update -> grad/LLH ->
    candidates -> Armijo -> sparse sumF); same step_fn contract as
    make_train_step (finalize_step attaches .jitted / .donating).
    `n_live` is the LIVE node count for the support-churn denominator
    (padding rows have no edges and never admit, so the padded slot
    count would dilute the fraction); None falls back to padded."""
    sup_every = max(int(cfg.support_every), 1)
    from bigclam_tpu.ops import diagnostics as dx

    def step(state: SparseTrainState) -> SparseTrainState:
        ids0, w0, it = state.ids, state.F, state.it

        def do_support(args):
            i, ww = args
            return sm.support_update(i, ww, blocks, m, k_pad)

        ids, w = jax.lax.cond(
            it % sup_every == 0, do_support, lambda a: a, (ids0, w0)
        )
        # recompute rather than carry: a support update may DROP members
        # (M < K), and the O(K) scatter is noise next to the edge sweep
        sumF = sm.sparse_sumF(ids, w, k_pad)
        grad, node_llh = sm.sparse_grad_llh(
            ids, w, sumF, edges, cfg, k_pad
        )
        llh_cur = node_llh.sum()
        cand_nbr = sm.sparse_candidates(ids, w, grad, edges, cfg, k_pad)
        w_new, hist = sm.sparse_armijo_update(
            ids, w, sumF, grad, node_llh, cand_nbr, cfg, k_pad
        )
        sumF_new = sm.sparse_sumF(ids, w_new, k_pad)
        health = None
        if dx.health_on(cfg):
            # support churn: fraction of LIVE member-id slots the
            # admission pass rewrote — one cheap comparison per step,
            # LATCHED (max-since-last-sample) so an off-cadence
            # admission burst still shows in the next health sample;
            # single-chip has no collectives, the cap slots stay NA.
            # The expensive grad stats ride the pack's cadence cond.
            slots = float(max(n_live or ids.shape[0], 1) * m)
            churn = jnp.sum((ids != ids0).astype(jnp.float32)) / slots
            extras, carry = dx.latch_extras(
                state.health, {"support_churn": churn}
            )
            health = dx.health_pack(
                cfg, it, w, w_new, sumF_new, hist, grad=grad,
                extras=extras, skip_carry=carry,
            )
        return SparseTrainState(
            F=w_new,
            ids=ids,
            sumF=sumF_new,
            llh=llh_cur.astype(w.dtype),
            it=it + 1,
            accept_hist=hist,
            comm_ids=state.comm_ids,
            comm_dense=state.comm_dense,
            health=health,
        )

    from bigclam_tpu.ops.sparse_members import merge_pallas_want

    merge = "merge_pallas" if merge_pallas_want(cfg) else "xla"
    return finalize_step(step), f"sparse_{merge}"


class SparseBigClamModel(MemoryAccountedModel):
    """Single-chip sparse-representation trainer.

    Usage:
        model = SparseBigClamModel(graph, cfg)   # cfg.representation="sparse"
        result = model.fit(F0)                   # F0: dense (N, K) init,
                                                 # sparsified to top-M rows
    """

    def __init__(self, g: Graph, cfg: BigClamConfig, dtype=None):
        if cfg.representation != "sparse":
            raise ValueError(
                "SparseBigClamModel requires cfg.representation='sparse' "
                f"(got {cfg.representation!r})"
            )
        if cfg.min_f != 0.0:
            # sentinel slots rely on clip(0 + eta*0) staying 0, exactly
            # like dense padding inertness
            raise ValueError(
                f"sparse representation requires min_f == 0.0 "
                f"(got {cfg.min_f})"
            )
        self.g = g
        self.cfg = cfg
        self.dtype = dtype or (
            jnp.float64 if cfg.dtype == "float64" else jnp.float32
        )
        self.m = effective_m(cfg)
        self.k_pad = cfg.num_communities
        self.block_b = sm.pick_block_b(
            cfg.sparse_score_block, g.num_nodes, self.m,
            g.num_directed_edges / max(g.num_nodes, 1),
        )
        self._setup()
        self._step_cache = {self._step_key(): (self._step, self.engaged_path)}
        self.path_reason = self._path_reason()
        from bigclam_tpu.obs import note_step_build

        note_step_build(cfg, type(self).__name__)
        log_engaged_path(
            type(self).__name__, self.engaged_path, self.path_reason
        )
        # static memory model (obs.memory, ISSUE 12): M-not-K state
        # scaling as a model, not just a gate assertion. The sharded
        # subclass re-bakes when the cap refinement moves its
        # collective layout (_set_comm).
        self._bake_memory_model()

    def _setup(self) -> None:
        """Build padding, device edge/block buffers, and the train step
        (subclass hook: the sharded trainer swaps the whole schedule)."""
        g, cfg = self.g, self.cfg
        self.n_pad = _round_up(max(g.num_nodes, 1), self.block_b)
        # edge chunks bound by the (chunk, M) gather width — M, not K
        self._edges, n_pad = prepare_graph(
            g, cfg, node_multiple=self.block_b, dtype=self.dtype,
            k_pad=self.m,
        )
        assert n_pad == self.n_pad, (n_pad, self.n_pad)
        self._blocks = sm.build_support_blocks(
            g, self.n_pad, self.block_b, dtype=self.dtype
        )
        self._step, self.engaged_path = self._make_step()

    def _path_reason(self) -> str:
        return f"representation=sparse M={self.m}"

    # --------------------------------------- memory accounting (ISSUE 12)
    def _graph_device_arrays(self) -> dict:
        e, b = self._edges, self._blocks
        return {
            "graph/edges_src": e.src,
            "graph/edges_dst": e.dst,
            "graph/edges_mask": e.mask,
            "graph/support_src": b.src_local,
            "graph/support_dst": b.dst,
            "graph/support_mask": b.mask,
        }

    def _memory_state_arrays(self, state) -> list:
        return [
            state.F, state.ids, state.sumF, state.llh, state.it,
            state.accept_hist, state.comm_ids, state.comm_dense,
            getattr(state, "health", None),
        ]

    def _build_memory_model(self):
        from bigclam_tpu.obs import memory as _mem

        cfg = self.cfg
        return _mem.sparse_memory_model(
            self.n_pad,
            self.m,
            self.k_pad,
            self._memory_dp(),
            jnp.dtype(self.dtype).itemsize,
            len(cfg.step_candidates),
            self._graph_buffer_bytes(),
            health_on=int(getattr(cfg, "health_every", 0) or 0) > 0,
            donate=bool(cfg.donate_state),
            rollback=int(getattr(cfg, "rollback_budget", 0) or 0) > 0,
            comms=getattr(self, "comms", None),
            model=type(self).__name__,
        )

    def _make_step(self):
        return make_sparse_train_step(
            self._edges, self._blocks, self.cfg, self.k_pad, self.m,
            n_live=self.g.num_nodes,
        )

    def _step_key(self):
        return step_cfg_key(self.cfg)

    def rebuild_step(self) -> None:
        """Same contract as BigClamModel.rebuild_step (step cache keyed
        by step_cfg_key; used by the rollback ladder's step_scale)."""
        key = self._step_key()
        if key not in self._step_cache:
            self._step_cache[key] = self._make_step()
            from bigclam_tpu.obs import note_step_build

            note_step_build(self.cfg, type(self).__name__)
        self._step, self.engaged_path = self._step_cache[key]

    # ------------------------------------------------------------ state
    def init_state(
        self, F0: Optional[np.ndarray] = None
    ) -> SparseTrainState:
        n, k = self.g.num_nodes, self.cfg.num_communities
        if F0 is None:
            from bigclam_tpu.models.bigclam import rowkeyed_init_F

            F0 = rowkeyed_init_F(self.g, self.cfg)
        assert F0.shape == (n, k), (F0.shape, (n, k))
        ids, w, truncated = sm.from_dense(
            np.asarray(F0), self.m, self.k_pad, self.n_pad
        )
        if truncated:
            import sys

            from bigclam_tpu.obs import telemetry as _obs

            tel = _obs.current()
            if tel is not None:
                tel.event(
                    "model_build", model="SparseBigClamModel",
                    path="init_truncated", reason=f"{truncated} entries",
                )
            import os

            if os.environ.get("BIGCLAM_QUIET") != "1":
                print(
                    f"[bigclam] sparse init: {truncated} positive F0 "
                    f"entries beyond top-{self.m} dropped",
                    file=sys.stderr,
                )
        self._on_init_sparsified(ids)
        return self.reset_state(*self._place(ids, w))

    def _place(self, ids: np.ndarray, w: np.ndarray):
        """Host arrays -> device (subclass hook: sharded placement)."""
        return jnp.asarray(ids), jnp.asarray(w, self.dtype)

    def _on_init_sparsified(self, ids: np.ndarray) -> None:
        """Hook: the sharded trainer sizes its sparse-allreduce buffers
        from the initial per-shard touched counts here."""

    def reset_state(self, ids: jax.Array, w: jax.Array) -> SparseTrainState:
        from bigclam_tpu.ops import diagnostics as dx

        return SparseTrainState(
            F=w,
            ids=ids,
            sumF=sm.sparse_sumF(ids, w, self.k_pad),
            llh=jnp.asarray(-jnp.inf, w.dtype),
            it=jnp.zeros((), jnp.int32),
            accept_hist=jnp.zeros(
                len(self.cfg.step_candidates) + 1, jnp.int32
            ),
            comm_ids=jnp.zeros((), jnp.int32),
            comm_dense=jnp.zeros((), jnp.int32),
            health=dx.init_health(self.cfg),
        )

    def extract_F(self, state: SparseTrainState) -> np.ndarray:
        """Densify the live (num_nodes, K) block on the host (the
        extraction/eval pipelines are dense consumers)."""
        return sm.to_dense(
            np.asarray(state.ids), np.asarray(state.F),
            self.g.num_nodes, self.cfg.num_communities,
        )

    def health_sig(self, state: SparseTrainState) -> jax.Array:
        """(N_pad,) int32 top-community signature from the member lists
        (obs.health churn snapshot; -1 on empty rows)."""
        from bigclam_tpu.ops.diagnostics import sparse_top_community

        return sparse_top_community(state.ids, state.F)

    # ------------------------------------------------------ checkpoints
    def _ckpt_meta(self) -> dict:
        return {
            "num_nodes": self.g.num_nodes,
            "num_directed_edges": self.g.num_directed_edges,
            "k": self.cfg.num_communities,
            "n_pad": self.n_pad,
            "k_pad": self.k_pad,
            "seed": self.cfg.seed,
            # two-array sparse state: a dense-run checkpoint (or a
            # different M) must refuse, not silently densify
            "representation": "sparse",
            "sparse_m": self.m,
        }

    def _state_to_arrays(self, state: SparseTrainState) -> dict:
        return {
            "F": np.asarray(state.F),
            "ids": np.asarray(state.ids),
            "sumF": np.asarray(state.sumF),
            "llh": np.asarray(state.llh),
            "it": np.asarray(state.it),
        }

    def _state_from_arrays(self, arrays: dict) -> SparseTrainState:
        if "ids" not in arrays:
            raise ValueError(
                "checkpoint holds no member-id array: dense-representation "
                "checkpoints cannot resume a sparse fit"
            )
        ids = jnp.asarray(arrays["ids"], jnp.int32)
        w = jnp.asarray(arrays["F"], self.dtype)
        from bigclam_tpu.ops import diagnostics as dx

        return SparseTrainState(
            F=w,
            ids=ids,
            sumF=sm.sparse_sumF(ids, w, self.k_pad),
            llh=jnp.asarray(arrays["llh"], self.dtype),
            it=jnp.asarray(arrays["it"], jnp.int32),
            accept_hist=jnp.zeros(
                len(self.cfg.step_candidates) + 1, jnp.int32
            ),
            comm_ids=jnp.zeros((), jnp.int32),
            comm_dense=jnp.zeros((), jnp.int32),
            health=dx.init_health(self.cfg),
        )

    def _restore(self, checkpoints):
        """Sparse restore: strict meta equality (representation, M, K,
        graph, padding, seed) — the dense path's cross-padding re-pad
        nicety does not apply to slot arrays. Emits the same `restore`
        telemetry event as models.bigclam.restore_checkpoint."""
        restored = checkpoints.restore()
        if restored is None:
            return None, ()
        ckpt_step, arrays, meta = restored
        from bigclam_tpu.obs import telemetry as _obs

        tel = _obs.current()
        if tel is not None:
            tel.event("restore", step=int(ckpt_step))
        expected = self._ckpt_meta()
        for key, val in expected.items():
            got = meta.get(key)
            if got is None and not val:
                continue
            if got != val:
                raise ValueError(
                    f"checkpoint incompatible with this sparse run: "
                    f"{key}={got} in checkpoint vs {val} expected "
                    f"(dir: {checkpoints.directory})"
                )
        return (
            self._state_from_arrays(arrays),
            tuple(meta.get("llh_history", ())),
        )

    # -------------------------------------------------------------- fit
    def fit(
        self,
        F0: np.ndarray,
        callback: Optional[Callable[[int, float], None]] = None,
        checkpoints=None,
        resume: bool = True,
    ) -> FitResult:
        state, hist = self.init_state(F0), ()
        if checkpoints is not None and resume:
            restored, hist = self._restore(checkpoints)
            if restored is not None:
                state = restored
        rebuilder = _ScaleRebuilder(self)
        try:
            return run_fit_loop(
                self._step,
                state,
                self.cfg,
                callback,
                self.extract_F,
                checkpoints=checkpoints,
                state_to_arrays=self._state_to_arrays,
                initial_hist=hist,
                ckpt_meta=self._ckpt_meta(),
                rebuild_step=rebuilder,
                health_sig=self.health_sig,
                health_n=self.g.num_nodes,
            )
        finally:
            rebuilder.restore()

    def fit_state(
        self,
        state: SparseTrainState,
        callback: Optional[Callable[[int, float], None]] = None,
    ):
        """State-resident convergence loop: the converged SparseTrainState
        comes back with NO dense materialization anywhere."""
        rebuilder = _ScaleRebuilder(self)
        try:
            return run_fit_loop(
                self._step, state, self.cfg, callback, None,
                rebuild_step=rebuilder,
                health_sig=self.health_sig,
                health_n=self.g.num_nodes,
            )
        finally:
            rebuilder.restore()

    def random_init(self, seed: Optional[int] = None) -> np.ndarray:
        return random_init_F(self.g, self.cfg, seed)

    def foldin_rows(
        self,
        state: SparseTrainState,
        nodes,
        max_deg: Optional[int] = None,
        max_iters: Optional[int] = None,
        conv_tol: Optional[float] = None,
        init: str = "own",
    ):
        """Batched fold-in against the frozen sparse state (the sparse
        twin of BigClamModel.foldin_rows, ISSUE 14 — see its docstring
        for the init="own"/"mean" warm-start semantics): neighbor member
        lists are densified per query batch (ops.foldin
        .densify_member_rows — only the B*D query window pays K columns,
        the state stays M-sized), then the identical row ascent runs.
        Returns dense (rows (B, K), llh (B,), iters (B,))."""
        from bigclam_tpu.ops import foldin as fi
        from bigclam_tpu.serve.snapshot import pad_neighbor_batch

        nodes = np.asarray(nodes, np.int64)
        nbr_ids, nbr_mask, _ = pad_neighbor_batch(
            self.g.indptr, self.g.indices, nodes, max_deg=max_deg
        )
        dt = state.F.dtype
        nbr_rows = fi.densify_member_rows(
            state.ids, state.F, jnp.asarray(nbr_ids), self.k_pad
        )
        mask = jnp.asarray(nbr_mask, dt)
        own = fi.densify_rows(
            state.ids, state.F, jnp.asarray(nodes), self.k_pad
        )
        sumF_others = state.sumF[None, :] - own
        rows0 = (
            own if init == "own"
            else fi.neighbor_mean_rows(nbr_rows, mask)
        )
        rows0 = jnp.array(rows0)        # donated: never alias live state
        fit = fi.make_foldin_fit(
            self.cfg, max_iters=max_iters, conv_tol=conv_tol
        )
        rows, llh, iters = fit(rows0, nbr_rows, mask, sumF_others)
        k = self.cfg.num_communities
        return (
            np.asarray(rows)[:, :k],
            np.asarray(llh),
            np.asarray(iters),
        )

    def refit_commit(
        self, state: SparseTrainState, nodes, rows: np.ndarray
    ) -> SparseTrainState:
        """Sparse twin of BigClamModel.refit_commit (ISSUE 15): freshly
        folded DENSE rows are re-sparsified to top-M member lists
        (ops.sparse_members.from_dense — the init-time truncation rule)
        and scattered into the slot arrays; sumF re-reduces from the
        member lists so it can never drift from the truncation."""
        nodes_arr = jnp.asarray(np.asarray(nodes, np.int64))
        ids_b, w_b, _ = sm.from_dense(
            np.asarray(rows, np.float64), self.m, self.k_pad, len(nodes)
        )
        ids = state.ids.at[nodes_arr].set(jnp.asarray(ids_b))
        w = state.F.at[nodes_arr].set(jnp.asarray(w_b, self.dtype))
        return state._replace(
            ids=ids, F=w, sumF=sm.sparse_sumF(ids, w, self.k_pad)
        )

    def warm_start_refit(self, F_prev: np.ndarray, touched, **kw):
        """Incremental warm-start refit restricted to touched rows +
        halo (ISSUE 15; see models.refit.warm_start_refit) — the state
        stays M-sized, only each fold-in query window densifies."""
        from bigclam_tpu.models.refit import warm_start_refit

        return warm_start_refit(self, F_prev, touched, **kw)

    def state_nbytes(self, state: Optional[SparseTrainState] = None) -> int:
        """Affiliation-state footprint in bytes (ids + weights + sumF):
        the figure the memory-pinned gate asserts scales with M, not K.
        Without a state it is computed from the model's shapes — same
        figure, no host-side sparsification pass needed."""
        if state is None:
            isz = np.dtype(self.dtype).itemsize
            return int(
                self.n_pad * self.m * (isz + 4)   # weights f32/f64 + int32 ids
                + self.k_pad * isz                # sumF
            )
        return int(
            state.F.size * state.F.dtype.itemsize
            + state.ids.size * state.ids.dtype.itemsize
            + state.sumF.size * state.sumF.dtype.itemsize
        )
