"""AGM synthetic graph generator: sample a graph from a planted F.

The Community-Affiliation Graph Model underlying BigCLAM (Yang & Leskovec
WSDM'13): P(edge u,v) = 1 - exp(-F_u . F_v). Not present in the reference —
built new as the recovery-test harness (generate from a planted F, fit, score
F1 against the planted communities), used by tests/test_eval.py.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from bigclam_tpu.graph.csr import Graph
from bigclam_tpu.graph.ingest import graph_from_edges


def sample_graph(
    F: np.ndarray, rng: Optional[np.random.Generator] = None
) -> Graph:
    """Sample an undirected simple graph with P(u~v) = 1 - exp(-F_u.F_v).

    Dense O(N^2) sampling — intended for test-scale graphs.
    """
    rng = rng or np.random.default_rng(0)
    F = np.asarray(F, dtype=np.float64)
    n = F.shape[0]
    P = 1.0 - np.exp(-(F @ F.T))
    iu, ju = np.triu_indices(n, k=1)
    hit = rng.random(iu.shape[0]) < P[iu, ju]
    edges = np.stack([iu[hit], ju[hit]], axis=1)
    return graph_from_edges(edges, num_nodes=n)


def sample_planted_graph(
    n: int,
    k: int,
    p_in: float = 0.15,
    overlap: int = 0,
    rng: Optional[np.random.Generator] = None,
) -> tuple[Graph, List[List[int]]]:
    """Sparse AGM-style sampler for planted equal blocks at community scale.

    Exploits the planted-partition structure (edges only inside blocks):
    per block, the edge count is Binomial(C(s,2), p_in) and pairs are drawn
    uniformly — O(E) total, unlike sample_graph's dense O(N^2) pass. With
    `overlap`, the first `overlap` nodes of each block also join the next
    block. Returns (graph, ground-truth communities).
    """
    rng = rng or np.random.default_rng(0)
    size = n // k
    assert size >= 2, (n, k)
    truth: List[List[int]] = []
    srcs, dsts = [], []
    for c in range(k):
        members = np.arange(c * size, min((c + 1) * size, n))
        if overlap:
            members = np.concatenate(
                [members, (members[:overlap] + size) % n]
            )
        s = members.size
        pairs = s * (s - 1) // 2
        m = rng.binomial(pairs, p_in)
        if m:
            # m uniform pairs (self-pairs dropped, duplicates deduped by
            # graph_from_edges) — realized density lands slightly under
            # p_in, which recovery tests must not depend on exactly
            a = rng.integers(0, s, m)
            b = rng.integers(0, s, m)
            keep = a != b
            srcs.append(members[a[keep]])
            dsts.append(members[b[keep]])
        truth.append(sorted(set(members.tolist())))
    if srcs:
        edges = np.stack(
            [np.concatenate(srcs), np.concatenate(dsts)], axis=1
        )
    else:
        edges = np.empty((0, 2), np.int64)
    return graph_from_edges(edges, num_nodes=n), truth


def planted_partition_F(
    n: int,
    k: int,
    strength: float = 3.0,
    overlap: int = 0,
) -> tuple[np.ndarray, List[List[int]]]:
    """A deterministic planted F with k equal blocks of n//k nodes at the
    given membership strength; `overlap` extra nodes per community straddle
    the next block. Randomness enters via sample_graph's rng, not here.
    Returns (F, ground-truth communities as node-id lists)."""
    F = np.zeros((n, k))
    size = n // k
    truth: List[List[int]] = []
    for c in range(k):
        members = list(range(c * size, min((c + 1) * size, n)))
        extra = [(m + size) % n for m in members[:overlap]]
        for u in members + extra:
            F[u, c] = strength
        truth.append(sorted(members + extra))
    return F, truth
