"""Large-K quality mode: noise-floor init + restart annealing.

NOT reference behavior — a flag-gated extension (cfg.quality_mode, default
off = exact parity). Why it exists (PARITY.md "Known reference-algorithm
behavior"): with faithful semantics, BASELINE config 3 (com-Amazon K=5000 +
F1) is unreachable — on planted AGM graphs at larger K the fit lands at
F1 ~ 0.1 while the planted optimum is a fixed point with far higher LLH.

Round-4 diagnosis (verified on planted N=6000 K=30, p_in=0.15):

  * The optimizer is NOT the problem: initialized with one seed per planted
    block, the faithful fit recovers F1 = 1.000 in 5 iterations.
  * The failure is seed coverage + frozen rows: the conductance top-K seeds
    cover only ~17/30 blocks (nominee ranking inside near-uniform blocks is
    arbitrary), and every node with an all-zero F row is FROZEN forever —
    its gradient is -sumF <= 0, which clips back to the zero row. This is
    the same property that makes node/K padding inert (ops.objective
    padding conventions), applied to real nodes: unseeded blocks can never
    acquire mass, and the reference behaves identically by construction
    (Bigclamv2.scala:99-102 clamps at MIN_F_=0).

Fix, in two parts (both only meaningful together):

  1. Noise floor: add U(0, init_noise) to the seeded F0. Every row becomes
     live; nodes in the same block share neighbors, so the first gradient
     sweeps amplify the correlated noise toward block indicators (the
     spectral alignment of A's top eigenvectors with community structure).
     The scale matters: 0.01 recovers F1 = 0.80 where 0.1 drowns the seed
     signal (F1 = 0.15) and 0 freezes (F1 = 0.13).
  2. Restart annealing: re-kick the CONVERGED F with the same small noise
     and refit. Each converged state has rows clipped to 0 that the kick
     revives; measured on the probe, cycles improve monotonically
     (F1 0.78 -> 0.83 over 5 cycles, LLH -642K -> -575K toward the planted
     -480K). Cycles whose converged LLH does not improve are reverted, so
     across cycles the kept LLH is non-decreasing; the loop stops when the
     relative gain falls below restart_tol.

Round-4 additions (both measured on planted N=2400 K=100 p_in=0.3,
24-node blocks — the com-Amazon-class small-community regime):

  3. Coverage-aware seeding (ops.seeding.select_seeds_covering,
     auto-engaged by conductance_seeds when quality_mode is on): the raw
     top-K nominee ranking piles seeds into a fraction of the communities
     (58/100 blocks covered); the greedy exclusion walk tiles the graph
     (92/100 at hops=2) and lifts quality F1 0.742 -> 0.894.
  4. MAX_P_ relaxation during annealing cycles: the probability clip
     bounds the gradient's 1/(1-p) neighbor amplification at
     amp = 1/(1-max_p), and a noise-level column entry at node u grows
     only when deg(u)*amp > N (its neighbor term must beat -sumF). The
     parity 0.9999 (amp=1e4) therefore freezes EVERY kick once
     N > 1e4*avg_deg — exactly the K=5000 gate failure
     (QUALITY_K5000_r04.json: N=120000, avg_deg 5.7, 4 gainless cycles,
     F1 0.001); measured the other way, pinning amp=100 at N=2400
     collapses quality F1 to the faithful 0.045. fit_quality relaxes
     max_p to 1 - avg_deg/(16*N) (>= parity, <= 1-1e-15 — the f64
     representability of max_p; the kernels' -expm1(-x) form of 1-p has
     no f32 floor, ops.objective.edge_terms),
     rebuilds the train step (model.rebuild_step — same kernels, new
     clip constant), and restores the parity step afterwards.

Round-4 addition, part 5 — discrete repair (cfg.quality_repair, default
on with quality mode): two defect classes are STABLE under the continuous
dynamics because gradients cannot move a whole column across the graph —
a fat column merged over disconnected regions, and a pair of columns
fragmenting one dense region. After the annealing loop,
repair_communities merges dense fragment pairs (freeing columns) and
re-seeds the freed columns on fat columns' extra components; a short
re-annealing polish follows and the result is kept only if LLH improves.
Measured on the N=2400 probe: F1 0.894 -> 0.914, LLH -32037 -> -31692
(planted optimum -31429).

Round-5 addition, part 6 — atomize re-tiling (cfg.quality_reassign,
default on; atomize_reassign): the round-5 planted anchor
(MIDSCALE_ANCHOR_r05.json) proved the annealing plateau at 24-node
blocks sits 7-10% of LLH BELOW a stable optimum band (planted F refits
to itself at -156.59K while the quality run plateaus at -173.8K), so
the plateau is an optimizer gap, not a model-family property. The
plateau's defect class is SHIFTED partitions (each column = one block +
a shard of a neighbor), which merge/split repair cannot unshift. The
atomize move shatters every thresholded column into its graph
components, dedupes majority-overlapping atoms, re-seeds the K columns
on the largest atoms at their measured-density AGM strength, refits,
and keeps on LLH gain (measured: -173.8K -> -156.26K in 2 accepted
rounds at N=12K K=500 p_in=0.3). Runs inside the discrete stage
(_repair_stage) interleaved with merge/split, every round LLH-gated.

Round-6 addition — device residency for the discrete stage: the six
mechanisms above made quality mode the dominant cost at midscale (644.7s
vs 17.7s faithful at N=12K K=500, QUALITY_MIDSCALE_r05.json) because
atomize/repair ran as per-column host scipy component scans and the
device path re-uploaded F for every discrete refit. The component scans
now dispatch to a batched on-device label-propagation primitive
(ops.components — one jitted pass over all thresholded columns with
membership/density stats fused in; the scipy path stays the oracle and
small-N fallback), and fit_quality_device keeps F resident through the
whole atomize->polish->repair cycle (_repair_stage_device: scatter-edit
repairs, state-resident refits, at most one F download per repair round,
repair-round checkpointing). Per-stage wall-clock + transfer counts ride
QualityResult.stages (utils.profiling.StageProfile).

Works with every trainer (single-chip / all-gather sharded / ring). The
required trainer surface is `.cfg`, `.g`, `.fit(F0, callback=)`, and
`.rebuild_step()` (invoked whenever the max_p relaxation engages — the
common case at real graph sizes); the schedule and kernels stay whatever
the model compiled. fit_quality's noise kick is host-side O(N*K) — fine
up to com-Amazon scale; past that, `fit_quality_device` (below) keeps
the whole schedule device-resident (adds `.init_state`/`.reset_state`/
`.fit_state`/`.extract_F` to the trainer surface) with an on-device
jax.random kick, so F never leaves the chips between cycles.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import warnings
from typing import Callable, List, Optional, Tuple

import numpy as np

from bigclam_tpu.models.bigclam import FitResult
from bigclam_tpu.utils.dist import is_primary


def _cycle_event(cycle: int, llh: float, kept: bool, iters: int) -> None:
    """Telemetry for one annealing cycle (host and device schedules share
    this): `cycle` events make the restart dynamics — which kicks were
    kept, how long each cycle annealed — readable from events.jsonl, and
    each completed cycle beats the stall heartbeat."""
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is None:
        return
    tel.event(
        "cycle", cycle=int(cycle), llh=float(llh), kept=bool(kept),
        iters=int(iters),
    )
    if tel.heartbeat is not None:
        tel.heartbeat.beat(cycle=int(cycle), llh=float(llh))


def auto_quality_max_p(
    num_nodes: int, avg_deg: float, floor: float = 0.0
) -> float:
    """The auto MAX_P_ relaxation rule (single source — quality_gate.py
    records it too): amp = 16*N/avg_deg covers node degrees down to
    avg/16. `floor` is the parity max_p (never relax BELOW it); the
    1-1e-15 ceiling applies to the combined value — even a floor above it
    is clamped. The ceiling is where max_p itself stops being f64-
    representable (1 - 1e-16 rounds to 1.0 and 1-max_p = 0 poisons the
    clip); the KERNELS no longer impose a floor at all — edge_terms forms
    1-p as -expm1(-x), exact to f32 relative eps at any amplification
    (see config.quality_max_p)."""
    amp = 16.0 * num_nodes / max(avg_deg, 1.0)
    return min(max(floor, 1.0 - 1.0 / amp), 1.0 - 1e-15)


def _relax_params(model, n_live: int) -> Tuple[float, float]:
    """(relaxed MAX_P_, kick scale eps) for this model's graph — shared by
    the host (fit_quality) and device (fit_quality_device) annealing loops.

    MAX_P_ relaxation: the clip caps the gradient's 1/(1-p) neighbor
    amplification; a noise-level column entry at node u only grows when
    deg(u)*amp > N (its neighbor term must beat -sumF), so the parity
    0.9999 freezes every kick dead once N > 1e4*avg_deg (the K=5000
    gate's original failure: 4 gainless cycles, F1 0.001). Auto rule in
    auto_quality_max_p; explicit overrides validated against the f64
    representability ceiling here. Kick scale: the kick's per-column
    sumF contribution
    (~eps*N/2) must stay comparable to one seeded ego-net column's mass
    (~avg_degree + 1) regardless of N (see config.init_noise).
    """
    cfg = model.cfg
    avg_deg = model.g.num_directed_edges / max(model.g.num_nodes, 1)
    max_p_q = cfg.quality_max_p
    if max_p_q is None:
        max_p_q = auto_quality_max_p(
            model.g.num_nodes, avg_deg, floor=cfg.max_p
        )
    elif not (0.0 < max_p_q <= 1.0 - 1e-15):
        # beyond 1-1e-15 the f64 value of max_p rounds toward 1.0 and the
        # host-computed clip floor 1-max_p collapses to 0: log(0) = -inf
        # poisons every cycle's LLH and NaN defeats the patience stop —
        # fail fast instead of burning restart_cycles of chip time
        raise ValueError(
            f"quality_max_p={max_p_q} out of range (need 0 < p <= 1-1e-15, "
            "the f64 representability floor of 1-max_p)"
        )
    elif max_p_q < cfg.max_p:
        # sub-floor pinning TIGHTENS the clip mid-quality-run; measured to
        # collapse recovery (F1 0.045 at amp=100, N=2400). Legal as an
        # explicit measurement hook, but never what a production run wants.
        warnings.warn(
            f"quality_max_p={max_p_q} is BELOW the parity clip "
            f"max_p={cfg.max_p}: the quality run will use a TIGHTER clip "
            "than the faithful fit (gradient amplification capped at "
            f"{1.0 / (1.0 - max_p_q):.3g}). This collapses recovery except "
            "as a deliberate measurement hook.",
            stacklevel=2,
        )
    eps = (
        cfg.init_noise
        if cfg.init_noise is not None
        else min(
            0.02, cfg.init_noise_mass * (avg_deg + 1.0) / max(n_live, 1)
        )
    )
    return max_p_q, eps


def _graph_components(mem: np.ndarray, indptr, indices) -> List[List[int]]:
    """Connected components of the subgraph induced by `mem` — shared by
    repair_communities (fat-column splits) and atomize_reassign (which
    calls it for EVERY thresholded column, so per-edge Python scans are
    out of budget at com-Amazon K~5k). Vectorized: induced-subgraph CSR
    via one flat neighbor gather + searchsorted remap, then
    scipy.sparse.csgraph.connected_components; iterative-BFS fallback
    when scipy is absent."""
    m = np.asarray(mem, np.int64)
    if m.size == 0:
        return []
    try:
        from scipy.sparse import csr_matrix
        from scipy.sparse.csgraph import connected_components
    except ImportError:
        return _graph_components_bfs(m, indptr, indices)
    nbr = _gather_neighbors(m, indptr, indices)
    deg = indptr[m + 1] - indptr[m]
    srcs = np.repeat(np.arange(m.size), deg)
    loc = np.searchsorted(m, nbr)              # mem is sorted (flatnonzero)
    ok = (loc < m.size) & (m[np.minimum(loc, m.size - 1)] == nbr)
    a = csr_matrix(
        (np.ones(int(ok.sum()), np.int8), (srcs[ok], loc[ok])),
        shape=(m.size, m.size),
    )
    _, labels = connected_components(a, directed=False)
    order = np.argsort(labels, kind="stable")
    bounds = np.flatnonzero(np.r_[True, np.diff(labels[order]) != 0])
    return [
        m[order[lo:hi]].tolist()
        for lo, hi in zip(bounds, np.r_[bounds[1:], order.size])
    ]


def _graph_components_bfs(mem: np.ndarray, indptr, indices) -> List[List[int]]:
    """Pure-Python fallback (no scipy): iterative BFS over CSR adjacency."""
    mset = set(mem.tolist())
    seen, comps = set(), []
    for s0 in mem.tolist():
        if s0 in seen:
            continue
        stack, comp = [int(s0)], []
        seen.add(s0)
        while stack:
            u = stack.pop()
            comp.append(u)
            for v in indices[indptr[u] : indptr[u + 1]]:
                v = int(v)
                if v in mset and v not in seen:
                    seen.add(v)
                    stack.append(v)
        comps.append(comp)
    return comps


def _gather_neighbors(nodes: np.ndarray, indptr, indices) -> np.ndarray:
    """Concatenated CSR adjacency of `nodes` in one flat fancy-index
    (position arange offset by each row's start) — the shared gather under
    both density counters below."""
    starts, ends = indptr[nodes], indptr[nodes + 1]
    deg = ends - starts
    total = int(deg.sum())
    if total == 0:
        return indices[:0]
    off = np.repeat(np.cumsum(deg) - deg, deg)
    return indices[np.repeat(starts, deg) + (np.arange(total) - off)]


def _internal_density(members: np.ndarray, indptr, indices) -> float:
    """Directed within-set edge density cnt/(s(s-1)) via one flat
    neighbor gather + sort-based isin."""
    m = np.asarray(members)
    if m.size < 2:
        return 0.0
    nbr = _gather_neighbors(m, indptr, indices)
    if nbr.size == 0:
        return 0.0
    cnt = int(np.isin(nbr, m).sum())
    return cnt / (m.size * (m.size - 1))


def _column_atoms_host(
    mask: np.ndarray, indptr, indices, min_comp: int
) -> List[Tuple[np.ndarray, Optional[float]]]:
    """Per-column atoms via the host scipy oracle (_graph_components) —
    the small-problem path and the parity reference for the device
    backend. Density is deferred (None): computed later for KEPT atoms
    only (one bounded _internal_density gather each)."""
    atoms: List[Tuple[np.ndarray, Optional[float]]] = []
    for c in range(mask.shape[1]):
        mem = np.flatnonzero(mask[:, c])
        if mem.size < min_comp:
            continue
        for comp in _graph_components(mem, indptr, indices):
            if len(comp) >= min_comp:
                atoms.append((np.sort(np.asarray(comp, np.int64)), None))
    return atoms


def _column_atoms_device(
    member_cols, g, min_comp: int, edges_dev=None
) -> List[Tuple[np.ndarray, Optional[float]]]:
    """Per-column atoms via the batched device label-propagation pass
    (ops.components): ONE jitted sweep covers every thresholded column,
    with component sizes and internal edge counts fused into it, so atom
    densities come from device reductions instead of host edge scans.
    `member_cols` is (C, N) bool — host OR device-resident (the device
    quality path passes the thresholded F slice without downloading F;
    only int32 label/stat arrays cross the host boundary)."""
    from bigclam_tpu.ops.components import (
        column_component_stats,
        components_from_labels,
        device_edges,
    )

    n = g.num_nodes
    if edges_dev is None:
        edges_dev = device_edges(g)
    labels, _sizes, counts = column_component_stats(
        member_cols, edges_dev[0], edges_dev[1], n
    )
    atoms: List[Tuple[np.ndarray, Optional[float]]] = []
    for c in range(labels.shape[0]):
        for comp in components_from_labels(labels[c], n, min_size=min_comp):
            s = comp.size
            # root label == min member id == comp[0] (components_from_labels
            # returns sorted members), so the fused stats index directly
            cnt = int(counts[c][comp[0]])
            d = cnt / (s * (s - 1)) if s > 1 else 0.0
            atoms.append((comp.astype(np.int64), d))
    return atoms


def _plan_atoms(
    atoms: List[Tuple[np.ndarray, Optional[float]]], n: int, ka: int
) -> List[Tuple[np.ndarray, Optional[float]]]:
    """Greedy largest-first dedupe + column assignment, shared by both
    component backends. Size ties break on min member id — a DETERMINISTIC
    order independent of the backend's collection order (host: scipy label
    order per column; device: root id per column), so the kept-atom set is
    identical across backends (pinned by test_components.py)."""
    atoms.sort(key=lambda a: (-len(a[0]), int(a[0][0])))
    kept: List[Tuple[np.ndarray, Optional[float]]] = []
    owner = np.full(n, -1, np.int64)
    for at, d in atoms:
        if len(kept) >= ka:
            break
        owners = owner[at]
        hit = owners[owners >= 0]
        if hit.size:
            _, counts = np.unique(hit, return_counts=True)
            if counts.max() >= 0.5 * at.size:
                continue          # majority-duplicate of a kept atom
        unowned = at[owners < 0]
        owner[unowned] = len(kept)
        kept.append((at, d))
    return kept


def _atom_strength(at: np.ndarray, d: Optional[float], indptr, indices
                   ) -> float:
    """AGM-consistent seed strength s = sqrt(-log(1-d)); the host backend
    defers density (d=None) to a bounded gather here."""
    if d is None:
        d = _internal_density(at, indptr, indices)
    d = min(max(float(d), 0.05), 0.95)
    return float(np.sqrt(-np.log1p(-d)))


def atomize_reassign(
    F: np.ndarray,
    g,
    delta: float,
    k_active: int,
    min_comp: int = 5,
    components: str = "auto",
) -> Tuple[np.ndarray, int]:
    """Discrete re-tiling move (cfg.quality_reassign): shatter every
    thresholded column into its graph components ("atoms"), dedupe atoms
    that majority-overlap an already-kept one (largest first), and
    re-seed the K columns on the kept atoms at their AGM-consistent
    strength s = sqrt(-log(1-d)) (d = atom's internal edge density — for
    a planted p_in=0.3 block this is the 0.597 the prototype validated).

    Why it exists (PARITY.md small-community account + the round-5
    planted anchor, MIDSCALE_ANCHOR_r05.json): annealing's plateau at
    24-node blocks consists of SHIFTED partitions — each column one
    block plus a shard of a neighbor — and gradient dynamics cannot
    unshift them, while the likelihood optimum band (planted F and its
    near-degenerate re-tilings) sits 7-10% of LLH above. Shattering to
    components + refit reaches that band (measured: -173.8K -> -156.26K
    at N=12K K=500 p_in=0.3, 2 accepted rounds). The caller refits and
    LLH-gates, so the move can only improve the model's own objective;
    at sub-identifiability p_in the extracted F1 may move either way
    (documented in PARITY.md) because the band is F1-degenerate.

    `components` picks the per-column connected-components backend
    (ops.components.components_backend): "host" = the scipy oracle (one
    induced-subgraph scan per column — the round-5 quality-stage cost),
    "device" = one batched label-propagation pass over all columns with
    fused density stats, "auto" = device above the work-size threshold.
    The two backends produce the same atom PARTITION; kept-atom choice can
    differ on exact size ties (both orders are valid and LLH-gated).

    Returns (reassigned F, number of kept atoms); num_atoms == 0 means
    nothing to do (no thresholded structure).
    """
    from bigclam_tpu.ops.components import components_backend

    F = np.asarray(F, np.float64)
    n = g.num_nodes
    ka = int(k_active)
    mask = F[:n, :ka] >= delta
    indptr, indices = g.indptr, g.indices
    if components_backend(n, ka, components) == "device":
        atoms = _column_atoms_device(mask.T, g, min_comp)
    else:
        atoms = _column_atoms_host(mask, indptr, indices, min_comp)
    if not atoms:
        return F.copy(), 0
    kept = _plan_atoms(atoms, n, ka)
    F_new = np.zeros_like(F)
    for c, (at, d) in enumerate(kept):
        F_new[at, c] = _atom_strength(at, d, indptr, indices)
    return F_new, len(kept)


def repair_plan(
    F: np.ndarray,
    g,
    delta: float,
    k_active: int,
    min_comp: int = 5,
    strength: float = 1.0,
    components: str = "auto",
    edges_dev=None,
) -> Tuple[list, int]:
    """Merge+split repair DETECTION over the thresholded communities —
    returns the edit list implementing one repair pass without touching F.

    Gradient dynamics cannot move a whole column across the graph, so two
    stable defect classes survive annealing (diagnosed on the planted
    probe): (a) a FAT column whose threshold members span multiple
    graph components (a merged community — its pieces share no edges),
    and (b) a PAIR of columns tiling one densely-connected region (two
    fragments of one community). The fix is one discrete move: merge each
    dense fragment pair into one column (freeing the other) and re-seed
    every freed column on an extra component of a fat column. The caller
    refits and accepts on LLH.

    Detection cost: O(N*K) vectorized mask/top-2 work (the dominant term
    — ~2e9 element ops at com-Amazon N=335K K=5120, seconds of host
    time) plus O(E) edge counting and component scans over fat columns
    only (batched on the device backend — see `components`, the same
    backend switch as atomize_reassign). Cross/within column edge counts
    use each node's top-2 above-threshold columns (exact for <= 2
    memberships, a subsample for more); nominees are verified with an
    exact exclusive-to-exclusive density scan.
    Only columns < k_active are touched (the K-sweep's padding columns
    must stay zero).

    Returns (edits, repairs): edits is an ORDERED list of
    ("clear", col) and ("set", rows, col, value) steps.
    repair_communities applies them to a host F; the device repair stage
    (fit_quality_device) applies them to the RESIDENT F as scatter
    updates — index vectors cross the host boundary, F does not.
    """
    F = np.asarray(F, np.float64)
    n = g.num_nodes
    ka = int(k_active)
    Fa = F[:n, :ka]
    mask = Fa >= delta
    sizes = mask.sum(axis=0)
    if not sizes.any():
        return [], 0
    # top-2 above-threshold columns per node
    if ka >= 2:
        top2 = np.argpartition(-Fa, 1, axis=1)[:, :2]
    else:
        top2 = np.zeros((n, 2), np.int64)
    valid = np.take_along_axis(Fa, top2, axis=1) >= delta
    # cross/within edge counts over the 4 (slot_u, slot_v) combos
    keys = []
    for su in range(2):
        for sv in range(2):
            m = valid[g.src, su] & valid[g.dst, sv]
            keys.append(
                top2[g.src[m], su].astype(np.int64) * ka
                + top2[g.dst[m], sv]
            )
    uk, uc = np.unique(np.concatenate(keys), return_counts=True)
    ca, cb = uk // ka, uk % ka
    within = np.zeros(ka)
    within[ca[ca == cb]] = uc[ca == cb]
    # within counts are DIRECTED (each undirected edge twice), normalized
    # by ordered pairs — i.e. plain undirected density, the same scale as
    # excl_cross_density's unordered cnt/(|ea|*|eb|) below
    dens_w = within / np.maximum(sizes * (sizes - 1), 1)
    cross: dict = {}
    for a, b, e in zip(ca, cb, uc):
        if a != b:
            key = (min(int(a), int(b)), max(int(a), int(b)))
            cross[key] = cross.get(key, 0) + int(e)
    members = [np.flatnonzero(mask[:, c]) for c in range(ka)]
    msets = [set(m.tolist()) for m in members]
    # merge candidates: the coarse cross counts (which include edges
    # incident to SHARED members, inflating genuine-overlap pairs) only
    # nominate; each nominee is verified with the EXACT
    # exclusive-to-exclusive edge density — the clean discriminator,
    # because two genuinely overlapping communities have (near-)zero
    # edges between their exclusive parts while two fragments of one
    # community are densely cross-connected at any overlap level.
    #   rule 1 (duplicates): inter/min >= 0.5
    #   rule 2 (fragments):  exact d_excl >= 0.25 * min(within density)
    indptr, indices = g.indptr, g.indices

    def excl_cross_density(a: int, b: int) -> float:
        # vectorized exact count: gather the concatenated adjacency of the
        # smaller exclusive side in one fancy-index, membership-test it
        # against the other side with one sort-based np.isin — O((deg_sum
        # + |other|) log) instead of a per-edge Python set scan (which at
        # com-Amazon K~5k grew the detector's worst case to minutes)
        ma, mb = members[a], members[b]          # sorted unique
        ea = np.setdiff1d(ma, mb, assume_unique=True)
        eb = np.setdiff1d(mb, ma, assume_unique=True)
        if ea.size == 0 or eb.size == 0:
            return 0.0
        small, other = (ea, eb) if ea.size <= eb.size else (eb, ea)
        nbr = _gather_neighbors(small, indptr, indices)
        if nbr.size == 0:
            return 0.0
        cnt = int(np.isin(nbr, other, assume_unique=False).sum())
        return cnt / (ea.size * eb.size)

    merges, used = [], set()
    nominees = sorted(cross.items(), key=lambda kv: -kv[1])[: 4 * ka]
    for (a, b), _e in nominees:
        la, lb = len(msets[a]), len(msets[b])
        if not la or not lb or a in used or b in used:
            continue
        inter_frac = len(msets[a] & msets[b]) / min(la, lb)

        def dense_excl(a=a, b=b):      # exact scan only when rule 1
            d = excl_cross_density(a, b)       # didn't already decide
            return d >= 0.25 * min(dens_w[a], dens_w[b]) and d > 0.025

        # rule 1 (duplicates/straddling fragments): heavy member overlap.
        # This DOES nominate some wrong merges (two merged columns sharing
        # one region); they are cheap — the LLH acceptance gate rejects
        # them (measured at N=12K) — and the freed column they would hand
        # to the split side is where the probe's measured gain comes from,
        # so precision-tightening this rule costs real recall (measured:
        # requiring connected exclusives here drops the probe's accepted
        # repair and its F1 0.894 -> 0.914 gain entirely).
        # rule 2 (disjoint fragments): dense exclusive-to-exclusive edges
        # — genuinely overlapping communities have none, so they never
        # merge by either rule at their ~0.2 overlap level.
        if inter_frac >= 0.5 or dense_excl():
            merges.append((a, b))
            used.update((a, b))
    if not merges:
        # repairs = min(#merges, #splits): without a freed column the
        # split component scan below would be a guaranteed no-op
        return [], 0
    # split candidates: extra components of fat columns. The candidate set
    # only depends on merge-used columns, so it can be precomputed — which
    # lets the device backend run ONE batched label-propagation pass over
    # all fat candidates instead of a host scipy scan per column.
    from bigclam_tpu.ops.components import components_backend

    cand = [
        int(c)
        for c in np.argsort(-sizes)
        if int(c) not in used and sizes[int(c)] >= 2 * min_comp
    ]
    comp_of = None
    if cand and components_backend(n, len(cand), components) == "device":
        from bigclam_tpu.ops.components import (
            column_component_stats,
            components_from_labels,
            device_edges,
        )

        if edges_dev is None:      # round-looping callers pass their cache
            edges_dev = device_edges(g)
        member = np.zeros((len(cand), n), bool)
        for i, c in enumerate(cand):
            member[i, members[c]] = True
        labels, _, _ = column_component_stats(member, *edges_dev, n)
        comp_of = {
            c: components_from_labels(labels[i], n, min_size=min_comp)
            for i, c in enumerate(cand)
        }
    splits = []
    for c in cand:
        comps = (
            list(comp_of[c])
            if comp_of is not None
            else [
                np.asarray(cc, np.int64)
                for cc in _graph_components(members[c], indptr, indices)
                if len(cc) >= min_comp
            ]
        )
        if len(comps) <= 1:
            continue
        # min-id tiebreak: backend-independent primary-component choice
        # (component member arrays are ascending on both backends)
        comps.sort(key=lambda cc: (-len(cc), int(cc[0])))
        for comp in comps[1:]:
            splits.append((c, np.asarray(comp, np.int64)))
    edits: list = []
    repairs = 0
    freed = []
    for a, b in merges:
        if repairs >= len(splits):
            break
        gained = np.fromiter(
            sorted(msets[b] - msets[a]), np.int64,
            count=len(msets[b] - msets[a]),
        )
        edits.append(("set", gained, int(a), float(strength)))
        edits.append(("clear", int(b)))
        freed.append(b)
        repairs += 1
    for (c, comp), v in zip(splits, freed):
        edits.append(("set", comp, int(v), float(strength)))
        edits.append(("set", comp, int(c), 0.0))
    return edits, repairs


def apply_repair_edits(F: np.ndarray, edits: list, num_nodes: int
                       ) -> np.ndarray:
    """Apply a repair_plan edit list to a host F in place (rows beyond
    num_nodes — padding — are never named by edits)."""
    for e in edits:
        if e[0] == "clear":
            F[:num_nodes, e[1]] = 0.0
        else:
            _, rows, col, val = e
            F[rows, col] = val
    return F


def repair_communities(
    F: np.ndarray,
    g,
    delta: float,
    k_active: int,
    min_comp: int = 5,
    strength: float = 1.0,
    components: str = "auto",
    edges_dev=None,
) -> Tuple[np.ndarray, int]:
    """One merge+split repair pass over the thresholded communities:
    repair_plan detection + host application of the edit list. Returns
    (repaired F, number of repairs); see repair_plan for the move's
    rationale and cost model."""
    F = np.asarray(F, np.float64).copy()
    edits, repairs = repair_plan(
        F, g, delta, k_active, min_comp=min_comp, strength=strength,
        components=components, edges_dev=edges_dev,
    )
    if not repairs:
        return F, 0
    return apply_repair_edits(F, edits, g.num_nodes), repairs


@dataclasses.dataclass(frozen=True)
class QualityResult:
    fit: FitResult            # best-LLH cycle's result
    cycles_llh: Tuple[float, ...]   # converged LLH per cycle (as run)
    num_cycles: int
    total_iters: int
    num_repairs: int = 0      # accepted merge+split repair rounds (the
    # repair stage can push fit.llh ABOVE max(cycles_llh))
    stages: Optional[dict] = None   # per-stage wall-clock + transfer
    # counters (utils.profiling.StageProfile.report()); populated by the
    # device schedule and by callers that pass a profile to fit_quality


def _repair_stamp(
    cfg, anneal_llh: float, kc: int, eps: float, min_comp: int, rng: str
) -> dict:
    """The invalidation stamp a repair checkpoint must match to resume
    (see _repair_stage). `rng` names the kick-stream family — "host"
    (NumPy streams) vs "device" (threefry folds): the two stages draw
    different polish kicks, so their checkpoints must never cross-resume."""
    return {
        "anneal_llh": float(anneal_llh),
        "kick_cols": int(kc),
        "reassign": bool(cfg.quality_reassign),
        "seed": cfg.seed,
        "eps": float(eps),
        "min_comp": int(min_comp),
        "rng": rng,
    }


def _repair_ckpt_open(checkpoints, stamp: dict):
    """(manager under <dir>/repair, restored (rr_done, arrays, meta) or
    None). A checkpoint whose meta mismatches ANY stamp key — including
    one written before a stamp key existed (.get() misses) — is stale:
    deleted, and a fresh manager is returned. The anneal_llh stamp is the
    resume-extension rule: a restart with more restart_cycles changes the
    post-annealing best, so the stale repair work is discarded and repair
    restarts from the NEW annealed state, exactly as an uninterrupted run
    would (ADVICE round-5 for the eps/min_comp keys)."""
    from bigclam_tpu.utils.checkpoint import CheckpointManager

    rep_ckpt = CheckpointManager(
        os.path.join(checkpoints.directory, "repair")
    )
    restored = rep_ckpt.restore()
    if restored is None:
        return rep_ckpt, None
    meta = restored[2]
    if all(meta.get(k) == v for k, v in stamp.items()):
        return rep_ckpt, restored
    shutil.rmtree(rep_ckpt.directory, ignore_errors=True)
    return CheckpointManager(rep_ckpt.directory), None


def _repair_stage(
    model,
    best: FitResult,
    kc: int,
    eps: float,
    callback,
    checkpoints=None,
    min_comp: int = 5,
    resume: bool = True,
) -> Tuple[FitResult, int, int]:
    """The DISCRETE improvement stage shared by fit_quality and
    fit_quality_device. Each round tries (a) the atomize re-tiling
    (atomize_reassign; cfg.quality_reassign) and (b) the merge/split
    repair (repair_communities), each refit and kept only on LLH
    improvement; the loop stops when a round accepts neither. Runs with model.cfg already swapped to the RELAXED
    quality config (the polish fits anneal under the same clip the cycles
    did); reads schedule knobs (repair_rounds, seed, min_f, max_f) off the
    live cfg — identical values to the caller's saved cfg since the swap
    touches only conv_tol/max_p.

    Returns (best, accepted_repairs, extra_iters).

    Checkpointing (SURVEY §5; VERDICT r4 item 7): with `checkpoints`, each
    completed repair round saves under <dir>/repair/ with the
    POST-ANNEALING best LLH stamped in the meta. A crash mid-repair
    resumes from the last completed round instead of redoing hours of
    polish fits. The stamp is also the invalidation rule that preserves
    resume-extension exactness: a restart with a larger restart_cycles
    changes the post-annealing best, the stamp mismatches, and the stale
    repair checkpoint is discarded — repair restarts from the NEW
    annealed state, exactly as an uninterrupted run would. Repair kick
    streams are fixed per (round, polish) so a resumed round reproduces
    the uninterrupted schedule.
    """
    from bigclam_tpu.ops.extraction import delta_threshold

    cfg = model.cfg
    n = best.F.shape[0]
    accepted_repairs = 0
    extra_iters = 0
    anneal_llh = float(best.llh)       # the post-annealing stamp
    start_round = 0
    rep_ckpt = None
    stamp: dict = {}
    if checkpoints is not None:
        # the stamp (incl. eps/min_comp — a checkpoint written under a
        # different polish kick scale or component floor replays a
        # different schedule on resume, ADVICE round-5) gates the restore
        stamp = _repair_stamp(cfg, anneal_llh, kc, eps, min_comp, "host")
        rep_ckpt, restored = _repair_ckpt_open(checkpoints, stamp)
        if not resume:
            restored = None      # cold start: keep saving, never restore
        if restored is not None:
            rr_done, arrays, meta = restored
            F_r = np.asarray(arrays["F"])
            best = FitResult(
                F=F_r,
                sumF=F_r.sum(axis=0),
                llh=float(meta["best_llh"]),
                num_iters=int(meta.get("fit_num_iters", best.num_iters)),
                llh_history=tuple(
                    np.asarray(arrays.get("llh_history", ())).tolist()
                ),
            )
            accepted_repairs = int(meta.get("accepted_repairs", 0))
            extra_iters = int(meta.get("extra_iters", 0))
            start_round = rr_done + 1
            if meta.get("done"):
                return best, accepted_repairs, extra_iters

    g_orig = getattr(model, "g_original", model.g)
    delta = delta_threshold(g_orig.num_nodes, g_orig.num_edges)

    def _save(rr: int, done: bool) -> None:
        if rep_ckpt is not None and is_primary():
            rep_ckpt.save(
                rr,
                {
                    "F": np.asarray(best.F),
                    "llh_history": np.asarray(best.llh_history, np.float64),
                },
                meta={
                    **stamp,
                    "best_llh": float(best.llh),
                    "fit_num_iters": int(best.num_iters),
                    "accepted_repairs": accepted_repairs,
                    "extra_iters": extra_iters,
                    "done": done,
                },
            )

    for rr in range(start_round, max(cfg.repair_rounds, 0)):
        changed = False
        # -- atomize re-tiling attempt (cfg.quality_reassign): one plain
        # refit from the shattered seeding, no polish kicks (the validated
        # prototype schedule) --
        if cfg.quality_reassign:
            F_at, n_atoms = atomize_reassign(
                best.F, g_orig, delta, kc, min_comp=min_comp
            )
            if n_atoms:
                res = model.fit(F_at, callback=callback)
                extra_iters += res.num_iters
                if res.llh > best.llh:
                    best = res
                    accepted_repairs += 1
                    changed = True
        # -- merge/split attempt with the round-4 kick-polish schedule --
        F_rep, nrep = repair_communities(best.F, g_orig, delta, kc)
        if nrep:
            cand = None
            F_c = F_rep
            for pc in range(6):        # polish: short re-annealing
                prng = np.random.default_rng([cfg.seed, 0xF17, rr, pc])
                F_try = np.asarray(F_c, np.float64).copy()
                F_try[:, :kc] = np.clip(
                    F_try[:, :kc] + prng.uniform(0.0, eps, size=(n, kc)),
                    cfg.min_f, cfg.max_f,
                )
                res = model.fit(F_try, callback=callback)
                extra_iters += res.num_iters
                if cand is None or res.llh > cand.llh:
                    cand = res
                    F_c = res.F
            if cand.llh > best.llh:
                best = cand
                accepted_repairs += 1
                changed = True
        _save(rr, not changed)
        if not changed:
            break
    return best, accepted_repairs, extra_iters


def fit_quality(
    model,
    F0: np.ndarray,
    callback: Optional[Callable[[int, float], None]] = None,
    checkpoints=None,
    kick_cols: Optional[int] = None,
    profile=None,
    resume: bool = True,
) -> QualityResult:
    """Train with the quality-mode schedule (see module docstring).

    model: any trainer exposing .cfg, .g, .rebuild_step(), and
    .fit(F0, callback=) -> FitResult (BigClamModel / ShardedBigClamModel /
    RingBigClamModel all do).

    `checkpoints` (utils.checkpoint.CheckpointManager) is used at CYCLE
    granularity: after each cycle the kept F is saved under step=cycle and
    a restart resumes from the newest cycle. With cfg.checkpoint_every > 0
    each cycle's fit ADDITIONALLY checkpoints within the cycle (under
    checkpoints.directory/cycle_<c>/, deleted once the cycle is
    journaled — the sweep's per-K pattern), so a crash deep inside a long
    cycle resumes inside it instead of restarting the cycle. Noise is
    drawn from per-cycle streams ([cfg.seed, 0x5EED, cycle]) so resume
    reproduces the uninterrupted schedule exactly either way.

    `kick_cols` restricts the noise kick to F[:, :kick_cols] (default: all
    columns). The K-sweep passes the active K here — its F buffer is sized
    to the grid max with columns >= K masked to zero, and an unrestricted
    kick would lift those padding columns off their inert zeros.

    `profile` (utils.profiling.StageProfile, created when omitted)
    accumulates anneal/repair wall-clock; the report lands in
    QualityResult.stages so artifacts can attribute the quality stage's
    cost (the device loop records finer stages plus transfer counts).

    `resume=False` (cli --resume never) ignores any existing cycle
    checkpoints — cold start from F0 — while still SAVING new ones.
    """
    import time

    from bigclam_tpu.utils.profiling import StageProfile

    profile = profile if profile is not None else StageProfile()
    cfg = model.cfg
    n, k = F0.shape
    kc = k if kick_cols is None else int(kick_cols)
    if not (0 < kc <= k):
        raise ValueError(f"kick_cols={kick_cols} out of range for K={k}")
    F_cur = np.asarray(F0, np.float64)
    cycles_llh: List[float] = []
    best: Optional[FitResult] = None
    total_iters = 0
    start_cycle = 0
    restored_gainless = 0
    max_p_q, eps = _relax_params(model, n)

    if checkpoints is not None and resume:
        restored = checkpoints.restore()
        if restored is not None:
            cyc, arrays, meta = restored
            if meta.get("quality_nk") != [n, k]:
                raise ValueError(
                    f"quality checkpoint incompatible: nk={meta.get('quality_nk')} "
                    f"vs ({n}, {k}) (dir: {checkpoints.directory})"
                )
            if int(meta.get("kick_cols", k)) != kc:
                raise ValueError(
                    f"quality checkpoint incompatible: kick_cols="
                    f"{meta.get('kick_cols')} vs {kc} "
                    f"(dir: {checkpoints.directory})"
                )
            # LLHs are computed under the step's clip bound: a checkpoint
            # written under a different effective max_p carries best_llh /
            # cycles_llh on a systematically different scale, silently
            # skewing acceptance and patience on resume. A meta WITHOUT
            # the stamp predates the stamp itself — the clip it actually
            # ran under is unrecorded, so refuse whenever this run would
            # relax (don't claim a max_p the checkpoint never wrote).
            ck_max_p = meta.get("quality_max_p")
            if ck_max_p is None:
                if max_p_q != cfg.max_p:
                    raise ValueError(
                        "quality checkpoint predates the quality_max_p "
                        "stamp (the clip bound its LLHs were computed "
                        f"under is unrecorded), but this run relaxes "
                        f"MAX_P_ to {max_p_q} — cannot verify the LLH "
                        f"scales match; restart without the stale "
                        f"checkpoint (dir: {checkpoints.directory})"
                    )
            elif ck_max_p != max_p_q:
                raise ValueError(
                    f"quality checkpoint incompatible: written under "
                    f"max_p={ck_max_p}, this run relaxes to {max_p_q} — "
                    f"LLH scales differ (dir: {checkpoints.directory})"
                )
            F_cur = np.asarray(arrays["F"])
            cycles_llh = list(meta.get("cycles_llh", []))
            best_llh = float(meta["best_llh"])
            best = FitResult(
                F=F_cur, sumF=F_cur.sum(axis=0), llh=best_llh,
                num_iters=int(meta.get("total_iters", 0)), llh_history=(),
            )
            total_iters = int(meta.get("total_iters", 0))
            start_cycle = cyc + 1
            restored_gainless = int(meta.get("gainless", 0))

    max_cycles = max(cfg.restart_cycles, 1)
    cfg_saved = model.cfg
    accepted_repairs = 0
    # patience state survives resume (persisted in the checkpoint meta) so
    # the resumed schedule stops exactly where the uninterrupted one would
    gainless = restored_gainless
    rebuilt = False
    try:
        # within-cycle fits use the TIGHTER quality_conv_tol (host-side
        # only); the max_p swap changes step-baked constants, so the step
        # is recompiled — same kernels/schedule, different clip bound
        # (cached by step_cfg_key)
        model.cfg = cfg.replace(
            conv_tol=cfg.quality_conv_tol, max_p=max_p_q
        )
        if max_p_q != cfg.max_p:
            model.rebuild_step()
            rebuilt = True
        t_anneal = time.perf_counter()
        from bigclam_tpu.obs import trace as _trace

        for cycle in range(start_cycle, max_cycles):
            if gainless >= cfg.restart_patience:
                break          # a restored run that already tripped
                # patience must not anneal further (resume-exactness)
            crng = np.random.default_rng([cfg.seed, 0x5EED, cycle])
            kick = crng.uniform(0.0, eps, size=(n, kc))
            F_try = np.asarray(F_cur, np.float64).copy()
            F_try[:, :kc] = np.clip(
                F_try[:, :kc] + kick, cfg.min_f, cfg.max_f
            )
            cyc_ckpt = cyc_dir = None
            if checkpoints is not None and cfg.checkpoint_every > 0:
                from bigclam_tpu.utils.checkpoint import CheckpointManager

                cyc_dir = os.path.join(
                    checkpoints.directory, f"cycle_{cycle:05d}"
                )
                cyc_ckpt = CheckpointManager(cyc_dir)
            # checkpoints= only when active: the documented trainer surface
            # (.cfg, .g, .fit(F0, callback=), .rebuild_step()) stays
            # sufficient for duck-typed trainers unless within-cycle
            # checkpointing was explicitly requested
            try:
                # one span per annealing cycle (obs.trace): the restart
                # schedule's time-per-cycle rides the span breakdown next
                # to the `cycle` events
                with _trace.span("cycle", cycle=cycle):
                    res = (
                        model.fit(
                            F_try, callback=callback, checkpoints=cyc_ckpt
                        )
                        if cyc_ckpt is not None
                        else model.fit(F_try, callback=callback)
                    )
            except FloatingPointError as e:
                # a kick blew up past the fit loop's rollback budget
                # (models.bigclam run_fit_loop): annealing is an OPTIONAL
                # refinement on top of a kept-best state, so with a best in
                # hand the right move is degrade-not-die — revert the kick,
                # keep the best, stop annealing. Without one (cycle 0)
                # there is nothing to fall back to: propagate.
                if best is None:
                    raise
                warnings.warn(
                    f"annealing cycle {cycle} aborted non-finite ({e}); "
                    "keeping the best converged state and stopping the "
                    "annealing loop"
                )
                from bigclam_tpu.obs import telemetry as _obs_t

                tel = _obs_t.current()
                if tel is not None:
                    tel.event(
                        "note",
                        msg="quality_cycle_nonfinite_abort",
                        cycle=cycle,
                        kept_llh=best.llh,
                    )
                break
            total_iters += res.num_iters
            cycles_llh.append(res.llh)
            prev_best = best.llh if best is not None else None
            kept = best is None or res.llh > best.llh
            _cycle_event(cycle, res.llh, kept, res.num_iters)
            if kept:
                best = res
                F_cur = res.F              # kick accepted: anneal from here
            # else: converged worse than the kept state — revert the kick
            if prev_best is not None and prev_best != 0.0:
                gain = (best.llh - prev_best) / abs(prev_best)
                gainless = gainless + 1 if gain < cfg.restart_tol else 0
            if checkpoints is not None:
                if is_primary():
                    checkpoints.save(
                        cycle,
                        {"F": F_cur},
                        meta={
                            "best_llh": best.llh,
                            "cycles_llh": cycles_llh,
                            "total_iters": total_iters,
                            "gainless": gainless,
                            "quality_nk": [n, k],
                            "kick_cols": kc,
                            "quality_max_p": max_p_q,
                        },
                    )
                    if cyc_dir is not None:
                        # journaled: the cycle's within-fit checkpoints are
                        # spent (and must not leak into a later cycle)
                        shutil.rmtree(cyc_dir, ignore_errors=True)
            if gainless >= cfg.restart_patience:
                break
        profile.add_seconds("anneal", time.perf_counter() - t_anneal)
        # --- discrete repair stage (cfg.quality_repair; _repair_stage):
        # runs after the cycle loop, checkpointed under <dir>/repair/ with
        # the post-annealing best LLH as its invalidation stamp — a
        # restart with a larger restart_cycles changes that stamp, the
        # stale repair checkpoint is discarded, and repair restarts from
        # the NEW annealed state (resume-extension exactness preserved).
        # Repairs use the ORIGINAL-id graph: FitResult.F is in original
        # ids even when a balanced sharded trainer relabeled rows.
        if cfg.quality_repair and best is not None:
            t_rep = time.perf_counter()
            best, accepted_repairs, rep_iters = _repair_stage(
                model, best, kc, eps, callback, checkpoints=checkpoints,
                resume=resume,
            )
            total_iters += rep_iters
            profile.add_seconds("repair", time.perf_counter() - t_rep)
    finally:
        model.cfg = cfg_saved
        if rebuilt:
            model.rebuild_step()           # restore the parity-clip step
    return QualityResult(
        fit=best,
        cycles_llh=tuple(cycles_llh),
        num_cycles=len(cycles_llh),
        total_iters=total_iters,
        num_repairs=accepted_repairs,
        stages=profile.report(),
    )


def _repair_stage_device(
    model,
    best_state,
    best_llh: float,
    best_iters: int,
    best_hist: tuple,
    kc: int,
    eps: float,
    callback,
    kick_fn,
    base_key,
    profile,
    checkpoints=None,
    min_comp: int = 5,
    resume: bool = True,
):
    """DEVICE-RESIDENT discrete stage: the _repair_stage twin that keeps F
    on the chips (fit_quality_device's residency protocol; DESIGN.md
    "Device-resident quality pipeline").

    Differences from the host stage, by design:

    * components + membership/density stats for atomize and the
      fat-column splits come from the batched device label-propagation
      pass (ops.components) — int32 label/stat arrays cross the host
      boundary; F itself does not.
    * move order is merge/split -> atomize within a round (the host stage
      runs atomize first): merge/split detection is a host pass over
      thresholded F VALUES (top-2 columns, exclusive densities), so it
      needs the round's one F fetch — running it first lets that fetch
      double as the previous round's checkpoint payload, holding the
      stage to AT MOST ONE full-F device->host download per repair round
      (the transfer contract pinned by tests/test_components.py).
      Atomize needs only the thresholded MASK, which stays on device.
    * repairs reach the resident F as scatter edits (repair_plan's edit
      list / the atomize plan's (rows, cols, vals) arrays — index vectors
      ~K times smaller than F), and every refit (the atomize refit and
      the 6 polish fits) runs state-resident through model.fit_state,
      reusing the donated TrainState ping-pong of run_fit_loop. Zero F
      uploads per refit.
    * polish kicks draw from the device threefry stream (folded per
      (round, polish)) — deterministic for a fixed seed/mesh, but a
      different schedule than the host stage's NumPy streams; repair
      checkpoints therefore carry rng="device" and never cross-resume
      with host-stage checkpoints (shared _repair_stamp).

    Round checkpoints are DEFERRED one fetch: round rr's state is saved
    by round rr+1's fetch (the identical F — nothing moves between
    rounds), and the last round's by the caller's final result fetch via
    the returned `finalize(F_host)` closure. Returns (best_state,
    best_llh, best_iters, best_hist, accepted_repairs, extra_iters,
    finalize).
    """
    import jax
    import jax.numpy as jnp

    from bigclam_tpu.ops.components import device_edges
    from bigclam_tpu.ops.extraction import delta_threshold

    cfg = model.cfg
    g = model.g
    g_orig = getattr(model, "g_original", g)
    n = g.num_nodes
    delta = delta_threshold(g_orig.num_nodes, g_orig.num_edges)
    accepted_repairs = 0
    extra_iters = 0
    anneal_llh = float(best_llh)
    start_round = 0
    rep_ckpt = None
    stamp: dict = {}
    if checkpoints is not None:
        stamp = _repair_stamp(cfg, anneal_llh, kc, eps, min_comp, "device")
        rep_ckpt, restored = _repair_ckpt_open(checkpoints, stamp)
        if not resume:
            restored = None      # cold start: keep saving, never restore
        if restored is not None:
            rr_done, arrays, meta = restored
            best_state = model.init_state(np.asarray(arrays["F"]))
            profile.count("f_host_uploads")
            best_llh = float(meta["best_llh"])
            best_iters = int(meta.get("fit_num_iters", best_iters))
            best_hist = tuple(
                np.asarray(arrays.get("llh_history", ())).tolist()
            )
            accepted_repairs = int(meta.get("accepted_repairs", 0))
            extra_iters = int(meta.get("extra_iters", 0))
            start_round = rr_done + 1
            if meta.get("done"):
                return (
                    best_state, best_llh, best_iters, best_hist,
                    accepted_repairs, extra_iters, lambda F_host: None,
                )

    perm = getattr(model, "_perm", None)   # edits arrive in ORIGINAL ids
    n_pad = int(best_state.F.shape[0])
    edges_dev = device_edges(g)            # one upload for every round
    # merge/split detection runs in ORIGINAL ids (on the fetched F); a
    # balanced trainer's g is relabeled, so its edge cache cannot be
    # shared with repair_plan there
    edges_dev_orig = (
        edges_dev if g_orig is g else device_edges(g_orig)
    )

    scatter_set = jax.jit(
        lambda F, rows, cols, vals: F.at[rows, cols].set(vals, mode="drop")
    )
    clear_col = jax.jit(
        lambda F, col: jnp.where(
            jnp.arange(F.shape[1], dtype=jnp.int32)[None, :] == col,
            jnp.zeros((), F.dtype),
            F,
        )
    )

    def apply_sets(F, rows, cols, vals):
        # pow-2 padding (pad rows land at n_pad, out of bounds -> dropped
        # by mode="drop"), so at most log2 scatter shapes ever compile
        r = np.asarray(rows, np.int32)
        size = 1 << max(int(r.size - 1).bit_length(), 0)
        pad = size - r.size
        r = np.pad(r, (0, pad), constant_values=n_pad)
        c = np.pad(np.asarray(cols, np.int32), (0, pad))
        v = np.pad(np.asarray(vals, np.float64), (0, pad))
        return scatter_set(
            F, jnp.asarray(r), jnp.asarray(c), jnp.asarray(v, F.dtype)
        )

    pending: list = [None]     # (round, done) awaiting an F fetch

    def _save(rr: int, done: bool, F_host: np.ndarray) -> None:
        if rep_ckpt is not None and is_primary():
            rep_ckpt.save(
                rr,
                {
                    "F": np.asarray(F_host),
                    "llh_history": np.asarray(best_hist, np.float64),
                },
                meta={
                    **stamp,
                    "best_llh": float(best_llh),
                    "fit_num_iters": int(best_iters),
                    "accepted_repairs": accepted_repairs,
                    "extra_iters": extra_iters,
                    "done": done,
                },
            )

    def finalize(F_host) -> None:
        if pending[0] is not None:
            _save(pending[0][0], pending[0][1], F_host)
            pending[0] = None

    for rr in range(start_round, max(cfg.repair_rounds, 0)):
        changed = False
        # --- the round's ONE F fetch: the previous round's deferred
        # checkpoint payload + the merge/split detection input ---
        with profile.stage("repair_fetch"):
            F_host = model.extract_F(best_state)
        profile.count("f_device_fetches")
        finalize(F_host)
        # --- (a) merge/split repair; polish refits state-resident ---
        with profile.stage("repair_detect"):
            edits, nrep = repair_plan(
                F_host, g_orig, delta, kc, min_comp=min_comp,
                edges_dev=edges_dev_orig,
            )
        del F_host
        if nrep:
            F_rep = best_state.F
            for e in edits:
                if e[0] == "clear":
                    F_rep = clear_col(F_rep, jnp.int32(e[1]))
                else:
                    _, rows, col, val = e
                    rows = rows if perm is None else perm[rows]
                    F_rep = apply_sets(
                        F_rep, rows,
                        np.full(rows.size, col, np.int32),
                        np.full(rows.size, val),
                    )
            cand_state = None
            cand_llh = None
            cand_iters, cand_hist = 0, ()
            F_c = F_rep
            with profile.stage("repair_polish"):
                for pc in range(6):    # polish: short re-annealing
                    key = jax.random.fold_in(
                        base_key, 0x0F17_0000 + rr * 64 + pc
                    )
                    final, llh, iters, hist = model.fit_state(
                        model.reset_state(kick_fn(F_c, key)),
                        callback=callback,
                    )
                    extra_iters += iters
                    if cand_llh is None or llh > cand_llh:
                        cand_state, cand_llh = final, llh
                        cand_iters, cand_hist = iters, hist
                        F_c = final.F
                    del final          # rejected polish buffers die now
            del F_rep, F_c
            if cand_llh is not None and cand_llh > best_llh:
                best_state, best_llh = cand_state, cand_llh
                best_iters, best_hist = cand_iters, cand_hist
                accepted_repairs += 1
                changed = True
                profile.count("repair_accepted")
            del cand_state
        # --- (b) atomize re-tiling from the DEVICE mask (no F fetch) ---
        if cfg.quality_reassign:
            with profile.stage("atomize_components"):
                mask_cols = (best_state.F[:n, :kc] >= delta).T
                # backend dispatch (ops.components.components_backend): on
                # an accelerator the batched device pass is the only
                # option that keeps F resident; on a CPU backend "device"
                # memory IS host memory, so the scipy oracle runs on the
                # same bool mask for a fraction of the wall-clock (the
                # mask is kc*n bools — not F)
                from bigclam_tpu.ops.components import components_backend

                if components_backend(n, kc) == "device":
                    atoms = _column_atoms_device(
                        mask_cols, g, min_comp, edges_dev
                    )
                else:
                    atoms = _column_atoms_host(
                        np.asarray(mask_cols).T, g.indptr, g.indices,
                        min_comp,
                    )
                del mask_cols
            if atoms:
                kept = _plan_atoms(atoms, n, kc)
                rows = np.concatenate([at for at, _ in kept])
                cols = np.concatenate([
                    np.full(at.size, c, np.int32)
                    for c, (at, _) in enumerate(kept)
                ])
                vals = np.concatenate([
                    np.full(
                        at.size,
                        _atom_strength(at, d, g.indptr, g.indices),
                    )
                    for at, d in kept
                ])
                F_at = apply_sets(
                    jnp.zeros_like(best_state.F), rows, cols, vals
                )
                with profile.stage("atomize_refit"):
                    final, llh, iters, hist = model.fit_state(
                        model.reset_state(F_at), callback=callback
                    )
                del F_at
                extra_iters += iters
                if llh > best_llh:
                    best_state, best_llh = final, llh
                    best_iters, best_hist = iters, hist
                    accepted_repairs += 1
                    changed = True
                    profile.count("atomize_accepted")
                del final
        profile.count("repair_rounds")
        pending[0] = (rr, not changed)
        if not changed:
            break
    return (
        best_state, best_llh, best_iters, best_hist, accepted_repairs,
        extra_iters, finalize,
    )


def fit_quality_device(
    model,
    F0: np.ndarray,
    callback: Optional[Callable[[int, float], None]] = None,
    kick_cols: Optional[int] = None,
    key_salt: int = 0,
    checkpoints=None,
    profile=None,
    resume: bool = True,
) -> QualityResult:
    """DEVICE-RESIDENT annealing + discrete stage: the pod-scale variant
    of fit_quality. `resume=False` skips the repair-round restore (cold
    start) while still saving new round checkpoints.

    The host loop round-trips the full (N, K) F to the host every cycle
    (res.F out, kicked F_try back in) — at com-Orkut scale (N=3.07M,
    K=15000, 184 GB global F) that F does not even fit one host. Here the
    state stays sharded on the devices for the WHOLE schedule — cycles AND
    the discrete repair stage: one init_state upload, then per cycle a
    jitted on-device kick (uniform noise masked to the live
    (num_nodes, kick_cols) region — padding rows and columns stay on their
    inert zeros) and the trainers' state-resident loop (fit_state); only
    per-iteration LLH scalars cross the host boundary. The discrete stage
    (_repair_stage_device) computes atomize components + density stats
    from the device mask via batched label propagation (ops.components),
    applies repairs as scatter edits to the resident F, runs every refit
    through fit_state with the donated TrainState ping-pong, and performs
    at most ONE full-F download per repair round (serving merge/split
    detection and repair-round checkpointing together). The final best F
    is fetched once at the end.

    `checkpoints` (utils.checkpoint.CheckpointManager) wires REPAIR-ROUND
    granularity checkpointing: a crash mid-repair at pod scale resumes
    from the last completed round instead of redoing hours of polish fits.
    Cycle-granularity checkpointing stays a host-loop feature (it is a
    full-F host pass by definition); device-stage checkpoints are stamped
    rng="device" and never cross-resume with host-stage ones.

    Differences from fit_quality, by design: kick noise comes from
    jax.random (threefry, folded per cycle / per (round, polish)) instead
    of the host NumPy streams — deterministic for a fixed seed/mesh but
    NOT bit-identical to the host schedule — and the discrete stage runs
    merge/split before atomize within a round (see _repair_stage_device).
    Stop rule, patience, MAX_P_ relaxation, and the kept-LLH semantics
    are identical (shared _relax_params). Per-stage wall-clock and
    transfer counts land in QualityResult.stages
    (utils.profiling.StageProfile).
    """
    import jax
    import jax.numpy as jnp

    from bigclam_tpu.utils.profiling import StageProfile

    profile = profile if profile is not None else StageProfile()
    cfg = model.cfg
    n, k = F0.shape
    kc = k if kick_cols is None else int(kick_cols)
    if not (0 < kc <= k):
        raise ValueError(f"kick_cols={kick_cols} out of range for K={k}")
    max_cycles = max(cfg.restart_cycles, 1)
    max_p_q, eps = _relax_params(model, n)

    state0 = model.init_state(F0)          # the ONE host->device upload
    profile.count("f_host_uploads")
    n_pad, k_pad = state0.F.shape

    @jax.jit
    def kick_fn(F, key):
        # full-shape uniform noise, masked to the live region: shards with
        # F under whatever mesh the trainer compiled (threefry is
        # partitionable), and the phantom rows/columns stay exactly zero
        live = (jnp.arange(n_pad) < n)[:, None] & (
            jnp.arange(k_pad) < kc
        )[None, :]
        noise = jax.random.uniform(
            key, F.shape, F.dtype, 0.0, eps
        )
        return jnp.clip(
            F + jnp.where(live, noise, 0.0), cfg.min_f, cfg.max_f
        )

    cfg_saved = model.cfg
    rebuilt = False
    cycles_llh: List[float] = []
    best_state = None
    best_llh = None
    total_iters = 0
    gainless = 0
    F_cur = state0.F
    del state0          # only F is needed; the state tuple must not pin an
    # extra F-sized buffer through the schedule (see the rejected-cycle del)
    # key_salt makes callers' schedules independent restarts — the K-sweep
    # salts with K so grid points do not share one noise stream (the host
    # path's per-K RNG streams, model_selection.py, for the same reason)
    base_key = jax.random.fold_in(
        jax.random.key((cfg.seed ^ 0x5EED) & 0xFFFFFFFF), key_salt
    )
    try:
        model.cfg = cfg.replace(
            conv_tol=cfg.quality_conv_tol, max_p=max_p_q
        )
        if max_p_q != cfg.max_p:
            model.rebuild_step()
            rebuilt = True
        best_iters, best_hist = 0, ()
        from bigclam_tpu.obs import trace as _trace

        with profile.stage("anneal"):
            for cycle in range(max_cycles):
                # span nests under the "anneal" stage span: path
                # ".../anneal/cycle" in the per-span breakdown
                with _trace.span("cycle", cycle=cycle):
                    F_try = kick_fn(
                        F_cur, jax.random.fold_in(base_key, cycle)
                    )
                    final, llh, iters, hist = model.fit_state(
                        model.reset_state(F_try), callback=callback
                    )
                    del F_try              # free the kicked input buffer
                total_iters += iters
                profile.count("anneal_cycles")
                cycles_llh.append(llh)
                _cycle_event(
                    cycle, llh, best_llh is None or llh > best_llh, iters
                )
                prev_best = best_llh
                if best_llh is None or llh > best_llh:
                    best_state, best_llh = final, llh
                    best_iters, best_hist = iters, hist
                    F_cur = final.F        # kick accepted: anneal from here
                # a rejected cycle's converged state must not stay live
                # through the next cycle — at pod scale that extra F-sized
                # buffer is the difference between fitting and OOM
                del final
                if prev_best is not None and prev_best != 0.0:
                    gain = (best_llh - prev_best) / abs(prev_best)
                    gainless = gainless + 1 if gain < cfg.restart_tol else 0
                if gainless >= cfg.restart_patience:
                    break
        # still under the RELAXED cfg: the discrete stage's refits must
        # anneal under the same clip the cycles did — one swap/rebuild
        # round-trip for the whole schedule. F STAYS DEVICE-RESIDENT
        # through the stage (the round-5 device path fetched F here and
        # ran the host stage, paying one F round trip per refit — the
        # exact transfer this path exists to avoid).
        finalize = None
        accepted_repairs = 0
        if cfg.quality_repair:
            (
                best_state, best_llh, best_iters, best_hist,
                accepted_repairs, rep_iters, finalize,
            ) = _repair_stage_device(
                model, best_state, best_llh, best_iters, best_hist, kc,
                eps, callback, kick_fn, base_key, profile,
                checkpoints=checkpoints, resume=resume,
            )
            total_iters += rep_iters
        with profile.stage("final_fetch"):
            F_best = model.extract_F(best_state)   # ONE device->host fetch
        profile.count("f_device_fetches")
        if finalize is not None:
            finalize(F_best)   # deferred last-round repair checkpoint
        # same FitResult contract as the host loop: the BEST fit's
        # iteration count and LLH trace (total_iters on the QualityResult)
        fit = FitResult(
            F=F_best, sumF=F_best.sum(axis=0), llh=best_llh,
            num_iters=best_iters, llh_history=best_hist,
        )
    finally:
        model.cfg = cfg_saved
        if rebuilt:
            model.rebuild_step()
    return QualityResult(
        fit=fit,
        cycles_llh=tuple(cycles_llh),
        num_cycles=len(cycles_llh),
        total_iters=total_iters,
        num_repairs=accepted_repairs,
        stages=profile.report(),
    )
