"""Warm-start incremental refit + the continuous fit->publish loop
(ISSUE 15 tentpole, ROADMAP item 3).

Real graphs never stop changing; the reference re-ran the whole Spark
pipeline per snapshot (PAPER.md). Here a graph delta costs only the work
it touched:

* `warm_start_refit` starts from the PREVIOUS converged F, restricts the
  optimization to the delta's touched rows plus a configurable HALO of
  their neighbors, and sweeps them with the batched fold-in operator
  (ops.foldin — the trainer's own per-node Armijo ascent against the
  frozen remainder, the ISSUE 14 operator). Each round is one
  block-coordinate sweep: every batch folds against the CURRENT frozen
  state and commits its rows (ops.foldin.apply_rows / the trainers'
  refit_commit) before the next batch runs, so the restricted objective
  ascends round over round exactly like the full fit's global LLH.

* The PR 8 health detectors run on the RESTRICTED objective series
  (obs.health.run_detectors, divergence/plateau): accumulated drift that
  the local updates cannot absorb — the frozen remainder is too stale —
  surfaces as a detector firing, and the result is flagged `escalated`
  so the caller (cli refit / the follow loop) runs a FULL fit instead of
  publishing a degraded snapshot.

* `follow_deltas` is the loop: watch a delta directory
  (graph.stream.scan_edge_files), and for each new edge file run
  delta re-ingest (GraphStore.apply_delta) -> warm-start refit ->
  atomic snapshot publication (serve.snapshot.publish_snapshot with
  monotonic generations via CheckpointManager.publish_next). A running
  `cli serve --watch-snapshots` hot-swaps each generation without
  dropping queries — the full continuous pipeline the delta gate
  (scripts/delta_gate.py) proves end to end.

Batching: fold-in batches are padded to a FIXED (B, D_pow2) shape so
jit's cache serves every chunk with a handful of compilations (the same
pow2 discipline as serve.server.FoldInEngine); padding query slots carry
zero rows + zero masks and stay at zero (the ops.foldin padding
argument), and commits scatter only the real slots.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import sys
import time
from typing import Callable, List, Optional

import numpy as np


@functools.lru_cache(maxsize=32)
def _cached_foldin_fit(cfg, max_iters: int, conv_tol: float):
    """One jitted fold-in optimizer per (cfg, iters, tol) — the
    continuous loop calls warm_start_refit once per delta, and
    make_foldin_fit returns a FRESH jit wrapper each time (jax caches
    per function instance), so without this every delta would re-pay
    the while_loop compile. BigClamConfig is a frozen dataclass:
    value-equal configs hit."""
    from bigclam_tpu.ops import foldin as fi

    return fi.make_foldin_fit(cfg, max_iters=max_iters,
                              conv_tol=conv_tol)


@dataclasses.dataclass(frozen=True)
class RefitResult:
    """One warm-start refit outcome (see warm_start_refit)."""

    F: np.ndarray            # (N, K) refit affiliation matrix
    llh: float               # restricted objective of the final round
    rounds: int              # block-coordinate sweeps run
    foldin_iters: int        # total per-node fold-in iterations
    touched: int             # delta-touched rows
    refit_nodes: int         # touched + halo rows actually optimized
    touched_frac: float      # refit_nodes / N
    halo: int                # halo hops requested
    converged: bool          # round-over-round rel change < conv_tol
    escalated: bool          # divergence/plateau fired on the
    #                          restricted objective: run a full fit
    anomalies: tuple         # detector findings (dicts)
    history: tuple           # restricted objective per round
    wall_s: float


def expand_halo(
    indptr: np.ndarray,
    indices: np.ndarray,
    touched: np.ndarray,
    hops: int,
) -> np.ndarray:
    """touched rows + `hops` rings of CSR neighbors, sorted unique — the
    refit's working set. A touched node's update shifts the objective of
    its neighbors (their frozen-F terms reference its row), so hop 1 is
    the default; hop 0 refits strictly the touched rows."""
    nodes = np.unique(np.asarray(touched, np.int64))
    frontier = nodes
    for _ in range(max(int(hops), 0)):
        if frontier.size == 0:
            break
        starts = indptr[frontier]
        counts = (indptr[frontier + 1] - starts).astype(np.int64)
        total = int(counts.sum())
        if total == 0:
            break
        take = np.repeat(starts, counts) + (
            np.arange(total, dtype=np.int64)
            - np.repeat(
                np.concatenate([[0], np.cumsum(counts[:-1])]), counts
            )
        )
        nbrs = np.unique(np.asarray(indices)[take].astype(np.int64))
        frontier = nbrs[~np.isin(nbrs, nodes, assume_unique=True)]
        nodes = np.union1d(nodes, frontier)
    return nodes


def touched_rows_from_delta(raw_ids: np.ndarray, delta_path: str):
    """Internal rows touched by a delta edge file: both endpoints of
    every edge, mapped through the cache/graph raw-id table (jax-free;
    unknown ids raise — a delta cannot grow N, see
    GraphStore.apply_delta)."""
    from bigclam_tpu.graph.store import rows_of_raw_ids
    from bigclam_tpu.graph.stream import load_edge_list_streaming

    pairs = load_edge_list_streaming(delta_path)
    if pairs.size == 0:
        return np.empty(0, dtype=np.int64)
    raw_ids = np.asarray(raw_ids)
    order = np.argsort(raw_ids, kind="stable")
    flat = np.unique(pairs)
    rows, known = rows_of_raw_ids(flat, order, raw_ids[order])
    if not known.all():
        raise ValueError(
            f"{delta_path}: contains node ids absent from the graph "
            f"(e.g. {flat[~known][:3].tolist()}) — re-ingest the merged "
            "edge list instead of refitting a delta"
        )
    return np.unique(rows)


def _rel_change(new: float, old: float) -> float:
    if old == 0.0:
        return 0.0 if new == 0.0 else float("inf")
    return abs(1.0 - new / old)


def _pow2(x: int, lo: int = 1) -> int:
    return max(1 << max(int(x) - 1, 0).bit_length(), lo)


def warm_start_refit(
    model,
    F_prev: np.ndarray,
    touched,
    halo: int = 1,
    max_rounds: int = 12,
    conv_tol: Optional[float] = None,
    batch: int = 512,
    foldin_max_iters: int = 100,
    foldin_conv_tol: Optional[float] = None,
    max_deg: int = 4096,
    thresholds: Optional[dict] = None,
    callback: Optional[Callable[[int, float], None]] = None,
) -> RefitResult:
    """Incremental refit of `touched` rows (+ halo) against the frozen
    remainder, warm-started from `F_prev` (see module docstring).

    `conv_tol` (default: the model's cfg.conv_tol) is the round-over-
    round stop rule on the restricted objective; `foldin_conv_tol`
    (default: same) the per-node stop inside each fold-in batch. The
    health detectors (obs.health.run_detectors) watch the round series:
    divergence or plateau-before-tol marks the result `escalated` — the
    caller should fall back to a full fit. Works on the dense and
    sparse trainers (both expose foldin-compatible state + a
    refit_commit scatter)."""
    import jax.numpy as jnp

    from bigclam_tpu.obs import telemetry as _obs
    from bigclam_tpu.obs.health import run_detectors
    from bigclam_tpu.ops import foldin as fi
    from bigclam_tpu.serve.snapshot import pad_neighbor_batch

    t0 = time.perf_counter()
    g, cfg = model.g, model.cfg
    n = g.num_nodes
    tol = float(cfg.conv_tol if conv_tol is None else conv_tol)
    ftol = float(tol if foldin_conv_tol is None else foldin_conv_tol)
    touched = np.unique(np.asarray(touched, np.int64))
    nodes = expand_halo(g.indptr, g.indices, touched, halo)
    state = model.init_state(np.asarray(F_prev, np.float64))
    sparse = hasattr(state, "ids")
    k_pad = model.k_pad
    fit = _cached_foldin_fit(cfg, int(foldin_max_iters), ftol)
    b = min(_pow2(max(batch, 1)), _pow2(max(nodes.size, 1)))
    # padded neighbor batches depend only on the (fixed) graph and the
    # chunking — build them ONCE, not once per round
    chunks: List[tuple] = []
    for i in range(0, nodes.size, b):
        chunk = nodes[i: i + b]
        real = chunk.size
        nodes_b = np.zeros(b, np.int64)
        nodes_b[:real] = chunk
        nbr_ids, nbr_mask, _ = pad_neighbor_batch(
            g.indptr, g.indices, nodes_b, max_deg=max_deg,
            pad_deg_to=64,
        )
        nbr_mask[real:] = 0.0              # padding query slots
        chunks.append((chunk, real, nodes_b, nbr_ids, nbr_mask))
    history: List[float] = []
    samples: List[dict] = []
    anomalies: List[dict] = []
    best: Optional[float] = None
    converged = escalated = False
    rounds = 0
    foldin_iters = 0
    for r in range(max(int(max_rounds), 1)):
        round_llh = 0.0
        for chunk, real, nodes_b, nbr_ids, nbr_mask in chunks:
            nodes_dev = jnp.asarray(nodes_b)
            if sparse:
                nbr_rows = fi.densify_member_rows(
                    state.ids, state.F, jnp.asarray(nbr_ids), k_pad
                )
                own = fi.densify_rows(state.ids, state.F, nodes_dev, k_pad)
            else:
                nbr_rows = fi.gather_neighbor_rows(
                    state.F, jnp.asarray(nbr_ids)
                )
                own = state.F[nodes_dev]
            dt = nbr_rows.dtype
            mask = jnp.asarray(nbr_mask, dt)
            sel = jnp.asarray(
                (np.arange(b) < real).astype(np.float64), dt
            )[:, None]
            own = own * sel                # pad slots: zero rows, stay zero
            sumF_others = state.sumF[None, :] - own
            rows, llh, iters = fit(
                jnp.array(own), nbr_rows, mask, sumF_others
            )
            rows_h = np.asarray(rows)[:real]
            round_llh += float(np.asarray(llh)[:real].sum())
            foldin_iters += int(np.asarray(iters)[:real].sum())
            k = cfg.num_communities
            state = model.refit_commit(state, chunk, rows_h[:, :k])
        rounds = r + 1
        history.append(round_llh)
        if callback is not None:
            callback(r, round_llh)
        samples.append({"iter": r, "llh": round_llh})
        if best is None or round_llh > best:
            best = round_llh
        found = [
            a for a in run_detectors(samples, best, tol, thresholds)
            if a["check"] in ("divergence", "plateau")
        ]
        if found:
            anomalies.extend(found)
            escalated = True
            break
        if r > 0 and _rel_change(history[-1], history[-2]) < tol:
            converged = True
            break
    F = model.extract_F(state)
    wall = time.perf_counter() - t0
    res = RefitResult(
        F=F,
        llh=history[-1] if history else float("-inf"),
        rounds=rounds,
        foldin_iters=foldin_iters,
        touched=int(touched.size),
        refit_nodes=int(nodes.size),
        touched_frac=round(nodes.size / n, 6) if n else 0.0,
        halo=int(halo),
        converged=converged,
        escalated=escalated,
        anomalies=tuple(anomalies),
        history=tuple(history),
        wall_s=round(wall, 4),
    )
    tel = _obs.current()
    if tel is not None:
        tel.event(
            "refit",
            touched=res.touched,
            rounds=res.rounds,
            refit_nodes=res.refit_nodes,
            touched_frac=res.touched_frac,
            halo=res.halo,
            foldin_iters=res.foldin_iters,
            converged=res.converged,
            escalated=res.escalated,
            llh=res.llh,
            seconds=res.wall_s,
        )
        for a in anomalies:
            tel.event("anomaly", **{**a, "source": "refit"})
    return res


def follow_deltas(
    store,
    cfg,
    F_start: np.ndarray,
    publish_dir: str,
    delta_dir: str,
    model_factory: Optional[Callable] = None,
    halo: int = 1,
    max_rounds: int = 12,
    interval_s: float = 0.5,
    max_deltas: int = 0,
    timeout_s: Optional[float] = None,
    escalate: bool = True,
    quiet: bool = False,
    refit_kw: Optional[dict] = None,
) -> dict:
    """The continuous fit->publish loop (ISSUE 15 tentpole part c): poll
    `delta_dir` for new edge files, and for each run delta re-ingest ->
    warm-start refit -> publish (next generation, atomic pointer flip a
    running `cli serve` hot-swaps). Deltas already recorded in the cache
    manifest are skipped, so a restarted loop never re-applies.

    Stops after `max_deltas` processed files (0 = only the timeout
    stops it), or when no new delta arrives for `timeout_s` seconds
    (None = poll forever). An `escalated` refit (detector-flagged drift)
    falls back to a FULL fit warm-started from the refit F when
    `escalate` is True. Returns {generations, processed, escalations,
    last_step}."""
    from bigclam_tpu.graph.stream import scan_edge_files
    from bigclam_tpu.serve.snapshot import publish_snapshot
    from bigclam_tpu.utils.checkpoint import (
        CheckpointManager,
        published_step_of,
    )

    if model_factory is None:
        from bigclam_tpu.models.bigclam import BigClamModel

        def model_factory(g, c):
            return BigClamModel(
                g, c, k_multiple=128 if c.dtype == "float32" else 1
            )

    processed = {
        d.get("path") for d in store.manifest.get("deltas", [])
    }
    F_cur = np.asarray(F_start, np.float64)
    out = {
        "generations": 0, "processed": [], "skipped_empty": [],
        "failed": [], "escalations": 0, "last_step": None,
    }
    # the full-fit cost baseline propagates through every generation
    # this loop publishes, so `cli refit` cost ratios keep meaning
    # "vs a from-scratch fit" — read it off the snapshot being
    # continued (None when the chain never recorded one)
    base_wall = None
    got = CheckpointManager(publish_dir).load_published()
    if got is not None:
        bw = got[2].get("fit_wall_s")
        if isinstance(bw, (int, float)) and not isinstance(bw, bool):
            base_wall = float(bw)
    kw = dict(refit_kw or {})
    idle_since = time.monotonic()
    try:
        return _follow_loop(
            store, cfg, F_cur, publish_dir, delta_dir, model_factory,
            halo, max_rounds, interval_s, max_deltas, timeout_s,
            escalate, quiet, kw, processed, out, base_wall, idle_since,
            scan_edge_files, publish_snapshot, published_step_of,
        )
    except KeyboardInterrupt:
        # an open-ended watch is stopped by Ctrl-C: the summary (and
        # with it the caller's fit JSON + telemetry final) must
        # survive the interrupt, not vanish in a traceback
        out["interrupted"] = True
        return out


def _follow_loop(
    store, cfg, F_cur, publish_dir, delta_dir, model_factory, halo,
    max_rounds, interval_s, max_deltas, timeout_s, escalate, quiet, kw,
    processed, out, base_wall, idle_since, scan_edge_files,
    publish_snapshot, published_step_of,
) -> dict:
    while True:
        fresh = scan_edge_files(delta_dir, processed)
        if not fresh:
            if max_deltas and len(out["processed"]) >= max_deltas:
                return out
            if timeout_s is not None and (
                time.monotonic() - idle_since > timeout_s
            ):
                return out
            time.sleep(max(interval_s, 0.01))
            continue
        for path in fresh:
            try:
                info = store.apply_delta(path)
            except ValueError as e:
                # a poison delta (new node ids, torn file) must not
                # kill an hours-long loop: skip it for this session,
                # surface it, keep watching. It stays unrecorded in
                # the manifest, so a restart retries it once (and
                # logs again) in case the producer fixed the file.
                print(
                    f"[bigclam] delta {os.path.basename(path)} "
                    f"REFUSED: {e}",
                    file=sys.stderr,
                )
                processed.add(os.path.abspath(path))
                out["failed"].append(os.path.abspath(path))
                idle_since = time.monotonic()
                continue
            processed.add(info["delta_path"])
            if not info["edges_added"]:
                # empty or duplicate-only delta: the graph did not
                # change — no refit, no generation churn (and no
                # pointless serve hot-swap). Counted separately so
                # max_deltas still bounds real work.
                out["skipped_empty"].append(info["delta_path"])
                idle_since = time.monotonic()
                continue
            g = store.load_graph()
            model = model_factory(g, cfg)
            res = warm_start_refit(
                model, F_cur, info["touched_rows"], halo=halo,
                max_rounds=max_rounds, **kw,
            )
            meta = {
                "refit": True,
                "delta_seq": int(info["delta_seq"]),
                "touched_frac": res.touched_frac,
                "refit_rounds": res.rounds,
                "refit_wall_s": res.wall_s,
                # propagate the from-scratch cost baseline (see above)
                "fit_wall_s": base_wall,
            }
            F_new = res.F
            if res.escalated and escalate:
                if not quiet:
                    print(
                        f"[bigclam] refit escalated on {path}: "
                        f"{[a['check'] for a in res.anomalies]} — "
                        "running a full fit",
                        file=sys.stderr,
                    )
                full = model.fit(res.F)
                F_new = full.F
                meta["escalated_full_fit"] = True
                meta["llh"] = full.llh
                out["escalations"] += 1
            spath = publish_snapshot(
                publish_dir, step=None, F=F_new, raw_ids=g.raw_ids,
                num_edges=g.num_edges, cfg=cfg, meta=meta,
            )
            step = published_step_of(spath)
            F_cur = np.asarray(F_new, np.float64)
            out["generations"] += 1
            out["last_step"] = step
            out["processed"].append(info["delta_path"])
            if not quiet:
                print(
                    f"[bigclam] delta {os.path.basename(path)}: "
                    f"{info['edges_added']} directed edges, "
                    f"{res.refit_nodes} rows refit in {res.rounds} "
                    f"round(s) ({res.wall_s:.2f}s) -> generation {step}",
                    file=sys.stderr,
                )
            idle_since = time.monotonic()
            if max_deltas and len(out["processed"]) >= max_deltas:
                return out
