"""Checkpoint / resume for long fits and K-sweeps.

The reference had NO checkpointing (SURVEY.md §5): a crashed run restarted
from scratch, with Spark's lineage-based RDD recomputation as the only
implicit recovery. TPU pods are gang-scheduled with no in-job elasticity, so
the equivalent capability is periodic checkpointing of the full state tuple
(F, sumF, iteration, PRNG seed, K-sweep position) + restart-from-checkpoint.

Format: one .npz per checkpoint (atomic tmp+rename) with a JSON sidecar of
scalar metadata; rotation keeps the newest `keep` checkpoints. No external
dependencies (orbax users can layer it on top; this manager is deliberately
self-contained so restores work anywhere NumPy does).

Integrity (ISSUE 5 satellite): `save` stamps a crc32 PER ARRAY into the
sidecar and `restore` verifies them, so SILENT corruption (a flipped byte
the filesystem never reports) is distinguished from truncation (a lost
writeback) — both fall back to the next-older checkpoint, with the cause
named in the warning. Rotation counts only VALID checkpoints toward
`keep`: when the newest files are corrupt, the newest readable checkpoint
is never deleted out from under the resume path.

Publication (ISSUE 14 satellite): `publish`/`latest`/`load_published` are
the snapshot API a running `cli serve` hot-swaps from. A published
snapshot is the same fsync-rename + per-array-crc32 archive as a
checkpoint under a `snap_` prefix, plus an atomically-replaced
`latest.json` pointer — so fit (the publisher) and serve (the consumer)
agree on ONE publication primitive, and a reader either sees the previous
complete snapshot or the new complete snapshot, never a torn one.
A corrupted newest snapshot falls back to the previous published one at
load, exactly like restore() does for checkpoints. Published snapshots
are never rotated away by the checkpoint rotation (different prefix).

Fleet publication (ISSUE 18): `publish_fleet_next` publishes ONE
generation as S per-shard `snap_` archives (each under `shard<NNNN>/`,
the same fsync-rename + per-array-crc32 primitive) plus a
`fleet_<step>.json` generation manifest listing every shard's row range,
raw-id range, archive path and crc set. The whole publication — head
selection, every shard archive, the manifest, the latest.json flip —
runs under the SAME publish.lock as single-archive publication, so fleet
and single-process generations share one strictly-monotonic counter and
the never-backward pointer rule, fleet-wide. A serving-fleet reader
(serve.router) resolves the manifest; a shard replica loads only its own
archive — nothing ever materializes the full N-row block on one host.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import zipfile
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

# np.load on a truncated/corrupted .npz surfaces any of these depending on
# where the truncation landed (zip directory, member header, deflate stream);
# CheckpointCorruption (a ValueError) covers the sidecar-crc mismatches
_CORRUPT_ERRORS = (
    OSError, ValueError, EOFError, KeyError, zipfile.BadZipFile, zlib.error,
)


class CheckpointCorruption(ValueError):
    """A checkpoint's payload failed its per-array crc32 (silent
    corruption — the file reads fine, the bytes are wrong)."""


def _array_crc32(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def published_step_of(path: str) -> int:
    """Generation step of a published snapshot archive path — the ONE
    place that knows the `snap_<step>.npz` naming scheme outside the
    manager's own path builders (callers must never slice filenames)."""
    name = os.path.basename(path)
    if not (name.startswith("snap_") and name.endswith(".npz")):
        raise ValueError(f"{path}: not a published snapshot archive")
    return int(name[5:-4])


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        # step -> ((size, mtime_ns), valid): integrity probes are full-file
        # reads (zip member CRCs), so results are memoized per on-disk
        # identity — rotation then costs stats, not re-reads, per save
        self._valid_cache: Dict[int, Tuple[Tuple[int, int], bool]] = {}
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{step:09d}.npz")

    def _snap_path(self, step: int) -> str:
        return os.path.join(self.directory, f"snap_{step:09d}.npz")

    def _fleet_manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"fleet_{step:09d}.json")

    def _shard_dir(self, shard: int) -> str:
        return os.path.join(self.directory, f"shard{shard:04d}")

    def _write_archive(
        self,
        path: str,
        step: int,
        arrays: Dict[str, np.ndarray],
        meta: Optional[Dict[str, Any]],
    ) -> Dict[str, np.ndarray]:
        """The shared atomic-write primitive (fsync + rename, per-array
        crc32 sidecar) behind both `save` (checkpoints) and `publish`
        (serving snapshots)."""
        arrays = {k: np.asarray(v) for k, v in arrays.items()}
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
                # fsync BEFORE the rename: os.replace is atomic in the
                # namespace but not in the page cache — a preemption between
                # rename and writeback would leave a fully-named, truncated
                # checkpoint, exactly what restore must never see
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        mp = path + ".json"
        sidecar = {
            "step": step,
            "array_crc32": {k: _array_crc32(v) for k, v in arrays.items()},
            **(meta or {}),
        }
        with open(mp + ".tmp", "w") as f:
            json.dump(sidecar, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(mp + ".tmp", mp)
        return arrays

    def save(
        self,
        step: int,
        arrays: Dict[str, np.ndarray],
        meta: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Atomically write arrays + metadata for `step`, then rotate. The
        sidecar always carries a crc32 per array (restore verifies)."""
        path = self._path(step)
        arrays = {k: np.asarray(v) for k, v in arrays.items()}
        self._write_archive(path, step, arrays, meta)
        # the file we just wrote and fsynced is valid by construction:
        # seed the probe cache so rotation never re-reads it (any later
        # mutation — including the fault site below — changes its stat
        # key and forces a real probe)
        key = self._stat_key(step)
        if key is not None:
            self._valid_cache[step] = (key, True)
        # fault-injection site (resilience.faults): a truncate/corrupt here
        # models a lost page-cache writeback / silent bit flip AFTER the
        # rename — the failure class restore()'s fallback exists for
        from bigclam_tpu.resilience import faults as _faults

        spec = _faults.maybe_fire("checkpoint.save", step=step, path=path)
        if spec is not None and spec["kind"] in (
            "truncate_checkpoint", "corrupt_checkpoint"
        ):
            _faults.apply_file_fault(spec, path)
        self._rotate()
        return path

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt_") and name.endswith(".npz"):
                out.append(int(name[5:-4]))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    def latest_valid_step(self) -> Optional[int]:
        """The newest step whose archive passes the container integrity
        probe — the step restore() will actually use (modulo meta checks).
        The resume lineage records this, not the newest filename."""
        for s in reversed(self.steps()):
            if self._is_valid(s):
                return s
        return None

    def restore(
        self, step: Optional[int] = None
    ) -> Optional[Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]]:
        """Load (step, arrays, meta); newest VALID checkpoint when step is
        None — a truncated newest file (lost writeback after a preemption)
        or a silently corrupted one (per-array crc mismatch) falls back to
        the next-older checkpoint with a warning naming the cause, instead
        of crashing (or worse, resuming from) the bad state. An explicitly
        requested step propagates its error."""
        if step is not None:
            return self._load(step)
        for s in reversed(self.steps()):
            try:
                return self._load(s)
            except _CORRUPT_ERRORS as e:
                cause = (
                    "silently corrupted"
                    if isinstance(e, CheckpointCorruption)
                    else "unreadable"
                )
                print(
                    f"warning: checkpoint step {s} {cause} "
                    f"({type(e).__name__}: {e}); trying an older one",
                    file=sys.stderr,
                )
        return None

    # ------------------------------------------------ publication (serve)
    def publish(
        self,
        step: int,
        arrays: Dict[str, np.ndarray],
        meta: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Atomically publish a serving snapshot for `step` (see module
        docstring): fsync-rename archive + crc32 sidecar under the
        `snap_` prefix, then an atomic `latest.json` pointer update. The
        pointer flip is the publication instant — a concurrent reader
        resolves either the previous snapshot or this one, complete.

        The pointer NEVER moves backward (ISSUE 15 satellite): when a
        newer generation is already published, the archive is written
        but latest.json is left pointing at the newer step — a slow
        publisher losing a race cannot roll the serving fleet back.
        The check-then-flip runs under the publish lock, so two
        racing publishers cannot interleave between the read and the
        replace."""
        path = self._snap_path(step)
        self._write_archive(path, step, arrays, meta)
        with self._publish_lock():
            self._flip_pointer_locked(step)
        return path

    def _publish_lock(self):
        """Exclusive cross-process publish lock (fcntl on a lock file
        inside the snapshot dir). ONE acquisition per publication —
        fcntl locks are per open-file-description, so nesting two
        acquisitions in one process would self-deadlock; callers that
        already hold it use the *_locked helpers directly."""
        import contextlib
        import fcntl

        lock_path = os.path.join(self.directory, "publish.lock")

        @contextlib.contextmanager
        def held():
            with open(lock_path, "w") as lock:
                fcntl.flock(lock.fileno(), fcntl.LOCK_EX)
                yield

        return held()

    def _flip_pointer_locked(self, step: int) -> None:
        """Atomically point latest.json at `step` unless a NEWER
        readable generation is already published (never backward).
        Caller holds the publish lock."""
        current = self._pointer_step()
        if current is not None and current > step and (
            os.path.exists(self._snap_path(current))
            or os.path.exists(self._fleet_manifest_path(current))
        ):
            # a fleet generation is as real as a single archive: a slow
            # single-process publisher must not roll a fleet back either
            return
        lp = os.path.join(self.directory, "latest.json")
        with open(lp + ".tmp", "w") as f:
            json.dump({"step": step}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(lp + ".tmp", lp)

    def _pointer_step(self) -> Optional[int]:
        """The raw latest.json step (no archive-existence fallback)."""
        try:
            with open(os.path.join(self.directory, "latest.json")) as f:
                return int(json.load(f)["step"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def publish_next(
        self,
        arrays: Dict[str, np.ndarray],
        meta: Optional[Dict[str, Any]] = None,
    ) -> Tuple[int, str]:
        """Publish at the NEXT generation: step = newest published + 1,
        chosen and written under an exclusive file lock so concurrent
        publishers (the continuous refit loop racing a manual `cli fit
        --publish-dir`, ISSUE 15) always take strictly monotonic,
        distinct generations. Returns (step, path)."""
        with self._publish_lock():
            steps = self.published_steps()
            head = max(
                steps[-1] if steps else 0, self._pointer_step() or 0
            )
            step = head + 1
            path = self._snap_path(step)
            self._write_archive(path, step, arrays, meta)
            self._flip_pointer_locked(step)
        return step, path

    def published_steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("snap_") and name.endswith(".npz"):
                out.append(int(name[5:-4]))
        return sorted(out)

    # --------------------------------------------- fleet publication
    def fleet_steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("fleet_") and name.endswith(".json"):
                try:
                    out.append(int(name[6:-5]))
                except ValueError:
                    continue
        return sorted(out)

    def publish_fleet_next(
        self,
        shard_arrays: list,
        shard_meta: list,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Tuple[int, str]:
        """Publish the NEXT generation as per-shard archives + a fleet
        manifest (see module docstring). `shard_arrays[s]` is shard s's
        array dict (its row range only — never the full block);
        `shard_meta[s]` its sidecar meta (must carry lo/hi and, for
        routing, raw_lo/raw_hi). One lock hold covers head selection,
        every shard write, the manifest, and the pointer flip — exactly
        `publish_next`'s monotonicity contract, fleet-wide. Returns
        (step, manifest_path)."""
        if len(shard_arrays) != len(shard_meta) or not shard_arrays:
            raise ValueError(
                "publish_fleet_next needs one meta per shard array "
                f"(got {len(shard_arrays)} arrays, {len(shard_meta)} meta)"
            )
        with self._publish_lock():
            steps = self.published_steps()
            fleet = self.fleet_steps()
            head = max(
                steps[-1] if steps else 0,
                fleet[-1] if fleet else 0,
                self._pointer_step() or 0,
            )
            step = head + 1
            entries = []
            for s, (arrays, smeta) in enumerate(
                zip(shard_arrays, shard_meta)
            ):
                sub = CheckpointManager(self._shard_dir(s))
                path = sub._snap_path(step)
                written = sub._write_archive(path, step, arrays, smeta)
                entries.append(
                    {
                        "shard": s,
                        "path": os.path.relpath(path, self.directory),
                        "bytes": os.path.getsize(path),
                        "array_crc32": {
                            k: _array_crc32(v) for k, v in written.items()
                        },
                        **{
                            k: smeta[k]
                            for k in (
                                "lo", "hi", "raw_lo", "raw_hi", "n",
                                "representation",
                            )
                            if k in smeta
                        },
                    }
                )
            manifest = {
                "step": step,
                "num_shards": len(entries),
                "shards": entries,
                **(meta or {}),
            }
            mp = self._fleet_manifest_path(step)
            with open(mp + ".tmp", "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(mp + ".tmp", mp)
            self._flip_pointer_locked(step)
        return step, mp

    def latest_fleet(self) -> Optional[int]:
        """The currently-published FLEET generation: the latest.json
        pointer when it names a readable fleet manifest, else the newest
        manifest on disk. None when no fleet generation exists (the dir
        may still hold single-archive publications)."""
        ptr = self._pointer_step()
        if ptr is not None and os.path.exists(
            self._fleet_manifest_path(ptr)
        ):
            return ptr
        fleet = self.fleet_steps()
        return fleet[-1] if fleet else None

    def load_fleet_manifest(
        self, step: Optional[int] = None
    ) -> Optional[Dict[str, Any]]:
        """Decode a fleet generation manifest (latest when step=None,
        falling back past an unreadable newest one — the manifest twin
        of load_published's corrupt-newest fallback). None when no
        readable fleet manifest exists."""
        if step is not None:
            with open(self._fleet_manifest_path(step)) as f:
                return json.load(f)
        steps = self.fleet_steps()
        head = self.latest_fleet()
        if head in steps:
            steps = [s for s in steps if s <= head]
        for s in reversed(steps):
            try:
                with open(self._fleet_manifest_path(s)) as f:
                    return json.load(f)
            except (OSError, ValueError) as e:
                print(
                    f"warning: fleet manifest step {s} unreadable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous fleet generation",
                    file=sys.stderr,
                )
        return None

    def load_fleet_shard(
        self, manifest: Dict[str, Any], shard: int
    ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]:
        """Load + crc-verify ONE shard's archive of a fleet generation.
        The manifest's own per-array crc set must agree with the shard
        sidecar's (a manifest pointing at a republished/torn archive is
        corruption, not a fallback case — the generation is atomic or it
        is nothing)."""
        entry = manifest["shards"][shard]
        path = os.path.join(self.directory, entry["path"])
        step, arrays, meta = self._load_archive(path, int(manifest["step"]))
        want = entry.get("array_crc32") or {}
        for name, expect in want.items():
            if name not in arrays:
                raise CheckpointCorruption(
                    f"{path}: array {name!r} in the fleet manifest is "
                    "missing from the shard archive"
                )
            if _array_crc32(arrays[name]) != int(expect):
                raise CheckpointCorruption(
                    f"{path}: array {name!r} does not match the fleet "
                    f"manifest crc for generation {manifest['step']} — "
                    "torn or republished shard archive"
                )
        return step, arrays, meta

    def latest(self) -> Optional[int]:
        """The currently-published snapshot step: the `latest.json`
        pointer when it names a readable archive, else the newest
        published snapshot on disk (pointer lost/corrupt — the archive
        set is still authoritative). None when nothing is published."""
        lp = os.path.join(self.directory, "latest.json")
        try:
            with open(lp) as f:
                step = int(json.load(f)["step"])
            if os.path.exists(self._snap_path(step)):
                return step
        except (OSError, ValueError, KeyError, TypeError):
            pass
        steps = self.published_steps()
        return steps[-1] if steps else None

    def load_published(
        self, step: Optional[int] = None
    ) -> Optional[Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]]:
        """Load a published snapshot, crc-verified. With step=None, the
        `latest()` snapshot — falling back past a truncated/corrupted
        newest one to the PREVIOUS published snapshot (the serve-side
        twin of restore()'s fallback). An explicit step propagates its
        error."""
        if step is not None:
            return self._load_archive(self._snap_path(step), step)
        steps = self.published_steps()
        head = self.latest()
        if head in steps:
            # try the pointed-at snapshot first, then strictly older ones
            steps = [s for s in steps if s <= head]
        for s in reversed(steps):
            try:
                return self._load_archive(self._snap_path(s), s)
            except _CORRUPT_ERRORS as e:
                cause = (
                    "silently corrupted"
                    if isinstance(e, CheckpointCorruption)
                    else "unreadable"
                )
                print(
                    f"warning: published snapshot step {s} {cause} "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous published snapshot",
                    file=sys.stderr,
                )
        return None

    def _load(
        self, step: int
    ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]:
        return self._load_archive(self._path(step), step)

    def _load_archive(
        self, path: str, step: int
    ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
        meta: Dict[str, Any] = {}
        if os.path.exists(path + ".json"):
            with open(path + ".json") as f:
                meta = json.load(f)
        crcs = meta.get("array_crc32")
        if crcs:
            for name, expect in crcs.items():
                if name not in arrays:
                    raise CheckpointCorruption(
                        f"{path}: array {name!r} stamped in the sidecar is "
                        "missing from the payload"
                    )
                got = _array_crc32(arrays[name])
                if got != int(expect):
                    raise CheckpointCorruption(
                        f"{path}: array {name!r} checksum mismatch "
                        f"(expected {expect}, got {got}) — silent "
                        "corruption"
                    )
        return step, arrays, meta

    def _stat_key(self, step: int) -> Optional[Tuple[int, int]]:
        try:
            st = os.stat(self._path(step))
        except OSError:
            return None
        return (st.st_size, st.st_mtime_ns)

    def _is_valid(self, step: int) -> bool:
        """Integrity probe for rotation: the zip container's own member
        CRCs cover truncation AND byte flips without a numpy parse (npz
        members are stored with per-member crc32s). The full-file read is
        memoized against (size, mtime_ns) — any later mutation of the
        file (truncation, in-place flip) changes the key and re-probes."""
        key = self._stat_key(step)
        if key is None:
            return False
        cached = self._valid_cache.get(step)
        if cached is not None and cached[0] == key:
            return cached[1]
        try:
            with zipfile.ZipFile(self._path(step)) as z:
                ok = z.testzip() is None
        except Exception:
            ok = False
        self._valid_cache[step] = (key, ok)
        return ok

    def _rotate(self) -> None:
        """Delete old checkpoints, keeping the newest `keep` — counting
        only VALID ones: if the newest files are corrupt, the cutoff walks
        back so the newest restorable checkpoint always survives. Corrupt
        files newer than the cutoff are left in place as evidence (restore
        skips past them)."""
        if self.keep <= 0:
            return
        steps = self.steps()
        if len(steps) <= self.keep:
            return
        valid = 0
        cutoff = None
        for s in reversed(steps):
            if self._is_valid(s):
                valid += 1
                if valid == self.keep:
                    cutoff = s
                    break
        if cutoff is None:
            return      # fewer than `keep` valid checkpoints: delete nothing
        for s in steps:
            if s >= cutoff:
                continue
            p = self._path(s)
            os.unlink(p)
            self._valid_cache.pop(s, None)
            if os.path.exists(p + ".json"):
                os.unlink(p + ".json")
