"""Checkpoint / resume for long fits and K-sweeps.

The reference had NO checkpointing (SURVEY.md §5): a crashed run restarted
from scratch, with Spark's lineage-based RDD recomputation as the only
implicit recovery. TPU pods are gang-scheduled with no in-job elasticity, so
the equivalent capability is periodic checkpointing of the full state tuple
(F, sumF, iteration, PRNG seed, K-sweep position) + restart-from-checkpoint.

Format: one .npz per checkpoint (atomic tmp+rename) with a JSON sidecar of
scalar metadata; rotation keeps the newest `keep` checkpoints. No external
dependencies (orbax users can layer it on top; this manager is deliberately
self-contained so restores work anywhere NumPy does).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import zipfile
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

# np.load on a truncated/corrupted .npz surfaces any of these depending on
# where the truncation landed (zip directory, member header, deflate stream)
_CORRUPT_ERRORS = (
    OSError, ValueError, EOFError, KeyError, zipfile.BadZipFile, zlib.error,
)


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{step:09d}.npz")

    def save(
        self,
        step: int,
        arrays: Dict[str, np.ndarray],
        meta: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Atomically write arrays + metadata for `step`, then rotate."""
        path = self._path(step)
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **{k: np.asarray(v) for k, v in arrays.items()})
                # fsync BEFORE the rename: os.replace is atomic in the
                # namespace but not in the page cache — a preemption between
                # rename and writeback would leave a fully-named, truncated
                # checkpoint, exactly what restore must never see
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        if meta is not None:
            mp = path + ".json"
            with open(mp + ".tmp", "w") as f:
                json.dump({"step": step, **meta}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(mp + ".tmp", mp)
        self._rotate()
        return path

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt_") and name.endswith(".npz"):
                out.append(int(name[5:-4]))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    def restore(
        self, step: Optional[int] = None
    ) -> Optional[Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]]:
        """Load (step, arrays, meta); newest READABLE checkpoint when step
        is None — a corrupted/truncated newest file (e.g. the filesystem
        lost the writeback after a preemption) falls back to the next-older
        one with a warning instead of crashing the resume. An explicitly
        requested step propagates its error."""
        if step is not None:
            return self._load(step)
        for s in reversed(self.steps()):
            try:
                return self._load(s)
            except _CORRUPT_ERRORS as e:
                print(
                    f"warning: checkpoint step {s} unreadable "
                    f"({type(e).__name__}: {e}); trying an older one",
                    file=sys.stderr,
                )
        return None

    def _load(
        self, step: int
    ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]:
        path = self._path(step)
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
        meta: Dict[str, Any] = {}
        if os.path.exists(path + ".json"):
            with open(path + ".json") as f:
                meta = json.load(f)
        return step, arrays, meta

    def _rotate(self) -> None:
        steps = self.steps()
        for s in steps[: -self.keep] if self.keep > 0 else []:
            p = self._path(s)
            os.unlink(p)
            if os.path.exists(p + ".json"):
                os.unlink(p + ".json")
