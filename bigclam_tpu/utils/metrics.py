"""Structured per-step metrics: JSONL records + stdout.

The reference's observability was `println` of iteration count and LLH
(Bigclamv2.scala:205,213; SURVEY.md §5). Here every step emits a structured
record — iteration, LLH, relative ΔLLH, wall-clock, edges/sec — appended to
a JSONL file and/or echoed to stdout, so the BASELINE headline metric
(edges/sec/chip) is instrumented from day one.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional, TextIO


class MetricsLogger:
    def __init__(
        self,
        path: Optional[str] = None,
        echo: bool = True,
        primary_only: bool = True,
    ):
        """primary_only (default): under multi-controller jax only process 0
        writes the JSONL / echoes (N processes appending one shared file
        would interleave; see utils.dist). Pass False for per-process logs
        pointed at distinct paths.

        The gate (and the file open) are deferred to the FIRST log call:
        jax.process_index() initializes the jax backend, and loggers are
        routinely constructed before jax.distributed.initialize (e.g. the
        CLI builds the logger before the model factory joins the process
        group) — checking at construction would both crash the later init
        and read index 0 on every process.

        "t" is seconds since the FIRST log, not since construction: the
        CLI builds the logger before loading the graph, so a
        construction-stamped t0 silently folded graph-load + model-build
        time into the first step's "t". That setup time is now reported
        once as "load_s" on the first record instead."""
        self.path = path
        self.echo = echo
        self.primary_only = primary_only
        self._fh: Optional[TextIO] = None
        self._gated = False
        self._created = time.perf_counter()
        self._t0: Optional[float] = None      # stamped lazily in _gate()
        self.load_s: Optional[float] = None
        self._last_t: Optional[float] = None
        self._last_llh: Optional[float] = None

    def _gate(self) -> None:
        if self._gated:
            return
        self._gated = True
        self._t0 = time.perf_counter()
        self.load_s = round(self._t0 - self._created, 4)
        if self.primary_only:
            from bigclam_tpu.utils.dist import is_primary

            if not is_primary():
                self.path, self.echo = None, False
        if self.path:
            self._fh = open(self.path, "a")

    def log(self, record: Dict[str, Any]) -> None:
        first = not self._gated
        self._gate()
        # "t" is MONOTONIC (perf_counter) and is what durations derive
        # from; "ts" is the wall clock for correlating with external logs
        # only — the same split the telemetry events carry (obs.schema v2)
        record = {
            "t": round(time.perf_counter() - self._t0, 4),
            "ts": round(time.time(), 3),
            **record,
        }
        if first:
            record["load_s"] = self.load_s
        line = json.dumps(record)
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self.echo:
            print(line, file=sys.stderr)
        # sink of the run-telemetry layer (bigclam_tpu.obs): records land
        # in events.jsonl too (as `step`/`metric` events) when telemetry is
        # installed — EVERY process forwards; the telemetry's own
        # single-writer gate decides who writes
        from bigclam_tpu.obs import telemetry as _obs

        tel = _obs.current()
        if tel is not None:
            tel.metric_record(record)

    def step_callback(
        self,
        num_directed_edges: int,
        chips: int = 1,
        path: str = "",
        num_nodes: int = 0,
    ):
        """A fit-loop callback(it, llh, extras) that logs iter/LLH/dllh/
        edges-per-sec and — when the loop supplies it — the accepted-step
        histogram + acceptance rate (SURVEY.md §5: a fit whose line search
        collapses to 1e-15 steps or rejects everything must be visible in
        the JSONL).

        `path` is the trainer's engaged edge-sweep implementation
        (model.engaged_path: csr | csr_grouped | csr_ring | pallas_vmem |
        xla) so production metrics record which kernels actually ran.
        `num_nodes` (real, unpadded) turns the histogram into an exact
        acceptance rate: padding rows can only ever land in the rejected
        slot, so accepted counts are real-node counts by construction."""

        def cb(it: int, llh: float, extras: Optional[Dict] = None) -> None:
            now = time.perf_counter()
            rec: Dict[str, Any] = {"iter": it, "llh": llh}
            if path:
                rec["path"] = path
            if self._last_llh not in (None, 0.0):
                rec["rel_dllh"] = abs(1.0 - llh / self._last_llh)
            if self._last_t is not None:
                dt = now - self._last_t
                rec["sec_per_iter"] = round(dt, 4)
                if dt > 0:
                    rec["edges_per_sec_per_chip"] = round(
                        num_directed_edges / dt / chips, 1
                    )
            if extras and extras.get("accept_hist") is not None:
                hist = list(extras["accept_hist"])
                accepted = int(sum(hist[:-1]))
                # slot order: one count per cfg.step_candidates entry
                # (descending eta), final slot = no-accepted-step rows
                rec["accept_hist"] = hist
                if num_nodes > 0:
                    rec["accept_rate"] = round(accepted / num_nodes, 4)
            self._last_t = now
            self._last_llh = llh
            self.log(rec)

        return cb

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
