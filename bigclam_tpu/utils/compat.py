"""Version-portable shims for the handful of jax APIs that moved between
the 0.4.x and 0.5+ lines.

The trainers target current jax (jax.shard_map, the varying-mesh-axes type
system, jax.distributed.is_initialized); CI containers and some driver
hosts still carry 0.4.x, where the same capabilities live under
jax.experimental.shard_map / check_rep and the VMA types do not exist at
all. Everything here resolves AT CALL TIME (no import-order sensitivity)
and degrades to exact equivalents: check_vma maps onto check_rep, and
varying-axis marking is a no-op where the type system is absent (it was
only ever a static annotation — no math moves).
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """jax.shard_map on 0.5+; jax.experimental.shard_map.shard_map on
    0.4.x. The older check_rep inference is strictly weaker than the VMA
    type system the trainer bodies are annotated for (it cannot see
    through the psum-completed accumulators the steps return), so the
    0.4.x path always disables it — the check is a static type audit, not
    a numeric transform, and the 0.5+ path keeps it fully on."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def vma_of(x) -> frozenset:
    """The varying-mesh-axes set of x's type; empty where the VMA type
    system does not exist (jax 0.4.x)."""
    if not hasattr(jax, "typeof"):
        return frozenset()
    return getattr(jax.typeof(x), "vma", frozenset())


def pcast_varying(x, axes: tuple):
    """lax.pcast(x, axes, to="varying") on jax 0.5+; identity on 0.4.x
    (no VMA types to satisfy — the cast never moved data)."""
    from jax import lax

    if not hasattr(lax, "pcast"):
        return x
    return lax.pcast(x, axes, to="varying")


def distributed_is_initialized() -> bool:
    """jax.distributed.is_initialized, with the 0.4.x fallback of probing
    the global state object the accessor reads. On 0.4.37 the public
    jax.distributed module exposes NEITHER (no is_initialized, no
    global_state re-export) — the state object lives only at
    jax._src.distributed.global_state, so the probe goes there last."""
    dist = jax.distributed
    if hasattr(dist, "is_initialized"):
        return dist.is_initialized()
    state = getattr(dist, "global_state", None)
    if state is None:
        try:
            from jax._src import distributed as _src_dist

            state = getattr(_src_dist, "global_state", None)
        except Exception:
            state = None
    return bool(state is not None and state.client is not None)
