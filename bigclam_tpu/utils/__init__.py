from bigclam_tpu.utils.checkpoint import CheckpointManager
from bigclam_tpu.utils.dist import is_primary
from bigclam_tpu.utils.metrics import MetricsLogger

__all__ = ["CheckpointManager", "MetricsLogger", "is_primary"]
