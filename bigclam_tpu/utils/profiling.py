"""Profiler hooks: jax.profiler trace scopes (SURVEY.md §5 — the reference
had none; `println` was its only instrumentation)."""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into log_dir (tensorboard-viewable);
    no-op when log_dir is None."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named sub-scope inside a trace (shows up on the TPU timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
