"""Profiler hooks: jax.profiler trace scopes (SURVEY.md §5 — the reference
had none; `println` was its only instrumentation), plus the step-time /
comm-hidden-fraction hooks consumed by bench.py and
scripts/weak_scaling.py."""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into log_dir (tensorboard-viewable);
    no-op when log_dir is None. Flags the capture to the span tracer
    (obs.trace) so per-iteration emit=False spans open TraceAnnotations
    for the duration — the captured timeline then carries the span names
    while the no-capture fast path stays annotation-free."""
    if log_dir is None:
        yield
        return
    import jax

    from bigclam_tpu.obs import trace as _trace

    with jax.profiler.trace(log_dir):
        _trace.capture_started()
        try:
            yield
        finally:
            _trace.capture_stopped()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named sub-scope inside a trace (shows up on the TPU timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class StageProfile:
    """Per-stage wall-clock + event counters for multi-stage host-driven
    schedules — the quality pipeline's anneal/repair/atomize stages and
    their host<->device transfer counts (models.quality), surfaced in the
    QUALITY_* artifacts via scripts/quality_gate.py.

    Why it exists (VERDICT round-5 weak #3): the quality stage was a
    single 644.7s number at the midscale config — per-stage attribution
    (annealing fits vs component scans vs polish refits) and the number
    of full-F transfers were folklore, and the "<= 1 F download per
    repair round" residency contract of the device schedule was not
    measurable, let alone testable. Counters are incremented at the
    actual fetch/upload sites, so tests pin the contract against the
    same numbers the artifacts report.

    Re-entering a stage accumulates (stages are wall-clock buckets, not a
    call tree); `count` is a plain event counter. `report()` returns the
    JSON-ready {"seconds": {...}, "counts": {...}} dict artifacts embed.

    SINK of the run telemetry layer (bigclam_tpu.obs): every completed
    stage additionally forwards (name, seconds) to the installed
    RunTelemetry — which logs a `stage` event, samples a device-memory
    watermark at the stage boundary, and beats the stall heartbeat. With
    telemetry off the forward is one None check.
    """

    def __init__(self) -> None:
        self.seconds: dict = {}
        self.counts: dict = {}

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        import time

        from bigclam_tpu.obs import trace as _trace

        # every stage is ALSO a span (obs.trace): same name, nested under
        # whatever span is open, so stage buckets and the hierarchical
        # span taxonomy agree by construction (ISSUE 6 acceptance)
        with _trace.span(name):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self.seconds[name] = self.seconds.get(name, 0.0) + dt
                _telemetry_stage(name, dt)

    def add_seconds(self, name: str, s: float) -> None:
        """Accumulate into a stage bucket without the context manager
        (for loops whose body already lives inside another `with`).
        Bridges into the span taxonomy too (trace.add_span) so
        self-timed stages still appear in the per-span breakdown."""
        from bigclam_tpu.obs import trace as _trace

        self.seconds[name] = self.seconds.get(name, 0.0) + s
        _trace.add_span(name, s)
        _telemetry_stage(name, s)

    def count(self, name: str, inc: int = 1) -> None:
        self.counts[name] = self.counts.get(name, 0) + inc

    def report(self) -> dict:
        return {
            "seconds": {k: round(v, 3) for k, v in self.seconds.items()},
            "counts": dict(self.counts),
        }


def _telemetry_stage(name: str, seconds: float) -> None:
    """Forward a completed stage to the installed RunTelemetry (lazy import:
    profiling is loaded by jax-free paths and must stay dependency-light)."""
    from bigclam_tpu.obs import telemetry

    tel = telemetry.current()
    if tel is not None:
        tel.stage_complete(name, seconds)


def current_rss_bytes() -> int:
    """This process's resident set right now (/proc/self/statm; Linux)."""
    try:
        import os

        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return peak_rss_bytes()        # no /proc: lifetime peak as fallback


def peak_rss_bytes() -> int:
    """Process-lifetime peak RSS (ru_maxrss; KiB on Linux)."""
    import resource
    import sys

    scale = 1024 if sys.platform != "darwin" else 1
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * scale


class IngestProfile(StageProfile):
    """StageProfile + host-RSS tracking for the streaming ingest pipeline
    (graph/store.compile_graph_cache; surfaced by `cli ingest` and
    scripts/ingest_bench.py in INGEST_* artifacts).

    The store's bounded-memory contract — peak RSS O(chunk + bucket + N),
    never O(file) — is only a contract if it's measured: `sample_rss()` is
    called at chunk/bucket granularity inside the compile stages, so the
    reported peak is the steady-state footprint of the out-of-core build
    sampled where the transients actually live. The report records the
    baseline taken at construction, the sampled peak, their delta (the
    ingest's own footprint, independent of whatever the host process had
    already mapped), and the process-lifetime ru_maxrss for cross-checking.
    `count("raw_edges", m)` at the parse sites feeds the edges/sec figure.

    Scope: THIS process only. With parse workers (spawn pool), the
    tokenizer transients live in the children and are not counted here —
    the bounded-RSS gate (scripts/ingest_bench.py) therefore measures
    workers=0, where the budget model's per-chunk transient is actually
    resident in the sampled process.
    """

    def __init__(self) -> None:
        super().__init__()
        self.rss_baseline = current_rss_bytes()
        self.rss_peak = self.rss_baseline

    def sample_rss(self) -> int:
        rss = current_rss_bytes()
        if rss > self.rss_peak:
            self.rss_peak = rss
        return rss

    def report(self) -> dict:
        rep = super().report()
        rep["rss"] = {
            "baseline_bytes": self.rss_baseline,
            "peak_sampled_bytes": self.rss_peak,
            "delta_bytes": self.rss_peak - self.rss_baseline,
            "process_peak_bytes": peak_rss_bytes(),
        }
        # two rates, explicitly (the old single figure divided raw_edges by
        # the sum of ALL stage buckets — scatter/dedup/shard-write included
        # — understating parse throughput): "scan" is the parse stage, the
        # all-stage sum is the end-to-end pipeline rate. edges_per_sec stays
        # as the end-to-end alias existing artifact consumers read.
        total_s = sum(self.seconds.values())
        parse_s = self.seconds.get("scan", 0.0)
        edges = self.counts.get("raw_edges", 0)
        if edges and total_s > 0:
            rep["edges_per_sec"] = round(edges / total_s, 1)
            rep["edges_per_sec_end_to_end"] = rep["edges_per_sec"]
        if edges and parse_s > 0:
            rep["edges_per_sec_parse"] = round(edges / parse_s, 1)
        return rep


def step_time(step_fn, state, steps: int = 5, warmup: int = 1) -> float:
    """Wall-clock seconds per compiled training step.

    Runs `warmup` un-timed steps (compilation + steady state), then times
    `steps` chained steps and blocks on the final F. The state threads
    through, so the measurement covers the real dependency chain — exactly
    what the fit loop pays per iteration."""
    import time

    import jax

    for _ in range(max(warmup, 0)):
        state = step_fn(state)
    jax.block_until_ready(state.F)
    t0 = time.perf_counter()
    for _ in range(max(steps, 1)):
        state = step_fn(state)
    jax.block_until_ready(state.F)
    return (time.perf_counter() - t0) / max(steps, 1)


def comm_hidden_fraction(overlap_s: float, serial_s: float) -> float:
    """Fraction of the FORCED-serial step time the overlapped schedule
    eliminated: (serial - overlap) / serial, clamped at 0. The single
    definition shared by overlap_report and scripts/weak_scaling.py.
    The serial baseline pins sweep->hop ordering with a barrier, so this
    is the hop time overlapping CAN hide — an upper bound on the win over
    a scheduler that already overlapped some of it."""
    if serial_s <= 0:
        return 0.0
    return round(max(1.0 - overlap_s / serial_s, 0.0), 4)


def overlap_report(model, state, steps: int = 5, warmup: int = 1) -> dict:
    """Time a ring trainer's step under BOTH rotation schedules and report
    the communication-hiding win (the hook ISSUE 1 instruments; consumed by
    bench.py's ring config and scripts/weak_scaling.py).

    Rebuilds the model's step with cfg.ring_overlap toggled (steps are
    cached by step_cfg_key, so each schedule compiles once) and restores
    the original cfg/step afterwards. comm_hidden_fraction is the fraction
    of the SERIAL step time the double-buffered schedule eliminated,
    (serial - overlap) / serial, clamped at 0 — on hardware it approaches
    the rotations' hop time share when the edge sweep outlasts the shard
    transfer; on the shared-core CPU fake it is noise around 0 (there is no
    async interconnect to hide) and only the plumbing is exercised.

    Returns {"sec_per_step": {"overlap": s, "serial": s},
             "comm_hidden_fraction": f}.
    """
    from bigclam_tpu.obs import trace as _trace

    cfg0 = model.cfg
    times = {}
    # the probe IS the ring's wait-vs-compute measurement (rotation waits
    # cannot be timed from inside the jitted scan): fold it into the span
    # taxonomy — one parent span carrying the verdict fields, one child
    # per schedule timing (ISSUE 6: overlap_report rides the span log)
    with _trace.span("ring_overlap_probe") as probe:
        try:
            for name, flag in (("overlap", True), ("serial", False)):
                with _trace.span(name):
                    model.cfg = cfg0.replace(ring_overlap=flag)
                    model.rebuild_step()
                    times[name] = step_time(model._step, state, steps,
                                            warmup)
        finally:
            model.cfg = cfg0
            model.rebuild_step()
        rep = {
            "sec_per_step": {k: round(v, 6) for k, v in times.items()},
            "comm_hidden_fraction": comm_hidden_fraction(
                times["overlap"], times["serial"]
            ),
        }
        probe.set(**rep)
    return rep
