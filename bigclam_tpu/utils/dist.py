"""Single-writer gating for multi-controller runs.

Under multi-controller JAX every process executes the same program, so any
host-side file write (checkpoints, sweep journals, metrics JSONL) would be
raced by N processes renaming onto the same shared-directory paths. The
convention here (and in jax ecosystem tools generally) is that process 0 is
the sole writer; every process still READS checkpoints on resume, which
assumes the checkpoint directory is on a filesystem all hosts share (true
for the GCS/NFS setups multi-host TPU jobs run on).
"""

from __future__ import annotations


def is_primary() -> bool:
    """True on the process that owns shared-filesystem writes (process 0).

    Trivially True single-process; safe to call before jax.distributed
    initialization (process_index is 0 then).
    """
    import jax

    return jax.process_index() == 0
