"""Single-writer gating for multi-controller runs.

Under multi-controller JAX every process executes the same program, so any
host-side file write (checkpoints, sweep journals, metrics JSONL) would be
raced by N processes renaming onto the same shared-directory paths. The
convention here (and in jax ecosystem tools generally) is that process 0 is
the sole writer; every process still READS checkpoints on resume, which
assumes the checkpoint directory is on a filesystem all hosts share (true
for the GCS/NFS setups multi-host TPU jobs run on).
"""

from __future__ import annotations


def request_cpu_devices(n: int) -> None:
    """Provision `n` virtual CPU devices, portably across jax generations.

    jax >= 0.5 exposes the `jax_num_cpu_devices` config option; 0.4.x only
    honors `XLA_FLAGS=--xla_force_host_platform_device_count`, which the
    backend reads at init — so either way this must run before the first
    backend use (jax.devices() etc.). Callers that need a hard guarantee
    should check len(jax.devices()) afterwards; once a backend is up,
    neither mechanism can resize it.
    """
    import os

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:           # jax < 0.5: env-flag fallback
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n}"
        if "xla_force_host_platform_device_count" in flags:
            # REPLACE an inherited count (a pytest parent exports 8; a
            # spawned two-process worker must drop to its own 2)
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags


def is_primary() -> bool:
    """True on the process that owns shared-filesystem writes (process 0).

    Trivially True single-process; safe to call before jax.distributed
    initialization (process_index is 0 then).
    """
    import jax

    return jax.process_index() == 0
