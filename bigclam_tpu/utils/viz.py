"""Visualization export (C23, SURVEY.md §2): Gephi-compatible GEXF.

The reference ships only static rendered figures (README.md:8-10 img.png /
BigClamK_1sp.png — a community-colored facebook graph drawn externally).
The equivalent capability here is a structured export: graph + per-node
community attributes in GEXF 1.2, which Gephi/Cytoscape/networkx open
directly. Pure-python writer, no dependencies.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional
from xml.sax.saxutils import escape

import numpy as np

from bigclam_tpu.graph.csr import Graph


def export_gexf(
    path: str,
    g: Graph,
    communities: Optional[Dict[int, Iterable[int]]] = None,
    F: Optional[np.ndarray] = None,
    max_edges: Optional[int] = None,
) -> None:
    """Write the graph (undirected, deduped) with community attributes.

    Per node: `community` = its primary community (argmax F when F given,
    else the first community containing it; -1 when none) and
    `n_communities` = overlap count. `max_edges` caps output size for
    viewer-friendly files (edges are kept in CSR order).
    """
    n = g.num_nodes
    primary = np.full(n, -1, dtype=np.int64)
    overlap = np.zeros(n, dtype=np.int64)
    if communities is not None:
        for cid in sorted(communities):
            members = np.asarray(list(communities[cid]), dtype=np.int64)
            overlap[members] += 1
            unset = members[primary[members] == -1]
            primary[unset] = cid
    if F is not None:
        has_mass = np.asarray(F).max(axis=1) > 0
        primary[has_mass] = np.asarray(F).argmax(axis=1)[has_mass]
    und = g.src < g.dst                       # one direction per edge
    src, dst = g.src[und], g.dst[und]
    if max_edges is not None and src.size > max_edges:
        src, dst = src[:max_edges], dst[:max_edges]
    with open(path, "w") as f:
        f.write(
            '<?xml version="1.0" encoding="UTF-8"?>\n'
            '<gexf xmlns="http://gexf.net/1.2" version="1.2">\n'
            '  <graph defaultedgetype="undirected">\n'
            '    <attributes class="node">\n'
            '      <attribute id="0" title="community" type="long"/>\n'
            '      <attribute id="1" title="n_communities" type="long"/>\n'
            "    </attributes>\n    <nodes>\n"
        )
        for u in range(n):
            f.write(
                f'      <node id="{u}" label="{escape(str(u))}">'
                f'<attvalues><attvalue for="0" value="{primary[u]}"/>'
                f'<attvalue for="1" value="{overlap[u]}"/></attvalues>'
                "</node>\n"
            )
        f.write("    </nodes>\n    <edges>\n")
        for i in range(src.size):
            f.write(f'      <edge id="{i}" source="{src[i]}" target="{dst[i]}"/>\n')
        f.write("    </edges>\n  </graph>\n</gexf>\n")
