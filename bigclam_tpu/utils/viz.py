"""Visualization export (C23, SURVEY.md §2): Gephi-compatible GEXF.

The reference ships only static rendered figures (README.md:8-10 img.png /
BigClamK_1sp.png — a community-colored facebook graph drawn externally).
The equivalent capability here is a structured export: graph + per-node
community attributes in GEXF 1.2, which Gephi/Cytoscape/networkx open
directly. Pure-python writer, no dependencies.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional
from xml.sax.saxutils import escape

import numpy as np

from bigclam_tpu.graph.csr import Graph


DEFAULT_MAX_NODES = 100_000
DEFAULT_MAX_EDGES = 1_000_000
_CHUNK = 65536


def export_gexf(
    path: str,
    g: Graph,
    communities: Optional[Dict[int, Iterable[int]]] = None,
    F: Optional[np.ndarray] = None,
    max_edges: Optional[int] = DEFAULT_MAX_EDGES,
    max_nodes: Optional[int] = DEFAULT_MAX_NODES,
) -> None:
    """Write the graph (undirected, deduped) with community attributes.

    Per node: `community` = its primary community (argmax F when F given,
    else the first community containing it; -1 when none) and
    `n_communities` = overlap count.

    GEXF is a per-element XML format for interactive viewers — useless (and
    enormous) at the graph sizes this framework trains on — so output is
    bounded by default: the first `max_nodes` node ids and the `max_edges`
    first CSR-order edges among them (pass None to lift either bound
    explicitly). Rows are rendered in chunked ''.join batches, not one
    f-string write per element (round-1/2 perf finding).
    """
    n = g.num_nodes
    primary = np.full(n, -1, dtype=np.int64)
    overlap = np.zeros(n, dtype=np.int64)
    if communities is not None:
        for cid in sorted(communities):
            members = np.asarray(list(communities[cid]), dtype=np.int64)
            overlap[members] += 1
            unset = members[primary[members] == -1]
            primary[unset] = cid
    if F is not None:
        has_mass = np.asarray(F).max(axis=1) > 0
        primary[has_mass] = np.asarray(F).argmax(axis=1)[has_mass]
    n_out = n if max_nodes is None else min(n, max_nodes)
    und = (g.src < g.dst) & (g.dst < n_out)   # one direction, kept nodes
    src, dst = g.src[und], g.dst[und]
    if max_edges is not None and src.size > max_edges:
        src, dst = src[:max_edges], dst[:max_edges]
    with open(path, "w") as f:
        f.write(
            '<?xml version="1.0" encoding="UTF-8"?>\n'
            '<gexf xmlns="http://gexf.net/1.2" version="1.2">\n'
            '  <graph defaultedgetype="undirected">\n'
            '    <attributes class="node">\n'
            '      <attribute id="0" title="community" type="long"/>\n'
            '      <attribute id="1" title="n_communities" type="long"/>\n'
            "    </attributes>\n    <nodes>\n"
        )
        for lo in range(0, n_out, _CHUNK):
            hi = min(lo + _CHUNK, n_out)
            f.write(
                "".join(
                    f'      <node id="{u}" label="{escape(str(u))}">'
                    f'<attvalues><attvalue for="0" value="{primary[u]}"/>'
                    f'<attvalue for="1" value="{overlap[u]}"/></attvalues>'
                    "</node>\n"
                    for u in range(lo, hi)
                )
            )
        f.write("    </nodes>\n    <edges>\n")
        for lo in range(0, src.size, _CHUNK):
            hi = min(lo + _CHUNK, src.size)
            s_c, d_c = src[lo:hi].tolist(), dst[lo:hi].tolist()
            f.write(
                "".join(
                    f'      <edge id="{i}" source="{s}" target="{d}"/>\n'
                    for i, (s, d) in enumerate(zip(s_c, d_c), start=lo)
                )
            )
        f.write("    </edges>\n  </graph>\n</gexf>\n")
