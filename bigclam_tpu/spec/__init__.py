from bigclam_tpu.spec.interpreter import SpecState, grad_llh, line_search_step, fit

__all__ = ["SpecState", "grad_llh", "line_search_step", "fit"]
