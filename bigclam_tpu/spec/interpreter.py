"""Spec interpreter: the reference's exact semantics in plain NumPy (float64).

This is the normative oracle (SURVEY.md §4.2) for every device kernel in the
framework: ~200 lines of obviously-correct NumPy that reproduce the
reference's per-node LLH/gradient math (Bigclamv2.scala:121-133, SURVEY.md
§2.1), the 16-candidate Armijo backtracking line search with max-accepted-step
selection (Bigclamv2.scala:136-146), and the Jacobi-style simultaneous update
(all nodes updated at once per outer iteration, Bigclamv2.scala:145-155).

Semantics notes (quirk decisions, SURVEY.md §2.3):
  * The reference's "pass-3" LLH (Bigclamv2.scala:158-181) looks mixed-state
    but substitutes updated rows for BOTH endpoints of every edge and the
    updated sumF — it equals the plain LLH of the post-update state. We
    compute exactly that (LLH(F_new, colsum(F_new))).
  * sumF is recomputed as column sums each step instead of incrementally
    updated (fixes the float-drift quirk Q7; values agree in exact arithmetic).
  * Node ids are contiguous [0, N) (ingest remaps), so the reference's
    missing-row fallback (C10) cannot trigger.

Model (SURVEY.md §2.1): P(edge u,v) = 1 - exp(-F_u . F_v), F in R^{N x K}, F >= 0.

  ell(u) = sum_{v in N(u)} [ log(1 - clip(exp(-F_u.F_v), min_p, max_p)) + F_u.F_v ]
           - F_u . sumF + F_u . F_u
  grad_u = sum_{v in N(u)} F_v / (1 - clip(exp(-F_u.F_v))) - sumF + F_u
"""

from __future__ import annotations

import dataclasses

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph


@dataclasses.dataclass
class SpecState:
    F: np.ndarray        # (N, K) float64, >= 0
    sumF: np.ndarray     # (K,) float64 — column sums of F
    llh: float           # LLH of the current F (post-update)
    num_iters: int = 0


def _edge_terms(F_src_rows, F_dst_rows, cfg: BigClamConfig):
    """Per-directed-edge dot, clipped prob, and LLH term log(1-p) + x."""
    x = np.einsum("ek,ek->e", F_src_rows, F_dst_rows)
    p = np.clip(np.exp(-x), cfg.min_p, cfg.max_p)
    # DELIBERATE form divergence from the implementation: the spec keeps
    # the reference's own f64 subtraction 1 - clip(exp(-x)) (the Scala
    # code's arithmetic), while every production path computes the
    # survival directly as clip(-expm1(-x), ...) (ops.objective.edge_terms)
    # for f32 stability under the quality-mode MAX_P_ relaxation. In f64
    # at parity clips the two agree to ~1e-15 relative (the trajectory
    # equality tests pin this); in the RELAXED regime (max_p -> 1-1e-15)
    # the spec's subtraction collapses first — the spec is the REFERENCE
    # oracle, not an oracle for the relaxed extension.
    return x, p, np.log(1.0 - p) + x


def grad_llh(F, sumF, g: Graph, cfg: BigClamConfig):
    """Per-node gradient and per-node LLH in one pass (Bigclamv2.scala:121-133).

    Returns (grad (N,K), node_llh (N,)).
    """
    n = g.num_nodes
    src, dst = g.src, g.dst
    x, p, ell_e = _edge_terms(F[src], F[dst], cfg)
    nbr_llh = np.zeros(n)
    np.add.at(nbr_llh, src, ell_e)
    coeff = 1.0 / (1.0 - p)                      # folds the +sum F_v term (§2.1)
    nbr_grad = np.zeros_like(F)
    np.add.at(nbr_grad, src, F[dst] * coeff[:, None])
    grad = nbr_grad - sumF[None, :] + F
    node_llh = nbr_llh - F @ sumF + np.einsum("nk,nk->n", F, F)
    return grad, node_llh


def loglikelihood(F, sumF, g: Graph, cfg: BigClamConfig) -> float:
    """Global LLH = sum of per-node LLH (Bigclamv2.scala:187-200)."""
    src, dst = g.src, g.dst
    _, _, ell_e = _edge_terms(F[src], F[dst], cfg)
    node_tail = -F @ sumF + np.einsum("nk,nk->n", F, F)
    return float(ell_e.sum() + node_tail.sum())


def line_search_step(F, sumF, g: Graph, cfg: BigClamConfig):
    """One outer iteration: grad/LLH pass, 16-candidate Armijo search,
    Jacobi simultaneous update. Returns (F_new, sumF_new, post_llh).

    Candidate evaluation follows Bigclamv2.scala:136-144 exactly: the
    candidate row F_u' = clip(F_u + eta*grad_u) is scored against everyone
    else's OLD rows, with sumF' = sumF - F_u + F_u' (node-local adjustment),
    and accepted iff ell_eta(u) >= ell(u) + alpha*eta*||grad_u||^2.
    The chosen step is the LARGEST accepted eta (groupByKey.max,
    Bigclamv2.scala:145); nodes with no accepted candidate keep their row.
    """
    n = g.num_nodes
    src, dst = g.src, g.dst
    grad, node_llh = grad_llh(F, sumF, g, cfg)
    gg = np.einsum("nk,nk->n", grad, grad)

    best_eta = np.zeros(n)
    accepted = np.zeros(n, dtype=bool)
    F_dst = F[dst]
    for eta in cfg.step_candidates:
        newF = np.clip(F + eta * grad, cfg.min_f, cfg.max_f)
        _, _, ell_e = _edge_terms(newF[src], F_dst, cfg)
        nbr = np.zeros(n)
        np.add.at(nbr, src, ell_e)
        sf_adj = sumF[None, :] - F + newF      # per-node adjusted sumF
        cand_llh = (
            nbr
            - np.einsum("nk,nk->n", newF, sf_adj)
            + np.einsum("nk,nk->n", newF, newF)
        )
        ok = cand_llh >= node_llh + cfg.alpha * eta * gg
        # max accepted step, independent of candidate evaluation order
        best_eta = np.where(ok, np.maximum(best_eta, eta), best_eta)
        accepted |= ok

    F_new = np.where(
        accepted[:, None],
        np.clip(F + best_eta[:, None] * grad, cfg.min_f, cfg.max_f),
        F,
    )
    sumF_new = F_new.sum(axis=0)
    post_llh = loglikelihood(F_new, sumF_new, g, cfg)
    return F_new, sumF_new, post_llh


def fit(F0, g: Graph, cfg: BigClamConfig, verbose: bool = False) -> SpecState:
    """Full training loop (MBSGD, Bigclamv2.scala:203-219): iterate line-search
    steps until |1 - LLH_new/LLH_old| < conv_tol, starting from the true
    initial LLH (v2 semantics; v3 starts from 0.0 — quirk Q4, not replicated).
    """
    F = np.asarray(F0, dtype=np.float64)
    sumF = F.sum(axis=0)
    llh_old = loglikelihood(F, sumF, g, cfg)
    if verbose:
        print(f"LLH: {llh_old}")
    it = 0
    while it < cfg.max_iters:
        F, sumF, llh = line_search_step(F, sumF, g, cfg)
        it += 1
        if verbose:
            print(f" Iter: {it} LLH: {llh}")
        if abs(1.0 - llh / llh_old) < cfg.conv_tol:
            llh_old = llh
            break
        llh_old = llh
    return SpecState(F=F, sumF=sumF, llh=llh_old, num_iters=it)
