"""Conductance-based seeding: ego-net conductance, locally-minimal ranking,
and the conductance-seeded F initializer.

Replaces C4-C7 (SURVEY.md §2; reference Bigclamv2.scala:37-96): the reference
computed, for every node u with ego-net S(u) = {u} ∪ N(u), the multiset z of
all members' neighbor lists, then

    cut_S = #entries of z outside S          (Bigclamv2.scala:49)
    vol_S = |z| - cut_S                      (Bigclamv2.scala:50)
    vol_T = 2E - vol_S - 2*cut_S             (Bigclamv2.scala:51)
    phi   = 0 if vol_S==0 else 1 if vol_T==0 else cut_S/min(vol_S, vol_T)

— a two-hop sweep per node. Here the same quantities come from closed forms
over per-node triangle counts (tri(u) = #edges among N(u)):

    |z|    = deg(u) + S1(u),   S1(u) = sum_{v in N(u)} deg(v)
    vol_S  = 2*deg(u) + 2*tri(u)          (ordered intra-ego edges)
    cut_S  = S1(u) - deg(u) - 2*tri(u)

so the whole scorer is one common-neighbor pass + segment sums. Two backends:
a NumPy host pass (one vectorized gather per node) and a dense-adjacency
device pass (A@A on the MXU) for graphs that fit an (N, N) tile; the C++
masked-SpGEMM backend in graph/native is used when built.

Seed ranking (Bigclamv2.scala:56; bigclamv3-7.scala:51): each node nominates
its minimum-conductance neighbor (neighbor-less nodes nominate themselves at
the sentinel phi = 10.0, the v3 fix); nominees are deduplicated and ranked by
ascending phi. NOTE a reference quirk (documented in PARITY.md): its Scala
``.min`` on (id, phi) tuples is lexicographic — it nominates the *smallest-id*
neighbor, not the min-phi one. We implement the intended min-phi semantics
(tie-broken by id for determinism), as in Yang & Leskovec's locally-minimal
neighborhood seeding.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph

# Above this node count the dense (N, N) device adjacency no longer fits
# comfortably in HBM; use the host/native sparse path instead.
DENSE_DEVICE_MAX_NODES = 16384
# float32 matmul accumulators are exact only below 2^24; 2*tri(u) <= deg(u)^2,
# so cap the degree the dense backend accepts
DENSE_DEVICE_MAX_DEGREE = 4095


def triangle_counts(g: Graph) -> np.ndarray:
    """tri(u) = number of edges among N(u) (= triangles through u).

    Host pass: per node u, one boolean-mask gather over the concatenated
    neighbor lists of N(u); sum of hits double-counts intra-neighborhood
    edges, so tri(u) = hits / 2. Cost O(sum_v deg(v)^2) total.
    """
    try:
        from bigclam_tpu.graph.native import triangle_counts as _native

        out = _native(g)
        if out is not None:
            return out
    except ImportError:
        pass
    n = g.num_nodes
    indptr, indices = g.indptr, g.indices
    flags = np.zeros(n, dtype=bool)
    tri = np.zeros(n, dtype=np.int64)
    for u in range(n):
        nbrs = indices[indptr[u] : indptr[u + 1]]
        if nbrs.size == 0:
            continue
        flags[nbrs] = True
        z = np.concatenate([indices[indptr[v] : indptr[v + 1]] for v in nbrs])
        tri[u] = np.count_nonzero(flags[z]) // 2
        flags[nbrs] = False
    return tri


def triangle_counts_dense_device(g: Graph) -> np.ndarray:
    """Device backend: tri = rowsum(A@A * A) / 2 on a dense adjacency.

    The A@A contraction maps straight onto the MXU; only viable while the
    (N, N) tile fits HBM (DENSE_DEVICE_MAX_NODES) and counts stay exactly
    representable in the float32 accumulator (DENSE_DEVICE_MAX_DEGREE).
    """
    import jax.numpy as jnp

    if g.degrees.size and int(g.degrees.max()) > DENSE_DEVICE_MAX_DEGREE:
        raise ValueError(
            f"max degree {int(g.degrees.max())} exceeds float32-exact bound "
            f"{DENSE_DEVICE_MAX_DEGREE}; use the host backend"
        )
    n = g.num_nodes
    A = np.zeros((n, n), dtype=np.float32)
    A[g.src, g.dst] = 1.0
    Ad = jnp.asarray(A)
    tri = jnp.einsum("ij,jk,ik->i", Ad, Ad, Ad) / 2.0
    return np.asarray(jnp.round(tri)).astype(np.int64)


def conductance(g: Graph, backend: str = "auto") -> np.ndarray:
    """Ego-net conductance phi(u) for every node (float64)."""
    deg = g.degrees
    two_e = float(g.num_directed_edges)
    if backend == "dense" or (
        backend == "auto"
        and 0 < g.num_nodes <= DENSE_DEVICE_MAX_NODES
        and (deg.size == 0 or int(deg.max()) <= DENSE_DEVICE_MAX_DEGREE)
    ):
        tri = triangle_counts_dense_device(g)
    else:
        tri = triangle_counts(g)
    s1 = np.zeros(g.num_nodes)
    np.add.at(s1, g.src, deg[g.dst].astype(np.float64))
    cut = s1 - deg - 2.0 * tri
    vol_s = 2.0 * deg + 2.0 * tri
    vol_t = two_e - vol_s - 2.0 * cut
    phi = np.where(
        vol_s == 0,
        0.0,
        np.where(vol_t == 0, 1.0, cut / np.maximum(np.minimum(vol_s, vol_t), 1e-300)),
    )
    return phi


def rank_seeds(g: Graph, phi: np.ndarray, cfg: Optional[BigClamConfig] = None
               ) -> np.ndarray:
    """Locally-minimal seed ranking (intended semantics of Bigclamv2.scala:56).

    Each node nominates argmin_{v in N(u)} (phi(v), v); neighbor-less nodes
    nominate themselves at the sentinel phi (bigclamv3-7.scala:51). Returns
    nominee ids deduplicated, sorted ascending by (phi, id).
    """
    cfg = cfg or BigClamConfig()
    n = g.num_nodes
    indptr, indices = g.indptr, g.indices
    if indices.size == 0:
        # every node self-nominates at the sentinel; rank ties by id
        return np.arange(n, dtype=np.int64)
    # segmented argmin over each neighbor list on the key (phi(v), v),
    # vectorized: sort all directed edges by (src, phi(dst), dst) and take
    # the first entry of every segment
    phi_nbr = phi[indices]
    order = np.lexsort((indices, phi_nbr, g.src))
    starts = indptr[:-1]
    has_nbrs = g.degrees > 0
    nominee = np.arange(n, dtype=np.int64)          # self-nomination default
    nominee_phi = np.full(n, float(cfg.isolated_phi_sentinel))
    first_in_seg = order[np.minimum(starts, indices.size - 1)]
    nominee[has_nbrs] = indices[first_in_seg[has_nbrs]]
    nominee_phi[has_nbrs] = phi_nbr[first_in_seg[has_nbrs]]
    cand, first = np.unique(nominee, return_index=True)
    cand_phi = nominee_phi[first]
    rank = np.lexsort((cand, cand_phi))
    return cand[rank]


def init_F(
    g: Graph,
    seeds: np.ndarray,
    cfg: BigClamConfig,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Conductance-seeded F0 (C7; Bigclamv2.scala:65-96).

    Community k's membership column is the ego-net indicator of seed k
    (adjacency row + self = 1.0, Bigclamv2.scala:70; set
    cfg.seed_include_self=False for the v3 neighbor-only variant,
    bigclamv3-7.scala:64-65). Columns beyond len(seeds) are Bernoulli(0.5)
    {0,1} rows of the transposed community matrix (Bigclamv2.scala:61-63).
    Seeds beyond K are dropped (bigclamv3-7.scala:62).
    """
    rng = rng or np.random.default_rng(cfg.seed)
    n, k = g.num_nodes, cfg.num_communities
    seeds = np.asarray(seeds, dtype=np.int64)[:k]
    F = np.zeros((n, k), dtype=np.float64)
    for c, s in enumerate(seeds):
        F[g.neighbors(s), c] = 1.0
        if cfg.seed_include_self:
            F[s, c] = 1.0
    if len(seeds) < k:
        F[:, len(seeds):] = rng.integers(0, 2, size=(n, k - len(seeds)))
    return F


def conductance_seeds(
    g: Graph, cfg: Optional[BigClamConfig] = None, backend: str = "auto"
) -> np.ndarray:
    """conductanceLocalMin (Bigclamv2.scala:42-59): phi + ranking in one call."""
    cfg = cfg or BigClamConfig()
    return rank_seeds(g, conductance(g, backend=backend), cfg)
