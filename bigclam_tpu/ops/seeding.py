"""Conductance-based seeding: ego-net conductance, locally-minimal ranking,
and the conductance-seeded F initializer.

Replaces C4-C7 (SURVEY.md §2; reference Bigclamv2.scala:37-96): the reference
computed, for every node u with ego-net S(u) = {u} ∪ N(u), the multiset z of
all members' neighbor lists, then

    cut_S = #entries of z outside S          (Bigclamv2.scala:49)
    vol_S = |z| - cut_S                      (Bigclamv2.scala:50)
    vol_T = 2E - vol_S - 2*cut_S             (Bigclamv2.scala:51)
    phi   = 0 if vol_S==0 else 1 if vol_T==0 else cut_S/min(vol_S, vol_T)

— a two-hop sweep per node. Here the same quantities come from closed forms
over per-node triangle counts (tri(u) = #edges among N(u)):

    |z|    = deg(u) + S1(u),   S1(u) = sum_{v in N(u)} deg(v)
    vol_S  = 2*deg(u) + 2*tri(u)          (ordered intra-ego edges)
    cut_S  = S1(u) - deg(u) - 2*tri(u)

so the whole scorer is one common-neighbor pass + segment sums. Two backends:
a NumPy host pass (one vectorized gather per node) and a dense-adjacency
device pass (A@A on the MXU) for graphs that fit an (N, N) tile; the C++
masked-SpGEMM backend in graph/native is used when built.

Seed ranking (Bigclamv2.scala:56; bigclamv3-7.scala:51): each node nominates
its minimum-conductance neighbor (neighbor-less nodes nominate themselves at
the sentinel phi = 10.0, the v3 fix); nominees are deduplicated and ranked by
ascending phi. NOTE a reference quirk (documented in PARITY.md): its Scala
``.min`` on (id, phi) tuples is lexicographic — it nominates the *smallest-id*
neighbor, not the min-phi one. We implement the intended min-phi semantics
(tie-broken by id for determinism), as in Yang & Leskovec's locally-minimal
neighborhood seeding.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.graph.csr import Graph

# Above this node count the dense (N, N) device adjacency no longer fits
# comfortably in HBM; use the host/native sparse path instead.
DENSE_DEVICE_MAX_NODES = 16384
# float32 matmul accumulators are exact only below 2^24; 2*tri(u) <= deg(u)^2,
# so cap the degree the dense backend accepts
DENSE_DEVICE_MAX_DEGREE = 4095


def triangle_counts(g: Graph) -> np.ndarray:
    """tri(u) = number of edges among N(u) (= triangles through u).

    Host pass: per node u, one boolean-mask gather over the concatenated
    neighbor lists of N(u); sum of hits double-counts intra-neighborhood
    edges, so tri(u) = hits / 2. Cost O(sum_v deg(v)^2) total.
    """
    try:
        from bigclam_tpu.graph.native import triangle_counts as _native

        out = _native(g)
        if out is not None:
            return out
    except ImportError:
        pass
    n = g.num_nodes
    indptr, indices = g.indptr, g.indices
    flags = np.zeros(n, dtype=bool)
    tri = np.zeros(n, dtype=np.int64)
    for u in range(n):
        nbrs = indices[indptr[u] : indptr[u + 1]]
        if nbrs.size == 0:
            continue
        flags[nbrs] = True
        z = np.concatenate([indices[indptr[v] : indptr[v + 1]] for v in nbrs])
        tri[u] = np.count_nonzero(flags[z]) // 2
        flags[nbrs] = False
    return tri


def triangle_counts_dense_device(g: Graph) -> np.ndarray:
    """Device backend: tri = rowsum(A@A * A) / 2 on a dense adjacency.

    The A@A contraction maps straight onto the MXU; only viable while the
    (N, N) tile fits HBM (DENSE_DEVICE_MAX_NODES) and counts stay exactly
    representable in the float32 accumulator (DENSE_DEVICE_MAX_DEGREE).
    """
    import jax.numpy as jnp

    if g.degrees.size and int(g.degrees.max()) > DENSE_DEVICE_MAX_DEGREE:
        raise ValueError(
            f"max degree {int(g.degrees.max())} exceeds float32-exact bound "
            f"{DENSE_DEVICE_MAX_DEGREE}; use the host backend"
        )
    n = g.num_nodes
    A = np.zeros((n, n), dtype=np.float32)
    A[g.src, g.dst] = 1.0
    Ad = jnp.asarray(A)
    tri = jnp.einsum("ij,jk,ik->i", Ad, Ad, Ad) / 2.0
    return np.asarray(jnp.round(tri)).astype(np.int64)


_M64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """The native sampler's PRNG (graph/native/native.cpp bc_splitmix64),
    bit-exact in Python ints."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def capped_neighbor_lists(
    indptr: np.ndarray,
    indices: np.ndarray,
    cap: int,
    seed: int,
    row_offset: int = 0,
):
    """Array-based capped-list sampler over a CSR row range.

    The splitmix64 stream is keyed by the GLOBAL row id `row_offset + r`,
    so a shard-local call (the graph store's ingest-time seed bake,
    graph/store.bake_seed_scores) produces bit-identical lists to the
    whole-graph call restricted to those rows — rankings never depend on
    who computed them. Returns (indptr_c, indices_c) with each capped
    list sorted ascending.
    """
    indptr = np.asarray(indptr, np.int64)
    indices = np.asarray(indices)
    deg = np.diff(indptr)
    nr = deg.size
    cdeg = np.minimum(deg, cap)
    indptr_c = np.concatenate([[0], np.cumsum(cdeg)])
    indices_c = np.empty(indptr_c[-1], dtype=indices.dtype)
    # uncapped rows: straight copy (already ascending in CSR)
    rows = np.repeat(np.arange(nr, dtype=np.int64), deg)
    pos = np.arange(indices.size, dtype=np.int64) - np.repeat(
        indptr[:-1], deg
    )
    small_e = deg[rows] <= cap
    indices_c[indptr_c[rows[small_e]] + pos[small_e]] = indices[small_e]
    # capped (hub) rows: replicate the native partial Fisher-Yates exactly
    seed &= _M64
    for r in np.flatnonzero(deg > cap):
        lo, hi = int(indptr[r]), int(indptr[r + 1])
        scratch = np.asarray(indices[lo:hi]).copy()
        d = scratch.size
        u = int(row_offset + r)
        s = _splitmix64(seed ^ ((u * 0x2545F4914F6CDD1D) & _M64))
        out_lo = int(indptr_c[r])
        for i in range(cap):
            s = _splitmix64(s)
            j = i + s % (d - i)
            scratch[i], scratch[j] = scratch[j], scratch[i]
            indices_c[out_lo + i] = scratch[i]
        indices_c[out_lo : out_lo + cap].sort()
    return indptr_c, indices_c


def capped_csr(g: Graph, cap: int, seed: int):
    """Per-node uniform sample (without replacement) of at most `cap`
    neighbors, bit-identical to the native backend's sampler (partial
    Fisher-Yates on a per-node splitmix64 stream, native.cpp
    bc_triangle_counts_capped) — so the NumPy and C++ estimators see the
    SAME capped lists and produce backend-independent seed rankings
    (ADVICE rounds 1-2). Returns (indptr_c, indices_c) with each capped
    list sorted ascending (so u*N + w keys are globally sorted for
    searchsorted; the hit SET is order-independent). The row loop lives in
    capped_neighbor_lists so the graph store's shard-local seed bake shares
    it verbatim."""
    return capped_neighbor_lists(g.indptr, g.indices, cap, seed)


def triangle_counts_sampled(
    g: Graph,
    cap: int,
    rng: Optional[np.random.Generator] = None,
    chunk_entries: int = 1 << 26,
    use_native: bool = True,
) -> np.ndarray:
    """Unbiased-style estimator of tri(u) with per-node degree cap.

    The exact pass is O(sum_v deg(v)^2) — edge-quadratic on hub nodes, which
    SURVEY.md §7 flags as infeasible at com-Friendster scale. Here each node
    keeps a uniform sample S_u of at most `cap` neighbors; triangles are
    counted over (v in S_u, w in S_v-capped-list) hits w in S_u, each hit
    weighted by deg(v)/|S_v| (inner-list thinning correction), and the total
    rescaled by C(deg_u, 2)/C(|S_u|, 2) (pair-sampling correction). With
    cap >= max degree this reduces EXACTLY to the unsampled count (all
    weights and scales are 1) — the exactness flag for small graphs.

    Work is O(N * cap^2), processed in node chunks bounded by
    `chunk_entries` two-hop entries at a time.

    Backend independence: ONE seed is drawn from `rng` regardless of which
    backend runs (identical generator consumption), and the NumPy path's
    sampler (capped_csr) replicates the native splitmix64 sampler
    bit-exactly — so native and NumPy return the same estimates (up to
    float summation order) and the same seed rankings.
    """
    rng = rng or np.random.default_rng(0)
    n = g.num_nodes
    deg = g.degrees.astype(np.int64)
    seed = int(rng.integers(2**63))       # drawn on EVERY path (see above)
    if n == 0 or g.indices.size == 0:
        return np.zeros(n, dtype=np.float64)
    if use_native:
        try:
            from bigclam_tpu.graph.native import triangle_counts_capped

            return triangle_counts_capped(g, cap, seed=seed)
        except ImportError:
            pass
    indptr_c, indices_c = capped_csr(g, cap, seed)
    cdeg = np.diff(indptr_c)
    # globally sorted ego keys u*n + w, one per capped edge
    ego_src = np.repeat(np.arange(n, dtype=np.int64), cdeg)
    ego_keys = ego_src * n + indices_c
    inner_w = deg / np.maximum(cdeg, 1)      # deg(v)/|S_v| hit weight
    tri_w = np.zeros(n, dtype=np.float64)

    # chunk nodes so the expanded two-hop arrays stay bounded
    two_hop = np.zeros(n, dtype=np.int64)    # per-u expanded entry count
    np.add.at(two_hop, ego_src, cdeg[indices_c])
    bounds = np.searchsorted(
        np.cumsum(two_hop), np.arange(1, two_hop.sum() // chunk_entries + 2)
        * chunk_entries
    )
    starts = np.concatenate([[0], np.minimum(bounds + 1, n)])
    for lo, hi in zip(starts[:-1], starts[1:]):
        if lo >= hi:
            continue
        e0, e1 = indptr_c[lo], indptr_c[hi]
        if e0 == e1:
            continue                         # chunk of isolated nodes only
        v = indices_c[e0:e1]                 # first-hop targets
        reps = cdeg[v]
        z_u = np.repeat(ego_src[e0:e1], reps)          # origin node u
        z_wt = np.repeat(inner_w[v], reps)             # deg(v)/|S_v|
        # second hop: concatenate v's capped lists
        take = np.repeat(indptr_c[v], reps) + (
            np.arange(reps.sum(), dtype=np.int64)
            - np.repeat(np.concatenate([[0], np.cumsum(reps[:-1])]), reps)
        )
        z_w = indices_c[take]
        # membership w in S_u via the sorted ego keys
        cand = z_u * n + z_w
        idx = np.searchsorted(ego_keys, cand)
        hit = (idx < ego_keys.size) & (ego_keys[np.minimum(idx, ego_keys.size - 1)] == cand)
        np.add.at(tri_w, z_u[hit], z_wt[hit])
    pairs = cdeg * (cdeg - 1)
    scale = np.where(
        pairs > 0, deg * (deg - 1) / np.maximum(pairs, 1), 0.0
    )
    return tri_w / 2.0 * scale


def triangle_counts_sampled_device(
    g: Graph,
    cap: int,
    seed: int,
    chunk_nodes: Optional[int] = None,
) -> np.ndarray:
    """Device backend of the degree-capped estimator — the C5 path past the
    16,384-node dense-A@A bound (SURVEY.md §7 "Seeding at Friendster
    scale").

    Same math and SAME capped lists as the host estimators (capped_csr's
    splitmix64 sampler, shared with native.cpp), evaluated as a chunked
    two-hop membership sweep on device: per node chunk, gather the (C, cap)
    capped neighbor rows, expand to the (C, cap, cap) two-hop candidates,
    and test membership in the (sorted) ego row by vmapped binary search —
    O(N * cap^2 * log cap) VPU compares with an O(chunk * cap^2) working
    set, no (N, N) anything. Weights/scales identical to
    triangle_counts_sampled; accumulation in float32 (counts <= cap^2 are
    exact; the deg/|S_v| weight ratios round at 1e-7 relative).
    """
    import jax
    import jax.numpy as jnp

    n = g.num_nodes
    deg = g.degrees.astype(np.int64)
    if n == 0 or g.indices.size == 0:
        return np.zeros(n, dtype=np.float64)
    if chunk_nodes is None:
        # bound the (chunk, cap, cap) two-hop working set to ~256 MiB of
        # int32 (large caps — e.g. the cap >= max_degree exactness mode —
        # would otherwise blow HBM); never beyond the graph itself
        chunk_nodes = max(64, min(n, (1 << 26) // max(cap * cap, 1)))
    indptr_c, indices_c = capped_csr(g, cap, seed)
    cdeg = np.diff(indptr_c)
    # dense (N, cap) padded rows, ascending with sentinel n (sorts last)
    S = np.full((n, cap), n, dtype=np.int32)
    pos = np.arange(indices_c.size, dtype=np.int64) - np.repeat(
        indptr_c[:-1], cdeg
    )
    S[np.repeat(np.arange(n, dtype=np.int64), cdeg), pos] = indices_c
    inner_w = (deg / np.maximum(cdeg, 1)).astype(np.float32)
    Sd = jnp.asarray(S)
    wd = jnp.asarray(inner_w)
    n_pad = -(-n // chunk_nodes) * chunk_nodes

    @jax.jit
    def chunk_tri(u0):
        u = u0 + jnp.arange(chunk_nodes)
        ego = jnp.take(Sd, u, axis=0, mode="fill", fill_value=n)  # (C, cap)
        v = ego                                                  # (C, cap)
        two = jnp.take(Sd, v.reshape(-1), axis=0, mode="fill",
                       fill_value=n).reshape(chunk_nodes, cap, cap)
        w = jnp.take(wd, v.reshape(-1), mode="fill",
                     fill_value=0.0).reshape(chunk_nodes, cap)
        idx = jax.vmap(
            lambda row, cands: jnp.searchsorted(row, cands)
        )(ego, two.reshape(chunk_nodes, cap * cap))
        idx = jnp.minimum(idx, cap - 1)
        found = jnp.take_along_axis(
            ego, idx, axis=1
        ) == two.reshape(chunk_nodes, cap * cap)
        # sentinel two-hop entries (== n) can never equal a real ego entry;
        # ego sentinel rows only "match" sentinel candidates — exclude both
        valid = two.reshape(chunk_nodes, cap * cap) < n
        hits = (found & valid).astype(jnp.float32).reshape(
            chunk_nodes, cap, cap
        )
        return (hits.sum(axis=2) * w).sum(axis=1)                # (C,)

    tri_w = np.zeros(n_pad, dtype=np.float64)
    for lo in range(0, n_pad, chunk_nodes):
        tri_w[lo : lo + chunk_nodes] = np.asarray(chunk_tri(lo))
    tri_w = tri_w[:n]
    pairs = cdeg * (cdeg - 1)
    scale = np.where(
        pairs > 0, deg * (deg - 1) / np.maximum(pairs, 1), 0.0
    )
    return tri_w / 2.0 * scale


def conductance(
    g: Graph, backend: str = "auto", degree_cap: Optional[int] = None,
    rng: Optional[np.random.Generator] = None,
    tri: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Ego-net conductance phi(u) for every node (float64).

    backends: "numpy" (exact host pass), "dense" (A@A on the MXU, small
    graphs), "sampled" (degree-capped host estimator, Friendster-scale),
    "sampled_device" (the same estimator's chunked two-hop sweep on the
    accelerator — C5 past the dense bound), "auto" (dense if it fits;
    sampled when degree_cap is set and some node exceeds it; exact host
    pass otherwise). A precomputed per-node triangle-count array `tri`
    skips the (dominant) counting stage entirely. All capped backends
    share one splitmix64 sampler, so rankings are backend-independent.
    """
    deg = g.degrees
    two_e = float(g.num_directed_edges)
    use_sampled = backend == "sampled" or (
        backend == "auto"
        and degree_cap is not None
        and deg.size > 0
        and int(deg.max()) > degree_cap
    )
    if tri is not None:
        pass
    elif backend == "sampled_device":
        seed = int((rng or np.random.default_rng(0)).integers(2**63))
        tri = triangle_counts_sampled_device(g, degree_cap or 128, seed)
    elif use_sampled:
        tri = triangle_counts_sampled(g, degree_cap or 128, rng)
    elif backend == "dense" or (
        backend == "auto"
        and 0 < g.num_nodes <= DENSE_DEVICE_MAX_NODES
        and (deg.size == 0 or int(deg.max()) <= DENSE_DEVICE_MAX_DEGREE)
    ):
        tri = triangle_counts_dense_device(g)
    else:
        tri = triangle_counts(g)
    s1 = np.zeros(g.num_nodes)
    np.add.at(s1, g.src, deg[g.dst].astype(np.float64))
    return phi_from_counts(deg, s1, tri, two_e)


def phi_from_counts(
    deg: np.ndarray, s1: np.ndarray, tri: np.ndarray, two_e: float
) -> np.ndarray:
    """Ego-net conductance from the closed-form counts: deg(u),
    S1(u) = sum_{v in N(u)} deg(v), tri(u), and 2E. The ONE formula shared
    by the fit-time scorer (conductance) and the ingest-time seed bake
    (graph/store.bake_seed_scores) — baked and streamed scores are the same
    arithmetic on the same integers, so the exact path is bit-identical."""
    # clamp tri into its feasible range [0, (s1-deg)/2] (exact counts always
    # satisfy it; the sampled estimator can stray and would otherwise drive
    # cut — and phi — negative, corrupting the seed ranking)
    tri = np.clip(tri, 0.0, np.maximum(s1 - deg, 0.0) / 2.0)
    cut = s1 - deg - 2.0 * tri
    vol_s = 2.0 * deg + 2.0 * tri
    vol_t = two_e - vol_s - 2.0 * cut      # >= 0 exact; may dip below under
    phi = np.where(                        # estimation -> treat as the
        vol_s == 0,                        # vol_t == 0 boundary case
        0.0,
        np.where(
            vol_t <= 0,
            1.0,
            cut / np.maximum(np.minimum(vol_s, vol_t), 1e-300),
        ),
    )
    return phi


def rank_seeds(g: Graph, phi: np.ndarray, cfg: Optional[BigClamConfig] = None
               ) -> np.ndarray:
    """Locally-minimal seed ranking (intended semantics of Bigclamv2.scala:56).

    Each node nominates argmin_{v in N(u)} (phi(v), v); neighbor-less nodes
    nominate themselves at the sentinel phi (bigclamv3-7.scala:51). Returns
    nominee ids deduplicated, sorted ascending by (phi, id).
    """
    cfg = cfg or BigClamConfig()
    n = g.num_nodes
    indptr, indices = g.indptr, g.indices
    if indices.size == 0:
        # every node self-nominates at the sentinel; rank ties by id
        return np.arange(n, dtype=np.int64)
    # Segmented argmin over each neighbor list on the key (phi(v), v).
    # Two O(E) reduceat passes replace the former O(E log E) 3-key lexsort
    # over all directed edges (the lexsort was the slowest seeding stage at
    # 100M edges — 127s in SEEDING_r04.json): first the per-segment min
    # phi, then the min id among the neighbors attaining it.
    # NaN phi would propagate through reduceat and nominate the
    # out-of-range id n (the old lexsort sorted NaN last); +inf keeps the
    # degraded-but-valid behavior for caller-supplied phi
    phi = np.where(np.isnan(phi), np.inf, np.asarray(phi, np.float64))
    phi_nbr = phi[indices]
    has_nbrs = g.degrees > 0
    # one +inf/n sentinel element keeps every indptr start a valid reduceat
    # index (trailing isolated nodes have start == E); min() ignores it in
    # non-empty segments, and empty segments' junk is masked by has_nbrs
    starts = indptr[:-1].astype(np.int64)
    nominee = np.arange(n, dtype=np.int64)          # self-nomination default
    nominee_phi = np.full(n, float(cfg.isolated_phi_sentinel))
    seg_min = np.minimum.reduceat(np.append(phi_nbr, np.inf), starts)
    src = g.src
    is_min = phi_nbr == seg_min[src]
    id_or_n = np.where(is_min, indices.astype(np.int64), n)  # n sorts last
    seg_min_id = np.minimum.reduceat(np.append(id_or_n, n), starts)
    nominee[has_nbrs] = seg_min_id[has_nbrs]
    nominee_phi[has_nbrs] = seg_min[has_nbrs]
    cand, first = np.unique(nominee, return_index=True)
    cand_phi = nominee_phi[first]
    rank = np.lexsort((cand, cand_phi))
    return cand[rank]


def covering_order(
    g: Graph, phi: np.ndarray, cfg: Optional[BigClamConfig] = None
) -> np.ndarray:
    """Candidate order for the covering walk: locally-minimal nominees
    first (rank_seeds), then every remaining node by ascending (phi, id)
    with NaN phi sorted last. The single source for both walk backends
    and the seeding bench."""
    cfg = cfg or BigClamConfig()
    n = g.num_nodes
    ranked = rank_seeds(g, phi, cfg)
    rest = np.setdiff1d(
        np.arange(n, dtype=np.int64), ranked, assume_unique=False
    )
    phi_fb = np.where(np.isnan(phi), np.inf, np.asarray(phi, np.float64))
    rest = rest[np.lexsort((rest, phi_fb[rest]))]
    return np.concatenate([ranked, rest])


def select_seeds_covering(
    g: Graph,
    phi: np.ndarray,
    k: int,
    cfg: Optional[BigClamConfig] = None,
    hops: int = 1,
    order: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Coverage-aware seed selection (quality mode's seeding rule).

    The reference ranking (Bigclamv2.scala:56) takes the K lowest-phi
    locally-minimal nominees as-is; on graphs with many similar communities
    the nominee order inside near-uniform regions is arbitrary and the top-K
    pile into a fraction of them (measured: 58 of 100 planted blocks covered
    at N=2400). Here candidates are walked in the same (phi, id) order —
    locally-minimal nominees first, then every remaining node — but a
    candidate already covered by a chosen seed's `hops`-neighborhood is
    skipped, so the K chosen ego-nets tile the graph. Measured on the
    N=2400/K=100 probe: hops=1 covers 81/100 blocks (quality F1 0.836),
    hops=2 covers 92/100 (F1 0.894) — one ego-net reaches only ~p_in of a
    sparse block, so 1-hop exclusion still lets two seeds land in one
    block. Marking cost: O(E) at hops=1; hops=2 adds sum_{v in N(s)} deg(v)
    per seed, capped per node at cfg.seeding_degree_cap (default 256 when
    unset — the 2-hop walk always bounds hub fans, both for cost and so one
    hub-adjacent seed's blanket cannot exclude a hub's entire neighborhood
    from later seeding).
    """
    cfg = cfg or BigClamConfig()
    if k <= 0:
        return np.empty(0, dtype=np.int64)
    # non-positive caps are meaningless for the 2-hop fan bound (and 0
    # would divide by zero below) — fall back to the built-in default
    cap = cfg.seeding_degree_cap
    if not cap or cap <= 0:
        cap = 256
    if order is None:
        order = covering_order(g, phi, cfg)
    try:
        # the candidate walk is a sequential Python loop over up to N
        # nodes — at Friendster-class N the native walk (same slicing,
        # bit-identical choices) is the difference between ms and minutes
        from bigclam_tpu.graph.native import (
            select_seeds_covering as _native_walk,
        )

        return _native_walk(g, order, k, hops, cap)
    except ImportError:
        pass
    return _covering_walk_numpy(g, order, k, hops, cap)


def _covering_walk_numpy(
    g: Graph, order: np.ndarray, k: int, hops: int, cap: int
) -> np.ndarray:
    """NumPy reference of the covering walk — the native walk
    (graph/native bc_select_seeds_covering) must stay bit-identical to
    this loop (tests/test_native.py compares them on this function)."""
    covered = np.zeros(g.num_nodes, dtype=bool)
    indptr, indices = g.indptr, g.indices
    out = []
    for s in order:
        s = int(s)
        if covered[s]:
            continue
        out.append(s)
        covered[s] = True
        nbrs = indices[indptr[s] : indptr[s + 1]]
        covered[nbrs] = True
        if hops >= 2:
            # hub guard: the 2-hop marking of one seed costs
            # sum_{v in N(s)} deg(v); cap both fans like the sampled
            # conductance scorer does
            if nbrs.size > cap:
                nbrs = nbrs[:: max(nbrs.size // cap, 1)][:cap]
            for v in nbrs:
                covered[indices[indptr[v] : indptr[v + 1]][:cap]] = True
        if len(out) >= k:
            break
    return np.asarray(out, dtype=np.int64)   # may be < k: fully covered


def init_F(
    g: Graph,
    seeds: np.ndarray,
    cfg: BigClamConfig,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Conductance-seeded F0 (C7; Bigclamv2.scala:65-96).

    Community k's membership column is the ego-net indicator of seed k
    (adjacency row + self = 1.0, Bigclamv2.scala:70; set
    cfg.seed_include_self=False for the v3 neighbor-only variant,
    bigclamv3-7.scala:64-65). Columns beyond len(seeds) are Bernoulli(0.5)
    {0,1} rows of the transposed community matrix (Bigclamv2.scala:61-63).
    Seeds beyond K are dropped (bigclamv3-7.scala:62).
    """
    rng = rng or np.random.default_rng(cfg.seed)
    n, k = g.num_nodes, cfg.num_communities
    seeds = np.asarray(seeds, dtype=np.int64)[:k]
    F = np.zeros((n, k), dtype=np.float64)
    for c, s in enumerate(seeds):
        F[g.neighbors(s), c] = 1.0
        if cfg.seed_include_self:
            F[s, c] = 1.0
    if len(seeds) < k:
        F[:, len(seeds):] = rng.integers(0, 2, size=(n, k - len(seeds)))
    return F


def conductance_seeds(
    g: Graph,
    cfg: Optional[BigClamConfig] = None,
    backend: str = "auto",
    phi: Optional[np.ndarray] = None,
) -> np.ndarray:
    """conductanceLocalMin (Bigclamv2.scala:42-59): phi + ranking in one call.

    With cfg.seed_exclusion (auto-on in quality mode) the ranking is the
    coverage-aware greedy walk (select_seeds_covering) instead of the
    reference's raw top-K nominee order. A precomputed `phi` (e.g. the
    graph store's ingest-baked seed scores, GraphStore.load_seed_scores)
    skips the conductance pass — the dominant seeding cost — entirely.
    """
    cfg = cfg or BigClamConfig()
    if phi is None:
        phi = conductance(
            g,
            backend=backend,
            degree_cap=cfg.seeding_degree_cap,
            rng=np.random.default_rng(cfg.seed),
        )
    else:
        phi = np.asarray(phi, np.float64)
        if phi.shape != (g.num_nodes,):
            raise ValueError(
                f"precomputed phi has shape {phi.shape}, want "
                f"({g.num_nodes},)"
            )
    exclude = (
        cfg.quality_mode if cfg.seed_exclusion is None else cfg.seed_exclusion
    )
    if exclude:
        return select_seeds_covering(
            g, phi, cfg.num_communities, cfg, hops=2
        )
    return rank_seeds(g, phi, cfg)
