"""Blocked-CSR Pallas kernels: the hot edge sweeps on the MXU.

The XLA edge path (ops.objective.grad_llh / ops.linesearch.candidates_pass)
is three memory-bound stages per sweep — gather F[src], gather F[dst],
scatter (E, K) contributions via segment_sum — and profiling on TPU v5e shows
gather/scatter running at ~15% of streaming HBM bandwidth while the MXU sits
idle. These kernels restructure the sweeps around the blocked-CSR tile layout
of ops.csr_tiles:

  * the ONLY remaining random access is the dst-side row gather, done once
    per step in XLA (`F[tiles.dst]`) and shared by both kernels
  * src-side row expansion is a (T, B)x(B, K) one-hot matmul against the
    (B, K) F block resident in VMEM (exact: one-hot entries are 0/1 and
    3-pass f32 matmul reconstructs f32 operands)
  * the (E, K) gradient scatter becomes a (B, T)x(T, K) one-hot matmul,
    accumulated into the block's VMEM output across its consecutive tiles
    (Pallas writes each output block back to HBM once)
  * the Armijo tail terms fold into the candidate kernel using the algebraic
    simplification  -F'.(sumF - F + F') + F'.F' = F'.(F - sumF)
    (SURVEY.md §2.1; reference Bigclamv2.scala:137-143), so the XLA-side
    update no longer makes 16 passes over (N, K)

Semantics are identical to the XLA path (same clipping, same masked terms;
reference Bigclamv2.scala:121-146); tests compare both in interpret mode.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.ops.csr_tiles import BlockTiles
from bigclam_tpu.ops.objective import edge_terms, node_tail

# one-hot matmul precision: f32 multi-pass decomposition — exact enough to
# reconstruct f32 rows (one-hot operand is 0/1). Mosaic supports only
# DEFAULT (1-pass bf16, would truncate F to bf16) and HIGHEST (6-pass).
_PREC = lax.Precision.HIGHEST


class TilesDev(NamedTuple):
    """Device-resident copy of ops.csr_tiles.BlockTiles.

    The per-tile vectors carry a middle singleton dim — Mosaic requires the
    last TWO dims of a block shape to be (8, 128)-aligned or full-size, so
    (n_tiles, 1, T) blocks as (1, 1, T) satisfy the rule where (n_tiles, T)
    as (1, T) would not.

    `seq` (ISSUE 13) is the fused superstep's grid-entry sequence — each
    block's tiles listed twice ([tile, phase] per entry, phase 0 = grad
    pass, phase 1 = candidate/update pass; ops.pallas_fused
    .fused_entry_seq); None on the split-kernel path. `kc` > 0 marks the
    K-blocked fused layout (flat tiles, kc columns per kernel call)."""

    src_local: jax.Array   # (n_tiles, 1, T) int32, block-local
    dst: jax.Array         # (n_tiles, T) int32, global (XLA gather operand)
    mask: jax.Array        # (n_tiles, 1, T) float
    block_id: jax.Array    # (n_tiles,) int32
    block_b: int
    tile_t: int
    n_blocks: int
    seq: Optional[jax.Array] = None   # (2*n_tiles, 2) int32 (fused only)
    kc: int = 0                       # K block columns (fused large-K only)

    @property
    def n_pad(self) -> int:
        return self.n_blocks * self.block_b


def device_tiles(
    bt: BlockTiles, dtype=jnp.float32, with_seq: bool = False, kc: int = 0
) -> TilesDev:
    n_tiles, t = bt.src_local.shape
    seq = None
    if with_seq:
        from bigclam_tpu.ops.pallas_fused import fused_entry_seq

        seq = jnp.asarray(fused_entry_seq(bt.block_id))
    return TilesDev(
        src_local=jnp.asarray(bt.src_local, jnp.int32).reshape(n_tiles, 1, t),
        dst=jnp.asarray(bt.dst, jnp.int32),
        mask=jnp.asarray(bt.mask, dtype).reshape(n_tiles, 1, t),
        block_id=jnp.asarray(bt.block_id, jnp.int32),
        block_b=bt.block_b,
        tile_t=bt.tile_t,
        n_blocks=bt.n_blocks,
        seq=seq,
        kc=kc,
    )


# conservative per-kernel VMEM budget: v5e VMEM is 16 MiB
VMEM_BUDGET = 12 << 20


def kernel_vmem_bytes(
    b: int, t: int, k_pad: int, fused: bool = False, num_s: int = 16
) -> int:
    """VMEM working-set model of the edge kernels at tile shape (b, t).

    Counts the PIPELINE'S double-buffered stream copies explicitly (round
    17 fix: Mosaic holds TWO copies of every blocked input/output while
    the automatic pipeline prefetches the next grid step — the old
    estimate priced single copies and auto-shrink could pick shapes that
    only fit with pipelining off):

      split candidate kernel (the working-set max of the split suite):
        2x (t, k) fd stream + 2x 2 (b, k) F/grad input blocks +
        2x (S, b) output + live temps fs/gs/nf (3 (t, k)) + (b, t) one-hot
      fused superstep kernel (ops.pallas_fused): the explicitly
        double-buffered (2, t, k) fd DMA scratch + 2x (b, k) F input
        stream + 4 resident (b, k) output blocks (F_new/grad x in+out
        copy) + (S, b) candidate accumulator + temps/one-hot as above
    """
    if fused:
        streams = 2 * t * k_pad + 2 * b * k_pad + 4 * b * k_pad + num_s * b
    else:
        streams = 2 * t * k_pad + 4 * b * k_pad + 2 * num_s * b
    temps = 3 * t * k_pad + 2 * b * t
    return (streams + temps) * 4


def fit_tile_shape(
    block_b: int, tile_t: int, k_pad: int, fused: bool = False
) -> Optional[Tuple[int, int]]:
    """Shrink (block_b, tile_t) — halving, floor 128 — until the kernels'
    VMEM working set (kernel_vmem_bytes, double-buffered streams counted)
    fits. None = not fittable at this k_pad (fall back to the XLA path or
    shard K). fused=True prices the fused superstep kernel's working set
    (in-kernel DMA scratch instead of a pipelined fd stream)."""

    def est(b: int, t: int) -> int:
        return kernel_vmem_bytes(b, t, k_pad, fused=fused)

    def shrink(v: int) -> int:
        # halve but keep Mosaic 128-alignment: a 128-multiple input must
        # yield a 128-multiple (384 -> 256, not 192, which would silently
        # fail csr_tiles_supported after an auto-shrink). Round the halved
        # value UP — the loop's budget check keeps shrinking if it is still
        # too big, so rounding up never over-shrinks a feasible shape
        h = v // 2
        return -(-h // 128) * 128 if h >= 128 else h

    b, t = block_b, tile_t
    while est(b, t) > VMEM_BUDGET and max(b, t) > 128:
        if t >= b and t > 128:
            t = shrink(t)
        else:
            b = shrink(b)
    return (b, t) if est(b, t) <= VMEM_BUDGET else None


def largest_fitting_kblock(
    block_b: int, tile_t: int, k_pad: int, fused: bool = False
) -> Optional[Tuple[int, Tuple[int, int]]]:
    """Large-K fallback policy shared by the single-chip and sharded
    trainers: the largest 128-multiple divisor kc of k_pad whose tile
    shape fits VMEM. Returns (kc, (block_b, tile_t)) or None — the K axis
    is then processed kc columns at a time by the kblocked passes."""
    m = k_pad // 128
    for d in sorted((d for d in range(1, m) if m % d == 0), reverse=True):
        s = fit_tile_shape(block_b, tile_t, 128 * d, fused=fused)
        if s is not None:
            return 128 * d, s
    return None


def csr_tiles_supported(
    block_b: int, tile_t: int, k_pad: int, interpret: bool = False
) -> bool:
    """Mosaic tiling constraints for the two kernels (relaxed in interpret).

    Static — callable BEFORE the O(E) host tile build."""
    if interpret:
        return True
    return (
        tile_t % 128 == 0
        and block_b % 128 == 0      # llh/cand outputs have B as minor dim
        and k_pad % 128 == 0
    )


def _out_struct(shape, dtype, *operands) -> jax.ShapeDtypeStruct:
    """Output spec carrying the union of the operands' varying-mesh-axes
    (vma) types — required when the kernels run inside jax.shard_map.
    (Empty on jax 0.4.x, where the VMA type system does not exist.)"""
    from bigclam_tpu.utils.compat import vma_of

    vma = frozenset().union(*(vma_of(x) for x in operands))
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _first_tile_of_block(bid_ref, i):
    prev = bid_ref[jnp.maximum(i - 1, 0)]
    return jnp.logical_or(i == 0, bid_ref[i] != prev)


def _expand_onehot(srcl, b, dtype):
    """(B, T) one-hot: row r of the block <- edges with src_local == r."""
    t = srcl.shape[0]
    rows = lax.broadcasted_iota(jnp.int32, (b, t), 0)
    return (rows == srcl[None, :]).astype(dtype)


def _grad_kernel(bid_ref, srcl_ref, mask_ref, fd_ref, f_blk_ref,
                 grad_out_ref, llh_out_ref, *, cfg, block_b):
    i = pl.program_id(0)
    srcl = srcl_ref[0, 0]                   # (T,)
    m = mask_ref[0, 0]                      # (T,)
    fd = fd_ref[0]                          # (T, K)
    fb = f_blk_ref[:]                       # (B, K)
    one = _expand_onehot(srcl, block_b, fd.dtype)        # (B, T)
    fs = lax.dot_general(                   # expand: (T, K) src rows
        one, fb, (((0,), (0,)), ((), ())),
        precision=_PREC, preferred_element_type=fd.dtype,
    )
    x = jnp.sum(fs * fd, axis=1)            # (T,) edge dots, VPU f32
    omp, ell_raw = edge_terms(x, cfg)       # same clipping as the XLA path
    ell = ell_raw * m
    coeff = m / omp                         # folds the +sum_N F_v term
    contrib = lax.dot_general(              # scatter: (B, K) block partial
        one, fd * coeff[:, None], (((1,), (0,)), ((), ())),
        precision=_PREC, preferred_element_type=fd.dtype,
    )
    llh_c = jnp.sum(one * ell[None, :], axis=1)          # (B,) VPU

    @pl.when(_first_tile_of_block(bid_ref, i))
    def _():
        grad_out_ref[0] = jnp.zeros_like(grad_out_ref)[0]
        llh_out_ref[0, 0] = jnp.zeros_like(llh_out_ref)[0, 0]

    grad_out_ref[0] += contrib
    llh_out_ref[0, 0] += llh_c


def _cand_kernel(bid_ref, srcl_ref, mask_ref, fd_ref, f_blk_ref, g_blk_ref,
                 sumf_ref, out_ref, *, cfg, block_b, with_tails=True):
    i = pl.program_id(0)
    srcl = srcl_ref[0, 0]
    m = mask_ref[0, 0]
    fd = fd_ref[0]
    fb = f_blk_ref[:]
    gb = g_blk_ref[:]
    sumf = sumf_ref[0]                       # (K,)
    one = _expand_onehot(srcl, block_b, fd.dtype)
    dims = (((0,), (0,)), ((), ()))
    fs = lax.dot_general(one, fb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    gs = lax.dot_general(one, gb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    ells = []
    for eta in cfg.step_candidates:
        nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
        x = jnp.sum(nf * fd, axis=1)
        _, ell = edge_terms(x, cfg)         # same clipping as the XLA path
        ells.append(ell * m)
    ell_t = jnp.stack(ells, axis=0)          # (S, T)
    scat = lax.dot_general(                  # (S, B) neighbor terms
        ell_t, one, (((1,), (1,)), ((), ())),
        precision=_PREC, preferred_element_type=fd.dtype,
    )

    @pl.when(_first_tile_of_block(bid_ref, i))
    def _():
        if with_tails:
            # Armijo tails, once per block: nf.(F_u - sumF) per candidate
            fms = fb - sumf[None, :]         # (B, K)
            tails = []
            for eta in cfg.step_candidates:
                nfb = jnp.clip(fb + eta * gb, cfg.min_f, cfg.max_f)
                tails.append(jnp.sum(nfb * fms, axis=1))
            out_ref[0] = jnp.stack(tails, axis=0)        # (S, B)
        else:
            # neighbor terms only (ring schedule: each phase sees a partial
            # edge set, tails are added once outside)
            out_ref[0] = jnp.zeros_like(out_ref)[0]

    out_ref[0] += scat


def gather_dst_rows(F: jax.Array, tiles: TilesDev) -> jax.Array:
    """The one true gather: (n_tiles, T, K) dst-endpoint F rows (XLA)."""
    return jnp.take(F, tiles.dst, axis=0)


def _grad_blocks(
    F: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    fd: jax.Array,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Raw per-block kernel outputs: (n_blocks, B, K) neighbor-gradient
    partials and (n_blocks, 1, B) neighbor-LLH partials (no tail terms)."""
    k = F.shape[1]
    b, t = tiles.block_b, tiles.tile_t
    n_tiles = tiles.src_local.shape[0]
    kernel = functools.partial(_grad_kernel, cfg=cfg, block_b=b)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, t, k), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, b, k), lambda i, bid: (bid[i], 0, 0)),
            pl.BlockSpec((1, 1, b), lambda i, bid: (bid[i], 0, 0)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((tiles.n_blocks, b, k), F.dtype, F, fd, tiles.mask),
            _out_struct((tiles.n_blocks, 1, b), F.dtype, F, fd, tiles.mask),
        ],
        interpret=interpret,
    )(tiles.block_id, tiles.src_local, tiles.mask, fd, F)


def grad_llh_csr(
    F: jax.Array,
    sumF: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    fd: jax.Array = None,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused gradient + per-node LLH via the blocked-CSR MXU kernel.

    Drop-in for ops.objective.grad_llh (same math, SURVEY.md §2.1): returns
    (grad (n_pad, K), node_llh (n_pad,)). `fd` lets the caller share one
    dst-row gather between this and candidates_csr.
    """
    n_pad, k = F.shape
    assert n_pad == tiles.n_pad, (n_pad, tiles.n_pad)
    if fd is None:
        fd = gather_dst_rows(F, tiles)
    grad_nbr, llh_nbr = _grad_blocks(F, tiles, cfg, fd, interpret)
    grad = grad_nbr.reshape(n_pad, k) - sumF[None, :] + F
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
    node_llh = (
        llh_nbr.reshape(n_pad).astype(adt) + node_tail(F, sumF).astype(adt)
    )
    return grad, node_llh


def _cand_blocks(
    F: jax.Array,
    grad: jax.Array,
    sumF: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    fd: jax.Array,
    interpret: bool,
    with_tails: bool = True,
) -> jax.Array:
    """Raw per-block candidate-LLH outputs (n_blocks, S, B), tails included
    unless with_tails=False (ring phases add tails once outside).

    NOTE: F/grad here are the rows covered by `tiles` (the whole model on
    the flat path; a group's row range on the grouped path) while `fd` rows
    are gathered from the FULL F."""
    k = F.shape[1]
    b, t = tiles.block_b, tiles.tile_t
    n_tiles = tiles.src_local.shape[0]
    num_s = len(cfg.step_candidates)
    kernel = functools.partial(
        _cand_kernel, cfg=cfg, block_b=b, with_tails=with_tails
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, t, k), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
            pl.BlockSpec((1, k), lambda i, bid: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, num_s, b), lambda i, bid: (bid[i], 0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct(
            (tiles.n_blocks, num_s, b), F.dtype, F, grad, fd, tiles.mask, sumF
        ),
        interpret=interpret,
    )(
        tiles.block_id, tiles.src_local, tiles.mask, fd, F, grad,
        sumF.reshape(1, k),
    )


def candidates_csr(
    F: jax.Array,
    grad: jax.Array,
    sumF: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    fd: jax.Array = None,
    interpret: bool = False,
) -> jax.Array:
    """FULL candidate LLH (neighbor terms + Armijo tails) for all 16 steps.

    Returns (S, n_pad) — unlike ops.linesearch.candidates_pass this already
    includes the tail terms, so feed it to armijo_select, not armijo_update.
    """
    n_pad, k = F.shape
    assert n_pad == tiles.n_pad, (n_pad, tiles.n_pad)
    if fd is None:
        fd = gather_dst_rows(F, tiles)
    out = _cand_blocks(F, grad, sumF, tiles, cfg, fd, interpret)
    num_s = len(cfg.step_candidates)
    return out.transpose(1, 0, 2).reshape(num_s, n_pad)


# --- K-sharded (TP) kernel suite -------------------------------------------
#
# Under a sharded K axis each device holds K_loc = K/tp columns of F, so the
# per-edge dot F_u.F_v needs a psum over "k" — which cannot happen inside a
# Pallas kernel. The sweep splits into two kernels with an XLA psum of the
# per-edge PARTIAL dots in between:
#
#   dots kernel   : (B, K_loc) F block x one-hot -> partial x per edge tile
#   [lax.psum over "k" of the (n_tiles, T) partials — 1 float/edge, far
#    smaller than any F-row exchange]
#   consume kernel: full x -> clipped edge terms -> (B, K_loc) grad partial
#                   (K-local: fd rows are K-local) / (S, B) candidate LLH
#                   terms (replicated over "k")
#
# The Armijo candidate dots are also K-local: clip(F_u + eta*grad_u) is
# ELEMENTWISE over K, so clipped candidate rows shard like F and their dots
# psum the same way. Armijo tail terms (which need row dots vs sumF) stay in
# XLA where psum is natural (parallel/sharded.py). Callers: the TP branch of
# parallel.sharded.make_sharded_csr_train_step.


def _dot_kernel(bid_ref, srcl_ref, fd_ref, f_blk_ref, x_out_ref, *, block_b):
    srcl = srcl_ref[0, 0]                   # (T,)
    fd = fd_ref[0]                          # (T, K_loc)
    fb = f_blk_ref[:]                       # (B, K_loc)
    one = _expand_onehot(srcl, block_b, fd.dtype)        # (B, T)
    fs = lax.dot_general(
        one, fb, (((0,), (0,)), ((), ())),
        precision=_PREC, preferred_element_type=fd.dtype,
    )
    x_out_ref[0, 0] = jnp.sum(fs * fd, axis=1)           # partial edge dots


def edge_dots_csr(
    F: jax.Array,
    tiles: TilesDev,
    fd: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """Per-edge PARTIAL dots over this device's K_loc columns: (n_tiles, 1, T).

    psum the result over the "k" mesh axis to obtain the full F_u.F_v dots."""
    n_pad, k = F.shape
    assert n_pad == tiles.n_pad, (n_pad, tiles.n_pad)
    b, t = tiles.block_b, tiles.tile_t
    n_tiles = tiles.src_local.shape[0]
    kernel = functools.partial(_dot_kernel, block_b=b)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, t, k), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((n_tiles, 1, t), F.dtype, F, fd),
        interpret=interpret,
    )(tiles.block_id, tiles.src_local, fd, F)


def _grad_from_x_kernel(bid_ref, srcl_ref, mask_ref, x_ref, fd_ref,
                        grad_out_ref, llh_out_ref, *, cfg, block_b):
    i = pl.program_id(0)
    srcl = srcl_ref[0, 0]
    m = mask_ref[0, 0]
    x = x_ref[0, 0]                         # (T,) FULL edge dots (post-psum)
    fd = fd_ref[0]                          # (T, K_loc)
    one = _expand_onehot(srcl, block_b, fd.dtype)
    omp, ell_raw = edge_terms(x, cfg)
    ell = ell_raw * m
    coeff = m / omp
    contrib = lax.dot_general(
        one, fd * coeff[:, None], (((1,), (0,)), ((), ())),
        precision=_PREC, preferred_element_type=fd.dtype,
    )
    llh_c = jnp.sum(one * ell[None, :], axis=1)

    @pl.when(_first_tile_of_block(bid_ref, i))
    def _():
        grad_out_ref[0] = jnp.zeros_like(grad_out_ref)[0]
        llh_out_ref[0, 0] = jnp.zeros_like(llh_out_ref)[0, 0]

    grad_out_ref[0] += contrib
    llh_out_ref[0, 0] += llh_c


def grad_nbr_from_x_csr(
    x: jax.Array,
    tiles: TilesDev,
    fd: jax.Array,
    cfg: BigClamConfig,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Neighbor-gradient partial (n_pad, K_loc) + neighbor LLH (n_pad,) from
    FULL edge dots `x` (edge_dots_csr psum'd over "k").

    The gradient output is K-local (fd rows are this device's columns); the
    LLH output depends only on x and so is replicated over "k". The caller
    adds the -sumF + F and tail terms (they need their own psums)."""
    n_tiles, _, t = x.shape
    b = tiles.block_b
    k = fd.shape[-1]
    kernel = functools.partial(_grad_from_x_kernel, cfg=cfg, block_b=b)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, t, k), lambda i, bid: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, b, k), lambda i, bid: (bid[i], 0, 0)),
            pl.BlockSpec((1, 1, b), lambda i, bid: (bid[i], 0, 0)),
        ],
    )
    grad_nbr, llh_nbr = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((tiles.n_blocks, b, k), fd.dtype, x, fd, tiles.mask),
            _out_struct((tiles.n_blocks, 1, b), fd.dtype, x, fd, tiles.mask),
        ],
        interpret=interpret,
    )(tiles.block_id, tiles.src_local, tiles.mask, x, fd)
    return grad_nbr.reshape(tiles.n_pad, k), llh_nbr.reshape(tiles.n_pad)


def _cand_dot_kernel(bid_ref, srcl_ref, fd_ref, f_blk_ref, g_blk_ref,
                     xc_out_ref, *, cfg, block_b):
    srcl = srcl_ref[0, 0]
    fd = fd_ref[0]
    fb = f_blk_ref[:]
    gb = g_blk_ref[:]
    one = _expand_onehot(srcl, block_b, fd.dtype)
    dims = (((0,), (0,)), ((), ()))
    fs = lax.dot_general(one, fb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    gs = lax.dot_general(one, gb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    for s, eta in enumerate(cfg.step_candidates):
        # clip is elementwise over K: the clipped candidate row's K_loc
        # slice only needs this device's fs/gs columns
        nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
        xc_out_ref[0, s] = jnp.sum(nf * fd, axis=1)


def cand_dots_csr(
    F: jax.Array,
    grad: jax.Array,
    tiles: TilesDev,
    fd: jax.Array,
    cfg: BigClamConfig,
    interpret: bool = False,
) -> jax.Array:
    """Per-edge PARTIAL candidate dots for all S steps: (n_tiles, S, T).

    psum over "k" gives the full clip(F_u + eta*grad_u).F_v dots."""
    n_pad, k = F.shape
    assert n_pad == tiles.n_pad, (n_pad, tiles.n_pad)
    b, t = tiles.block_b, tiles.tile_t
    n_tiles = tiles.src_local.shape[0]
    num_s = len(cfg.step_candidates)
    kernel = functools.partial(_cand_dot_kernel, cfg=cfg, block_b=b)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, t, k), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, num_s, t), lambda i, bid: (i, 0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((n_tiles, num_s, t), F.dtype, F, grad, fd),
        interpret=interpret,
    )(tiles.block_id, tiles.src_local, fd, F, grad)


def _cand_from_x_kernel(bid_ref, srcl_ref, mask_ref, xc_ref, out_ref,
                        *, cfg, block_b):
    i = pl.program_id(0)
    srcl = srcl_ref[0, 0]
    m = mask_ref[0, 0]
    xc = xc_ref[0]                          # (S, T) FULL candidate dots
    one = _expand_onehot(srcl, block_b, xc.dtype)
    ells = []
    for s in range(len(cfg.step_candidates)):
        _, ell = edge_terms(xc[s], cfg)
        ells.append(ell * m)
    ell_t = jnp.stack(ells, axis=0)          # (S, T)
    scat = lax.dot_general(
        ell_t, one, (((1,), (1,)), ((), ())),
        precision=_PREC, preferred_element_type=xc.dtype,
    )

    @pl.when(_first_tile_of_block(bid_ref, i))
    def _():
        out_ref[0] = jnp.zeros_like(out_ref)[0]

    out_ref[0] += scat


def cand_nbr_from_x_csr(
    xc: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    interpret: bool = False,
) -> jax.Array:
    """NEIGHBOR candidate-LLH terms (S, n_pad) from full candidate dots.

    Unlike candidates_csr this does NOT include the Armijo tails (they need
    psums over "k"; the TP caller computes them in XLA)."""
    n_tiles, num_s, t = xc.shape
    b = tiles.block_b
    kernel = functools.partial(_cand_from_x_kernel, cfg=cfg, block_b=b)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, num_s, t), lambda i, bid: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, num_s, b), lambda i, bid: (bid[i], 0, 0)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct(
            (tiles.n_blocks, num_s, b), xc.dtype, xc, tiles.mask
        ),
        interpret=interpret,
    )(tiles.block_id, tiles.src_local, tiles.mask, xc)
    return out.transpose(1, 0, 2).reshape(num_s, tiles.n_pad)


class GroupedTilesDev(NamedTuple):
    """Device-resident ops.csr_tiles.GroupedBlockTiles (large-K layout).

    kc > 0 additionally processes the K axis in kc-column blocks inside
    each group (single-chip large-K mode — see
    train_pass_csr_grouped_kblocked)."""

    src_local: jax.Array   # (n_groups, G, 1, T)
    dst: jax.Array         # (n_groups, G, T)
    mask: jax.Array        # (n_groups, G, 1, T)
    block_id: jax.Array    # (n_groups, G)
    block_b: int
    tile_t: int
    nb: int
    n_groups: int
    kc: int = 0

    @property
    def n_pad(self) -> int:
        return self.n_groups * self.nb * self.block_b


def device_grouped_tiles(gbt, dtype=jnp.float32, kc: int = 0) -> GroupedTilesDev:
    ng, g, t = gbt.src_local.shape
    return GroupedTilesDev(
        src_local=jnp.asarray(gbt.src_local, jnp.int32).reshape(ng, g, 1, t),
        dst=jnp.asarray(gbt.dst, jnp.int32),
        mask=jnp.asarray(gbt.mask, dtype).reshape(ng, g, 1, t),
        block_id=jnp.asarray(gbt.block_id, jnp.int32),
        block_b=gbt.block_b,
        tile_t=gbt.tile_t,
        nb=gbt.nb,
        n_groups=gbt.n_groups,
        kc=kc,
    )


def _group_view(gt: GroupedTilesDev, xs) -> TilesDev:
    srcl, dst, mask, bid = xs
    return TilesDev(
        src_local=srcl, dst=dst, mask=mask, block_id=bid,
        block_b=gt.block_b, tile_t=gt.tile_t, n_blocks=gt.nb,
    )


def grad_llh_csr_grouped(
    F: jax.Array,
    sumF: jax.Array,
    gt: GroupedTilesDev,
    cfg: BigClamConfig,
    interpret: bool = False,
    F_gather: jax.Array = None,
) -> Tuple[jax.Array, jax.Array]:
    """grad_llh_csr over the grouped layout: lax.scan over block groups,
    gathering only each group's (G, T, K) dst rows per iteration — the
    large-K path where one whole-graph gather would blow the HBM budget.

    `F_gather` is the array dst indices point into (defaults to F itself;
    the sharded trainer passes the all-gathered full F while F holds only
    this shard's rows)."""
    n_pad, k = F.shape
    assert n_pad == gt.n_pad, (n_pad, gt.n_pad)
    rows = gt.nb * gt.block_b
    F_src = F if F_gather is None else F_gather

    def body(_, xs):
        gi, tile_xs = xs
        td = _group_view(gt, tile_xs)
        fd = jnp.take(F_src, td.dst, axis=0)
        F_g = lax.dynamic_slice_in_dim(F, gi * rows, rows)
        return None, _grad_blocks(F_g, td, cfg, fd, interpret)

    _, (gn, ln) = lax.scan(
        body,
        None,
        (
            jnp.arange(gt.n_groups),
            (gt.src_local, gt.dst, gt.mask, gt.block_id),
        ),
    )
    grad = gn.reshape(n_pad, k) - sumF[None, :] + F
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
    node_llh = (
        ln.reshape(n_pad).astype(adt) + node_tail(F, sumF).astype(adt)
    )
    return grad, node_llh


def train_pass_csr_grouped(
    F: jax.Array,
    sumF: jax.Array,
    gt: GroupedTilesDev,
    cfg: BigClamConfig,
    interpret: bool = False,
    F_gather: jax.Array = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Grad + candidates in ONE scan over block groups, sharing each group's
    dst-row gather (the dominant memory cost on this path).

    Works because everything the candidate kernel needs is group-local: the
    group's grad rows are complete once its grad kernel ran (grad_g =
    gn_g - sumF + F_g), and fd comes from the OLD full F either way.
    `F_gather` as in grad_llh_csr_grouped (sharded trainers pass the
    all-gathered F).
    Returns (grad (n_pad, K), node_llh (n_pad,), cand_full (S, n_pad)).
    """
    n_pad, k = F.shape
    assert n_pad == gt.n_pad, (n_pad, gt.n_pad)
    rows = gt.nb * gt.block_b
    num_s = len(cfg.step_candidates)
    F_src = F if F_gather is None else F_gather

    def body(_, xs):
        gi, tile_xs = xs
        td = _group_view(gt, tile_xs)
        fd = jnp.take(F_src, td.dst, axis=0)
        F_g = lax.dynamic_slice_in_dim(F, gi * rows, rows)
        gn, ln = _grad_blocks(F_g, td, cfg, fd, interpret)
        grad_g = gn.reshape(rows, k) - sumF[None, :] + F_g
        cand_g = _cand_blocks(F_g, grad_g, sumF, td, cfg, fd, interpret)
        return None, (grad_g, ln, cand_g)

    _, (gr, ln, cd) = lax.scan(
        body,
        None,
        (
            jnp.arange(gt.n_groups),
            (gt.src_local, gt.dst, gt.mask, gt.block_id),
        ),
    )
    grad = gr.reshape(n_pad, k)
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
    node_llh = (
        ln.reshape(n_pad).astype(adt) + node_tail(F, sumF).astype(adt)
    )
    cand_full = cd.transpose(2, 0, 1, 3).reshape(num_s, n_pad)
    return grad, node_llh, cand_full


def train_pass_csr_grouped_tp(
    F: jax.Array,
    sumF: jax.Array,
    gt: GroupedTilesDev,
    cfg: BigClamConfig,
    k_axis: str,
    interpret: bool = False,
    F_gather: jax.Array = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """train_pass_csr_grouped under a SHARDED K axis: per group, the TP
    kernel split — partial-dot kernel over this device's K_loc columns,
    lax.psum of the per-edge partials over `k_axis`, consume kernels —
    instead of the fused kernels (in-VMEM dots cannot psum mid-kernel).

    F/sumF/F_gather hold K_loc columns; the returned candidate terms are
    NEIGHBOR-only (S, n_pad) — the caller adds the Armijo tails with its
    own psums (parallel.sharded.armijo_tail_select_sharded), exactly like
    the flat TP path. Returns (grad (n_pad, K_loc), llh_nbr (n_pad,),
    cand_nbr (S, n_pad))."""
    n_pad, k = F.shape
    assert n_pad == gt.n_pad, (n_pad, gt.n_pad)
    rows = gt.nb * gt.block_b
    num_s = len(cfg.step_candidates)
    F_src = F if F_gather is None else F_gather

    def body(_, xs):
        gi, tile_xs = xs
        td = _group_view(gt, tile_xs)
        fd = jnp.take(F_src, td.dst, axis=0)     # (G, T, K_loc)
        F_g = lax.dynamic_slice_in_dim(F, gi * rows, rows)
        x = lax.psum(edge_dots_csr(F_g, td, fd, interpret=interpret), k_axis)
        gn, ln = grad_nbr_from_x_csr(x, td, fd, cfg, interpret=interpret)
        grad_g = gn - sumF[None, :] + F_g
        xc = lax.psum(
            cand_dots_csr(F_g, grad_g, td, fd, cfg, interpret=interpret),
            k_axis,
        )
        cb = cand_nbr_from_x_csr(xc, td, cfg, interpret=interpret)  # (S, rows)
        return None, (grad_g, ln, cb)

    _, (gr, ln, cd) = lax.scan(
        body,
        None,
        (
            jnp.arange(gt.n_groups),
            (gt.src_local, gt.dst, gt.mask, gt.block_id),
        ),
    )
    grad = gr.reshape(n_pad, k)
    llh_nbr = ln.reshape(n_pad)
    cand_nbr = cd.transpose(1, 0, 2).reshape(num_s, n_pad)
    return grad, llh_nbr, cand_nbr


def train_pass_csr_grouped_kblocked(
    F: jax.Array,
    sumF: jax.Array,
    gt: GroupedTilesDev,
    cfg: BigClamConfig,
    interpret: bool = False,
    F_gather: jax.Array = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Grouped train pass with the K axis processed in gt.kc-column blocks
    — single-chip large K, where whole (T, K)/(B, K) rows no longer fit
    VMEM (fit_tile_shape refuses at K ≳ 2500 and round-3 fell back to XLA).

    Same two-stage shape as the TP kernel split, with a lax.scan over K
    blocks in place of the psum over "k": per group, (1) accumulate the
    full per-edge dots x across K blocks (partial-dot kernel per block),
    then (2) per K block, consume x into that block's gradient columns and
    accumulate the candidate partial dots, (3) one candidate-consume kernel
    per group. Each fd row is gathered TWICE (once by the dots stage, once
    by the consume stage — the two scans cannot share the gather without
    holding a full-K fd, which is exactly what doesn't fit), so gather
    traffic is 2x the plain grouped pass; the VMEM win is what buys the
    path its existence at K ≳ 2500.

    Returns (grad (n_pad, K), llh_nbr (n_pad,), cand_nbr (S, n_pad)) —
    candidate terms are NEIGHBOR-only; feed armijo_update (which adds the
    Armijo tails in XLA, where full-K row ops are cheap)."""
    n_pad, k = F.shape
    assert n_pad == gt.n_pad, (n_pad, gt.n_pad)
    kc = gt.kc
    assert kc > 0 and k % kc == 0, (k, kc)
    n_kb = k // kc
    rows = gt.nb * gt.block_b
    num_s = len(cfg.step_candidates)
    F_src = F if F_gather is None else F_gather

    def body(_, xs):
        gi, tile_xs = xs
        td = _group_view(gt, tile_xs)
        F_g = lax.dynamic_slice_in_dim(F, gi * rows, rows)
        gmax, t = td.src_local.shape[0], td.tile_t

        def fd_of(kb):
            cols = lax.dynamic_slice_in_dim(F_src, kb * kc, kc, axis=1)
            return jnp.take(cols, td.dst, axis=0)        # (G, T, kc)

        # stage 1: full edge dots, accumulated over K blocks
        def dots_kb(x_acc, kb):
            F_g_kb = lax.dynamic_slice_in_dim(F_g, kb * kc, kc, axis=1)
            x_kb = edge_dots_csr(F_g_kb, td, fd_of(kb), interpret=interpret)
            return x_acc + x_kb, None

        x, _ = lax.scan(
            dots_kb, jnp.zeros((gmax, 1, t), F.dtype), jnp.arange(n_kb)
        )

        # stage 2: per K block, gradient columns + candidate partial dots
        def consume_kb(xc_acc, kb):
            fd = fd_of(kb)
            F_g_kb = lax.dynamic_slice_in_dim(F_g, kb * kc, kc, axis=1)
            sumF_kb = lax.dynamic_slice_in_dim(sumF, kb * kc, kc)
            gn_kb, ln_kb = grad_nbr_from_x_csr(
                x, td, fd, cfg, interpret=interpret
            )
            grad_kb = gn_kb - sumF_kb[None, :] + F_g_kb
            xc_kb = cand_dots_csr(
                F_g_kb, grad_kb, td, fd, cfg, interpret=interpret
            )
            return xc_acc + xc_kb, (grad_kb, ln_kb)

        xc, (grads, lns) = lax.scan(
            consume_kb,
            jnp.zeros((gmax, num_s, t), F.dtype),
            jnp.arange(n_kb),
        )
        grad_g = grads.transpose(1, 0, 2).reshape(rows, k)
        cb = cand_nbr_from_x_csr(xc, td, cfg, interpret=interpret)
        # llh_nbr depends only on x and the mask — identical across blocks
        return None, (grad_g, lns[0], cb)

    _, (gr, ln, cd) = lax.scan(
        body,
        None,
        (
            jnp.arange(gt.n_groups),
            (gt.src_local, gt.dst, gt.mask, gt.block_id),
        ),
    )
    grad = gr.reshape(n_pad, k)
    llh_nbr = ln.reshape(n_pad)
    cand_nbr = cd.transpose(1, 0, 2).reshape(num_s, n_pad)
    return grad, llh_nbr, cand_nbr


def train_pass_csr_grouped_kblocked_tp(
    F: jax.Array,
    sumF: jax.Array,
    gt: GroupedTilesDev,
    cfg: BigClamConfig,
    k_axis: str,
    interpret: bool = False,
    F_gather: jax.Array = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """train_pass_csr_grouped_kblocked under a SHARDED K axis — the last
    layout cell: K so large that even K_loc = K/tp exceeds the kernels'
    VMEM bound (e.g. K=25600 at tp=8 -> K_loc=3200, refused by
    fit_tile_shape; round-4 PARITY.md deferred item).

    Composition of the two existing schedules: per group, (1) accumulate
    per-edge partial dots over this device's LOCAL kc-column K blocks
    (lax.scan), then ONE lax.psum over `k_axis` completes the global dots
    (still 1 float/edge — the K-block scan adds no collective traffic);
    (2) per local K block, consume the global x into that block's gradient
    columns and accumulate candidate partial dots; (3) psum the candidate
    partials over `k_axis`, one candidate-consume kernel per group.

    With tp == 1 the psums are identity and this is exactly the
    single-chip kblocked pass — the sharded trainer uses it for BOTH, so
    the DP-only large-K path and the TP path share one step.

    F/sumF/F_gather hold K_loc columns, gt.kc | K_loc. Returns
    (grad (n_pad, K_loc), llh_nbr (n_pad,), cand_nbr (S, n_pad)) —
    candidate terms NEIGHBOR-only, Armijo tails are the caller's psums
    (parallel.sharded.armijo_tail_select_sharded)."""
    n_pad, k = F.shape
    assert n_pad == gt.n_pad, (n_pad, gt.n_pad)
    kc = gt.kc
    assert kc > 0 and k % kc == 0, (k, kc)
    n_kb = k // kc
    rows = gt.nb * gt.block_b
    num_s = len(cfg.step_candidates)
    F_src = F if F_gather is None else F_gather

    def body(_, xs):
        gi, tile_xs = xs
        td = _group_view(gt, tile_xs)
        F_g = lax.dynamic_slice_in_dim(F, gi * rows, rows)
        gmax, t = td.src_local.shape[0], td.tile_t

        def fd_of(kb):
            cols = lax.dynamic_slice_in_dim(F_src, kb * kc, kc, axis=1)
            return jnp.take(cols, td.dst, axis=0)        # (G, T, kc)

        def dots_kb(x_acc, kb):
            F_g_kb = lax.dynamic_slice_in_dim(F_g, kb * kc, kc, axis=1)
            x_kb = edge_dots_csr(F_g_kb, td, fd_of(kb), interpret=interpret)
            return x_acc + x_kb, None

        x_loc, _ = lax.scan(
            dots_kb, jnp.zeros((gmax, 1, t), F.dtype), jnp.arange(n_kb)
        )
        x = lax.psum(x_loc, k_axis)                      # global edge dots

        def consume_kb(xc_acc, kb):
            fd = fd_of(kb)
            F_g_kb = lax.dynamic_slice_in_dim(F_g, kb * kc, kc, axis=1)
            sumF_kb = lax.dynamic_slice_in_dim(sumF, kb * kc, kc)
            gn_kb, ln_kb = grad_nbr_from_x_csr(
                x, td, fd, cfg, interpret=interpret
            )
            grad_kb = gn_kb - sumF_kb[None, :] + F_g_kb
            xc_kb = cand_dots_csr(
                F_g_kb, grad_kb, td, fd, cfg, interpret=interpret
            )
            return xc_acc + xc_kb, (grad_kb, ln_kb)

        xc_loc, (grads, lns) = lax.scan(
            consume_kb,
            jnp.zeros((gmax, num_s, t), F.dtype),
            jnp.arange(n_kb),
        )
        xc = lax.psum(xc_loc, k_axis)
        grad_g = grads.transpose(1, 0, 2).reshape(rows, k)
        cb = cand_nbr_from_x_csr(xc, td, cfg, interpret=interpret)
        # ln depends only on the (already global) x and the mask —
        # identical across local K blocks and across K shards
        return None, (grad_g, lns[0], cb)

    _, (gr, ln, cd) = lax.scan(
        body,
        None,
        (
            jnp.arange(gt.n_groups),
            (gt.src_local, gt.dst, gt.mask, gt.block_id),
        ),
    )
    grad = gr.reshape(n_pad, k)
    llh_nbr = ln.reshape(n_pad)
    cand_nbr = cd.transpose(1, 0, 2).reshape(num_s, n_pad)
    return grad, llh_nbr, cand_nbr


def candidates_csr_grouped(
    F: jax.Array,
    grad: jax.Array,
    sumF: jax.Array,
    gt: GroupedTilesDev,
    cfg: BigClamConfig,
    interpret: bool = False,
) -> jax.Array:
    """candidates_csr over the grouped layout (see grad_llh_csr_grouped).
    The train step uses train_pass_csr_grouped instead (shares the gather);
    this standalone form exists for tests and ad-hoc use."""
    n_pad, k = F.shape
    assert n_pad == gt.n_pad, (n_pad, gt.n_pad)
    rows = gt.nb * gt.block_b
    num_s = len(cfg.step_candidates)

    def body(_, xs):
        gi, tile_xs = xs
        td = _group_view(gt, tile_xs)
        fd = jnp.take(F, td.dst, axis=0)
        F_g = lax.dynamic_slice_in_dim(F, gi * rows, rows)
        G_g = lax.dynamic_slice_in_dim(grad, gi * rows, rows)
        return None, _cand_blocks(F_g, G_g, sumF, td, cfg, fd, interpret)

    _, out = lax.scan(
        body,
        None,
        (
            jnp.arange(gt.n_groups),
            (gt.src_local, gt.dst, gt.mask, gt.block_id),
        ),
    )
    # (n_groups, nb, S, B) -> (S, n_pad)
    return out.transpose(2, 0, 1, 3).reshape(num_s, n_pad)
