"""Armijo backtracking line search, all 16 candidates in one fused pass.

Replaces C14 (SURVEY.md §2; reference Bigclamv2.scala:136-146): the reference
evaluated the 16 candidate steps via an RDD `cartesian` — 16 more full
neighbor sweeps, each re-broadcasting F. Here each edge chunk is gathered
ONCE (F_src, grad_src, F_dst) and all candidates are evaluated against the
gathered tiles (lax.scan over candidates inside the chunk), so HBM traffic is
~1 gather per edge instead of 16. Candidate semantics are exactly the
reference's: F_u' = clip(F_u + eta*grad_u, min_f, max_f) scored against
everyone else's OLD rows with the node-local sumF adjustment
sumF' = sumF - F_u + F_u' (Bigclamv2.scala:137-143), accepted iff

    ell_eta(u) >= ell(u) + alpha * eta * ||grad_u||^2     (Bigclamv2.scala:144)

and the chosen step is the LARGEST accepted eta (groupByKey.max,
Bigclamv2.scala:145); nodes with no accepted candidate keep their row
(the Jacobi simultaneous update, C15).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.ops.objective import EdgeChunks, edge_terms


def candidates_scan(
    F: jax.Array,
    grad: jax.Array,
    edges: EdgeChunks,
    cfg: BigClamConfig,
    terms_fn,
) -> jax.Array:
    """Shared chunk-scan scaffold for the candidate pass: gather edge tiles
    once per chunk, let terms_fn produce the (S, chunk) masked LLH edge
    terms, segment-sum back to nodes. terms_fn(fs, gs, fd, mask) is either
    the XLA body below or the Pallas VMEM kernel
    (ops.pallas_kernels.candidate_edge_terms)."""
    n = F.shape[0]
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
    num_s = len(cfg.step_candidates)

    def chunk_body(acc, sdm):
        s, d, m = sdm
        ell = terms_fn(F[s], grad[s], F[d], m)   # (S, chunk)
        parts = jax.vmap(
            lambda v: jax.ops.segment_sum(
                v.astype(adt), s, num_segments=n, indices_are_sorted=True
            )
        )(ell)
        return acc + parts, None

    acc, _ = lax.scan(chunk_body, jnp.zeros((num_s, n), adt), edges)
    return acc


def candidates_pass(
    F: jax.Array,
    grad: jax.Array,
    edges: EdgeChunks,
    cfg: BigClamConfig,
) -> jax.Array:
    """Neighbor-sum part of ell_eta(u) for every candidate step (XLA body).

    Returns (S, N): for each candidate eta_i and node u,
    sum_{v in N(u)} [log(1 - clip(exp(-F_u'.F_v))) + F_u'.F_v].
    """
    etas = jnp.asarray(cfg.step_candidates, F.dtype)

    def terms_fn(fs, gs, fd, m):
        def one_eta(eta):
            nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
            x = jnp.einsum("ek,ek->e", nf, fd)
            _, ell = edge_terms(x, cfg)
            return ell * m

        return lax.map(one_eta, etas)   # (S, chunk), gathered tiles reused

    return candidates_scan(F, grad, edges, cfg, terms_fn)


def accept_stats(ok: jax.Array) -> jax.Array:
    """(S+1,) int32 accepted-step histogram from the (S, N) acceptance mask:
    slot s = #nodes whose CHOSEN (max-accepted) step is step_candidates[s],
    slot S = #rows with no accepted candidate.

    step_candidates is descending, so the chosen step is the first accepted
    row (argmax of the boolean mask). Padding rows never accept (their grad
    is -sumF <= 0, ops.objective padding conventions), so the accepted
    slots count REAL nodes only; the rejected slot includes padding — the
    metrics layer subtracts it out via the known node count (SURVEY.md §5
    line-search observability)."""
    num_s = ok.shape[0]
    accepted = jnp.any(ok, axis=0)
    chosen = jnp.argmax(ok, axis=0)            # first True (descending etas)
    onehot = (
        (chosen[None, :] == jnp.arange(num_s)[:, None]) & accepted[None, :]
    )
    counts = onehot.sum(axis=1).astype(jnp.int32)
    rejected = (~accepted).sum().astype(jnp.int32)
    return jnp.concatenate([counts, rejected[None]])


def armijo_select(
    F: jax.Array,
    grad: jax.Array,
    node_llh: jax.Array,
    cand_llh: jax.Array,
    cfg: BigClamConfig,
    with_stats: bool = False,
):
    """Acceptance test + max-accepted-step selection + Jacobi update, given
    the FULL per-candidate LLH (neighbor terms + Armijo tails), shape (S, N).

    Returns (F_new, sumF_new) with sumF recomputed as fresh column sums
    (fixes the incremental-update float drift, SURVEY.md Q7); with
    with_stats=True additionally returns the accept_stats histogram.
    """
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
    etas = jnp.asarray(cfg.step_candidates, F.dtype)
    gg = jnp.einsum("nk,nk->n", grad, grad).astype(adt)
    ok = cand_llh >= node_llh[None, :] + cfg.alpha * etas[:, None] * gg[None, :]
    # max accepted step per node; 0.0 when nothing accepted
    best_eta = jnp.max(jnp.where(ok, etas[:, None], 0.0), axis=0)
    accepted = jnp.any(ok, axis=0)
    F_new = jnp.where(
        accepted[:, None],
        jnp.clip(F + best_eta[:, None] * grad, cfg.min_f, cfg.max_f),
        F,
    )
    if with_stats:
        return F_new, F_new.sum(axis=0), accept_stats(ok)
    return F_new, F_new.sum(axis=0)


def armijo_update(
    F: jax.Array,
    sumF: jax.Array,
    grad: jax.Array,
    node_llh: jax.Array,
    cand_nbr: jax.Array,
    cfg: BigClamConfig,
    with_stats: bool = False,
):
    """armijo_select for callers holding only the NEIGHBOR candidate terms
    (candidates_pass output): adds the Armijo tail terms
    -F'.(sumF - F_u + F') + F'.F' per candidate, then selects/updates."""
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype
    etas = jnp.asarray(cfg.step_candidates, F.dtype)

    def tail_for(eta):
        nf = jnp.clip(F + eta * grad, cfg.min_f, cfg.max_f)
        sf_adj = sumF[None, :] - F + nf        # node-local sumF adjustment
        return (
            -jnp.einsum("nk,nk->n", nf, sf_adj)
            + jnp.einsum("nk,nk->n", nf, nf)
        ).astype(adt)

    tails = lax.map(tail_for, etas)            # (S, N)
    return armijo_select(
        F, grad, node_llh, cand_nbr + tails, cfg, with_stats=with_stats
    )
