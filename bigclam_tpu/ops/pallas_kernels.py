"""Pallas TPU kernel for the hot loop: all 16 Armijo candidates evaluated in
VMEM against once-loaded edge tiles.

Why: profiling the XLA path on Email-Enron K=100 (TPU v5e) shows the
candidate pass dominating the step (≈116 ms of 148 ms) at ~39 GB/s effective
HBM traffic — the 16 per-candidate sweeps re-stream the gathered
(chunk, K) F_src/grad_src/F_dst tiles from HBM. This kernel loads each edge
tile into VMEM ONCE and evaluates every candidate step size against it on
the VPU, writing only the (S, chunk) per-edge LLH terms back — a ~16x cut
in candidate-pass HBM reads.

The kernel consumes PRE-GATHERED per-edge rows (XLA's gather feeds it); the
semantics are bit-identical to ops.linesearch.candidates_pass's inner body:

    nf  = clip(F_src + eta * grad_src, min_f, max_f)
    x   = sum(nf * F_dst, axis=-1)
    omp = clip(-expm1(-x), 1-max_p, 1-min_p)   # ops.objective.edge_terms
    ell = log(omp) + x         (masked)

Layout: edge tiles (BLOCK_E, K_pad) with K_pad a multiple of 128 lanes;
the eta loop is unrolled at trace time (16 candidates). Correctness vs the
XLA path is tested in interpret mode on CPU and exercised on real TPU by
bench.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.ops.objective import edge_terms

BLOCK_E = 1024          # edges per tile: 3 * 1024 * 128 * 4B = 1.5 MB at K=128
VMEM_BUDGET_BYTES = 10 * 1024 * 1024   # input tiles must fit well under ~16 MB


def pallas_block_size(m_e: int, k: int = 128, interpret: bool = False):
    """The edge-tile size for a (m_e, k) chunk, or None if unsupported.

    Hardware constraints: XLA lays the 1-D mask out in 1024-element tiles,
    so the edge block must be exactly BLOCK_E and divide the chunk; the
    three (BLOCK_E, k) input tiles must also fit the VMEM budget (large
    K_pad falls back to the XLA path rather than failing Mosaic compile).
    Interpret mode relaxes only the alignment, not divisibility."""
    if 3 * BLOCK_E * k * 4 > VMEM_BUDGET_BYTES:
        return None
    if m_e % BLOCK_E == 0:
        return BLOCK_E
    if interpret and m_e <= BLOCK_E:
        return m_e          # single exact block; no tiling in interpret mode
    return None


def _cand_kernel(fs_ref, gs_ref, fd_ref, m_ref, out_ref, *, etas, cfg):
    fs = fs_ref[:]
    gs = gs_ref[:]
    fd = fd_ref[:]
    m = m_ref[:]
    for i, eta in enumerate(etas):
        nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
        x = jnp.sum(nf * fd, axis=1)
        _, ell = edge_terms(x, cfg)         # single source of the clip math
        out_ref[i, :] = ell * m


def candidate_edge_terms(
    fs: jax.Array,
    gs: jax.Array,
    fd: jax.Array,
    mask: jax.Array,
    cfg: BigClamConfig,
    interpret: bool = False,
) -> jax.Array:
    """(S, M) masked candidate LLH edge terms from pre-gathered rows.

    fs/gs/fd: (M, K_pad) gathered F_src/grad_src/F_dst; mask: (M,).
    M must be a multiple of BLOCK_E and K_pad a multiple of 128 (the
    caller pads; models.bigclam.prepare_graph chunks are already padded).
    """
    m_e, k = fs.shape
    block = pallas_block_size(m_e, k, interpret)
    if block is None:
        raise ValueError(
            f"chunk {m_e} x K_pad {k} unsupported by the pallas kernel "
            f"(needs chunk % {BLOCK_E} == 0 and tiles within VMEM budget)"
        )
    if not interpret:
        assert k % 128 == 0, k
    etas = cfg.step_candidates
    num_s = len(etas)
    kernel = functools.partial(_cand_kernel, etas=etas, cfg=cfg)
    grid = (m_e // block,)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((num_s, m_e), fs.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block,), lambda i: (i,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (num_s, block), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(fs, gs, fd, mask)


def candidates_pass_pallas(
    F: jax.Array,
    grad: jax.Array,
    edges,
    cfg: BigClamConfig,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in replacement for ops.linesearch.candidates_pass using the
    fused VMEM kernel for the per-edge terms (gather + segment_sum stay in
    XLA, via the shared candidates_scan scaffold). Returns (S, N)."""
    from bigclam_tpu.ops.linesearch import candidates_scan

    def terms_fn(fs, gs, fd, m):
        return candidate_edge_terms(fs, gs, fd, m, cfg, interpret=interpret)

    return candidates_scan(F, grad, edges, cfg, terms_fn)
