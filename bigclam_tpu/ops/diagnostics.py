"""Device-fused model-health diagnostics (ISSUE 8 tentpole).

The telemetry (ISSUE 4) and span/ledger layers (ISSUE 6) answer *where
the time went*; nothing answered *what the optimizer is doing* — a
diverging or silently-plateaued NMF ascent looks identical to a healthy
one until the final LLH. This module computes a compact health pack
INSIDE the already-jitted train step of every trainer (dense, sharded,
ring, sparse, sparse-sharded), where the gradient is in scope and the
numbers are free of host round trips:

    grad norm / max, update norm, effective Armijo step + accept
    fraction, active-community count, top-community mass share, max F
    entry, and (sparse) support churn + comm-cap occupancy + dense-
    fallback flag + exchanged-id count

packed into one (HEALTH_LEN,) float32 vector riding the TrainState
(`state.health`). The pack is gated by `cfg.health_every`:

* `health_every == 0` (the config default): the steps return
  `health=None` and compute NOTHING — the trajectory and the compiled
  step's math are bit-identical to the pre-health trainers (pinned by
  tests/test_health.py), the zero-cost off path of the NULL_SPAN
  contract.
* `health_every > 0` (step-baked — NOT in _HOST_ONLY_FIELDS, so two
  cadences never share a compiled step): a `lax.cond` keyed on
  `it % health_every` computes the pack on cadence iterations and
  returns zeros otherwise; the handful of reductions it adds is noise
  next to the step's 17 edge sweeps (<2% pinned at the default CLI
  cadence).

The host side (obs.health.HealthMonitor, driven from run_fit_loop)
fetches the vector only on cadence iterations, adds the LLH-window
derivatives (delta, slope, relative change) and the membership churn
against a rolling device-resident snapshot (the `*_top_community`
signatures below — an (N,) int32 argmax, not an F copy), and emits
`health` / `anomaly` telemetry events.

Slots that do not apply to a trainer (comm-cap occupancy on a single
chip) carry the NA sentinel -1.0; the monitor omits them from events.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

# Field order of the device health vector. Consumers index by name via
# HEALTH_INDEX; the host monitor turns it into a dict (dropping NA
# slots) before the event is emitted.
HEALTH_FIELDS = (
    "iter",            # iteration the pack describes (the update it->it+1)
    "llh",             # LLH of the step's INPUT F (same scalar the loop syncs)
    "grad_norm",       # global L2 norm of the gradient
    "grad_max",        # global max |grad| entry
    "update_norm",     # global L2 norm of F_new - F_old (the applied update)
    "step_eff",        # accept-weighted mean Armijo step (0 = all rejected)
    "accept_frac",     # fraction of rows that accepted any candidate step
    "active_comms",    # communities with column mass > ACTIVE_EPS
    "top_share",       # largest column mass / total mass
    "f_max",           # max F entry (box-ceiling proximity)
    "support_churn",   # sparse: fraction of member-id slots changed by the
                       # support update this iteration (NA on dense)
    "cap_occupancy",   # sparse sharded: touched ids / comm cap (NA else)
    "dense_fallback",  # sparse sharded: 1 when the sparse allreduce fell
                       # back to the dense psum this step (NA else)
    "exchanged_ids",   # sparse sharded: touched ids exchanged (NA else)
)
HEALTH_LEN = len(HEALTH_FIELDS)
HEALTH_INDEX = {name: i for i, name in enumerate(HEALTH_FIELDS)}

# sentinel for slots a trainer does not produce
NA = -1.0
# a community column counts as alive above this mass (padding columns
# are exact zeros, so they never count)
ACTIVE_EPS = 1e-12


def health_on(cfg) -> bool:
    """The single engagement predicate (trainer step builders branch on
    it at TRACE time — the off path adds no ops at all)."""
    return int(getattr(cfg, "health_every", 0) or 0) > 0


def init_health(cfg) -> Optional[jax.Array]:
    """The health leaf for FRESH states (init / checkpoint restore):
    an NA-filled vector when health is on, None when off. Seeding the
    initial state with the same (HEALTH_LEN,) leaf the step outputs
    keeps the TrainState pytree structure CONSTANT across the fit —
    otherwise the first iteration's None->array transition would
    retrace and recompile every jitted step (and its donating twin)
    once per fit."""
    if not health_on(cfg):
        return None
    return jnp.full((HEALTH_LEN,), NA, jnp.float32)


def grad_stats(grad, node_axis=None, k_axis=None) -> jax.Array:
    """(2,) float32 [sum of grad^2, max |grad|] — the only health inputs
    that exist solely inside the edge-sweep body, so the sharded steps
    compute them in-shard (psum/pmax over the given mesh axes) and ship
    the two scalars out of shard_map; everything else in the pack is
    derived from state arrays in the step wrapper."""
    gsq = jnp.sum((grad * grad).astype(jnp.float32))
    gmax = jnp.max(jnp.abs(grad)).astype(jnp.float32)
    if node_axis is not None:
        gsq = lax.psum(gsq, node_axis)
        gmax = lax.pmax(gmax, node_axis)
    if k_axis is not None:
        gsq = lax.psum(gsq, k_axis)
        gmax = lax.pmax(gmax, k_axis)
    return jnp.stack([gsq, gmax])


def zero_grad_stats() -> jax.Array:
    """Placeholder for steps built with health off (keeps the in-shard
    return arity uniform; a constant, so XLA folds it away)."""
    return jnp.zeros(2, jnp.float32)


def gated_grad_stats(cfg, it, grad, node_axis=None, k_axis=None):
    """grad_stats under the cadence cond: the O(N*K) reductions (the
    only expensive part of the pack) run ONLY on cadence iterations —
    off-cadence steps pay the cond, nothing else. Collectives inside
    the branch are fine where the existing support-update cond already
    runs all_gathers: the predicate is replicated."""
    every = max(int(cfg.health_every), 1)
    return lax.cond(
        (it % every) == 0,
        lambda g: grad_stats(g, node_axis=node_axis, k_axis=k_axis),
        lambda g: jnp.zeros(2, jnp.float32),
        grad,
    )


def latch_extras(prev_health, extras: Dict[str, jax.Array]):
    """Max-since-last-sample latch for the cheap per-step event slots
    (sparse dense_fallback / cap_occupancy / exchanged_ids /
    support_churn): a fallback on an OFF-cadence step must still be
    visible in the next health sample, so these scalars are computed
    every step (they are O(1) or one cheap pass — unlike the gated grad
    stats) and folded into a running max that resets after each emitted
    sample. NA (-1) is the max-identity, so never-produced slots stay
    NA.

    Returns (latched extras dict, skip_carry vector): the pack's
    compute branch emits the latched values; the skip branch returns
    `skip_carry` so the latch RIDES state.health between samples
    (iter slot stays NA — the host only reads on cadence iterations).
    """
    if prev_health is None:
        prev_health = jnp.full((HEALTH_LEN,), NA, jnp.float32)
    sampled_last = prev_health[HEALTH_INDEX["iter"]] >= 0
    out: Dict[str, jax.Array] = {}
    carry = jnp.full((HEALTH_LEN,), NA, jnp.float32)
    for name, cur in extras.items():
        idx = HEALTH_INDEX[name]
        base = jnp.where(
            sampled_last, jnp.float32(NA), prev_health[idx]
        )
        val = jnp.maximum(base, jnp.asarray(cur, jnp.float32))
        out[name] = val
        carry = carry.at[idx].set(val)
    return out, carry


def health_pack(
    cfg,
    it,
    F_old,
    F_new,
    sumF_new,
    accept_hist,
    gstats=None,
    extras: Optional[Dict[str, jax.Array]] = None,
    grad=None,
    skip_carry=None,
) -> jax.Array:
    """The (HEALTH_LEN,) float32 pack, lax.cond-gated on the cadence —
    off-cadence iterations pay the cond and nothing else (every
    reduction, including the grad stats when `grad` is given, lives
    inside the compute branch).

    Called inside the jitted step (single-chip: in the step body, pass
    `grad` directly; sharded: in the step wrapper after shard_map, pass
    `gstats` from the in-shard gated_grad_stats — the full grad never
    leaves the shard). `it` is the step's INPUT iteration counter,
    `extras` optional named overrides for the sparse slots (pre-latched
    via latch_extras where off-cadence events must survive to the next
    sample), `skip_carry` the off-cadence return (default NA-full; the
    latch rides it). llh is stamped by the host monitor (the loop
    already syncs it; keeping it out of the pack spares the sharded
    steps one more replicated output).
    """
    every = max(int(cfg.health_every), 1)
    ex = extras or {}
    assert (gstats is None) != (grad is None), "pass gstats XOR grad"

    def compute(g):
        f32 = jnp.float32
        gs = grad_stats(g) if g is not None else gstats
        dF = (F_new - F_old).astype(f32)
        update_norm = jnp.sqrt(jnp.sum(dF * dF))
        etas = jnp.asarray(cfg.step_candidates, f32)
        hist = accept_hist.astype(f32)
        total = jnp.maximum(hist.sum(), 1.0)
        accepted = hist[:-1]
        step_eff = (etas * accepted).sum() / total
        accept_frac = accepted.sum() / total
        colmass = sumF_new.astype(f32)
        active = (colmass > ACTIVE_EPS).sum().astype(f32)
        mass = colmass.sum()
        top_share = jnp.max(colmass) / jnp.maximum(mass, ACTIVE_EPS)
        f_max = jnp.max(F_new).astype(f32)
        slots = {
            "iter": it.astype(f32),
            "llh": jnp.asarray(jnp.nan, f32),   # host-stamped
            "grad_norm": jnp.sqrt(gs[0]),
            "grad_max": gs[1],
            "update_norm": update_norm,
            "step_eff": step_eff,
            "accept_frac": accept_frac,
            "active_comms": active,
            "top_share": top_share,
            "f_max": f_max,
            "support_churn": jnp.asarray(NA, f32),
            "cap_occupancy": jnp.asarray(NA, f32),
            "dense_fallback": jnp.asarray(NA, f32),
            "exchanged_ids": jnp.asarray(NA, f32),
        }
        for name, val in ex.items():
            assert name in slots, name
            slots[name] = jnp.asarray(val, f32)
        return jnp.stack([slots[name] for name in HEALTH_FIELDS])

    def skip(g):
        # never read by the host (it only fetches on cadence iterations);
        # slot 0 = -1 marks the vector as not-computed for any stray
        # reader, and the latched extras (when any) ride the carry
        del g
        if skip_carry is not None:
            return skip_carry
        return jnp.full((HEALTH_LEN,), NA, jnp.float32)

    return lax.cond((it % every) == 0, compute, skip, grad)


# ------------------------------------------------------- membership churn
# (N,) int32 top-community signatures: the rolling snapshot the monitor
# keeps device-resident between health samples is this argmax, not a full
# F copy — O(N) bytes, donation-free. -1 marks empty (all-zero) rows, so
# padding rows compare equal forever and never contribute churn.

@jax.jit
def dense_top_community(F) -> jax.Array:
    rowmax = jnp.max(F, axis=1)
    arg = jnp.argmax(F, axis=1).astype(jnp.int32)
    return jnp.where(rowmax > 0, arg, jnp.int32(-1))


@jax.jit
def sparse_top_community(ids, w) -> jax.Array:
    j = jnp.argmax(w, axis=1)
    top = jnp.take_along_axis(ids, j[:, None], axis=1)[:, 0].astype(jnp.int32)
    return jnp.where(jnp.max(w, axis=1) > 0, top, jnp.int32(-1))


@jax.jit
def sig_changed(a, b) -> jax.Array:
    """Count of signature entries that differ (host divides by the live
    row count for the churn fraction)."""
    return (a != b).sum().astype(jnp.int32)
