"""Fold-in inference: optimize node rows against a FROZEN F (ISSUE 14).

The serving tentpole's observation (ROADMAP item 2, and the same locality
argument as "Speeding Up BigClam Implementation on SNAP", arXiv:1712.01209):
the per-node row update the trainer already jits IS the fold-in operator.
Holding everyone else's rows fixed, the terms of the global LLH that
involve node u are

    ell(u) = sum_{v in N(u)} [ log(1 - clip(exp(-r.F_v))) + r.F_v ]
             - r . sumF_others

where r is u's candidate row and sumF_others = sum_w F_w over the FROZEN
rows (for an existing node that is sumF - F_u; for a brand-new node it is
the global sumF as-is). This is exactly the trainer's per-node objective
(ops.objective: nbr terms + node_tail with the node-local sumF adjustment
folded), so optimizing r with the same Armijo candidate ladder
(ops.linesearch semantics: accept iff ell_eta >= ell + alpha*eta*||g||^2,
take the LARGEST accepted eta) converges to the same row the full fit
would have produced for u against that F — the fold-in correctness test
pins it.

Everything here is BATCHED over B query nodes with padded neighbor lists
(B, D): each node's trajectory depends only on its own row and the frozen
F, so batched fold-in equals sequential fold-in node-for-node (pinned by
tests/test_serve.py), and a request batcher can coalesce arbitrary
suggest queries into one device call. The whole optimization runs inside
ONE jitted lax.while_loop with per-node convergence (|1 - llh/llh_prev| <
conv_tol, mirroring models.bigclam._rel_change / run_fit_loop): converged
rows freeze while the rest keep iterating, and there are no host round
trips. The initial rows buffer is DONATED (the serving hot loop's
ping-pong, same discipline as run_fit_loop's donate_state).

Padding conventions: neighbor slots beyond a node's degree carry mask 0
(their gathered rows are ignored by construction: coeff = mask/omp = 0);
padding QUERY rows (batch rounded up for compile-cache reuse) carry
all-zero rows + all-zero masks and stay at zero forever (grad =
-sumF_others <= 0 clips back to the zero row — the ops.objective padding
argument).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.ops.objective import edge_terms


def foldin_pass(
    rows: jax.Array,
    nbr_rows: jax.Array,
    nbr_mask: jax.Array,
    sumF_others: jax.Array,
    cfg: BigClamConfig,
) -> Tuple[jax.Array, jax.Array]:
    """Fused gradient + per-node LLH of a row batch vs frozen neighbors.

    rows (B, K), nbr_rows (B, D, K), nbr_mask (B, D), sumF_others (B, K)
    -> (grad (B, K), llh (B,)). Same math as ops.objective.grad_llh
    restricted to the batch: coeff = mask/omp folds the +F_v term, and
    the node tail is -r.sumF_others (== -r.sumF + r.r for an existing
    node, ops.objective.node_tail)."""
    x = jnp.einsum("bk,bdk->bd", rows, nbr_rows)
    omp, ell = edge_terms(x, cfg)
    nbr_llh = (ell * nbr_mask).sum(axis=-1)
    coeff = nbr_mask / omp
    grad = jnp.einsum("bd,bdk->bk", coeff, nbr_rows) - sumF_others
    llh = nbr_llh - jnp.einsum("bk,bk->b", rows, sumF_others)
    return grad, llh


def foldin_candidates(
    rows: jax.Array,
    grad: jax.Array,
    nbr_rows: jax.Array,
    nbr_mask: jax.Array,
    sumF_others: jax.Array,
    cfg: BigClamConfig,
) -> jax.Array:
    """(S, B) candidate LLHs: ell_eta per node for every Armijo step
    (ops.linesearch.candidates_pass semantics, gathered tiles reused)."""
    etas = jnp.asarray(cfg.step_candidates, rows.dtype)

    def one_eta(eta):
        nf = jnp.clip(rows + eta * grad, cfg.min_f, cfg.max_f)
        x = jnp.einsum("bk,bdk->bd", nf, nbr_rows)
        _, ell = edge_terms(x, cfg)
        return (ell * nbr_mask).sum(axis=-1) - jnp.einsum(
            "bk,bk->b", nf, sumF_others
        )

    return lax.map(one_eta, etas)


def _rel_change_elem(new: jax.Array, old: jax.Array) -> jax.Array:
    """Elementwise |1 - new/old| with the old == 0 corner handled — the
    jnp twin of models.bigclam._rel_change (run_fit_loop's convergence
    predicate), applied per node instead of per fit."""
    safe = jnp.where(old == 0.0, 1.0, old)
    rc = jnp.abs(1.0 - new / safe)
    return jnp.where(
        old == 0.0, jnp.where(new == 0.0, 0.0, jnp.inf), rc
    )


def neighbor_mean_rows(
    nbr_rows: jax.Array, nbr_mask: jax.Array
) -> jax.Array:
    """Warm-start rows: the masked mean of the frozen neighbor rows —
    a node joins its neighborhood's communities at average strength, the
    analog of the trainer's ego-net conductance seeding for one row. A
    zero init would be a fixed point (grad = -sumF_others <= 0 clips
    straight back), so fold-in always starts here unless the caller
    passes explicit rows."""
    deg = jnp.maximum(nbr_mask.sum(axis=-1, keepdims=True), 1.0)
    return jnp.einsum("bd,bdk->bk", nbr_mask, nbr_rows) / deg


def make_foldin_fit(
    cfg: BigClamConfig,
    max_iters: Optional[int] = None,
    conv_tol: Optional[float] = None,
):
    """Build the jitted batched fold-in optimizer.

    Returns fit(rows0, nbr_rows, nbr_mask, sumF_others) ->
    (rows (B, K), llh (B,), iters (B,)): Armijo row ascent to per-node
    convergence inside one lax.while_loop (no host round trips — the
    serving hot loop). rows0 is DONATED; jit's shape cache makes one
    returned callable serve every padded (B, D) the batcher produces.
    `llh` is each node's ell at its final row (the fold-in quality
    figure the serve gate bands against a full refit)."""
    mi = int(cfg.max_iters if max_iters is None else max_iters)
    tol = float(cfg.conv_tol if conv_tol is None else conv_tol)

    def fit(rows, nbr_rows, nbr_mask, sumF_others):
        dt = rows.dtype
        etas = jnp.asarray(cfg.step_candidates, dt)

        def cond(carry):
            it, rows, llh_prev, active, iters = carry
            return (it < mi) & jnp.any(active)

        def body(carry):
            it, rows, llh_prev, active, iters = carry
            grad, llh = foldin_pass(
                rows, nbr_rows, nbr_mask, sumF_others, cfg
            )
            # per-node convergence BEFORE applying this iteration's
            # update: a converged node keeps the row whose llh fired the
            # test (run_fit_loop returns the converged step's INPUT
            # state for the same reason)
            conv = (it > 0) & (_rel_change_elem(llh, llh_prev) < tol)
            act = active & ~conv
            cand = foldin_candidates(
                rows, grad, nbr_rows, nbr_mask, sumF_others, cfg
            )
            gg = jnp.einsum("bk,bk->b", grad, grad)
            ok = (
                cand
                >= llh[None, :] + cfg.alpha * etas[:, None] * gg[None, :]
            )
            best_eta = jnp.max(
                jnp.where(ok, etas[:, None], 0.0), axis=0
            )
            accepted = jnp.any(ok, axis=0)
            rows = jnp.where(
                (act & accepted)[:, None],
                jnp.clip(
                    rows + best_eta[:, None] * grad, cfg.min_f, cfg.max_f
                ),
                rows,
            )
            llh_prev = jnp.where(active, llh, llh_prev)
            return (it + 1, rows, llh_prev, act, iters + act)

        b = rows.shape[0]
        init = (
            jnp.zeros((), jnp.int32),
            rows,
            jnp.full((b,), -jnp.inf, dt),
            jnp.ones((b,), bool),
            jnp.zeros((b,), jnp.int32),
        )
        _, rows, llh, _, iters = lax.while_loop(cond, body, init)
        return rows, llh, iters

    return jax.jit(fit, donate_argnums=(0,))


@jax.jit
def apply_rows(
    F: jax.Array,
    sumF: jax.Array,
    nodes: jax.Array,
    rows: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Commit a folded row batch into a frozen state (ISSUE 15: the
    warm-start refit's write half): F[nodes] <- rows, sumF updated by
    the exact row delta (no O(N*K) re-reduction per batch — the refit
    sweeps many batches per round). Padded columns are zero in `rows`
    by construction, so sumF's padding stays inert."""
    old = F[nodes]
    F = F.at[nodes].set(rows)
    return F, sumF + (rows - old).sum(axis=0)


# ------------------------------------------------- frozen-state gathers
def gather_neighbor_rows(F: jax.Array, nbr_ids: jax.Array) -> jax.Array:
    """Dense frozen rows for a padded neighbor batch: (B, D, K). Padding
    slots may point at any valid row — their mask is 0."""
    return F[nbr_ids]


def densify_member_rows(
    ids: jax.Array, w: jax.Array, nbr_ids: jax.Array, k_pad: int
) -> jax.Array:
    """Sparse-representation frozen rows: gather the (B, D, M) member
    lists of the neighbor batch and scatter them dense to (B, D, k_pad).
    Sentinel slots (id == k_pad, ops.sparse_members) land in a discarded
    overflow column. O(B*D*K) is the fold-in working set either way —
    the sparse trainer's state stays M-sized; only the query batch pays
    K columns."""
    mi = ids[nbr_ids]
    mw = w[nbr_ids]

    def one(row_ids, row_w):
        return (
            jnp.zeros((k_pad + 1,), row_w.dtype).at[row_ids].add(row_w)
        )[:k_pad]

    return jax.vmap(jax.vmap(one))(mi, mw)


def densify_rows(
    ids: jax.Array, w: jax.Array, node_ids: jax.Array, k_pad: int
) -> jax.Array:
    """(B, k_pad) dense rows of the given nodes from sparse member lists
    (the sumF_others subtraction for existing sparse nodes)."""
    mi = ids[node_ids]
    mw = w[node_ids]

    def one(row_ids, row_w):
        return (
            jnp.zeros((k_pad + 1,), row_w.dtype).at[row_ids].add(row_w)
        )[:k_pad]

    return jax.vmap(one)(mi, mw)
