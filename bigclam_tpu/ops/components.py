"""On-device connected components: batched min-label propagation over CSR.

Why this exists (QUALITY_MIDSCALE_r05.json): the quality pipeline's discrete
stage (models/quality.py atomize_reassign / repair_communities) needs the
graph components of EVERY thresholded column, and the host implementation —
a scipy.sparse.csgraph call per column over a freshly built induced-subgraph
CSR — is a K-long sequential host scan. At the midscale gate (N=12K, K=500)
those scans dominate the 644.7s quality stage; at the com-Amazon K~5k gate
they are minutes per repair round. Here all columns propagate together in
ONE jitted pass over the graph's directed-edge arrays (the same src-sorted
CSR order the train-step tiles are built from, so segment reductions run
with sorted indices), batched over columns to bound the (CB, E) working set.

Algorithm: min-label propagation with pointer jumping (path halving).

    labels0[v] = v if member[v] else N          (slot N = sentinel)
    per round:
      (1) edge relaxation — for each directed edge (s, d) with BOTH
          endpoints members, label[d] is offered to s; a segment_min over
          the src-sorted edges folds all offers per node;
      (2) pointer jumping — labels <- min(labels, labels[labels]): a
          member's label is always a member node id of the same component
          (true at init, preserved by both moves), so the label chain can
          be followed and halved.

Edge relaxation alone converges in diameter(component) rounds; composed
with pointer jumping the min-label forest's depth at least halves per
round, giving the O(log N) Shiloach-Vishkin style bound that makes the
`while_loop` safe to jit at any N. Convergence is detected exactly (no
label changed), so the bound is a safety property, not a tuning knob.

The host scipy path (models.quality._graph_components) remains the ORACLE
and the small-problem fallback — per-column partition equality on random
planted graphs is pinned by tests/test_components.py. The per-component
membership/size/internal-edge-density stats the discrete stage consumes are
fused into the same jitted pass (one extra segment_sum pair + gathers), so
repair decisions read device reductions instead of a downloaded F.
"""

from __future__ import annotations

import functools
import os
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# below this many (node x column) cells the per-column host scipy path is
# faster than a device dispatch + download round trip (measured on the
# midscale fixtures; override with BIGCLAM_COMPONENTS=host|device)
DEVICE_MIN_CELLS = 1 << 21
# per-batch edge-gather element budget: columns are processed CB at a time
# with CB ~ EDGE_ELEM_BUDGET / E so the (CB, E) relaxation arrays stay
# bounded (~256 MB at int32) regardless of K
EDGE_ELEM_BUDGET = 1 << 26


def components_backend(
    num_nodes: int, num_cols: int, override: str = "auto"
) -> str:
    """Resolve the components implementation: 'host' (scipy oracle) or
    'device' (batched label propagation). `override` other than 'auto'
    wins; then the BIGCLAM_COMPONENTS env hook; then the auto rule:
    device only on an ACCELERATOR backend and above the work-size floor.
    Measured rationale (round 6, N=12K K=500): on a CPU backend the
    "device" pass runs on the same cores the scipy scan would — paying
    XLA dispatch and O(log N) whole-array rounds to replace a 0.35 s
    sequential scan with a 5.6 s one — while on TPU the host path is not
    even an option without downloading F (the transfer the quality
    residency protocol forbids) and the batched pass rides the VPU."""
    if override in ("host", "device"):
        return override
    env = os.environ.get("BIGCLAM_COMPONENTS", "")
    if env in ("host", "device"):
        return env
    if jax.default_backend() == "cpu":
        return "host"
    return (
        "device"
        if num_nodes * max(num_cols, 1) >= DEVICE_MIN_CELLS
        else "host"
    )


@functools.partial(jax.jit, static_argnames=("n",))
def _labels_and_stats(src, dst, member, n):
    """The fused device pass for one column batch.

    src, dst: (E,) int32 directed edges, src sorted (CSR order).
    member:   (CB, n) bool — one thresholded column per row.
    Returns (labels, comp_size, comp_edges), each (CB, n) int32:
      labels[c, v]     min member node id of v's component (n if not member)
      comp_size[c, v]  node count of v's component (0 if not member)
      comp_edges[c, v] internal DIRECTED edge count of v's component
    """
    cb = member.shape[0]
    sentinel = jnp.int32(n)
    iota = jnp.arange(n, dtype=jnp.int32)
    lab0 = jnp.where(member, iota[None, :], sentinel)
    # sentinel slot n: labels[n] = n, so pointer jumps through non-members
    # are fixed points
    lab0 = jnp.concatenate(
        [lab0, jnp.full((cb, 1), sentinel, jnp.int32)], axis=1
    )
    ok_edge = member[:, src] & member[:, dst]          # (CB, E)

    def relax(labels):
        cand = jnp.where(ok_edge, labels[:, dst], sentinel)
        seg = jax.vmap(
            lambda c: jax.ops.segment_min(
                c, src, num_segments=n + 1, indices_are_sorted=True
            )
        )(cand)
        new = jnp.minimum(labels, seg)
        # pointer jumping (path halving); min keeps the invariant that a
        # member's label only ever decreases toward its component root
        return jnp.minimum(new, jnp.take_along_axis(new, new, axis=1))

    def cond(carry):
        return carry[1]

    def body(carry):
        labels, _ = carry
        new = relax(labels)
        return new, jnp.any(new != labels)

    labels, _ = jax.lax.while_loop(cond, body, (lab0, jnp.bool_(True)))

    ones = member.astype(jnp.int32)
    sizes_root = jax.vmap(
        lambda lab, m: jax.ops.segment_sum(m, lab, num_segments=n + 1)
    )(labels[:, :n], ones)
    e_lab = jnp.where(ok_edge, labels[:, src], sentinel)
    edges_root = jax.vmap(
        lambda el: jax.ops.segment_sum(
            jnp.ones_like(el, jnp.int32) * (el < sentinel), el,
            num_segments=n + 1,
        )
    )(e_lab)
    comp_size = jnp.take_along_axis(sizes_root, labels, axis=1)[:, :n]
    comp_edges = jnp.take_along_axis(edges_root, labels, axis=1)[:, :n]
    live = labels[:, :n] < sentinel
    return (
        labels[:, :n],
        jnp.where(live, comp_size, 0),
        jnp.where(live, comp_edges, 0),
    )


def device_edges(g):
    """The graph's directed-edge arrays on device (one upload; callers that
    loop rounds should hold onto the result)."""
    return jnp.asarray(g.src, jnp.int32), jnp.asarray(g.dst, jnp.int32)


def column_component_stats(
    member_cols,
    src_dev,
    dst_dev,
    num_nodes: int,
    col_batch: int = 0,
    as_numpy: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Component labels + fused stats for every column of `member_cols`.

    member_cols: (C, N) bool — host or device array; rows are independent
    induced-subgraph membership masks (one per thresholded column). Columns
    are processed in batches of `col_batch` (auto: EDGE_ELEM_BUDGET / E) so
    the per-batch (CB, E) relaxation arrays stay bounded; the last batch is
    zero-padded to the same CB, so at most one kernel is compiled per
    (graph, batch) shape.

    Returns (labels, comp_size, comp_edges), (C, N) int32 each, as host
    NumPy (as_numpy=True) or device arrays. labels[c, v] == num_nodes
    marks a non-member. Note these are int32 node-indexed arrays — the
    quality pipeline downloads THEM instead of F, and nothing here ever
    reads F itself.
    """
    c_total = int(member_cols.shape[0])
    n = int(num_nodes)
    e = int(src_dev.shape[0])
    if col_batch <= 0:
        col_batch = max(int(EDGE_ELEM_BUDGET // max(e, 1)), 1)
    cb = min(max(col_batch, 1), max(c_total, 1))
    outs: List[tuple] = []
    for lo in range(0, c_total, cb):
        hi = min(lo + cb, c_total)
        batch = jnp.asarray(member_cols[lo:hi], bool)
        if hi - lo < cb:                       # pad: one compile per shape
            batch = jnp.concatenate(
                [batch, jnp.zeros((cb - (hi - lo), n), bool)]
            )
        lab, siz, cnt = _labels_and_stats(src_dev, dst_dev, batch, n)
        outs.append((lab[: hi - lo], siz[: hi - lo], cnt[: hi - lo]))
    if not outs:
        z = np.zeros((0, n), np.int32)
        return z, z.copy(), z.copy()
    labs, sizs, cnts = zip(*outs)
    if as_numpy:
        return (
            np.concatenate([np.asarray(x) for x in labs]),
            np.concatenate([np.asarray(x) for x in sizs]),
            np.concatenate([np.asarray(x) for x in cnts]),
        )
    return (
        jnp.concatenate(labs),
        jnp.concatenate(sizs),
        jnp.concatenate(cnts),
    )


def components_from_labels(
    labels_row: np.ndarray, num_nodes: int, min_size: int = 1
) -> List[np.ndarray]:
    """One column's label vector -> list of sorted member-id arrays
    (components ordered by root id — the device-path collection order; the
    host oracle orders by scipy label, so parity tests compare partitions,
    not list order)."""
    lab = np.asarray(labels_row)
    members = np.flatnonzero(lab < num_nodes)
    if members.size == 0:
        return []
    labm = lab[members]
    order = np.argsort(labm, kind="stable")
    nodes_sorted = members[order]
    lab_sorted = labm[order]
    bounds = np.flatnonzero(np.r_[True, np.diff(lab_sorted) != 0])
    return [
        nodes_sorted[lo:hi]
        for lo, hi in zip(bounds, np.r_[bounds[1:], lab_sorted.size])
        if hi - lo >= min_size
    ]


def graph_components_device(
    mem: np.ndarray, g, src_dev=None, dst_dev=None
) -> List[List[int]]:
    """Drop-in device twin of models.quality._graph_components for ONE
    membership set: same (members -> component lists) contract, component
    order by root id. Mainly the oracle-parity test surface; the quality
    pipeline calls column_component_stats directly to batch all columns."""
    m = np.asarray(mem, np.int64)
    if m.size == 0:
        return []
    if src_dev is None or dst_dev is None:
        src_dev, dst_dev = device_edges(g)
    n = g.num_nodes
    member = np.zeros((1, n), bool)
    member[0, m] = True
    labels, _, _ = column_component_stats(member, src_dev, dst_dev, n)
    return [c.tolist() for c in components_from_labels(labels[0], n)]
