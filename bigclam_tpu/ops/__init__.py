"""Device ops package.

LAZY attribute re-exports (PEP 562): the eager re-export of
objective/linesearch/components here meant that importing ANY ops
submodule — including the numpy-only ones (`ops.seeding`,
`ops.csr_tiles`) — executed `import jax` as a side effect of the package
init. That silently broke the jax-free contract of `cli ingest` (the
default seed bake does `from bigclam_tpu.ops.seeding import ...` — the
submodule is numpy-only, the package init was not), caught by
tests/test_cli_jaxfree.py (ISSUE 10 satellite). Submodule imports now
touch only what they name; `from bigclam_tpu.ops import grad_llh` still
works through the module __getattr__.
"""

_LAZY = {
    "grad_llh": ("bigclam_tpu.ops.objective", "grad_llh"),
    "loglikelihood": ("bigclam_tpu.ops.objective", "loglikelihood"),
    "candidates_pass": ("bigclam_tpu.ops.linesearch", "candidates_pass"),
    "armijo_update": ("bigclam_tpu.ops.linesearch", "armijo_update"),
    "column_component_stats": (
        "bigclam_tpu.ops.components", "column_component_stats",
    ),
    "components_backend": (
        "bigclam_tpu.ops.components", "components_backend",
    ),
    "graph_components_device": (
        "bigclam_tpu.ops.components", "graph_components_device",
    ),
    # fold-in inference (ISSUE 14, jax-touching — lazy like the rest)
    "foldin_pass": ("bigclam_tpu.ops.foldin", "foldin_pass"),
    "make_foldin_fit": ("bigclam_tpu.ops.foldin", "make_foldin_fit"),
    "neighbor_mean_rows": (
        "bigclam_tpu.ops.foldin", "neighbor_mean_rows",
    ),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(mod_name), attr)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
