from bigclam_tpu.ops.objective import grad_llh, loglikelihood
from bigclam_tpu.ops.linesearch import candidates_pass, armijo_update
from bigclam_tpu.ops.components import (
    column_component_stats,
    components_backend,
    graph_components_device,
)

__all__ = [
    "grad_llh",
    "loglikelihood",
    "candidates_pass",
    "armijo_update",
    "column_component_stats",
    "components_backend",
    "graph_components_device",
]
