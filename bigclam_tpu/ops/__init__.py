from bigclam_tpu.ops.objective import grad_llh, loglikelihood
from bigclam_tpu.ops.linesearch import candidates_pass, armijo_update

__all__ = ["grad_llh", "loglikelihood", "candidates_pass", "armijo_update"]
