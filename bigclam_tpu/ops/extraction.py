"""Community extraction: delta-thresholding of F with argmax fallback.

Replaces C18 (SURVEY.md §2; reference Bigclamv2.scala:223-230). The
threshold is delta = sqrt(-log(1 - eps)) with eps = 2E / (N(N-1)) — the
*intended* Yang & Leskovec formula. The reference's eps numerator actually
counted vertices-with-edges, not edges (`collectEdges(...).count`,
Bigclamv2.scala:223 — quirk Q8); we implement the intended formula and
document the deviation in PARITY.md.

Membership semantics exactly as Bigclamv2.scala:226-229: node u belongs to
community c iff F_uc >= delta; if max(F_u) < delta, u is assigned to every
community whose value EQUALS the row max (the reference's `value == Fmax`
indicator — on ties, all tied columns; an all-zero row therefore lands in
every community, which we preserve for parity).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from bigclam_tpu.graph.csr import Graph


def delta_threshold(num_nodes: int, num_edges: int) -> float:
    """delta = sqrt(-log(1 - eps)), eps = 2E/(N(N-1)) (background edge prob)."""
    n = max(num_nodes, 2)
    eps = 2.0 * num_edges / (n * (n - 1.0))
    eps = min(eps, 1.0 - 1e-12)
    return float(np.sqrt(-np.log1p(-eps)))


def membership_mask(F: np.ndarray, delta: float) -> np.ndarray:
    """(N, K) boolean membership per Bigclamv2.scala:226-229."""
    F = np.asarray(F)
    above = F >= delta
    row_max = F.max(axis=1, keepdims=True)
    fallback = (row_max < delta) & (F == row_max)
    return above | fallback


def extract_communities(F: np.ndarray, g: Graph, delta: float | None = None
                        ) -> Dict[int, List[int]]:
    """Invert per-node memberships to community -> sorted member list
    (the reference's flatMap/groupByKey inversion, Bigclamv2.scala:230).
    Empty communities are omitted. Node ids are the graph's raw ids."""
    if delta is None:
        delta = delta_threshold(g.num_nodes, g.num_edges)
    mask = membership_mask(F, delta)
    nodes, comms = np.nonzero(mask)
    # single linear pass: group members by community via sort + split
    return _group_pairs(nodes, comms, g.raw_ids)


def _group_pairs(
    nodes: np.ndarray, comms: np.ndarray, raw_ids: np.ndarray
) -> Dict[int, List[int]]:
    """(node, community) pairs -> {community: sorted raw member ids}."""
    raw = raw_ids[nodes]
    order = np.argsort(comms, kind="stable")
    comms_sorted, raw_sorted = comms[order], raw[order]
    uniq, starts = np.unique(comms_sorted, return_index=True)
    out: Dict[int, List[int]] = {}
    for c, members in zip(uniq, np.split(raw_sorted, starts[1:])):
        out[int(c)] = sorted(members.tolist())
    return out


def extract_communities_device(
    F_dev,
    g: Graph,
    delta: float | None = None,
    num_communities: int | None = None,
    chunk_rows: int = 1 << 16,
    row_to_node=None,
) -> Dict[int, List[int]]:
    """extract_communities for a DEVICE-RESIDENT (possibly sharded) F —
    the C18 path composing with fit_quality_device / fit_state, where F
    never fits (or never visits) the host.

    Thresholding runs on device in row chunks; only the (node, community)
    membership PAIRS come back — a jitted nonzero with a power-of-two
    static size per chunk (one scalar count round trip picks the size, so
    at most log2 distinct compilations), total transfer O(#memberships)
    instead of the O(N*K) float fetch. Semantics identical to
    extract_communities (including the argmax-tie fallback, Q13) —
    pinned by tests/test_extraction_eval.py equality tests.

    `F_dev` may be padded: rows >= g.num_nodes and columns >=
    num_communities (default: all columns) are ignored — the row loop
    never slices past g.num_nodes, so padding rows never reach the kernel.

    Relabeled trainers (balance=True): pass the TRAINER's graph
    (`model.g`) — Graph.permute carries raw_ids, so device row order and
    raw ids already agree. Callers holding only the ORIGINAL graph must
    pass `row_to_node` (device row -> original node index;
    ShardedBigClamModel.internal_row_to_node()); None = identity.
    """
    import functools

    import jax
    import jax.numpy as jnp

    if delta is None:
        delta = delta_threshold(g.num_nodes, g.num_edges)
    n = g.num_nodes
    k = num_communities or int(F_dev.shape[1])
    # bound the on-device mask (and its int32 count) per chunk: a boolean
    # sum over > 2^31 elements would silently wrap
    chunk_rows = max(1, min(chunk_rows, (1 << 27) // max(k, 1)))

    @jax.jit
    def chunk_mask(F_c):
        F_c = F_c[:, :k]               # native dtype: boundary decisions
        above = F_c >= delta           # must match the host path exactly
        row_max = F_c.max(axis=1, keepdims=True)
        fallback = (row_max < delta) & (F_c == row_max)
        mask = above | fallback
        return mask, mask.sum()

    @functools.partial(jax.jit, static_argnums=1)
    def gather_pairs(mask, size):
        return jnp.nonzero(mask, size=size, fill_value=-1)

    all_nodes: list = []
    all_comms: list = []
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        F_c = jax.lax.slice_in_dim(F_dev, lo, hi, axis=0)
        mask, cnt = chunk_mask(F_c)
        cnt = int(cnt)
        if cnt == 0:
            continue
        size = 1 << (cnt - 1).bit_length()     # pow-2 pad: few recompiles
        r, c = gather_pairs(mask, size)
        # multi-controller safe: a pair array derived from a globally
        # sharded F may span non-addressable devices (parallel.multihost)
        from bigclam_tpu.parallel.multihost import fetch_global

        r = fetch_global(r)[:cnt]
        c = fetch_global(c)[:cnt]
        all_nodes.append(r + lo)
        all_comms.append(c)
    if not all_nodes:
        return {}
    nodes = np.concatenate(all_nodes)
    if row_to_node is not None:
        nodes = np.asarray(row_to_node)[nodes]
    return _group_pairs(nodes, np.concatenate(all_comms), g.raw_ids)


def save_communities(path: str, communities: Dict[int, List[int]]) -> None:
    """SNAP cmty format: one community per line, tab-separated member ids
    (the format of com-amazon.all.dedup.cmty.txt, SURVEY.md §0/C22)."""
    with open(path, "w") as f:
        for c in sorted(communities):
            f.write("\t".join(str(u) for u in communities[c]) + "\n")


def load_communities(path: str) -> List[List[int]]:
    """Parse a SNAP cmty file into a list of member-id lists."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            out.append([int(t) for t in line.split()])
    return out
