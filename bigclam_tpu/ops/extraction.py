"""Community extraction: delta-thresholding of F with argmax fallback.

Replaces C18 (SURVEY.md §2; reference Bigclamv2.scala:223-230). The
threshold is delta = sqrt(-log(1 - eps)) with eps = 2E / (N(N-1)) — the
*intended* Yang & Leskovec formula. The reference's eps numerator actually
counted vertices-with-edges, not edges (`collectEdges(...).count`,
Bigclamv2.scala:223 — quirk Q8); we implement the intended formula and
document the deviation in PARITY.md.

Membership semantics exactly as Bigclamv2.scala:226-229: node u belongs to
community c iff F_uc >= delta; if max(F_u) < delta, u is assigned to every
community whose value EQUALS the row max (the reference's `value == Fmax`
indicator — on ties, all tied columns; an all-zero row therefore lands in
every community, which we preserve for parity).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from bigclam_tpu.graph.csr import Graph


def delta_threshold(num_nodes: int, num_edges: int) -> float:
    """delta = sqrt(-log(1 - eps)), eps = 2E/(N(N-1)) (background edge prob)."""
    n = max(num_nodes, 2)
    eps = 2.0 * num_edges / (n * (n - 1.0))
    eps = min(eps, 1.0 - 1e-12)
    return float(np.sqrt(-np.log1p(-eps)))


def membership_mask(F: np.ndarray, delta: float) -> np.ndarray:
    """(N, K) boolean membership per Bigclamv2.scala:226-229."""
    F = np.asarray(F)
    above = F >= delta
    row_max = F.max(axis=1, keepdims=True)
    fallback = (row_max < delta) & (F == row_max)
    return above | fallback


def extract_communities(F: np.ndarray, g: Graph, delta: float | None = None
                        ) -> Dict[int, List[int]]:
    """Invert per-node memberships to community -> sorted member list
    (the reference's flatMap/groupByKey inversion, Bigclamv2.scala:230).
    Empty communities are omitted. Node ids are the graph's raw ids."""
    if delta is None:
        delta = delta_threshold(g.num_nodes, g.num_edges)
    mask = membership_mask(F, delta)
    nodes, comms = np.nonzero(mask)
    raw = g.raw_ids[nodes]
    # single linear pass: group members by community via sort + split
    order = np.argsort(comms, kind="stable")
    comms_sorted, raw_sorted = comms[order], raw[order]
    uniq, starts = np.unique(comms_sorted, return_index=True)
    out: Dict[int, List[int]] = {}
    for c, members in zip(uniq, np.split(raw_sorted, starts[1:])):
        out[int(c)] = sorted(members.tolist())
    return out


def save_communities(path: str, communities: Dict[int, List[int]]) -> None:
    """SNAP cmty format: one community per line, tab-separated member ids
    (the format of com-amazon.all.dedup.cmty.txt, SURVEY.md §0/C22)."""
    with open(path, "w") as f:
        for c in sorted(communities):
            f.write("\t".join(str(u) for u in communities[c]) + "\n")


def load_communities(path: str) -> List[List[int]]:
    """Parse a SNAP cmty file into a list of member-id lists."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            out.append([int(t) for t in line.split()])
    return out
