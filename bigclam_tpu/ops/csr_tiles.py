"""Host-side CSR block-tile builder for the MXU edge kernels.

The XLA edge path (ops.objective / ops.linesearch) gathers BOTH endpoint rows
per directed edge and scatters (E, K) gradient contributions with
`segment_sum` — three memory-bound passes that run far below HBM peak on TPU
(gather/scatter achieve ~15% of streaming bandwidth). The blocked-CSR layout
built here lets the Pallas kernels (ops.pallas_csr) eliminate the src-side
gather and the big scatter entirely:

  * nodes are grouped into blocks of B consecutive rows; each block's CSR
    edge range (already contiguous, src-sorted) is padded to tiles of T edges
  * per tile, `src` is stored block-LOCAL (src - B*block_id), so the kernel
    can expand F rows / scatter contributions with a (B, T) one-hot matmul
    on the MXU against the (B, K) F block resident in VMEM
  * `block_id[tile]` is scalar-prefetched; tiles of one block are contiguous,
    so the kernel accumulates the block's (B, K) output in VMEM and Pallas
    writes it back once per block

Only the dst-side F-row gather remains in XLA (random access is the one part
the hardware actually has to pay for); everything else rides the MXU.

Replaces the hot-loop data layout of C11/C13/C14 (SURVEY.md §2; reference
Bigclamv2.scala:121-146 looped per-node neighbor lists against a broadcast F).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from bigclam_tpu.graph.csr import Graph


class BlockTiles(NamedTuple):
    """Edge tiles aligned to node blocks (all host NumPy; device-put later).

    src_local: (n_tiles, T) int32 — src row index RELATIVE to the tile's block
    dst:       (n_tiles, T) int32 — global dst node index (0 for padding)
    mask:      (n_tiles, T) float32 — 1.0 real edge, 0.0 padding
    block_id:  (n_tiles,)   int32 — owning node block of every tile
    """

    src_local: np.ndarray
    dst: np.ndarray
    mask: np.ndarray
    block_id: np.ndarray
    block_b: int
    tile_t: int
    n_blocks: int

    @property
    def n_tiles(self) -> int:
        return self.src_local.shape[0]

    @property
    def n_pad(self) -> int:
        return self.n_blocks * self.block_b

    @property
    def padded_edges(self) -> int:
        return self.src_local.size - int(self.mask.sum())


def build_block_tiles_arrays(
    src: np.ndarray,
    dst: np.ndarray,
    num_nodes: int,
    block_b: int,
    tile_t: int,
) -> BlockTiles:
    """Tile src-sorted directed-edge arrays by node block (core builder).

    Every node block gets at least one tile (possibly all-padding) so the
    kernels visit — and zero-initialize — every output block. `num_nodes`
    may exceed max(src)+1 (trailing isolated/padding rows get empty tiles).
    """
    assert block_b >= 1 and tile_t >= 1
    n = num_nodes
    n_blocks = max(-(-n // block_b), 1)
    src = np.asarray(src, np.int32)
    dst = np.asarray(dst, np.int32)

    # vectorized layout (no per-block Python work — Friendster-scale graphs
    # have hundreds of thousands of blocks): every block's CSR edge range is
    # laid into its own ntile*T slot span; edges land at
    #   slot = span_start[block] + (edge_index - block_edge_start)
    block_edge_start = np.searchsorted(src, np.arange(n_blocks) * block_b)
    block_edge_end = np.searchsorted(src, (np.arange(n_blocks) + 1) * block_b)
    counts = block_edge_end - block_edge_start
    ntiles = np.maximum(-(-counts // tile_t), 1)
    span_start = np.concatenate([[0], np.cumsum(ntiles * tile_t)])
    total = int(span_start[-1])

    blk_of_edge = src.astype(np.int64) // block_b
    slot = (
        span_start[blk_of_edge]
        + np.arange(src.shape[0], dtype=np.int64)
        - block_edge_start[blk_of_edge]
    )
    src_local = np.zeros(total, np.int32)
    dst_out = np.zeros(total, np.int32)
    mask = np.zeros(total, np.float32)
    src_local[slot] = src - (blk_of_edge * block_b).astype(np.int32)
    dst_out[slot] = dst
    mask[slot] = 1.0

    n_tiles = int(ntiles.sum())
    return BlockTiles(
        src_local=src_local.reshape(n_tiles, tile_t),
        dst=dst_out.reshape(n_tiles, tile_t),
        mask=mask.reshape(n_tiles, tile_t),
        block_id=np.repeat(
            np.arange(n_blocks, dtype=np.int32), ntiles
        ),
        block_b=block_b,
        tile_t=tile_t,
        n_blocks=n_blocks,
    )


def build_block_tiles(g: Graph, block_b: int = 512, tile_t: int = 512) -> BlockTiles:
    """Tile the graph's CSR edge ranges by node block."""
    return build_block_tiles_arrays(g.src, g.dst, g.num_nodes, block_b, tile_t)


def _pad_leading(a: np.ndarray, pad_to: int, fill) -> np.ndarray:
    """Pad a's LEADING axis to pad_to with `fill` — the one padding
    convention every stacked tile layout shares (padding tiles attach
    after the real ones; block_id fills carry the layout's last valid
    block id so the kernels stay in range with mask 0). Every pad site in
    this module goes through here: the store-built and host-global
    layouts must stay byte-identical, so the convention lives in exactly
    one place."""
    pad = pad_to - a.shape[0]
    if pad <= 0:
        return a
    return np.concatenate([a, np.full((pad,) + a.shape[1:], fill, a.dtype)])


def tile_pad_stats(mask: np.ndarray) -> dict:
    """Slot accounting of any tile/edge layout's mask (ISSUE 10): total
    slots, real edges, and the padding fraction — per-step kernel work
    scales with SLOTS, so pad_frac is the fraction of the sweep spent on
    phantom edges. Every trainer build folds this into its `balance`
    telemetry event; the same numbers feed layout_economical's accept
    decision, this just makes the waste observable instead of only
    gateable."""
    slots = int(mask.size)
    real = int(round(float(np.asarray(mask, np.float64).sum())))
    return {
        "slots": slots,
        "real_edges": real,
        "pad_frac": round((slots - real) / max(slots, 1), 4),
    }


def tile_layout_nbytes(
    n_tiles: int, tile_t: int, itemsize: int, per_tile_int32: int = 1
) -> int:
    """Device bytes of a blocked-CSR tile layout with `n_tiles` tiles of
    `tile_t` edge slots each: src_local + dst (int32) + mask (model
    dtype) per slot, plus `per_tile_int32` int32 words per tile (the
    block-id array). The closed-form twin of summing the built arrays'
    nbytes — the jax-free capacity preflight (obs.memory) prices
    un-built CSR layouts with it, and the built layouts agree by
    construction (same slot arithmetic as tile_pad_stats)."""
    slots = int(n_tiles) * int(tile_t)
    return slots * (8 + int(itemsize)) + int(n_tiles) * 4 * int(
        per_tile_int32
    )


def layout_economical(
    slots: int, num_directed_edges: int, n_blocks_total: int, tile_t: int
) -> bool:
    """Shared padding-economy policy for the CSR kernel layouts (single-chip
    AND sharded — keep ONE formula): per-step kernel work scales with slot
    count, so a layout is accepted when padding stays within ~50% of the
    edges plus one tile per block, OR — for small graphs, where absolute
    waste is trivial (toy/dryrun meshes) — within a 4x ratio capped at 1M
    absolute slots."""
    e = max(num_directed_edges, 1)
    return slots <= max(
        1.5 * e + n_blocks_total * tile_t, min(1 << 20, 4 * e)
    )


class GroupedBlockTiles(NamedTuple):
    """Block tiles regrouped into uniform scan windows for large-K runs.

    When the whole-graph dst-row gather exceeds the HBM budget, the step
    scans over groups of NB consecutive blocks, gathering only that group's
    (G, T, K) dst rows per scan iteration. Tile counts are padded to the
    max group (G) so one compiled kernel serves every group.

    src_local: (n_groups, G, T) int32 — src relative to the tile's block
    dst:       (n_groups, G, T) int32 — global dst
    mask:      (n_groups, G, T) float32
    block_id:  (n_groups, G)    int32 — block index LOCAL TO THE GROUP [0, NB)
    """

    src_local: np.ndarray
    dst: np.ndarray
    mask: np.ndarray
    block_id: np.ndarray
    block_b: int
    tile_t: int
    nb: int                  # blocks per group
    n_groups: int

    @property
    def n_pad(self) -> int:
        return self.n_groups * self.nb * self.block_b

    @property
    def slots(self) -> int:
        return self.src_local.size


def group_tiles(bt: BlockTiles, nb: int) -> GroupedBlockTiles:
    """Regroup a flat BlockTiles layout into windows of `nb` whole blocks.

    The block count is padded up to a multiple of nb with phantom empty
    blocks (one all-masked tile each — the kernels must zero-init every
    output block); group tile counts are padded to the global max G with
    all-masked tiles attached to the group's last block (ordering keeps the
    first-tile-of-block accumulation flags correct).
    """
    assert nb >= 1
    n_blocks_pad = -(-bt.n_blocks // nb) * nb
    t = bt.tile_t
    # per-block tile counts (every block has >= 1 by construction)
    counts = np.bincount(bt.block_id, minlength=n_blocks_pad)
    counts[bt.n_blocks:] = 1                    # phantom blocks: 1 empty tile
    starts = np.concatenate([[0], np.cumsum(counts[: bt.n_blocks])])
    n_groups = n_blocks_pad // nb
    g_tiles = counts.reshape(n_groups, nb).sum(axis=1)
    g_max = int(g_tiles.max())

    src = np.zeros((n_groups, g_max, t), np.int32)
    dst = np.zeros((n_groups, g_max, t), np.int32)
    mask = np.zeros((n_groups, g_max, t), np.float32)
    bid = np.full((n_groups, g_max), nb - 1, np.int32)
    for gi in range(n_groups):
        b_lo = gi * nb
        b_hi = min(b_lo + nb, bt.n_blocks)
        cnt = 0
        if b_lo < bt.n_blocks:
            e0, e1 = starts[b_lo], starts[b_hi]
            cnt = e1 - e0
            src[gi, :cnt] = bt.src_local[e0:e1]
            dst[gi, :cnt] = bt.dst[e0:e1]
            mask[gi, :cnt] = bt.mask[e0:e1]
            bid[gi, :cnt] = bt.block_id[e0:e1] - b_lo
        # every phantom block gets one empty tile so its output block is
        # visited (and zero-initialized); remaining padding rides the last
        # block, keeping block_id non-decreasing within the group
        n_phantom = nb - (b_hi - b_lo)
        if n_phantom:
            bid[gi, cnt : cnt + n_phantom] = np.arange(
                b_hi - b_lo, nb, dtype=np.int32
            )
    return GroupedBlockTiles(
        src_local=src, dst=dst, mask=mask, block_id=bid,
        block_b=bt.block_b, tile_t=t, nb=nb, n_groups=n_groups,
    )


class ShardedGroupedTiles(NamedTuple):
    """Per-shard GroupedBlockTiles stacked on a leading shard axis — the
    large-K layout for the SHARDED trainer (uniform (n_groups, G) across
    shards so shard_map runs one SPMD program).

    src_local: (dp, n_groups, G, T) int32 — src relative to the tile's block
    dst:       (dp, n_groups, G, T) int32 — GLOBAL dst (points into the
               all-gathered F)
    mask:      (dp, n_groups, G, T) float32
    block_id:  (dp, n_groups, G)    int32 — block index local to the group
    """

    src_local: np.ndarray
    dst: np.ndarray
    mask: np.ndarray
    block_id: np.ndarray
    block_b: int
    tile_t: int
    nb: int
    n_groups: int
    shard_rows: int          # = n_groups * nb * block_b

    @property
    def slots(self) -> int:
        return self.src_local.size


def shard_grouped_tiles(
    g: Graph, dp: int, n_pad: int, block_b: int, tile_t: int, nb: int
) -> ShardedGroupedTiles:
    """Build each node shard's grouped tile layout (src block-local, dst
    global), padded to uniform group count and tiles-per-group across shards.

    n_pad must be a multiple of dp * nb * block_b so every shard has whole
    groups and the same n_groups.
    """
    assert n_pad % dp == 0, (n_pad, dp)
    shard_rows = n_pad // dp
    assert shard_rows % (nb * block_b) == 0, (shard_rows, nb, block_b)
    bounds = np.searchsorted(g.src, np.arange(0, n_pad + shard_rows, shard_rows))
    parts = []
    for i in range(dp):
        lo, hi = bounds[i], bounds[i + 1]
        bt = build_block_tiles_arrays(
            g.src[lo:hi] - i * shard_rows,
            g.dst[lo:hi],
            shard_rows,
            block_b,
            tile_t,
        )
        parts.append(group_tiles(bt, nb))
    n_groups = parts[0].n_groups
    assert all(p.n_groups == n_groups for p in parts)
    g_max = max(p.src_local.shape[1] for p in parts)

    def pad_stack(field: str, fill):
        outs = []
        for p in parts:
            a = getattr(p, field)
            pad = g_max - a.shape[1]
            if pad:
                shape = (a.shape[0], pad) + a.shape[2:]
                filler = np.full(shape, fill, a.dtype)
                a = np.concatenate([a, filler], axis=1)
            outs.append(a)
        return np.stack(outs)

    return ShardedGroupedTiles(
        src_local=pad_stack("src_local", 0),
        dst=pad_stack("dst", 0),
        mask=pad_stack("mask", 0.0),
        # padding tiles attach to the group's last block (valid id, zero mask)
        block_id=pad_stack("block_id", nb - 1),
        block_b=block_b,
        tile_t=tile_t,
        nb=nb,
        n_groups=n_groups,
        shard_rows=shard_rows,
    )


def _local_shard_edge_slices(shard, dp: int, n_pad: int):
    """Yield (global_shard_id, src_shard_local, dst_global) per store shard
    this host holds — the shared edge-slicing of every store-native builder.

    `shard` is a graph/store.HostShard (duck-typed: lo/indptr/indices/
    num_nodes/shard_ids): its indptr is rebased at `lo` and its indices
    keep GLOBAL dst ids, so slicing shard s's rows out needs only the
    manifest node ranges — no global CSR anywhere.
    """
    shard_rows = n_pad // dp
    n = shard.num_nodes
    deg = np.diff(shard.indptr)
    for s in shard.shard_ids:
        glo = min(s * shard_rows, n)
        ghi = min((s + 1) * shard_rows, n)
        e0 = int(shard.indptr[glo - shard.lo])
        e1 = int(shard.indptr[ghi - shard.lo])
        src_local = (
            np.repeat(
                np.arange(glo, ghi, dtype=np.int64),
                deg[glo - shard.lo : ghi - shard.lo],
            )
            - s * shard_rows
        ).astype(np.int32)
        yield s, src_local, np.asarray(shard.indices[e0:e1], np.int32)


def local_block_tile_parts(
    shard, dp: int, n_pad: int, block_b: int, tile_t: int
) -> list:
    """Per-local-shard BlockTiles built from a HostShard — the store-native
    first stage of shard_block_tiles (src rebased shard-local, dst GLOBAL).
    The caller pads tile counts to the cross-host maximum
    (stack_block_tile_parts) so shard_map stays SPMD."""
    assert n_pad % dp == 0 and (n_pad // dp) % block_b == 0, (
        n_pad, dp, block_b,
    )
    shard_rows = n_pad // dp
    return [
        build_block_tiles_arrays(src, dst, shard_rows, block_b, tile_t)
        for _, src, dst in _local_shard_edge_slices(shard, dp, n_pad)
    ]


def stack_block_tile_parts(parts: list, pad_tiles: int) -> "ShardedBlockTiles":
    """Pad local BlockTiles to `pad_tiles` (the GLOBAL max tile count — from
    the manifest-agreed geometry or a tiny cross-host max exchange) and
    stack on a leading local-shard axis. Identical to the matching rows of
    shard_block_tiles when pad_tiles is the true global max."""
    local_max = max(p.n_tiles for p in parts)
    if pad_tiles < local_max:
        raise ValueError(
            f"pad_tiles={pad_tiles} below this host's tile count "
            f"{local_max} — the cross-host max exchange is broken"
        )
    n_blocks = parts[0].n_blocks

    def pad_stack(field: str, fill):
        return np.stack(
            [_pad_leading(getattr(p, field), pad_tiles, fill) for p in parts]
        )

    return ShardedBlockTiles(
        src_local=pad_stack("src_local", 0),
        dst=pad_stack("dst", 0),
        mask=pad_stack("mask", 0.0),
        block_id=pad_stack("block_id", n_blocks - 1),
        block_b=parts[0].block_b,
        tile_t=parts[0].tile_t,
        n_blocks=n_blocks,
        shard_rows=n_blocks * parts[0].block_b,
    )


def shard_block_tiles_local(
    shard, dp: int, n_pad: int, block_b: int, tile_t: int,
    pad_tiles: int = 0,
) -> "ShardedBlockTiles":
    """This host's rows of the sharded block-tile layout, built from a
    per-host graph-store slice — the out-of-core twin of shard_block_tiles:
    no global CSR exists anywhere. pad_tiles=0 pads to the LOCAL max
    (exact on single-host loads, where local == global)."""
    parts = local_block_tile_parts(shard, dp, n_pad, block_b, tile_t)
    return stack_block_tile_parts(
        parts, pad_tiles or max(p.n_tiles for p in parts)
    )


def local_ring_tile_parts(
    shard, dp: int, n_pad: int, block_b: int, tile_t: int
) -> list:
    """Per-(local shard, phase) BlockTiles from a HostShard — the
    store-native first stage of ring_block_tiles. dst is stored LOCAL to
    the rotating shard resident in that phase (dst - ((i + r) % dp) *
    shard_rows): the translation needs only the manifest node ranges.
    Returns a list of per-local-shard lists of dp phase parts."""
    assert n_pad % dp == 0 and (n_pad // dp) % block_b == 0, (
        n_pad, dp, block_b,
    )
    shard_rows = n_pad // dp
    out = []
    for i, src_local, dst in _local_shard_edge_slices(shard, dp, n_pad):
        phase = ((dst.astype(np.int64) // shard_rows) - i) % dp
        # CSR order within each bucket (matches ring_block_tiles' global
        # lexsort, which is stable within one (shard, phase) run)
        order = np.lexsort((np.arange(dst.size), phase))
        s_sorted = src_local[order]
        d_sorted = dst[order].astype(np.int64)
        ph = phase[order]
        bounds = np.searchsorted(ph, np.arange(dp + 1))
        phase_parts = []
        for r in range(dp):
            lo, hi = bounds[r], bounds[r + 1]
            phase_parts.append(
                build_block_tiles_arrays(
                    s_sorted[lo:hi],
                    d_sorted[lo:hi] - ((i + r) % dp) * shard_rows,
                    shard_rows,
                    block_b,
                    tile_t,
                )
            )
        out.append(phase_parts)
    return out


def stack_ring_tile_parts(parts: list, pad_tiles: int) -> "RingBlockTiles":
    """Pad per-(local shard, phase) BlockTiles to the global max tile count
    and stack into (n_local, dp, n_tiles, ...) arrays — this host's rows of
    ring_block_tiles."""
    flat = [p for phase_parts in parts for p in phase_parts]
    local_max = max(p.n_tiles for p in flat)
    if pad_tiles < local_max:
        raise ValueError(
            f"pad_tiles={pad_tiles} below this host's ring tile count "
            f"{local_max} — the cross-host max exchange is broken"
        )
    n_blocks = flat[0].n_blocks
    dpp = len(parts[0])

    def pad_stack(field: str, fill):
        stacked = np.stack(
            [_pad_leading(getattr(p, field), pad_tiles, fill) for p in flat]
        )
        return stacked.reshape((len(parts), dpp) + stacked.shape[1:])

    return RingBlockTiles(
        src_local=pad_stack("src_local", 0),
        dst_local=pad_stack("dst", 0),
        mask=pad_stack("mask", 0.0),
        block_id=pad_stack("block_id", n_blocks - 1),
        block_b=flat[0].block_b,
        tile_t=flat[0].tile_t,
        n_blocks=n_blocks,
        shard_rows=n_blocks * flat[0].block_b,
    )


def ring_block_tiles_local(
    shard, dp: int, n_pad: int, block_b: int, tile_t: int,
    pad_tiles: int = 0,
) -> "RingBlockTiles":
    """This host's rows of the ring (shard, phase) tile layout, built from
    a per-host graph-store slice. pad_tiles=0 pads to the LOCAL max (exact
    on single-host loads)."""
    parts = local_ring_tile_parts(shard, dp, n_pad, block_b, tile_t)
    return stack_ring_tile_parts(
        parts,
        pad_tiles
        or max(p.n_tiles for phase_parts in parts for p in phase_parts),
    )


class RingBlockTiles(NamedTuple):
    """Per-(shard, ring-phase) block-tile layouts for the ring-pass CSR
    schedule (parallel/ring.py): in phase r, shard i runs the kernels over
    the bucket of its edges whose destinations live in shard (i + r) % dp,
    against the resident rotating F shard — so dst is stored LOCAL to that
    shard. Uniform n_tiles across (shard, phase) keeps shard_map SPMD.

    src_local: (dp, dp, n_tiles, T) int32 — src relative to the tile's block
    dst_local: (dp, dp, n_tiles, T) int32 — dst relative to the ROTATING
               shard resident in that phase
    mask:      (dp, dp, n_tiles, T) float32
    block_id:  (dp, dp, n_tiles)    int32 — shard-local block index
    """

    src_local: np.ndarray
    dst_local: np.ndarray
    mask: np.ndarray
    block_id: np.ndarray
    block_b: int
    tile_t: int
    n_blocks: int            # per shard
    shard_rows: int

    @property
    def slots(self) -> int:
        return self.src_local.size


def ring_block_tiles(
    g: Graph, dp: int, n_pad: int, block_b: int, tile_t: int
) -> RingBlockTiles:
    """Build the (shard, phase)-bucketed block-tile layouts.

    Bucket membership matches parallel.ring.ring_shard_edges (phase =
    (dst_shard - src_shard) mod dp); within a bucket, edges keep CSR
    (src-sorted) order so tiles of one block stay contiguous. All dp*dp
    layouts are padded to the max tile count. n_pad must be a multiple of
    dp * block_b.
    """
    assert n_pad % dp == 0 and (n_pad // dp) % block_b == 0, (
        n_pad, dp, block_b,
    )
    shard_rows = n_pad // dp
    src_shard = g.src // shard_rows
    dst_shard = g.dst // shard_rows
    phase = (dst_shard - src_shard) % dp
    order = np.lexsort((np.arange(g.src.size), phase, src_shard))
    s_sorted = g.src[order]
    d_sorted = g.dst[order]
    ss = src_shard[order]
    ph = phase[order]
    if ss.size:
        run_starts = np.flatnonzero(
            np.r_[True, (ss[1:] != ss[:-1]) | (ph[1:] != ph[:-1])]
        )
        run_ends = np.r_[run_starts[1:], ss.size]
        runs = {
            (int(ss[lo]), int(ph[lo])): (lo, hi)
            for lo, hi in zip(run_starts, run_ends)
        }
    else:
        runs = {}                # edgeless graph: all buckets empty
    parts = []
    for i in range(dp):
        for r in range(dp):
            lo, hi = runs.get((i, r), (0, 0))
            parts.append(
                build_block_tiles_arrays(
                    s_sorted[lo:hi] - i * shard_rows,
                    d_sorted[lo:hi] - ((i + r) % dp) * shard_rows,
                    shard_rows,
                    block_b,
                    tile_t,
                )
            )
    n_tiles = max(p.n_tiles for p in parts)
    n_blocks = parts[0].n_blocks

    def pad_stack(field: str, fill):
        outs = [_pad_leading(getattr(p, field), n_tiles, fill) for p in parts]
        return np.stack(outs).reshape((dp, dp) + outs[0].shape)

    return RingBlockTiles(
        src_local=pad_stack("src_local", 0),
        dst_local=pad_stack("dst", 0),
        mask=pad_stack("mask", 0.0),
        # padding tiles attach to the last block (valid id, zero mask)
        block_id=pad_stack("block_id", n_blocks - 1),
        block_b=block_b,
        tile_t=tile_t,
        n_blocks=n_blocks,
        shard_rows=shard_rows,
    )


class ShardedBlockTiles(NamedTuple):
    """Per-shard tile layouts, stacked on a leading shard axis (equal tile
    counts across shards — shard_map runs one SPMD program).

    src_local: (dp, n_tiles, T) int32 — src relative to the TILE'S BLOCK,
               blocks counted within the shard
    dst:       (dp, n_tiles, T) int32 — GLOBAL dst (gathered from the
               all-gathered F)
    mask:      (dp, n_tiles, T) float32
    block_id:  (dp, n_tiles)    int32 — shard-local block index
    """

    src_local: np.ndarray
    dst: np.ndarray
    mask: np.ndarray
    block_id: np.ndarray
    block_b: int
    tile_t: int
    n_blocks: int            # per shard
    shard_rows: int

    @property
    def n_tiles(self) -> int:
        return self.src_local.shape[1]


def shard_block_tiles(
    g: Graph, dp: int, n_pad: int, block_b: int, tile_t: int
) -> ShardedBlockTiles:
    """Build each node shard's block-tile layout (src rebased shard-local,
    dst global), padded with all-masked tiles to the max shard tile count.

    n_pad must be a multiple of dp * block_b.
    """
    assert n_pad % dp == 0 and (n_pad // dp) % block_b == 0, (n_pad, dp, block_b)
    shard_rows = n_pad // dp
    bounds = np.searchsorted(g.src, np.arange(0, n_pad + shard_rows, shard_rows))
    parts = []
    for i in range(dp):
        lo, hi = bounds[i], bounds[i + 1]
        parts.append(
            build_block_tiles_arrays(
                g.src[lo:hi] - i * shard_rows,
                g.dst[lo:hi],
                shard_rows,
                block_b,
                tile_t,
            )
        )
    n_tiles = max(p.n_tiles for p in parts)
    n_blocks = parts[0].n_blocks

    def pad_stack(field: str, fill):
        return np.stack(
            [_pad_leading(getattr(p, field), n_tiles, fill) for p in parts]
        )

    return ShardedBlockTiles(
        src_local=pad_stack("src_local", 0),
        dst=pad_stack("dst", 0),
        mask=pad_stack("mask", 0.0),
        # padding tiles attach to the last block (valid id, zero mask)
        block_id=pad_stack("block_id", n_blocks - 1),
        block_b=block_b,
        tile_t=tile_t,
        n_blocks=n_blocks,
        shard_rows=shard_rows,
    )
