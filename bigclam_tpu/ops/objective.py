"""Fused LLH + gradient kernels: the reference's hot inner loop, edge-parallel.

Replaces C11/C13 (SURVEY.md §2; reference Bigclamv2.scala:121-133,187-200):
the reference's PASS-1 looped each node's neighbor list on an executor,
computing F_u.F_v dots against a driver-broadcast copy of all of F. Here the
same math is one fused edge-parallel pass on device: gather F rows at both
endpoints of every directed edge, dot on the MXU-friendly K axis, clipped
log-prob terms, and `segment_sum` back to nodes. Edges are processed in
static-shape chunks (lax.scan) so the (chunk, K) gather working set stays
bounded in HBM regardless of graph size.

Math (SURVEY.md §2.1, normative):
  ell(u)  = sum_{v in N(u)} [ log(1 - clip(exp(-F_u.F_v), min_p, max_p)) + F_u.F_v ]
            - F_u . sumF + F_u . F_u
  grad_u  = sum_{v in N(u)} F_v / (1 - clip(exp(-F_u.F_v))) - sumF + F_u

Padding conventions (established by models.bigclam.prepare_graph):
  * edge padding: src = n_pad - 1, dst = 0, mask = 0 (keeps src sorted so
    segment_sum can use indices_are_sorted=True; masked terms add 0.0)
  * node padding: all-zero F rows are mathematically inert (their LLH terms
    are 0 and Armijo never accepts a step for them, since grad = -sumF <= 0
    clips to the zero row again) — verified by tests/test_jax_core.py
  * K padding: all-zero columns are preserved by the update and contribute 0
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigclam_tpu.config import BigClamConfig


class EdgeChunks(NamedTuple):
    """Static-shape directed-edge arrays, chunked: each (num_chunks, chunk)."""

    src: jax.Array   # int32
    dst: jax.Array   # int32
    mask: jax.Array  # float (1.0 = real edge, 0.0 = padding)


def edge_terms(x: jax.Array, cfg: BigClamConfig) -> Tuple[jax.Array, jax.Array]:
    """Per-edge clipped probability p = clip(exp(-x)) and LLH term log(1-p)+x."""
    p = jnp.clip(jnp.exp(-x), cfg.min_p, cfg.max_p)
    return p, jnp.log1p(-p) + x


def node_tail(F: jax.Array, sumF: jax.Array) -> jax.Array:
    """The folded non-edge terms per node: -F_u.sumF + F_u.F_u (SURVEY.md §2.1)."""
    return -(F @ sumF) + jnp.einsum("nk,nk->n", F, F)


def grad_llh(
    F: jax.Array, sumF: jax.Array, edges: EdgeChunks, cfg: BigClamConfig
) -> Tuple[jax.Array, jax.Array]:
    """Fused per-node gradient + per-node LLH (one edge sweep).

    Returns (grad (N,K), node_llh (N,)); global LLH = node_llh.sum().
    """
    n = F.shape[0]
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype

    def body(carry, sdm):
        nbr_llh, nbr_grad = carry
        s, d, m = sdm
        fd = F[d]
        x = jnp.einsum("ek,ek->e", F[s], fd)
        p, ell = edge_terms(x, cfg)
        coeff = m / (1.0 - p)              # folds the +sum_N F_v term
        nbr_llh = nbr_llh + jax.ops.segment_sum(
            (ell * m).astype(adt), s, num_segments=n, indices_are_sorted=True
        )
        nbr_grad = nbr_grad + jax.ops.segment_sum(
            fd * coeff[:, None], s, num_segments=n, indices_are_sorted=True
        )
        return (nbr_llh, nbr_grad), None

    init = (jnp.zeros(n, adt), jnp.zeros_like(F))
    (nbr_llh, nbr_grad), _ = lax.scan(body, init, edges)
    grad = nbr_grad - sumF[None, :] + F
    node_llh = nbr_llh + node_tail(F, sumF).astype(adt)
    return grad, node_llh


def loglikelihood(
    F: jax.Array, sumF: jax.Array, edges: EdgeChunks, cfg: BigClamConfig
) -> jax.Array:
    """Global LLH only (Bigclamv2.scala:187-200), one edge sweep."""
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype

    def body(acc, sdm):
        s, d, m = sdm
        x = jnp.einsum("ek,ek->e", F[s], F[d])
        _, ell = edge_terms(x, cfg)
        return acc + (ell * m).sum(dtype=adt), None

    acc, _ = lax.scan(body, jnp.zeros((), adt), edges)
    return acc + node_tail(F, sumF).sum(dtype=adt)
