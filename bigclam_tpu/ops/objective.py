"""Fused LLH + gradient kernels: the reference's hot inner loop, edge-parallel.

Replaces C11/C13 (SURVEY.md §2; reference Bigclamv2.scala:121-133,187-200):
the reference's PASS-1 looped each node's neighbor list on an executor,
computing F_u.F_v dots against a driver-broadcast copy of all of F. Here the
same math is one fused edge-parallel pass on device: gather F rows at both
endpoints of every directed edge, dot on the MXU-friendly K axis, clipped
log-prob terms, and `segment_sum` back to nodes. Edges are processed in
static-shape chunks (lax.scan) so the (chunk, K) gather working set stays
bounded in HBM regardless of graph size.

Math (SURVEY.md §2.1, normative):
  ell(u)  = sum_{v in N(u)} [ log(1 - clip(exp(-F_u.F_v), min_p, max_p)) + F_u.F_v ]
            - F_u . sumF + F_u . F_u
  grad_u  = sum_{v in N(u)} F_v / (1 - clip(exp(-F_u.F_v))) - sumF + F_u

Padding conventions (established by models.bigclam.prepare_graph):
  * edge padding: src = n_pad - 1, dst = 0, mask = 0 (keeps src sorted so
    segment_sum can use indices_are_sorted=True; masked terms add 0.0)
  * node padding: all-zero F rows are mathematically inert (their LLH terms
    are 0 and Armijo never accepts a step for them, since grad = -sumF <= 0
    clips to the zero row again) — verified by tests/test_jax_core.py
  * K padding: all-zero columns are preserved by the update and contribute 0
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigclam_tpu.config import BigClamConfig


class EdgeChunks(NamedTuple):
    """Static-shape directed-edge arrays, chunked: each (num_chunks, chunk)."""

    src: jax.Array   # int32
    dst: jax.Array   # int32
    mask: jax.Array  # float (1.0 = real edge, 0.0 = padding)


def edge_terms(x: jax.Array, cfg: BigClamConfig) -> Tuple[jax.Array, jax.Array]:
    """Per-edge clipped survival 1-p (p = exp(-x)) and LLH term log(1-p)+x.

    1-p is formed DIRECTLY as -expm1(-x), then clipped: p in [min_p, max_p]
    <=> 1-p in [1-max_p, 1-min_p] (bounds computed on the host in f64).
    The naive 1 - clip(exp(-x)) loses all relative precision near p=1 —
    in f32, exp(-x) rounds to 1.0 once x < 2^-24, collapsing 1-p to 0 and
    capping the gradient's 1/(1-p) neighbor amplification at ~1.7e7; with
    expm1 the small-x branch is exact to f32 eps RELATIVE error down to
    denormals, so the MAX_P_ relaxation (models/quality.py) scales to the
    f64 representability floor of max_p itself (1 - ~1e-15) instead of the
    old f32 ceiling of 1e6. Identical math in every path: XLA edge sweep,
    both Pallas kernel families, and the ring/sharded phase bodies all
    call this function.
    Returns (one_minus_p, ell); gradient coefficient = mask / one_minus_p.
    """
    omp = jnp.clip(-jnp.expm1(-x), 1.0 - cfg.max_p, 1.0 - cfg.min_p)
    return omp, jnp.log(omp) + x


def node_tail(F: jax.Array, sumF: jax.Array) -> jax.Array:
    """The folded non-edge terms per node: -F_u.sumF + F_u.F_u (SURVEY.md §2.1)."""
    return -(F @ sumF) + jnp.einsum("nk,nk->n", F, F)


def grad_llh(
    F: jax.Array, sumF: jax.Array, edges: EdgeChunks, cfg: BigClamConfig
) -> Tuple[jax.Array, jax.Array]:
    """Fused per-node gradient + per-node LLH (one edge sweep).

    Returns (grad (N,K), node_llh (N,)); global LLH = node_llh.sum().
    """
    n = F.shape[0]
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype

    def body(carry, sdm):
        nbr_llh, nbr_grad = carry
        s, d, m = sdm
        fd = F[d]
        x = jnp.einsum("ek,ek->e", F[s], fd)
        omp, ell = edge_terms(x, cfg)
        coeff = m / omp                    # folds the +sum_N F_v term
        nbr_llh = nbr_llh + jax.ops.segment_sum(
            (ell * m).astype(adt), s, num_segments=n, indices_are_sorted=True
        )
        nbr_grad = nbr_grad + jax.ops.segment_sum(
            fd * coeff[:, None], s, num_segments=n, indices_are_sorted=True
        )
        return (nbr_llh, nbr_grad), None

    init = (jnp.zeros(n, adt), jnp.zeros_like(F))
    (nbr_llh, nbr_grad), _ = lax.scan(body, init, edges)
    grad = nbr_grad - sumF[None, :] + F
    node_llh = nbr_llh + node_tail(F, sumF).astype(adt)
    return grad, node_llh


def loglikelihood(
    F: jax.Array, sumF: jax.Array, edges: EdgeChunks, cfg: BigClamConfig
) -> jax.Array:
    """Global LLH only (Bigclamv2.scala:187-200), one edge sweep."""
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else F.dtype

    def body(acc, sdm):
        s, d, m = sdm
        x = jnp.einsum("ek,ek->e", F[s], F[d])
        _, ell = edge_terms(x, cfg)
        return acc + (ell * m).sum(dtype=adt), None

    acc, _ = lax.scan(body, jnp.zeros((), adt), edges)
    return acc + node_tail(F, sumF).sum(dtype=adt)
