"""Sparse top-M membership representation: the last O(N*K) wall.

Dense F is ~6.5 TB at the Friendster target (N=65M, K=25K) — no HBM
budget survives it, which is why the reference's own v3 went sparse
(PAPER.md §0, bigclamv3-7.scala at K=8385). Real memberships are
power-law sparse, so each node keeps only its top-M communities:

  ids  (N_pad, M) int32 — member community ids, sorted ascending per row,
                          empty slots hold the sentinel K_pad (sorts last)
  w    (N_pad, M) float — member weights, 0.0 in sentinel slots

HBM for the affiliation state and bytes-per-edge both scale with M, not
K — K becomes a pure capacity knob. The kernels here mirror
ops.objective / ops.linesearch exactly, restricted to the support:

  * edge dot F_u.F_v  = merge of the two SORTED member lists (a vmapped
    searchsorted per edge — O(M log M), no (M, M) compare matrix)
  * gradient          = gather of neighbor weights at u's member ids +
    segment_sum over member slots (slot space, (N, M))
  * ||grad||^2        = slot terms + the closed-form off-support
    correction sum_{c not in S} sumF[c]^2 (exact whenever off-support
    columns carry no neighbor mass — guaranteed right after a support
    update, see below), so the Armijo acceptance rule matches the dense
    path's semantics instead of silently relaxing it
  * support update    = every cfg.support_every iterations: admit
    candidate communities from neighbor member lists (scored by neighbor
    weight mass), keep top-M by weight+mass. Sort-based over the
    candidate ENTRIES of each node block (own slots + neighbor slots,
    (block_b + eb) * M of them, bounded by cfg.sparse_score_block) —
    O((N + E) * M log) total with no K-sized axis, so the support pass
    stays flat in K like everything else here.

PARITY: with M >= K and support_every=1 the restricted dynamics equal
the dense dynamics: a community with zero neighbor mass has
grad = -sumF[c] <= 0 at F_u[c] = 0, which the box clip pins at zero — so
admission-from-neighbor-lists loses nothing, and admission runs BEFORE
the gradient pass so same-step dense growth is captured. Pinned by
tests/test_sparse.py against the dense trajectory.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.ops.linesearch import accept_stats
from bigclam_tpu.ops.objective import EdgeChunks, edge_terms


class SparseTrainState(NamedTuple):
    """TrainState twin for the sparse path. `F` holds the (N_pad, M)
    member WEIGHTS (named F so the shared fit-loop machinery —
    nan-injection faults, non-finite diagnostics, rollback snapshots —
    keeps working unchanged); `ids` is the second array of the two-array
    sparse state the checkpoint sidecar crc-stamps."""

    F: jax.Array                 # (N_pad, M) member weights
    ids: jax.Array               # (N_pad, M) int32 sorted member ids
    sumF: jax.Array              # (K_pad,) dense column sums (O(K) only)
    llh: jax.Array               # scalar: LLH of the PREVIOUS state
    it: jax.Array
    accept_hist: Optional[jax.Array] = None
    # sparse-collective observability (sharded trainer only; zeros on a
    # single chip): ids exchanged by the last sparse allreduce (max over
    # shards) and whether that step fell back to the dense psum
    comm_ids: Optional[jax.Array] = None
    comm_dense: Optional[jax.Array] = None
    # (ops.diagnostics.HEALTH_LEN,) float32 device health pack (ISSUE 8;
    # support churn + cap occupancy ride the sparse slots); None with
    # health off — see models.bigclam.TrainState.health
    health: Optional[jax.Array] = None


class SupportBlocks(NamedTuple):
    """Per-node-block edge layout for the support-update scatter: block b
    owns src rows [b*block_b, (b+1)*block_b), src stored block-local.
    Shapes (n_blocks, eb) host-padded to the max per-block edge count
    (mask 0 on padding, dst 0, src_local block_b - 1)."""

    src_local: jax.Array         # int32
    dst: jax.Array               # int32 (global)
    mask: jax.Array              # float
    block_b: int


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def pick_block_b(budget_elems: int, n: int, m: int, avg_deg: float) -> int:
    """Support-update block size: the sort kernel works on
    ~(block_b * (1 + avg_deg)) * M candidate entries per block, so size
    block_b to keep that near the element budget — K plays no part.
    Clamped to [8, 1024] and rounded to 8."""
    per_row = max(int(m) * (1.0 + max(avg_deg, 0.0)), 1.0)
    b = max(int(budget_elems / per_row), 8)
    b = min(b, 1024, _round_up(max(n, 8), 8))
    return _round_up(b, 8) if b % 8 else b


def from_dense(
    F: np.ndarray, m: int, k_pad: int, n_pad: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Sparsify a dense (N, K) init: per-row top-m entries by weight
    (ties to the LOWEST community id via the stable sort), ids sorted
    ascending with sentinel k_pad padding. Returns (ids, w, truncated) —
    `truncated` counts positive entries dropped because a row held more
    than m (0 whenever m >= max row support; the M >= K parity regime)."""
    F = np.asarray(F)
    n, k = F.shape
    assert k <= k_pad, (k, k_pad)
    order = np.argsort(-F, axis=1, kind="stable")[:, :m]
    vals = np.take_along_axis(F, order, axis=1)
    keep = vals > 0
    truncated = int((F > 0).sum() - keep.sum())
    sel_ids = np.where(keep, order, k_pad)
    srt = np.argsort(sel_ids, axis=1, kind="stable")
    ids = np.full((n_pad, m), k_pad, dtype=np.int32)
    w = np.zeros((n_pad, m), dtype=F.dtype)
    ids[:n] = np.take_along_axis(sel_ids, srt, axis=1)
    w[:n] = np.take_along_axis(np.where(keep, vals, 0.0), srt, axis=1)
    return ids, w, truncated


def to_dense(
    ids: np.ndarray, w: np.ndarray, n: int, k: int
) -> np.ndarray:
    """Densify the live (n, k) block of a sparse state (host side; the
    extraction/eval pipelines consume dense F)."""
    ids = np.asarray(ids)[:n]
    w = np.asarray(w)[:n]
    out = np.zeros((n, k), dtype=w.dtype)
    valid = ids < k
    rows = np.broadcast_to(np.arange(n)[:, None], ids.shape)
    np.add.at(out, (rows[valid], ids[valid]), w[valid])
    return out


def support_blocks_host(
    g, n_pad: int, block_b: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host arrays of the per-block edge layout: (src_local, dst, mask),
    each (n_blocks, eb) with eb the graph-wide max per-block edge count
    (uniform so the sharded trainer can reshape to (dp, blocks/dp, eb)).
    CSR order means each block's edges are one contiguous src slice."""
    assert n_pad % block_b == 0, (n_pad, block_b)
    n_blocks = n_pad // block_b
    src, dst = g.src, g.dst
    bounds = np.searchsorted(src, np.arange(0, n_pad + block_b, block_b))
    counts = np.diff(bounds)
    eb = _round_up(max(int(counts.max()) if counts.size else 1, 1), 8)
    sl = np.full((n_blocks, eb), block_b - 1, dtype=np.int32)
    dd = np.zeros((n_blocks, eb), dtype=np.int32)
    mm = np.zeros((n_blocks, eb), dtype=np.float32)
    for b in range(n_blocks):
        e0, e1 = int(bounds[b]), int(bounds[b + 1])
        cnt = e1 - e0
        sl[b, :cnt] = src[e0:e1] - b * block_b
        dd[b, :cnt] = dst[e0:e1]
        mm[b, :cnt] = 1.0
    return sl, dd, mm


def build_support_blocks(
    g, n_pad: int, block_b: int, dtype=np.float32
) -> SupportBlocks:
    """Device-resident SupportBlocks over the whole graph (single-chip)."""
    sl, dd, mm = support_blocks_host(g, n_pad, block_b)
    return SupportBlocks(
        src_local=jnp.asarray(sl),
        dst=jnp.asarray(dd),
        mask=jnp.asarray(mm, dtype),
        block_b=block_b,
    )


def member_lookup(
    iv: jax.Array, wv: jax.Array, iu: jax.Array, k_pad: int
) -> jax.Array:
    """For each (edge, slot): the neighbor's weight in community iu, or
    0.0 when the neighbor is not a member. iv/wv/iu are (E, M) with iv
    sorted ascending per row (sentinels sort last and never match)."""
    m = iv.shape[-1]
    pos = jax.vmap(jnp.searchsorted)(iv, iu)
    pos = jnp.minimum(pos, m - 1)
    hit = jnp.take_along_axis(iv, pos, axis=-1) == iu
    hit = hit & (iu < k_pad)
    return jnp.where(hit, jnp.take_along_axis(wv, pos, axis=-1), 0.0)


# --- Pallas member-merge kernel (ISSUE 13) --------------------------------
#
# The searchsorted merge above is gather-bound XLA: a vmapped binary
# search per (edge, slot) plus two take_along_axis gathers — per-element
# random access the TPU pays latency for. The Pallas kernel below merges
# an edge BLOCK's member lists with M slot-compare sweeps over VMEM-
# resident tiles (M is small — 64 by default — so the M^2 compares per
# edge are dense VPU work instead of E*M scattered loads). EXACT against
# member_lookup whenever member ids are unique per row (they are by
# construction: from_dense and support_update both dedup): the compare
# mask hits at most one slot, and summing one weight plus zeros is the
# weight bit-for-bit. Pinned by tests/test_fused.py incl. sentinel
# padding and M < K truncation.

_MERGE_BLOCK_E = 256      # edge rows per kernel block


def merge_pallas_want(cfg: BigClamConfig) -> bool:
    """Should the Pallas member-merge engage? (auto: TPU backends, or
    interpret mode for the CPU-gated tests — mirrors csr_want_reason)."""
    want = cfg.sparse_pallas_merge
    if want is None:
        want = jax.default_backend() == "tpu" or cfg.pallas_interpret
    return bool(want)


def _merge_kernel(iv_ref, wv_ref, iu_ref, out_ref, *, m, k_pad):
    iv = iv_ref[:]                       # (eb, M) neighbor member ids
    wv = wv_ref[:]                       # (eb, M) neighbor weights
    iu = iu_ref[:]                       # (eb, M) own member ids
    valid = iu < k_pad                   # sentinel own slots never match
    acc = jnp.zeros_like(wv)
    for p in range(m):                   # M slot-compare sweeps, unrolled
        hit = jnp.logical_and(iv[:, p : p + 1] == iu, valid)
        acc = acc + jnp.where(hit, wv[:, p : p + 1], 0.0)
    out_ref[:] = acc


def member_lookup_pallas(
    iv: jax.Array,
    wv: jax.Array,
    iu: jax.Array,
    k_pad: int,
    interpret: bool = False,
) -> jax.Array:
    """member_lookup as a Pallas merge kernel over edge blocks (see the
    section comment). Same (E, M) -> (E, M) contract; rows are padded to
    the block size with sentinel ids (k_pad — they produce exact 0.0)
    and sliced back."""
    from jax.experimental import pallas as pl

    from bigclam_tpu.ops.pallas_csr import _out_struct

    e, m = iu.shape
    eb = min(_MERGE_BLOCK_E, max(_round_up(e, 8), 8))
    e_pad = _round_up(max(e, 1), eb)
    if e_pad != e:
        pad = e_pad - e
        iv = jnp.pad(iv, ((0, pad), (0, 0)), constant_values=k_pad)
        wv = jnp.pad(wv, ((0, pad), (0, 0)))
        iu = jnp.pad(iu, ((0, pad), (0, 0)), constant_values=k_pad)
    import functools

    out = pl.pallas_call(
        functools.partial(_merge_kernel, m=m, k_pad=k_pad),
        grid=(e_pad // eb,),
        in_specs=[
            pl.BlockSpec((eb, m), lambda i: (i, 0)),
            pl.BlockSpec((eb, m), lambda i: (i, 0)),
            pl.BlockSpec((eb, m), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((eb, m), lambda i: (i, 0)),
        out_shape=_out_struct((e_pad, m), wv.dtype, iv, wv, iu),
        interpret=interpret,
    )(iv, wv, iu)
    return out[:e]


def member_lookup_impl(
    iv: jax.Array,
    wv: jax.Array,
    iu: jax.Array,
    k_pad: int,
    cfg: BigClamConfig,
) -> jax.Array:
    """The ONE merge dispatch every sparse edge sweep goes through: the
    Pallas merge kernel when engaged (merge_pallas_want), else the XLA
    searchsorted merge — so the single-chip and sharded sparse trainers
    can never resolve the path differently for one config."""
    if merge_pallas_want(cfg):
        return member_lookup_pallas(
            iv, wv, iu, k_pad, interpret=cfg.pallas_interpret
        )
    return member_lookup(iv, wv, iu, k_pad)


def sparse_sumF(ids: jax.Array, w: jax.Array, k_pad: int) -> jax.Array:
    """Dense (K_pad,) column sums from the sparse state — a scatter-add
    of N*M values, never an (N, K) array. Sentinel ids (== k_pad) are
    out of bounds and dropped by the scatter."""
    return (
        jnp.zeros(k_pad, w.dtype)
        .at[ids.reshape(-1)]
        .add(w.reshape(-1), mode="drop")
    )


def presence(ids: jax.Array, k_pad: int) -> jax.Array:
    """(K_pad,) bool: communities present in ANY member list (the
    'touched' set the sparse allreduce exchanges)."""
    return (
        jnp.zeros(k_pad, bool)
        .at[ids.reshape(-1)]
        .set(True, mode="drop")
    )


def masked_sumF_at(
    ids: jax.Array, sumF: jax.Array, k_pad: int
) -> Tuple[jax.Array, jax.Array]:
    """(valid, sumF gathered at each member slot — 0 in sentinel slots)."""
    valid = ids < k_pad
    at = jnp.where(
        valid, sumF[jnp.minimum(ids, k_pad - 1)], jnp.zeros((), sumF.dtype)
    )
    return valid, at


def sparse_node_tail(w: jax.Array, sumF_at: jax.Array) -> jax.Array:
    """-F_u.sumF + F_u.F_u restricted to the support (exact: off-support
    entries of F_u are zero)."""
    return -jnp.einsum("nm,nm->n", w, sumF_at) + jnp.einsum(
        "nm,nm->n", w, w
    )


def sparse_grad_llh(
    ids: jax.Array,
    w: jax.Array,
    sumF: jax.Array,
    edges: EdgeChunks,
    cfg: BigClamConfig,
    k_pad: int,
    ids_dst: Optional[jax.Array] = None,
    w_dst: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused per-node slot-space gradient + per-node LLH (one edge
    sweep), the sparse twin of ops.objective.grad_llh. Returns
    (grad (N, M) — 0 in sentinel slots, node_llh (N,)). On the sharded
    path `ids`/`w` are the LOCAL rows edge src indexes (rebased) and
    `ids_dst`/`w_dst` the all-gathered global rows dst indexes."""
    if ids_dst is None:
        ids_dst, w_dst = ids, w
    n = ids.shape[0]
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else w.dtype

    def body(carry, sdm):
        nbr_llh, nbr_grad = carry
        s, d, m = sdm
        iu, wu = ids[s], w[s]
        vals = member_lookup_impl(
            ids_dst[d], w_dst[d], iu, k_pad, cfg
        )                                                      # (chunk, M)
        x = jnp.einsum("em,em->e", wu, vals)
        omp, ell = edge_terms(x, cfg)
        coeff = m / omp
        nbr_llh = nbr_llh + jax.ops.segment_sum(
            (ell * m).astype(adt), s, num_segments=n,
            indices_are_sorted=True,
        )
        nbr_grad = nbr_grad + jax.ops.segment_sum(
            vals * coeff[:, None], s, num_segments=n,
            indices_are_sorted=True,
        )
        return (nbr_llh, nbr_grad), None

    init = (jnp.zeros(n, adt), jnp.zeros_like(w))
    (nbr_llh, nbr_grad), _ = lax.scan(body, init, edges)
    valid, sumF_at = masked_sumF_at(ids, sumF, k_pad)
    grad = jnp.where(valid, nbr_grad - sumF_at + w, 0.0)
    node_llh = nbr_llh + sparse_node_tail(w, sumF_at).astype(adt)
    return grad, node_llh


def sparse_candidates(
    ids: jax.Array,
    w: jax.Array,
    grad: jax.Array,
    edges: EdgeChunks,
    cfg: BigClamConfig,
    k_pad: int,
    ids_dst: Optional[jax.Array] = None,
    w_dst: Optional[jax.Array] = None,
) -> jax.Array:
    """Neighbor-sum candidate terms for every Armijo step, shape (S, N)
    — the sparse twin of ops.linesearch.candidates_pass. The member
    lookup is done ONCE per chunk and reused by all 16 candidates (the
    support does not move within a step). ids_dst/w_dst as in
    sparse_grad_llh."""
    if ids_dst is None:
        ids_dst, w_dst = ids, w
    n = ids.shape[0]
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else w.dtype
    etas = jnp.asarray(cfg.step_candidates, w.dtype)
    num_s = len(cfg.step_candidates)

    def body(acc, sdm):
        s, d, m = sdm
        iu, wu, gu = ids[s], w[s], grad[s]
        vals = member_lookup_impl(ids_dst[d], w_dst[d], iu, k_pad, cfg)

        def one_eta(eta):
            nw = jnp.clip(wu + eta * gu, cfg.min_f, cfg.max_f)
            x = jnp.einsum("em,em->e", nw, vals)
            _, ell = edge_terms(x, cfg)
            return ell * m

        terms = lax.map(one_eta, etas)                  # (S, chunk)
        parts = jax.vmap(
            lambda v: jax.ops.segment_sum(
                v.astype(adt), s, num_segments=n, indices_are_sorted=True
            )
        )(terms)
        return acc + parts, None

    acc, _ = lax.scan(body, jnp.zeros((num_s, n), adt), edges)
    return acc


def sparse_armijo_update(
    ids: jax.Array,
    w: jax.Array,
    sumF: jax.Array,
    grad: jax.Array,
    node_llh: jax.Array,
    cand_nbr: jax.Array,
    cfg: BigClamConfig,
    k_pad: int,
):
    """Armijo acceptance + max-accepted-step Jacobi update on the slot
    arrays — the sparse twin of ops.linesearch.armijo_update. ||grad||^2
    carries the exact off-support correction (module docstring), so the
    acceptance rule is the dense path's, not a laxer one. Returns
    (w_new, accept_hist)."""
    adt = jnp.dtype(cfg.accum_dtype) if cfg.accum_dtype else w.dtype
    etas = jnp.asarray(cfg.step_candidates, w.dtype)
    _, sumF_at = masked_sumF_at(ids, sumF, k_pad)
    gg_slots = jnp.einsum("nm,nm->n", grad, grad)
    off_support = (sumF @ sumF) - jnp.einsum(
        "nm,nm->n", sumF_at, sumF_at
    )
    gg = (gg_slots + off_support).astype(adt)

    def tail_for(eta):
        nf = jnp.clip(w + eta * grad, cfg.min_f, cfg.max_f)
        sf_adj = sumF_at - w + nf
        return (
            -jnp.einsum("nm,nm->n", nf, sf_adj)
            + jnp.einsum("nm,nm->n", nf, nf)
        ).astype(adt)

    tails = lax.map(tail_for, etas)                     # (S, N)
    cand_llh = cand_nbr + tails
    ok = cand_llh >= node_llh[None, :] + cfg.alpha * etas[:, None] * gg[None, :]
    best_eta = jnp.max(jnp.where(ok, etas[:, None], 0.0), axis=0)
    accepted = jnp.any(ok, axis=0)
    w_new = jnp.where(
        accepted[:, None],
        jnp.clip(w + best_eta[:, None] * grad, cfg.min_f, cfg.max_f),
        w,
    )
    return w_new, accept_stats(ok)


def support_update(
    ids: jax.Array,
    w: jax.Array,
    blocks: SupportBlocks,
    m: int,
    k_pad: int,
    ids_nbr: Optional[jax.Array] = None,
    w_nbr: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One support-update pass: per node, admit candidate communities
    from neighbor member lists and keep the top-M by weight + neighbor
    mass. rank(c) = w_u[c] + sum_{v in N(u)} w_v[c]; only rank > 0
    entries keep a slot (everything else is sentinel), surviving members
    keep their weight EXACTLY, admissions start at weight 0 (their first
    gradient step is then identical to the dense path's).

    Sort-based, no K-sized axis: each block's candidate ENTRIES — own
    member slots + one entry per (edge, neighbor slot), (block_b + eb)*M
    of them — are lex-sorted by (node, community), duplicate runs
    segment-summed into ranks, then ordered by descending rank (stable:
    ties keep the lower community id, matching what lax.top_k over a
    dense rank row would pick) and cut to the first M per node. The
    support pass therefore costs O((N + E) * M log), flat in K — a
    dense (block, K) scratch + top_k(K) here would make the *sparse*
    step itself scale with K and forfeit the representation's whole
    point. `ids_nbr`/`w_nbr` supply the rows `blocks.dst` indexes (the
    ALL-GATHERED global rows on the sharded path, where `ids`/`w` are
    this shard's local rows and `blocks` covers exactly that row range;
    defaults to ids/w single-chip).
    """
    if ids_nbr is None:
        ids_nbr, w_nbr = ids, w
    block_b = blocks.block_b
    n_rows = ids.shape[0]
    n_blocks = n_rows // block_b
    assert n_blocks * block_b == n_rows, (n_rows, block_b)
    dtype = w.dtype
    eb = blocks.dst.shape[1]
    p = (block_b + eb) * m

    def block_fn(xs):
        sl, dd, mm, b = xs
        rows_ids = lax.dynamic_slice(ids, (b * block_b, 0), (block_b, m))
        rows_w = lax.dynamic_slice(w, (b * block_b, 0), (block_b, m))
        iv = ids_nbr[dd]                                # (eb, M)
        wv = w_nbr[dd] * mm[:, None]
        own_node = jnp.broadcast_to(
            jnp.arange(block_b, dtype=jnp.int32)[:, None], (block_b, m)
        )
        nbr_node = jnp.broadcast_to(sl[:, None], (eb, m))
        node = jnp.concatenate(
            [own_node.reshape(-1), nbr_node.reshape(-1)]
        )
        cid = jnp.concatenate([rows_ids.reshape(-1), iv.reshape(-1)])
        rc = jnp.concatenate([rows_w.reshape(-1), wv.reshape(-1)])
        wc = jnp.concatenate(
            [rows_w.reshape(-1), jnp.zeros(eb * m, dtype)]
        )
        # lexicographic (node asc, community asc) via two stable sorts;
        # duplicate (node, community) entries land in one contiguous run
        o1 = jnp.argsort(cid, stable=True)
        node, cid, rc, wc = node[o1], cid[o1], rc[o1], wc[o1]
        o2 = jnp.argsort(node, stable=True)
        node, cid, rc, wc = node[o2], cid[o2], rc[o2], wc[o2]
        first = jnp.concatenate([
            jnp.ones((1,), bool),
            (node[1:] != node[:-1]) | (cid[1:] != cid[:-1]),
        ])
        seg = jnp.cumsum(first) - 1
        rank = jax.ops.segment_sum(
            rc, seg, num_segments=p, indices_are_sorted=True
        )[seg]
        wsum = jax.ops.segment_sum(
            wc, seg, num_segments=p, indices_are_sorted=True
        )[seg]
        # a NaN/inf member weight must SURVIVE the top-M cut: ranking by
        # `> 0` alone would silently drop it (NaN > 0 is False),
        # laundering poisoned state before the fit loop's non-finite
        # detection (rollback/abort, models.bigclam.run_fit_loop) ever
        # sees the LLH go non-finite — rank it +inf instead so it keeps
        # a slot and the poison propagates to the LLH like on the dense
        # path
        rank = jnp.where(jnp.isfinite(rank), rank, jnp.inf)
        keep = first & (cid < k_pad) & (rank > 0)
        # order candidates by (node, rank desc): stable sort on -rank
        # (ties keep the (node, community)-asc order = lower id first),
        # then stable sort on node to group rows back together
        prio = jnp.where(keep, -rank, jnp.inf)
        o3 = jnp.argsort(prio, stable=True)
        node, cid, wsum, keep = node[o3], cid[o3], wsum[o3], keep[o3]
        o4 = jnp.argsort(node, stable=True)
        node, cid, wsum, keep = node[o4], cid[o4], wsum[o4], keep[o4]
        idxp = jnp.arange(p)
        row_start = lax.cummax(
            jnp.where(
                jnp.concatenate(
                    [jnp.ones((1,), bool), node[1:] != node[:-1]]
                ),
                idxp,
                0,
            )
        )
        pos = idxp - row_start                  # slot within the node's run
        take = keep & (pos < m)
        row = jnp.where(take, node, block_b)    # block_b is out of bounds:
        col = jnp.where(take, pos, 0)           # non-kept entries drop
        new_ids = (
            jnp.full((block_b, m), k_pad, jnp.int32)
            .at[row, col]
            .set(cid.astype(jnp.int32), mode="drop")
        )
        new_w = (
            jnp.zeros((block_b, m), dtype)
            .at[row, col]
            .set(wsum, mode="drop")
        )
        order = jnp.argsort(new_ids, axis=1)
        return (
            jnp.take_along_axis(new_ids, order, axis=1),
            jnp.take_along_axis(new_w, order, axis=1),
        )

    xs = (
        blocks.src_local, blocks.dst, blocks.mask,
        jnp.arange(n_blocks),
    )
    ids2, w2 = lax.map(block_fn, xs)
    return ids2.reshape(n_rows, m), w2.reshape(n_rows, m)
