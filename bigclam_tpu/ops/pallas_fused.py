"""Fused Pallas edge superstep: in-kernel dst gather, double-buffered DMA.

ISSUE 13 / ROADMAP item 4. The split blocked-CSR schedule (ops.pallas_csr)
feeds its kernels an XLA-gathered `fd = F[tiles.dst]` buffer: an
(E_pad, K) HBM array written once and read twice per step. At K=128 that
buffer alone moves more bytes than the CSR structure, so the r06 roofline
pinned the schedule at ~35% of v5e HBM bandwidth — bandwidth spent on a
buffer that never needs to exist. "Speeding Up BigClam" (arXiv:1712.01209)
got its wins from row caching and fusing the neighbor sum with the update;
this module is that idea on the MXU:

  * the dst-side row gather moves INSIDE the kernel: each tile's T dst
    rows are DMA'd from the HBM-resident F (memory_space=ANY) into a
    (2, T, K) VMEM scratch, one async copy per row, DOUBLE-BUFFERED — the
    copies for tile j+1 are issued before tile j's compute, so the gather
    latency hides behind the one-hot matmuls (no `fd` ever exists in HBM)
  * the whole superstep — gather -> exp/σ edge terms -> weighted
    scatter-add -> Armijo candidate ladder -> acceptance -> non-negative
    projection — runs in ONE pallas_call: the grid walks each block's
    tiles twice ([tile, phase] entries, fused_entry_seq), the block's
    gradient accumulates in the VMEM-resident grad output across its
    phase-0 entries and never round-trips to HBM before the candidate
    pass reads it, and the block's last entry applies the Armijo
    selection + clip projection and writes F_new directly
  * the per-tile index stream (dst row ids) is pipelined through SMEM
    blocks (current + next tile), so DMA addresses for tile j+1 are
    available while tile j computes — the two-deep software pipeline

  Accumulation ORDER matches the split kernels exactly (zero-init at the
  block's first tile, per-tile adds in tile order, candidate accumulator
  seeded with the Armijo tails before the first scatter add), so fused
  and split trajectories are bit-identical in interpret mode — pinned by
  tests/test_fused.py; real-chip hbm_frac stays with the ROADMAP 1 pod
  drill.

The gather-fused split kernels at the bottom (edge_dots_fused /
grad_nbr_from_x_fused / cand_dots_fused) give the SAME in-kernel DMA to
the schedules that cannot run the one-pass superstep: the TP suite (the
per-edge dot must psum over "k" mid-sweep), the K-blocked large-K passes
(a (B, K) grad block no longer fits VMEM — columns are processed kc at a
time, with the DMA slicing kc columns per row), and the ring phases
(neighbor terms accumulate across rotations). Because no fd is ever
materialized, the K-blocked fused pass runs on the FLAT tile layout —
which the store-native builders already produce — closing the
grouped/K-blocked store-layout gap that used to fall back to XLA.

The dst-id stream is POSITIONAL into whatever source buffer the caller
passes — the kernels never assume it is the full gathered F. The 1D
trainers hand the gathered row band with shard-order ids; the 2D
edge-block trainers (round 21, parallel/twod.py) hand the received
CLOSURE buffer (own block ‖ capped per-peer rows) with ids rewritten to
closure positions at build time by twod_block_tiles. At replica_cols=1
the closure buffer IS the gathered band in shard order, which is what
makes the 2D fused trajectory bit-identical to the 1D one — the CI
anchor pinning the relabeling as bookkeeping, not math.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bigclam_tpu.config import BigClamConfig
from bigclam_tpu.ops.objective import edge_terms
from bigclam_tpu.ops.pallas_csr import (
    _PREC,
    TilesDev,
    _expand_onehot,
    _out_struct,
)


def fused_entry_seq(block_id: np.ndarray) -> np.ndarray:
    """The fused superstep's grid-entry sequence from a flat tile layout's
    block_id: each block's (contiguous) tiles listed twice — once for the
    grad phase, once for the candidate/update phase. Returns
    (2*n_tiles, 2) int32 [tile_index, phase]; vectorized (no per-block
    Python — Friendster-scale layouts have hundreds of thousands of
    blocks)."""
    block_id = np.asarray(block_id, np.int32)
    nt = block_id.shape[0]
    tile2 = np.concatenate([np.arange(nt), np.arange(nt)]).astype(np.int32)
    phase = np.concatenate(
        [np.zeros(nt, np.int32), np.ones(nt, np.int32)]
    )
    # stable (block, phase, tile) order: all of a block's phase-0 tiles,
    # then its phase-1 tiles, blocks in layout order
    order = np.lexsort((tile2, phase, block_id[tile2]))
    return np.stack([tile2[order], phase[order]], axis=1)


# --- the in-kernel dst-row DMA pipeline -----------------------------------
#
# One async copy per dst row, HBM -> VMEM scratch slot, all on one DMA
# semaphore per slot; the wait loop decrements the same descriptors. Row
# ids come from the SMEM-resident index block the caller pipelines
# (current/next tile); `col0`/`kc` slice kc columns per row for the
# K-blocked passes (the DMA is the only place a column window exists).


def _rows_start(dref, f_src_ref, fd_scr, slot, sem, t, col0=None, kc=None):
    def body(r, _):
        row = dref[0, r]
        src = (
            f_src_ref.at[row]
            if col0 is None
            else f_src_ref.at[row, pl.ds(col0, kc)]
        )
        pltpu.make_async_copy(src, fd_scr.at[slot, r], sem.at[slot]).start()
        return _

    lax.fori_loop(0, t, body, 0)


def _rows_wait(f_src_ref, fd_scr, slot, sem, t, col0=None, kc=None):
    def body(r, _):
        src = (
            f_src_ref.at[0]
            if col0 is None
            else f_src_ref.at[0, pl.ds(0, kc)]
        )
        pltpu.make_async_copy(src, fd_scr.at[slot, r], sem.at[slot]).wait()
        return _

    lax.fori_loop(0, t, body, 0)


def _fd_pipeline(i, n, dcur_ref, dnxt_ref, f_src_ref, fd_scr, sem, t,
                 col0=None, kc=None):
    """The shared double-buffer: at grid step i, issue tile i+1's row
    copies (addresses from the pipelined NEXT index block), wait tile
    i's, return the resident (T, Kc) fd slot. Step 0 pays one un-hidden
    fetch (the prologue); every later tile's gather was issued one step
    earlier and overlaps that step's compute."""

    @pl.when(i == 0)
    def _():
        _rows_start(dcur_ref, f_src_ref, fd_scr, 0, sem, t, col0, kc)

    @pl.when(i + 1 < n)
    def _():
        _rows_start(
            dnxt_ref, f_src_ref, fd_scr, (i + 1) % 2, sem, t, col0, kc
        )

    _rows_wait(f_src_ref, fd_scr, i % 2, sem, t, col0, kc)
    return fd_scr[i % 2]


def _dst_specs(nj: int, t: int, tile_of):
    """(current, next) SMEM index-block specs: tile_of(j, *scalars) names
    the tile whose dst row-id block grid entry j needs."""
    return (
        pl.BlockSpec(
            (1, t), lambda j, *s: (tile_of(j, *s), 0),
            memory_space=pltpu.SMEM,
        ),
        pl.BlockSpec(
            (1, t),
            lambda j, *s: (tile_of(jnp.minimum(j + 1, nj - 1), *s), 0),
            memory_space=pltpu.SMEM,
        ),
    )


# --- the one-pass fused superstep -----------------------------------------


def _superstep_kernel(seq_ref, bid_ref, srcl_ref, mask_ref, dcur_ref,
                      dnxt_ref, f_blk_ref, sumf_ref, f_src_ref,
                      fnew_ref, grad_ref, llh_ref, ok_ref, fd_scr, sem,
                      *, cfg, block_b, tile_t):
    j = pl.program_id(0)
    nj = pl.num_programs(0)
    tile = seq_ref[j, 0]
    phase = seq_ref[j, 1]
    blk = bid_ref[tile]
    jp = jnp.maximum(j - 1, 0)
    first = jnp.logical_or(
        j == 0,
        jnp.logical_or(
            bid_ref[seq_ref[jp, 0]] != blk, seq_ref[jp, 1] != phase
        ),
    )
    jn = jnp.minimum(j + 1, nj - 1)
    last = jnp.logical_or(j == nj - 1, bid_ref[seq_ref[jn, 0]] != blk)

    fd = _fd_pipeline(
        j, nj, dcur_ref, dnxt_ref, f_src_ref, fd_scr, sem, tile_t
    )                                        # (T, K) dst rows, in VMEM only
    srcl = srcl_ref[0, 0]                    # (T,)
    m = mask_ref[0, 0]                       # (T,)
    fb = f_blk_ref[:]                        # (B, K)
    sumf = sumf_ref[0]                       # (K,)
    one = _expand_onehot(srcl, block_b, fd.dtype)        # (B, T)
    dims = (((0,), (0,)), ((), ()))
    fs = lax.dot_general(one, fb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    etas = cfg.step_candidates

    @pl.when(phase == 0)
    def _grad_phase():
        @pl.when(first)
        def _():
            grad_ref[0] = jnp.zeros_like(grad_ref)[0]
            llh_ref[0, 0] = jnp.zeros_like(llh_ref)[0, 0]

        x = jnp.sum(fs * fd, axis=1)         # (T,) edge dots, VPU f32
        omp, ell_raw = edge_terms(x, cfg)    # same clipping as every path
        ell = ell_raw * m
        coeff = m / omp
        grad_ref[0] += lax.dot_general(      # neighbor-grad scatter
            one, fd * coeff[:, None], (((1,), (0,)), ((), ())),
            precision=_PREC, preferred_element_type=fd.dtype,
        )
        llh_ref[0, 0] += jnp.sum(one * ell[None, :], axis=1)

    @pl.when(phase == 1)
    def _cand_phase():
        @pl.when(first)
        def _():
            # the block's grad is complete (its phase-0 entries all ran):
            # finalize IN VMEM — the -sumF + F fold and the node tail
            # never round-trip through HBM — and seed the candidate
            # accumulator with the Armijo tails (split-kernel order:
            # tails first, then the per-tile neighbor scatters)
            gfull = grad_ref[0] - sumf[None, :] + fb
            grad_ref[0] = gfull
            llh_ref[0, 0] = llh_ref[0, 0] + (
                -jnp.sum(fb * sumf[None, :], axis=1) + jnp.sum(fb * fb, axis=1)
            )
            fms = fb - sumf[None, :]
            tails = []
            for eta in etas:
                nfb = jnp.clip(fb + eta * gfull, cfg.min_f, cfg.max_f)
                tails.append(jnp.sum(nfb * fms, axis=1))
            ok_ref[0] = jnp.stack(tails, axis=0)         # (S, B)

        gfull = grad_ref[0]
        gs = lax.dot_general(one, gfull, dims, precision=_PREC,
                             preferred_element_type=fd.dtype)
        ells = []
        for eta in etas:
            nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
            x = jnp.sum(nf * fd, axis=1)
            _, ell = edge_terms(x, cfg)
            ells.append(ell * m)
        ok_ref[0] += lax.dot_general(        # (S, B) neighbor terms
            jnp.stack(ells, axis=0), one, (((1,), (1,)), ((), ())),
            precision=_PREC, preferred_element_type=fd.dtype,
        )

    @pl.when(last)                           # last => phase == 1
    def _select():
        gfull = grad_ref[0]
        cand_llh = ok_ref[0]                 # (S, B), tails included
        nllh = llh_ref[0, 0]                 # (B,)
        gg = jnp.sum(gfull * gfull, axis=1)
        # per-eta scalar loop (etas are compile-time floats — kernels
        # cannot capture array constants); best_eta is the MAX accepted
        # step, order-independent like armijo_select
        oks = []
        best_eta = jnp.zeros_like(nllh)
        for s, eta in enumerate(etas):
            ok_s = cand_llh[s] >= nllh + cfg.alpha * eta * gg
            oks.append(ok_s)
            best_eta = jnp.where(
                ok_s, jnp.maximum(best_eta, eta), best_eta
            )
        okm = jnp.stack(oks, axis=0)
        accepted = jnp.any(okm, axis=0)
        fnew_ref[0] = jnp.where(
            accepted[:, None],
            jnp.clip(fb + best_eta[:, None] * gfull, cfg.min_f, cfg.max_f),
            fb,
        )
        ok_ref[0] = okm.astype(fb.dtype)     # acceptance mask out (0/1)


def fused_superstep_csr(
    F: jax.Array,
    sumF: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    interpret: bool = False,
    F_gather: jax.Array = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """The whole edge superstep in one Pallas pass over the flat tile
    layout (tiles.seq required): in-kernel double-buffered dst-row DMA,
    VMEM-resident per-block grad, Armijo ladder + selection + projection
    fused. Returns (F_new (n_pad, K), grad (n_pad, K), node_llh (n_pad,),
    ok (S, n_pad) 0/1 acceptance mask — feed accept_stats). `F_gather` is
    the DMA source the dst ids index (the all-gathered full F on the
    sharded path; defaults to F)."""
    n_pad, k = F.shape
    assert n_pad == tiles.n_pad, (n_pad, tiles.n_pad)
    assert tiles.seq is not None, "fused superstep needs tiles.seq"
    b, t = tiles.block_b, tiles.tile_t
    nj = tiles.seq.shape[0]
    num_s = len(cfg.step_candidates)
    F_src = F if F_gather is None else F_gather
    kernel = functools.partial(
        _superstep_kernel, cfg=cfg, block_b=b, tile_t=t
    )
    dcur, dnxt = _dst_specs(nj, t, lambda j, seq, bid: seq[j, 0])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nj,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda j, seq, bid: (seq[j, 0], 0, 0)),
            pl.BlockSpec((1, 1, t), lambda j, seq, bid: (seq[j, 0], 0, 0)),
            dcur,
            dnxt,
            pl.BlockSpec((b, k), lambda j, seq, bid: (bid[seq[j, 0]], 0)),
            pl.BlockSpec((1, k), lambda j, seq, bid: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, b, k), lambda j, seq, bid: (bid[seq[j, 0]], 0, 0)
            ),
            pl.BlockSpec(
                (1, b, k), lambda j, seq, bid: (bid[seq[j, 0]], 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, b), lambda j, seq, bid: (bid[seq[j, 0]], 0, 0)
            ),
            pl.BlockSpec(
                (1, num_s, b), lambda j, seq, bid: (bid[seq[j, 0]], 0, 0)
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, t, F_src.shape[1]), F.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    nb = tiles.n_blocks
    operands = (F, F_src, sumF, tiles.mask)
    F_new, grad, llh, ok = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((nb, b, k), F.dtype, *operands),
            _out_struct((nb, b, k), F.dtype, *operands),
            _out_struct((nb, 1, b), F.dtype, *operands),
            _out_struct((nb, num_s, b), F.dtype, *operands),
        ],
        interpret=interpret,
    )(
        tiles.seq, tiles.block_id, tiles.src_local, tiles.mask,
        tiles.dst, tiles.dst, F, sumF.reshape(1, k), F_src,
    )
    return (
        F_new.reshape(n_pad, k),
        grad.reshape(n_pad, k),
        llh.reshape(n_pad),
        ok.transpose(1, 0, 2).reshape(num_s, n_pad),
    )


# --- gather-fused split kernels (ring phases, TP suite, K-blocked) --------
#
# Same compute bodies as the ops.pallas_csr split kernels, with the XLA fd
# operand replaced by the in-kernel DMA pipeline. These serve the
# schedules the one-pass superstep cannot: ring phases (grad/cand
# accumulate across rotations), the K-sharded TP split (per-edge dots
# psum over "k" between kernels), and the K-blocked large-K passes
# (kc columns per call — the DMA slices the column window per row, so no
# (N, kc) column copy is materialized either).


def _grad_blocks_kernel(bid_ref, srcl_ref, mask_ref, dcur_ref, dnxt_ref,
                        f_blk_ref, f_src_ref, grad_out_ref, llh_out_ref,
                        fd_scr, sem, *, cfg, block_b, tile_t):
    i = pl.program_id(0)
    n = pl.num_programs(0)
    fd = _fd_pipeline(
        i, n, dcur_ref, dnxt_ref, f_src_ref, fd_scr, sem, tile_t
    )
    srcl = srcl_ref[0, 0]
    m = mask_ref[0, 0]
    fb = f_blk_ref[:]
    one = _expand_onehot(srcl, block_b, fd.dtype)
    fs = lax.dot_general(one, fb, (((0,), (0,)), ((), ())),
                         precision=_PREC, preferred_element_type=fd.dtype)
    x = jnp.sum(fs * fd, axis=1)
    omp, ell_raw = edge_terms(x, cfg)
    ell = ell_raw * m
    coeff = m / omp
    contrib = lax.dot_general(
        one, fd * coeff[:, None], (((1,), (0,)), ((), ())),
        precision=_PREC, preferred_element_type=fd.dtype,
    )
    llh_c = jnp.sum(one * ell[None, :], axis=1)
    prev = bid_ref[jnp.maximum(i - 1, 0)]

    @pl.when(jnp.logical_or(i == 0, bid_ref[i] != prev))
    def _():
        grad_out_ref[0] = jnp.zeros_like(grad_out_ref)[0]
        llh_out_ref[0, 0] = jnp.zeros_like(llh_out_ref)[0, 0]

    grad_out_ref[0] += contrib
    llh_out_ref[0, 0] += llh_c


def _grad_blocks_fused(
    F: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    F_gather: jax.Array,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """ops.pallas_csr._grad_blocks with the dst rows DMA'd in-kernel from
    `F_gather` (the ring's rotating shard / the all-gathered F) — raw
    (n_blocks, B, K) neighbor-grad partials + (n_blocks, 1, B) LLH
    partials, no HBM fd."""
    n_pad, k = F.shape
    b, t = tiles.block_b, tiles.tile_t
    n_tiles = tiles.src_local.shape[0]
    kernel = functools.partial(
        _grad_blocks_kernel, cfg=cfg, block_b=b, tile_t=t
    )
    dcur, dnxt = _dst_specs(n_tiles, t, lambda i, bid: i)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            dcur,
            dnxt,
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, b, k), lambda i, bid: (bid[i], 0, 0)),
            pl.BlockSpec((1, 1, b), lambda i, bid: (bid[i], 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, t, F_gather.shape[1]), F.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    operands = (F, F_gather, tiles.mask)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((tiles.n_blocks, b, k), F.dtype, *operands),
            _out_struct((tiles.n_blocks, 1, b), F.dtype, *operands),
        ],
        interpret=interpret,
    )(
        tiles.block_id, tiles.src_local, tiles.mask, tiles.dst, tiles.dst,
        F, F_gather,
    )


def _cand_blocks_kernel(bid_ref, srcl_ref, mask_ref, dcur_ref, dnxt_ref,
                        f_blk_ref, g_blk_ref, f_src_ref, out_ref,
                        fd_scr, sem, *, cfg, block_b, tile_t):
    i = pl.program_id(0)
    n = pl.num_programs(0)
    fd = _fd_pipeline(
        i, n, dcur_ref, dnxt_ref, f_src_ref, fd_scr, sem, tile_t
    )
    srcl = srcl_ref[0, 0]
    m = mask_ref[0, 0]
    fb = f_blk_ref[:]
    gb = g_blk_ref[:]
    one = _expand_onehot(srcl, block_b, fd.dtype)
    dims = (((0,), (0,)), ((), ()))
    fs = lax.dot_general(one, fb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    gs = lax.dot_general(one, gb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    ells = []
    for eta in cfg.step_candidates:
        nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
        x = jnp.sum(nf * fd, axis=1)
        _, ell = edge_terms(x, cfg)
        ells.append(ell * m)
    scat = lax.dot_general(
        jnp.stack(ells, axis=0), one, (((1,), (1,)), ((), ())),
        precision=_PREC, preferred_element_type=fd.dtype,
    )
    prev = bid_ref[jnp.maximum(i - 1, 0)]

    @pl.when(jnp.logical_or(i == 0, bid_ref[i] != prev))
    def _():
        out_ref[0] = jnp.zeros_like(out_ref)[0]

    out_ref[0] += scat


def _cand_blocks_fused(
    F: jax.Array,
    grad: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    F_gather: jax.Array,
    interpret: bool = False,
) -> jax.Array:
    """ops.pallas_csr._cand_blocks (with_tails=False — ring phases see a
    partial edge set, the tails are added once outside) with the dst rows
    DMA'd in-kernel: (n_blocks, S, B) neighbor candidate partials."""
    n_pad, k = F.shape
    b, t = tiles.block_b, tiles.tile_t
    n_tiles = tiles.src_local.shape[0]
    num_s = len(cfg.step_candidates)
    kernel = functools.partial(
        _cand_blocks_kernel, cfg=cfg, block_b=b, tile_t=t
    )
    dcur, dnxt = _dst_specs(n_tiles, t, lambda i, bid: i)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, bid: (i, 0, 0)),
            dcur,
            dnxt,
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
            pl.BlockSpec((b, k), lambda i, bid: (bid[i], 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, num_s, b), lambda i, bid: (bid[i], 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, t, F_gather.shape[1]), F.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    operands = (F, grad, F_gather, tiles.mask)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct(
            (tiles.n_blocks, num_s, b), F.dtype, *operands
        ),
        interpret=interpret,
    )(
        tiles.block_id, tiles.src_local, tiles.mask, tiles.dst, tiles.dst,
        F, grad, F_gather,
    )


def _edge_dots_kernel(bid_ref, kb_ref, srcl_ref, dcur_ref, dnxt_ref,
                      f_blk_ref, f_src_ref, x_out_ref, fd_scr, sem,
                      *, block_b, tile_t, kc):
    i = pl.program_id(0)
    n = pl.num_programs(0)
    fd = _fd_pipeline(
        i, n, dcur_ref, dnxt_ref, f_src_ref, fd_scr, sem, tile_t,
        col0=kb_ref[0] * kc, kc=kc,
    )
    srcl = srcl_ref[0, 0]
    fb = f_blk_ref[:]                        # (B, kc) — spec-sliced columns
    one = _expand_onehot(srcl, block_b, fd.dtype)
    fs = lax.dot_general(one, fb, (((0,), (0,)), ((), ())),
                         precision=_PREC, preferred_element_type=fd.dtype)
    x_out_ref[0, 0] = jnp.sum(fs * fd, axis=1)


def edge_dots_fused(
    F: jax.Array,
    tiles: TilesDev,
    F_gather: jax.Array,
    kb: jax.Array,
    kc: int,
    interpret: bool = False,
) -> jax.Array:
    """Per-edge PARTIAL dots over columns [kb*kc, (kb+1)*kc) with the dst
    rows' column window DMA'd in-kernel from `F_gather`: (n_tiles, 1, T).
    The column window exists only in the DMA descriptors — neither an fd
    nor an (N, kc) column slice is ever materialized. kc == K with kb=0
    is the flat TP form (whole K_loc rows)."""
    n_pad, k = F.shape
    b, t = tiles.block_b, tiles.tile_t
    n_tiles = tiles.src_local.shape[0]
    kernel = functools.partial(
        _edge_dots_kernel, block_b=b, tile_t=t, kc=kc
    )
    dcur, dnxt = _dst_specs(n_tiles, t, lambda i, bid, kbv: i)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid, kbv: (i, 0, 0)),
            dcur,
            dnxt,
            pl.BlockSpec((b, kc), lambda i, bid, kbv: (bid[i], kbv[0])),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, t), lambda i, bid, kbv: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, t, kc), F.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    operands = (F, F_gather)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((n_tiles, 1, t), F.dtype, *operands),
        interpret=interpret,
    )(
        tiles.block_id, jnp.asarray(kb, jnp.int32).reshape(1),
        tiles.src_local, tiles.dst, tiles.dst, F, F_gather,
    )


def _grad_from_x_kernel(bid_ref, kb_ref, srcl_ref, mask_ref, x_ref,
                        dcur_ref, dnxt_ref, *rest, cfg, block_b, tile_t,
                        kc, fold):
    if fold:
        (f_blk_ref, sumf_ref, f_src_ref, grad_out_ref, llh_out_ref,
         fd_scr, sem) = rest
    else:
        f_src_ref, grad_out_ref, llh_out_ref, fd_scr, sem = rest
    i = pl.program_id(0)
    n = pl.num_programs(0)
    fd = _fd_pipeline(
        i, n, dcur_ref, dnxt_ref, f_src_ref, fd_scr, sem, tile_t,
        col0=kb_ref[0] * kc, kc=kc,
    )
    srcl = srcl_ref[0, 0]
    m = mask_ref[0, 0]
    x = x_ref[0, 0]                          # (T,) FULL edge dots
    one = _expand_onehot(srcl, block_b, fd.dtype)
    omp, ell_raw = edge_terms(x, cfg)
    ell = ell_raw * m
    coeff = m / omp
    contrib = lax.dot_general(
        one, fd * coeff[:, None], (((1,), (0,)), ((), ())),
        precision=_PREC, preferred_element_type=fd.dtype,
    )
    llh_c = jnp.sum(one * ell[None, :], axis=1)
    prev = bid_ref[jnp.maximum(i - 1, 0)]

    @pl.when(jnp.logical_or(i == 0, bid_ref[i] != prev))
    def _():
        grad_out_ref[0] = jnp.zeros_like(grad_out_ref)[0]
        llh_out_ref[0, 0] = jnp.zeros_like(llh_out_ref)[0, 0]

    grad_out_ref[0] += contrib
    llh_out_ref[0, 0] += llh_c
    if fold:
        # last tile of the block: fold -sumF + F into the completed
        # neighbor sum so the caller gets the FULL gradient columns
        nxt = bid_ref[jnp.minimum(i + 1, n - 1)]

        @pl.when(jnp.logical_or(i == n - 1, nxt != bid_ref[i]))
        def _():
            grad_out_ref[0] = (
                grad_out_ref[0] - sumf_ref[0][None, :] + f_blk_ref[:]
            )


def grad_nbr_from_x_fused(
    x: jax.Array,
    tiles: TilesDev,
    F_gather: jax.Array,
    kb: jax.Array,
    kc: int,
    cfg: BigClamConfig,
    interpret: bool = False,
    F: jax.Array = None,
    sumF: jax.Array = None,
) -> Tuple[jax.Array, jax.Array]:
    """Gradient columns [kb*kc, (kb+1)*kc) + neighbor LLH from FULL edge
    dots `x`, dst rows DMA'd in-kernel. With F/sumF given the -sumF + F
    fold happens in-kernel at each block's last tile (the K-blocked
    passes — the caller gets full gradient columns); without, neighbor
    partials only (ring phases accumulate across rotations). Returns
    (grad (n_pad, kc), llh (n_pad,))."""
    n_tiles, _, t = x.shape
    b = tiles.block_b
    fold = F is not None
    kernel = functools.partial(
        _grad_from_x_kernel, cfg=cfg, block_b=b, tile_t=t, kc=kc,
        fold=fold,
    )
    dcur, dnxt = _dst_specs(n_tiles, t, lambda i, bid, kbv: i)
    in_specs = [
        pl.BlockSpec((1, 1, t), lambda i, bid, kbv: (i, 0, 0)),
        pl.BlockSpec((1, 1, t), lambda i, bid, kbv: (i, 0, 0)),
        pl.BlockSpec((1, 1, t), lambda i, bid, kbv: (i, 0, 0)),
        dcur,
        dnxt,
    ]
    args = [
        tiles.src_local, tiles.mask, x, tiles.dst, tiles.dst,
    ]
    if fold:
        in_specs += [
            pl.BlockSpec((b, kc), lambda i, bid, kbv: (bid[i], kbv[0])),
            pl.BlockSpec((1, kc), lambda i, bid, kbv: (0, kbv[0])),
        ]
        args += [F, sumF.reshape(1, -1)]
    in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
    args.append(F_gather)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, b, kc), lambda i, bid, kbv: (bid[i], 0, 0)),
            pl.BlockSpec((1, 1, b), lambda i, bid, kbv: (bid[i], 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, t, kc), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    operands = (x, F_gather, tiles.mask)
    grad_out, llh_out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((tiles.n_blocks, b, kc), x.dtype, *operands),
            _out_struct((tiles.n_blocks, 1, b), x.dtype, *operands),
        ],
        interpret=interpret,
    )(tiles.block_id, jnp.asarray(kb, jnp.int32).reshape(1), *args)
    return grad_out.reshape(tiles.n_pad, kc), llh_out.reshape(tiles.n_pad)


def _cand_dots_kernel(bid_ref, kb_ref, srcl_ref, dcur_ref, dnxt_ref,
                      f_blk_ref, g_blk_ref, f_src_ref, xc_out_ref,
                      fd_scr, sem, *, cfg, block_b, tile_t, kc):
    i = pl.program_id(0)
    n = pl.num_programs(0)
    fd = _fd_pipeline(
        i, n, dcur_ref, dnxt_ref, f_src_ref, fd_scr, sem, tile_t,
        col0=kb_ref[0] * kc, kc=kc,
    )
    srcl = srcl_ref[0, 0]
    fb = f_blk_ref[:]
    gb = g_blk_ref[:]
    one = _expand_onehot(srcl, block_b, fd.dtype)
    dims = (((0,), (0,)), ((), ()))
    fs = lax.dot_general(one, fb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    gs = lax.dot_general(one, gb, dims, precision=_PREC,
                         preferred_element_type=fd.dtype)
    for s, eta in enumerate(cfg.step_candidates):
        nf = jnp.clip(fs + eta * gs, cfg.min_f, cfg.max_f)
        xc_out_ref[0, s] = jnp.sum(nf * fd, axis=1)


def cand_dots_fused(
    F: jax.Array,
    grad_kb: jax.Array,
    tiles: TilesDev,
    F_gather: jax.Array,
    kb: jax.Array,
    kc: int,
    cfg: BigClamConfig,
    interpret: bool = False,
) -> jax.Array:
    """Per-edge PARTIAL candidate dots over columns [kb*kc, (kb+1)*kc),
    dst rows DMA'd in-kernel: (n_tiles, S, T). `grad_kb` holds the kc
    gradient COLUMNS (n_pad, kc) — already a column window, indexed at
    block 0."""
    n_pad, k = F.shape
    b, t = tiles.block_b, tiles.tile_t
    n_tiles = tiles.src_local.shape[0]
    num_s = len(cfg.step_candidates)
    kernel = functools.partial(
        _cand_dots_kernel, cfg=cfg, block_b=b, tile_t=t, kc=kc
    )
    dcur, dnxt = _dst_specs(n_tiles, t, lambda i, bid, kbv: i)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1, t), lambda i, bid, kbv: (i, 0, 0)),
            dcur,
            dnxt,
            pl.BlockSpec((b, kc), lambda i, bid, kbv: (bid[i], kbv[0])),
            pl.BlockSpec((b, kc), lambda i, bid, kbv: (bid[i], 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, num_s, t), lambda i, bid, kbv: (i, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, t, kc), F.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    operands = (F, grad_kb, F_gather)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_out_struct((n_tiles, num_s, t), F.dtype, *operands),
        interpret=interpret,
    )(
        tiles.block_id, jnp.asarray(kb, jnp.int32).reshape(1),
        tiles.src_local, tiles.dst, tiles.dst, F, grad_kb, F_gather,
    )


def train_pass_csr_kblocked_fused(
    F: jax.Array,
    sumF: jax.Array,
    tiles: TilesDev,
    cfg: BigClamConfig,
    k_axis: Optional[str] = None,
    interpret: bool = False,
    F_gather: jax.Array = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The K-blocked large-K train pass on FLAT tiles with in-kernel
    gather — the fused twin of ops.pallas_csr
    .train_pass_csr_grouped_kblocked_tp, minus the grouped layout (no fd
    is materialized, so there is no per-group gather to bound; the flat
    layout the store-native builders already produce suffices — this is
    what closes the grouped/K-blocked store-layout gap).

    Per step: (1) accumulate full per-edge dots over kc-column K blocks
    (edge_dots_fused per block), one psum over `k_axis` completes them
    (identity when k_axis is None — single chip / tp == 1); (2) per K
    block, consume x into that block's FULL gradient columns (the
    -sumF + F fold happens in-kernel) and accumulate candidate partial
    dots; (3) psum the candidate partials, one consume kernel.

    F/sumF hold this device's K_loc columns, tiles.kc | K_loc. Returns
    (grad (n_pad, K_loc), llh_nbr (n_pad,), cand_nbr (S, n_pad)) —
    candidate terms NEIGHBOR-only; the caller adds the Armijo tails
    (armijo_update / armijo_tail_select_sharded)."""
    from bigclam_tpu.ops.pallas_csr import cand_nbr_from_x_csr

    n_pad, k = F.shape
    assert n_pad == tiles.n_pad, (n_pad, tiles.n_pad)
    kc = tiles.kc
    assert kc > 0 and k % kc == 0, (k, kc)
    n_kb = k // kc
    n_tiles = tiles.src_local.shape[0]
    t = tiles.tile_t
    num_s = len(cfg.step_candidates)
    F_src = F if F_gather is None else F_gather

    def psum(v):
        return v if k_axis is None else lax.psum(v, k_axis)

    def dots_kb(x_acc, kb):
        x_kb = edge_dots_fused(
            F, tiles, F_src, kb, kc, interpret=interpret
        )
        return x_acc + x_kb, None

    x_loc, _ = lax.scan(
        dots_kb, jnp.zeros((n_tiles, 1, t), F.dtype), jnp.arange(n_kb)
    )
    x = psum(x_loc)

    def consume_kb(xc_acc, kb):
        grad_kb, ln_kb = grad_nbr_from_x_fused(
            x, tiles, F_src, kb, kc, cfg, interpret=interpret,
            F=F, sumF=sumF,
        )
        xc_kb = cand_dots_fused(
            F, grad_kb, tiles, F_src, kb, kc, cfg, interpret=interpret
        )
        return xc_acc + xc_kb, (grad_kb, ln_kb)

    xc_loc, (grads, lns) = lax.scan(
        consume_kb, jnp.zeros((n_tiles, num_s, t), F.dtype),
        jnp.arange(n_kb),
    )
    xc = psum(xc_loc)
    cand_nbr = cand_nbr_from_x_csr(xc, tiles, cfg, interpret=interpret)
    grad = grads.transpose(1, 0, 2).reshape(n_pad, k)
    # llh depends only on the (already global) x and the mask — identical
    # across K blocks
    return grad, lns[0], cand_nbr
