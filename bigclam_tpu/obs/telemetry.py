"""RunTelemetry: the run-scoped telemetry object every entry point threads
through (see obs.__init__ for the architecture).

Design constraints that shaped this file:

* **jax-free at import.** `cli ingest` runs on data-prep hosts and must not
  pay the jax import (RSS + time); this module only touches jax when the
  entry point opted into device telemetry (`device_memory=True`) or jax is
  already loaded (`sys.modules` probe — never triggers an import).

* **Single-writer event log.** Under multi-controller jax, N processes
  appending one events.jsonl would interleave. Like MetricsLogger, the
  primary gate is decided lazily — but telemetry starts BEFORE
  jax.distributed.initialize (the CLI creates it before the model factory
  joins the process group), when every process reads index 0. Events are
  therefore buffered in memory until `commit_gate()` (auto on first event
  by default; entry points that will join a process group construct with
  `auto_gate=False` and commit after the join), and only the primary opens
  the file. Every process still counts events locally for its own report.

* **Compile visibility.** jax.monitoring duration listeners fire
  `/jax/core/compile/backend_compile_duration` per real XLA compile (and
  jaxpr_trace per retrace) on both the 0.4 and 0.5 lines — a module-level
  listener dispatches to the installed telemetry. Where the listener API
  is absent, `note_step_build` (called at every trainer step-cache miss,
  keyed by models.bigclam.step_cfg_key) still counts step builds — the
  fallback signal, and on both paths the per-key attribution that makes a
  sweep silently recompiling per-K visible.

* **Thread safety.** The heartbeat emits from its own thread; event writes
  and counter updates take one lock.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, TextIO

from bigclam_tpu.obs.schema import SCHEMA_VERSION
from bigclam_tpu.utils.profiling import current_rss_bytes, peak_rss_bytes

EVENTS_NAME = "events.jsonl"
REPORT_NAME = "run_report.json"

_CURRENT: Optional["RunTelemetry"] = None
# jax.monitoring listener registration is irreversible on the public API
# (there is clear_event_listeners but no targeted unregister on 0.4.x), so
# ONE module-level listener is registered on first need and dispatches to
# whatever telemetry is currently installed.
_MONITOR_STATE = {"registered": False, "available": None}


def current() -> Optional["RunTelemetry"]:
    """The installed telemetry, or None when observability is off — the
    whole off-path cost at instrumentation sites is this None check."""
    return _CURRENT


def install(tel: "RunTelemetry") -> "RunTelemetry":
    global _CURRENT
    _CURRENT = tel
    return tel


def uninstall(tel: Optional["RunTelemetry"] = None) -> None:
    """Clear the slot (only if `tel` still owns it, when given)."""
    global _CURRENT
    if tel is None or _CURRENT is tel:
        _CURRENT = None


def note_step_build(cfg, model: str = "") -> None:
    """Record a trainer step build keyed by step_cfg_key — called at every
    step-cache MISS (model __init__ / rebuild_step), so per-cfg-key build
    counts exist even where jax.monitoring listeners do not. No-op with
    telemetry off."""
    tel = _CURRENT
    if tel is None:
        return
    from bigclam_tpu.models.bigclam import step_cfg_key

    key = repr(step_cfg_key(cfg))
    # deterministic short digest (repr of the frozen dataclass is stable;
    # hash() is not across processes), so per-process reports merge
    digest = hashlib.sha1(key.encode()).hexdigest()[:10]
    label = f"{model}:{digest}" if model else digest
    tel.record_step_build(label)


def _on_monitoring_duration(name: str, secs: float, **kw) -> None:
    tel = _CURRENT
    if tel is not None and "/compile/" in name:
        tel._compile_observed(name, secs)


def _ensure_monitor() -> bool:
    """Register the jax.monitoring duration listener once; False when the
    API is unavailable (note_step_build counts remain the compile signal)."""
    if _MONITOR_STATE["registered"]:
        return True
    if _MONITOR_STATE["available"] is False:
        return False
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(
            _on_monitoring_duration
        )
    except Exception:
        _MONITOR_STATE["available"] = False
        return False
    _MONITOR_STATE["registered"] = True
    _MONITOR_STATE["available"] = True
    return True


def _json_default(obj):
    """numpy scalars/arrays slip into event fields from callers (an int
    from a manifest, an accept histogram) — serialize them as their
    Python values instead of crashing the event log mid-run."""
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    return str(obj)


def _finite_safe(obj):
    """Replace non-finite floats with their repr strings ("nan", "inf",
    "-inf") recursively. json.dumps would otherwise write literal NaN —
    not JSON — and the one event that carries a NaN by design is the
    nonfinite sentinel's, exactly the line strict consumers (jq, log
    pipelines) must be able to parse."""
    import math as _math

    if isinstance(obj, float):
        return obj if _math.isfinite(obj) else repr(obj)
    if isinstance(obj, dict):
        return {k: _finite_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite_safe(v) for v in obj]
    if hasattr(obj, "item") or hasattr(obj, "tolist"):
        return _finite_safe(_json_default(obj))
    return obj


def _resolve_run_id(directory: str) -> str:
    """One run id per telemetry DIRECTORY, shared across the processes of
    a multi-controller run with no coordinator: the first process to
    os.link its candidate onto `run_id` wins (atomic on POSIX), everyone
    else reads the winner. A dir reused across runs keeps its id — one
    telemetry dir = one run is the contract (events append; resume after
    a crash correlates under the same id)."""
    path = os.path.join(directory, "run_id")
    rid = f"{int(time.time()):x}-{os.urandom(3).hex()}"
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            f.write(rid)
        os.link(tmp, path)
        return rid
    except OSError:
        pass                    # somebody else claimed it (or no link())
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    for _ in range(100):        # winner may still be mid-write
        try:
            with open(path) as f:
                got = f.read().strip()
            if got:
                return got
        except OSError:
            pass
        time.sleep(0.01)
    return rid


def _jax_loaded() -> bool:
    return "jax" in sys.modules


def _jax_ready() -> bool:
    """True when asking jax for process/device state cannot change the
    world: the backend is already up, or the process group is joined.

    Telemetry runs BEFORE jax.distributed.initialize (the CLI constructs
    it first), and jax.process_index()/local_devices() on a cold jax
    INITIALIZE the backend — after which distributed.initialize raises
    ("must be called before any JAX computations"). Every telemetry read
    of jax state therefore goes through this guard; pre-init the answers
    are the definitional defaults (index 0, no devices) anyway."""
    if not _jax_loaded():
        return False
    try:
        from bigclam_tpu.utils.compat import distributed_is_initialized

        if distributed_is_initialized():
            return True
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized"):
            return bool(xla_bridge.backends_are_initialized())
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def _process_index() -> int:
    """jax.process_index when jax is UP (see _jax_ready); 0 on jax-free
    entries (ingest) and before any backend/process-group exists."""
    if not _jax_ready():
        return 0
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def _process_count() -> int:
    if not _jax_ready():
        return 1
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def _fingerprint() -> Dict[str, Any]:
    """Host/device identity for the perf ledger's baseline matching
    (obs.ledger): a step-time number is only comparable against a run on
    the same host AND the same accelerator. Device fields are None on
    jax-free entries and before the backend is up (_jax_ready guard —
    fingerprinting must never initialize a backend either)."""
    import socket

    fp: Dict[str, Any] = {
        "host": socket.gethostname(),
        "platform": sys.platform,
        "backend": None,
        "device_kind": None,
        "devices": 0,
    }
    if _jax_ready():
        try:
            import jax

            fp["backend"] = jax.default_backend()
            devs = jax.local_devices()
            fp["devices"] = len(devs)
            if devs:
                fp["device_kind"] = devs[0].device_kind
        except Exception:
            pass
    return fp


class RunTelemetry:
    """One run = one instance = one telemetry directory.

    Usage (the CLI pattern)::

        tel = RunTelemetry(dir, entry="fit", heartbeat_s=args.heartbeat_s,
                           quiet=args.quiet)
        with tel:                       # install() + finalize() on exit
            ... run ...
            tel.set_final({"llh": ...})

    Artifacts: `events.jsonl` (primary process only) and `run_report.json`
    (primary) / `run_report.p<i>.json` (others — merged by obs.report at
    render time, no cross-process synchronization needed).
    """

    def __init__(
        self,
        directory: str,
        entry: str = "",
        run_id: Optional[str] = None,
        heartbeat_s: float = 0.0,
        quiet: bool = False,
        device_memory: bool = True,
        auto_gate: bool = True,
        heartbeat_escalate: int = 0,
        ledger_path: Optional[str] = None,
    ):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.entry = entry
        # explicit perf-ledger target (cli --perf-ledger); falls back to
        # the BIGCLAM_PERF_LEDGER env at finalize (obs.ledger)
        self.ledger_path = ledger_path
        self.run_id = run_id or _resolve_run_id(directory)
        self.quiet = quiet
        self.device_memory = device_memory
        self.auto_gate = auto_gate
        self.started_unix = time.time()
        self._t0 = time.perf_counter()
        self._lock = threading.RLock()
        self._fh: Optional[TextIO] = None
        self._gated = False
        self._pending: List[str] = []
        self._finalized = False
        self.event_counts: Dict[str, int] = {}
        self.stage_seconds: Dict[str, float] = {}
        self.stage_counts: Dict[str, int] = {}
        # span sinks (obs.trace): per-path running totals — the run
        # report's span table and the perf ledger read these
        self.span_seconds: Dict[str, float] = {}
        self.span_counts: Dict[str, int] = {}
        self.span_orphans = 0
        # per-step wall-clock samples (sec_per_iter / eps forwarded by the
        # MetricsLogger sink) — the ledger's step_p50/p99 source
        self._step_secs: List[float] = []
        self._step_eps: List[float] = []
        # model-health (ISSUE 8): last `health` event payload + per-check
        # anomaly counts, folded in by event() — the heartbeat embeds
        # last_health into stall reports (a stall then says "diverging",
        # not just "silent"), the run report grows a health section, and
        # the perf ledger reads the final grad norm from it
        self.last_health: Optional[Dict[str, Any]] = None
        self.health_samples = 0
        self.anomaly_counts: Dict[str, int] = {}
        # collective-traffic accounting (obs.comms, ISSUE 10): modeled
        # bytes/step per collective site, keyed per MODEL so a re-emitted
        # model (reset_model on its first event) replaces its whole site
        # set — the sparse cap refinement can flip the collective mode,
        # and a stale site from the abandoned layout must not keep
        # inflating the total. Plus the last fit-loop sync-span duration
        # (span_complete tracks it) so heartbeat stall reports can say
        # whether the run died WAITING on the gang or computing.
        self._comms_by_model: Dict[str, Dict[str, float]] = {}
        self.last_sync_s: Optional[float] = None
        # memory accounting (obs.memory, ISSUE 12): modeled per-device
        # HBM buffers and per-host RSS stages, keyed per MODEL with the
        # same reset_model replace-the-whole-set contract as comms (a
        # quality/rollback rebuild re-emits; stale buffers must not
        # inflate the total). Values are (bytes, category) pairs so the
        # report can split addressable (state+graph) from scratch/
        # transient/collective.
        self._mem_by_model: Dict[str, Dict[str, tuple]] = {}
        self._mem_host_by_model: Dict[str, Dict[str, float]] = {}
        self._mem_host_dominant: Optional[str] = None
        # tag -> number of watermark samples; dev -> running max stats
        self.watermark_tags: Dict[str, int] = {}
        self.device_peak: Dict[str, Dict[str, Optional[int]]] = {}
        self.compiles = {
            "backend_compiles": 0,
            "backend_compile_s": 0.0,
            "retraces": 0,
            "by_key": {},
            "step_builds": 0,
            "monitor": False,
        }
        self._compile_key = ""
        self.final: Dict[str, Any] = {}
        self.heartbeat = None
        if heartbeat_s and heartbeat_s > 0:
            from bigclam_tpu.obs.heartbeat import Heartbeat

            self.heartbeat = Heartbeat(
                self, heartbeat_s, echo=not quiet,
                escalate_after=heartbeat_escalate,
            ).start()
        if device_memory or _jax_loaded():
            self.compiles["monitor"] = _ensure_monitor()
        self.event("start", entry=entry)

    # ------------------------------------------------------------- events
    def event(self, kind: str, **fields) -> None:
        """Append one schema event (obs.schema). Thread-safe; buffered
        until the primary gate is committed (see class docstring)."""
        elapsed = time.perf_counter() - self._t0
        rec = {
            "v": SCHEMA_VERSION,
            "run": self.run_id,
            "pid": _process_index(),
            "t": round(elapsed, 4),
            # wall clock for external correlation; elapsed_s (monotonic)
            # is the ordering/duration field — obs.report never computes a
            # duration from ts, so a mid-run clock jump cannot corrupt
            # stage timings (ISSUE 6 satellite)
            "ts": round(time.time(), 3),
            "elapsed_s": round(elapsed, 6),
            "kind": kind,
            **fields,
        }
        try:
            line = json.dumps(rec, default=_json_default, allow_nan=False)
        except ValueError:       # a non-finite float somewhere in fields
            line = json.dumps(
                _finite_safe(rec), default=_json_default, allow_nan=False
            )
        with self._lock:
            self.event_counts[kind] = self.event_counts.get(kind, 0) + 1
            if kind == "health":
                self.last_health = dict(fields)
                self.health_samples += 1
            elif kind == "anomaly":
                check = str(fields.get("check", "?"))
                self.anomaly_counts[check] = (
                    self.anomaly_counts.get(check, 0) + 1
                )
            elif kind == "comms":
                model = str(fields.get("model", "?"))
                if fields.get("reset_model"):
                    self._comms_by_model[model] = {}
                sites = self._comms_by_model.setdefault(model, {})
                try:
                    sites[str(fields.get("site", "?"))] = float(
                        fields.get("bytes_per_step", 0.0) or 0.0
                    )
                except (TypeError, ValueError):
                    pass
            elif kind == "memory_model":
                model = str(fields.get("model", "?"))
                host_scope = fields.get("scope") == "host"
                target = (
                    self._mem_host_by_model
                    if host_scope
                    else self._mem_by_model
                )
                if fields.get("reset_model"):
                    target[model] = {}
                bufs = target.setdefault(model, {})
                try:
                    b = float(fields.get("bytes", 0.0) or 0.0)
                    name = str(fields.get("buffer", "?"))
                    if host_scope:
                        bufs[name] = b
                        if fields.get("dominant"):
                            self._mem_host_dominant = str(
                                fields.get("stage", name)
                            )
                    else:
                        bufs[name] = (
                            b, str(fields.get("category", ""))
                        )
                except (TypeError, ValueError):
                    pass
            if not self._gated:
                if self.auto_gate:
                    self._commit_gate_locked()
                else:
                    self._pending.append(line)
                    return
            if self._fh is not None:
                self._fh.write(line + "\n")
                self._fh.flush()

    def commit_gate(self) -> None:
        """Decide the single-writer gate NOW (call once jax.distributed
        membership is known); flushes buffered events. Idempotent."""
        with self._lock:
            self._commit_gate_locked()

    def _commit_gate_locked(self) -> None:
        if self._gated:
            return
        self._gated = True
        if _process_index() == 0:
            self._fh = open(os.path.join(self.directory, EVENTS_NAME), "a")
            for line in self._pending:
                self._fh.write(line + "\n")
            self._fh.flush()
        self._pending = []

    # -------------------------------------------------------------- sinks
    def stage_complete(self, name: str, seconds: float) -> None:
        """StageProfile sink: stage wall-clock + a memory watermark at the
        stage boundary + a heartbeat beat."""
        with self._lock:
            self.stage_seconds[name] = (
                self.stage_seconds.get(name, 0.0) + seconds
            )
            self.stage_counts[name] = self.stage_counts.get(name, 0) + 1
        self.event("stage", name=name, seconds=round(seconds, 4))
        self.watermark(f"stage:{name}")
        if self.heartbeat is not None:
            self.heartbeat.beat(stage=name)

    def metric_record(self, record: Dict[str, Any]) -> None:
        """MetricsLogger sink: per-step records land as `step` events,
        other records (sweep per-K lines) as `metric`. The logger's own
        relative "t" / wall "ts" are dropped — telemetry stamps its own.
        Per-step timings (sec_per_iter, edges/sec) are additionally folded
        into the run's step-time distribution — the perf ledger's
        step_p50/p99 source (obs.ledger)."""
        fields = {k: v for k, v in record.items() if k not in ("t", "ts")}
        kind = "step" if "iter" in fields else "metric"
        if kind == "step":
            sec = fields.get("sec_per_iter")
            eps = fields.get("edges_per_sec_per_chip")
            with self._lock:
                if isinstance(sec, (int, float)):
                    self._step_secs.append(float(sec))
                if isinstance(eps, (int, float)):
                    self._step_eps.append(float(eps))
        self.event(kind, **fields)

    def span_complete(
        self,
        path: str,
        seconds: float,
        ok: bool = True,
        emit: bool = True,
        fields: Optional[Dict[str, Any]] = None,
        orphans: int = 0,
    ) -> None:
        """obs.trace sink: fold one closed span into the per-path totals
        and (emit=True) write its `span` event. Must stay cheap — the fit
        loop closes several emit=False spans per iteration."""
        with self._lock:
            self.span_seconds[path] = (
                self.span_seconds.get(path, 0.0) + seconds
            )
            self.span_counts[path] = self.span_counts.get(path, 0) + 1
            if orphans:
                self.span_orphans += orphans
            if path.endswith("fit_loop/sync"):
                # last collective-wait duration, for stall context (one
                # suffix check per span close — rides the <2% pin)
                self.last_sync_s = round(seconds, 6)
        if emit:
            payload = dict(fields) if fields else {}
            if not ok:
                payload["ok"] = False
            self.event(
                "span",
                name=path.rsplit("/", 1)[-1],
                path=path,
                seconds=round(seconds, 6),
                **payload,
            )

    def step_beat(self, it: int, llh: float) -> None:
        """Fit-loop heartbeat hook (run_fit_loop): progress only, no event
        — step events arrive via the MetricsLogger sink when one is wired."""
        if self.heartbeat is not None:
            self.heartbeat.beat(iter=int(it), llh=float(llh))

    # ------------------------------------------------------------- memory
    def device_memory_snapshot(self) -> List[dict]:
        """Per-device memory_stats right now; [] when device telemetry is
        off or no jax backend is up yet (_jax_ready — sampling must never
        INITIALIZE a backend: a pre-distributed-init sample would poison
        jax.distributed.initialize, and there is nothing on any device to
        measure before the backend exists anyway). CPU backends report
        null stats (their allocator does not track — the shape of the
        record survives so TPU runs and tests share one schema)."""
        if not (self.device_memory and _jax_ready()):
            return []
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return []
        out = []
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            stats = stats or {}
            out.append(
                {
                    "device": str(d),
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
            )
        return out

    def watermark(self, tag: str) -> List[dict]:
        """Sample device memory, fold into the per-device running peaks,
        and emit a `memory` event. Called at stage boundaries (the sink)
        and explicitly after big placements (model build, edge upload)."""
        devices = self.sample_device_peak(tag)
        if not devices:
            return []
        self.event("memory", tag=tag, devices=devices)
        return devices

    def sample_device_peak(self, tag: str) -> List[dict]:
        """Fold one device-memory sample into the running per-device
        peaks WITHOUT emitting an event. The heartbeat calls this on its
        poll cadence (ISSUE 12 fix): stage-boundary-only sampling made a
        peak INSIDE a long fit stage invisible — the running max now
        sees intra-stage transients too, without flooding the event log
        at the poll rate (stalls still carry full snapshots)."""
        devices = self.device_memory_snapshot()
        if not devices:
            return []
        with self._lock:
            self.watermark_tags[tag] = self.watermark_tags.get(tag, 0) + 1
            for d in devices:
                peak = self.device_peak.setdefault(
                    d["device"],
                    {"bytes_in_use": None, "peak_bytes_in_use": None,
                     "bytes_limit": d["bytes_limit"]},
                )
                for key in ("bytes_in_use", "peak_bytes_in_use"):
                    v = d[key]
                    if v is not None and (
                        peak[key] is None or v > peak[key]
                    ):
                        peak[key] = v
        return devices

    def hbm_modeled_bytes(self) -> Optional[float]:
        """Total modeled per-device HBM over the emitted memory models
        (obs.memory), or None when no trainer baked one — the figure
        heartbeat stall events embed next to the measured device
        snapshot, and the watch headroom line reads."""
        with self._lock:
            if not self._mem_by_model:
                return None
            return round(
                sum(
                    b for bufs in self._mem_by_model.values()
                    for b, _cat in bufs.values()
                ),
                1,
            )

    # ------------------------------------------------------------ compile
    def record_step_build(self, key: str) -> None:
        with self._lock:
            self.compiles["step_builds"] += 1
            by = self.compiles["by_key"]
            entry = by.setdefault(key, {"builds": 0, "compiles": 0})
            entry["builds"] += 1
            self._compile_key = key

    def _compile_observed(self, name: str, secs: float) -> None:
        with self._lock:
            if name.endswith("backend_compile_duration"):
                self.compiles["backend_compiles"] += 1
                self.compiles["backend_compile_s"] = round(
                    self.compiles["backend_compile_s"] + secs, 4
                )
                key = self._compile_key
                if key:
                    self.compiles["by_key"].setdefault(
                        key, {"builds": 0, "compiles": 0}
                    )["compiles"] += 1
            elif name.endswith("jaxpr_trace_duration"):
                self.compiles["retraces"] += 1
                return          # traces are counted, not event-logged
            else:
                return          # lowering etc. ride the backend count
        self.event(
            "compile",
            name=name.rsplit("/", 1)[-1],
            seconds=round(secs, 4),
            key=self._compile_key,
        )

    def compile_count(self) -> int:
        """The headline compile counter: real XLA backend compiles when the
        monitoring listener is live, step builds otherwise."""
        if self.compiles["monitor"]:
            return self.compiles["backend_compiles"]
        return self.compiles["step_builds"]

    # ------------------------------------------------------------- report
    def set_final(self, outcome: Dict[str, Any]) -> None:
        """Entry-point outcome embedded in the run report (fit LLH, sweep
        chosen K, ingest stats, ...)."""
        self.final.update(outcome)

    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "v": SCHEMA_VERSION,
                "run": self.run_id,
                "pid": _process_index(),
                "processes": _process_count(),
                "entry": self.entry,
                "started_unix": round(self.started_unix, 3),
                "wall_s": round(time.perf_counter() - self._t0, 3),
                "stages": {
                    "seconds": {
                        k: round(v, 3)
                        for k, v in self.stage_seconds.items()
                    },
                    "counts": dict(self.stage_counts),
                },
                "spans": {
                    "seconds": {
                        k: round(v, 4)
                        for k, v in self.span_seconds.items()
                    },
                    "counts": dict(self.span_counts),
                    "orphans": self.span_orphans,
                },
                "steps_timed": len(self._step_secs),
                "health": {
                    "samples": self.health_samples,
                    "last": (
                        dict(self.last_health)
                        if self.last_health is not None
                        else None
                    ),
                    "anomalies": dict(self.anomaly_counts),
                },
                "comms": {
                    "bytes_per_step": round(
                        sum(
                            v
                            for sites in self._comms_by_model.values()
                            for v in sites.values()
                        ),
                        1,
                    ),
                    "sites": {
                        k: round(v, 1)
                        for sites in self._comms_by_model.values()
                        for k, v in sites.items()
                    },
                },
                "fingerprint": _fingerprint(),
                "memory": {
                    "host_rss_bytes": current_rss_bytes(),
                    "host_rss_peak_bytes": peak_rss_bytes(),
                    "device_peak": {
                        k: dict(v) for k, v in self.device_peak.items()
                    },
                    "watermark_tags": dict(self.watermark_tags),
                    # static memory model (obs.memory, ISSUE 12): the
                    # modeled per-device HBM buffers + per-host RSS
                    # stages the trainer builds emitted — the perf
                    # ledger's hbm_modeled_bytes / host_rss_modeled_
                    # bytes source, rendered by `cli report`
                    "modeled": self._memory_modeled_locked(),
                },
                "compiles": {
                    **{k: v for k, v in self.compiles.items()},
                    "by_key": {
                        k: dict(v)
                        for k, v in self.compiles["by_key"].items()
                    },
                    "count": self.compile_count(),
                },
                "heartbeat": {
                    "deadline_s": (
                        self.heartbeat.deadline_s
                        if self.heartbeat is not None
                        else None
                    ),
                    "stalls": (
                        self.heartbeat.stalls
                        if self.heartbeat is not None
                        else 0
                    ),
                    "escalations": (
                        self.heartbeat.escalations
                        if self.heartbeat is not None
                        else 0
                    ),
                },
                "events": dict(self.event_counts),
                "final": dict(self.final),
            }

    def _memory_modeled_locked(self) -> Optional[Dict[str, Any]]:
        """The memory-model summary for the run report (caller holds the
        lock via report()): per-buffer/per-category device totals summed
        over emitted models (reset_model replaced stale sets already)
        and the host-stage table. None when no model was emitted."""
        if not self._mem_by_model and not self._mem_host_by_model:
            return None
        buffers: Dict[str, float] = {}
        by_cat: Dict[str, float] = {}
        addressable = 0.0
        for bufs in self._mem_by_model.values():
            for name, (b, cat) in bufs.items():
                buffers[name] = round(buffers.get(name, 0.0) + b, 1)
                by_cat[cat] = by_cat.get(cat, 0.0) + b
                if cat in ("state", "graph"):
                    addressable += b
        host_stages: Dict[str, float] = {}
        for stages in self._mem_host_by_model.values():
            for name, b in stages.items():
                stage = name.split("/", 1)[-1]
                host_stages[stage] = round(
                    max(host_stages.get(stage, 0.0), b), 1
                )
        return {
            "hbm_bytes_per_device": round(sum(by_cat.values()), 1),
            "addressable_bytes": round(addressable, 1),
            "by_category": {k: round(v, 1) for k, v in by_cat.items()},
            "buffers": buffers,
            "host_stages": host_stages,
            "host_rss_bytes": (
                round(max(host_stages.values()), 1)
                if host_stages
                else None
            ),
            "host_dominant_stage": self._mem_host_dominant,
        }

    def report_path(self) -> str:
        pid = _process_index()
        name = REPORT_NAME if pid == 0 else f"run_report.p{pid}.json"
        return os.path.join(self.directory, name)

    def finalize(self) -> Dict[str, Any]:
        """Stop the heartbeat, take a last watermark, emit `end`, write
        this process's run report, close the log. Idempotent."""
        with self._lock:
            if self._finalized:
                return self.report()
            self._finalized = True
        if self.heartbeat is not None:
            self.heartbeat.stop()
        self.watermark("final")
        self.event(
            "end", wall_s=round(time.perf_counter() - self._t0, 3)
        )
        self.commit_gate()        # a run with zero primary events still
        rep = self.report()       # gets its report written
        tmp = self.report_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_finite_safe(rep), f, indent=1, sort_keys=True,
                      default=_json_default, allow_nan=False)
        os.replace(tmp, self.report_path())
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        # perf ledger (obs.ledger): with BIGCLAM_PERF_LEDGER set, every
        # finished run appends its compact perf record — the trajectory
        # `cli perf diff` gates against. Never allowed to break finalize.
        try:
            from bigclam_tpu.obs import ledger as _ledger

            with self._lock:
                step_secs = list(self._step_secs)
                step_eps = list(self._step_eps)
            _ledger.maybe_append_env(
                rep, step_secs, step_eps, path=self.ledger_path
            )
        except Exception as e:
            if not self.quiet:
                print(
                    f"[telemetry] warning: perf-ledger append failed "
                    f"({type(e).__name__}: {e}) — run report is intact, "
                    f"but `cli perf diff` will not see this run",
                    file=sys.stderr,
                )
        return rep

    # ------------------------------------------------------- context mgmt
    def __enter__(self) -> "RunTelemetry":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        try:
            self.finalize()
        finally:
            uninstall(self)
