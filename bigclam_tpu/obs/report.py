"""Render a telemetry directory human-readable (`cli report`) and merge
per-process run reports.

A multi-controller run leaves `run_report.json` (process 0) plus
`run_report.p<i>.json` siblings — each written independently at finalize,
with no cross-process synchronization. Merging happens HERE, at read time:
stage seconds are reported per process (wall-clock buckets across
processes do not add — every process spans the same wall time), device
peaks union (each process only sees its own addressable devices), and
compile counts sum (each process compiles its own executables).
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from bigclam_tpu.obs.schema import summarize_kinds, validate_events_file
from bigclam_tpu.obs.telemetry import EVENTS_NAME, REPORT_NAME


def _report_pid(path: str) -> int:
    """NUMERIC pid from a run_report filename: lexical sort put p10 before
    p2, which scrambled merge order (and any per-pid rendering) past nine
    processes."""
    base = os.path.basename(path)
    if base == REPORT_NAME:
        return 0
    m = re.match(r"run_report\.p(\d+)\.json$", base)
    return int(m.group(1)) if m else 1 << 30


def _pid_key(pid: str) -> int:
    """Same numeric ordering for the string pid keys of the merged
    per-pid dicts (p2 before p10)."""
    return int(pid) if pid.isdigit() else 1 << 30


def load_reports(directory: str) -> List[dict]:
    """Every run_report*.json in the dir, primary first then by NUMERIC
    pid (p2 before p10)."""
    paths = sorted(
        glob.glob(os.path.join(directory, "run_report*.json")),
        key=lambda p: (_report_pid(p), p),
    )
    out = []
    for p in paths:
        with open(p) as f:
            out.append(json.load(f))
    return out


def _event_order(e: dict) -> float:
    """Merge-order key: the MONOTONIC elapsed_s. The `t` fallback is
    defensive, for malformed lines missing it (schema validation still
    reports those — v1 logs are rejected, not silently read; pinned by
    test). Never the wall-clock `ts` — a clock jump must not reorder the
    timeline."""
    v = e.get("elapsed_s", e.get("t", 0.0))
    return v if isinstance(v, (int, float)) else 0.0


def load_events(directory: str) -> Optional[List[dict]]:
    """events.jsonl decoded and STABLY ordered by elapsed_s: the heartbeat
    thread and the main thread stamp their events before taking the write
    lock, so adjacent lines can land microseconds out of order — the
    stable sort repairs that while preserving file order for equal
    timestamps (multi-writer interleave contract, tested)."""
    path = os.path.join(directory, EVENTS_NAME)
    if not os.path.exists(path):
        return None
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    events.append(json.loads(line))
                except ValueError:
                    events.append({"kind": "?", "unparsed": line[:80]})
    # events without a numeric elapsed_s — the "?" placeholders for
    # corrupt lines, exactly the ones whose FILE position is the evidence
    # — inherit the previous event's key so they stay next to their
    # neighbors; the stable sort then only repairs real out-of-order
    # stamps (heartbeat-thread interleave)
    last = 0.0
    keyed = []
    for e in events:
        v = e.get("elapsed_s", e.get("t"))
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            last = float(v)
        keyed.append((last, e))
    keyed.sort(key=lambda kv: kv[0])    # stable: ties keep file order
    return [e for _, e in keyed]


def run_duration_s(events: List[dict]) -> Optional[float]:
    """Run duration from MONOTONIC event times (first -> last elapsed_s).
    The report quotes this instead of subtracting wall clocks, so an NTP
    step mid-run cannot corrupt the figure (ISSUE 6 satellite)."""
    stamped = [
        _event_order(e)
        for e in events
        if isinstance(e.get("elapsed_s", e.get("t")), (int, float))
    ]
    if not stamped:
        return None
    return max(stamped) - min(stamped)


def span_coverage(report: dict) -> Optional[float]:
    """Fraction of the run's wall time attributed by TOP-LEVEL spans
    (paths without a '/'): children re-count their parents' time, so only
    depth-0 spans sum against the wall. The telemetry smoke gates this at
    >= 0.95 — unattributed time is the regression this layer exists to
    prevent."""
    spans = (report.get("spans", {}) or {}).get("seconds", {}) or {}
    wall = report.get("wall_s")
    if not wall:
        return None
    top = sum(v for k, v in spans.items() if "/" not in k)
    return top / float(wall)


def merge_reports(reports: List[dict]) -> dict:
    """One cross-process view of a run (see module docstring for the
    per-field merge rules)."""
    if not reports:
        return {}
    merged = {
        "run": reports[0].get("run"),
        "entry": reports[0].get("entry"),
        "processes_reported": len(reports),
        "processes_expected": max(
            int(r.get("processes", 1) or 1) for r in reports
        ),
        "wall_s": max(float(r.get("wall_s", 0.0)) for r in reports),
        "stages_by_pid": {
            str(r.get("pid", "?")): r.get("stages", {}).get("seconds", {})
            for r in reports
        },
        "spans_by_pid": {
            str(r.get("pid", "?")): r.get("spans", {}).get("seconds", {})
            for r in reports
        },
        "span_orphans": sum(
            int(r.get("spans", {}).get("orphans", 0)) for r in reports
        ),
        "stalls": sum(
            int(r.get("heartbeat", {}).get("stalls", 0)) for r in reports
        ),
        "final": reports[0].get("final", {}),
    }
    # model health (ISSUE 8): sample counts and anomaly tallies SUM
    # across processes (each process's monitor watches the same global
    # optimizer state, but only its own report records what it saw); the
    # last-snapshot payload is the primary's (its events.jsonl carries
    # the authoritative event stream)
    health = {
        "samples": sum(
            int((r.get("health", {}) or {}).get("samples", 0))
            for r in reports
        ),
        "last": (reports[0].get("health", {}) or {}).get("last"),
        "anomalies": {},
    }
    for r in reports:
        for check, n in ((r.get("health", {}) or {}).get(
            "anomalies", {}
        ) or {}).items():
            health["anomalies"][check] = (
                health["anomalies"].get(check, 0) + int(n)
            )
    merged["health"] = health
    # collective-traffic accounting (obs.comms, ISSUE 10): the primary's
    # modeled bytes/step table (every process compiles the same SPMD
    # step, so the models agree; the primary's event log is
    # authoritative), plus the per-pid fit-loop sync totals — the raw
    # signal behind the straggler detector (a host everyone waits on has
    # the SMALLEST sync total; its peers' balloon)
    merged["comms"] = next(
        (
            r.get("comms")
            for r in reports
            if (r.get("comms", {}) or {}).get("sites")
        ),
        None,
    )
    # memory model (obs.memory, ISSUE 12): every process bakes the same
    # SPMD step, so the models agree — the first report carrying one is
    # authoritative (same rule as comms)
    merged["memory_model"] = next(
        (
            (r.get("memory", {}) or {}).get("modeled")
            for r in reports
            if (r.get("memory", {}) or {}).get("modeled")
        ),
        None,
    )
    from bigclam_tpu.obs.comms import sync_seconds

    sync_by_pid = {}
    for r in reports:
        s = sync_seconds(r)
        if s > 0:
            sync_by_pid[str(r.get("pid", "?"))] = round(s, 4)
    merged["sync_by_pid"] = sync_by_pid
    device_peak: Dict[str, dict] = {}
    compiles = {"count": 0, "backend_compiles": 0, "step_builds": 0,
                "backend_compile_s": 0.0, "by_key": {}}
    events: Dict[str, int] = {}
    for r in reports:
        for dev, stats in r.get("memory", {}).get("device_peak", {}).items():
            seen = device_peak.setdefault(dev, dict(stats))
            for key in ("bytes_in_use", "peak_bytes_in_use"):
                v = stats.get(key)
                if v is not None and (
                    seen.get(key) is None or v > seen[key]
                ):
                    seen[key] = v
        comp = r.get("compiles", {})
        for key in ("count", "backend_compiles", "step_builds"):
            compiles[key] += int(comp.get(key, 0))
        compiles["backend_compile_s"] = round(
            compiles["backend_compile_s"]
            + float(comp.get("backend_compile_s", 0.0)),
            4,
        )
        for key, stats in comp.get("by_key", {}).items():
            agg = compiles["by_key"].setdefault(
                key, {"builds": 0, "compiles": 0}
            )
            agg["builds"] += int(stats.get("builds", 0))
            agg["compiles"] += int(stats.get("compiles", 0))
        for kind, n in r.get("events", {}).items():
            events[kind] = events.get(kind, 0) + int(n)
    merged["device_peak"] = device_peak
    merged["compiles"] = compiles
    merged["events"] = events
    return merged


def _load_lineage(directory: str) -> List[dict]:
    """resume_lineage.json written by resilience.supervisor.record_resume
    (--resume auto); [] when absent/unreadable."""
    try:
        with open(os.path.join(directory, "resume_lineage.json")) as f:
            out = json.load(f)
        return out if isinstance(out, list) else []
    except (OSError, ValueError):
        return []


def _fmt_bytes(v: Optional[float]) -> str:
    """The ONE byte formatter of the obs rendering layer (report, watch,
    and obs.memory's preflight all import it — two formatters for the
    same quantities would drift)."""
    if v is None:
        return "-"
    v = float(v)
    if v >= 1 << 30:
        return f"{v / (1 << 30):.2f} GiB"
    if v >= 1 << 20:
        return f"{v / (1 << 20):.1f} MiB"
    if v >= 1 << 10:
        return f"{v / (1 << 10):.1f} KiB"
    return f"{v:.0f} B"


def render_json(directory: str) -> Tuple[dict, int]:
    """(machine-readable report object, error count) — `cli report
    --json` for CI consumption (ISSUE 8 satellite). Same inputs and the
    same error accounting as render(), so the exit-code contract is
    unchanged: errors > 0 ⇔ nonzero exit, anomalies/stalls are findings.
    The object is strict JSON (no NaN/Infinity: events already went
    through telemetry's _finite_safe at write time, and the merged
    reports were serialized the same way)."""
    reports = load_reports(directory)
    events = load_events(directory)
    if not reports and events is None:
        return {"directory": directory, "error": "no telemetry artifacts",
                "errors": 1}, 1
    errors = 0
    merged = merge_reports(reports)
    if merged and merged["processes_reported"] < merged["processes_expected"]:
        errors += 1
    if merged and merged["events"].get("gave_up", 0):
        errors += 1
    if merged and merged.get("span_orphans"):
        errors += 1
    schema_errors: List[str] = []
    n_events = 0
    if events is not None:
        n_events, schema_errors = validate_events_file(
            os.path.join(directory, EVENTS_NAME)
        )
        errors += len(schema_errors)
    anomalies = [
        {k: v for k, v in e.items()
         if k not in ("v", "run", "pid", "t", "ts")}
        for e in (events or [])
        if e.get("kind") == "anomaly"
    ]
    # report-time host-skew findings (obs.comms, ISSUE 10): stragglers
    # are only visible ACROSS the per-process reports, so they cannot be
    # events — they join the anomalies list here, tagged with their
    # source. Findings, never exit-code errors (same contract as the
    # event-sourced anomalies).
    from bigclam_tpu.obs.comms import detect_host_skew

    anomalies.extend(
        {**f, "source": "report"} for f in detect_host_skew(reports)
    )
    recovery_kinds = (
        "retry", "recovered", "gave_up", "rollback", "quarantine",
        "resume", "fault_injected", "stall_escalated",
    )
    out = {
        "directory": directory,
        "merged": merged,
        "events": {
            "count": n_events,
            "kinds": summarize_kinds(events or []),
            "duration_s": run_duration_s(events or []),
        },
        "health": (merged or {}).get("health", {}),
        # resolved edge-kernel paths (ISSUE 13): one entry per trainer
        # build — CI can refuse a run whose path silently fell back
        "kernel_paths": [
            {
                "model": e.get("model"),
                "path": e.get("path"),
                "reason": e.get("reason", ""),
            }
            for e in (events or [])
            if e.get("kind") == "model_build" and e.get("path")
        ],
        "comms": (merged or {}).get("comms"),
        "memory_model": (merged or {}).get("memory_model"),
        "sync_by_pid": (merged or {}).get("sync_by_pid", {}),
        "anomalies": anomalies,
        "recovery": {
            k: (merged or {}).get("events", {}).get(k, 0)
            for k in recovery_kinds
            if (merged or {}).get("events", {}).get(k, 0)
        },
        "resume_lineage": _load_lineage(directory),
        "schema_errors": schema_errors[:50],
        "errors": errors,
    }
    return out, errors


def render(directory: str) -> Tuple[str, int]:
    """(human-readable report text, error count). Errors are schema
    violations in events.jsonl plus a missing-artifact note; the CLI maps
    error count > 0 to a nonzero exit so CI can gate on a telemetry dir."""
    lines: List[str] = []
    errors = 0
    reports = load_reports(directory)
    events = load_events(directory)
    if not reports and events is None:
        return f"{directory}: no telemetry artifacts found", 1

    merged = merge_reports(reports)
    if merged:
        lines.append(
            f"run {merged['run']}  entry={merged['entry']}  "
            f"wall {merged['wall_s']:.1f}s  "
            f"processes {merged['processes_reported']}"
            f"/{merged['processes_expected']}"
        )
        if merged["processes_reported"] < merged["processes_expected"]:
            errors += 1
            lines.append(
                "  WARNING: fewer per-process reports than processes — "
                "a process died before finalize"
            )
        # --- resolved edge-kernel paths (ISSUE 13 satellite): every
        # trainer build states which implementation compiled (fused /
        # split / xla) and WHY a fallback fell back — a silent XLA
        # fallback must be visible here, not only on a stderr line
        # nobody watched
        builds = [
            e for e in (events or [])
            if e.get("kind") == "model_build" and e.get("path")
        ]
        if builds:
            lines.append("")
            lines.append("kernel paths (model builds):")
            for e in builds:
                why = f"  ({e['reason']})" if e.get("reason") else ""
                lines.append(
                    f"  {e.get('model', '?'):<28} {e['path']}{why}"
                )
        lines.append("")
        lines.append("stage seconds (per process):")
        for pid, stages in sorted(
            merged["stages_by_pid"].items(), key=lambda kv: _pid_key(kv[0])
        ):
            if not stages:
                lines.append(f"  p{pid}: (none)")
                continue
            total = sum(stages.values())
            lines.append(f"  p{pid}: total {total:.1f}s")
            for name, secs in sorted(
                stages.items(), key=lambda kv: -kv[1]
            ):
                pct = 100.0 * secs / total if total else 0.0
                lines.append(f"    {name:<20} {secs:>9.2f}s  {pct:5.1f}%")
        # --- per-span time breakdown (obs.trace, ISSUE 6): hierarchical
        # attribution; only TOP-LEVEL spans sum against the wall (children
        # re-count their parents), and the coverage line says how much of
        # the run the taxonomy attributed at all.
        for pid, spans in sorted(
            merged["spans_by_pid"].items(), key=lambda kv: _pid_key(kv[0])
        ):
            if not spans:
                continue
            rep_for_pid = next(
                (r for r in reports if str(r.get("pid", "?")) == pid),
                reports[0],
            )
            counts = rep_for_pid.get("spans", {}).get("counts", {})
            lines.append("")
            lines.append(f"span breakdown (p{pid}):")
            top_total = sum(v for k, v in spans.items() if "/" not in k)
            for path in sorted(
                spans, key=lambda p: (p.split("/")[0], p)
            ):
                depth = path.count("/")
                secs = spans[path]
                pct = (
                    100.0 * secs / top_total
                    if depth == 0 and top_total
                    else None
                )
                name = path.rsplit("/", 1)[-1]
                lines.append(
                    f"  {'  ' * depth}{name:<{max(24 - 2 * depth, 4)}}"
                    f" {secs:>10.3f}s"
                    + (f"  {pct:5.1f}%" if pct is not None else "       ")
                    + f"  x{counts.get(path, 0)}"
                )
            wall = float(rep_for_pid.get("wall_s", 0.0) or 0.0)
            if wall:
                lines.append(
                    f"  top-level spans cover {top_total:.1f}s = "
                    f"{100.0 * top_total / wall:.1f}% of wall {wall:.1f}s"
                )
        if merged.get("span_orphans"):
            errors += 1
            lines.append(
                f"  SPAN ORPHANS: {merged['span_orphans']} span(s) were "
                "abandoned without close (tracer repaired the stack)"
            )

        lines.append("")
        lines.append("device memory watermarks (max over samples):")
        if merged["device_peak"]:
            for dev, stats in sorted(merged["device_peak"].items()):
                lines.append(
                    f"  {dev:<24} in_use {_fmt_bytes(stats.get('bytes_in_use')):>10}  "
                    f"peak {_fmt_bytes(stats.get('peak_bytes_in_use')):>10}  "
                    f"limit {_fmt_bytes(stats.get('bytes_limit')):>10}"
                )
        else:
            lines.append(
                "  (none sampled — CPU backend or device telemetry off)"
            )

        # --- static memory model (obs.memory, ISSUE 12): modeled
        # per-device HBM by component next to the measured watermarks
        # above, and the per-stage host-RSS model with its dominant
        # stage named — the capacity story `cli preflight` predicts,
        # rendered from what the run actually baked.
        mm = merged.get("memory_model") or {}
        if mm.get("buffers"):
            lines.append("")
            lines.append(
                "memory model (per device, modeled): "
                f"{_fmt_bytes(int(mm.get('hbm_bytes_per_device', 0)))}"
                f" ({_fmt_bytes(int(mm.get('addressable_bytes', 0)))}"
                " addressable state+graph)"
            )
            for cat, b in sorted(
                (mm.get("by_category") or {}).items(),
                key=lambda kv: -kv[1],
            ):
                lines.append(f"  {cat:<12} {_fmt_bytes(int(b)):>12}")
            for name, b in sorted(
                (mm.get("buffers") or {}).items(), key=lambda kv: -kv[1]
            )[:8]:
                lines.append(
                    f"    {name:<30} {_fmt_bytes(int(b)):>12}"
                )
            # modeled vs measured headroom, when the allocator reported
            # watermarks (TPU; the CPU fake reports none)
            measured = [
                v for v in (
                    (stats.get("peak_bytes_in_use")
                     or stats.get("bytes_in_use"))
                    for stats in merged["device_peak"].values()
                )
                if isinstance(v, (int, float))
            ]
            if measured:
                peak = max(measured)
                modeled = float(mm.get("hbm_bytes_per_device", 0) or 0)
                lines.append(
                    f"  measured peak {_fmt_bytes(int(peak))} vs "
                    f"modeled {_fmt_bytes(int(modeled))}"
                    + (
                        f" (measured/modeled {peak / modeled:.2f}x)"
                        if modeled
                        else ""
                    )
                )
        if mm.get("host_stages"):
            lines.append("")
            dom = mm.get("host_dominant_stage")
            lines.append(
                "host RSS model (per stage, modeled peak "
                f"{_fmt_bytes(int(mm.get('host_rss_bytes') or 0))}):"
            )
            for stage, b in sorted(
                mm["host_stages"].items(), key=lambda kv: -kv[1]
            ):
                mark = "  <- dominant (host-global O(N*K) F0, " \
                    "ROADMAP 1a)" if stage == dom and stage == "f0_init" \
                    else ("  <- dominant" if stage == dom else "")
                lines.append(
                    f"  {stage:<12} {_fmt_bytes(int(b)):>12}{mark}"
                )
        comp = merged["compiles"]
        lines.append("")
        lines.append(
            f"compiles: {comp['count']} "
            f"(backend {comp['backend_compiles']}, "
            f"{comp['backend_compile_s']:.1f}s; "
            f"step builds {comp['step_builds']})"
        )
        for key, stats in sorted(comp["by_key"].items()):
            lines.append(
                f"  {key:<40} builds {stats['builds']}  "
                f"compiles {stats['compiles']}"
            )
        if merged["stalls"]:
            # stalls are a finding, not a schema error — reported, not
            # counted into the exit code
            lines.append("")
            lines.append(f"STALLS: {merged['stalls']} heartbeat deadline(s) hit")

        # --- collective traffic + host skew (obs.comms, ISSUE 10) ---
        comms = merged.get("comms") or {}
        if comms.get("sites"):
            lines.append("")
            lines.append(
                "collective traffic (modeled): "
                f"{_fmt_bytes(int(comms.get('bytes_per_step', 0)))}"
                f"/step over {len(comms['sites'])} site(s)"
            )
            for site, b in sorted(
                comms["sites"].items(), key=lambda kv: -kv[1]
            )[:10]:
                lines.append(
                    f"  {site:<34} {_fmt_bytes(int(b)):>10}/step"
                )
        sync = merged.get("sync_by_pid") or {}
        if len(sync) >= 2:
            ordered = sorted(sync.items(), key=lambda kv: kv[1])
            (lo_pid, lo_s), (hi_pid, hi_s) = ordered[0], ordered[-1]
            lines.append("")
            lines.append(
                "per-iteration sync totals: "
                + "  ".join(
                    f"p{pid} {s:.2f}s" for pid, s in sorted(
                        sync.items(), key=lambda kv: _pid_key(kv[0])
                    )
                )
                + f"  (skew p{hi_pid}/p{lo_pid} "
                f"{hi_s / max(lo_s, 1e-9):.1f}x)"
            )
        from bigclam_tpu.obs.comms import detect_host_skew

        for f in detect_host_skew(reports):
            # a finding, like the event anomalies — never an exit error
            lines.append(
                f"  STRAGGLER: p{f['pid']} (host {f['host']}) — "
                f"{f['rule']} rule"
                + (
                    f", sync {f['sync_s']}s vs peers "
                    f"{f['peers_sync_s']}s"
                    if f["rule"] == "waiters"
                    else f", unattributed loop time {f['overhead_s']}s "
                    f"vs peers {f['peers_overhead_s']}s"
                )
            )

        # --- recovery history (ISSUE 5): retries, rollbacks, quarantines,
        # injected faults, escalations, resume lineage. A gave_up means the
        # run ENDED in an unrecovered failure: counted into the exit code.
        recovery_kinds = (
            "retry", "recovered", "gave_up", "rollback", "quarantine",
            "resume", "fault_injected", "stall_escalated",
        )
        rec_counts = {
            k: merged["events"].get(k, 0)
            for k in recovery_kinds
            if merged["events"].get(k, 0)
        }
        lineage = _load_lineage(directory)
        if rec_counts or lineage:
            lines.append("")
            lines.append(
                "recovery: "
                + (json.dumps(rec_counts) if rec_counts else "(clean)")
            )
            for e in (events or []):
                kind = e.get("kind")
                if kind == "gave_up":
                    lines.append(
                        f"  GAVE UP at {e.get('site')}: "
                        f"{e.get('attempts')} attempt(s), "
                        f"{e.get('error', '?')}"
                    )
                elif kind == "rollback":
                    lines.append(
                        f"  rollback #{e.get('rollbacks')} at iter "
                        f"{e.get('iter')} -> iter {e.get('resume_iter')} "
                        f"(step_scale {e.get('step_scale')})"
                    )
                elif kind == "quarantine":
                    lines.append(
                        f"  quarantined shard {e.get('shard')} "
                        f"(rebuilt, crc restamped: "
                        f"{e.get('crc_restamped')})"
                    )
            if lineage:
                lines.append(
                    f"  resume lineage: {len(lineage)} resumed attempt(s)"
                )
                for a in lineage:
                    lines.append(
                        f"    attempt {a.get('attempt_id')} run "
                        f"{a.get('run')} resumed at step "
                        f"{a.get('resumed_step')}"
                    )
            if merged["events"].get("gave_up", 0):
                errors += 1
                lines.append(
                    "  ERROR: run ended in gave_up (retry budget exhausted)"
                )

        # --- model health (ISSUE 8): the optimizer's last vital signs +
        # fired anomaly detectors. Anomalies are FINDINGS, not schema
        # errors — they never touch the exit code (gave_up stays the only
        # outcome-level error).
        health = merged.get("health", {}) or {}
        if health.get("samples") or health.get("anomalies"):
            lines.append("")
            lines.append(f"model health: {health.get('samples', 0)} sample(s)")
            last = health.get("last") or {}
            if last:
                parts = []
                for key in (
                    "llh", "grad_norm", "update_norm", "step_eff",
                    "accept_frac", "top_share", "churn", "support_churn",
                    "cap_occupancy",
                ):
                    v = last.get(key)
                    if isinstance(v, (int, float)):
                        parts.append(f"{key} {v:.4g}")
                    elif isinstance(v, str):      # strict-JSON "inf"/"nan"
                        parts.append(f"{key} {v}")
                dead = last.get("dead_comms")
                active = last.get("active_comms")
                if dead is not None and active is not None:
                    parts.append(f"dead {dead}/{int(dead) + int(active)}")
                lines.append(
                    f"  last (iter {last.get('iter', '?')}): "
                    + "  ".join(parts)
                )
            anomalies = health.get("anomalies") or {}
            if anomalies:
                lines.append(
                    "  ANOMALIES: "
                    + ", ".join(
                        f"{check} x{n}" for check, n in sorted(
                            anomalies.items()
                        )
                    )
                )
                for e in (events or []):
                    if e.get("kind") != "anomaly":
                        continue
                    detail = {
                        k: v for k, v in e.items()
                        if k not in ("v", "run", "pid", "t", "ts",
                                     "elapsed_s", "kind", "check", "iter")
                    }
                    lines.append(
                        f"    {e.get('check')} at iter {e.get('iter')}: "
                        + json.dumps(detail)
                    )
            else:
                lines.append("  anomalies: none")
            comm = [
                e for e in (events or []) if e.get("kind") == "sparse_comm"
            ]
            if comm:
                c = comm[-1]
                lines.append(
                    f"  sparse collectives: cap {c.get('comm_cap')} "
                    f"mode {c.get('comm_mode')} "
                    f"(sized from {c.get('touched_per_shard')} touched/"
                    f"shard, K={c.get('k')}, M={c.get('m')}, "
                    f"dp={c.get('dp')})"
                )
                if isinstance(last.get("exchanged_max"), (int, float)):
                    lines.append(
                        f"    exchanged-ids high-water "
                        f"{int(last['exchanged_max'])} of cap "
                        f"{c.get('comm_cap')}"
                    )
        # --- membership serving (ISSUE 14): the query scoreboard of a
        # `cli serve` run — latency percentiles, throughput, cache hit
        # rate, hot-swaps. Figures come from the final outcome (what the
        # perf ledger records); batch/swap counts from the events.
        final = merged.get("final") or {}
        if final.get("serve_queries"):
            lines.append("")
            lines.append(
                f"serving: {final['serve_queries']} queries "
                f"({final.get('serve_errors', 0)} error(s)) "
                f"over {merged['events'].get('serve', 0)} batch(es)"
            )
            parts = []
            for key, label in (
                ("serve_p50_s", "p50"), ("serve_p99_s", "p99"),
            ):
                v = final.get(key)
                if isinstance(v, (int, float)):
                    parts.append(f"{label} {v * 1e3:.3g} ms")
            if isinstance(final.get("serve_qps"), (int, float)):
                parts.append(f"{final['serve_qps']:.4g} qps")
            if isinstance(final.get("cache_hit_rate"), (int, float)):
                parts.append(
                    f"cache hit rate {final['cache_hit_rate']:.2%}"
                )
            if parts:
                lines.append("  " + "  ".join(parts))
            if final.get("serve_mix"):
                lines.append(f"  mix: {final['serve_mix']}")
            swaps = merged["events"].get("snapshot_swap", 0)
            if swaps or final.get("snapshot_swaps"):
                lines.append(
                    f"  hot-swaps: {swaps or final.get('snapshot_swaps')} "
                    f"(serving snapshot step "
                    f"{final.get('snapshot_step', '?')})"
                )
            # serving fleet (ISSUE 18): shed/overload, generation age,
            # and the per-shard p99 table of a routed (`cli route`) run
            if final.get("serve_shed"):
                lines.append(
                    f"  shed: {final['serve_shed']} "
                    f"({final.get('serve_shed_rate', 0):.2%} of offered "
                    "load) — admission control"
                )
            if isinstance(final.get("generation_age_s"), (int, float)):
                lines.append(
                    f"  generation age: {final['generation_age_s']:.1f}s "
                    "since publish"
                )
            if final.get("serve_shards"):
                lines.append(
                    f"  fleet: {final['serve_shards']} shard(s) x "
                    f"{final.get('serve_replicas', '?')} replica(s), "
                    f"serving generation "
                    f"{final.get('serving_generation', '?')}, "
                    f"{final.get('rollouts', 0)} rollout(s), "
                    f"{final.get('mixed_generation', 0)} mixed-generation "
                    "answer(s)"
                )
            # failover tripwires (ISSUE 19 satellite): counters that used
            # to live only in the router's stats() dict and die with the
            # process — rendered whenever the router recorded them
            if (
                "transport_failovers" in final
                or "pruned_generation" in final
            ):
                lines.append(
                    f"  failovers: {final.get('transport_failovers', 0)} "
                    "transport, "
                    f"{final.get('pruned_generation', 0)} "
                    "pruned-generation"
                )
            # per-hop latency decomposition (ISSUE 19 tentpole): the
            # cross-process trace means — where a routed query's time
            # went, fleet-wide
            hop_parts = []
            for hop in ("transport", "decode", "queue", "batch_wait",
                        "execute", "merge"):
                v = final.get(f"serve_hop_{hop}_s")
                if isinstance(v, (int, float)):
                    hop_parts.append(f"{hop} {v * 1e3:.3g}ms")
            if hop_parts:
                lines.append(
                    "  per-hop mean: " + "  ".join(hop_parts)
                    + f"  (over {final.get('traced_queries', '?')} "
                    "traced)"
                )
            shard_stats = final.get("serve_shard_stats") or {}
            if isinstance(shard_stats, dict) and shard_stats:
                lines.append(
                    "  shard    queries      p50 ms      p99 ms       qps"
                )
                for s, st in sorted(
                    shard_stats.items(), key=lambda kv: int(kv[0])
                ):
                    if not isinstance(st, dict):
                        continue
                    p50 = st.get("p50_s")
                    p99 = st.get("p99_s")
                    qps = st.get("qps")
                    lines.append(
                        f"  {s:>5} {st.get('queries', 0):>10} "
                        + (
                            f"{p50 * 1e3:>11.3f} "
                            if isinstance(p50, (int, float))
                            else f"{'-':>11} "
                        )
                        + (
                            f"{p99 * 1e3:>11.3f} "
                            if isinstance(p99, (int, float))
                            else f"{'-':>11} "
                        )
                        + (
                            f"{qps:>9.1f}"
                            if isinstance(qps, (int, float))
                            else f"{'-':>9}"
                        )
                    )
                for s, st in sorted(
                    shard_stats.items(), key=lambda kv: int(kv[0])
                ):
                    hops = (
                        st.get("hops") if isinstance(st, dict) else None
                    )
                    if isinstance(hops, dict) and hops:
                        lines.append(
                            f"    shard {s} hops: " + "  ".join(
                                f"{k} {v * 1e3:.3g}ms"
                                for k, v in hops.items()
                                if isinstance(v, (int, float))
                            )
                        )
        if merged["final"]:
            lines.append("")
            lines.append("final: " + json.dumps(merged["final"]))

    if events is not None:
        n, schema_errors = validate_events_file(
            os.path.join(directory, EVENTS_NAME)
        )
        errors += len(schema_errors)
        lines.append("")
        lines.append(
            f"events.jsonl: {n} events "
            + json.dumps(summarize_kinds(events))
        )
        dur = run_duration_s(events)
        if dur is not None:
            # monotonic, by construction: first->last elapsed_s, never a
            # wall-clock subtraction
            lines.append(f"  event timeline: {dur:.3f}s (monotonic)")
        if schema_errors:
            lines.append(f"  SCHEMA ERRORS ({len(schema_errors)}):")
            lines.extend(f"    {e}" for e in schema_errors[:20])
        steps = [
            e for e in events
            if e.get("kind") == "step"
            and isinstance(e.get("llh"), (int, float))
        ]
        if steps:
            first, last = steps[0], steps[-1]
            lines.append(
                f"  steps: {len(steps)}  iter {first.get('iter')}→"
                f"{last.get('iter')}  llh {first.get('llh'):.6g}→"
                f"{last.get('llh'):.6g}"
            )
        stalls = [e for e in events if e.get("kind") == "stall"]
        for s in stalls[:5]:
            where = s.get("spans") or []
            lines.append(
                f"  stall at t={s.get('elapsed_s', s.get('t'))}s: "
                f"silent {s.get('silent_s')}s, "
                f"last progress {s.get('progress')}"
                + (f", open span {where[-1]}" if where else "")
            )
    elif merged and merged["events"].get("start"):
        lines.append("")
        lines.append(
            "events.jsonl: absent (non-primary dir? events are written by "
            "process 0 only)"
        )
    return "\n".join(lines), errors


# ------------------------------------------------------------------ fleet
# Fleet-wide aggregation (ISSUE 19 tentpole): `cli report --fleet ROOT` /
# `cli watch --fleet ROOT` treat ROOT as a parent directory whose
# SUBDIRECTORIES are member telemetry dirs — the router's and every
# replica's --telemetry-dir side by side. Merging is read-time and
# tolerant by construction: a missing replica dir is simply not a member,
# an empty or torn events.jsonl decodes to what it holds (load_events),
# and a member mid-run (events, no run_report yet) contributes its live
# event stream with an empty final.


def fleet_dirs(root: str) -> List[str]:
    """Member telemetry dirs of a fleet root: immediate subdirectories
    holding an events.jsonl or any run_report*.json, sorted by name."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in sorted(os.listdir(root)):
        d = os.path.join(root, name)
        if not os.path.isdir(d):
            continue
        if os.path.exists(os.path.join(d, EVENTS_NAME)) or glob.glob(
            os.path.join(d, "run_report*.json")
        ):
            out.append(d)
    return out


def load_fleet(root: str) -> List[dict]:
    """One record per member dir: name, entry (report first, start event
    fallback), final outcome, decoded events (None when the log is
    absent). Unreadable reports are treated as not-yet-written — a
    member can be rendered mid-run."""
    members = []
    for d in fleet_dirs(root):
        try:
            reports = load_reports(d)
        except (OSError, ValueError):
            reports = []
        events = load_events(d)
        rep = reports[0] if reports else {}
        entry = rep.get("entry")
        if not entry and events:
            start = next(
                (e for e in events if e.get("kind") == "start"), {}
            )
            entry = start.get("entry")
        members.append({
            "dir": d,
            "name": os.path.basename(d.rstrip(os.sep)),
            "entry": entry or "?",
            "final": rep.get("final") or {},
            "finalized": bool(reports),
            "events": events,
        })
    return members


def _fleet_router(members: List[dict]) -> Optional[dict]:
    """The router member: entry == "route", or (synthesized dirs) the
    member whose final carries the per-shard stats table."""
    for m in members:
        if m["entry"] == "route":
            return m
    for m in members:
        if m["final"].get("serve_shard_stats"):
            return m
    return None


def _fleet_supervision(members: List[dict]) -> Optional[dict]:
    """Supervisor state (ISSUE 20): from the `fleet` entry's final
    outcome when it finalized, else the roster of its LAST membership
    event (the supervisor publishes one per state change, with the
    per-member id/shard/state/restarts riding as the `roster` extra) —
    so a LIVE fleet renders up/restarting/quarantined/draining per
    member mid-drill."""
    sup = next((m for m in members if m["entry"] == "fleet"), None)
    if sup is None:
        return None
    f = sup["final"] or {}
    roster = None
    if isinstance(f.get("fleet_members"), dict):
        roster = [
            dict(
                (v if isinstance(v, dict) else {}), id=str(mid)
            )
            for mid, v in sorted(f["fleet_members"].items())
        ]
    if roster is None:
        last = next(
            (
                e for e in reversed(sup["events"] or [])
                if e.get("kind") == "membership"
                and isinstance(e.get("roster"), list)
            ),
            None,
        )
        if last is not None:
            roster = [
                r for r in last["roster"] if isinstance(r, dict)
            ]
    events = sup["events"] or []
    restarts = f.get("replica_restarts")
    if not isinstance(restarts, int):
        restarts = sum(
            1 for e in events if e.get("kind") == "replica_restart"
        )
    quarantined = f.get("quarantined")
    if not isinstance(quarantined, int):
        quarantined = sum(
            1 for e in events
            if e.get("kind") == "replica_quarantined"
        )
    return {
        "dir": sup["name"],
        "finalized": sup["finalized"],
        "replica_restarts": restarts,
        "quarantined": quarantined,
        "members": roster or [],
    }


def render_fleet_json(root: str) -> Tuple[dict, int]:
    """Machine-readable fleet view: member roster, the router's final
    scoreboard verbatim, and replica finals grouped by shard. Exit-code
    errors only when ROOT yields no members at all."""
    members = load_fleet(root)
    errors = 0 if members else 1
    router = _fleet_router(members)
    by_shard: Dict[str, List[dict]] = {}
    for m in members:
        if m is router or (
            m["entry"] not in ("serve", "?") and "shard" not in m["final"]
        ):
            continue
        if "shard" not in m["final"] and m["entry"] != "serve":
            continue
        f = m["final"]
        s = f.get("shard")
        key = str(s) if isinstance(s, int) else "?"
        by_shard.setdefault(key, []).append({
            "name": m["name"],
            "finalized": m["finalized"],
            "queries": f.get("queries"),
            "errors": f.get("errors"),
            "shed": f.get("shed"),
            "depth_peak": f.get("depth_peak"),
            "generations": f.get("generations"),
            "gen_age_s": f.get("gen_age_s"),
            "events": (
                len(m["events"]) if m["events"] is not None else None
            ),
            "stalls": sum(
                1 for e in (m["events"] or [])
                if e.get("kind") == "stall"
            ),
        })
    obj = {
        "root": root,
        "members": [
            {
                "name": m["name"],
                "entry": m["entry"],
                "finalized": m["finalized"],
                "events": (
                    len(m["events"]) if m["events"] is not None else None
                ),
            }
            for m in members
        ],
        "router": (router["final"] or None) if router else None,
        "router_dir": router["name"] if router else None,
        "replicas": dict(sorted(by_shard.items())),
        "supervision": _fleet_supervision(members),
    }
    return obj, errors


def render_fleet(root: str) -> Tuple[str, int]:
    """Human fleet view: per-shard p50/p99/QPS from the router next to
    each replica's own queue/shed/generation figures, the per-hop
    latency decomposition, freshness, and the failover tripwires — one
    screen answering 'which tier, which shard'."""
    obj, errors = render_fleet_json(root)
    if not obj["members"]:
        return (
            f"{root}: no member telemetry dirs (expected the router's "
            "and each replica's --telemetry-dir as subdirectories)",
            errors,
        )
    lines = [f"fleet {root}: {len(obj['members'])} member dir(s)"]
    for m in obj["members"]:
        lines.append(
            f"  {m['name']} [{m['entry']}]  "
            + (
                f"{m['events']} event(s)" if m["events"] is not None
                else "no events.jsonl"
            )
            + ("" if m["finalized"] else "  [running]")
        )
    rf = obj["router"] or {}
    if rf:
        lines.append("")
        parts = [f"router: {rf.get('serve_queries', 0)} queries"]
        for key, label in (
            ("serve_p50_s", "p50"), ("serve_p99_s", "p99"),
        ):
            v = rf.get(key)
            if isinstance(v, (int, float)):
                parts.append(f"{label} {v * 1e3:.3g} ms")
        if isinstance(rf.get("serve_qps"), (int, float)):
            parts.append(f"{rf['serve_qps']:.4g} qps")
        if rf.get("serve_shed"):
            parts.append(f"shed {rf['serve_shed']}")
        lines.append("  ".join(parts))
        lines.append(
            f"  generations: serving {rf.get('serving_generation', '?')}"
            + (
                f", age {rf['generation_age_s']:.1f}s"
                if isinstance(rf.get("generation_age_s"), (int, float))
                else ""
            )
            + f", {rf.get('rollouts', 0)} rollout(s), "
            f"{rf.get('mixed_generation', 0)} mixed, "
            f"{rf.get('pruned_generation', 0)} pruned-gen failover(s), "
            f"{rf.get('transport_failovers', 0)} transport failover(s)"
        )
        hop_parts = []
        for hop in ("transport", "decode", "queue", "batch_wait",
                    "execute", "merge"):
            v = rf.get(f"serve_hop_{hop}_s")
            if isinstance(v, (int, float)):
                hop_parts.append(f"{hop} {v * 1e3:.3g}ms")
        if hop_parts:
            lines.append(
                "  per-hop mean: " + "  ".join(hop_parts)
                + f"  (over {rf.get('traced_queries', '?')} traced)"
            )
        heal_parts = []
        for key, label in (
            ("router_retries", "retried"),
            ("hedged", "hedged"),
            ("hedge_wins", "hedge wins"),
            ("deadline_exceeded", "deadline exceeded"),
            ("membership_reloads", "membership reloads"),
        ):
            v = rf.get(key)
            if isinstance(v, int) and v:
                heal_parts.append(f"{label} {v}")
        if heal_parts:
            lines.append("  self-healing: " + "  ".join(heal_parts))
    sup = obj.get("supervision")
    if sup:
        lines.append("")
        lines.append(
            f"supervisor [{sup['dir']}]: "
            f"{sup['replica_restarts']} restart(s), "
            f"{sup['quarantined']} quarantined"
            + ("" if sup["finalized"] else "  [running]")
        )
        for r in sup["members"]:
            lines.append(
                f"  {r.get('id', '?'):<8} shard "
                f"{r.get('shard', '?')}  "
                f"{str(r.get('state', '?')):<12} "
                f"restarts {r.get('restarts', 0)}"
            )
    shard_stats = rf.get("serve_shard_stats") or {}
    shard_keys = sorted(
        set(shard_stats) | set(obj["replicas"]),
        key=lambda s: (not s.isdigit(), int(s) if s.isdigit() else 0),
    )
    if shard_keys:
        lines.append("")
        lines.append(
            "  shard    queries      p50 ms      p99 ms       qps"
            "  replicas      shed  depth pk"
        )
        for s in shard_keys:
            st = shard_stats.get(s) or {}
            reps = obj["replicas"].get(s) or []
            p50, p99, qps = (
                st.get("p50_s"), st.get("p99_s"), st.get("qps")
            )
            shed = sum(
                int(r["shed"]) for r in reps
                if isinstance(r.get("shed"), int)
            )
            dpk = max(
                (
                    int(r["depth_peak"]) for r in reps
                    if isinstance(r.get("depth_peak"), int)
                ),
                default=None,
            )
            lines.append(
                f"  {s:>5} {st.get('queries', 0):>10} "
                + (
                    f"{p50 * 1e3:>11.3f} "
                    if isinstance(p50, (int, float)) else f"{'-':>11} "
                )
                + (
                    f"{p99 * 1e3:>11.3f} "
                    if isinstance(p99, (int, float)) else f"{'-':>11} "
                )
                + (
                    f"{qps:>9.1f}"
                    if isinstance(qps, (int, float)) else f"{'-':>9}"
                )
                + f" {len(reps):>9}"
                + f" {shed:>9}"
                + (f" {dpk:>9}" if dpk is not None else f" {'-':>9}")
            )
            hops = st.get("hops")
            if isinstance(hops, dict) and hops:
                lines.append(
                    f"    shard {s} hops: " + "  ".join(
                        f"{k} {v * 1e3:.3g}ms"
                        for k, v in hops.items()
                        if isinstance(v, (int, float))
                    )
                )
            for r in reps:
                lines.append(
                    f"    replica {r['name']}: "
                    f"{r.get('queries', '?')} queries, "
                    f"{r.get('errors', 0) or 0} error(s), "
                    f"shed {r.get('shed', 0) or 0}"
                    + (
                        f", gen age {r['gen_age_s']:.1f}s"
                        if isinstance(r.get("gen_age_s"), (int, float))
                        else ""
                    )
                    + (
                        f", STALLS {r['stalls']}" if r.get("stalls")
                        else ""
                    )
                    + ("" if r["finalized"] else "  [running]")
                )
    return "\n".join(lines), errors
