"""`cli watch <telemetry-dir>`: a live terminal tailer for a running (or
finished) fit, rendered from events.jsonl alone (ISSUE 8).

`cli report` is a post-mortem; watch answers "is this 30-minute pod fit
healthy RIGHT NOW" from any host that can read the telemetry directory —
no jax, no run access. Each refresh re-reads the event log (append-only,
single writer, line-framed — a torn last line is skipped by the decoder)
and renders:

* unicode sparklines over the trailing `health` samples: LLH, grad norm,
  update norm, membership churn (plus support churn / cap occupancy on
  sparse runs) — the optimizer's vital signs at a glance
* the step counter / LLH trajectory from `step` events when the run has
  a metrics sink wired, fit progress from the health samples otherwise
* fired anomalies, stalls, rollbacks, and the run's last event age (a
  growing age with no stall event yet is the earliest hang signal)

Dependency-free and read-only by design (the data-prep-host contract of
obs.report). `once=True` renders a single frame and returns — the mode
tests and CI use; the live loop redraws every `interval` seconds and
exits on its own when an `end` event lands (the run finalized) or on
Ctrl-C.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence

from bigclam_tpu.obs.report import load_events, run_duration_s
from bigclam_tpu.obs.telemetry import EVENTS_NAME

BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(vals: Sequence[float], width: int = 48) -> str:
    """Trailing `width` values as a unicode block sparkline (constant
    series render mid-scale; non-finite samples render as '!' — the
    blow-up must be visible, not crash the tailer)."""
    import math

    vals = list(vals)[-width:]
    if not vals:
        return ""
    finite = [v for v in vals if isinstance(v, (int, float))
              and math.isfinite(v)]
    if not finite:
        return "!" * len(vals)
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in vals:
        if not (isinstance(v, (int, float)) and math.isfinite(v)):
            out.append("!")
        elif span <= 0:
            out.append(BLOCKS[3])
        else:
            idx = int((v - lo) / span * (len(BLOCKS) - 1))
            out.append(BLOCKS[max(0, min(idx, len(BLOCKS) - 1))])
    return "".join(out)


def _series(events: List[dict], kind: str, field: str) -> List[float]:
    out = []
    for e in events:
        if e.get("kind") != kind:
            continue
        v = e.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append(float(v))
        elif isinstance(v, str) and v in ("nan", "inf", "-inf"):
            out.append(float(v))    # strict-JSON stringified non-finite
    return out


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    return f"{v:.6g}"


def render_frame(directory: str, width: int = 48) -> str:
    """One watch frame (pure render; the loop and the CLI --once mode
    both call this)."""
    return _render_events(directory, load_events(directory), width)


def _render_events(
    directory: str, events: Optional[List[dict]], width: int
) -> str:
    if events is None:
        return (
            f"{directory}: no {EVENTS_NAME} yet (run not started, or a "
            "non-primary process dir)"
        )
    lines: List[str] = []
    start = next((e for e in events if e.get("kind") == "start"), {})
    # None while the log holds no decodable timestamped line yet (empty
    # file / torn first write) — the startup window watch exists to cover
    dur = run_duration_s(events)
    ended = any(e.get("kind") == "end" for e in events)
    lines.append(
        f"run {start.get('run', '?')}  entry={start.get('entry', '?')}  "
        f"events {len(events)}  elapsed "
        + ("-" if dur is None else f"{dur:.1f}s")
        + ("  [finalized]" if ended else "")
    )

    steps = [e for e in events if e.get("kind") == "step"]
    health = [e for e in events if e.get("kind") == "health"]
    prog = steps[-1] if steps else (health[-1] if health else None)
    if prog is not None:
        llh = prog.get("llh")
        lines.append(
            f"iter {prog.get('iter', '?')}  llh "
            f"{llh if isinstance(llh, str) else _fmt(llh)}"
        )

    def spark_row(label: str, series: List[float]) -> None:
        if not series:
            return
        lines.append(
            f"  {label:<12} {sparkline(series, width):<{width}} "
            f"last {_fmt(series[-1])}"
        )

    src = health if health else steps
    spark_row("llh", _series(src, src[0]["kind"], "llh") if src else [])
    if health:
        spark_row("grad_norm", _series(health, "health", "grad_norm"))
        spark_row("update_norm", _series(health, "health", "update_norm"))
        spark_row("churn", _series(health, "health", "churn"))
        spark_row("support_churn",
                  _series(health, "health", "support_churn"))
        spark_row("cap_occ", _series(health, "health", "cap_occupancy"))
        spark_row("step_eff", _series(health, "health", "step_eff"))
    else:
        lines.append(
            "  (no health samples — run with --health-every N > 0)"
        )
    if steps:
        spark_row("sec/iter", _series(steps, "step", "sec_per_iter"))

    # collective-traffic + balance snapshot (obs.comms): per-MODEL site
    # sets, a re-emitted model (reset_model on its first event — the
    # sparse cap refinement can flip the collective mode) replaces its
    # previous sites; latest balance skew — the "is the interconnect/
    # work-split sane" line
    comms_by_model = {}
    for e in events:
        if e.get("kind") != "comms" or not isinstance(
            e.get("bytes_per_step"), (int, float)
        ):
            continue
        model = str(e.get("model", "?"))
        if e.get("reset_model"):
            comms_by_model[model] = {}
        comms_by_model.setdefault(model, {})[
            str(e.get("site", "?"))
        ] = float(e["bytes_per_step"])
    if comms_by_model:
        from bigclam_tpu.obs.report import _fmt_bytes

        sites = [
            v for m in comms_by_model.values() for v in m.values()
        ]
        lines.append(
            f"  comms {_fmt_bytes(int(sum(sites)))}/step modeled over "
            f"{len(sites)} site(s)"
        )
    # memory model vs measured headroom (obs.memory, ISSUE 12): the
    # modeled per-device HBM (memory_model events, reset_model replace
    # semantics like comms) against the latest watermark's measured
    # in-use + the device limit — "will this fit" rendered live
    mem_by_model = {}
    for e in events:
        if e.get("kind") != "memory_model" or e.get("scope") == "host":
            continue
        if not isinstance(e.get("bytes"), (int, float)):
            continue
        model = str(e.get("model", "?"))
        if e.get("reset_model"):
            mem_by_model[model] = {}
        mem_by_model.setdefault(model, {})[
            str(e.get("buffer", "?"))
        ] = float(e["bytes"])
    if mem_by_model:
        from bigclam_tpu.obs.report import _fmt_bytes

        modeled = sum(
            v for bufs in mem_by_model.values() for v in bufs.values()
        )
        measured = limit = None
        for e in reversed(events):
            if e.get("kind") == "memory" and e.get("devices"):
                vals = [
                    d.get("bytes_in_use") for d in e["devices"]
                    if isinstance(d.get("bytes_in_use"), (int, float))
                ]
                lims = [
                    d.get("bytes_limit") for d in e["devices"]
                    if isinstance(d.get("bytes_limit"), (int, float))
                ]
                if vals:
                    measured = max(vals)
                if lims:
                    limit = max(lims)
                break
        line = f"  hbm modeled {_fmt_bytes(int(modeled))}/device"
        if measured is not None:
            line += f"  measured {_fmt_bytes(int(measured))}"
        if limit:
            line += (
                f"  headroom {_fmt_bytes(int(limit - modeled))}"
                f" of {_fmt_bytes(int(limit))}"
            )
        lines.append(line)
    balances = [e for e in events if e.get("kind") == "balance"]
    if balances:
        b = balances[-1]
        skew = b.get("skew")
        lines.append(
            f"  balance {b.get('what')}: skew "
            f"{skew if isinstance(skew, (int, float)) else '?'}x "
            f"(max {b.get('max')} vs mean {b.get('mean')})"
        )
    # serving generation age + queue depth (ISSUE 18 satellite): the
    # newest serve batch carries wall-clock-since-publish and the live
    # admission queue — "how stale is serving" and "how loaded" as
    # rendered numbers, refreshed every frame
    serves = [e for e in events if e.get("kind") == "serve"]
    if serves:
        s = serves[-1]
        parts = [f"serving gen {s.get('step', '?')}"]
        if isinstance(s.get("gen_age_s"), (int, float)):
            parts.append(f"age {s['gen_age_s']:.1f}s")
        if isinstance(s.get("queue_depth"), (int, float)):
            parts.append(f"queue depth {int(s['queue_depth'])}")
        lines.append("  " + "  ".join(parts))
    anomalies = [e for e in events if e.get("kind") == "anomaly"]
    for a in anomalies:
        it = a.get("iter")
        where = "build" if isinstance(it, int) and it < 0 else f"iter {it}"
        lines.append(f"  ANOMALY {a.get('check')} at {where}")
    stalls = [e for e in events if e.get("kind") == "stall"]
    if stalls:
        s = stalls[-1]
        lines.append(
            f"  STALLS {len(stalls)} (last: silent {s.get('silent_s')}s"
            + (f", open span {s['spans'][-1]}" if s.get("spans") else "")
            + ")"
        )
    rollbacks = sum(1 for e in events if e.get("kind") == "rollback")
    if rollbacks:
        lines.append(f"  rollbacks {rollbacks}")
    if not ended and events:
        # staleness from the file's side, not the event clock: how long
        # since the writer last appended anything
        try:
            age = time.time() - os.path.getmtime(
                os.path.join(directory, EVENTS_NAME)
            )
            lines.append(f"  last write {age:.0f}s ago")
        except OSError:
            pass
    return "\n".join(lines)


def watch(
    directory: str,
    interval: float = 2.0,
    once: bool = False,
    width: int = 48,
    max_frames: int = 0,
    out=None,
) -> int:
    """The watch loop. Returns 0, or 1 when `once` finds no event log.
    `max_frames` bounds the loop for tests (0 = until end/Ctrl-C)."""
    import sys

    out = out or sys.stdout
    frames = 0
    while True:
        # one read+decode per refresh: the same event list feeds the
        # frame AND the run-ended exit test
        events = load_events(directory)
        frame = _render_events(directory, events, width)
        if once:
            print(frame, file=out)
            return 0 if os.path.exists(
                os.path.join(directory, EVENTS_NAME)
            ) else 1
        # ANSI clear + home keeps the frame stable in a terminal; piped
        # output just sees frame separators
        if getattr(out, "isatty", lambda: False)():
            print("\x1b[2J\x1b[H", end="", file=out)
        print(frame, file=out, flush=True)
        frames += 1
        if events is not None and any(
            e.get("kind") == "end" for e in events
        ):
            return 0
        if max_frames and frames >= max_frames:
            return 0
        try:
            time.sleep(max(interval, 0.1))
        except KeyboardInterrupt:
            return 0


# ------------------------------------------------------------------ fleet
def render_fleet_frame(root: str, width: int = 48) -> str:
    """One live frame over a fleet root (ISSUE 19): ROOT's
    subdirectories are member telemetry dirs (obs.report.fleet_dirs) —
    the router's and every replica's. Same tolerance contract as the
    single-dir tailer: a member with no events.jsonl yet renders as a
    placeholder row, torn lines are skipped by the decoder."""
    from bigclam_tpu.obs.report import load_fleet

    return _render_fleet_members(root, load_fleet(root), width)


def _render_fleet_members(root, members, width: int) -> str:
    if not members:
        return (
            f"{root}: no member telemetry dirs yet (expected the "
            "router's and each replica's --telemetry-dir as "
            "subdirectories)"
        )
    lines = [f"fleet {root}: {len(members)} member(s)"]
    for m in members:
        name, entry, events = m["name"], m["entry"], m["events"]
        if events is None:
            lines.append(f"  {name} [{entry}]: no events.jsonl yet")
            continue
        ended = any(e.get("kind") == "end" for e in events)
        parts = [f"events {len(events)}"]
        fresh = [e for e in events if e.get("kind") == "freshness"]
        if fresh:
            f0 = fresh[-1]
            age = f0.get("generation_age_s")
            parts.append(
                f"gen {f0.get('step', '?')}"
                + (
                    f" age {age:.1f}s"
                    if isinstance(age, (int, float)) else ""
                )
            )
        else:
            serves = [e for e in events if e.get("kind") == "serve"]
            if serves:
                s = serves[-1]
                parts.append(f"gen {s.get('step', '?')}")
                if isinstance(s.get("gen_age_s"), (int, float)):
                    parts.append(f"age {s['gen_age_s']:.1f}s")
                if isinstance(s.get("queue_depth"), (int, float)):
                    parts.append(
                        f"queue depth {int(s['queue_depth'])}"
                    )
        rollouts = sum(
            1 for e in events if e.get("kind") == "rollout"
        )
        if rollouts:
            parts.append(f"rollouts {rollouts}")
        stalls = [e for e in events if e.get("kind") == "stall"]
        if stalls:
            s = stalls[-1]
            stall_part = f"STALLS {len(stalls)}"
            if isinstance(s.get("open_traces"), int):
                stall_part += (
                    f" (open traces {s['open_traces']}, oldest "
                    f"{s.get('oldest_inflight_s', '?')}s)"
                )
            parts.append(stall_part)
        if ended:
            parts.append("[finalized]")
        else:
            try:
                age = time.time() - os.path.getmtime(
                    os.path.join(m["dir"], EVENTS_NAME)
                )
                parts.append(f"last write {age:.0f}s ago")
            except OSError:
                pass
        lines.append(f"  {name} [{entry}]: " + "  ".join(parts))
        if entry == "fleet":
            # supervisor member (ISSUE 20): per-slot lifecycle state
            # from the LAST membership event's roster — a live chaos
            # drill shows up/restarting/quarantined/draining as it runs
            restarts = sum(
                1 for e in events
                if e.get("kind") == "replica_restart"
            )
            quar = sum(
                1 for e in events
                if e.get("kind") == "replica_quarantined"
            )
            if restarts or quar:
                lines.append(
                    f"    supervision: {restarts} restart(s), "
                    f"{quar} quarantined"
                )
            last = next(
                (
                    e for e in reversed(events)
                    if e.get("kind") == "membership"
                    and isinstance(e.get("roster"), list)
                ),
                None,
            )
            for r in (last or {}).get("roster", []):
                if isinstance(r, dict):
                    lines.append(
                        f"    {str(r.get('id', '?')):<8} shard "
                        f"{r.get('shard', '?')}  "
                        f"{str(r.get('state', '?')):<12} "
                        f"restarts {r.get('restarts', 0)}"
                    )
        # the router member's slow-query exemplar trail (qtrace events):
        # end-to-end ms of the top-N traces per window as a sparkline —
        # a widening tail is visible live, before any report runs
        qt = [
            float(e["total_s"]) * 1e3
            for e in events
            if e.get("kind") == "qtrace"
            and isinstance(e.get("total_s"), (int, float))
        ]
        if qt:
            lines.append(
                f"    slow traces  {sparkline(qt, width):<{width}} "
                f"last {qt[-1]:.3g}ms"
            )
        fr = [
            float(e["generation_age_s"])
            for e in fresh
            if isinstance(e.get("generation_age_s"), (int, float))
        ]
        if len(fr) >= 2:
            lines.append(
                f"    gen age s    {sparkline(fr, width):<{width}} "
                f"last {fr[-1]:.1f}s"
            )
    return "\n".join(lines)


def watch_fleet(
    root: str,
    interval: float = 2.0,
    once: bool = False,
    width: int = 48,
    max_frames: int = 0,
    out=None,
) -> int:
    """The fleet watch loop (`cli watch --fleet`). Returns 0, or 1 when
    `once` finds no member dirs; the live loop exits once every member
    has finalized (each log carries an `end` event)."""
    import sys

    from bigclam_tpu.obs.report import load_fleet

    out = out or sys.stdout
    frames = 0
    while True:
        members = load_fleet(root)
        frame = _render_fleet_members(root, members, width)
        if once:
            print(frame, file=out)
            return 0 if members else 1
        if getattr(out, "isatty", lambda: False)():
            print("\x1b[2J\x1b[H", end="", file=out)
        print(frame, file=out, flush=True)
        frames += 1
        if members and all(
            m["events"] is not None
            and any(e.get("kind") == "end" for e in m["events"])
            for m in members
        ):
            return 0
        if max_frames and frames >= max_frames:
            return 0
        try:
            time.sleep(max(interval, 0.1))
        except KeyboardInterrupt:
            return 0
