"""Stall heartbeat: a daemon thread that notices when NOTHING completes.

Multihost collectives hang with zero output (a wedged DCN hop blocks every
process inside the same jitted step), and a host-side stage that silently
spins looks identical to progress from the outside. The heartbeat inverts
the burden: fit iterations and stage completions call `beat()`, and when no
beat lands within `deadline_s` the thread emits a `stall` event — last
known progress, how long the run has been silent, host RSS, and a device
memory snapshot — to the telemetry event log (always) and to stderr
(unless the run is quiet; --quiet silences the echo, never the JSONL).

The thread samples, it never interrupts: a stalled collective cannot be
cancelled from Python anyway, so the job is to make the hang *visible* and
attributable (which phase, which process, what memory state) rather than
to kill it. Repeated stalls re-emit once per deadline, so a 30-minute hang
produces a timeline, not one line.

ESCALATION (ISSUE 5 satellite): beating forever is itself a failure mode —
a wedged run emitting its 40th identical stall line is not recovering.
With `escalate_after=N`, the Nth CONSECUTIVE stall (no beat in between)
additionally emits ONE `stall_escalated` event and invokes `on_escalate`
(the resilience supervisor's hook, which can abort-and-retry the attempt
for host-side stalls). One escalation per silence episode: a beat resets
the consecutive counter and re-arms it.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional


class Heartbeat:
    """Daemon watchdog bound to a RunTelemetry (`telemetry.event` is the
    sink; it is thread-safe). Deterministically testable: `poll_s` pins the
    check cadence and `stop()` joins the thread."""

    def __init__(
        self,
        telemetry,
        deadline_s: float,
        echo: bool = True,
        poll_s: Optional[float] = None,
        escalate_after: int = 0,
        on_escalate=None,
    ):
        self.telemetry = telemetry
        self.deadline_s = float(deadline_s)
        self.echo = echo
        self.poll_s = poll_s if poll_s is not None else max(
            self.deadline_s / 4.0, 0.01
        )
        self.stalls = 0
        self.escalate_after = int(escalate_after)
        self.on_escalate = on_escalate
        self.escalations = 0
        self._consecutive = 0
        self._last_beat = time.monotonic()
        self._last_emit = self._last_beat
        self._progress: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Heartbeat":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="bigclam-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def beat(self, **progress) -> None:
        """Record forward progress (called from the fit loop / stage sink;
        must stay cheap — two attribute writes under a lock)."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._last_emit = self._last_beat
            self._consecutive = 0       # progress re-arms escalation
            if progress:
                self._progress = progress

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(self.poll_s * 4, 1.0))
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            # device-memory watermark on the POLL cadence (ISSUE 12
            # fix): stage-boundary-only sampling made a peak inside a
            # long fit stage invisible — fold a sample into the running
            # per-device max every poll (no event emitted; stalls still
            # carry full snapshots). Never allowed to kill the watchdog.
            try:
                self.telemetry.sample_device_peak("heartbeat")
            except Exception:
                pass
            now = time.monotonic()
            with self._lock:
                silent = now - self._last_beat
                since_emit = now - self._last_emit
                progress = dict(self._progress)
            if silent < self.deadline_s or since_emit < self.deadline_s:
                continue
            with self._lock:
                self._last_emit = now
            self._emit(silent, progress)

    def _emit(self, silent_s: float, progress: dict) -> None:
        from bigclam_tpu.obs import trace as _trace
        from bigclam_tpu.utils.profiling import current_rss_bytes

        self.stalls += 1
        with self._lock:
            self._consecutive += 1
            consecutive = self._consecutive
        rss = current_rss_bytes()
        devices = self.telemetry.device_memory_snapshot()
        # the currently-OPEN span stack (obs.trace, ISSUE 6): a stall
        # report answers "stuck in WHICH phase" — a hung collective shows
        # e.g. ["fit", "fit/fit_loop", "fit/fit_loop/sync"], innermost
        # last, instead of only "no progress for Ns"
        spans = _trace.open_spans()
        # the last model-health snapshot (ISSUE 8 satellite), next to the
        # open span stack: a stall report then distinguishes "stuck
        # compiling / wedged collective" (healthy last snapshot) from
        # "diverging" (grad norm exploding) — None when health is off
        health = getattr(self.telemetry, "last_health", None)
        # the last fit-loop sync-span duration (obs.comms / ISSUE 10):
        # a stall whose final sync was already ballooning reads as
        # "waiting on the gang / a straggler host", not "computing" —
        # None before the first iteration completes
        sync_s = getattr(self.telemetry, "last_sync_s", None)
        # modeled-vs-measured HBM (obs.memory, ISSUE 12): next to the
        # live device snapshot, the static model's per-device total —
        # a stall with measured >> modeled reads as "leaked/retained
        # buffers", measured ~ modeled as "wedged, memory healthy"
        hbm_fn = getattr(self.telemetry, "hbm_modeled_bytes", None)
        hbm_modeled = hbm_fn() if callable(hbm_fn) else None
        # the serving queue depth (serve.batcher admission control,
        # ISSUE 18), next to the span stack: a serve stall with a full
        # queue reads as "overloaded / handler wedged under load", an
        # empty one as "idle or transport-starved" — None off serve
        depth = getattr(self.telemetry, "last_queue_depth", None)
        queue_depth = depth() if callable(depth) else depth
        # the router's in-flight trace registry (serve.router, ISSUE
        # 19), the fleet analogue of the open-span stack: a route stall
        # with open traces + a growing oldest-in-flight age reads as
        # "wedged on a replica hop", zero open traces as "idle between
        # batches / driver starved" — None off the router entry
        ot = getattr(self.telemetry, "open_traces", None)
        open_traces = ot() if callable(ot) else ot
        oi = getattr(self.telemetry, "oldest_inflight_s", None)
        oldest_inflight_s = oi() if callable(oi) else oi
        if isinstance(oldest_inflight_s, float):
            oldest_inflight_s = round(oldest_inflight_s, 3)
        self.telemetry.event(
            "stall",
            silent_s=round(silent_s, 3),
            rss_bytes=rss,
            progress=progress,
            devices=devices,
            spans=spans,
            health=health,
            sync_s=sync_s,
            hbm_modeled_bytes=hbm_modeled,
            queue_depth=queue_depth,
            open_traces=open_traces,
            oldest_inflight_s=oldest_inflight_s,
        )
        if self.echo:
            where = f"; open span: {spans[-1]}" if spans else ""
            print(
                f"[bigclam] STALL: no step/stage completed for "
                f"{silent_s:.0f}s (deadline {self.deadline_s:g}s); "
                f"last progress: {progress or 'none'}; "
                f"rss {rss >> 20} MiB{where}",
                file=sys.stderr,
                flush=True,
            )
        if self.escalate_after and consecutive == self.escalate_after:
            self.escalations += 1
            self.telemetry.event(
                "stall_escalated",
                stalls=consecutive,
                silent_s=round(silent_s, 3),
                progress=progress,
                spans=spans,
                health=health,
                sync_s=sync_s,
                hbm_modeled_bytes=hbm_modeled,
                queue_depth=queue_depth,
                open_traces=open_traces,
                oldest_inflight_s=oldest_inflight_s,
            )
            if self.echo:
                print(
                    f"[bigclam] STALL ESCALATED after {consecutive} "
                    f"consecutive deadline(s)",
                    file=sys.stderr,
                    flush=True,
                )
            cb = self.on_escalate
            if cb is not None:
                try:
                    cb({"silent_s": silent_s, "stalls": consecutive,
                        "progress": progress})
                except Exception:
                    pass            # the watchdog must never kill the run
