"""Persistent perf-regression ledger (ISSUE 6 tentpole, part b).

BENCH_*.json artifacts exist but nothing compares run N against run N-1 —
a perf regression lands silently. This module gives every telemetry-
carrying run a compact, schema'd perf record appended to a ledger JSONL
(one line per run, append-only, human-diffable), and a diff that compares
the latest run against its MATCHED baseline with noise bands:

    BIGCLAM_PERF_LEDGER=perf/ledger.jsonl python -m bigclam_tpu.cli fit ...
    python -m bigclam_tpu.cli perf diff --ledger perf/ledger.jsonl

Record fields (LEDGER_VERSION 1): run id, wall-clock ts, entry point,
host/platform/backend/device fingerprint, config digest (sha1 over the
run's step_cfg_key digests — the compile by_key labels), step-time
percentiles (p10/p50/p90/p99 over the per-iteration sec_per_iter samples
the MetricsLogger sink forwarded), eps p50, hbm_frac (when the entry
recorded one — bench), compile count, per-span second totals (obs.trace),
and the final LLH.

BASELINE MATCHING: a record's baseline is the MOST RECENT EARLIER record
with the same (entry, cfg_digest, workload, backend, device_kind, host)
— a step time is only comparable against the same work on the same
hardware; runs with a different K, kernel-path config, or chip never
match, and neither do runs over different GRAPHS: cfg_digest is
config-only (step_cfg_key excludes the graph), so the workload axis is
the (n, edges, k) triple the entry points stamp into the run's `final`
outcome (fit/profile stamp all three; sweep and bench stamp n/edges only
— sweep's chosen_k is a noisy OUTPUT and bench's headline graph carries
no single K — and axes an entry does not record match on the Nones).
The affiliation representation ("dense" | "sparse", + sparse_m) is part
of the key too: a sparse top-M run does O(M) work per edge where dense
does O(K), so a same-K cross-baseline would be meaningless ("dense"
normalizes to None so pre-field dense records keep matching). A
run re-recorded into the same ledger (`perf record` after an
auto-append) is never its own baseline.

NOISE BANDS: the regression threshold is max(tolerance, rel spread of
either run), where a run's spread is (step_p90 - step_p50)/step_p50 — a
run whose own timing wobbles 30% cannot be failed by a 25% band. `diff`
VERDICTS on step_p50 and eps_p50 (or wall_s for steploss runs) and on
hbm_frac when both runs recorded one; step_p99 (a single sample on short
runs), compile growth, and per-span deltas are reported as findings, not
failures (the compile-flatness pin lives in tests/test_telemetry.py).

jax-free: the ledger must be writable/diffable on data-prep hosts and in
CI without an accelerator.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

LEDGER_ENV = "BIGCLAM_PERF_LEDGER"
LEDGER_VERSION = 1
DEFAULT_PATH = os.path.join("perf", "ledger.jsonl")

_NUM = (int, float)
# field -> allowed types; None-able numerics are (type..., type(None))
_RECORD_SCHEMA = {
    "lv": (int,),
    "run": (str,),
    "ts": _NUM,
    "entry": (str,),
    "host": (str,),
    "cfg_digest": (str,),
    "wall_s": _NUM,
    "steps": (int,),
    "compiles": (int,),
    "spans": (dict,),
}


def _percentile(vals: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over a copy; None on empty input."""
    if not vals:
        return None
    s = sorted(vals)
    idx = min(int(round(q / 100.0 * (len(s) - 1))), len(s) - 1)
    return s[idx]


def validate_record(rec: Any) -> List[str]:
    """Schema errors for one ledger record; [] when valid."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    errors = []
    for field, types in _RECORD_SCHEMA.items():
        if field not in rec:
            errors.append(f"missing field {field!r}")
        elif not isinstance(rec[field], types) or isinstance(
            rec[field], bool
        ):
            errors.append(
                f"{field!r} is {type(rec[field]).__name__}, "
                f"want {'/'.join(t.__name__ for t in types)}"
            )
    if not errors and rec["lv"] != LEDGER_VERSION:
        errors.append(f"ledger version {rec['lv']} != {LEDGER_VERSION}")
    return errors


def build_record(
    report: Dict[str, Any],
    step_secs: Optional[Sequence[float]] = None,
    step_eps: Optional[Sequence[float]] = None,
    note: str = "",
) -> Dict[str, Any]:
    """One ledger record from a finalized run report (+ the per-step
    timing samples RunTelemetry collected from the MetricsLogger sink)."""
    fp = report.get("fingerprint", {}) or {}
    keys = sorted((report.get("compiles", {}) or {}).get("by_key", {}))
    digest = (
        hashlib.sha1("|".join(keys).encode()).hexdigest()[:12]
        if keys
        else "none"
    )
    final = report.get("final", {}) or {}
    secs = [float(v) for v in (step_secs or [])]
    eps = [float(v) for v in (step_eps or [])]
    rec: Dict[str, Any] = {
        "lv": LEDGER_VERSION,
        "run": str(report.get("run", "")),
        "ts": round(time.time(), 3),
        "entry": str(report.get("entry", "")),
        "host": str(fp.get("host", "")),
        "platform": fp.get("platform"),
        "backend": fp.get("backend"),
        "device_kind": fp.get("device_kind"),
        "devices": fp.get("devices"),
        "cfg_digest": digest,
        "cfg_keys": keys,
        # workload identity (see module docstring): the graph/K the entry
        # point recorded in its final outcome — part of the match key,
        # because cfg_digest alone cannot tell two graphs apart
        "n": final.get("n"),
        "edges": final.get("edges"),
        "k": final.get("k"),
        # affiliation-state representation (ISSUE 7): a sparse top-M run
        # and a dense run at the same K do different work per edge —
        # match_key refuses the cross-baseline even when an entry point
        # leaves these unset in its final outcome (None == dense by
        # construction: the sparse trainers always stamp them)
        "representation": final.get("representation"),
        "sparse_m": final.get("sparse_m"),
        # resolved edge-kernel path (ISSUE 13 satellite): fused / split /
        # xla-fallback as the entry point stamped it (cli fit/profile
        # stamp "kernel_path", bench stamps "path"). Part of the match
        # key: a run whose kernels silently fell back to XLA must never
        # baseline against a fused run — the 7.66M-vs-27.4M round-1
        # capture artifact, now structurally impossible
        "kernel_path": final.get("kernel_path") or final.get("path"),
        # execution shape (ISSUE 10 satellite): a 2-proc run must never
        # baseline against a single-proc run of the same cfg on the same
        # box (each process times only its shard's work), and a (4,1)
        # mesh does different collective work than (2,2) at equal device
        # count — both join the match key. `processes` comes from the
        # run report (jax.process_count at finalize); `mesh` is the
        # "dpxtp" string the sharded entry points stamp into their final
        # outcome (None on single-chip runs — matches on the None)
        "processes": int(report.get("processes", 1) or 1),
        "mesh": final.get("mesh"),
        # node-axis partition (ISSUE 16): a 2D (rows, cols) closure-
        # gather run moves a fraction of the 1D all-gather's bytes at
        # equal device count — its step times and comms totals must
        # never baseline against a 1D run of the same cfg/mesh. None
        # (1D entry points that predate the stamp) matches only None
        "partition": final.get("partition"),
        # 2D neighbor-grad exchange mode (ISSUE 17): a closure run ships
        # cap-sized touched-row buffers where a dense run psums the full
        # row band — comms totals and step times are not comparable, so
        # the mode joins the match key. None (1D runs and pre-r21
        # records) matches only None
        "grad_exchange": final.get("grad_exchange"),
        "wall_s": float(report.get("wall_s", 0.0) or 0.0),
        "steps": len(secs),
        "step_p10": _round6(_percentile(secs, 10)),
        "step_p50": _round6(_percentile(secs, 50)),
        "step_p90": _round6(_percentile(secs, 90)),
        "step_p99": _round6(_percentile(secs, 99)),
        "eps_p50": _round6(_percentile(eps, 50)),
        "compiles": int((report.get("compiles", {}) or {}).get("count", 0)),
        "hbm_frac": final.get("hbm_frac"),
        "overlap_frac": final.get("overlap_frac"),
        "spans": {
            k: round(float(v), 4)
            for k, v in (report.get("spans", {}) or {})
            .get("seconds", {})
            .items()
        },
        "final_llh": final.get("llh"),
    }
    # collective-traffic accounting (obs.comms, ISSUE 10): the modeled
    # bytes/step total + per-site table of the run's compiled steps —
    # `cli perf diff` VERDICTS on the total (a layout/padding change that
    # silently inflates wire traffic is a regression even at flat step
    # time on a small testbed), per-site deltas ride the record for the
    # human diff. None when the run built no sharded trainer.
    comms = report.get("comms", {}) or {}
    comms_sites = comms.get("sites") or {}
    rec["comms_bytes_per_step"] = (
        round(float(comms.get("bytes_per_step", 0.0)), 1)
        if comms_sites
        else None
    )
    rec["comms_sites"] = {
        k: round(float(v), 1) for k, v in comms_sites.items()
    }
    # convergence figures (ISSUE 8): a fit that still lands the same LLH
    # but needs 3x the iterations — or stops with a grad norm an order of
    # magnitude hotter — is a regression `cli perf diff` must catch even
    # when per-step time is flat. iters_to_tol is the entry's recorded
    # iteration count (fit converged at conv_tol; max_iters runs record
    # the cap — same cfg, still comparable); final_grad_norm comes from
    # the run's last health sample (None with health off).
    iters = final.get("iters")
    rec["iters_to_tol"] = int(iters) if isinstance(iters, _NUM) and not (
        isinstance(iters, bool)
    ) else None
    health = report.get("health", {}) or {}
    last_health = health.get("last") or {}
    # non-finite -> None: the pack legitimately goes inf/nan mid-blow-up
    # (schema.py), but the ledger line must stay strict JSON, and the
    # `cli perf record` path (reading the finite-safed on-disk report,
    # where non-finite is the string "inf") already records None — the
    # finalize auto-append must agree
    gn = last_health.get("grad_norm")
    rec["final_grad_norm"] = (
        _round6(float(gn))
        if isinstance(gn, _NUM) and math.isfinite(float(gn))
        else None
    )
    rec["anomalies"] = sum(
        int(v) for v in (health.get("anomalies", {}) or {}).values()
    )
    # memory accounting (obs.memory, ISSUE 12): the modeled per-device
    # HBM total and per-host RSS peak of the run's trainer builds —
    # VERDICTED by `cli perf diff`, so a layout/padding/state change
    # that silently inflates memory fails CI exactly like a perf or
    # comms regression (the CPU testbed's step time cannot see it; the
    # pod's HBM can). An explicit final stamp (bench pins the headline
    # model's figure next to its measured peak) wins over the report
    # accumulation, which sums every model the run built.
    mem = (report.get("memory", {}) or {}).get("modeled") or {}
    hbm = final.get("hbm_modeled_bytes")
    if not isinstance(hbm, _NUM) or isinstance(hbm, bool):
        hbm = mem.get("hbm_bytes_per_device")
    rec["hbm_modeled_bytes"] = (
        round(float(hbm), 1)
        if isinstance(hbm, _NUM) and not isinstance(hbm, bool) and hbm > 0
        else None
    )
    host_rss = mem.get("host_rss_bytes")
    rec["host_rss_modeled_bytes"] = (
        round(float(host_rss), 1)
        if isinstance(host_rss, _NUM)
        and not isinstance(host_rss, bool)
        and host_rss > 0
        else None
    )
    # membership serving (ISSUE 14 satellite): a serve run's record
    # carries the latency/throughput scoreboard the server stamped into
    # its final outcome — `cli perf diff` VERDICTS serve_p99_s and
    # serve_qps (the serving SLO axes; unlike the trainer's step_p99,
    # serve p99 is computed over hundreds of per-request samples, so it
    # is a stable gate figure), cache_hit_rate rides as a finding. The
    # entry point ("serve") is already the first element of match_key,
    # so a serve record can never cross-baseline a fit record; serve_mix
    # (the query-family ratio string) joins the key below because two
    # runs with different family mixes do different work per query.
    for field in ("serve_p50_s", "serve_p99_s", "serve_qps"):
        v = final.get(field)
        rec[field] = (
            _round6(float(v))
            if isinstance(v, _NUM) and not isinstance(v, bool)
            else None
        )
    sq = final.get("serve_queries")
    rec["serve_queries"] = (
        int(sq) if isinstance(sq, _NUM) and not isinstance(sq, bool)
        else None
    )
    chr_ = final.get("cache_hit_rate")
    rec["cache_hit_rate"] = (
        _round6(float(chr_))
        if isinstance(chr_, _NUM) and not isinstance(chr_, bool)
        else None
    )
    mix = final.get("serve_mix")
    rec["serve_mix"] = str(mix) if mix else None
    # serving fleet (ISSUE 18 satellite): shards × replicas join the
    # match key (a 2×2 fleet's p99 is not a single-process baseline —
    # None on non-fleet records matches only None, the usual rebaseline
    # rule) and the shed rate is VERDICTED (an admission-control
    # regression that sheds 10x more traffic at flat p99 must fail)
    for field in ("serve_shards", "serve_replicas", "serve_shed"):
        v = final.get(field)
        rec[field] = (
            int(v) if isinstance(v, _NUM) and not isinstance(v, bool)
            else None
        )
    sr = final.get("serve_shed_rate")
    rec["serve_shed_rate"] = (
        _round6(float(sr))
        if isinstance(sr, _NUM) and not isinstance(sr, bool)
        else None
    )
    # distributed query tracing + freshness (ISSUE 19): the router's
    # per-hop latency decomposition means and the serving generation age
    # land as first-class record fields so `cli perf diff` can VERDICT
    # them — "the router got slower" (merge/transport up) and "shard N's
    # replica got slower" (queue/execute up) become distinguishable
    # regressions instead of one conflated p99, and "how stale is
    # serving" (ROADMAP 3a) gets a baseline. None (untraced / non-route
    # records) skips the checks as usual.
    for field in (
        "serve_hop_transport_s",
        "serve_hop_decode_s",
        "serve_hop_queue_s",
        "serve_hop_batch_wait_s",
        "serve_hop_execute_s",
        "serve_hop_merge_s",
        "generation_age_s",
    ):
        v = final.get(field)
        rec[field] = (
            _round6(float(v))
            if isinstance(v, _NUM) and not isinstance(v, bool)
            else None
        )
    # self-healing fleet (ISSUE 20): the failure-path scoreboard.
    # replica_restarts comes from the supervisor's final (`cli fleet
    # up`), the rest from the router's. The rates are VERDICTED by
    # `cli perf diff` — a fleet that suddenly retries 10x more often,
    # hedges most of its traffic, or blows deadlines it used to make is
    # regressing even at flat p99 (the retries ARE hiding the latency).
    for field in ("hedged_rate", "deadline_exceeded_rate"):
        v = final.get(field)
        rec[field] = (
            _round6(float(v))
            if isinstance(v, _NUM) and not isinstance(v, bool)
            else None
        )
    for field in ("replica_restarts", "router_retries"):
        v = final.get(field)
        rec[field] = (
            int(v) if isinstance(v, _NUM) and not isinstance(v, bool)
            else None
        )
    # incremental refit (ISSUE 15): cost ratio vs the last full fit and
    # the touched fraction — both VERDICTED by `cli perf diff` (a refit
    # silently re-touching the whole graph, or costing as much as the
    # full fit it exists to avoid, is a regression even at flat step
    # time). The `refit` entry point (match_key element 0) keeps these
    # records from ever cross-baselining a fit or serve record.
    for field in ("refit_cost_ratio", "touched_frac"):
        v = final.get(field)
        rec[field] = (
            _round6(float(v))
            if isinstance(v, _NUM) and not isinstance(v, bool)
            else None
        )
    rr = final.get("refit_rounds")
    rec["refit_rounds"] = (
        int(rr) if isinstance(rr, _NUM) and not isinstance(rr, bool)
        else None
    )
    if note:
        rec["note"] = note
    return rec


def _round6(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)


def match_key(rec: Dict[str, Any]) -> Tuple:
    """Baseline identity: same entry + config + workload + representation
    + hardware + host (see module docstring). "dense" normalizes to None
    so records from entry points that never stamp a representation in
    their final outcome (always dense — the sparse trainers always
    stamp) match explicitly-stamped dense records; sparse records never
    match either. Note this does NOT resurrect pre-r11 baselines: the
    new config fields changed cfg_digest for every run, so old records
    stop matching on the digest regardless — by design, cfg-schema
    changes rebaseline."""
    rep = rec.get("representation")
    return (
        rec.get("entry"),
        rec.get("cfg_digest"),
        rec.get("n"),
        rec.get("edges"),
        rec.get("k"),
        None if rep == "dense" else rep,
        rec.get("sparse_m"),
        rec.get("backend"),
        rec.get("device_kind"),
        rec.get("host"),
        # execution shape (ISSUE 10 satellite): before these, a 2-proc
        # run silently baselined against a single-proc run on the same
        # box, and (4,1) against (2,2). Pre-field records carry None and
        # stop matching new ones — by design, the same rebaseline rule
        # as every match-key widening
        rec.get("processes"),
        rec.get("mesh"),
        # node-axis partition (ISSUE 16): 1d vs 2d runs do different
        # collective work at equal mesh size — None (pre-r20 records)
        # matches only None, the usual rebaseline rule
        rec.get("partition"),
        # 2D grad-exchange mode (ISSUE 17): closure vs dense backward
        # collectives move different bytes — None (1D / pre-r21 records)
        # matches only None, the usual rebaseline rule
        rec.get("grad_exchange"),
        # the resolved edge-kernel path (ISSUE 13): fused vs split vs
        # xla runs do different per-edge work — None (pre-r17 records /
        # entry points that never stamp it) matches only None, the same
        # rebaseline rule as every match-key widening
        rec.get("kernel_path"),
        # serving workload identity (ISSUE 14 satellite): the entry
        # point (element 0) already splits serve from fit — a serve p99
        # baseline can never cross-match a fit step-time baseline — and
        # the query-family mix splits serve runs whose per-query work
        # differs (a fold-in-heavy load is not comparable to a read-only
        # load at equal QPS). None (non-serve entries) matches None
        rec.get("serve_mix"),
        # fleet shape (ISSUE 18 satellite): a routed 2-shard × 2-replica
        # run does different per-query work (scatter-gather, TCP hops)
        # than a single-process server — fleet and single-process
        # records never cross-baseline. None matches None as usual
        rec.get("serve_shards"),
        rec.get("serve_replicas"),
    )


class PerfLedger:
    """Append-only JSONL of perf records; unparsable lines are skipped at
    read time (counted in .load_errors) so one corrupt line cannot take
    down the gate."""

    def __init__(self, path: str = DEFAULT_PATH):
        self.path = path
        self.load_errors = 0

    def append(self, rec: Dict[str, Any]) -> Dict[str, Any]:
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec

    def load(self) -> List[Dict[str, Any]]:
        self.load_errors = 0
        out: List[Dict[str, Any]] = []
        try:
            fh = open(self.path)
        except OSError:
            return out
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    self.load_errors += 1
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
                else:
                    self.load_errors += 1
        return out

    def latest(
        self, records: Optional[List[dict]] = None, run: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        records = self.load() if records is None else records
        if run is not None:
            for rec in reversed(records):
                if rec.get("run") == run:
                    return rec
            return None
        return records[-1] if records else None

    def baseline_for(
        self, rec: Dict[str, Any], records: Optional[List[dict]] = None
    ) -> Optional[Dict[str, Any]]:
        """Most recent EARLIER record with rec's match key (ledger order =
        append order; a record never baselines against itself or anything
        appended after it)."""
        records = self.load() if records is None else records
        key = match_key(rec)
        best = None
        for other in records:
            if other is rec or (
                other.get("run") == rec.get("run")
                and other.get("ts") == rec.get("ts")
            ):
                break
            if other.get("run") == rec.get("run"):
                # the same run re-recorded (auto-append + `perf record`
                # on the same dir stamps a fresh ts): identical step
                # samples, so it can never be its own baseline
                continue
            if match_key(other) == key:
                best = other
        return best


def maybe_append_env(
    report: Dict[str, Any],
    step_secs: Optional[Sequence[float]] = None,
    step_eps: Optional[Sequence[float]] = None,
    path: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """RunTelemetry.finalize hook: append this run's record when an
    explicit ledger `path` was wired (cli --perf-ledger) or
    BIGCLAM_PERF_LEDGER names one. Primary process only (one record per
    run, like events.jsonl)."""
    path = path or os.environ.get(LEDGER_ENV)
    if not path or int(report.get("pid", 0)) != 0:
        return None
    rec = build_record(report, step_secs, step_eps)
    return PerfLedger(path).append(rec)


def record_from_dir(directory: str, note: str = "") -> Dict[str, Any]:
    """Build a record from a finished telemetry directory (`cli perf
    record`): the primary run report + per-step timings recovered from the
    step events in events.jsonl."""
    from bigclam_tpu.obs.report import load_events, load_reports

    reports = load_reports(directory)
    if not reports:
        raise FileNotFoundError(f"{directory}: no run_report*.json")
    events = load_events(directory) or []
    secs = [
        float(e["sec_per_iter"])
        for e in events
        if e.get("kind") == "step"
        and isinstance(e.get("sec_per_iter"), _NUM)
    ]
    eps = [
        float(e["edges_per_sec_per_chip"])
        for e in events
        if e.get("kind") == "step"
        and isinstance(e.get("edges_per_sec_per_chip"), _NUM)
    ]
    return build_record(reports[0], secs, eps, note=note)


# ------------------------------------------------------------------- diff
def _rel_spread(rec: Dict[str, Any]) -> float:
    p50, p90 = rec.get("step_p50"), rec.get("step_p90")
    if not p50 or not p90:
        return 0.0
    return max((p90 - p50) / p50, 0.0)


def diff_records(
    base: Dict[str, Any], new: Dict[str, Any], tolerance: float = 0.25
) -> Dict[str, Any]:
    """Compare `new` against its baseline `base` (see module docstring for
    band/verdict rules). Returns a JSON-ready dict; "regression" is the
    gate verdict `cli perf diff` maps to a nonzero exit."""
    band = max(float(tolerance), _rel_spread(base), _rel_spread(new))
    checks: List[Dict[str, Any]] = []
    state = {"regression": False}

    def check(metric, bval, nval, worse_if_higher=True, band_mult=1.0,
              verdicted=True):
        if not isinstance(bval, _NUM) or not isinstance(nval, _NUM) or not bval:
            checks.append(
                {"metric": metric, "base": bval, "new": nval,
                 "skipped": True}
            )
            return
        ratio = nval / bval
        b = band * band_mult
        bad = ratio > 1.0 + b if worse_if_higher else ratio < 1.0 - b
        checks.append(
            {
                "metric": metric,
                "base": bval,
                "new": nval,
                "ratio": round(ratio, 4),
                "band": round(b, 4),
                "regression": bad,
                "verdicted": verdicted,
            }
        )
        if bad and verdicted:
            state["regression"] = True

    if new.get("steps") and base.get("steps"):
        check("step_p50", base.get("step_p50"), new.get("step_p50"))
        # p99 is a SINGLE sample on short runs (one GC pause or page fault
        # owns it): reported with a doubled band, never verdicted — the
        # gate verdict rides the median and throughput
        check("step_p99", base.get("step_p99"), new.get("step_p99"),
              band_mult=2.0, verdicted=False)
        check("eps_p50", base.get("eps_p50"), new.get("eps_p50"),
              worse_if_higher=False)
    elif isinstance(new.get("serve_p99_s"), _NUM) and isinstance(
        base.get("serve_p99_s"), _NUM
    ):
        # serving runs (ISSUE 14): the SLO axes are tail latency and
        # throughput. serve_p99 is a percentile over per-request samples
        # (hundreds per run), not the trainer's single-sample step_p99 —
        # it is VERDICTED, which is the whole point of the serve gate's
        # ledger baseline. Cache hit rate is a finding (worse_if_higher
        # False, not verdicted): a mix change legitimately moves it
        check("serve_p99_s", base["serve_p99_s"], new["serve_p99_s"])
        check("serve_p50_s", base.get("serve_p50_s"),
              new.get("serve_p50_s"))
        check("serve_qps", base.get("serve_qps"), new.get("serve_qps"),
              worse_if_higher=False)
        check("cache_hit_rate", base.get("cache_hit_rate"),
              new.get("cache_hit_rate"), worse_if_higher=False,
              verdicted=False)
        # fleet shed rate (ISSUE 18 satellite): admission control
        # shedding materially more of the load at flat p99 is a
        # capacity regression — verdicted on router records (check()
        # itself skips when the baseline shed nothing)
        check("serve_shed_rate", base.get("serve_shed_rate"),
              new.get("serve_shed_rate"))
        # per-hop decomposition (ISSUE 19): verdicted separately so the
        # diff NAMES the slow hop. Hop means are micro-quantities over
        # traced samples — noisier than the aggregate p99 — so they get
        # a wider band (2x the p50->p90-spread-widened tolerance)
        for hop in ("transport", "decode", "queue", "batch_wait",
                    "execute", "merge"):
            field = f"serve_hop_{hop}_s"
            check(field, base.get(field), new.get(field), band_mult=2.0)
        # freshness (ROADMAP 3a): serving a materially older generation
        # than baseline is a staleness regression — the publish cadence
        # broke, not the query path. Wall-clock age is scheduler-noisy,
        # hence the widest band
        check("generation_age_s", base.get("generation_age_s"),
              new.get("generation_age_s"), band_mult=4.0)
        # self-healing rates (ISSUE 20): verdicted when the baseline
        # exercised them (check() skips a zero/None baseline — a
        # fault-free baseline cannot band a chaos run). Retries going UP
        # at flat p99 means the fleet is failing more and hiding it;
        # hedges going up means the tail got heavier; deadline misses
        # are client-visible errors.
        check("router_retries", base.get("router_retries"),
              new.get("router_retries"))
        check("hedged_rate", base.get("hedged_rate"),
              new.get("hedged_rate"))
        check("deadline_exceeded_rate",
              base.get("deadline_exceeded_rate"),
              new.get("deadline_exceeded_rate"))
    else:
        # steploss entries (ingest, report-only runs): wall time is the
        # only comparable figure
        check("wall_s", base.get("wall_s"), new.get("wall_s"))
    if isinstance(base.get("hbm_frac"), _NUM) and isinstance(
        new.get("hbm_frac"), _NUM
    ):
        check("hbm_frac", base["hbm_frac"], new["hbm_frac"],
              worse_if_higher=False)
    # collective-traffic verdicts (obs.comms, ISSUE 10): modeled
    # bytes/step growing past the band is a layout regression the
    # step-time checks cannot see on a small testbed (the wire cost
    # scales with the pod, the CPU fake's doesn't); a shrinking overlap
    # fraction means rotation hops stopped hiding behind compute
    if isinstance(base.get("comms_bytes_per_step"), _NUM) and isinstance(
        new.get("comms_bytes_per_step"), _NUM
    ):
        check("comms_bytes_per_step", base["comms_bytes_per_step"],
              new["comms_bytes_per_step"])
    if isinstance(base.get("overlap_frac"), _NUM) and isinstance(
        new.get("overlap_frac"), _NUM
    ):
        check("overlap_frac", base["overlap_frac"], new["overlap_frac"],
              worse_if_higher=False)
    # memory verdicts (obs.memory, ISSUE 12): modeled per-device HBM or
    # modeled host-RSS growing past the band is a capacity regression —
    # invisible to step time on a small testbed, fatal on the pod whose
    # HBM the config was sized against
    if isinstance(base.get("hbm_modeled_bytes"), _NUM) and isinstance(
        new.get("hbm_modeled_bytes"), _NUM
    ):
        check("hbm_modeled_bytes", base["hbm_modeled_bytes"],
              new["hbm_modeled_bytes"])
    if isinstance(
        base.get("host_rss_modeled_bytes"), _NUM
    ) and isinstance(new.get("host_rss_modeled_bytes"), _NUM):
        check("host_rss_modeled_bytes", base["host_rss_modeled_bytes"],
              new["host_rss_modeled_bytes"])
    # incremental-refit verdicts (ISSUE 15): refit_cost_ratio growing
    # past the band means the warm-start stopped saving work vs the
    # full fit it replaces; touched_frac growing means a delta of the
    # same shape started touching more of the graph (halo/discovery
    # regression). Both only exist on `refit` entries, which the match
    # key (entry element 0) keeps disjoint from fit/serve baselines.
    if isinstance(base.get("refit_cost_ratio"), _NUM) and isinstance(
        new.get("refit_cost_ratio"), _NUM
    ):
        check("refit_cost_ratio", base["refit_cost_ratio"],
              new["refit_cost_ratio"])
    if isinstance(base.get("touched_frac"), _NUM) and isinstance(
        new.get("touched_frac"), _NUM
    ):
        check("touched_frac", base["touched_frac"],
              new["touched_frac"])
    # fleet supervision verdict (ISSUE 20): a `cli fleet up` record
    # whose restart count grew past the band means replicas are dying
    # more than the matched baseline drill — a stability regression the
    # router's retry counters can mask. check() skips a zero baseline
    # (a clean run cannot band a chaos drill).
    if isinstance(base.get("replica_restarts"), _NUM) and isinstance(
        new.get("replica_restarts"), _NUM
    ):
        check("replica_restarts", base["replica_restarts"],
              new["replica_restarts"])
    # convergence verdicts (ISSUE 8): iteration count to tolerance is
    # VERDICTED (same cfg + workload + seed ⇒ deterministic up to float
    # summation order — growth past the band is a real optimizer
    # regression, not timing noise); the final grad norm is reported as a
    # finding (its scale is workload-dependent)
    if isinstance(base.get("iters_to_tol"), _NUM) and isinstance(
        new.get("iters_to_tol"), _NUM
    ):
        check("iters_to_tol", base["iters_to_tol"], new["iters_to_tol"])
    if isinstance(base.get("final_grad_norm"), _NUM) and isinstance(
        new.get("final_grad_norm"), _NUM
    ):
        check("final_grad_norm", base["final_grad_norm"],
              new["final_grad_norm"], verdicted=False)

    # findings (reported, never verdicted): compile growth + span deltas
    compile_growth = int(new.get("compiles", 0)) - int(
        base.get("compiles", 0)
    )
    deltas = []
    bspans, nspans = base.get("spans", {}) or {}, new.get("spans", {}) or {}
    for path in sorted(set(bspans) & set(nspans)):
        bs, ns = float(bspans[path]), float(nspans[path])
        if bs > 0:
            deltas.append(
                {"path": path, "base_s": bs, "new_s": ns,
                 "ratio": round(ns / bs, 4)}
            )
    deltas.sort(key=lambda d: -d["ratio"])
    # per-site comms deltas (findings — the verdict rides the total):
    # which collective site grew is the actionable half of a bytes/step
    # regression
    comms_deltas = []
    bc = base.get("comms_sites", {}) or {}
    nc = new.get("comms_sites", {}) or {}
    for site in sorted(set(bc) & set(nc)):
        bs, ns = float(bc[site]), float(nc[site])
        if bs > 0 and ns != bs:
            comms_deltas.append(
                {"site": site, "base_bytes": bs, "new_bytes": ns,
                 "ratio": round(ns / bs, 4)}
            )
    comms_deltas.sort(key=lambda d: -d["ratio"])
    return {
        "base_run": base.get("run"),
        "new_run": new.get("run"),
        "band": round(band, 4),
        "checks": checks,
        "regression": state["regression"],
        "compile_growth": compile_growth,
        "span_deltas": deltas[:8],
        "comms_deltas": comms_deltas[:8],
        # finding, not a verdict: anomaly events in the new run (the
        # detectors already said WHAT; the diff just surfaces that the
        # baseline was clean and the new run was not)
        "anomalies_new": int(new.get("anomalies", 0) or 0),
        "anomalies_base": int(base.get("anomalies", 0) or 0),
    }


def render_diff(d: Dict[str, Any]) -> str:
    lines = [
        f"perf diff: run {d['new_run']} vs baseline {d['base_run']} "
        f"(noise band {d['band']:.0%})"
    ]
    for c in d["checks"]:
        if c.get("skipped"):
            lines.append(
                f"  {c['metric']:<10} skipped "
                f"(base={c['base']} new={c['new']})"
            )
            continue
        verdict = (
            "REGRESSION"
            if c["regression"] and c.get("verdicted", True)
            else ("slow (not verdicted)" if c["regression"] else "ok")
        )
        lines.append(
            f"  {c['metric']:<10} base {c['base']:<12g} new {c['new']:<12g}"
            f" ratio {c['ratio']:.3f} (band {c['band']:.0%})  {verdict}"
        )
    if d.get("compile_growth"):
        lines.append(
            f"  note: compile count changed by {d['compile_growth']:+d}"
        )
    if d.get("anomalies_new") and not d.get("anomalies_base"):
        lines.append(
            f"  note: {d['anomalies_new']} health anomaly event(s) in the "
            "new run (baseline was clean) — see `cli report`"
        )
    hot = [s for s in d.get("span_deltas", []) if s["ratio"] > 1.0]
    if hot:
        lines.append("  slowest-growing spans:")
        for s in hot[:3]:
            lines.append(
                f"    {s['path']:<32} {s['base_s']:.3f}s -> "
                f"{s['new_s']:.3f}s ({s['ratio']:.2f}x)"
            )
    grew = [c for c in d.get("comms_deltas", []) if c["ratio"] > 1.0]
    if grew:
        lines.append("  collective sites moving more bytes/step:")
        for c in grew[:3]:
            lines.append(
                f"    {c['site']:<32} {c['base_bytes']:.0f} -> "
                f"{c['new_bytes']:.0f} B/step ({c['ratio']:.2f}x)"
            )
    lines.append(
        "  verdict: " + ("REGRESSION" if d["regression"] else "PASS")
    )
    return "\n".join(lines)
