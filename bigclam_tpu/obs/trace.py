"""Hierarchical span tracing (ISSUE 6 tentpole, part a): WHERE the time went.

PR 4's telemetry records what happened (events, watermarks, compile counts)
but not where the time went — and the step-time pushes on the roadmap
(store-native compute, fused Pallas edge kernel) cannot be claimed or
defended without per-phase attribution ("Speeding Up BigClam",
arXiv:1712.01209, got its wins precisely by knowing which phase dominated).
A `span` is a named, nested wall-clock interval:

    with span("fit_loop"):
        with span("dispatch", emit=False):
            ...

Spans nest by a per-thread stack: a span's PATH is its parent's path plus
its own name ("fit/fit_loop/dispatch" when the CLI's "fit" stage encloses
the loop), so the same instrumentation yields stable, hierarchical
attribution from every entry point. Two sinks, both on the installed
RunTelemetry:

* running per-path totals (seconds + counts) — always, one dict update
  under the telemetry lock; these feed the run report's span table,
  `cli report`'s breakdown, and the perf ledger (obs.ledger);
* a `span` event in events.jsonl on close — only for `emit=True` spans.
  High-frequency spans (the fit loop's per-iteration phases) use
  `emit=False`: exact totals, no per-occurrence event, so a 10^5-iteration
  fit does not write 4x10^5 event lines.

COST CONTRACT (pinned by tests/test_trace.py): with telemetry off,
`span()` returns one shared no-op object — no event, no dict, no stack
touch (the off path is a current()-is-None check). With telemetry on and
no profiler capture, the whole per-iteration span set costs <2% of the
step time.

XLA-PROFILE ALIGNMENT: when jax is already loaded (sys.modules probe —
this module must stay importable on jax-free entry points like `cli
ingest`), every span additionally opens a jax.profiler.TraceAnnotation
with the span's path, so a captured device profile (`cli profile`,
--profile-dir) carries OUR phase names on the TraceMe timeline. The shim
resolves lazily and tolerates jax builds without the API.

THREAD MODEL: the span stack is per-thread (plain dict keyed by thread id;
list push/pop are GIL-atomic). `open_spans()` snapshots every thread's
open stack — the stall heartbeat embeds it so a stall report answers
"stuck in which phase" instead of only "no progress for Ns".

CLOSE INVARIANTS: closes are exception-safe (the context manager records
the interval with ok=False and still pops). A close that finds younger
spans still open above it (a span entered and abandoned without exit)
repairs the stack — the abandoned entries are dropped and counted in the
telemetry's span_orphans counter, so misuse is visible, never corrupting.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List

from bigclam_tpu.obs import telemetry as _telemetry

# thread id -> stack of open span PATHS (innermost last). Mutations are
# single-owner (each thread touches only its own list) and list append/pop
# are atomic under the GIL; readers (heartbeat, tests) take snapshots.
_STACKS: Dict[int, List[str]] = {}

# jax.profiler TraceAnnotation / StepTraceAnnotation, resolved lazily and
# only when jax is ALREADY imported (never triggers the import)
_ANN = {"resolved": False, "cls": None, "step_cls": None}

# profiler-capture refcount, flipped by utils.profiling.trace (every
# capture in this repo goes through it: --profile-dir, `cli profile`).
# emit=False spans only pay the TraceAnnotation construction while a
# capture is live — that object is the dominant per-span cost, and the
# no-capture overhead contract (<2% of step time) is what per-iteration
# spans are held to. emit=True spans (stages, cycles — low frequency)
# always annotate, so an externally-started capture still sees them.
_CAPTURE = {"active": 0}


def capture_started() -> None:
    _CAPTURE["active"] += 1


def capture_stopped() -> None:
    _CAPTURE["active"] = max(_CAPTURE["active"] - 1, 0)


def capture_active() -> bool:
    return _CAPTURE["active"] > 0


class _NullSpan:
    """The telemetry-off span: one shared instance, no state, no work."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **fields) -> None:
        pass


NULL_SPAN = _NullSpan()


def _resolve_annotations():
    if _ANN["resolved"]:
        return
    if "jax" not in sys.modules:
        return                   # stay unresolved; maybe jax loads later
    try:
        from jax import profiler as _prof

        _ANN["cls"] = getattr(_prof, "TraceAnnotation", None)
        _ANN["step_cls"] = getattr(_prof, "StepTraceAnnotation", None)
    except Exception:
        _ANN["cls"] = _ANN["step_cls"] = None
    _ANN["resolved"] = True


def step_annotation(step_num: int, name: str = "train"):
    """jax.profiler.StepTraceAnnotation for one profiled step (the profiler
    UI groups TraceMes under step boundaries), or the no-op span when jax
    is not loaded / the API is absent. `cli profile` wraps each timed step
    in one of these so the XLA timeline and our span names align."""
    _resolve_annotations()
    cls = _ANN["step_cls"]
    if cls is None:
        return NULL_SPAN
    try:
        return cls(name, step_num=int(step_num))
    except Exception:
        return NULL_SPAN


class Span:
    """One open span (use via `span(...)`, not directly). Context-manager
    only; `set(**fields)` attaches extra fields to the close event."""

    __slots__ = ("_tel", "name", "emit", "fields", "path", "_t0", "_ann")

    def __init__(self, tel, name: str, emit: bool, fields: dict):
        self._tel = tel
        self.name = name
        self.emit = emit
        self.fields = fields
        self.path = name
        self._t0 = 0.0
        self._ann = None

    def set(self, **fields) -> None:
        self.fields.update(fields)

    def __enter__(self) -> "Span":
        tid = threading.get_ident()
        stack = _STACKS.get(tid)
        if stack is None:
            stack = _STACKS.setdefault(tid, [])
        self.path = f"{stack[-1]}/{self.name}" if stack else self.name
        stack.append(self.path)
        if self.emit or _CAPTURE["active"]:
            _resolve_annotations()
            cls = _ANN["cls"]
            if cls is not None:
                try:
                    self._ann = cls(self.path)
                    self._ann.__enter__()
                except Exception:
                    self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        dt = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(et, ev, tb)
            except Exception:
                pass
        orphans = 0
        stack = _STACKS.get(threading.get_ident())
        if stack and stack[-1] == self.path:
            stack.pop()
        elif stack and self.path in stack:
            # younger spans were entered and never exited: repair — drop
            # them (counted), then pop ourselves
            idx = len(stack) - 1 - stack[::-1].index(self.path)
            orphans = len(stack) - idx - 1
            del stack[idx:]
        # else: our entry is already gone (an enclosing span repaired past
        # us) — the interval is still real, record it without re-counting
        self._tel.span_complete(
            self.path, dt, ok=et is None, emit=self.emit,
            fields=self.fields, orphans=orphans,
        )
        return False


def span(name: str, emit: bool = True, **fields):
    """Open a span named `name` under the installed telemetry.

    Returns the shared no-op object when telemetry is off — the zero-cost
    contract (no Span construction, no stack or dict touch). `emit=False`
    keeps exact per-path totals but writes no per-occurrence event (for
    per-iteration phases). Extra keyword `fields` ride the close event."""
    tel = _telemetry.current()
    if tel is None:
        return NULL_SPAN
    return Span(tel, name, emit, fields)


def add_span(name: str, seconds: float, emit: bool = True, **fields) -> None:
    """Record an already-measured interval as a span completion at the
    current stack position (StageProfile.add_seconds' bridge: loops that
    time themselves still land in the span taxonomy). No-op when off."""
    tel = _telemetry.current()
    if tel is None:
        return
    stack = _STACKS.get(threading.get_ident())
    path = f"{stack[-1]}/{name}" if stack else name
    tel.span_complete(path, seconds, ok=True, emit=emit, fields=fields)


_TRACE_ID = {"n": 0}
_TRACE_ID_LOCK = threading.Lock()


def new_trace_id() -> str:
    """Process-unique compact trace id ("<pid hex>-<seq hex>") for the
    distributed query trace context (ISSUE 19): the fleet router stamps
    one on every routed query and correlates the per-hop timing blocks
    its replicas echo. Counter-based, not random — ids stay short,
    collision-free within a process, and orderable per router."""
    with _TRACE_ID_LOCK:
        _TRACE_ID["n"] += 1
        n = _TRACE_ID["n"]
    return f"{os.getpid():x}-{n:x}"


def open_spans() -> List[str]:
    """Snapshot of every thread's open span paths, innermost last per
    thread — what the stall heartbeat embeds in `stall` events."""
    out: List[str] = []
    for stack in list(_STACKS.values()):
        out.extend(list(stack))
    return out


def current_path() -> str:
    """The calling thread's innermost open span path ('' when none)."""
    stack = _STACKS.get(threading.get_ident())
    return stack[-1] if stack else ""
