"""Run-scoped telemetry (ISSUE 4): one structured event log + device-memory
watermarks + compile counters + stall heartbeat + final run report, shared
by every entry point (cli fit/sweep/ingest, bench.py, the gate scripts, the
multihost workers).

The reference's only instrumentation was `println` of iteration and LLH
(SURVEY.md §5). The pre-existing slices — MetricsLogger JSONL, StageProfile
/ IngestProfile, overlap_report — are SINKS of this layer now: they keep
their local contracts (per-step JSONL, per-stage seconds in artifacts) and
additionally forward into the active RunTelemetry, so one events.jsonl
carries steps, stage transitions, checkpoint saves, compiles, memory
watermarks and stalls under a single schema (obs.schema).

Activation is a process-global current-telemetry slot (install/current):
entry points create and install a RunTelemetry; library code asks
`current()` and does nothing when telemetry is off — the off path costs one
None check, which is what keeps the fit loop's overhead pinned under 2%
(tests/test_telemetry.py).

ISSUE 6 adds the perf-observability pair on top: obs.trace (hierarchical
span tracing — WHERE the time went, per phase, aligned with captured XLA
profiles) and obs.ledger (a persistent perf ledger + `cli perf diff`
regression gate with noise bands). Spans share the events.jsonl schema
(kind `span`) and the RunTelemetry sinks; the ledger appends one compact
record per run when BIGCLAM_PERF_LEDGER is set.
"""

from bigclam_tpu.obs.comms import (
    IMBALANCE_FACTOR,
    CommsModel,
    balance_stats,
    detect_host_skew,
)
from bigclam_tpu.obs.health import DEFAULTS as HEALTH_DEFAULTS
from bigclam_tpu.obs.memory import (
    HostModel,
    MemoryModel,
    measured_device_bytes,
    preflight,
)
from bigclam_tpu.obs.health import HealthMonitor, run_detectors
from bigclam_tpu.obs.heartbeat import Heartbeat
from bigclam_tpu.obs.ledger import LEDGER_ENV, PerfLedger
from bigclam_tpu.obs.schema import (
    EVENT_KINDS,
    SCHEMA_VERSION,
    validate_event,
    validate_events_file,
)
from bigclam_tpu.obs.telemetry import (
    RunTelemetry,
    current,
    install,
    note_step_build,
    uninstall,
)
from bigclam_tpu.obs.trace import add_span, open_spans, span, step_annotation

__all__ = [
    "CommsModel",
    "EVENT_KINDS",
    "HEALTH_DEFAULTS",
    "HealthMonitor",
    "Heartbeat",
    "HostModel",
    "IMBALANCE_FACTOR",
    "LEDGER_ENV",
    "MemoryModel",
    "measured_device_bytes",
    "PerfLedger",
    "preflight",
    "RunTelemetry",
    "SCHEMA_VERSION",
    "add_span",
    "balance_stats",
    "detect_host_skew",
    "current",
    "install",
    "note_step_build",
    "open_spans",
    "run_detectors",
    "span",
    "step_annotation",
    "uninstall",
    "validate_event",
    "validate_events_file",
]
