"""Model-health monitoring + anomaly detection (ISSUE 8, host half).

The device half (ops.diagnostics) fuses a compact health pack into every
jitted train step; this module is the consumer run_fit_loop drives at the
cfg.health_every cadence:

* HealthMonitor fetches the pack (one tiny D2H per cadence iteration,
  after the loop's existing LLH sync), adds what only the host can know —
  LLH delta / slope / relative change over the sample window, membership
  churn against a rolling device-resident top-community signature, the
  exchanged-ids high-water — and emits one `health` event per sample.

* run_detectors is a PURE function over the sample window (list of
  dicts in, list of anomaly dicts out — unit-testable without jax), with
  deterministic, threshold-based rules:

    divergence    LLH below the best-so-far by more than div_tol for
                  div_patience consecutive samples (catches both the
                  monotone slope blow-up and a growing oscillation; a
                  healthy Armijo ascent never degrades past float noise)
    plateau       |relative LLH change| inside max(plateau_mult *
                  conv_tol, plateau_floor) for plateau_patience
                  consecutive samples — the fit is crawling just above
                  the stop rule (or, at conv_tol=0, flat outright):
                  plateau-before-tol, the K-sweep stop rule's blind spot
    oscillation   LLH deltas strictly alternating sign with relative
                  magnitude above osc_min_rel for osc_patience
                  consecutive alternations (step ladder too hot)
    dead_communities   dead-column fraction >= dead_frac_max (gradient
                  dynamics can never revive an all-zero column — see
                  PARITY.md; quality mode exists for this)
    cap_pressure  sparse-allreduce occupancy >= cap_frac of the comm
                  cap, or a runtime dense-psum fallback fired — the
                  build-time cap guess (arXiv:1312.3020) is invalidated

Each anomaly kind fires at most ONCE per monitor (= per fit loop): the
events are findings, and a 40-sample divergence is one finding, not 40
lines. Thresholds are host-side knobs (DEFAULTS, overridable per
monitor), deliberately NOT config fields: they gate nothing and must not
rebaseline the perf ledger's cfg digests.

The emitted `health` events also enrich the rest of the stack: telemetry
keeps the last snapshot (RunTelemetry.last_health) so heartbeat stall /
stall_escalated reports distinguish "stuck compiling" from "diverging",
the run report grows a health section, and the perf ledger records
iters-to-tol + final grad norm for convergence-regression diffs.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

# NOTE: ops.diagnostics (and with it jax) is imported LAZILY inside the
# monitor methods — this module rides the jax-free obs package import
# (cli ingest / cli watch / cli report run on data-prep hosts), and
# run_detectors is pure numpy by design.

# detector thresholds (see module docstring); all overridable via the
# HealthMonitor `thresholds` kwarg / run_detectors argument
DEFAULTS: Dict[str, float] = {
    "div_tol": 0.02,         # rel degradation vs best-so-far LLH
    "div_patience": 3,       # consecutive degraded samples
    "plateau_mult": 3.0,     # plateau band = plateau_mult * conv_tol ...
    "plateau_floor": 1e-7,   # ... floored here (conv_tol=0 probe runs)
    "plateau_patience": 8,   # consecutive flat samples
    "osc_patience": 5,       # consecutive sign alternations
    "osc_min_rel": 1e-6,     # alternation magnitude floor (rel to |llh|)
    "dead_frac_max": 0.75,   # dead-community fraction alarm
    "cap_frac": 0.85,        # comm-cap occupancy alarm
}

# trailing samples the detectors look at (divergence additionally uses
# the monitor's running best, so the bound does not blunt it)
WINDOW = 64

# pack slots that mean "not produced by this trainer" when negative
_NA_SLOTS = (
    "support_churn", "cap_occupancy", "dense_fallback", "exchanged_ids",
)
_INT_FIELDS = ("active_comms", "exchanged_ids")


def _rel(a: float, b: float) -> float:
    """|a - b| relative to |b| with the b == 0 corner (all-zero F0 has
    LLH exactly 0.0) handled like models.bigclam._rel_change."""
    if b == 0.0:
        return 0.0 if a == 0.0 else float("inf")
    return abs(a - b) / abs(b)


def run_detectors(
    samples: List[Dict[str, Any]],
    best_llh: Optional[float],
    conv_tol: float,
    thresholds: Optional[Dict[str, float]] = None,
) -> List[Dict[str, Any]]:
    """Anomalies present in the CURRENT window (pure; see module
    docstring for the rules). `samples` is the ordered health-sample
    window (dicts with at least iter + llh; optional dead_frac,
    cap_occupancy, dense_fallback), `best_llh` the best LLH ever
    observed by the monitor (None = use the window max). De-duplication
    across calls is the caller's job (HealthMonitor fires each check
    once)."""
    th = {**DEFAULTS, **(thresholds or {})}
    out: List[Dict[str, Any]] = []
    if not samples:
        return out
    last = samples[-1]
    it = int(last.get("iter", -1))
    if best_llh is not None:
        best = best_llh
    else:
        llhs = [s["llh"] for s in samples if isinstance(
            s.get("llh"), (int, float)) and math.isfinite(s.get("llh"))]
        best = max(llhs) if llhs else None

    # --- divergence: trailing run of samples degraded past div_tol ---
    if best is not None and math.isfinite(best):
        run = 0
        worst_drop = 0.0
        for s in reversed(samples):
            llh = s.get("llh")
            if not isinstance(llh, (int, float)) or not math.isfinite(llh):
                break
            drop = _rel(llh, best) if llh < best else 0.0
            if llh < best and drop > th["div_tol"]:
                run += 1
                worst_drop = max(worst_drop, drop)
            else:
                break
        if run >= th["div_patience"]:
            out.append({
                "check": "divergence", "iter": it, "samples": run,
                "rel_drop": round(worst_drop, 6), "best_llh": best,
            })

    # --- plateau-before-tol: trailing run of flat samples ---
    band = max(th["plateau_mult"] * float(conv_tol), th["plateau_floor"])
    run = 0
    for prev, cur in zip(reversed(samples[:-1]), reversed(samples)):
        a, b = cur.get("llh"), prev.get("llh")
        if not (isinstance(a, (int, float)) and isinstance(b, (int, float))
                and math.isfinite(a) and math.isfinite(b)):
            break
        if _rel(a, b) < band:
            run += 1
        else:
            break
    if run >= th["plateau_patience"]:
        out.append({
            "check": "plateau", "iter": it, "samples": run,
            "band": band, "conv_tol": conv_tol,
        })

    # --- oscillation: trailing strict sign alternation of LLH deltas ---
    deltas = []
    for prev, cur in zip(samples[:-1], samples[1:]):
        a, b = prev.get("llh"), cur.get("llh")
        if not (isinstance(a, (int, float)) and isinstance(b, (int, float))
                and math.isfinite(a) and math.isfinite(b)):
            deltas.append(0.0)
            continue
        deltas.append(b - a)
    flips = 0
    for d_prev, d_cur in zip(reversed(deltas[:-1]), reversed(deltas)):
        scale = max(abs(samples[-1]["llh"]), 1e-30)
        if (
            d_prev * d_cur < 0
            and abs(d_cur) / scale > th["osc_min_rel"]
            and abs(d_prev) / scale > th["osc_min_rel"]
        ):
            flips += 1
        else:
            break
    if flips >= th["osc_patience"]:
        out.append({
            "check": "oscillation", "iter": it, "alternations": flips,
        })

    # --- dead communities ---
    df = last.get("dead_frac")
    if isinstance(df, (int, float)) and df >= th["dead_frac_max"]:
        out.append({
            "check": "dead_communities", "iter": it,
            "dead_frac": round(float(df), 4),
            "dead_comms": last.get("dead_comms"),
        })

    # --- sparse comm-cap pressure ---
    occ = last.get("cap_occupancy")
    fb = last.get("dense_fallback")
    occ_hot = isinstance(occ, (int, float)) and occ >= th["cap_frac"]
    fell_back = isinstance(fb, (int, float)) and fb >= 1.0
    if occ_hot or fell_back:
        out.append({
            "check": "cap_pressure", "iter": it,
            "cap_occupancy": occ, "dense_fallback": fell_back,
        })
    return out


class HealthMonitor:
    """One fit loop's health consumer (constructed by run_fit_loop when
    telemetry is active and cfg.health_every > 0). Not thread-safe — it
    runs on the fit loop's thread, like the loop's other bookkeeping."""

    def __init__(self, cfg, telemetry, sig_fn=None, n_live=None,
                 thresholds: Optional[Dict[str, float]] = None):
        self.every = max(int(getattr(cfg, "health_every", 1) or 1), 1)
        self.k = max(int(cfg.num_communities), 1)
        self.conv_tol = float(cfg.conv_tol)
        self.tel = telemetry
        self.sig_fn = sig_fn
        # live node count for the churn denominator (the signature is
        # PADDED; padding rows are -1 forever and never churn, so
        # dividing by the padded length would systematically dilute the
        # fraction). None = unknown, fall back to the signature length.
        self.n_live = int(n_live) if n_live else None
        self.th = {**DEFAULTS, **(thresholds or {})}
        self.samples: List[Dict[str, Any]] = []
        self.best_llh: Optional[float] = None
        self.exchanged_max = 0.0
        self._sig = None
        self._fired: set = set()

    def maybe_observe(self, it: int, llh: float, state) -> None:
        """Per-iteration hook (run_fit_loop): one modulo + one getattr
        off-cadence."""
        if it % self.every:
            return
        pack = getattr(state, "health", None)
        if pack is None:
            return
        from bigclam_tpu.ops.diagnostics import HEALTH_INDEX

        vec = np.asarray(pack, dtype=np.float64)
        if vec[HEALTH_INDEX["iter"]] < 0:
            return              # pack's cond disagreed (resumed mid-cadence)
        self.observe(it, llh, vec, state)

    def observe(self, it: int, llh: float, vec: np.ndarray, state) -> None:
        from bigclam_tpu.ops.diagnostics import HEALTH_FIELDS, HEALTH_INDEX, NA

        fields: Dict[str, Any] = {}
        for name in HEALTH_FIELDS:
            if name in ("iter", "llh"):
                continue        # stamped from the loop's own scalars
            v = float(vec[HEALTH_INDEX[name]])
            if name in _NA_SLOTS and v == NA:
                continue        # trainer does not produce this slot
            fields[name] = int(v) if name in _INT_FIELDS else round(v, 8)
        active = int(fields.get("active_comms", self.k))
        dead = max(self.k - active, 0)
        fields["dead_comms"] = dead
        fields["dead_frac"] = round(dead / self.k, 6)
        if "exchanged_ids" in fields:
            self.exchanged_max = max(
                self.exchanged_max, fields["exchanged_ids"]
            )
            fields["exchanged_max"] = int(self.exchanged_max)
        # membership churn vs the rolling snapshot: an (N,) int32 device
        # signature, compared device-side — no F fetch
        if self.sig_fn is not None:
            from bigclam_tpu.ops.diagnostics import sig_changed

            try:
                sig = self.sig_fn(state)
            except Exception:
                sig = None      # diagnostics must never kill the fit
            if sig is not None:
                if self._sig is not None:
                    changed = int(sig_changed(self._sig, sig))
                    denom = self.n_live or int(np.prod(sig.shape))
                    fields["churn"] = round(changed / max(denom, 1), 6)
                self._sig = sig
        # LLH-window derivatives
        prev = self.samples[-1] if self.samples else None
        if prev is not None and math.isfinite(llh) and math.isfinite(prev["llh"]):
            delta = llh - prev["llh"]
            fields["llh_delta"] = delta
            if it > prev["iter"]:
                fields["llh_slope"] = delta / (it - prev["iter"])
            fields["llh_rel_change"] = _rel(llh, prev["llh"])
        sample = {"iter": it, "llh": llh, **fields}
        self.samples.append(sample)
        del self.samples[:-WINDOW]
        if math.isfinite(llh) and (
            self.best_llh is None or llh > self.best_llh
        ):
            self.best_llh = llh
        self.tel.event("health", **sample)
        for anomaly in run_detectors(
            self.samples, self.best_llh, self.conv_tol, self.th
        ):
            if anomaly["check"] in self._fired:
                continue
            self._fired.add(anomaly["check"])
            self.tel.event("anomaly", **anomaly)
