"""Memory accounting: static HBM / host-RSS models, live reconciliation,
and the capacity preflight (ISSUE 12).

The obs stack accounts for time (obs.trace + the perf ledger), model
health (obs.health), and wire bytes (obs.comms) — but the axis that
actually kills a pod run, MEMORY, was only sampled (`Device.memory_stats`
watermarks), never modeled: the only way to learn whether a config fits
in HBM, or whether the host-global O(N*K) F0 upload OOMs the host, was
to launch it. Memory-constrained graph clustering at scale lives or dies
on exactly this per-device capacity model (HipMCL's pre-exascale
analysis, arXiv:2002.10083), and per-replica state accounting is the
same discipline that makes sharded-update training plannable
(arXiv:2004.13336). This module makes both first-class, gateable run
signals, mirroring the comms-model pattern (obs.comms):

* **Static per-device HBM model.** Each trainer family bakes a
  `MemoryModel` at step-build time: one `Buffer` per live device buffer
  of its compiled step, built from the SAME shape arithmetic the trainer
  committed (n_pad/k_pad/dp/tp/M, the committed edge/tile layout's slot
  counts). Buffer categories:

    state      the TrainState arrays (F/sumF/scalars; ids+weights on the
               sparse representation) — per-device shard bytes
    graph      the committed edge blocks / CSR tiles / support blocks
               the compiled step keeps resident (jit args or closure
               constants) — per-device shard bytes
    scratch    persistent state-sized extras: the donation ping-pong
               twin (cfg.donate_state) and the in-HBM rollback snapshot
               (cfg.rollback_budget)
    transient  peak in-step temporaries: the all-gathered F / member
               lists, the ring's rotating shard pair, the dst-row
               gather, the gradient, the Armijo candidate accumulators
    collective the largest single-occurrence collective receive buffer,
               PRICED FROM THE COMMS SITES (obs.comms) the trainer
               already baked — the two models can never disagree about
               what is on the wire

  Emitted as schema'd `memory_model` events (one per buffer), summed
  into the run report and the perf ledger (`hbm_modeled_bytes`,
  `host_rss_modeled_bytes`, both VERDICTED by `cli perf diff`).

* **Reconciliation.** `MemoryModel.addressable_bytes()` — the state +
  graph categories — is the part of the model that corresponds to
  long-lived, addressable device buffers, and `measured_device_bytes`
  sums the LIVE per-device shard nbytes of exactly those arrays. On the
  CPU fake the two agree EXACTLY (scripts/memory_gate.py asserts drift
  == 0); `reconcile` flags drift past the band as a `memory_drift`
  anomaly — the leak/retained-buffer detector (a snapshot that should
  have been donated, a cached gather that outlived its step). Where
  `Device.memory_stats` exists (TPU), the watermark layer
  (RunTelemetry.device_peak — sampled at stage boundaries AND on the
  heartbeat cadence since this PR) gives the allocator-level second
  opinion the report renders next to the model.

* **Host-RSS model.** A per-stage model of the host side: the ingest
  chunk budget (the same explicit formula INGEST_r07 gates), the graph /
  shard load, seeding, and the host-global O(N*K) F0 init — flagged as
  the DOMINANT host term (ROADMAP item 1a: the per-host init_state
  refactor is what removes it; --store-native shrinks every other stage
  to O(shard) but NOT this one yet).

* **Preflight.** `preflight()` builds the same models from a config + a
  workload (cache manifest numbers or text-size estimates) + a
  device-kind/count target, with NO jax and NO arrays — the go/no-go
  answer `cli preflight` prints before a pod job touches hardware:
  predicted per-device HBM, per-host RSS, bytes/step, a fits-or-doesn't
  verdict naming the binding constraint, and the knobs that relax it
  (sparse_m, csr tile shape, mesh, --schedule ring, --store-native).

jax-free at import, like every obs module: `cli preflight` and `cli
report` run on data-prep hosts.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from bigclam_tpu.obs.comms import CommsModel, wire_bytes

# (HEALTH_LEN,) float32 health pack riding the TrainState when
# cfg.health_every > 0 — mirrored from ops.diagnostics.HEALTH_LEN (which
# imports jax; the tier-1 test pins the two equal)
HEALTH_LEN = 14

# live-vs-model reconciliation band: exact on the CPU fake (the gate
# asserts 0 drift); real allocators round to pages/tiles, so the anomaly
# threshold leaves margin. Host-side knob like obs.comms.DEFAULTS —
# deliberately NOT a config field.
DEFAULTS: Dict[str, float] = {
    "drift_frac": 0.02,
}

# preflight verdicts keep this fraction of HBM free for allocator
# rounding, XLA fusion temporaries, and infeed buffers the model cannot
# see — an "exactly fits" prediction is an OOM in practice
HBM_HEADROOM_FRAC = 0.08

# per-chip HBM of the device kinds the preflight knows; --hbm-gb
# overrides (the table is a convenience, not a registry)
DEVICE_HBM_BYTES: Dict[str, int] = {
    "v3": 16 << 30,
    "v4": 32 << 30,
    "v5e": 16 << 30,
    "v5litepod": 16 << 30,
    "v5p": 95 << 30,
    "v6e": 32 << 30,
}

CATEGORIES = ("state", "graph", "scratch", "transient", "collective")
# categories whose buffers are long-lived addressable arrays — the exact
# reconciliation target (scratch/transient/collective are real HBM but
# not measurable from the state object)
ADDRESSABLE = ("state", "graph")


@dataclasses.dataclass(frozen=True)
class Buffer:
    """One live device buffer of a compiled train step (per-DEVICE
    bytes; `count` for repeated buffers like the ring's rotation pair)."""

    name: str
    bytes: float
    category: str = "state"
    count: float = 1.0
    note: str = ""

    @property
    def total_bytes(self) -> float:
        return float(self.bytes) * float(self.count)

    def to_fields(self) -> Dict[str, Any]:
        out = {
            "buffer": self.name,
            "bytes": round(self.total_bytes, 1),
            "category": self.category,
            "count": round(float(self.count), 2),
        }
        if self.note:
            out["note"] = self.note
        return out


@dataclasses.dataclass(frozen=True)
class MemoryModel:
    """The static per-device HBM model one trainer baked at step build."""

    family: str                  # dense | sharded | ring | sparse
    model: str                   # trainer class name
    buffers: Tuple[Buffer, ...]
    params: Dict[str, Any]       # the shape arithmetic inputs

    def hbm_bytes(self) -> float:
        """Modeled per-device HBM peak: every category, scratch and
        transients included — the capacity/preflight figure."""
        return sum(b.total_bytes for b in self.buffers)

    def addressable_bytes(self) -> float:
        """The state + graph categories only — the long-lived buffers
        `measured_device_bytes` can sum exactly (the reconciliation
        target; exact on the CPU fake)."""
        return sum(
            b.total_bytes for b in self.buffers
            if b.category in ADDRESSABLE
        )

    def category_bytes(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for b in self.buffers:
            out[b.category] = out.get(b.category, 0.0) + b.total_bytes
        return {k: round(v, 1) for k, v in out.items()}

    def buffer_bytes(self) -> Dict[str, float]:
        return {b.name: round(b.total_bytes, 1) for b in self.buffers}

    def reconcile(
        self, measured_bytes: float, band: Optional[float] = None
    ) -> Dict[str, Any]:
        """Modeled addressable bytes vs the LIVE per-device sum (see
        measured_device_bytes). drift > band means a buffer the model
        does not know is resident (leak / retained snapshot); drift <
        -band means the model prices a buffer that does not exist
        (stale arithmetic). Pure — emit_drift_anomaly turns a bad
        verdict into the anomaly event."""
        band = DEFAULTS["drift_frac"] if band is None else float(band)
        modeled = self.addressable_bytes()
        drift = (float(measured_bytes) - modeled) / max(modeled, 1.0)
        return {
            "model": self.model,
            "family": self.family,
            "modeled_bytes": round(modeled, 1),
            "measured_bytes": round(float(measured_bytes), 1),
            "drift_frac": round(drift, 6),
            "band": band,
            "ok": abs(drift) <= band,
            "hbm_modeled_bytes": round(self.hbm_bytes(), 1),
        }

    def to_dict(self) -> Dict[str, Any]:
        return {
            "family": self.family,
            "model": self.model,
            "hbm_bytes": round(self.hbm_bytes(), 1),
            "addressable_bytes": round(self.addressable_bytes(), 1),
            "by_category": self.category_bytes(),
            "buffers": [b.to_fields() for b in self.buffers],
            "params": dict(self.params),
        }


@dataclasses.dataclass(frozen=True)
class HostStage:
    """One stage of the per-host RSS model (stages are sequential, so
    the host peak is the max stage, not the sum)."""

    stage: str
    bytes: float
    note: str = ""


@dataclasses.dataclass(frozen=True)
class HostModel:
    stages: Tuple[HostStage, ...]

    def peak_bytes(self) -> float:
        return max((s.bytes for s in self.stages), default=0.0)

    def dominant(self) -> Optional[HostStage]:
        if not self.stages:
            return None
        return max(self.stages, key=lambda s: s.bytes)

    def stage_bytes(self) -> Dict[str, float]:
        return {s.stage: round(s.bytes, 1) for s in self.stages}

    def to_dict(self) -> Dict[str, Any]:
        dom = self.dominant()
        return {
            "host_rss_bytes": round(self.peak_bytes(), 1),
            "dominant_stage": dom.stage if dom else None,
            "stages": [
                {"stage": s.stage, "bytes": round(s.bytes, 1),
                 **({"note": s.note} if s.note else {})}
                for s in self.stages
            ],
        }


# ----------------------------------------------------- state arithmetic
def _scalar_state_bytes(
    itemsize: int, num_candidates: int, health_on: bool,
    extra_int32: int = 0,
) -> float:
    """The replicated per-device scalar bundle every TrainState carries:
    llh (dtype) + it (int32) + accept_hist ((S+1,) int32) + the health
    pack when on + `extra_int32` counters (the sparse comm_ids/
    comm_dense pair)."""
    return (
        itemsize
        + 4
        + (num_candidates + 1) * 4
        + (HEALTH_LEN * 4 if health_on else 0)
        + extra_int32 * 4
    )


def dense_state_buffers(
    n_pad: int, k_pad: int, dp: int, tp: int, itemsize: int,
    num_candidates: int, health_on: bool, extra_int32: int = 0,
) -> List[Buffer]:
    """Per-device bytes of the dense TrainState: F sharded P(nodes, k),
    sumF sharded P(k) (replicated over nodes), scalars replicated.
    `extra_int32` counts the exchange counters a capped-collective step
    adds to the state (the 2D closure grad exchange's comm_ids/
    comm_dense pair)."""
    n_loc = n_pad // max(dp, 1)
    k_loc = k_pad // max(tp, 1)
    return [
        Buffer("state/F", n_loc * k_loc * itemsize, "state"),
        Buffer("state/sumF", k_loc * itemsize, "state"),
        Buffer(
            "state/scalars",
            _scalar_state_bytes(
                itemsize, num_candidates, health_on,
                extra_int32=extra_int32,
            ),
            "state",
        ),
    ]


def sparse_state_buffers(
    n_pad: int, m: int, k_pad: int, dp: int, itemsize: int,
    num_candidates: int, health_on: bool,
) -> List[Buffer]:
    """Per-device bytes of the SparseTrainState: weights + int32 member
    ids sharded P(nodes), the (K_pad,) sumF accumulator replicated, and
    the scalar bundle + the two exchange counters."""
    n_loc = n_pad // max(dp, 1)
    return [
        Buffer("state/weights", n_loc * m * itemsize, "state"),
        Buffer("state/member_ids", n_loc * m * 4, "state"),
        Buffer("state/sumF", k_pad * itemsize, "state"),
        Buffer(
            "state/scalars",
            _scalar_state_bytes(
                itemsize, num_candidates, health_on, extra_int32=2
            ),
            "state",
        ),
    ]


def _graph_buffers(graph_bytes: Dict[str, float]) -> List[Buffer]:
    return [
        Buffer(name, float(b), "graph")
        for name, b in sorted(graph_bytes.items())
    ]


def _scratch_buffers(
    state_bytes: float, donate: bool, rollback: bool
) -> List[Buffer]:
    out = []
    if donate:
        out.append(Buffer(
            "scratch/donation_pingpong", state_bytes, "scratch",
            note="cfg.donate_state ping-pong twin (run_fit_loop)",
        ))
    if rollback:
        out.append(Buffer(
            "scratch/rollback_snapshot", state_bytes, "scratch",
            note="cfg.rollback_budget last-verified-finite snapshot",
        ))
    return out


def collective_buffers(comms: Optional[CommsModel]) -> List[Buffer]:
    """Collective scratch priced from the comms Sites the trainer
    already baked: the largest single-occurrence receive buffer of the
    step (the all-gather result / psum double buffer / ppermute
    in-flight shard). One buffer, named after the site, so the memory
    and comms models can never disagree about the wire payloads."""
    if comms is None or not comms.sites:
        return []
    best, best_bytes = None, 0.0
    for s in comms.sites:
        b = wire_bytes(s.op, s.payload_bytes, s.participants)
        if b > best_bytes:
            best, best_bytes = s, b
    if best is None or best_bytes <= 0:
        return []
    return [Buffer(
        "collective/in_flight", best_bytes, "collective",
        note=f"largest single-occurrence receive ({best.site})",
    )]


def _total(buffers: Sequence[Buffer]) -> float:
    return sum(b.total_bytes for b in buffers)


# ------------------------------------------------------- family builders
def _fd_buffers(fd_bytes: float, fused: bool, note: str) -> list:
    """The dst-row transient of one edge sweep: the HBM-resident fd
    gather on the split kernel/XLA paths, or — when the fused superstep
    engages (ISSUE 13) — the (2, T, Kc) double-buffered in-kernel DMA
    scratch that replaces it. The rename is deliberate: a fused run's
    model must show the fd buffer GONE, not merely smaller, and the
    scratch it bought instead."""
    if not fd_bytes:
        return []
    if fused:
        return [Buffer(
            "transient/fd_dma_scratch", fd_bytes, "transient",
            note="double-buffered in-kernel dst-row DMA (fused superstep; "
                 "VMEM-resident — no HBM fd gather exists): " + note,
        )]
    return [Buffer("transient/fd_gather", fd_bytes, "transient", note=note)]


def dense_memory_model(
    n_pad: int,
    k_pad: int,
    itemsize: int,
    num_candidates: int,
    graph_bytes: Dict[str, float],
    health_on: bool = False,
    donate: bool = True,
    rollback: bool = False,
    fd_bytes: float = 0.0,
    fused: bool = False,
    model: str = "BigClamModel",
) -> MemoryModel:
    """Single-chip dense trainer (models.bigclam.BigClamModel). The
    transient set is the step's in-flight temporaries: the gradient
    (state-F-sized), the shared dst-row gather (fd — CSR flat/grouped
    or the XLA (chunk, K) gather), and the (S, N) Armijo candidate
    accumulators."""
    state = dense_state_buffers(
        n_pad, k_pad, 1, 1, itemsize, num_candidates, health_on
    )
    buffers = (
        state
        + _graph_buffers(graph_bytes)
        + _scratch_buffers(_total(state), donate, rollback)
        + [
            Buffer("transient/grad", n_pad * k_pad * itemsize, "transient"),
            Buffer(
                "transient/candidates",
                num_candidates * n_pad * itemsize, "transient",
                note="(S, N) Armijo candidate accumulators",
            ),
        ]
        + _fd_buffers(fd_bytes, fused, "shared dst-row gather")
    )
    return MemoryModel(
        family="dense", model=model, buffers=tuple(buffers),
        params={"n_pad": n_pad, "k_pad": k_pad, "itemsize": itemsize,
                "donate": donate, "rollback": rollback},
    )


def sharded_memory_model(
    n_pad: int,
    k_pad: int,
    dp: int,
    tp: int,
    itemsize: int,
    num_candidates: int,
    graph_bytes: Dict[str, float],
    health_on: bool = False,
    donate: bool = True,
    rollback: bool = False,
    fd_bytes: float = 0.0,
    fused: bool = False,
    comms: Optional[CommsModel] = None,
    model: str = "ShardedBigClamModel",
) -> MemoryModel:
    """All-gather sharded trainer (parallel.sharded): the dominant
    transient is the full gathered F copy every device materializes
    per step — (n_pad, k_loc) regardless of dp, exactly why the ring
    schedule exists (ring_memory_model prices the alternative)."""
    n_loc = n_pad // max(dp, 1)
    k_loc = k_pad // max(tp, 1)
    state = dense_state_buffers(
        n_pad, k_pad, dp, tp, itemsize, num_candidates, health_on
    )
    buffers = (
        state
        + _graph_buffers(graph_bytes)
        + _scratch_buffers(_total(state), donate, rollback)
        + [
            Buffer(
                "transient/F_allgather", n_pad * k_loc * itemsize,
                "transient",
                note="full gathered F per device — O(N*K_loc), the "
                     "all-gather schedule's memory ceiling",
            ),
            Buffer("transient/grad", n_loc * k_loc * itemsize, "transient"),
            Buffer(
                "transient/candidates",
                num_candidates * n_loc * itemsize, "transient",
            ),
        ]
        + _fd_buffers(fd_bytes, fused, "per-shard dst-row gather")
        + collective_buffers(comms)
    )
    return MemoryModel(
        family="sharded", model=model, buffers=tuple(buffers),
        params={"n_pad": n_pad, "k_pad": k_pad, "dp": dp, "tp": tp,
                "itemsize": itemsize, "donate": donate,
                "rollback": rollback},
    )


def ring_memory_model(
    n_pad: int,
    k_pad: int,
    dp: int,
    tp: int,
    itemsize: int,
    num_candidates: int,
    graph_bytes: Dict[str, float],
    health_on: bool = False,
    donate: bool = True,
    rollback: bool = False,
    fd_bytes: float = 0.0,
    fused: bool = False,
    overlap: bool = True,
    comms: Optional[CommsModel] = None,
    model: str = "RingBigClamModel",
) -> MemoryModel:
    """Ring-pass trainer: the full-F gather is replaced by the rotating
    shard pair — the resident rotating copy plus (with ring_overlap)
    the in-flight double buffer, O(2 * N/dp * K_loc) peak instead of
    O(N * K_loc). This model is the schedule's memory claim in numbers;
    its comms model is its (higher) wire claim — the honest tradeoff."""
    n_loc = n_pad // max(dp, 1)
    k_loc = k_pad // max(tp, 1)
    state = dense_state_buffers(
        n_pad, k_pad, dp, tp, itemsize, num_candidates, health_on
    )
    rot_copies = 2.0 if (overlap and dp > 1) else (1.0 if dp > 1 else 0.0)
    buffers = (
        state
        + _graph_buffers(graph_bytes)
        + _scratch_buffers(_total(state), donate, rollback)
        + ([Buffer(
            "transient/ring_rotation", n_loc * k_loc * itemsize,
            "transient", count=rot_copies,
            note="rotating F shard"
                 + (" + in-flight double buffer (ring_overlap)"
                    if rot_copies == 2.0 else ""),
        )] if rot_copies else [])
        + [
            Buffer("transient/grad", n_loc * k_loc * itemsize, "transient"),
            Buffer(
                "transient/candidates",
                num_candidates * n_loc * itemsize, "transient",
            ),
        ]
        + _fd_buffers(fd_bytes, fused, "per-phase dst-row gather")
        + collective_buffers(comms)
    )
    return MemoryModel(
        family="ring", model=model, buffers=tuple(buffers),
        params={"n_pad": n_pad, "k_pad": k_pad, "dp": dp, "tp": tp,
                "itemsize": itemsize, "overlap": overlap,
                "donate": donate, "rollback": rollback},
    )


def sparse_memory_model(
    n_pad: int,
    m: int,
    k_pad: int,
    dp: int,
    itemsize: int,
    num_candidates: int,
    graph_bytes: Dict[str, float],
    health_on: bool = False,
    donate: bool = True,
    rollback: bool = False,
    comms: Optional[CommsModel] = None,
    model: str = "SparseBigClamModel",
) -> MemoryModel:
    """Sparse top-M trainers (models.sparse / parallel.sparse_sharded):
    state and the gathered member lists scale with M, not K — the whole
    point of the representation, now visible as a model instead of a
    gate assertion. The sharded trainer's gathered id/weight pair is
    the dominant transient (n_pad * M per device)."""
    n_loc = n_pad // max(dp, 1)
    state = sparse_state_buffers(
        n_pad, m, k_pad, dp, itemsize, num_candidates, health_on
    )
    buffers = (
        state
        + _graph_buffers(graph_bytes)
        + _scratch_buffers(_total(state), donate, rollback)
        + ([Buffer(
            "transient/members_allgather", n_pad * m * (4 + itemsize),
            "transient",
            note="gathered member ids+weights per device (O(N*M))",
        )] if dp > 1 else [])
        + [
            Buffer("transient/grad", n_loc * m * itemsize, "transient"),
            Buffer(
                "transient/candidates",
                num_candidates * n_loc * itemsize, "transient",
            ),
        ]
        + collective_buffers(comms)
    )
    return MemoryModel(
        family="sparse", model=model, buffers=tuple(buffers),
        params={"n_pad": n_pad, "m": m, "k_pad": k_pad, "dp": dp,
                "itemsize": itemsize, "donate": donate,
                "rollback": rollback},
    )


def twod_memory_model(
    n_pad: int,
    k_pad: int,
    rows: int,
    cols: int,
    itemsize: int,
    num_candidates: int,
    graph_bytes: Dict[str, float],
    closure_cap: int = 1,
    m: int = 0,
    health_on: bool = False,
    donate: bool = True,
    rollback: bool = False,
    fd_bytes: float = 0.0,
    comms: Optional[CommsModel] = None,
    model: str = "TwoDShardedBigClamModel",
    fused: bool = False,
    grad_exchange: str = "dense",
    grad_cap: int = 0,
) -> MemoryModel:
    """2D edge-block trainer (parallel.twod): the O(N * K_loc) gathered
    F of the 1D schedule is replaced by the processor row's own src rows
    (cols blocks) plus the CAPPED closure table (rows * cap rows) — the
    memory claim that pairs with twod_step_model's wire claim. With
    m > 0 this prices the sparse-representation layout (member rows of
    m ids+weights instead of k_pad floats) — forward-looking preflight
    pricing; the wired 2d trainer is dense.

    ISSUE 17: `fused` re-prices the dst-row transient as the in-kernel
    DMA scratch (kernel_path csr_fused_2d[_kb] — same rename as the 1D
    fused model); grad_exchange="closure" adds the exchange counters to
    the state scalars and the two-phase routing buffers (grad_cap rows
    per peer, phases A+B) as the grad-exchange transient, replacing
    nothing — the (n_row, K) grad band itself stays resident either
    way."""
    p = max(rows * cols, 1)
    n_blk = n_pad // p
    row_b = m * (4.0 + itemsize) if m else float(k_pad * itemsize)
    feat = m if m else k_pad
    closure_grad = grad_exchange == "closure"
    state = (
        sparse_state_buffers(n_pad, m, k_pad, p, itemsize,
                             num_candidates, health_on)
        if m else
        dense_state_buffers(n_pad, k_pad, p, 1, itemsize,
                            num_candidates, health_on,
                            extra_int32=2 if closure_grad else 0)
    )
    buffers = (
        state
        + _graph_buffers(graph_bytes)
        + _scratch_buffers(_total(state), donate, rollback)
        + ([Buffer(
            "transient/F_rowgather", cols * n_blk * row_b, "transient",
            note="processor row's src rows — 1/rows of the 1D "
                 "F_allgather, the schedule's whole point",
        )] if cols > 1 else [])
        + [
            Buffer(
                "transient/closure_recv", rows * closure_cap * row_b,
                "transient",
                note="capped closure table (rows * cap dst rows); the "
                     "send staging lives only across the exchange and "
                     "is the collective/in_flight buffer below",
            ),
            Buffer(
                "transient/grad_row", cols * n_blk * feat * itemsize,
                "transient",
                note="row-group gradient before the cols reduction",
            ),
            Buffer(
                "transient/candidates",
                num_candidates * cols * n_blk * itemsize, "transient",
            ),
        ]
        + ([Buffer(
            "transient/grad_closure_exchange",
            2.0 * cols * grad_cap * k_pad * itemsize
            + n_blk * k_pad * itemsize,
            "transient",
            note="touched-rows grad exchange: (cols, cap, K) send + "
                 "recv staging per phase plus the (n_blk, K) phase-A "
                 "block accumulator",
        )] if closure_grad and grad_cap > 0 else [])
        + _fd_buffers(
            fd_bytes, fused,
            "per-tile closure-buffer rows" if fused
            else "per-block closure-row gather",
        )
        + collective_buffers(comms)
    )
    return MemoryModel(
        family="twod", model=model, buffers=tuple(buffers),
        params={"n_pad": n_pad, "k_pad": k_pad, "rows": rows,
                "cols": cols, "itemsize": itemsize, "m": m,
                "closure_cap": closure_cap, "donate": donate,
                "rollback": rollback, "fused": bool(fused),
                "grad_exchange": grad_exchange, "grad_cap": grad_cap},
    )


# -------------------------------------------------------- host RSS model
def ingest_rss_bytes(
    chunk_bytes: int, n: int, directed_edges: int, num_shards: int
) -> float:
    """The ingest pipeline's explicit RSS budget — the SAME formula
    scripts/ingest_bench.py gates INGEST_r07 against (12 B of tokenizer
    transients per chunk byte + 6x the largest scatter bucket + 4x the
    int64 raw-id table + a 96 MiB allocator floor), now also a model
    stage instead of only a gate constant."""
    bucket_bytes = 16 * directed_edges // max(num_shards, 1)
    idtable_bytes = 8 * n
    return float(
        12 * chunk_bytes + 6 * bucket_bytes + 4 * idtable_bytes
        + (96 << 20)
    )


def f0_init_rss_bytes(n: int, k: int, n_pad: int, k_pad: int,
                      itemsize: int) -> float:
    """The host-global O(N*K) F0 init: the float64 (N, K) init array
    (seeding / random_init_F), the padded float64 staging copy
    (init_state), and the dtype cast handed to the device upload. THE
    dominant host term on the in-memory trainers — the store-native
    trainers now default to the PER-HOST row-keyed counter init
    (ISSUE 15 satellite, ROADMAP 1a closed there:
    rowkeyed_f0_rss_bytes), and only an explicit host-global F0 upload
    (conductance seeding) still pays this."""
    return float(n * k * 8 + n_pad * k_pad * (8 + itemsize))


def rowkeyed_f0_rss_bytes(n_pad: int, k_pad: int, itemsize: int,
                          processes: int) -> float:
    """The PER-HOST row-keyed counter init (ISSUE 15 satellite /
    ROADMAP 1a): each host materializes only its own padded row range —
    the float64 local block (rowkeyed_init_rows + zero staging) plus
    the dtype cast handed to make_array_from_process_local_data. The
    uint64 counter lattice is freed before the cast, so it shares the
    same budget term."""
    rows_local = n_pad / max(processes, 1)
    return float(rows_local * k_pad * (8 + itemsize))


def host_rss_model(
    n: int,
    directed_edges: int,
    k: int,
    itemsize: int,
    n_pad: int = 0,
    k_pad: int = 0,
    store_native: bool = False,
    processes: int = 1,
    num_shards: int = 1,
    chunk_bytes: int = 0,
    representation: str = "dense",
    sparse_m: int = 0,
    rowkeyed_f0: Optional[bool] = None,
) -> HostModel:
    """Per-stage host-RSS model of a fit entry (per HOST, not per
    device). Stages are sequential; the peak is the max stage.

    `rowkeyed_f0` (default: follows store_native) prices the `f0_init`
    stage at the PER-HOST row-keyed counter init the store-backed
    trainers now default to (ISSUE 15 satellite — O(N_loc*K); the
    dominant flag then moves to the arg-max remaining stage, typically
    `extract`, which stays host-global). With it False the stage is the
    host-global O(N*K) upload (the in-memory trainers, and store-native
    runs seeded from an explicit host-global F0 — conductance seeding's
    init_F is still a host-global array, the open remainder of
    ROADMAP 1a)."""
    n_pad = n_pad or n
    k_pad = k_pad or k
    p = max(processes, 1)
    stages: List[HostStage] = []
    if chunk_bytes:
        stages.append(HostStage(
            "ingest",
            ingest_rss_bytes(chunk_bytes, n, directed_edges, num_shards),
            note="chunk + scatter bucket + id table (the INGEST_r07 "
                 "budget); O(chunk), never O(file)",
        ))
    if store_native:
        stages.append(HostStage(
            "shard_load",
            (directed_edges / p) * 12.0 + 8.0 * (n / p + num_shards),
            note="this host's shard slice + local edge-block build "
                 "(O(shard) — no global CSR)",
        ))
    else:
        # full Graph on the host: indices (2E int32) + indptr int64 +
        # the materialized src/dst directed-edge views the edge
        # builders read (int32 each)
        stages.append(HostStage(
            "graph_load",
            directed_edges * 12.0 + 8.0 * (n + 1),
            note="global CSR + src/dst edge views (host-global)",
        ))
    stages.append(HostStage(
        "seeding", 24.0 * n,
        note="conductance phi/degree/order arrays (O(N))",
    ))
    if rowkeyed_f0 is None:
        rowkeyed_f0 = store_native
    if representation == "sparse" and sparse_m:
        f0 = float(n * k * 8 + n_pad * sparse_m * (8 + itemsize + 4))
        note = (
            "dense (N, K) float64 F0 sparsified to top-M host-side — "
            "the dense staging is still O(N*K) (ROADMAP 1a)"
        )
    elif rowkeyed_f0:
        f0 = rowkeyed_f0_rss_bytes(n_pad, k_pad, itemsize, p)
        note = (
            "per-host row-keyed counter F0 init (ISSUE 15: O(N_loc*K), "
            "ROADMAP 1a closed on the store-native path; an explicit "
            "host-global F0 — conductance seeding — re-opens it)"
        )
    else:
        f0 = f0_init_rss_bytes(n, k, n_pad, k_pad, itemsize)
        note = (
            "host-global O(N*K) F0 init + padded staging — the "
            "dominant host term (ROADMAP 1a: closed for store-native "
            "random inits via the per-host row-keyed counter init; "
            "this in-memory/explicit-F0 path still pays it)"
        )
    stages.append(HostStage("f0_init", f0, note=note))
    stages.append(HostStage(
        "extract", n * k * (8.0 + itemsize),
        note="fetched (N, K) F + float64 staging at extract_F",
    ))
    return HostModel(stages=tuple(stages))


# --------------------------------------------------------- reconciliation
def measured_device_bytes(arrays: Sequence[Any]) -> float:
    """Exact per-device bytes of the given live arrays: every
    addressable shard's nbytes, grouped by device, MAX over devices
    (layouts are uniform, so max == each; max is the capacity-relevant
    figure when they are not). Plain numpy arrays (no shard API) count
    as resident on every device. None entries are skipped (health off).
    """
    per_dev: Dict[str, float] = {}
    plain = 0.0
    for a in arrays:
        if a is None:
            continue
        shards = getattr(a, "addressable_shards", None)
        if shards:
            for s in shards:
                key = str(s.device)
                per_dev[key] = per_dev.get(key, 0.0) + s.data.nbytes
        else:
            nbytes = getattr(a, "nbytes", None)
            if nbytes is None:
                nbytes = int(a.size) * a.dtype.itemsize
            plain += float(nbytes)
    if not per_dev:
        return plain
    return max(per_dev.values()) + plain


def nbytes_of(arr: Any) -> float:
    """Shape-based total bytes of a (possibly globally sharded, possibly
    not fully addressable) array — .nbytes where it exists, else
    size * itemsize. Used by the trainers' graph-buffer accounting."""
    nbytes = getattr(arr, "nbytes", None)
    if nbytes is not None:
        return float(nbytes)
    return float(int(arr.size) * arr.dtype.itemsize)


# ------------------------------------------------------------- emission
def emit_model(
    mm: MemoryModel, host: Optional[HostModel] = None
) -> None:
    """One `memory_model` event per device buffer (+ one per host stage
    when a host model rides along). The FIRST device event of the batch
    carries reset_model=True — a re-emitted model (quality mode /
    rollback rebuilds, the sparse cap refinement) REPLACES its previous
    buffer set in every consumer, exactly the obs.comms contract. No-op
    with telemetry off."""
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is None:
        return
    for i, b in enumerate(mm.buffers):
        tel.event(
            "memory_model", model=mm.model, family=mm.family,
            scope="device", reset_model=1 if i == 0 else 0,
            **b.to_fields(),
        )
    if host is not None:
        dom = host.dominant()
        for j, st in enumerate(host.stages):
            fields: Dict[str, Any] = {
                "model": mm.model,
                "family": mm.family,
                "scope": "host",
                "reset_model": 1 if j == 0 else 0,
                "buffer": f"host/{st.stage}",
                "stage": st.stage,
                "bytes": round(st.bytes, 1),
                "category": "host",
            }
            if st.note:
                fields["note"] = st.note
            if dom is not None and st.stage == dom.stage:
                fields["dominant"] = 1
            tel.event("memory_model", **fields)


def emit_drift_anomaly(recon: Dict[str, Any]) -> None:
    """A failed reconciliation as a first-class anomaly event
    (check="memory_drift", build/probe-time: iter=-1): the live
    addressable bytes disagree with the model past the band — a leaked
    or retained buffer (positive drift) or stale model arithmetic
    (negative). No-op with telemetry off."""
    from bigclam_tpu.obs import telemetry as _obs

    tel = _obs.current()
    if tel is None:
        return
    tel.event(
        "anomaly", check="memory_drift", iter=-1,
        model=recon.get("model"),
        modeled_bytes=recon.get("modeled_bytes"),
        measured_bytes=recon.get("measured_bytes"),
        drift_frac=recon.get("drift_frac"),
        band=recon.get("band"),
        hint="retained/leaked device buffer (positive drift) or stale "
             "model arithmetic (negative)",
    )


# ------------------------------------------------------------- preflight
def _round_up(x: int, m: int) -> int:
    m = max(int(m), 1)
    return ((int(x) + m - 1) // m) * m


def _chunk_geometry(
    max_count: int, edge_chunk: int, gather_cols: int, itemsize: int
) -> Tuple[int, int]:
    """(padded per-shard edge-slot count, per-scan chunk) of the XLA
    edge-block layout — the SAME chunk arithmetic shard_edges /
    edge_chunk_bound commit (chunk bound from the ~1 GB gather budget,
    even chunk count, padded to chunk * ceil). The chunk is the live
    (chunk, gather_cols) dst-row gather per scan step — the fd
    transient the trainers' baked models price."""
    bound = min(
        max(edge_chunk, 1),
        max((1 << 30) // max(gather_cols * itemsize, 1), 1024),
    )
    chunk = min(bound, max(max_count, 1))
    c = max(1, -(-max(max_count, 1) // chunk))
    return c * chunk, chunk


def preflight(
    n: int,
    directed_edges: int,
    k: int,
    dp: int = 1,
    tp: int = 1,
    itemsize: int = 4,
    num_candidates: int = 16,
    representation: str = "dense",
    sparse_m: int = 64,
    support_every: int = 1,
    schedule: str = "allgather",
    store_native: bool = False,
    health_every: int = 10,
    donate: bool = True,
    rollback: bool = True,
    edge_chunk: int = 1 << 20,
    shard_edge_counts: Optional[Sequence[int]] = None,
    device_hbm_bytes: float = 0.0,
    host_ram_bytes: float = 0.0,
    processes: int = 1,
    chunk_bytes: int = 0,
    csr_block_b: int = 256,
    rows_per_shard: int = 0,
    partition: str = "1d",
    replica_cols: int = 1,
    closure_pair_counts: Optional[Sequence[Sequence[int]]] = None,
) -> Dict[str, Any]:
    """The jax-free capacity verdict (`cli preflight`): build the same
    memory + comms models the trainer would bake, from workload numbers
    alone (cache manifest or text-size estimates), against a
    device-kind/count target. Returns the full component breakdown, a
    fits-or-doesn't verdict naming the BINDING constraint, and the
    knobs that relax it. Estimates where the trainer has data the
    preflight does not (ring bucket skew without a manifest); exact
    shard geometry when per-shard counts are given."""
    from bigclam_tpu.obs import comms as _comms

    dp, tp = max(int(dp), 1), max(int(tp), 1)
    sparse = representation == "sparse"
    if sparse:
        tp = 1
    partition = str(partition or "1d")
    cols2 = max(int(replica_cols), 1)
    if partition not in ("1d", "2d"):
        raise ValueError(f"unknown partition {partition!r} (1d or 2d)")
    if partition == "2d":
        if schedule == "ring":
            raise ValueError(
                "partition=2d is its own closure-gather schedule — "
                "drop --schedule ring"
            )
        if tp != 1:
            raise ValueError(
                "partition=2d requires tp == 1 (the k axis rides the "
                "2d mesh unsharded)"
            )
        if dp % cols2:
            raise ValueError(
                f"replica_cols={cols2} does not divide the {dp}-chip "
                "mesh"
            )
    n_pad = _round_up(max(n, dp), dp)
    k_pad = _round_up(k, tp)
    k_loc = k_pad // tp
    m = max(1, min(int(sparse_m), int(k))) if sparse else 0
    if shard_edge_counts:
        max_shard = max(int(c) for c in shard_edge_counts)
        counts_known = True
    else:
        # uniform split + 15% power-law padding allowance, noted below
        max_shard = int(math.ceil(directed_edges / dp * (1.15 if dp > 1
                                                         else 1.0)))
        counts_known = False

    gather_cols = m if sparse else k_loc
    notes: List[str] = []
    if not counts_known and dp > 1:
        notes.append(
            "per-shard edge counts estimated (uniform split +15%); "
            "compile a cache and pass it for exact shard geometry"
        )

    # --- graph buffers + comms model per family ---
    if partition == "2d":
        rows2 = dp // cols2
        n_blk = n_pad // dp
        feat2 = m if sparse else k_pad
        row_b2 = m * (4.0 + itemsize) if sparse else float(k_pad
                                                           * itemsize)
        # closure rows per pair: exact requester-group unions are upper
        # bounded off the baked manifest when given, else the
        # coupon-collector touched-row estimate on a uniform random
        # graph — the estimate the COMMS2D gate checks against measured
        cap2 = 0
        if closure_pair_counts and len(closure_pair_counts) == dp:
            for i in range(rows2):
                for b in range(dp):
                    tot, over = 0, False
                    for s in range(i * cols2, (i + 1) * cols2):
                        c = int(closure_pair_counts[s][b])
                        if c < 0:
                            over = True
                            break
                        tot += c
                    cap2 = max(cap2, n_blk if over else min(tot, n_blk))
        else:
            e_pair = directed_edges / max(rows2 * dp, 1)
            cap2 = int(math.ceil(
                n_blk * (1.0 - math.exp(-e_pair / max(n_blk, 1)))
            ))
            notes.append(
                "closure rows estimated (coupon-collector, uniform "
                "random graph) — bake closures (`cli ingest`) and pass "
                "the cache for exact pair counts"
            )
        cap2 = max(min(cap2, n_blk), 1)
        # ISSUE 17: the 2d verdict prices the COMBINED config the 2d
        # trainer actually engages at scale — the fused superstep kernel
        # path (dense only) plus the closure-compressed grad exchange
        # over the cols axis. The grad cap is the worst per-(chip,
        # block) touched-row count: exact-manifest upper bound when the
        # pair counts are baked, coupon-collector otherwise.
        fused2 = not sparse
        gx2 = "closure" if (cols2 > 1 and not sparse) else "dense"
        gcap2 = 0
        if cols2 > 1 and not sparse:
            if closure_pair_counts and len(closure_pair_counts) == dp:
                for s_i in range(dp):
                    for b in range(dp):
                        c = int(closure_pair_counts[s_i][b])
                        gcap2 = max(gcap2,
                                    n_blk if c < 0 else min(c, n_blk))
            else:
                e_pair = directed_edges / max(dp * cols2, 1)
                gcap2 = int(math.ceil(
                    n_blk * (1.0 - math.exp(-e_pair / max(n_blk, 1)))
                ))
            gcap2 = max(min(gcap2, n_blk), 1)
        slots, _chunk = _chunk_geometry(max_shard, edge_chunk,
                                        gather_cols, itemsize)
        graph = {
            "graph/edge_blocks": slots * (8.0 + itemsize),
            "graph/closure_send_idx": float(rows2 * cap2 * 4),
        }
        comms = _comms.twod_step_model(
            n_pad, feat2, rows2, cols2, itemsize, num_candidates,
            edge_slots=slots, closure_cap=cap2,
            health_every=health_every, row_bytes=row_b2,
            grad_exchange=gx2, grad_cap=gcap2, fused=fused2,
        ) if dp > 1 else None
        mm = twod_memory_model(
            n_pad, k_pad, rows2, cols2, itemsize, num_candidates,
            graph, closure_cap=cap2, m=m, health_on=health_every > 0,
            donate=donate, rollback=rollback, comms=comms,
            fused=fused2, grad_exchange=gx2, grad_cap=gcap2,
        )
        if fused2:
            notes.append(
                "2d priced at the combined config: kernel_path "
                "csr_fused_2d (fused superstep, closure rows feed the "
                "dst DMA) + grad_exchange="
                + gx2
                + (f" (cap {gcap2} touched rows/peer)" if cols2 > 1
                   else "")
            )
        if sparse:
            notes.append(
                "sparse x 2d is priced forward-looking — the wired 2d "
                "trainer is dense (`--partition 2d` without "
                "--representation sparse)"
            )
    elif sparse:
        slots, _chunk = _chunk_geometry(max_shard, edge_chunk, m,
                                        itemsize)
        graph = {"graph/edge_blocks": slots * (8.0 + itemsize)}
        # support blocks: every directed edge once + block rounding
        graph["graph/support_blocks"] = (
            directed_edges / dp * 1.1 * (8.0 + itemsize)
        )
        cap = min(_round_up(max(8 * m, 8), 8), k_pad)
        mode = "sparse" if dp > 1 and cap < 0.5 * k_pad else "dense"
        comms = _comms.sparse_step_model(
            n_pad, m, k_pad, dp, itemsize, num_candidates, cap, mode,
            support_every=support_every, health_every=health_every,
        ) if dp > 1 else None
        mm = sparse_memory_model(
            n_pad, m, k_pad, dp, itemsize, num_candidates, graph,
            health_on=health_every > 0, donate=donate, rollback=rollback,
            comms=comms,
            model="SparseShardedBigClamModel" if dp > 1
            else "SparseBigClamModel",
        )
    elif schedule == "ring" and dp > 1:
        # per-(shard, phase) buckets padded to the max bucket; without
        # bucket data assume the balanced distribution (what a
        # --balance ingest delivers — an unbalanced cache can be up to
        # dp x worse, which the trainer warns about at build)
        max_bucket = int(math.ceil(max_shard / dp))
        padded, _chunk = _chunk_geometry(max_bucket, edge_chunk,
                                         gather_cols, itemsize)
        slots = dp * padded
        graph = {"graph/ring_buckets": slots * (8.0 + itemsize)}
        fd = _chunk * gather_cols * itemsize
        comms = _comms.ring_step_model(
            n_pad, k_pad, dp, tp, itemsize, num_candidates,
            bucket_slots=padded, health_every=health_every,
        )
        mm = ring_memory_model(
            n_pad, k_pad, dp, tp, itemsize, num_candidates, graph,
            health_on=health_every > 0, donate=donate,
            rollback=rollback, fd_bytes=fd, comms=comms,
        )
        notes.append(
            "ring buckets priced at the balanced distribution — an "
            "unbalanced cache pads up to dp x worse (ingest --balance)"
        )
    else:
        slots, _chunk = _chunk_geometry(max_shard, edge_chunk,
                                        gather_cols, itemsize)
        graph = {"graph/edge_blocks": slots * (8.0 + itemsize)}
        # the live per-scan (chunk, K_loc) dst gather — the same fd
        # transient the trainers' baked models price on every family
        fd = _chunk * gather_cols * itemsize
        comms = _comms.sharded_step_model(
            n_pad, k_pad, dp, tp, itemsize, num_candidates,
            edge_slots=slots, health_every=health_every,
        ) if dp * tp > 1 else None
        if dp * tp > 1:
            mm = sharded_memory_model(
                n_pad, k_pad, dp, tp, itemsize, num_candidates, graph,
                health_on=health_every > 0, donate=donate,
                rollback=rollback, fd_bytes=fd, comms=comms,
            )
        else:
            mm = dense_memory_model(
                n_pad, k_pad, itemsize, num_candidates, graph,
                health_on=health_every > 0, donate=donate,
                rollback=rollback, fd_bytes=fd,
            )

    host = host_rss_model(
        n, directed_edges, k, itemsize, n_pad=n_pad, k_pad=k_pad,
        store_native=store_native, processes=processes,
        num_shards=dp if store_native else max(dp, 1),
        chunk_bytes=chunk_bytes, representation=representation,
        sparse_m=m,
    )

    # --- verdict: which constraint binds? ---
    hbm = mm.hbm_bytes()
    host_peak = host.peak_bytes()
    hbm_budget = float(device_hbm_bytes) * (1.0 - HBM_HEADROOM_FRAC) \
        if device_hbm_bytes else 0.0
    fits_hbm = not hbm_budget or hbm <= hbm_budget
    fits_host = not host_ram_bytes or host_peak <= float(host_ram_bytes)
    fits = fits_hbm and fits_host
    binding = None
    if not fits_hbm and not fits_host:
        binding = (
            "hbm"
            if hbm / max(hbm_budget, 1.0)
            >= host_peak / max(float(host_ram_bytes), 1.0)
            else "host_rss"
        )
    elif not fits_hbm:
        binding = "hbm"
    elif not fits_host:
        binding = "host_rss"

    # --- the knobs that relax the binding constraint ---
    knobs: List[str] = []
    cat = mm.category_bytes()
    if not fits_hbm:
        if not sparse and (k_pad * itemsize) > 256:
            m_hint = max(min(64, k // 4), 1)
            knobs.append(
                f"--representation sparse --sparse-m {m_hint}: state "
                "and member exchange scale with M, not K "
                f"(state {_fmt_bytes(cat.get('state', 0))} -> "
                f"~{_fmt_bytes(n_pad // dp * m_hint * (4 + itemsize))} "
                "ids+weights)"
            )
        if dp * tp < 64:
            knobs.append(
                f"--mesh {dp * 2},{tp}: per-device state/graph shrink "
                "~1/dp"
            )
        if schedule != "ring" and partition == "1d" and dp > 1:
            knobs.append(
                "--schedule ring: O(2 * N/dp) rotating shards replace "
                "the full per-device F gather "
                f"({_fmt_bytes(mm.buffer_bytes().get('transient/F_allgather', 0))})"
            )
        if partition == "1d" and dp * tp >= 4:
            p2 = dp * tp
            c_hint = int(math.isqrt(p2))
            while c_hint > 1 and p2 % c_hint:
                c_hint -= 1
            c_src = "sqrt heuristic"
            if closure_pair_counts and len(closure_pair_counts) == p2:
                # BAKED pair counts (ISSUE 17 satellite): instead of the
                # sqrt heuristic, price the closure exchange at every
                # divisor grid and recommend the cheapest — the cap per
                # (requester row, block) is the summed touched counts of
                # the row's store shards, exactly what the 2d trainer
                # will bake
                n_blk2 = _round_up(max(n, p2), p2) // p2
                row_b = (m * (4.0 + itemsize) if sparse
                         else float(k_pad * itemsize))
                best = None
                for c_try in range(1, p2):
                    if p2 % c_try:
                        continue
                    r_try = p2 // c_try
                    cap_t = 0
                    for i in range(r_try):
                        for b in range(p2):
                            tot, over = 0, False
                            for s_i in range(i * c_try,
                                             (i + 1) * c_try):
                                cc = int(closure_pair_counts[s_i][b])
                                if cc < 0:
                                    over = True
                                    break
                                tot += cc
                            cap_t = max(
                                cap_t,
                                n_blk2 if over else min(tot, n_blk2),
                            )
                    cap_t = max(min(cap_t, n_blk2), 1)
                    bps = _comms.twod_step_model(
                        n_pad, m if sparse else k_pad, r_try, c_try,
                        itemsize, num_candidates, closure_cap=cap_t,
                        health_every=health_every, row_bytes=row_b,
                        grad_exchange=(
                            "closure" if (c_try > 1 and not sparse)
                            else "dense"
                        ),
                        grad_cap=(
                            cap_t if (c_try > 1 and not sparse) else 0
                        ),
                        fused=not sparse,
                    ).bytes_per_step()
                    if best is None or bps < best[1]:
                        best = (c_try, bps)
                if best is not None:
                    c_hint = best[0]
                    c_src = "cheapest grid by baked closure pair counts"
            gname = ("transient/members_allgather" if sparse
                     else "transient/F_allgather")
            gb = mm.buffer_bytes().get(gname, 0)
            knobs.append(
                f"--partition 2d --replica-cols {c_hint} (mesh "
                f"{p2},1; {c_src}): the O(N) "
                f"{'member' if sparse else 'F'} gather "
                f"({_fmt_bytes(gb)}) shrinks to the processor row's "
                "1/rows slice plus the capped closure exchange "
                "(~3-4/sqrt(p) of the 1D wire at scale)"
            )
    if not fits_host:
        if not store_native:
            knobs.append(
                "--store-native (after `cli ingest`): graph stages drop "
                "to O(shard) host RSS — the F0 init stays host-global "
                "(ROADMAP 1a)"
            )
        dom = host.dominant()
        if dom is not None and dom.stage == "f0_init":
            knobs.append(
                "the binding stage is the host-global O(N*K) F0 init — "
                "no CLI knob relaxes it yet (ROADMAP 1a: per-host "
                "init_state)"
            )
    if rows_per_shard and csr_block_b and rows_per_shard % csr_block_b:
        notes.append(
            f"cache rows_per_shard={rows_per_shard} is not a multiple "
            f"of csr_block_b={csr_block_b}: the store-native CSR tile "
            "kernels will NOT engage (re-ingest block-aligned or set "
            "csr_block_b to a divisor)"
        )
    if not sparse:
        # the CSR tile layout's graph bytes at the default tile shape —
        # the tile-shape knob in numbers (ops.csr_tiles owns the
        # closed-form; built layouts agree by construction)
        from bigclam_tpu.ops.csr_tiles import tile_layout_nbytes

        tile_t = 512
        n_blocks = max((n_pad // dp) // max(csr_block_b, 1), 1)
        est_tiles = -(-max_shard // tile_t) + n_blocks
        csr_graph = tile_layout_nbytes(est_tiles, tile_t, itemsize)
        notes.append(
            f"csr tile layout (block_b={csr_block_b}, tile_t={tile_t}) "
            f"estimated at {_fmt_bytes(csr_graph)}/device vs "
            f"{_fmt_bytes(sum(graph.values()))} edge blocks — tile pad "
            "waste scales with blocks, shrink csr_block_b on skewed "
            "graphs"
        )

    return {
        "workload": {
            "n": int(n),
            "directed_edges": int(directed_edges),
            "k": int(k),
            "representation": representation,
            **({"sparse_m": m} if sparse else {}),
            "mesh": f"{dp}x{tp}",
            "partition": partition,
            **({"replica_cols": cols2} if partition == "2d" else {}),
            # the combined config the 2d price covers (ISSUE 17): the
            # fused superstep kernel path + the resolved grad exchange
            **(
                {
                    "kernel_path": (
                        "csr_fused_2d" if not sparse else "xla_2d"
                    ),
                    "grad_exchange": (
                        "closure" if (cols2 > 1 and not sparse)
                        else "dense"
                    ),
                }
                if partition == "2d"
                else {}
            ),
            "schedule": schedule,
            "store_native": bool(store_native),
            "itemsize": itemsize,
            "shard_counts_known": counts_known,
        },
        "device": mm.to_dict(),
        "host": host.to_dict(),
        "comms_bytes_per_step": (
            round(comms.bytes_per_step(), 1) if comms is not None else 0.0
        ),
        "hbm_bytes_per_device": round(hbm, 1),
        "hbm_budget_bytes": round(hbm_budget, 1),
        "host_rss_bytes": round(host_peak, 1),
        "host_ram_bytes": round(float(host_ram_bytes), 1),
        "fits": fits,
        "fits_hbm": fits_hbm,
        "fits_host": fits_host,
        "binding": binding,
        "knobs": knobs,
        "notes": notes,
    }


def _fmt_bytes(v: float) -> str:
    # the shared obs byte formatter (lazy import: report pulls telemetry
    # at import, which preflight-only callers should not pay up front)
    from bigclam_tpu.obs.report import _fmt_bytes as fmt

    return fmt(v)


def render_preflight(p: Dict[str, Any]) -> str:
    """Human rendering of a preflight() verdict (`cli preflight`)."""
    w = p["workload"]
    lines = [
        f"preflight: N={w['n']}  2E={w['directed_edges']}  K={w['k']}"
        f"  {w['representation']}"
        + (f" M={w['sparse_m']}" if w.get("sparse_m") else "")
        + f"  mesh {w['mesh']}  schedule {w['schedule']}"
        + ("  store-native" if w["store_native"] else "")
        + (
            f"  partition 2d(cols={w.get('replica_cols', 1)})"
            f" {w.get('kernel_path', '')}"
            f" grad_exchange={w.get('grad_exchange', '')}"
            if w.get("partition") == "2d"
            else ""
        ),
        "",
        f"per-device HBM (modeled): {_fmt_bytes(p['hbm_bytes_per_device'])}"
        + (
            f"  vs budget {_fmt_bytes(p['hbm_budget_bytes'])}"
            f" ({'fits' if p['fits_hbm'] else 'DOES NOT FIT'})"
            if p["hbm_budget_bytes"]
            else "  (no device budget given: --device-kind or --hbm-gb)"
        ),
    ]
    for cat, b in sorted(
        p["device"]["by_category"].items(), key=lambda kv: -kv[1]
    ):
        lines.append(f"  {cat:<12} {_fmt_bytes(b):>12}")
    top = sorted(
        p["device"]["buffers"], key=lambda b: -b["bytes"]
    )[:6]
    for b in top:
        lines.append(
            f"    {b['buffer']:<28} {_fmt_bytes(b['bytes']):>12}"
        )
    lines.append("")
    lines.append(
        f"per-host RSS (modeled peak): {_fmt_bytes(p['host_rss_bytes'])}"
        + (
            f"  vs {_fmt_bytes(p['host_ram_bytes'])}"
            f" ({'fits' if p['fits_host'] else 'DOES NOT FIT'})"
            if p["host_ram_bytes"]
            else ""
        )
    )
    dom = p["host"].get("dominant_stage")
    for s in p["host"]["stages"]:
        mark = "  <- dominant" if s["stage"] == dom else ""
        lines.append(
            f"  {s['stage']:<12} {_fmt_bytes(s['bytes']):>12}{mark}"
        )
    if p["comms_bytes_per_step"]:
        lines.append("")
        lines.append(
            "collective traffic (modeled): "
            f"{_fmt_bytes(p['comms_bytes_per_step'])}/step"
        )
    lines.append("")
    verdict = "FITS" if p["fits"] else (
        f"DOES NOT FIT (binding: {p['binding']})"
    )
    lines.append(f"verdict: {verdict}")
    for knob in p["knobs"]:
        lines.append(f"  knob: {knob}")
    for note in p["notes"]:
        lines.append(f"  note: {note}")
    return "\n".join(lines)


def serve_preflight(
    n: int,
    directed_edges: int,
    k: int,
    shards: int = 1,
    replicas: int = 1,
    representation: str = "dense",
    sparse_m: int = 64,
    itemsize: int = 4,
    cache_slots: int = 64,
    avg_memberships: float = 2.0,
    qps_target: float = 0.0,
    qps_per_replica: float = 9000.0,
    host_ram_bytes: float = 0.0,
) -> Dict[str, Any]:
    """The jax-free serving-fleet capacity verdict (`cli preflight
    --serve`, ISSUE 18 satellite): price one replica of one shard —
    snapshot rows (sparse-aware: M member slots per row, never a
    densified N*K block), the load-time inverted index, the hot-community
    cache, and the suggest adjacency slice — then the fleet total
    (shards × replicas), against a per-replica RAM budget and a
    `--qps-target`. The QPS capacity baseline (`qps_per_replica`)
    defaults to the measured single-process SERVE gate figure; pass your
    own replica measurement for a calibrated verdict."""
    shards = max(int(shards), 1)
    replicas = max(int(replicas), 1)
    sparse = representation == "sparse"
    m = max(1, min(int(sparse_m), int(k))) if sparse else 0
    rows = -(-int(n) // shards)                      # ceil rows/shard
    # --- one shard's snapshot archive, loaded ---
    if sparse:
        row_bytes = m * (4.0 + itemsize)             # ids(i32) + w
    else:
        row_bytes = float(k) * itemsize              # dense F row
    snapshot = rows * (row_bytes + 8.0) + float(k) * itemsize
    # rows + raw_ids(i64) + the global sumF vector every shard carries
    # --- the load-time inverted index (community -> member raw ids) ---
    pairs = rows * max(float(avg_memberships), 0.0)
    index = pairs * 8.0 + (k + 1) * 8.0 + rows * 8.0
    # comm_members(i64) + comm_indptr + the sorted raw-id row map
    # --- the Zipf-aware hot-community cache (resident member lists) ---
    avg_members = (n * max(float(avg_memberships), 0.0)) / max(k, 1)
    cache = min(int(cache_slots), int(k)) * avg_members * 8.0
    # --- the suggest adjacency slice (CSR over the shard's rows) ---
    adjacency = (rows + 1) * 8.0 + (directed_edges / shards) * 4.0
    per_replica = snapshot + index + cache + adjacency
    fleet_total = per_replica * shards * replicas
    # --- throughput: node-routed families hit ONE shard, so shards
    # multiply capacity; members_of fans out to every shard, so its
    # capacity is replicas × the per-replica figure alone ---
    qps_capacity = shards * replicas * float(qps_per_replica)
    qps_members = replicas * float(qps_per_replica)
    fits_ram = (
        per_replica <= float(host_ram_bytes) if host_ram_bytes else True
    )
    fits_qps = (
        qps_capacity >= float(qps_target) if qps_target else True
    )
    fits = fits_ram and fits_qps
    binding = None if fits else ("host_ram" if not fits_ram else "qps")
    knobs: List[str] = []
    if not fits_ram:
        if not sparse:
            knobs.append(
                f"--representation sparse --sparse-m {min(64, k)}: "
                f"snapshot rows shrink ~K/M "
                f"({_fmt_bytes(rows * row_bytes)} -> "
                f"{_fmt_bytes(rows * min(64, k) * (4.0 + itemsize))} "
                "per replica)"
            )
        knobs.append(
            f"--serve-shards {shards * 2}: per-replica snapshot bytes "
            "halve (rows/shard halve)"
        )
        if cache_slots > 8:
            knobs.append(
                f"--cache-slots {max(cache_slots // 4, 8)}: resident "
                f"member lists drop {_fmt_bytes(cache)} -> "
                f"{_fmt_bytes(max(cache_slots // 4, 8) * avg_members * 8.0)}"
            )
    if not fits_qps:
        need = -(-int(qps_target) // max(int(shards * qps_per_replica), 1))
        knobs.append(
            f"--serve-replicas {max(need, replicas + 1)}: QPS capacity "
            "scales linearly with replicas"
        )
    notes = [
        "avg memberships/node estimated at "
        f"{avg_memberships:g} (index + cache sizing); pass "
        "--avg-memberships from a fitted health pack for exact figures",
        "interpreter + numpy baseline RSS excluded (model covers the "
        "snapshot-dependent bytes only)",
        f"members_of scatter-gathers every shard: its capacity is "
        f"{qps_members:,.0f} qps (replicas x per-replica), not the "
        "node-routed figure",
    ]
    return {
        "workload": {
            "n": int(n),
            "directed_edges": int(directed_edges),
            "k": int(k),
            "representation": representation,
            **({"sparse_m": m} if sparse else {}),
            "serve_shards": shards,
            "serve_replicas": replicas,
            "cache_slots": int(cache_slots),
            "itemsize": itemsize,
        },
        "per_replica": {
            "snapshot_bytes": round(snapshot, 1),
            "index_bytes": round(index, 1),
            "cache_bytes": round(cache, 1),
            "adjacency_bytes": round(adjacency, 1),
            "total_bytes": round(per_replica, 1),
        },
        "fleet_total_bytes": round(fleet_total, 1),
        "qps_capacity": round(qps_capacity, 1),
        "qps_capacity_members": round(qps_members, 1),
        "qps_target": float(qps_target),
        "host_ram_bytes": round(float(host_ram_bytes), 1),
        "fits": fits,
        "fits_ram": fits_ram,
        "fits_qps": fits_qps,
        "binding": binding,
        "knobs": knobs,
        "notes": notes,
    }


def render_serve_preflight(p: Dict[str, Any]) -> str:
    """Human rendering of a serve_preflight() verdict."""
    w = p["workload"]
    r = p["per_replica"]
    lines = [
        f"serve preflight: N={w['n']}  2E={w['directed_edges']}"
        f"  K={w['k']}  {w['representation']}"
        + (f" M={w['sparse_m']}" if w.get("sparse_m") else "")
        + f"  fleet {w['serve_shards']} shard(s) x "
        f"{w['serve_replicas']} replica(s)",
        "",
        f"per-replica RSS (modeled): {_fmt_bytes(r['total_bytes'])}"
        + (
            f"  vs {_fmt_bytes(p['host_ram_bytes'])}"
            f" ({'fits' if p['fits_ram'] else 'DOES NOT FIT'})"
            if p["host_ram_bytes"]
            else ""
        ),
    ]
    for key, label in (
        ("snapshot_bytes", "snapshot"),
        ("index_bytes", "inverted index"),
        ("adjacency_bytes", "adjacency"),
        ("cache_bytes", "hot cache"),
    ):
        lines.append(f"  {label:<16} {_fmt_bytes(r[key]):>12}")
    lines.append(
        f"fleet total ({w['serve_shards']}x{w['serve_replicas']}): "
        f"{_fmt_bytes(p['fleet_total_bytes'])}"
    )
    lines.append("")
    lines.append(
        f"QPS capacity (node-routed): {p['qps_capacity']:,.0f}"
        + (
            f"  vs target {p['qps_target']:,.0f}"
            f" ({'fits' if p['fits_qps'] else 'DOES NOT FIT'})"
            if p["qps_target"]
            else "  (no --qps-target given)"
        )
    )
    lines.append("")
    verdict = "FITS" if p["fits"] else (
        f"DOES NOT FIT (binding: {p['binding']})"
    )
    lines.append(f"verdict: {verdict}")
    for knob in p["knobs"]:
        lines.append(f"  knob: {knob}")
    for note in p["notes"]:
        lines.append(f"  note: {note}")
    return "\n".join(lines)
